"""sr25519 (schnorrkel) signatures: Schnorr over ristretto255 + Merlin.

The reference supports sr25519 validator keys through ChainSafe/
go-schnorrkel (crypto/sr25519/privkey.go:25-43, pubkey.go:34-58 in
/root/reference). This is a from-scratch host implementation of the same
scheme on the repo's primitives (crypto/merlin.py transcripts over
keccak-f[1600], crypto/ristretto.py group, crypto/ed25519.py curve):

- key expansion `ExpandEd25519`: scalar = clamp(SHA-512(mini)[:32]) / 8,
  nonce = SHA-512(mini)[32:] (go-schnorrkel mini_secret.go semantics);
- signing context: Transcript("SigningContext") absorbing an empty ctx
  label and the message under "sign-bytes" (pubkey.go:51);
- sign/verify transcript: "proto-name"=Schnorr-sig, "sign:pk", "sign:R",
  challenge scalar at "sign:c" (64 PRF bytes mod L);
- signature wire form: R_ristretto(32) || s(32) with bit 255 of s set as
  the schnorrkel marker; s must be canonical (< L) on decode.

SURVEY.md §2.2 marks sr25519 as CPU-fallback-acceptable; there is no
device kernel. Sign-side nonces are deterministic (transcript witness
bound to the expanded nonce), which verifies identically but does not
reproduce go-schnorrkel's randomized signatures byte-for-byte.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from . import ristretto
from .ed25519 import BASEPOINT as _BASEPOINT
from .ed25519 import L, point_add, point_neg, scalar_mult
from .merlin import Transcript

KEY_TYPE = "sr25519"
PUB_KEY_SIZE = 32
SIGNATURE_SIZE = 64


def _signing_context(msg: bytes) -> Transcript:
    """schnorrkel.NewSigningContext([]byte{}, msg) (pubkey.go:51)."""
    t = Transcript(b"SigningContext")
    t.append_message(b"", b"")
    t.append_message(b"sign-bytes", msg)
    return t


def _challenge_scalar(t: Transcript, label: bytes) -> int:
    return int.from_bytes(t.challenge_bytes(label, 64), "little") % L


def expand_ed25519(mini: bytes) -> tuple[int, bytes]:
    """Mini secret -> (scalar, nonce), go-schnorrkel ExpandEd25519."""
    h = hashlib.sha512(mini).digest()
    key = bytearray(h[:32])
    key[0] &= 248
    key[31] &= 63
    key[31] |= 64
    scalar = int.from_bytes(bytes(key), "little") >> 3  # divide by cofactor
    return scalar, h[32:]


@dataclass(frozen=True)
class PubKey:
    data: bytes  # 32-byte ristretto255 encoding

    type_name = KEY_TYPE

    def address(self) -> bytes:
        from .tmhash import sum_truncated

        return sum_truncated(self.data)

    def verify(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE or len(self.data) != PUB_KEY_SIZE:
            return False
        if sig[63] & 0x80 == 0:
            return False  # not marked as a schnorrkel signature
        a = ristretto.decode(self.data)
        r_bytes = sig[:32]
        if a is None or ristretto.decode(r_bytes) is None:
            return False
        s_arr = bytearray(sig[32:])
        s_arr[31] &= 0x7F
        s = int.from_bytes(bytes(s_arr), "little")
        if s >= L:
            return False
        t = _signing_context(msg)
        t.append_message(b"proto-name", b"Schnorr-sig")
        t.append_message(b"sign:pk", self.data)
        t.append_message(b"sign:R", r_bytes)
        k = _challenge_scalar(t, b"sign:c")
        # R == [s]B - [k]A  <=>  encode([s]B + [k](-A)) == R_bytes
        q = point_add(
            scalar_mult(s, _BASEPOINT), scalar_mult(k, point_neg(a))
        )
        return ristretto.encode(q) == r_bytes

    # interface parity with ed25519.PubKey
    verify_signature = verify


@dataclass(frozen=True)
class PrivKey:
    mini: bytes  # 32-byte mini secret (the reference's PrivKey bytes)

    type_name = KEY_TYPE

    @classmethod
    def generate(cls) -> "PrivKey":
        """Canonical 32-byte secret (< L): a uniform 512-bit value reduced
        mod L, encoded little-endian. Raw token_bytes would be >= L with
        ~94% probability and be rejected by reference-compatible software
        (go-schnorrkel NewMiniSecretKeyFromRaw canonical decode; the
        reference's genPrivKey emits ExpandEd25519().Encode(), also a
        canonical scalar — crypto/sr25519/privkey.go:83-97)."""
        import secrets

        k = int.from_bytes(secrets.token_bytes(64), "little") % L
        return cls(k.to_bytes(32, "little"))

    @classmethod
    def from_secret(cls, seed: bytes) -> "PrivKey":
        """Deterministic key from a seed (test factories)."""
        return cls(hashlib.sha256(b"sr25519:" + seed).digest())

    @classmethod
    def from_bytes(cls, data: bytes) -> "PrivKey":
        if len(data) != 32:
            raise ValueError("sr25519 mini secret must be 32 bytes")
        return cls(data)

    def bytes(self) -> bytes:
        return self.mini

    def public_key(self) -> PubKey:
        scalar, _ = expand_ed25519(self.mini)
        return PubKey(ristretto.encode(scalar_mult(scalar, _BASEPOINT)))

    def sign(self, msg: bytes) -> bytes:
        scalar, nonce = expand_ed25519(self.mini)
        pub = ristretto.encode(scalar_mult(scalar, _BASEPOINT))
        t = _signing_context(msg)
        t.append_message(b"proto-name", b"Schnorr-sig")
        t.append_message(b"sign:pk", pub)
        # deterministic witness: transcript state bound to the secret nonce
        wt = t.clone()
        wt.append_message(b"signing-nonce", nonce)
        r = int.from_bytes(wt.challenge_bytes(b"witness", 64), "little") % L
        r_point = scalar_mult(r, _BASEPOINT)
        r_bytes = ristretto.encode(r_point)
        t.append_message(b"sign:R", r_bytes)
        k = _challenge_scalar(t, b"sign:c")
        s = (k * scalar + r) % L
        s_arr = bytearray(s.to_bytes(32, "little"))
        s_arr[31] |= 0x80  # schnorrkel marker bit
        return r_bytes + bytes(s_arr)
