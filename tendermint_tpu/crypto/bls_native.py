"""ctypes loader for the native BLS12-381 host library.

native/bls12_381.cpp re-implements crypto/bls12_381.py's exact
construction (same flat-sextic tower, same wire format) in C++ with
Montgomery 6x64 arithmetic — the framework's native equivalent of the
reference's Go kilic dependency (blssignatures/bls_signatures.go imports;
SURVEY.md §7.1 budgeted this host fast path). ~10x over the pure-Python
pairing on this box.

All entry points return None when the library is unavailable (no
compiler); callers fall back to the pure-Python path.
"""

from __future__ import annotations

import ctypes
from typing import Optional

from ._native_build import NativeLoader

_loader = NativeLoader(
    "_tmbls.so",
    "bls12_381.cpp",
    funcs=(
        "tmbls_pairing_check",
        "tmbls_g1_mul",
        "tmbls_g2_mul",
        "tmbls_g1_msm",
        "tmbls_g2_msm",
        "tmbls_g1_check",
        "tmbls_g2_check",
    ),
    # late additions: a stale .so without these keeps its core functions
    optional_funcs=(
        "tmbls_fp_inv48",
        "tmbls_fp_sqrt48",
        "tmbls_keccak256",
    ),
)


def native_lib(build: bool = True) -> Optional[ctypes.CDLL]:
    lib = _loader.get(build=build)
    if lib is not None and not getattr(lib, "_tm_argtypes_set", False):
        # declare size_t counts explicitly — default ctypes int conversion
        # truncates through c_int, corrupting lengths >= 2^31
        cp, sz = ctypes.c_char_p, ctypes.c_size_t
        lib.tmbls_pairing_check.argtypes = [cp, cp, sz]
        lib.tmbls_g1_msm.argtypes = [cp, cp, cp, sz]
        lib.tmbls_g2_msm.argtypes = [cp, cp, cp, sz]
        lib._tm_argtypes_set = True
    return lib


def pairing_check(g1s: bytes, g2s: bytes, n: int) -> Optional[bool]:
    """prod e(P_i, Q_i) == 1 over wire-format point arrays; None = no lib,
    raises ValueError on malformed points (callers validated already)."""
    lib = native_lib()
    if lib is None:
        return None
    rc = lib.tmbls_pairing_check(g1s, g2s, n)
    if rc < 0:
        raise ValueError("malformed point passed to native pairing")
    return bool(rc)


def g1_mul(point96: bytes, scalar32: bytes) -> Optional[bytes]:
    lib = native_lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(96)
    if lib.tmbls_g1_mul(out, point96, scalar32) < 0:
        raise ValueError("malformed G1 point")
    return out.raw


def g2_mul(point192: bytes, scalar32: bytes) -> Optional[bytes]:
    lib = native_lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(192)
    if lib.tmbls_g2_mul(out, point192, scalar32) < 0:
        raise ValueError("malformed G2 point")
    return out.raw


def g1_msm(points: bytes, scalars: Optional[bytes], n: int) -> Optional[bytes]:
    """sum k_i * P_i (scalars None => plain sum). Wire-format in/out."""
    lib = native_lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(96)
    if lib.tmbls_g1_msm(out, points, scalars, n) < 0:
        raise ValueError("malformed G1 point in MSM")
    return out.raw


def g2_msm(points: bytes, scalars: Optional[bytes], n: int) -> Optional[bytes]:
    lib = native_lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(192)
    if lib.tmbls_g2_msm(out, points, scalars, n) < 0:
        raise ValueError("malformed G2 point in MSM")
    return out.raw


def fp_inv48(v48: bytes) -> Optional[bytes]:
    """a^-1 mod p over 48-byte BE; inv(0) = 0 (matching pow(0, p-2, p));
    None = no library; raises on non-canonical input."""
    lib = native_lib()
    if lib is None or not hasattr(lib, "tmbls_fp_inv48"):
        return None
    out = ctypes.create_string_buffer(48)
    rc = lib.tmbls_fp_inv48(out, v48)
    if rc < 0:
        raise ValueError("fp_inv48: input not a canonical field element")
    if rc == 0:
        return b"\x00" * 48
    return out.raw


def fp_sqrt48(v48: bytes) -> Optional[bytes]:
    """sqrt mod p over 48-byte BE; b"" = non-square; None = no library."""
    lib = native_lib()
    if lib is None or not hasattr(lib, "tmbls_fp_sqrt48"):
        return None
    out = ctypes.create_string_buffer(48)
    rc = lib.tmbls_fp_sqrt48(out, v48)
    if rc < 0:
        raise ValueError("fp_sqrt48: input not a canonical field element")
    if rc == 0:
        return b""
    return out.raw


def keccak256(data: bytes) -> Optional[bytes]:
    """build=False: hashing must never pay an inline g++ build — general
    hash callers (ethutil, address derivation, CLI tools) get the fast
    path only once the library is loaded (node/light preload, or any
    prior BLS operation)."""
    lib = native_lib(build=False)
    if lib is None or not hasattr(lib, "tmbls_keccak256"):
        return None
    fn = lib.tmbls_keccak256
    if fn.argtypes is None:
        # without argtypes ctypes would truncate len(data) through c_int,
        # silently corrupting the length for >= 2 GiB inputs
        fn.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
    out = ctypes.create_string_buffer(32)
    fn(out, data, len(data))
    return out.raw


def g1_check(point96: bytes) -> Optional[bool]:
    """on-curve + subgroup; None = no lib; False = bad subgroup;
    raises on malformed encoding."""
    lib = native_lib()
    if lib is None:
        return None
    rc = lib.tmbls_g1_check(point96)
    if rc < 0:
        raise ValueError("malformed G1 encoding")
    return bool(rc)


def g2_check(point192: bytes) -> Optional[bool]:
    lib = native_lib()
    if lib is None:
        return None
    rc = lib.tmbls_g2_check(point192)
    if rc < 0:
        raise ValueError("malformed G2 encoding")
    return bool(rc)
