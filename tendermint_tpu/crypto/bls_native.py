"""ctypes loader for the native BLS12-381 host library.

native/bls12_381.cpp re-implements crypto/bls12_381.py's exact
construction (same flat-sextic tower, same wire format) in C++ with
Montgomery 6x64 arithmetic — the framework's native equivalent of the
reference's Go kilic dependency (blssignatures/bls_signatures.go imports;
SURVEY.md §7.1 budgeted this host fast path). ~10x over the pure-Python
pairing on this box.

All entry points return None when the library is unavailable (no
compiler); callers fall back to the pure-Python path.
"""

from __future__ import annotations

import ctypes
from typing import Optional

from ._native_build import NativeLoader

_loader = NativeLoader(
    "_tmbls.so",
    "bls12_381.cpp",
    funcs=(
        "tmbls_pairing_check",
        "tmbls_g1_mul",
        "tmbls_g2_mul",
        "tmbls_g1_msm",
        "tmbls_g2_msm",
        "tmbls_g1_check",
        "tmbls_g2_check",
    ),
)


def native_lib() -> Optional[ctypes.CDLL]:
    return _loader.get()


def pairing_check(g1s: bytes, g2s: bytes, n: int) -> Optional[bool]:
    """prod e(P_i, Q_i) == 1 over wire-format point arrays; None = no lib,
    raises ValueError on malformed points (callers validated already)."""
    lib = native_lib()
    if lib is None:
        return None
    rc = lib.tmbls_pairing_check(g1s, g2s, n)
    if rc < 0:
        raise ValueError("malformed point passed to native pairing")
    return bool(rc)


def g1_mul(point96: bytes, scalar32: bytes) -> Optional[bytes]:
    lib = native_lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(96)
    if lib.tmbls_g1_mul(out, point96, scalar32) < 0:
        raise ValueError("malformed G1 point")
    return out.raw


def g2_mul(point192: bytes, scalar32: bytes) -> Optional[bytes]:
    lib = native_lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(192)
    if lib.tmbls_g2_mul(out, point192, scalar32) < 0:
        raise ValueError("malformed G2 point")
    return out.raw


def g1_msm(points: bytes, scalars: Optional[bytes], n: int) -> Optional[bytes]:
    """sum k_i * P_i (scalars None => plain sum). Wire-format in/out."""
    lib = native_lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(96)
    if lib.tmbls_g1_msm(out, points, scalars, n) < 0:
        raise ValueError("malformed G1 point in MSM")
    return out.raw


def g2_msm(points: bytes, scalars: Optional[bytes], n: int) -> Optional[bytes]:
    lib = native_lib()
    if lib is None:
        return None
    out = ctypes.create_string_buffer(192)
    if lib.tmbls_g2_msm(out, points, scalars, n) < 0:
        raise ValueError("malformed G2 point in MSM")
    return out.raw


def g1_check(point96: bytes) -> Optional[bool]:
    """on-curve + subgroup; None = no lib; False = bad subgroup;
    raises on malformed encoding."""
    lib = native_lib()
    if lib is None:
        return None
    rc = lib.tmbls_g1_check(point96)
    if rc < 0:
        raise ValueError("malformed G1 encoding")
    return bool(rc)


def g2_check(point192: bytes) -> Optional[bool]:
    lib = native_lib()
    if lib is None:
        return None
    rc = lib.tmbls_g2_check(point192)
    if rc < 0:
        raise ValueError("malformed G2 encoding")
    return bool(rc)
