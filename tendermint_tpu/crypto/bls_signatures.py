"""BLS signature scheme over BLS12-381 — the fork's L2 dual-signing crypto.

Mirrors the behavior of the reference's blssignatures package
(/root/reference/blssignatures/bls_signatures.go):

- secret keys: scalars mod r; public keys in G2 (pk = sk*G2gen);
  signatures in G1 (sig = sk * H(m)).
- H(m) = MapToCurve(16-byte padding || keccak256(m)); padding[0] = 1 in
  key-validation mode for domain separation (bls_signatures.go:179-188).
- proof-of-possession (Ristenpart–Yilek): the private key signs its own
  serialized public key under the tweaked hash (bls_signatures.go:66-75).
- verification: 2-pairing check e(H(m), pk) == e(sig, G2gen)
  (bls_signatures.go:114-127), implemented as a single product
  e(H(m), pk) * e(-sig, G2gen) == 1.
- aggregation: point sums of keys (G2) / signatures (G1)
  (bls_signatures.go:129-149); aggregate verification over distinct
  messages does n+1 pairings (bls_signatures.go:151-171).
- serialization: uncompressed big-endian — G1 96 bytes (x||y), G2 192
  bytes (x.c1||x.c0||y.c1||y.c0); infinity encodes as zeros. Public keys
  serialize as proof-length-prefixed proof+key (bls_signatures.go:195-213).

Unlike the reference (which trusts kilic's FromBytes on-curve check only),
deserialization here also subgroup-checks — defense in depth; documented
divergence.
"""

from __future__ import annotations

import json
import os
import secrets
from dataclasses import dataclass

from . import bls12_381 as c
from . import bls_native as native
from .keccak import keccak256


class BLSError(Exception):
    pass


# --- hash to curve --------------------------------------------------------


def hash_to_g1(message: bytes, key_validation_mode: bool = False):
    """16-byte padding || keccak256(msg), mapped to G1."""
    padding = bytearray(16)
    if key_validation_mode:
        padding[0] = 1
    return c.map_to_curve_g1(bytes(padding) + keccak256(message))


# --- serialization --------------------------------------------------------


def g1_to_bytes(p) -> bytes:
    a = c.g1_to_affine(p)
    if a is None:
        return b"\x00" * 96
    return a[0].to_bytes(48, "big") + a[1].to_bytes(48, "big")


def g1_from_bytes(b: bytes):
    if len(b) != 96:
        raise BLSError("G1 encoding must be 96 bytes")
    if b == b"\x00" * 96:
        return c.G1_INF
    ok = _native_check(native.g1_check, b)
    if ok is not None and not ok:
        raise BLSError("G1 point not on curve / not in subgroup")
    x = int.from_bytes(b[:48], "big")
    y = int.from_bytes(b[48:], "big")
    if x >= c.P or y >= c.P:
        raise BLSError("G1 coordinate out of range")
    p = (x, y, 1)
    if ok is None:
        if not c.g1_on_curve(p):
            raise BLSError("G1 point not on curve")
        if not c.g1_in_subgroup(p):
            raise BLSError("G1 point not in the prime-order subgroup")
    return p


def _native_check(fn, b: bytes):
    """Run a native point check: True/False verdict, None = no library;
    malformed encodings surface as BLSError like the python path."""
    try:
        return fn(b)
    except ValueError as e:
        raise BLSError(str(e)) from None


def g2_to_bytes(p) -> bytes:
    a = c.g2_to_affine(p)
    if a is None:
        return b"\x00" * 192
    (x0, x1), (y0, y1) = a
    return (
        x1.to_bytes(48, "big")
        + x0.to_bytes(48, "big")
        + y1.to_bytes(48, "big")
        + y0.to_bytes(48, "big")
    )


def g2_from_bytes(b: bytes):
    if len(b) != 192:
        raise BLSError("G2 encoding must be 192 bytes")
    if b == b"\x00" * 192:
        return c.G2_INF
    ok = _native_check(native.g2_check, b)
    if ok is not None and not ok:
        raise BLSError("G2 point not on curve / not in subgroup")
    vals = [int.from_bytes(b[i * 48 : (i + 1) * 48], "big") for i in range(4)]
    if any(v >= c.P for v in vals):
        raise BLSError("G2 coordinate out of range")
    x = (vals[1], vals[0])
    y = (vals[3], vals[2])
    p = (x, y, c.F2_ONE)
    if ok is None:
        if not c.g2_on_curve(p):
            raise BLSError("G2 point not on curve")
        if not c.g2_in_subgroup(p):
            raise BLSError("G2 point not in the prime-order subgroup")
    return p


# --- native-accelerated primitives ----------------------------------------
# Point values stay python int tuples throughout (the wire format is the
# exchange format with the native library); every helper falls back to the
# pure-python bls12_381 module when the C++ library is unavailable.


def _pairing_is_one(pairs) -> bool:
    """prod e(P_i, Q_i) == 1 — three tiers: device kernel (gated for
    real silicon, TM_TPU_BLS_PAIRING_DEVICE=1 — the PERF_ANALYSIS §6
    pattern; closes SURVEY §7.3(2)'s "then move" half), native C++,
    host bigints."""
    if os.environ.get("TM_TPU_BLS_PAIRING_DEVICE") == "1":
        try:
            from ..ops import bls_pairing

            return bls_pairing.check_pairs(pairs)
        except Exception:
            pass  # device unavailable mid-flight: fall through to host
    if native.native_lib() is not None:
        g1s = b"".join(g1_to_bytes(p) for p, _ in pairs)
        g2s = b"".join(g2_to_bytes(q) for _, q in pairs)
        try:
            return bool(native.pairing_check(g1s, g2s, len(pairs)))
        except ValueError:
            return False
    return c.multi_pairing_is_one(pairs)


def _g1_mul_point(p, k: int):
    if native.native_lib() is not None:
        out = native.g1_mul(g1_to_bytes(p), (k % c.R).to_bytes(32, "big"))
        if out is not None:
            return _g1_parse_unchecked(out)
    return c.g1_mul(p, k)


def _g2_mul_point(p, k: int):
    if native.native_lib() is not None:
        out = native.g2_mul(g2_to_bytes(p), (k % c.R).to_bytes(32, "big"))
        if out is not None:
            return _g2_parse_unchecked(out)
    return c.g2_mul(p, k)


def _g1_parse_unchecked(b: bytes):
    """Wire bytes from the native library (already a group element)."""
    if b == b"\x00" * 96:
        return c.G1_INF
    return (int.from_bytes(b[:48], "big"), int.from_bytes(b[48:], "big"), 1)


def _g2_parse_unchecked(b: bytes):
    if b == b"\x00" * 192:
        return c.G2_INF
    v = [int.from_bytes(b[i * 48 : (i + 1) * 48], "big") for i in range(4)]
    return ((v[1], v[0]), (v[3], v[2]), c.F2_ONE)


# --- keys and signatures --------------------------------------------------


@dataclass(frozen=True)
class PublicKey:
    """G2 key + optional proof-of-possession (None => trusted source)."""

    key: tuple
    validity_proof: tuple | None = None

    def to_trusted(self) -> "PublicKey":
        return PublicKey(self.key, None)


def _pub_wire(pub: PublicKey) -> bytes:
    """Wire-format G2 bytes of a public key, cached on the instance —
    registry keys are serialized for every native MSM/pairing call, and
    the 4 int.to_bytes per call add up across 1k-member aggregates."""
    w = pub.__dict__.get("_wire")
    if w is None:
        w = g2_to_bytes(pub.key)
        object.__setattr__(pub, "_wire", w)
    return w


def generate_priv_key() -> int:
    return secrets.randbelow(c.R - 1) + 1


def pubkey_from_priv(priv: int) -> PublicKey:
    key = _g2_mul_point(c.G2_GEN, priv)
    proof = key_validity_proof(key, priv)
    pub = new_public_key(key, proof)
    return pub


def key_validity_proof(key, priv: int):
    """PoP: sign the serialized public key in key-validation mode."""
    return _sign2(priv, g2_to_bytes(key), key_validation_mode=True)


def new_public_key(key, validity_proof) -> PublicKey:
    pub = PublicKey(key, validity_proof)
    if not _verify2(validity_proof, g2_to_bytes(key), pub, key_validation_mode=True):
        raise BLSError("public key validation failed")
    return pub


def new_trusted_public_key(key) -> PublicKey:
    return PublicKey(key, None)


def sign(priv: int, message: bytes):
    """Signature = priv * H(message) in G1."""
    return _sign2(priv, message, key_validation_mode=False)


def _sign2(priv: int, message: bytes, key_validation_mode: bool):
    h = hash_to_g1(message, key_validation_mode)
    return _g1_mul_point(h, priv)


def verify(sig, message: bytes, pub: PublicKey) -> bool:
    return _verify2(sig, message, pub, key_validation_mode=False)


def _verify2(sig, message: bytes, pub: PublicKey, key_validation_mode: bool) -> bool:
    h = hash_to_g1(message, key_validation_mode)
    # e(H, pk) == e(sig, G2gen)  <=>  e(H, pk) * e(-sig, G2gen) == 1
    return _pairing_is_one(
        [(h, pub.key), (c.g1_neg(sig), c.G2_GEN)]
    )


def aggregate_public_keys(pubs: list[PublicKey]) -> PublicKey:
    """Point sum of N G2 public keys — same preference order as
    aggregate_signatures: native C++ batch-affine sum, then the device
    tree reduction (ops/bls_g2), then the exact host loop."""
    if native.native_lib() is not None and len(pubs) > 1:
        out = native.g2_msm(
            b"".join(_pub_wire(pk) for pk in pubs), None, len(pubs)
        )
        return new_trusted_public_key(_g2_parse_unchecked(out))
    if len(pubs) >= DEVICE_AGGREGATE_MIN:
        try:
            return new_trusted_public_key(
                aggregate_public_keys_device(pubs)
            )
        except Exception:  # no usable backend: the host paths are exact
            pass
    acc = c.G2_INF
    for pk in pubs:
        acc = c.g2_add(acc, pk.key)
    return new_trusted_public_key(acc)


def aggregate_public_keys_device(pubs: list[PublicKey]):
    """Sum N G2 keys as a log2(N)-level device tree reduction
    (ops/bls_g2 — the G2 half of SURVEY §2.2's aggregate kernel row)."""
    import numpy as np

    import jax.numpy as jnp

    from ..ops import bls_g2 as dev

    pts = np.stack([dev.g2_from_host(pk.key) for pk in pubs])
    return dev.g2_to_host(dev.g2_aggregate(jnp.asarray(pts)))


# host->device switchover for signature aggregation: below this the
# serial host loop beats the device round-trip; above it the tree
# reduction in ops/bls_g1.py wins (the N-proportional part of
# AggregateSignatures, bls_signatures.go:138-149)
DEVICE_AGGREGATE_MIN = 64


def aggregate_signatures(sigs: list):
    """Point sum of N G1 signatures. Preference order (r3, measured):
    native C++ MSM (~2 us/add, no warm-up), then the device tree
    reduction (ops/bls_g1 — the mesh-scale path; pays a one-time compile,
    so it only leads where the native library is unavailable or the
    deployment pins aggregation on-device), then the exact host loop."""
    if native.native_lib() is not None and len(sigs) > 1:
        out = native.g1_msm(
            b"".join(g1_to_bytes(s) for s in sigs), None, len(sigs)
        )
        return _g1_parse_unchecked(out)
    if len(sigs) >= DEVICE_AGGREGATE_MIN:
        try:
            return aggregate_signatures_device(sigs)
        except Exception:  # no usable backend: the host paths are exact
            pass
    acc = c.G1_INF
    for s in sigs:
        acc = c.g1_add(acc, s)
    return acc


def aggregate_signatures_device(sigs: list):
    """Sum N G1 signatures as a log2(N)-level device tree reduction."""
    import numpy as np

    from ..ops import bls_g1 as dev

    pts = np.stack([dev.g1_from_host(s) for s in sigs])
    import jax.numpy as jnp

    return dev.g1_to_host(dev.g1_aggregate_jit(jnp.asarray(pts)))


def verify_aggregated_same_message(sig, message: bytes, pubs: list[PublicKey]) -> bool:
    return verify(sig, message, aggregate_public_keys(pubs))


# Batch verification coefficients: 128-bit random scalars make a forged
# batch pass with probability 2^-128 (the standard random-linear-combination
# argument; a plain unweighted sum would let two colluding validators submit
# sig+D and sig-D that cancel in aggregate but are individually invalid —
# poisoning the commit's L1-bound aggregate, which uses a different subset).
_BATCH_COEFF_BITS = 128


def verify_batch_same_message(
    message: bytes, pubs: list[PublicKey], sigs: list
) -> list[bool]:
    """Per-signature verdicts for N (pk_i, sig_i) over ONE message, in 2
    pairings for the all-valid case instead of 2N.

    Check: e(H(m), sum r_i*pk_i) == e(sum r_i*sig_i, G2gen) with random
    128-bit r_i. On failure, bisect to isolate the invalid indices —
    O(bad * log N) aggregate checks, each 2 pairings.

    This is the TPU-framework replacement for the reference's serial
    per-precommit L2 verify (consensus/state.go:2362-2379): the consensus
    workload verifies many signatures over the SAME batch hash each round,
    so the batch amortizes the pairing cost across the round's burst.
    """
    n = len(pubs)
    if n != len(sigs):
        raise BLSError("len(pubs) != len(sigs)")
    if n == 0:
        return []
    if n == 1:
        return [verify(sigs[0], message, pubs[0])]
    h = hash_to_g1(message, False)

    def check(idx: list[int]) -> bool:
        if len(idx) == 1:
            i = idx[0]
            # single item: plain 2-pairing verify, no coefficient needed
            return _pairing_is_one(
                [(h, pubs[i].key), (c.g1_neg(sigs[i]), c.G2_GEN)]
            )
        coeffs = [secrets.randbits(_BATCH_COEFF_BITS) | 1 for _ in idx]
        if native.native_lib() is not None:
            ks = b"".join(r.to_bytes(32, "big") for r in coeffs)
            pk_bytes = b"".join(_pub_wire(pubs[i]) for i in idx)
            sig_bytes = b"".join(g1_to_bytes(sigs[i]) for i in idx)
            acc_pk = _g2_parse_unchecked(native.g2_msm(pk_bytes, ks, len(idx)))
            acc_sig = _g1_parse_unchecked(
                native.g1_msm(sig_bytes, ks, len(idx))
            )
        else:
            acc_pk = c.G2_INF
            acc_sig = c.G1_INF
            for r, i in zip(coeffs, idx):
                acc_pk = c.g2_add(acc_pk, c.g2_mul(pubs[i].key, r))
                acc_sig = c.g1_add(acc_sig, c.g1_mul(sigs[i], r))
        return _pairing_is_one(
            [(h, acc_pk), (c.g1_neg(acc_sig), c.G2_GEN)]
        )

    out = [False] * n

    def solve(idx: list[int]) -> None:
        if check(idx):
            for i in idx:
                out[i] = True
            return
        if len(idx) == 1:
            return
        mid = len(idx) // 2
        solve(idx[:mid])
        solve(idx[mid:])

    solve(list(range(n)))
    return out


# signer-key parse cache for the QC engine: full deserialization
# (on-curve + SUBGROUP check) costs ~0.5 ms/key — linear in committee
# size, and it is exactly the cost the QC plane exists to flatten.
# Keys arrive from hash-committed validator sets, so the same 192-byte
# strings recur for every block of a catchup window: each distinct key
# pays the full check ONCE, then parses free. Bounded dict (insertion-
# ordered eviction) so a hostile stream of fabricated keys cannot grow
# it; thread-safe under the GIL (worst case a key is checked twice).
_QC_KEY_CACHE: dict = {}
_QC_KEY_CACHE_MAX = 8192


def _qc_signer_key(kb: bytes):
    p = _QC_KEY_CACHE.get(kb)
    if p is None:
        p = g2_from_bytes(kb)  # full check; raises BLSError on junk
        if len(_QC_KEY_CACHE) >= _QC_KEY_CACHE_MAX:
            _QC_KEY_CACHE.pop(next(iter(_QC_KEY_CACHE)))
        _QC_KEY_CACHE[kb] = p
    return p


def verify_qc_items(items: list[tuple]) -> list:
    """The `qc_verify` engine: per-item verdicts for quorum-certificate
    aggregate checks. Each item is wire-able bytes —
    (message, agg_sig_96, signer_pubkeys_concat) where the third part is
    the signers' uncompressed G2 keys back to back (192 bytes each, in
    bitset order) — so the same engine serves the in-proc scheduler's
    fn lane and the verify-service's cross-process wire table.

    One item costs 2 pairings + one G2 MSM regardless of signer count
    (the flat-in-committee-size property the QC plane exists for). A
    round of N items verifies as ONE random-linear-combination
    multi-pairing — N+1 pairings for the all-valid case instead of 2N —
    with bisection isolating invalid items on failure. Unparseable
    points are False verdicts, never an engine error (the bls_agg
    contract)."""
    n = len(items)
    if n == 0:
        return []
    from .shape_registry import default_shape_registry

    reg = default_shape_registry()
    reg.record_dispatch("qc_verify", reg.bucket_for(n))
    parsed: list = [None] * n  # (H(m), apk, sig) per parseable item
    out: list = [False] * n
    for i, parts in enumerate(items):
        if len(parts) != 3:
            raise BLSError("qc_verify item needs (msg, agg_sig, pubkeys)")
        msg, sig_b, pks_b = parts
        if len(pks_b) == 0 or len(pks_b) % 192 != 0:
            continue
        try:
            sig = g1_from_bytes(sig_b)
            keys = [
                _qc_signer_key(pks_b[j : j + 192])
                for j in range(0, len(pks_b), 192)
            ]
        except BLSError:
            continue
        if native.native_lib() is not None and len(keys) > 1:
            # the wire slices ARE the MSM input — no per-key
            # re-serialization on the aggregate path
            apk = _g2_parse_unchecked(
                native.g2_msm(pks_b, None, len(keys))
            )
        else:
            apk = c.G2_INF
            for k in keys:
                apk = c.g2_add(apk, k)
        parsed[i] = (hash_to_g1(msg, False), apk, sig)

    def check(idx: list[int]) -> bool:
        if len(idx) == 1:
            h, apk, sig = parsed[idx[0]]
            return _pairing_is_one([(h, apk), (c.g1_neg(sig), c.G2_GEN)])
        pairs = []
        acc_sig = c.G1_INF
        for i in idx:
            h, apk, sig = parsed[i]
            r = secrets.randbits(_BATCH_COEFF_BITS) | 1
            pairs.append((_g1_mul_point(h, r), apk))
            acc_sig = c.g1_add(acc_sig, _g1_mul_point(sig, r))
        pairs.append((c.g1_neg(acc_sig), c.G2_GEN))
        return _pairing_is_one(pairs)

    def solve(idx: list[int]) -> None:
        if check(idx):
            for i in idx:
                out[i] = True
            return
        if len(idx) == 1:
            return
        mid = len(idx) // 2
        solve(idx[:mid])
        solve(idx[mid:])

    live = [i for i in range(n) if parsed[i] is not None]
    if live:
        solve(live)
    return out


def verify_aggregated_different_messages(
    sig, messages: list[bytes], pubs: list[PublicKey]
) -> bool:
    """n+1 pairings: prod e(H(m_i), pk_i) * e(-sig, G2gen) == 1
    (bls_signatures.go:151-171)."""
    if len(messages) != len(pubs):
        raise BLSError("len(messages) != len(pub keys)")
    pairs = [
        (hash_to_g1(m, False), pk.key) for m, pk in zip(messages, pubs)
    ]
    pairs.append((c.g1_neg(sig), c.G2_GEN))
    return _pairing_is_one(pairs)


# --- byte-level public key (proof-prefixed, bls_signatures.go:195-258) ----


def public_key_to_bytes(pub: PublicKey) -> bytes:
    key_bytes = g2_to_bytes(pub.key)
    if pub.validity_proof is None:
        return b"\x00" + key_bytes
    sig_bytes = g1_to_bytes(pub.validity_proof)
    if len(sig_bytes) > 255:
        raise BLSError("validity proof too large to serialize")
    return bytes([len(sig_bytes)]) + sig_bytes + key_bytes


def public_key_from_bytes(data: bytes, trusted_source: bool) -> PublicKey:
    if not data:
        raise BLSError("tried to deserialize empty public key")
    proof_len = data[0]
    if proof_len == 0:
        if not trusted_source:
            raise BLSError(
                "tried to deserialize unvalidated public key from untrusted source"
            )
        return new_trusted_public_key(g2_from_bytes(data[1:]))
    if len(data) < 1 + proof_len:
        raise BLSError("invalid serialized public key")
    proof = g1_from_bytes(data[1 : 1 + proof_len])
    key = g2_from_bytes(data[1 + proof_len :])
    if trusted_source:
        return PublicKey(key, proof)
    return new_public_key(key, proof)


def priv_key_to_bytes(priv: int) -> bytes:
    # big.Int.Bytes() semantics: minimal big-endian, empty for zero
    n = (priv.bit_length() + 7) // 8
    return priv.to_bytes(n, "big")


def priv_key_from_bytes(data: bytes) -> int:
    return int.from_bytes(data, "big")


# --- key file (blssignatures/file.go) -------------------------------------


@dataclass
class FileBLSKey:
    pub_key: bytes
    priv_key: bytes

    def save(self, file_path: str) -> None:
        if not file_path:
            raise BLSError("cannot save bls key: filePath not set")
        data = json.dumps(
            {
                "pub_key": self.pub_key.hex(),
                "priv_key": self.priv_key.hex(),
            },
            indent=2,
        )
        tmp = file_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, file_path)


def gen_file_bls_key() -> FileBLSKey:
    priv = generate_priv_key()
    pub = pubkey_from_priv(priv)
    return FileBLSKey(
        pub_key=public_key_to_bytes(pub), priv_key=priv_key_to_bytes(priv)
    )


def load_bls_key(file_path: str) -> FileBLSKey:
    with open(file_path) as f:
        d = json.load(f)
    return FileBLSKey(
        pub_key=bytes.fromhex(d["pub_key"]), priv_key=bytes.fromhex(d["priv_key"])
    )


def load_or_gen_bls_key(file_path: str) -> FileBLSKey:
    if os.path.exists(file_path):
        return load_bls_key(file_path)
    k = gen_file_bls_key()
    k.save(file_path)
    return k


# --- consensus integration helpers ----------------------------------------


def signer_for(priv: int):
    """bls_signer callable for ConsensusState: batch_hash -> 96-byte G1 sig
    (the reference signs the raw BatchHash bytes — consensus/state.go:2560)."""

    def _sign(batch_hash: bytes) -> bytes:
        return g1_to_bytes(sign(priv, batch_hash))

    return _sign


class BLSKeyRegistry:
    """tm-validator-pubkey -> BLS public key mapping.

    Stands in for the L2 node's on-chain sequencer-set registry that backs
    l2Node.VerifySignature (the real Morph node resolves the tm key to a
    staked BLS key; reference call site consensus/state.go:2362-2379).
    """

    def __init__(self) -> None:
        self._by_tm: dict[bytes, PublicKey] = {}

    def register(self, tm_pubkey: bytes, pub: PublicKey) -> None:
        self._by_tm[bytes(tm_pubkey)] = pub

    def verifier(self):
        """(tm_pubkey, message, sig_bytes) -> bool|None, for MockL2Node.
        None = tm key not registered (registry lag for a newly added
        validator is not a cryptographic rejection — the relaying peer
        must not be punished for it)."""

        def _verify(tm_pubkey: bytes, message: bytes, sig_bytes: bytes):
            pub = self._by_tm.get(bytes(tm_pubkey))
            if pub is None:
                return None
            try:
                s = g1_from_bytes(bytes(sig_bytes))
            except BLSError:
                return False
            return verify(s, bytes(message), pub)

        return _verify

    def batch_verifier(self):
        """(tm_pubkeys, message, sig_bytes_list) -> list[bool] for
        MockL2Node.verify_signatures: one batched same-message check
        (2 pairings all-valid) instead of 2 per signature."""

        def _verify_batch(
            tm_pubkeys: list, message: bytes, sig_list: list
        ) -> list:
            out: list = [False] * len(tm_pubkeys)
            idx, pubs, sigs = [], [], []
            for i, (tk, sb) in enumerate(zip(tm_pubkeys, sig_list)):
                pub = self._by_tm.get(bytes(tk))
                if pub is None:
                    out[i] = None  # unknown key: not a crypto rejection
                    continue
                try:
                    s = g1_from_bytes(bytes(sb))
                except BLSError:
                    continue
                idx.append(i)
                pubs.append(pub)
                sigs.append(s)
            if idx:
                verdicts = verify_batch_same_message(bytes(message), pubs, sigs)
                for i, v in zip(idx, verdicts):
                    out[i] = v
            return out

        return _verify_batch
