"""BLS signature scheme over BLS12-381 — the fork's L2 dual-signing crypto.

Mirrors the behavior of the reference's blssignatures package
(/root/reference/blssignatures/bls_signatures.go):

- secret keys: scalars mod r; public keys in G2 (pk = sk*G2gen);
  signatures in G1 (sig = sk * H(m)).
- H(m) = MapToCurve(16-byte padding || keccak256(m)); padding[0] = 1 in
  key-validation mode for domain separation (bls_signatures.go:179-188).
- proof-of-possession (Ristenpart–Yilek): the private key signs its own
  serialized public key under the tweaked hash (bls_signatures.go:66-75).
- verification: 2-pairing check e(H(m), pk) == e(sig, G2gen)
  (bls_signatures.go:114-127), implemented as a single product
  e(H(m), pk) * e(-sig, G2gen) == 1.
- aggregation: point sums of keys (G2) / signatures (G1)
  (bls_signatures.go:129-149); aggregate verification over distinct
  messages does n+1 pairings (bls_signatures.go:151-171).
- serialization: uncompressed big-endian — G1 96 bytes (x||y), G2 192
  bytes (x.c1||x.c0||y.c1||y.c0); infinity encodes as zeros. Public keys
  serialize as proof-length-prefixed proof+key (bls_signatures.go:195-213).

Unlike the reference (which trusts kilic's FromBytes on-curve check only),
deserialization here also subgroup-checks — defense in depth; documented
divergence.
"""

from __future__ import annotations

import json
import os
import secrets
from dataclasses import dataclass

from . import bls12_381 as c
from .keccak import keccak256


class BLSError(Exception):
    pass


# --- hash to curve --------------------------------------------------------


def hash_to_g1(message: bytes, key_validation_mode: bool = False):
    """16-byte padding || keccak256(msg), mapped to G1."""
    padding = bytearray(16)
    if key_validation_mode:
        padding[0] = 1
    return c.map_to_curve_g1(bytes(padding) + keccak256(message))


# --- serialization --------------------------------------------------------


def g1_to_bytes(p) -> bytes:
    a = c.g1_to_affine(p)
    if a is None:
        return b"\x00" * 96
    return a[0].to_bytes(48, "big") + a[1].to_bytes(48, "big")


def g1_from_bytes(b: bytes):
    if len(b) != 96:
        raise BLSError("G1 encoding must be 96 bytes")
    if b == b"\x00" * 96:
        return c.G1_INF
    x = int.from_bytes(b[:48], "big")
    y = int.from_bytes(b[48:], "big")
    if x >= c.P or y >= c.P:
        raise BLSError("G1 coordinate out of range")
    p = (x, y, 1)
    if not c.g1_on_curve(p):
        raise BLSError("G1 point not on curve")
    if not c.g1_in_subgroup(p):
        raise BLSError("G1 point not in the prime-order subgroup")
    return p


def g2_to_bytes(p) -> bytes:
    a = c.g2_to_affine(p)
    if a is None:
        return b"\x00" * 192
    (x0, x1), (y0, y1) = a
    return (
        x1.to_bytes(48, "big")
        + x0.to_bytes(48, "big")
        + y1.to_bytes(48, "big")
        + y0.to_bytes(48, "big")
    )


def g2_from_bytes(b: bytes):
    if len(b) != 192:
        raise BLSError("G2 encoding must be 192 bytes")
    if b == b"\x00" * 192:
        return c.G2_INF
    vals = [int.from_bytes(b[i * 48 : (i + 1) * 48], "big") for i in range(4)]
    if any(v >= c.P for v in vals):
        raise BLSError("G2 coordinate out of range")
    x = (vals[1], vals[0])
    y = (vals[3], vals[2])
    p = (x, y, c.F2_ONE)
    if not c.g2_on_curve(p):
        raise BLSError("G2 point not on curve")
    if not c.g2_in_subgroup(p):
        raise BLSError("G2 point not in the prime-order subgroup")
    return p


# --- keys and signatures --------------------------------------------------


@dataclass(frozen=True)
class PublicKey:
    """G2 key + optional proof-of-possession (None => trusted source)."""

    key: tuple
    validity_proof: tuple | None = None

    def to_trusted(self) -> "PublicKey":
        return PublicKey(self.key, None)


def generate_priv_key() -> int:
    return secrets.randbelow(c.R - 1) + 1


def pubkey_from_priv(priv: int) -> PublicKey:
    key = c.g2_mul(c.G2_GEN, priv)
    proof = key_validity_proof(key, priv)
    pub = new_public_key(key, proof)
    return pub


def key_validity_proof(key, priv: int):
    """PoP: sign the serialized public key in key-validation mode."""
    return _sign2(priv, g2_to_bytes(key), key_validation_mode=True)


def new_public_key(key, validity_proof) -> PublicKey:
    pub = PublicKey(key, validity_proof)
    if not _verify2(validity_proof, g2_to_bytes(key), pub, key_validation_mode=True):
        raise BLSError("public key validation failed")
    return pub


def new_trusted_public_key(key) -> PublicKey:
    return PublicKey(key, None)


def sign(priv: int, message: bytes):
    """Signature = priv * H(message) in G1."""
    return _sign2(priv, message, key_validation_mode=False)


def _sign2(priv: int, message: bytes, key_validation_mode: bool):
    h = hash_to_g1(message, key_validation_mode)
    return c.g1_mul(h, priv)


def verify(sig, message: bytes, pub: PublicKey) -> bool:
    return _verify2(sig, message, pub, key_validation_mode=False)


def _verify2(sig, message: bytes, pub: PublicKey, key_validation_mode: bool) -> bool:
    h = hash_to_g1(message, key_validation_mode)
    # e(H, pk) == e(sig, G2gen)  <=>  e(H, pk) * e(-sig, G2gen) == 1
    return c.multi_pairing_is_one(
        [(h, pub.key), (c.g1_neg(sig), c.G2_GEN)]
    )


def aggregate_public_keys(pubs: list[PublicKey]) -> PublicKey:
    acc = c.G2_INF
    for pk in pubs:
        acc = c.g2_add(acc, pk.key)
    return new_trusted_public_key(acc)


# host->device switchover for signature aggregation: below this the
# serial host loop beats the device round-trip; above it the tree
# reduction in ops/bls_g1.py wins (the N-proportional part of
# AggregateSignatures, bls_signatures.go:138-149)
DEVICE_AGGREGATE_MIN = 64


def aggregate_signatures(sigs: list):
    if len(sigs) >= DEVICE_AGGREGATE_MIN:
        try:
            return aggregate_signatures_device(sigs)
        except Exception:  # no usable backend: the host loop is exact
            pass
    acc = c.G1_INF
    for s in sigs:
        acc = c.g1_add(acc, s)
    return acc


def aggregate_signatures_device(sigs: list):
    """Sum N G1 signatures as a log2(N)-level device tree reduction."""
    import numpy as np

    from ..ops import bls_g1 as dev

    pts = np.stack([dev.g1_from_host(s) for s in sigs])
    import jax.numpy as jnp

    return dev.g1_to_host(dev.g1_aggregate_jit(jnp.asarray(pts)))


def verify_aggregated_same_message(sig, message: bytes, pubs: list[PublicKey]) -> bool:
    return verify(sig, message, aggregate_public_keys(pubs))


def verify_aggregated_different_messages(
    sig, messages: list[bytes], pubs: list[PublicKey]
) -> bool:
    """n+1 pairings: prod e(H(m_i), pk_i) * e(-sig, G2gen) == 1
    (bls_signatures.go:151-171)."""
    if len(messages) != len(pubs):
        raise BLSError("len(messages) != len(pub keys)")
    pairs = [
        (hash_to_g1(m, False), pk.key) for m, pk in zip(messages, pubs)
    ]
    pairs.append((c.g1_neg(sig), c.G2_GEN))
    return c.multi_pairing_is_one(pairs)


# --- byte-level public key (proof-prefixed, bls_signatures.go:195-258) ----


def public_key_to_bytes(pub: PublicKey) -> bytes:
    key_bytes = g2_to_bytes(pub.key)
    if pub.validity_proof is None:
        return b"\x00" + key_bytes
    sig_bytes = g1_to_bytes(pub.validity_proof)
    if len(sig_bytes) > 255:
        raise BLSError("validity proof too large to serialize")
    return bytes([len(sig_bytes)]) + sig_bytes + key_bytes


def public_key_from_bytes(data: bytes, trusted_source: bool) -> PublicKey:
    if not data:
        raise BLSError("tried to deserialize empty public key")
    proof_len = data[0]
    if proof_len == 0:
        if not trusted_source:
            raise BLSError(
                "tried to deserialize unvalidated public key from untrusted source"
            )
        return new_trusted_public_key(g2_from_bytes(data[1:]))
    if len(data) < 1 + proof_len:
        raise BLSError("invalid serialized public key")
    proof = g1_from_bytes(data[1 : 1 + proof_len])
    key = g2_from_bytes(data[1 + proof_len :])
    if trusted_source:
        return PublicKey(key, proof)
    return new_public_key(key, proof)


def priv_key_to_bytes(priv: int) -> bytes:
    # big.Int.Bytes() semantics: minimal big-endian, empty for zero
    n = (priv.bit_length() + 7) // 8
    return priv.to_bytes(n, "big")


def priv_key_from_bytes(data: bytes) -> int:
    return int.from_bytes(data, "big")


# --- key file (blssignatures/file.go) -------------------------------------


@dataclass
class FileBLSKey:
    pub_key: bytes
    priv_key: bytes

    def save(self, file_path: str) -> None:
        if not file_path:
            raise BLSError("cannot save bls key: filePath not set")
        data = json.dumps(
            {
                "pub_key": self.pub_key.hex(),
                "priv_key": self.priv_key.hex(),
            },
            indent=2,
        )
        tmp = file_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, file_path)


def gen_file_bls_key() -> FileBLSKey:
    priv = generate_priv_key()
    pub = pubkey_from_priv(priv)
    return FileBLSKey(
        pub_key=public_key_to_bytes(pub), priv_key=priv_key_to_bytes(priv)
    )


def load_bls_key(file_path: str) -> FileBLSKey:
    with open(file_path) as f:
        d = json.load(f)
    return FileBLSKey(
        pub_key=bytes.fromhex(d["pub_key"]), priv_key=bytes.fromhex(d["priv_key"])
    )


def load_or_gen_bls_key(file_path: str) -> FileBLSKey:
    if os.path.exists(file_path):
        return load_bls_key(file_path)
    k = gen_file_bls_key()
    k.save(file_path)
    return k


# --- consensus integration helpers ----------------------------------------


def signer_for(priv: int):
    """bls_signer callable for ConsensusState: batch_hash -> 96-byte G1 sig
    (the reference signs the raw BatchHash bytes — consensus/state.go:2560)."""

    def _sign(batch_hash: bytes) -> bytes:
        return g1_to_bytes(sign(priv, batch_hash))

    return _sign


class BLSKeyRegistry:
    """tm-validator-pubkey -> BLS public key mapping.

    Stands in for the L2 node's on-chain sequencer-set registry that backs
    l2Node.VerifySignature (the real Morph node resolves the tm key to a
    staked BLS key; reference call site consensus/state.go:2362-2379).
    """

    def __init__(self) -> None:
        self._by_tm: dict[bytes, PublicKey] = {}

    def register(self, tm_pubkey: bytes, pub: PublicKey) -> None:
        self._by_tm[bytes(tm_pubkey)] = pub

    def verifier(self):
        """(tm_pubkey, message, sig_bytes) -> bool, for MockL2Node."""

        def _verify(tm_pubkey: bytes, message: bytes, sig_bytes: bytes) -> bool:
            pub = self._by_tm.get(bytes(tm_pubkey))
            if pub is None:
                return False
            try:
                s = g1_from_bytes(bytes(sig_bytes))
            except BLSError:
                return False
            return verify(s, bytes(message), pub)

        return _verify
