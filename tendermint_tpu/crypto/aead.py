"""ChaCha20-Poly1305 AEAD: native C++ fast path + pure-Python fallback.

The native library (native/chacha20poly1305.cpp) is compiled on first use
with g++ into the package directory and loaded via ctypes — the framework's
native equivalent of x/crypto's assembly AEAD (SURVEY.md §2.2). The Python
fallback implements RFC 8439 directly; it is slow but keeps everything
working where no compiler exists.
"""

from __future__ import annotations

import ctypes
import struct
from typing import Optional

from ._native_build import NativeLoader

KEY_SIZE = 32
NONCE_SIZE = 12
TAG_SIZE = 16

_loader = NativeLoader(
    "_tmcrypto.so",
    "chacha20poly1305.cpp",
    funcs=("tm_aead_seal", "tm_aead_open"),
    timeout=120,
)


def _native_lib() -> Optional[ctypes.CDLL]:
    return _loader.get()


# --- pure-python fallback (RFC 8439) --------------------------------------


def _rotl(x, n):
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


def _chacha_block(key_words, counter, nonce_words):
    state = (
        [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574]
        + key_words
        + [counter]
        + nonce_words
    )
    x = list(state)
    for _ in range(10):
        for a, b, c, d in (
            (0, 4, 8, 12), (1, 5, 9, 13), (2, 6, 10, 14), (3, 7, 11, 15),
            (0, 5, 10, 15), (1, 6, 11, 12), (2, 7, 8, 13), (3, 4, 9, 14),
        ):
            x[a] = (x[a] + x[b]) & 0xFFFFFFFF; x[d] = _rotl(x[d] ^ x[a], 16)
            x[c] = (x[c] + x[d]) & 0xFFFFFFFF; x[b] = _rotl(x[b] ^ x[c], 12)
            x[a] = (x[a] + x[b]) & 0xFFFFFFFF; x[d] = _rotl(x[d] ^ x[a], 8)
            x[c] = (x[c] + x[d]) & 0xFFFFFFFF; x[b] = _rotl(x[b] ^ x[c], 7)
    return struct.pack(
        "<16I", *[(a + b) & 0xFFFFFFFF for a, b in zip(x, state)]
    )


def _chacha20_xor(key: bytes, nonce: bytes, counter: int, data: bytes) -> bytes:
    kw = list(struct.unpack("<8I", key))
    nw = list(struct.unpack("<3I", nonce))
    out = bytearray()
    for i in range(0, len(data), 64):
        block = _chacha_block(kw, counter + i // 64, nw)
        chunk = data[i : i + 64]
        out += bytes(a ^ b for a, b in zip(chunk, block))
    return bytes(out)


def _poly1305(key: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key[:16], "little") & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key[16:], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16] + b"\x01"
        acc = (acc + int.from_bytes(block, "little")) * r % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(b: bytes) -> bytes:
    return b"\x00" * (-len(b) % 16)


def _py_tag(key, nonce, ad, ct) -> bytes:
    polykey = _chacha_block(
        list(struct.unpack("<8I", key)), 0, list(struct.unpack("<3I", nonce))
    )[:32]
    mac_data = (
        ad + _pad16(ad) + ct + _pad16(ct)
        + struct.pack("<QQ", len(ad), len(ct))
    )
    return _poly1305(polykey, mac_data)


# --- public API -----------------------------------------------------------


def seal(key: bytes, nonce: bytes, plaintext: bytes, ad: bytes = b"") -> bytes:
    lib = _native_lib()
    if lib is not None:
        out = ctypes.create_string_buffer(len(plaintext) + TAG_SIZE)
        lib.tm_aead_seal(
            key, nonce, plaintext, len(plaintext), ad, len(ad), out
        )
        return out.raw
    ct = _chacha20_xor(key, nonce, 1, plaintext)
    return ct + _py_tag(key, nonce, ad, ct)


def open_(key: bytes, nonce: bytes, sealed: bytes, ad: bytes = b"") -> bytes:
    """Raises ValueError on authentication failure."""
    if len(sealed) < TAG_SIZE:
        raise ValueError("ciphertext too short")
    lib = _native_lib()
    if lib is not None:
        out = ctypes.create_string_buffer(max(1, len(sealed) - TAG_SIZE))
        rc = lib.tm_aead_open(
            key, nonce, sealed, len(sealed), ad, len(ad), out
        )
        if rc != 0:
            raise ValueError("aead authentication failed")
        return out.raw[: len(sealed) - TAG_SIZE]
    ct, tag = sealed[:-TAG_SIZE], sealed[-TAG_SIZE:]
    want = _py_tag(key, nonce, ad, ct)
    import hmac as _hmac

    if not _hmac.compare_digest(tag, want):
        raise ValueError("aead authentication failed")
    return _chacha20_xor(key, nonce, 1, ct)


def using_native() -> bool:
    return _native_lib() is not None
