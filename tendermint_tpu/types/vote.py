"""Vote — a signed prevote/precommit, optionally BLS dual-signed.

Reference: types/vote.go. The morph fork adds `BLSSignature` (vote.go:59):
at batch points, precommits carry a second BLS12-381 signature over the
batch hash, verified through the L2 node in the consensus vote path
(consensus/state.go:2362-2379) and aggregated for L1 submission.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

from ..libs import protoio as pio
from . import canonical
from .block_id import BlockID


class VoteType(enum.IntEnum):
    PREVOTE = canonical.PREVOTE_TYPE
    PRECOMMIT = canonical.PRECOMMIT_TYPE


# canonical display names: the cluster-trace merge joins `type` fields
# across quorum.* (height_vote_set.py) and gossip.* (consensus/
# reactor.py) events, so every emitter must use this one map
VOTE_TYPE_NAMES = {
    int(VoteType.PREVOTE): "prevote",
    int(VoteType.PRECOMMIT): "precommit",
}


MAX_VOTE_BYTES = 2048  # generous bound incl. BLS signature


@dataclass
class Vote:
    type: int
    height: int
    round: int
    block_id: BlockID
    timestamp_ns: int
    validator_address: bytes
    validator_index: int
    signature: bytes = b""
    bls_signature: bytes = b""  # morph: set on batch-point precommits
    # QC plane: BLS signature over the canonical QC message
    # (types/quorum_cert.qc_sign_bytes) — set on every non-nil precommit
    # when [consensus] quorum_certificates is on, aggregated at +2/3
    qc_signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.vote_sign_bytes(chain_id, self)

    def verify(self, chain_id: str, pub_key) -> bool:
        """Serial one-vote verify (reference types/vote.go:149-158). The
        consensus path batches instead — see crypto.batch_verifier."""
        if pub_key.address() != self.validator_address:
            return False
        return pub_key.verify(self.sign_bytes(chain_id), self.signature)

    def validate_basic(self) -> None:
        if self.type not in (VoteType.PREVOTE, VoteType.PRECOMMIT):
            raise ValueError("invalid vote type")
        if self.height < 0:
            raise ValueError("negative height")
        if self.round < 0:
            raise ValueError("negative round")
        self.block_id.validate_basic()
        if not self.block_id.is_zero() and not self.block_id.is_complete():
            raise ValueError("vote block_id must be nil or complete")
        if len(self.validator_address) != 20:
            raise ValueError("wrong validator address size")
        if self.validator_index < 0:
            raise ValueError("negative validator index")
        if not self.signature:
            raise ValueError("vote missing signature")
        if len(self.signature) > 64:
            raise ValueError("signature too big")

    def is_nil(self) -> bool:
        return self.block_id.is_zero()

    def encode(self) -> bytes:
        return b"".join(
            [
                pio.field_varint(1, self.type),
                pio.field_varint(2, self.height),
                pio.field_varint(3, self.round),
                pio.field_message(4, self.block_id.encode()),
                pio.field_message(
                    5, canonical.encode_timestamp(self.timestamp_ns)
                ),
                pio.field_bytes(6, self.validator_address),
                pio.field_varint(7, self.validator_index + 1),  # 0 is valid
                pio.field_bytes(8, self.signature),
                pio.field_bytes(9, self.bls_signature),
                pio.field_bytes(10, self.qc_signature),
            ]
        )

    @classmethod
    def decode(cls, data: bytes) -> "Vote":
        f = pio.decode_fields(data)
        return cls(
            type=f.get(1, [0])[0],
            height=f.get(2, [0])[0],
            round=f.get(3, [0])[0],
            block_id=BlockID.decode(f.get(4, [b""])[0]),
            timestamp_ns=canonical.decode_timestamp(f.get(5, [b""])[0]),
            validator_address=f.get(6, [b""])[0],
            validator_index=f.get(7, [1])[0] - 1,
            signature=f.get(8, [b""])[0],
            bls_signature=f.get(9, [b""])[0],
            qc_signature=f.get(10, [b""])[0],
        )

    def __repr__(self) -> str:
        t = "Prevote" if self.type == VoteType.PREVOTE else "Precommit"
        tgt = self.block_id.hash.hex()[:12] if not self.is_nil() else "nil"
        return (
            f"Vote{{{self.validator_index}:"
            f"{self.validator_address.hex()[:12]} {self.height}/"
            f"{self.round} {t} {tgt}}}"
        )
