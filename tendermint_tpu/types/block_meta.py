"""BlockMeta — header + block id + sizes, the block-store index record
(reference types/block_meta.go)."""

from __future__ import annotations

from dataclasses import dataclass

from ..libs import protoio as pio
from .block import Block, Header
from .block_id import BlockID
from .part_set import PartSet


@dataclass
class BlockMeta:
    block_id: BlockID
    block_size: int
    header: Header
    num_txs: int

    @classmethod
    def from_block(cls, block: Block, part_set: PartSet) -> "BlockMeta":
        return cls(
            block_id=BlockID(block.hash(), part_set.header),
            block_size=sum(
                len(part_set.get_part(i).bytes_) for i in range(part_set.total)
            ),
            header=block.header,
            num_txs=len(block.data.txs),
        )

    def encode(self) -> bytes:
        return b"".join(
            [
                pio.field_message(1, self.block_id.encode()),
                pio.field_varint(2, self.block_size),
                pio.field_message(3, self.header.encode()),
                pio.field_varint(4, self.num_txs + 1),
            ]
        )

    @classmethod
    def decode(cls, data: bytes) -> "BlockMeta":
        f = pio.decode_fields(data)
        return cls(
            block_id=BlockID.decode(f[1][0]),
            block_size=f.get(2, [0])[0],
            header=Header.decode(f[3][0]),
            num_txs=f.get(4, [1])[0] - 1,
        )
