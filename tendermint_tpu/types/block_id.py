"""BlockID — block hash + part-set header (reference types/block.go BlockID)."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..libs import protoio as pio
from .part_set import PartSetHeader


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    part_set_header: PartSetHeader = field(default_factory=PartSetHeader)

    def is_zero(self) -> bool:
        return len(self.hash) == 0 and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        return (
            len(self.hash) == 32
            and self.part_set_header.total > 0
            and len(self.part_set_header.hash) == 32
        )

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != 32:
            raise ValueError("wrong block hash size")
        self.part_set_header.validate_basic()

    def key(self) -> bytes:
        """Map key for vote tallies (reference BlockID.Key)."""
        return self.hash + self.part_set_header.encode()

    def encode(self) -> bytes:
        return pio.field_bytes(1, self.hash) + pio.field_message(
            2, self.part_set_header.encode()
        )

    @classmethod
    def decode(cls, data: bytes) -> "BlockID":
        if not data:
            return cls()
        f = pio.decode_fields(data)
        return cls(
            hash=f.get(1, [b""])[0],
            part_set_header=PartSetHeader.decode(f.get(2, [b""])[0]),
        )

    def __repr__(self) -> str:
        if self.is_zero():
            return "BlockID{nil}"
        return f"BlockID{{{self.hash.hex()[:12]}}}"
