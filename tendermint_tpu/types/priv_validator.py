"""PrivValidator — the signing interface consensus talks to.

Reference: types/priv_validator.go (PrivValidator iface: GetPubKey,
SignVote, SignProposal) + MockPV for tests. File-backed and remote-socket
implementations live in tendermint_tpu/privval/.
"""

from __future__ import annotations

import time
from typing import Protocol, runtime_checkable

from ..crypto import ed25519
from .proposal import Proposal
from .vote import Vote


@runtime_checkable
class PrivValidator(Protocol):
    def get_pub_key(self): ...

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        """Sets vote.signature (and may adjust timestamp on re-sign)."""
        ...

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None: ...


class MockPV:
    """In-memory signer for tests (reference types/priv_validator.go MockPV).
    No double-sign protection — that's FilePV's job."""

    def __init__(self, priv_key: ed25519.PrivKey | None = None):
        self.priv_key = priv_key or ed25519.PrivKey.generate()

    @classmethod
    def from_secret(cls, secret: bytes) -> "MockPV":
        return cls(ed25519.PrivKey.from_secret(secret))

    def get_pub_key(self):
        return self.priv_key.public_key()

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        vote.signature = self.priv_key.sign(vote.sign_bytes(chain_id))

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        proposal.signature = self.priv_key.sign(
            proposal.sign_bytes(chain_id)
        )
