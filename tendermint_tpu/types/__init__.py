"""Core consensus types (SURVEY.md layer 3, reference types/ ~7.1k LoC)."""

from .canonical import (  # noqa: F401
    CanonicalVoteEncoder,
    proposal_sign_bytes,
    vote_sign_bytes,
)
from .block import (  # noqa: F401
    Block,
    Commit,
    CommitSig,
    Data,
    Header,
    BlockIDFlag,
    L2BatchHeader,
    L2BlockMeta,
)
from .block_id import BlockID  # noqa: F401
from .evidence import (  # noqa: F401
    DuplicateVoteEvidence,
    LightClientAttackEvidence,
)
from .part_set import Part, PartSet, PartSetHeader  # noqa: F401
from .proposal import Proposal  # noqa: F401
from .validator import Validator  # noqa: F401
from .validator_set import ValidatorSet  # noqa: F401
from .vote import Vote, VoteType  # noqa: F401
from .vote_set import VoteSet  # noqa: F401
