"""Evidence of byzantine behavior.

Reference: types/evidence.go — DuplicateVoteEvidence (two conflicting votes
by one validator at the same H/R/type) and LightClientAttackEvidence (a
conflicting light block + the byzantine validators behind it). Conflicting
votes are captured in VoteSet.addVote (types/vote_set.go:209-213) and
verified in evidence/verify.go:162 / :113.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..crypto import merkle
from ..libs import protoio as pio
from .vote import Vote


@dataclass
class DuplicateVoteEvidence:
    vote_a: Vote  # lexicographically smaller block key
    vote_b: Vote
    total_voting_power: int = 0
    validator_power: int = 0
    timestamp_ns: int = 0

    TYPE = 1

    @classmethod
    def from_votes(
        cls, vote1: Vote, vote2: Vote, total_power: int, val_power: int, ts: int
    ) -> "DuplicateVoteEvidence":
        a, b = sorted(
            (vote1, vote2), key=lambda v: v.block_id.key()
        )
        return cls(a, b, total_power, val_power, ts)

    def height(self) -> int:
        return self.vote_a.height

    def validate_basic(self) -> None:
        a, b = self.vote_a, self.vote_b
        a.validate_basic()
        b.validate_basic()
        if (a.height, a.round, a.type) != (b.height, b.round, b.type):
            raise ValueError("votes are not for the same H/R/type")
        if a.validator_address != b.validator_address:
            raise ValueError("votes from different validators")
        if a.block_id.key() == b.block_id.key():
            raise ValueError("votes for the same block — not conflicting")
        if a.block_id.key() > b.block_id.key():
            raise ValueError("votes out of canonical order")

    def encode(self) -> bytes:
        return b"".join(
            [
                pio.field_varint(1, self.TYPE),
                pio.field_message(2, self.vote_a.encode()),
                pio.field_message(3, self.vote_b.encode()),
                pio.field_varint(4, self.total_voting_power),
                pio.field_varint(5, self.validator_power),
                pio.field_varint(6, self.timestamp_ns),
            ]
        )

    def hash(self) -> bytes:
        return merkle.leaf_hash(self.encode())

    @classmethod
    def decode_body(cls, f: dict) -> "DuplicateVoteEvidence":
        return cls(
            vote_a=Vote.decode(f[2][0]),
            vote_b=Vote.decode(f[3][0]),
            total_voting_power=f.get(4, [0])[0],
            validator_power=f.get(5, [0])[0],
            timestamp_ns=f.get(6, [0])[0],
        )


@dataclass
class LightClientAttackEvidence:
    """A conflicting (signed but forked) light block.

    conflicting_block is kept encoded: (header bytes, commit bytes,
    validator-set bytes) — the evidence module decodes as needed.
    """

    conflicting_header: bytes
    conflicting_commit: bytes
    conflicting_validators: bytes
    common_height: int
    byzantine_validators: list[bytes] = field(default_factory=list)
    total_voting_power: int = 0
    timestamp_ns: int = 0

    TYPE = 2

    def height(self) -> int:
        return self.common_height

    def validate_basic(self) -> None:
        if self.common_height <= 0:
            raise ValueError("invalid common height")
        if not self.conflicting_header:
            raise ValueError("missing conflicting header")

    def encode(self) -> bytes:
        return b"".join(
            [
                pio.field_varint(1, self.TYPE),
                pio.field_bytes(2, self.conflicting_header),
                pio.field_bytes(3, self.conflicting_commit),
                pio.field_bytes(4, self.conflicting_validators),
                pio.field_varint(5, self.common_height),
            ]
            + [
                pio.field_bytes(6, a) for a in self.byzantine_validators
            ]
            + [
                pio.field_varint(7, self.total_voting_power),
                pio.field_varint(8, self.timestamp_ns),
            ]
        )

    def hash(self) -> bytes:
        return merkle.leaf_hash(self.encode())

    @classmethod
    def decode_body(cls, f: dict) -> "LightClientAttackEvidence":
        return cls(
            conflicting_header=f.get(2, [b""])[0],
            conflicting_commit=f.get(3, [b""])[0],
            conflicting_validators=f.get(4, [b""])[0],
            common_height=f.get(5, [0])[0],
            byzantine_validators=f.get(6, []),
            total_voting_power=f.get(7, [0])[0],
            timestamp_ns=f.get(8, [0])[0],
        )


def decode_evidence(data: bytes):
    f = pio.decode_fields(data)
    t = f.get(1, [0])[0]
    if t == DuplicateVoteEvidence.TYPE:
        return DuplicateVoteEvidence.decode_body(f)
    if t == LightClientAttackEvidence.TYPE:
        return LightClientAttackEvidence.decode_body(f)
    raise ValueError(f"unknown evidence type {t}")


def encode_evidence_list(evs: list) -> bytes:
    return b"".join(pio.field_message(1, ev.encode()) for ev in evs)


def decode_evidence_list(data: bytes) -> list:
    if not data:
        return []
    f = pio.decode_fields(data)
    return [decode_evidence(d) for d in f.get(1, [])]


def evidence_hash(evs: list) -> bytes:
    return merkle.hash_from_byte_slices([ev.encode() for ev in evs])
