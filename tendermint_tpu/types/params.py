"""On-chain consensus parameters.

Reference: types/params.go — distinct from local node config; updatable by
the application (and, in the morph fork, the L2 node updates the Batch
params per block, state/execution.go:247,290-307).
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field

from ..crypto import tmhash

MAX_BLOCK_SIZE_BYTES = 104857600  # 100MB


@dataclass
class BlockParams:
    max_bytes: int = 22020096  # 21MB
    max_gas: int = -1
    time_iota_ms: int = 1000

    def validate(self) -> None:
        if not 0 < self.max_bytes <= MAX_BLOCK_SIZE_BYTES:
            raise ValueError("block.max_bytes out of range")
        if self.max_gas < -1:
            raise ValueError("block.max_gas < -1")


@dataclass
class EvidenceParams:
    max_age_num_blocks: int = 100000
    max_age_duration_ns: int = 48 * 3600 * 1_000_000_000
    max_bytes: int = 1048576

    def validate(self) -> None:
        if self.max_age_num_blocks <= 0:
            raise ValueError("evidence.max_age_num_blocks must be positive")
        if self.max_age_duration_ns <= 0:
            raise ValueError("evidence.max_age_duration must be positive")


@dataclass
class ValidatorParams:
    pub_key_types: list[str] = field(default_factory=lambda: ["ed25519"])

    def validate(self) -> None:
        if not self.pub_key_types:
            raise ValueError("validator.pub_key_types must not be empty")
        for t in self.pub_key_types:
            if t not in ("ed25519", "secp256k1", "sr25519"):
                raise ValueError(f"unknown pubkey type {t!r}")


@dataclass
class VersionParams:
    app_version: int = 0

    def validate(self) -> None:
        pass


@dataclass
class BatchParams:
    """Morph L2 batch-point parameters (reference types/params.go Batch
    section; updatable by the L2 node per block per
    state/execution.go:290-307): seal a batch every `blocks_interval`
    blocks or after `timeout_ns` or when the batch exceeds `max_bytes`."""

    blocks_interval: int = 0  # 0 = batching disabled
    max_bytes: int = 0
    timeout_ns: int = 0
    max_chunks: int = 0

    def validate(self) -> None:
        if self.blocks_interval < 0:
            raise ValueError("batch.blocks_interval cannot be negative")


@dataclass
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)
    version: VersionParams = field(default_factory=VersionParams)
    batch: BatchParams = field(default_factory=BatchParams)

    def validate(self) -> None:
        self.block.validate()
        self.evidence.validate()
        self.validator.validate()
        self.version.validate()
        self.batch.validate()

    def hash(self) -> bytes:
        """Deterministic hash committed in Header.consensus_hash."""
        blob = json.dumps(asdict(self), sort_keys=True).encode()
        return tmhash.sum(blob)

    def to_json(self) -> dict:
        return asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "ConsensusParams":
        return cls(
            block=BlockParams(**d.get("block", {})),
            evidence=EvidenceParams(**d.get("evidence", {})),
            validator=ValidatorParams(**d.get("validator", {})),
            version=VersionParams(**d.get("version", {})),
            batch=BatchParams(**d.get("batch", {})),
        )

    def update(self, changes: dict) -> "ConsensusParams":
        d = asdict(self)
        for section, vals in changes.items():
            if section in d and isinstance(vals, dict):
                d[section].update(vals)
        params = ConsensusParams.from_json(d)
        params.validate()
        return params
