"""VoteSet — tallies one (height, round, type) of votes toward 2/3.

Reference: types/vote_set.go (VoteSet:63, addVote:156, the conflicting-vote
capture :209-213 that feeds duplicate-vote evidence, and 2/3 bookkeeping).
Signature verification is injectable: the consensus path verifies votes
through the TPU micro-batcher *before* insertion (add_vote(verified=True));
standalone callers keep the serial host check.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..libs.bits import BitArray
from .block_id import BlockID
from .block import BlockIDFlag, Commit, CommitSig
from .validator_set import ValidatorSet
from .vote import Vote, VoteType


class ConflictingVoteError(Exception):
    def __init__(self, existing: Vote, new: Vote):
        super().__init__(
            f"conflicting votes from validator {new.validator_address.hex()}"
        )
        self.existing = existing
        self.new = new


@dataclass
class _BlockVotes:
    peer_maj23: bool
    bit_array: BitArray
    votes: list[Optional[Vote]]
    sum: int = 0

    @classmethod
    def new(cls, peer_maj23: bool, num_validators: int) -> "_BlockVotes":
        return cls(
            peer_maj23, BitArray(num_validators), [None] * num_validators
        )

    def add_verified_vote(self, vote: Vote, power: int) -> None:
        idx = vote.validator_index
        if self.votes[idx] is None:
            self.bit_array.set(idx, True)
            self.votes[idx] = vote
            self.sum += power


class VoteSet:
    def __init__(
        self,
        chain_id: str,
        height: int,
        round_: int,
        signed_msg_type: int,
        val_set: ValidatorSet,
    ):
        if height == 0:
            raise ValueError("cannot make VoteSet for height 0")
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.signed_msg_type = signed_msg_type
        self.val_set = val_set
        self.votes_bit_array = BitArray(val_set.size())
        self.votes: list[Optional[Vote]] = [None] * val_set.size()
        self.sum = 0
        self.maj23: Optional[BlockID] = None
        self.votes_by_block: dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: dict[str, BlockID] = {}

    def size(self) -> int:
        return self.val_set.size()

    # --- adding votes -----------------------------------------------------

    def add_vote(self, vote: Optional[Vote], verified: bool = False) -> bool:
        """Returns True if the vote was added, False if it was a duplicate.
        Raises ValueError for invalid votes, ConflictingVoteError for
        equivocation (captured for evidence, reference vote_set.go:209-213).
        """
        if vote is None:
            raise ValueError("nil vote")
        val_index = vote.validator_index
        if val_index < 0:
            raise ValueError("vote has negative validator index")
        if (
            vote.height != self.height
            or vote.round != self.round
            or vote.type != self.signed_msg_type
        ):
            raise ValueError(
                f"vote H/R/T {vote.height}/{vote.round}/{vote.type} does not "
                f"match VoteSet {self.height}/{self.round}/{self.signed_msg_type}"
            )
        val = self.val_set.get_by_index(val_index)
        if val is None:
            raise ValueError(f"validator index {val_index} out of range")
        if val.address != vote.validator_address:
            raise ValueError("vote validator address does not match index")

        # dedupe / conflict detection before paying for verification
        existing = self.votes[val_index]
        if existing is not None:
            if existing.block_id == vote.block_id:
                if existing.signature != vote.signature and not verified:
                    # same vote, different signature: only the first counts
                    raise ValueError("non-deterministic signature")
                return False  # duplicate

        if not verified:
            if not vote.verify(self.chain_id, val.pub_key):
                raise ValueError("invalid vote signature")

        block_key = vote.block_id.key()
        by_block_existing = self.votes_by_block.get(block_key)
        if (
            by_block_existing is not None
            and by_block_existing.votes[val_index] is not None
        ):
            return False  # already tracked for this block (duplicate)
        if existing is not None and existing.block_id.key() != block_key:
            if by_block_existing is None or not by_block_existing.peer_maj23:
                # equivocation — surfaced for duplicate-vote evidence; the
                # conflicting vote is NOT tallied (reference vote_set.go:209)
                raise ConflictingVoteError(existing, vote)
            # tracked because a peer claimed 2/3 for this block; fall through

        by_block = self.votes_by_block.get(block_key)
        if by_block is None:
            by_block = _BlockVotes.new(False, self.size())
            self.votes_by_block[block_key] = by_block

        if existing is None:
            self.votes[val_index] = vote
            self.votes_bit_array.set(val_index, True)
            self.sum += val.voting_power

        before = by_block.sum
        by_block.add_verified_vote(vote, val.voting_power)
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        if before < quorum <= by_block.sum and self.maj23 is None:
            self.maj23 = vote.block_id
            # promote this block's votes into the canonical list
            for i, v in enumerate(by_block.votes):
                if v is not None:
                    self.votes[i] = v
        return existing is None or existing.block_id.key() != block_key

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """A peer claims to have seen 2/3 for block_id; start tracking its
        votes even if they conflict with this node's view
        (reference vote_set.go SetPeerMaj23)."""
        existing = self.peer_maj23s.get(peer_id)
        if existing is not None:
            if existing != block_id:
                raise ValueError("conflicting maj23 claim from peer")
            return
        self.peer_maj23s[peer_id] = block_id
        key = block_id.key()
        if key not in self.votes_by_block:
            self.votes_by_block[key] = _BlockVotes.new(True, self.size())
        else:
            self.votes_by_block[key].peer_maj23 = True

    # --- queries ----------------------------------------------------------

    def get_vote(self, val_index: int, block_key: bytes) -> Optional[Vote]:
        v = (
            self.votes[val_index]
            if 0 <= val_index < len(self.votes)
            else None
        )
        if v is not None and v.block_id.key() == block_key:
            return v
        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            return bv.votes[val_index]
        return None

    def get_by_index(self, val_index: int) -> Optional[Vote]:
        return self.votes[val_index]

    def bit_array(self) -> BitArray:
        return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> Optional[BitArray]:
        bv = self.votes_by_block.get(block_id.key())
        return bv.bit_array.copy() if bv else None

    def has_two_thirds_majority(self) -> bool:
        return self.maj23 is not None

    def two_thirds_majority(self) -> tuple[BlockID, bool]:
        if self.maj23 is not None:
            return self.maj23, True
        return BlockID(), False

    def has_two_thirds_any(self) -> bool:
        return self.sum > self.val_set.total_voting_power() * 2 // 3

    def has_all(self) -> bool:
        return self.sum == self.val_set.total_voting_power()

    # --- commit construction ---------------------------------------------

    def make_commit(self) -> Commit:
        if self.signed_msg_type != VoteType.PRECOMMIT:
            raise ValueError("cannot make commit from non-precommit VoteSet")
        if self.maj23 is None:
            raise ValueError("cannot make commit: no 2/3 majority")
        if self.maj23.is_zero():
            raise ValueError("cannot make commit: 2/3 majority is for nil")
        sigs = []
        for v in self.votes:
            if v is not None and v.block_id == self.maj23:
                flag = BlockIDFlag.COMMIT
            elif v is not None and v.is_nil():
                flag = BlockIDFlag.NIL
            else:
                sigs.append(CommitSig.absent())
                continue
            sigs.append(
                CommitSig(
                    block_id_flag=flag,
                    validator_address=v.validator_address,
                    timestamp_ns=v.timestamp_ns,
                    signature=v.signature,
                    bls_signature=v.bls_signature,
                    qc_signature=v.qc_signature,
                )
            )
        return Commit(
            height=self.height,
            round=self.round,
            block_id=self.maj23,
            signatures=sigs,
        )

    def __repr__(self) -> str:
        return (
            f"VoteSet{{H:{self.height} R:{self.round} T:{self.signed_msg_type}"
            f" {self.votes_bit_array}}}"
        )
