"""Block part sets — blocks gossiped as merkle-proven 64KB chunks.

Reference: types/part_set.go (`Part`, `PartSetHeader`, `PartSet`). Blocks
are serialized, split into BlockPartSizeBytes chunks, and each part carries
a merkle proof against the PartSetHeader hash that rides in the BlockID.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..crypto import merkle
from ..libs import protoio as pio
from ..libs.bits import BitArray

BLOCK_PART_SIZE_BYTES = 65536


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and len(self.hash) == 0

    def validate_basic(self) -> None:
        if self.total < 0:
            raise ValueError("negative part set total")
        if self.hash and len(self.hash) != 32:
            raise ValueError("wrong part set hash size")

    def encode(self) -> bytes:
        return pio.field_varint(1, self.total) + pio.field_bytes(2, self.hash)

    @classmethod
    def decode(cls, data: bytes) -> "PartSetHeader":
        f = pio.decode_fields(data)
        return cls(total=f.get(1, [0])[0], hash=f.get(2, [b""])[0])


@dataclass
class Part:
    index: int
    bytes_: bytes
    proof: merkle.Proof
    # memoized wire encoding: a part is immutable after construction and
    # is re-encoded per gossip send AND per block-store save on the host
    # hot path — §10-style cache, ~64KB copied instead of re-framed
    _encoded: Optional[bytes] = field(
        default=None, compare=False, repr=False
    )

    def validate_basic(self) -> None:
        if self.index < 0:
            raise ValueError("negative part index")
        if len(self.bytes_) > BLOCK_PART_SIZE_BYTES:
            raise ValueError("part too big")

    def encode(self) -> bytes:
        if self._encoded is not None:
            return self._encoded
        proof = (
            pio.field_varint(1, self.proof.total)
            + pio.field_varint(2, self.proof.index)
            + pio.field_bytes(3, self.proof.leaf_hash)
            + b"".join(pio.field_bytes(4, a) for a in self.proof.aunts)
        )
        self._encoded = (
            pio.field_varint(1, self.index)
            + pio.field_bytes(2, self.bytes_)
            + pio.field_message(3, proof)
        )
        return self._encoded

    @classmethod
    def decode(cls, data: bytes) -> "Part":
        f = pio.decode_fields(data)
        pf = pio.decode_fields(f[3][0])
        proof = merkle.Proof(
            total=pf.get(1, [0])[0],
            index=pf.get(2, [0])[0],
            leaf_hash=pf.get(3, [b""])[0],
            aunts=pf.get(4, []),
        )
        return cls(
            index=f.get(1, [0])[0], bytes_=f.get(2, [b""])[0], proof=proof
        )


class PartSet:
    """Either built complete from a block's bytes (proposer side) or
    assembled incrementally from gossiped parts (receiver side)."""

    def __init__(self, header: PartSetHeader):
        self._header = header
        self._parts: list[Optional[Part]] = [None] * header.total
        self._bit_array = BitArray(header.total)
        self._count = 0
        self._byte_size = 0

    @classmethod
    def from_data(
        cls, data: bytes, part_size: int = BLOCK_PART_SIZE_BYTES
    ) -> "PartSet":
        chunks = [
            data[i : i + part_size] for i in range(0, len(data), part_size)
        ] or [b""]
        root, proofs = merkle.proofs_from_byte_slices(chunks)
        ps = cls(PartSetHeader(total=len(chunks), hash=root))
        for i, (chunk, proof) in enumerate(zip(chunks, proofs)):
            part = Part(index=i, bytes_=chunk, proof=proof)
            ps._parts[i] = part
            ps._bit_array.set(i, True)
            ps._count += 1
            ps._byte_size += len(chunk)
        return ps

    @property
    def header(self) -> PartSetHeader:
        return self._header

    def has_header(self, h: PartSetHeader) -> bool:
        return self._header == h

    @property
    def bit_array(self) -> BitArray:
        return self._bit_array.copy()

    @property
    def byte_size(self) -> int:
        return self._byte_size

    @property
    def count(self) -> int:
        return self._count

    @property
    def total(self) -> int:
        return self._header.total

    def is_complete(self) -> bool:
        return self._count == self._header.total

    def get_part(self, index: int) -> Optional[Part]:
        if 0 <= index < len(self._parts):
            return self._parts[index]
        return None

    def add_part(self, part: Part) -> bool:
        """Returns True if added; raises on invalid proof (the reference's
        ErrPartSetInvalidProof)."""
        if part.index >= self._header.total:
            raise ValueError("part index out of bounds")
        if self._parts[part.index] is not None:
            return False
        if not part.proof.verify(self._header.hash, part.bytes_):
            raise ValueError("invalid part proof")
        if part.proof.index != part.index or part.proof.total != self.total:
            raise ValueError("part proof index mismatch")
        self._parts[part.index] = part
        self._bit_array.set(part.index, True)
        self._count += 1
        self._byte_size += len(part.bytes_)
        return True

    def get_bytes(self) -> bytes:
        if not self.is_complete():
            raise ValueError("part set incomplete")
        return b"".join(p.bytes_ for p in self._parts)  # type: ignore
