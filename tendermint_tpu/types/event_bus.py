"""EventBus — typed publishers over the pubsub server.

Reference: types/event_bus.go:33 (EventBus wrapping pubsub.Server with
typed publish methods :102-161) + types/events.go event names. RPC
websocket subscriptions and the tx/block indexers all hang off this.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..libs.pubsub import PubSubServer, Query, Subscription

# event type tag (reference types/events.go)
EVENT_TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"
BLOCK_HEIGHT_KEY = "block.height"

EventNewBlock = "NewBlock"
EventNewBlockHeader = "NewBlockHeader"
EventNewRound = "NewRound"
EventNewRoundStep = "NewRoundStep"
EventCompleteProposal = "CompleteProposal"
EventPolka = "Polka"
EventLock = "Lock"
EventRelock = "Relock"
EventTimeoutPropose = "TimeoutPropose"
EventTimeoutWait = "TimeoutWait"
EventUnlock = "Unlock"
EventValidBlock = "ValidBlock"
EventVote = "Vote"
EventTx = "Tx"
EventValidatorSetUpdates = "ValidatorSetUpdates"
EventNewEvidence = "NewEvidence"


def query_for_event(event_type: str) -> Query:
    return Query(f"{EVENT_TYPE_KEY} = '{event_type}'")


class EventBus:
    def __init__(self):
        self._server = PubSubServer()

    def subscribe(
        self, subscriber: str, query: Query, capacity: Optional[int] = None
    ) -> Subscription:
        return self._server.subscribe(subscriber, query, capacity)

    def unsubscribe(self, subscriber: str, query: Query) -> None:
        self._server.unsubscribe(subscriber, query)

    def unsubscribe_all(self, subscriber: str) -> None:
        self._server.unsubscribe_all(subscriber)

    def num_clients(self) -> int:
        return self._server.num_clients()

    def num_client_subscriptions(self, subscriber: str) -> int:
        return self._server.num_client_subscriptions(subscriber)

    async def _publish(
        self, event_type: str, data: Any, extra: Optional[dict] = None
    ) -> None:
        events = {EVENT_TYPE_KEY: [event_type]}
        if extra:
            for k, v in extra.items():
                events.setdefault(k, []).extend(v)
        await self._server.publish(data, events)

    async def publish_new_block(
        self, block, result_events: Optional[dict] = None
    ) -> None:
        extra = {BLOCK_HEIGHT_KEY: [str(block.header.height)]}
        if result_events:
            for k, v in result_events.items():
                extra.setdefault(k, []).extend(v)
        await self._publish(EventNewBlock, block, extra)

    async def publish_new_block_header(self, header) -> None:
        await self._publish(
            EventNewBlockHeader,
            header,
            {BLOCK_HEIGHT_KEY: [str(header.height)]},
        )

    async def publish_tx(
        self,
        height: int,
        tx_hash: bytes,
        tx: bytes,
        result_events: Optional[dict] = None,
    ) -> None:
        extra = {
            TX_HASH_KEY: [tx_hash.hex().upper()],
            TX_HEIGHT_KEY: [str(height)],
        }
        if result_events:
            for k, v in result_events.items():
                extra.setdefault(k, []).extend(v)
        await self._publish(EventTx, (height, tx_hash, tx), extra)

    async def publish_vote(self, vote) -> None:
        await self._publish(EventVote, vote)

    async def publish_new_round_step(self, rs) -> None:
        await self._publish(EventNewRoundStep, rs)

    async def publish_new_round(self, rs) -> None:
        await self._publish(EventNewRound, rs)

    async def publish_complete_proposal(self, rs) -> None:
        await self._publish(EventCompleteProposal, rs)

    async def publish_polka(self, rs) -> None:
        await self._publish(EventPolka, rs)

    async def publish_lock(self, rs) -> None:
        await self._publish(EventLock, rs)

    async def publish_unlock(self, rs) -> None:
        await self._publish(EventUnlock, rs)

    async def publish_relock(self, rs) -> None:
        await self._publish(EventRelock, rs)

    async def publish_timeout_propose(self, rs) -> None:
        await self._publish(EventTimeoutPropose, rs)

    async def publish_timeout_wait(self, rs) -> None:
        await self._publish(EventTimeoutWait, rs)

    async def publish_valid_block(self, rs) -> None:
        await self._publish(EventValidBlock, rs)

    async def publish_validator_set_updates(self, updates) -> None:
        await self._publish(EventValidatorSetUpdates, updates)

    async def publish_new_evidence(self, evidence, height: int) -> None:
        await self._publish(
            EventNewEvidence, (evidence, height)
        )
