"""Canonical sign-bytes for votes and proposals.

Reference: types/canonical.go:18,57 + types/vote.go:95-103 — sign-bytes are
`protoio.MarshalDelimited(CanonicalVote{...})` where CanonicalVote uses
sfixed64 height/round (fixed-width so signing devices can parse offsets) and
a trailing chain_id. The per-vote timestamp makes every vote's message
unique — which is why the TPU verifier takes ragged per-vote messages
(SURVEY.md §7.3 hard part 4).

Timestamps are integer nanoseconds since the Unix epoch throughout the
framework; they encode here as protobuf Timestamp (seconds + nanos).
"""

from __future__ import annotations

from ..libs import protoio as pio

# SignedMsgType values (reference types/signed_msg_type.go)
PREVOTE_TYPE = 1
PRECOMMIT_TYPE = 2
PROPOSAL_TYPE = 32


def encode_timestamp(ns: int) -> bytes:
    seconds, nanos = divmod(ns, 1_000_000_000)
    return pio.field_varint(1, seconds) + pio.field_varint(2, nanos)


def decode_timestamp(data: bytes) -> int:
    fields = pio.decode_fields(data)
    seconds = fields.get(1, [0])[0]
    nanos = fields.get(2, [0])[0]
    return seconds * 1_000_000_000 + nanos


def _canonical_part_set_header(total: int, hash_: bytes) -> bytes:
    return pio.field_varint(1, total) + pio.field_bytes(2, hash_)


def canonical_block_id(hash_: bytes, psh_total: int, psh_hash: bytes) -> bytes:
    """CanonicalBlockID; empty when the block id is nil (returns b'')."""
    if not hash_ and psh_total == 0 and not psh_hash:
        return b""
    return pio.field_bytes(1, hash_) + pio.field_message(
        2, _canonical_part_set_header(psh_total, psh_hash)
    )


class CanonicalVoteEncoder:
    """Stateless canonical encoders, exposed for privval/remote-signer
    compatibility checks."""

    @staticmethod
    def vote_parts(
        msg_type: int,
        height: int,
        round_: int,
        block_id_bytes: bytes,
        chain_id: str,
    ) -> tuple[bytes, bytes]:
        """(prefix, suffix) of the canonical vote body around its only
        per-signer field — the timestamp (field 5):
        vote(...) == marshal_delimited(prefix + field_message(5,
        encode_timestamp(ts)) + suffix). Exposed so batch commit
        verification can encode O(validators) sign-bytes per commit
        without re-encoding the shared fields (types/block.py caches
        these parts per commit); `vote` below composes the same parts,
        keeping one source of truth for the layout."""
        prefix = b"".join(
            [
                pio.field_varint(1, msg_type),
                pio.field_sfixed64(2, height),
                pio.field_sfixed64(3, round_),
                (
                    pio.field_message(4, block_id_bytes)
                    if block_id_bytes
                    else b""
                ),
            ]
        )
        return prefix, pio.field_bytes(6, chain_id.encode())

    @staticmethod
    def vote_from_parts(
        prefix: bytes, suffix: bytes, timestamp_ns: int
    ) -> bytes:
        """Assemble the final sign-bytes from vote_parts output — the
        ONLY place the timestamp field number and the delimited framing
        live, so cached-parts callers cannot drift from `vote`."""
        return pio.marshal_delimited(
            prefix
            + pio.field_message(5, encode_timestamp(timestamp_ns))
            + suffix
        )

    @staticmethod
    def vote(
        msg_type: int,
        height: int,
        round_: int,
        block_id_bytes: bytes,
        timestamp_ns: int,
        chain_id: str,
    ) -> bytes:
        prefix, suffix = CanonicalVoteEncoder.vote_parts(
            msg_type, height, round_, block_id_bytes, chain_id
        )
        return CanonicalVoteEncoder.vote_from_parts(
            prefix, suffix, timestamp_ns
        )

    @staticmethod
    def proposal(
        height: int,
        round_: int,
        pol_round: int,
        block_id_bytes: bytes,
        timestamp_ns: int,
        chain_id: str,
    ) -> bytes:
        body = b"".join(
            [
                pio.field_varint(1, PROPOSAL_TYPE),
                pio.field_sfixed64(2, height),
                pio.field_sfixed64(3, round_),
                pio.field_sfixed64(4, pol_round),
                (
                    pio.field_message(5, block_id_bytes)
                    if block_id_bytes
                    else b""
                ),
                pio.field_message(6, encode_timestamp(timestamp_ns)),
                pio.field_bytes(7, chain_id.encode()),
            ]
        )
        return pio.marshal_delimited(body)


def vote_sign_bytes(chain_id: str, vote) -> bytes:
    """The message the TPU verifier checks per vote
    (reference types/vote.go:95 VoteSignBytes)."""
    bid = vote.block_id
    return CanonicalVoteEncoder.vote(
        vote.type,
        vote.height,
        vote.round,
        canonical_block_id(
            bid.hash, bid.part_set_header.total, bid.part_set_header.hash
        ),
        vote.timestamp_ns,
        chain_id,
    )


def proposal_sign_bytes(chain_id: str, proposal) -> bytes:
    bid = proposal.block_id
    return CanonicalVoteEncoder.proposal(
        proposal.height,
        proposal.round,
        proposal.pol_round,
        canonical_block_id(
            bid.hash, bid.part_set_header.total, bid.part_set_header.hash
        ),
        proposal.timestamp_ns,
        chain_id,
    )
