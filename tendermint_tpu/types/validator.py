"""Validator — address, pubkey, voting power, proposer priority.

Reference: types/validator.go. Key-type agnostic: pubkey is any object with
`.data: bytes`, `.address() -> bytes`, `.verify(msg, sig) -> bool` and a
`.type_name` ("ed25519" / "secp256k1" / "sr25519" / "bls12-381").
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

from ..libs import protoio as pio
from ..crypto import ed25519


def pubkey_from_type(type_name: str, data: bytes):
    if type_name == "ed25519":
        return ed25519.PubKey(data)
    if type_name == "secp256k1":
        from ..crypto import secp256k1

        return secp256k1.PubKey(data)
    if type_name == "sr25519":
        from ..crypto import sr25519

        return sr25519.PubKey(data)
    raise ValueError(f"unknown pubkey type {type_name!r}")


def pubkey_type_name(pubkey) -> str:
    return getattr(pubkey, "type_name", "ed25519")


@dataclass
class Validator:
    pub_key: object  # crypto pubkey
    voting_power: int
    proposer_priority: int = 0
    _address: Optional[bytes] = None
    # morph QC plane: the validator's BLS12-381 public key (uncompressed
    # G2 wire, 192 bytes) — committed into the validator-set hash when
    # present, so a hash-verified set pins the keys a QuorumCertificate
    # aggregate verifies against. Empty = not QC-capable (legacy sets
    # hash identically: the field is omitted from the encoding).
    bls_pub_key: bytes = b""

    @property
    def address(self) -> bytes:
        if self._address is None:
            object.__setattr__(self, "_address", self.pub_key.address())
        return self._address

    def copy(self) -> "Validator":
        return Validator(
            self.pub_key, self.voting_power, self.proposer_priority,
            bls_pub_key=self.bls_pub_key,
        )

    def compare_proposer_priority(self, other: "Validator") -> "Validator":
        """Higher priority wins; ties break to the lower address
        (reference types/validator.go CompareProposerPriority)."""
        if self.proposer_priority > other.proposer_priority:
            return self
        if self.proposer_priority < other.proposer_priority:
            return other
        return self if self.address < other.address else other

    def encode(self) -> bytes:
        """Deterministic encoding for validator-set hashing
        (reference types/validator.go Bytes: pubkey + voting power)."""
        return (
            pio.field_bytes(1, pubkey_type_name(self.pub_key).encode())
            + pio.field_bytes(2, self.pub_key.data)
            + pio.field_varint(3, self.voting_power)
            # field 5 (4 is the set-level priority field, validator_set
            # encode): only present for QC-capable validators, so legacy
            # sets keep their exact hash
            + (
                pio.field_bytes(5, self.bls_pub_key)
                if self.bls_pub_key
                else b""
            )
        )

    def validate_basic(self) -> None:
        if self.pub_key is None:
            raise ValueError("validator has nil pubkey")
        if self.voting_power < 0:
            raise ValueError("validator has negative voting power")
        if len(self.address) != 20:
            raise ValueError("wrong validator address size")
        if self.bls_pub_key and len(self.bls_pub_key) != 192:
            raise ValueError("wrong bls pubkey size (uncompressed G2)")

    def __repr__(self) -> str:
        return (
            f"Validator{{{self.address.hex()[:12]} "
            f"VP:{self.voting_power} A:{self.proposer_priority}}}"
        )
