"""GenesisDoc — the chain's origin document (reference types/genesis.go)."""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Optional

from ..crypto import tmhash
from .params import ConsensusParams
from .validator import Validator, pubkey_from_type
from .validator_set import ValidatorSet

MAX_CHAIN_ID_LEN = 50


@dataclass
class GenesisValidator:
    pub_key_type: str
    pub_key_data: bytes
    power: int
    name: str = ""
    # QC plane: uncompressed G2 BLS key (192 bytes) — committed into the
    # validator-set hash so quorum certificates verify against it
    bls_pub_key: bytes = b""

    def to_validator(self) -> Validator:
        return Validator(
            pub_key=pubkey_from_type(self.pub_key_type, self.pub_key_data),
            voting_power=self.power,
            bls_pub_key=self.bls_pub_key,
        )


@dataclass
class GenesisDoc:
    chain_id: str
    genesis_time_ns: int = 0
    initial_height: int = 1
    consensus_params: ConsensusParams = field(default_factory=ConsensusParams)
    validators: list[GenesisValidator] = field(default_factory=list)
    app_hash: bytes = b""
    app_state: dict = field(default_factory=dict)

    def validate_and_complete(self) -> None:
        if not self.chain_id or len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise ValueError("invalid chain_id")
        if self.initial_height < 0:
            raise ValueError("initial_height cannot be negative")
        if self.initial_height == 0:
            self.initial_height = 1
        self.consensus_params.validate()
        for v in self.validators:
            if v.power < 0:
                raise ValueError("genesis validator with negative power")
        if self.genesis_time_ns == 0:
            self.genesis_time_ns = time.time_ns()

    def validator_set(self) -> ValidatorSet:
        return ValidatorSet([v.to_validator() for v in self.validators])

    def hash(self) -> bytes:
        return tmhash.sum(json.dumps(self.to_json(), sort_keys=True).encode())

    def to_json(self) -> dict:
        return {
            "chain_id": self.chain_id,
            "genesis_time": self.genesis_time_ns,
            "initial_height": self.initial_height,
            "consensus_params": self.consensus_params.to_json(),
            "validators": [
                {
                    "pub_key": {
                        "type": v.pub_key_type,
                        "value": v.pub_key_data.hex(),
                    },
                    "power": str(v.power),
                    "name": v.name,
                    **(
                        {"bls_pub_key": v.bls_pub_key.hex()}
                        if v.bls_pub_key
                        else {}
                    ),
                }
                for v in self.validators
            ],
            "app_hash": self.app_hash.hex(),
            "app_state": self.app_state,
        }

    @classmethod
    def from_json(cls, d: dict) -> "GenesisDoc":
        doc = cls(
            chain_id=d["chain_id"],
            genesis_time_ns=int(d.get("genesis_time", 0)),
            initial_height=int(d.get("initial_height", 1)),
            consensus_params=ConsensusParams.from_json(
                d.get("consensus_params", {})
            ),
            validators=[
                GenesisValidator(
                    pub_key_type=v["pub_key"]["type"],
                    pub_key_data=bytes.fromhex(v["pub_key"]["value"]),
                    power=int(v["power"]),
                    name=v.get("name", ""),
                    bls_pub_key=bytes.fromhex(v.get("bls_pub_key", "")),
                )
                for v in d.get("validators", [])
            ],
            app_hash=bytes.fromhex(d.get("app_hash", "")),
            app_state=d.get("app_state", {}),
        )
        doc.validate_and_complete()
        return doc

    def save_as(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2)

    @classmethod
    def from_file(cls, path: str) -> "GenesisDoc":
        with open(path) as f:
            return cls.from_json(json.load(f))
