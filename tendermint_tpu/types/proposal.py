"""Proposal — a proposer's signed block proposal for a round.

Reference: types/proposal.go. POLRound points at the round of the proof-of-
lock the proposer is re-proposing from (-1 when none).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..libs import protoio as pio
from . import canonical
from .block_id import BlockID


@dataclass
class Proposal:
    height: int
    round: int
    pol_round: int
    block_id: BlockID
    timestamp_ns: int
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.proposal_sign_bytes(chain_id, self)

    def validate_basic(self) -> None:
        if self.height < 0 or self.round < 0:
            raise ValueError("negative height/round")
        if self.pol_round < -1 or (
            self.pol_round >= 0 and self.pol_round >= self.round
        ):
            raise ValueError("invalid POL round")
        self.block_id.validate_basic()
        if not self.block_id.is_complete():
            raise ValueError("proposal block_id must be complete")
        if not self.signature or len(self.signature) > 64:
            raise ValueError("bad proposal signature")

    def encode(self) -> bytes:
        return b"".join(
            [
                pio.field_varint(1, self.height),
                pio.field_varint(2, self.round + 1),
                pio.field_varint(3, self.pol_round + 2),  # -1 encodes as 1
                pio.field_message(4, self.block_id.encode()),
                pio.field_varint(5, self.timestamp_ns),
                pio.field_bytes(6, self.signature),
            ]
        )

    @classmethod
    def decode(cls, data: bytes) -> "Proposal":
        f = pio.decode_fields(data)
        return cls(
            height=f.get(1, [0])[0],
            round=f.get(2, [1])[0] - 1,
            pol_round=f.get(3, [2])[0] - 2,
            block_id=BlockID.decode(f.get(4, [b""])[0]),
            timestamp_ns=f.get(5, [0])[0],
            signature=f.get(6, [b""])[0],
        )
