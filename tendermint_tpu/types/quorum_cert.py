"""QuorumCertificate — one BLS aggregate per commit instead of N sigs.

The paper's defining crypto delta is the BLS12-381 dual-sign plane: every
validator carries a BLS key next to its ed25519 consensus key and
dual-signs batch-point precommits for L1 aggregation. This module points
that plane at the OTHER cost center ("Performance of EdDSA and BLS
Signatures in Committee-Based Consensus", PAPERS.md): a commit ships and
re-verifies N ed25519 signatures in every blocksync/light/replay consumer,
so catchup and light-proof verification scale linearly in committee size.

With `[consensus] quorum_certificates` on, validators additionally
BLS-sign every non-nil precommit over a canonical QC message — one shared
message per (chain, height, round, block_id), unlike the ed25519 sign
bytes whose per-vote timestamp makes every message unique. At +2/3 the
per-vote contributions aggregate (G1 point sum) into a single
`QuorumCertificate`: a 96-byte aggregate signature plus a signer bitset
(`libs/bits.py` word-wise words on the wire). Consumers then verify ONE
aggregate pairing check against the signers' BLS keys (committed in the
validator set via `Validator.bls_pub_key`, so `validators_hash` pins
them) instead of N ed25519 rows — verify cost flat in committee size,
and a light proof collapses from N CommitSigs to ~100 bytes + bitset.

Verification routes through the `qc_verify` engine
(crypto/bls_signatures.verify_qc_items) — registered in both the in-proc
scheduler's wire-engine table and the verify-service's, so aggregate
checks coalesce into shared rounds (and one round's many QCs verify as a
single random-linear-combination multi-pairing) exactly like ed25519
batches.

Reference counterpart: none — the reference ships full commits
everywhere; the QC plane is the aggregate-signature round compression
the committee-crypto papers motivate (ROADMAP item 3).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..libs import protoio as pio
from ..libs.bits import BitArray
from . import canonical
from .block_id import BlockID

# domain prefix: a QC message can never collide with a batch hash (the
# dual-sign plane's other message family, raw 32-byte hashes) nor with
# the key-validation padding domain inside hash_to_g1
QC_DOMAIN = b"tm-tpu/qc/v1\x00"

# aggregate signature is one uncompressed G1 point
QC_SIG_BYTES = 96


def qc_sign_bytes(
    chain_id: str, height: int, round_: int, block_id: BlockID
) -> bytes:
    """The ONE message every QC contribution at (height, round, block)
    signs: the canonical precommit body WITHOUT the per-signer timestamp
    field, under the QC domain prefix. Same layout source of truth as
    the ed25519 sign bytes (CanonicalVoteEncoder), so the QC commits to
    exactly what the precommit committed to."""
    prefix, suffix = canonical.CanonicalVoteEncoder.vote_parts(
        canonical.PRECOMMIT_TYPE,
        height,
        round_,
        canonical.canonical_block_id(
            block_id.hash,
            block_id.part_set_header.total,
            block_id.part_set_header.hash,
        ),
        chain_id,
    )
    return QC_DOMAIN + prefix + suffix


@dataclass
class QuorumCertificate:
    """Aggregate precommit proof: `signers` indexes into the validator
    set at `height` (the set whose hash the certified header carries),
    `agg_signature` is the G1 sum of their per-vote QC signatures."""

    height: int
    round: int
    block_id: BlockID
    signers: BitArray
    agg_signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return qc_sign_bytes(chain_id, self.height, self.round, self.block_id)

    def num_signers(self) -> int:
        return self.signers.num_set()

    def proof_bytes(self) -> int:
        """Wire size of this proof — the number the light plane's
        compression claim is measured in."""
        return len(self.encode())

    def validate_basic(self) -> None:
        if self.height < 1:
            raise ValueError("qc height must be >= 1")
        if self.round < 0:
            raise ValueError("negative qc round")
        if self.block_id.is_zero():
            raise ValueError("qc cannot certify a nil block")
        if len(self.agg_signature) != QC_SIG_BYTES:
            raise ValueError(
                f"qc aggregate signature must be {QC_SIG_BYTES} bytes"
            )
        if self.signers.size <= 0 or self.signers.num_set() == 0:
            raise ValueError("qc has no signers")

    def encode(self) -> bytes:
        return b"".join(
            [
                pio.field_varint(1, self.height),
                pio.field_varint(2, self.round + 1),
                pio.field_message(3, self.block_id.encode()),
                pio.field_varint(4, self.signers.size),
                pio.field_bytes(5, self.signers.to_bytes()),
                pio.field_bytes(6, self.agg_signature),
            ]
        )

    @classmethod
    def decode(cls, data: bytes) -> "QuorumCertificate":
        f = pio.decode_fields(data)
        size = f.get(4, [0])[0]
        return cls(
            height=f.get(1, [0])[0],
            round=f.get(2, [1])[0] - 1,
            block_id=BlockID.decode(f.get(3, [b""])[0]),
            signers=BitArray.from_bytes(size, f.get(5, [b""])[0]),
            agg_signature=f.get(6, [b""])[0],
        )

    def __repr__(self) -> str:
        return (
            f"QC{{h={self.height}/{self.round} "
            f"signers={self.num_signers()}/{self.signers.size} "
            f"block={self.block_id.hash.hex()[:12]}}}"
        )


# --- assembly from retained CommitSigs -------------------------------------


def assemble_qc(chain_id: str, commit, val_set) -> Optional["QuorumCertificate"]:
    """Build a QuorumCertificate from a full Commit's retained
    CommitSigs (the on-demand path: proposers compress their seen
    commit; a store can compress any retained canonical commit).

    Counts ForBlock rows that carry a `qc_signature` AND whose validator
    has a registered BLS key. The aggregate is verified before it is
    returned — a byzantine validator's garbage contribution (its ed25519
    vote was valid, its QC dual-sign was not) is isolated by the
    random-linear-combination bisect and dropped. Returns None when the
    surviving signers hold <= 2/3 of the set's power: the commit stays
    servable as a full commit, it just cannot compress."""
    from ..crypto import bls_signatures as bls

    n = val_set.size()
    if commit is None or commit.size() != n:
        return None
    msg = qc_sign_bytes(chain_id, commit.height, commit.round, commit.block_id)
    idxs: list[int] = []
    pubs: list = []
    sigs: list = []
    for i, cs in enumerate(commit.signatures):
        if not cs.for_block() or not getattr(cs, "qc_signature", b""):
            continue
        val = val_set.get_by_index(i)
        if val is None or not val.bls_pub_key:
            continue
        try:
            # _qc_signer_key: the verify plane's once-per-distinct-key
            # parse cache — assembly re-runs per height on the proposer
            # and must not re-pay the subgroup check for a static set
            pub = bls.new_trusted_public_key(
                bls._qc_signer_key(val.bls_pub_key)
            )
            sig = bls.g1_from_bytes(cs.qc_signature)
        except bls.BLSError:
            continue  # unparseable contribution: neither list grows
        pubs.append(pub)
        sigs.append(sig)
        idxs.append(i)
    if not idxs:
        return None
    verdicts = bls.verify_batch_same_message(msg, pubs, sigs)
    good = [
        (i, s) for i, s, ok in zip(idxs, sigs, verdicts) if ok
    ]
    if not good:
        return None
    tallied = sum(
        val_set.get_by_index(i).voting_power for i, _ in good
    )
    if tallied <= val_set.total_voting_power() * 2 // 3:
        return None
    agg = bls.aggregate_signatures([s for _, s in good])
    return QuorumCertificate(
        height=commit.height,
        round=commit.round,
        block_id=commit.block_id,
        signers=BitArray.from_indices(n, [i for i, _ in good]),
        agg_signature=bls.g1_to_bytes(agg),
    )


# --- dispatch --------------------------------------------------------------


def qc_verify_items_direct(items: list[tuple]) -> list:
    """Direct (schedulerless) engine call — the fallback every dispatch
    path degrades to."""
    from ..crypto.bls_signatures import verify_qc_items

    return verify_qc_items(items)


def qc_dispatch(klass: str = "blocksync"):
    """items -> verdicts through the process verify scheduler's
    `qc_verify` engine under `klass` priority when one is installed
    (in-proc scheduler or the remote verify-service client — both carry
    the wire-fn surface, so cross-process coalescing is free), else the
    direct check. The returned callable is safe from worker threads; on
    an event-loop thread the scheduler self-degrades to direct."""

    def _verify(items: list[tuple]) -> list:
        from ..parallel.scheduler import default_scheduler

        sched = default_scheduler()
        if sched is None:
            return qc_verify_items_direct(items)
        return sched.submit_wire_fn_sync(
            "qc_verify",
            items,
            klass,
            fallback=lambda: qc_verify_items_direct(items),
        )

    return _verify
