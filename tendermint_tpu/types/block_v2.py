"""BlockV2 — the post-upgrade centralized-sequencer block format.

Reference: types/block_v2.go:15-42 (ExecutableL2Data-shaped block with an
ECDSA sequencer signature over the 32-byte block hash) and :80-93
(RecoverBlockV2Signer via eth-style recoverable signatures). The wire format
mirrors proto/tendermint/sequencer BlockV2 field numbering.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..crypto import secp256k1
from ..libs import protoio as pio

# process-wide count of ACTUAL wire serializations (memo misses): the
# encode-once fan-out bench asserts one serialization per broadcast
# block regardless of subscriber count (tests/bench read the delta)
_SERIALIZATIONS = 0


def serializations() -> int:
    """Total BlockV2 cache-miss serializations this process."""
    return _SERIALIZATIONS


@dataclass
class BlockV2:
    parent_hash: bytes = b"\x00" * 32
    miner: bytes = b"\x00" * 20
    number: int = 0
    gas_limit: int = 0
    base_fee: int = 0
    timestamp: int = 0
    transactions: list[bytes] = field(default_factory=list)
    state_root: bytes = b"\x00" * 32
    gas_used: int = 0
    receipt_root: bytes = b"\x00" * 32
    logs_bloom: bytes = b""
    withdraw_trie_root: bytes = b"\x00" * 32
    next_l1_message_index: int = 0
    hash: bytes = b"\x00" * 32
    signature: bytes = b""
    # memoized wire encoding (the Part.encode pattern): a sequencer
    # gossips one block to N subscriber peers and serves it again on the
    # 0x51 sync channel — one serialization, N framed copies. Field
    # assignment invalidates (__setattr__ below); in-place mutation of
    # `transactions` after an encode does not, same immutability
    # contract as Part.bytes_.
    _encoded: Optional[bytes] = field(default=None, compare=False, repr=False)

    def __setattr__(self, name, value):
        if name != "_encoded":
            object.__setattr__(self, "_encoded", None)
        object.__setattr__(self, name, value)

    # --- SyncableBlock interface (types/block_v2.go:57-63) ----------------

    def get_height(self) -> int:
        return self.number

    def get_hash(self) -> bytes:
        return self.hash

    # --- signatures --------------------------------------------------------

    def recover_signer(self) -> Optional[bytes]:
        """Eth address of the signer, or None (RecoverBlockV2Signer,
        types/block_v2.go:80-93)."""
        if not self.signature:
            return None
        return secp256k1.eth_recover_address(self.hash, self.signature)

    # --- wire (proto field numbering of seqproto.BlockV2) -------------------

    def encode(self) -> bytes:
        if self._encoded is not None:
            return self._encoded
        global _SERIALIZATIONS
        _SERIALIZATIONS += 1
        out = b""
        out += pio.field_bytes(1, self.parent_hash)
        out += pio.field_bytes(2, self.miner)
        out += pio.field_varint(3, self.number)
        out += pio.field_varint(4, self.gas_limit)
        out += pio.field_bytes(
            5,
            self.base_fee.to_bytes((self.base_fee.bit_length() + 7) // 8, "big")
            if self.base_fee
            else b"",
        )
        out += pio.field_varint(6, self.timestamp)
        for tx in self.transactions:
            out += pio.field_bytes(7, tx)
        out += pio.field_bytes(8, self.state_root)
        out += pio.field_varint(9, self.gas_used)
        out += pio.field_bytes(10, self.receipt_root)
        out += pio.field_bytes(11, self.logs_bloom)
        out += pio.field_bytes(12, self.withdraw_trie_root)
        out += pio.field_varint(13, self.next_l1_message_index)
        out += pio.field_bytes(14, self.hash)
        out += pio.field_bytes(15, self.signature)
        # assign via object.__setattr__: a plain assignment would
        # immediately invalidate the cache it is trying to fill
        object.__setattr__(self, "_encoded", out)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "BlockV2":
        b = cls()
        b.transactions = []
        for num, wire, val in pio.iter_fields(data):
            if num == 1:
                b.parent_hash = val
            elif num == 2:
                b.miner = val
            elif num == 3:
                b.number = val
            elif num == 4:
                b.gas_limit = val
            elif num == 5:
                b.base_fee = int.from_bytes(val, "big") if val else 0
            elif num == 6:
                b.timestamp = val
            elif num == 7:
                b.transactions.append(val)
            elif num == 8:
                b.state_root = val
            elif num == 9:
                b.gas_used = val
            elif num == 10:
                b.receipt_root = val
            elif num == 11:
                b.logs_bloom = val
            elif num == 12:
                b.withdraw_trie_root = val
            elif num == 13:
                b.next_l1_message_index = val
            elif num == 14:
                b.hash = val
            elif num == 15:
                b.signature = val
        if len(b.parent_hash) != 32:
            raise ValueError("invalid parent hash length")
        if len(b.hash) != 32:
            raise ValueError("invalid block hash length")
        return b
