"""ValidatorSet — membership, proposer rotation, and commit verification.

Reference: types/validator_set.go. Two things matter here:

1. **Proposer priority arithmetic** (validator_set.go:105-246): the
   deterministic weighted-round-robin. Reproduced exactly (rescale to the
   2×total window, center on zero, add voting power, pick max, subtract
   total) because every node must agree on the proposer.

2. **Commit verification** (VerifyCommit :676, VerifyCommitLight :730,
   VerifyCommitLightTrusting :782) — the reference's serial per-signer
   ed25519 loops with 2/3 early exit. Here each becomes ONE TPU batch:
   gather (pubkey, sign-bytes, sig) for every counted signer, verify all at
   once, tally voting power under the accept mask (SURVEY.md §2.3: "full-
   batch verify + masked power tally"). Semantics note: the reference
   fails on the first invalid signature it happens to scan before reaching
   2/3; the masked tally simply never counts invalid signatures, so any
   commit carrying ≥2/3 of valid power verifies — never weaker, order-
   independent, and branch-free on device. VerifyCommit (the full variant)
   still requires every non-absent signature to be valid, as upstream does.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..crypto import merkle
from ..crypto.batch_verifier import BatchVerifier, SigItem, default_verifier
from ..libs import protoio as pio
from .block import BlockIDFlag, Commit
from .block_id import BlockID
from .validator import Validator, pubkey_from_type, pubkey_type_name

PRIORITY_WINDOW_SIZE_FACTOR = 2
MAX_TOTAL_VOTING_POWER = 2**63 // 8


def _default_qc_engine():
    """Scheduler-routed qc_verify dispatch (blocksync class: the bulk
    consumers — catchup, light, replay — are the QC verify callers;
    live consensus paths pass their own engine)."""
    from .quorum_cert import qc_dispatch

    return qc_dispatch("blocksync")


class ValidatorSet:
    def __init__(self, validators: list[Validator]):
        self.validators: list[Validator] = sorted(
            [v.copy() for v in validators], key=lambda v: v.address
        )
        self.proposer: Optional[Validator] = None
        self._total_voting_power: Optional[int] = None
        self._hash: Optional[bytes] = None
        if self.validators:
            self._validate_unique()
            self.increment_proposer_priority(1)

    @classmethod
    def empty(cls) -> "ValidatorSet":
        return cls([])

    def _validate_unique(self) -> None:
        seen = set()
        for v in self.validators:
            v.validate_basic()
            if v.address in seen:
                raise ValueError(f"duplicate validator {v.address.hex()}")
            seen.add(v.address)

    # --- basic queries ----------------------------------------------------

    def size(self) -> int:
        return len(self.validators)

    def is_nil_or_empty(self) -> bool:
        return not self.validators

    def total_voting_power(self) -> int:
        if self._total_voting_power is None:
            t = sum(v.voting_power for v in self.validators)
            if t > MAX_TOTAL_VOTING_POWER:
                raise ValueError("total voting power exceeds maximum")
            self._total_voting_power = t
        return self._total_voting_power

    def get_by_address(self, addr: bytes) -> tuple[int, Optional[Validator]]:
        for i, v in enumerate(self.validators):
            if v.address == addr:
                return i, v
        return -1, None

    def get_by_index(self, idx: int) -> Optional[Validator]:
        if 0 <= idx < len(self.validators):
            return self.validators[idx]
        return None

    def has_address(self, addr: bytes) -> bool:
        return self.get_by_address(addr)[0] >= 0

    def hash(self) -> bytes:
        """Merkle root of validator encodings
        (reference types/validator_set.go:351). Memoized — the
        encoding excludes proposer priority, so only membership/power
        changes (update_with_change_set) invalidate; callers on the
        serving hot path (lightserve verdict keys, per-vote header
        checks) hash the same shared set per request."""
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [v.encode() for v in self.validators]
            )
        return self._hash

    def copy(self) -> "ValidatorSet":
        vs = ValidatorSet.__new__(ValidatorSet)
        vs.validators = [v.copy() for v in self.validators]
        if self.proposer is not None:
            i, _ = self.get_by_address(self.proposer.address)
            vs.proposer = vs.validators[i] if i >= 0 else self.proposer.copy()
        else:
            vs.proposer = None
        vs._total_voting_power = self._total_voting_power
        vs._hash = self._hash
        return vs

    # --- proposer priority (validator_set.go:105-246) ---------------------

    def increment_proposer_priority(self, times: int) -> None:
        if not self.validators:
            raise ValueError("empty validator set")
        if times <= 0:
            raise ValueError("times must be positive")
        diff_max = PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        self._rescale_priorities(diff_max)
        self._shift_by_avg_proposer_priority()
        proposer = None
        for _ in range(times):
            proposer = self._increment_proposer_priority_once()
        self.proposer = proposer

    def _increment_proposer_priority_once(self) -> Validator:
        for v in self.validators:
            v.proposer_priority += v.voting_power
        mostest = self.validators[0]
        for v in self.validators[1:]:
            mostest = mostest.compare_proposer_priority(v)
        mostest.proposer_priority -= self.total_voting_power()
        return mostest

    def _rescale_priorities(self, diff_max: int) -> None:
        if diff_max <= 0 or not self.validators:
            return
        prios = [v.proposer_priority for v in self.validators]
        diff = max(prios) - min(prios)
        if diff > diff_max:
            ratio = (diff + diff_max - 1) // diff_max
            for v in self.validators:
                # Go integer division truncates toward zero
                q = abs(v.proposer_priority) // ratio
                v.proposer_priority = q if v.proposer_priority >= 0 else -q

    def _shift_by_avg_proposer_priority(self) -> None:
        n = len(self.validators)
        total = sum(v.proposer_priority for v in self.validators)
        avg = abs(total) // n
        avg = avg if total >= 0 else -avg  # truncate toward zero
        for v in self.validators:
            v.proposer_priority -= avg

    def get_proposer(self) -> Validator:
        if not self.validators:
            raise ValueError("empty validator set")
        if self.proposer is None:
            mostest = self.validators[0]
            for v in self.validators[1:]:
                mostest = mostest.compare_proposer_priority(v)
            self.proposer = mostest
        return self.proposer

    def copy_increment_proposer_priority(self, times: int) -> "ValidatorSet":
        c = self.copy()
        c.increment_proposer_priority(times)
        return c

    # --- updates (validator_set.go UpdateWithChangeSet) -------------------

    def update_with_change_set(self, changes: list[Validator]) -> None:
        if not changes:
            return
        by_addr = {}
        for c in changes:
            if c.voting_power < 0:
                raise ValueError("voting power cannot be negative")
            if c.address in by_addr:
                raise ValueError("duplicate address in changes")
            by_addr[c.address] = c

        removals = {a for a, c in by_addr.items() if c.voting_power == 0}
        for a in removals:
            if not self.has_address(a):
                raise ValueError("removing unknown validator")

        updated: dict[bytes, Validator] = {
            v.address: v for v in self.validators
        }
        # compute the new total first: new members join with priority
        # -1.125 * new_total (validator_set.go computeNewPriorities)
        tentative = dict(updated)
        for a, c in by_addr.items():
            if a in removals:
                tentative.pop(a, None)
            else:
                tentative[a] = c
        new_total = sum(v.voting_power for v in tentative.values())
        if new_total > MAX_TOTAL_VOTING_POWER:
            raise ValueError("total voting power exceeds maximum")

        for a, c in by_addr.items():
            if a in removals:
                updated.pop(a, None)
                continue
            prev = updated.get(a)
            nv = c.copy()
            if prev is None:
                nv.proposer_priority = -(new_total + (new_total >> 3))
            else:
                nv.proposer_priority = prev.proposer_priority
                # a power update with no BLS key keeps the key on
                # record — otherwise every L2 rotation would silently
                # strip QC capability from sitting members
                if not nv.bls_pub_key:
                    nv.bls_pub_key = prev.bls_pub_key
            updated[a] = nv

        self.validators = sorted(updated.values(), key=lambda v: v.address)
        self._total_voting_power = None
        self._hash = None  # membership/power changed
        if self.validators:
            self._rescale_priorities(
                PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
            )
            self._shift_by_avg_proposer_priority()
            # recompute proposer pointer into the new list
            if self.proposer is not None:
                i, v = self.get_by_address(self.proposer.address)
                self.proposer = v if i >= 0 else None

    # --- commit verification (the TPU batch path) -------------------------

    def _gather_items(
        self,
        chain_id: str,
        commit: Commit,
        only_for_block: bool,
    ) -> tuple[list[SigItem], list[int]]:
        """(items, indices): one SigItem per counted commit signature.

        The per-commit (prefix, suffix) sign-bytes parts are built ONCE
        ahead of the per-validator loop — within a commit only the
        timestamp field differs, so each row is a cheap three-way concat
        (the §10 commit-encode fix, hoisted; this gather is what every
        commit-verify caller — consensus gossip, blocksync, light
        client, evidence — runs per batch)."""
        from .canonical import CanonicalVoteEncoder

        items, idxs = [], []
        parts_for = commit._sign_bytes_parts(chain_id, True)
        parts_nil = None  # lazily: absent in the light (ForBlock) paths
        for i, cs in enumerate(commit.signatures):
            if cs.is_absent():
                continue
            if cs.for_block():
                prefix, suffix = parts_for
            elif only_for_block:
                continue
            else:
                if parts_nil is None:
                    parts_nil = commit._sign_bytes_parts(chain_id, False)
                prefix, suffix = parts_nil
            val = self.validators[i]
            items.append(
                SigItem(
                    val.pub_key.data,
                    CanonicalVoteEncoder.vote_from_parts(
                        prefix, suffix, cs.timestamp_ns
                    ),
                    cs.signature,
                    key_type=getattr(val.pub_key, "type_name", "ed25519"),
                )
            )
            idxs.append(i)
        return items, idxs

    def verify_commits_light(
        self,
        chain_id: str,
        entries: list,
        verifier: Optional[BatchVerifier] = None,
    ) -> list[bool]:
        """Light-verify MANY commits as ONE device batch.

        entries: [(block_id, height, commit)]. Returns a per-commit verdict
        list (no exception per commit — callers fall back per entry). This
        is the blocksync/light bulk shape (SURVEY.md §3.4: pipeline many
        blocks' commits as one sharded batch instead of one device call per
        block; reference loops serially at blocksync/reactor.go:553).
        All commits must be against THIS validator set — callers batch
        only across heights with an unchanged set.
        """
        verifier = verifier or default_verifier()
        all_items: list[SigItem] = []
        spans = []  # (start, idxs); idxs=None -> malformed entry
        for block_id, height, commit in entries:
            try:
                if commit is None:
                    raise ValueError("nil commit")
                self._check_commit_shape(block_id, height, commit)
            except ValueError:
                spans.append((len(all_items), None))
                continue
            items, idxs = self._gather_items(chain_id, commit, True)
            spans.append((len(all_items), idxs))
            all_items.extend(items)
        ok = verifier.verify(all_items) if all_items else []
        out = []
        for start, idxs in spans:
            if idxs is None:
                out.append(False)
                continue
            tallied = sum(
                self.validators[i].voting_power
                for valid, i in zip(ok[start : start + len(idxs)], idxs)
                if valid
            )
            try:
                self._check_maj23(tallied)
                out.append(True)
            except ValueError:
                out.append(False)
        return out

    def verify_commit(
        self,
        chain_id: str,
        block_id: BlockID,
        height: int,
        commit: Commit,
        verifier: Optional[BatchVerifier] = None,
    ) -> None:
        """Full verification (reference :676): every non-absent signature
        must be valid AND >2/3 of total power must have signed the block."""
        self._check_commit_shape(block_id, height, commit)
        verifier = verifier or default_verifier()
        items, idxs = self._gather_items(chain_id, commit, False)
        ok = verifier.verify(items)
        tallied = 0
        for valid, i in zip(ok, idxs):
            if not valid:
                raise ValueError(f"wrong signature at index {i}")
            if commit.signatures[i].for_block():
                tallied += self.validators[i].voting_power
        self._check_maj23(tallied)

    def verify_commit_light(
        self,
        chain_id: str,
        block_id: BlockID,
        height: int,
        commit: Commit,
        verifier: Optional[BatchVerifier] = None,
    ) -> None:
        """Light verification (reference :730, the blocksync/light-client
        hot path): only ForBlock signatures counted; masked tally replaces
        the serial 2/3 early exit."""
        self._check_commit_shape(block_id, height, commit)
        verifier = verifier or default_verifier()
        items, idxs = self._gather_items(chain_id, commit, True)
        ok = verifier.verify(items)
        tallied = sum(
            self.validators[i].voting_power
            for valid, i in zip(ok, idxs)
            if valid
        )
        self._check_maj23(tallied)

    def verify_commit_light_trusting(
        self,
        chain_id: str,
        commit: Commit,
        trust_numerator: int = 1,
        trust_denominator: int = 3,
        verifier: Optional[BatchVerifier] = None,
    ) -> None:
        """Trusted-overlap verification (reference :782): this (old,
        trusted) set need only overlap the commit by > trust-level of its
        own power. Signers are matched by address, not index."""
        if trust_denominator == 0:
            raise ValueError("trust level has zero denominator")
        from .canonical import CanonicalVoteEncoder

        verifier = verifier or default_verifier()
        items, powers = [], []
        seen: set[bytes] = set()
        # parts hoisted out of the per-validator loop (only ForBlock rows
        # are gathered here, so one (prefix, suffix) covers every row)
        prefix, suffix = commit._sign_bytes_parts(chain_id, True)
        for i, cs in enumerate(commit.signatures):
            if not cs.for_block():
                continue
            idx, val = self.get_by_address(cs.validator_address)
            if idx < 0 or val is None:
                continue
            if val.address in seen:
                raise ValueError("double vote from validator")
            seen.add(val.address)
            items.append(
                SigItem(
                    val.pub_key.data,
                    CanonicalVoteEncoder.vote_from_parts(
                        prefix, suffix, cs.timestamp_ns
                    ),
                    cs.signature,
                    key_type=getattr(val.pub_key, "type_name", "ed25519"),
                )
            )
            powers.append(val.voting_power)
        ok = verifier.verify(items)
        tallied = sum(p for valid, p in zip(ok, powers) if valid)
        needed = (
            self.total_voting_power() * trust_numerator
        ) // trust_denominator
        if tallied <= needed:
            raise ValueError(
                f"insufficient trusted voting power: {tallied} <= {needed}"
            )

    # --- quorum-certificate verification (the QC plane) -------------------

    def qc_capable(self) -> bool:
        """True when every member carries a BLS key — the precondition
        for verifying (and assembling) quorum certificates against this
        set."""
        return bool(self.validators) and all(
            v.bls_pub_key for v in self.validators
        )

    def _qc_item(self, chain_id: str, qc) -> tuple[bytes, bytes, bytes, int]:
        """(msg, agg_sig, signer-keys-concat, tallied-power) for one QC
        against this set, after the structural checks. Raises ValueError
        on shape/quorum problems — the cryptographic verdict is the
        engine's."""
        if qc is None:
            raise ValueError("nil quorum certificate")
        qc.validate_basic()
        if qc.signers.size != self.size():
            raise ValueError(
                f"qc signer bitset size {qc.signers.size} != "
                f"valset size {self.size()}"
            )
        keys = []
        tallied = 0
        for i in qc.signers.ones():
            val = self.validators[i]
            if not val.bls_pub_key:
                raise ValueError(
                    f"validator {i} has no bls key; set is not qc-capable"
                )
            keys.append(val.bls_pub_key)
            tallied += val.voting_power
        self._check_maj23(tallied)
        return (
            qc.sign_bytes(chain_id),
            qc.agg_signature,
            b"".join(keys),
            tallied,
        )

    def verify_commit_qc(
        self,
        chain_id: str,
        block_id: BlockID,
        height: int,
        qc,
        engine=None,
    ) -> None:
        """The QC replacement for verify_commit_light: >2/3 of this
        set's power in the signer bitset, then ONE aggregate pairing
        check over the signers' committed BLS keys — cost flat in
        committee size. `engine` is an items->verdicts callable (the
        qc_verify engine); defaults to the scheduler-routed dispatch."""
        if height != qc.height:
            raise ValueError("qc height mismatch")
        if block_id != qc.block_id:
            raise ValueError("qc block id mismatch")
        msg, sig, keys, _ = self._qc_item(chain_id, qc)
        engine = engine or _default_qc_engine()
        ok = engine([(msg, sig, keys)])
        if not (len(ok) == 1 and ok[0]):
            raise ValueError("invalid quorum certificate aggregate")

    def verify_commits_qc(
        self, chain_id: str, entries: list, engine=None
    ) -> list[bool]:
        """Bulk form — entries: [(block_id, height, qc)], one verdict
        per entry (no exception per entry; callers fall back per
        height). All well-shaped entries verify as ONE engine
        submission, i.e. one random-linear-combination multi-pairing
        round for the whole blocksync window."""
        items = []
        spans: list[int] = []  # item index per entry; -1 = malformed
        for block_id, height, qc in entries:
            try:
                if qc is None:
                    raise ValueError("nil qc")
                if height != qc.height:
                    raise ValueError("qc height mismatch")
                if block_id != qc.block_id:
                    raise ValueError("qc block id mismatch")
                msg, sig, keys, _ = self._qc_item(chain_id, qc)
            except ValueError:
                spans.append(-1)
                continue
            spans.append(len(items))
            items.append((msg, sig, keys))
        engine = engine or _default_qc_engine()
        ok = engine(items) if items else []
        return [bool(ok[s]) if s >= 0 else False for s in spans]

    def verify_commit_qc_trusting(
        self,
        chain_id: str,
        qc,
        signer_set: "ValidatorSet",
        trust_numerator: int = 1,
        trust_denominator: int = 3,
        engine=None,
    ) -> None:
        """QC form of verify_commit_light_trusting: the aggregate
        verifies against `signer_set` (the NEW set, whose hash the
        certified header pins), and this (old, trusted) set need only
        overlap the signers by > trust-level of its own power — matched
        by address, exactly like the commit path, but proven by the one
        aggregate check instead of per-signer verifies."""
        if trust_denominator == 0:
            raise ValueError("trust level has zero denominator")
        msg, sig, keys, _ = signer_set._qc_item(chain_id, qc)
        engine = engine or _default_qc_engine()
        ok = engine([(msg, sig, keys)])
        if not (len(ok) == 1 and ok[0]):
            raise ValueError("invalid quorum certificate aggregate")
        tallied = 0
        seen: set[bytes] = set()
        for i in qc.signers.ones():
            addr = signer_set.validators[i].address
            if addr in seen:
                continue
            seen.add(addr)
            idx, val = self.get_by_address(addr)
            if idx >= 0 and val is not None:
                tallied += val.voting_power
        needed = (
            self.total_voting_power() * trust_numerator
        ) // trust_denominator
        if tallied <= needed:
            raise ValueError(
                f"insufficient trusted voting power: {tallied} <= {needed}"
            )

    def _check_commit_shape(
        self, block_id: BlockID, height: int, commit: Commit
    ) -> None:
        if self.size() != commit.size():
            raise ValueError(
                f"commit size {commit.size()} != valset size {self.size()}"
            )
        if height != commit.height:
            raise ValueError("commit height mismatch")
        if block_id != commit.block_id:
            raise ValueError("commit block id mismatch")

    def _check_maj23(self, tallied: int) -> None:
        needed = self.total_voting_power() * 2 // 3
        if tallied <= needed:
            raise ValueError(
                f"insufficient voting power: {tallied} <= {needed}"
            )

    # --- encoding ---------------------------------------------------------

    def encode(self) -> bytes:
        body = b"".join(
            pio.field_message(
                1,
                v.encode() + pio.field_varint(4, v.proposer_priority + 2**62),
            )
            for v in self.validators
        )
        if self.proposer is not None:
            body += pio.field_bytes(2, self.proposer.address)
        return body

    @classmethod
    def decode(cls, data: bytes) -> "ValidatorSet":
        f = pio.decode_fields(data)
        vals = []
        for vd in f.get(1, []):
            vf = pio.decode_fields(vd)
            pk = pubkey_from_type(
                vf.get(1, [b"ed25519"])[0].decode(), vf[2][0]
            )
            v = Validator(
                pub_key=pk,
                voting_power=vf.get(3, [0])[0],
                proposer_priority=vf.get(4, [2**62])[0] - 2**62,
                bls_pub_key=vf.get(5, [b""])[0],
            )
            vals.append(v)
        vs = cls.__new__(cls)
        vs.validators = sorted(vals, key=lambda v: v.address)
        vs._total_voting_power = None
        vs._hash = None
        vs.proposer = None
        if 2 in f:
            i, v = vs.get_by_address(f[2][0])
            vs.proposer = v
        return vs

    def __repr__(self) -> str:
        return f"ValidatorSet{{n={self.size()} tvp={self.total_voting_power()}}}"
