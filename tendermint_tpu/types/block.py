"""Block, Header, Commit, CommitSig, Data.

Reference: types/block.go (Block:43, Header:338, CommitSig:623, Commit:657).
Morph-specific capabilities reproduced here:
- `Header.batch_hash` (types/block.go:366) — the L2 batch hash at batch
  points,
- `CommitSig.bls_signature` (types/block.go:628) — BLS12-381 dual signature
  carried in commits,
- `Data.l2_block_meta` / `Data.l2_batch_header` (types/block.go:1037-1038)
  — opaque L2 payloads produced by the execution node and committed with
  the block.

Hashes are RFC 6962 merkle roots of deterministic field encodings
(spec/core/encoding.md shape); this framework defines its own wire, it does
not chase the reference's protobuf bytes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..crypto import merkle
from ..libs import protoio as pio
from . import canonical
from .block_id import BlockID
from .part_set import PartSet, PartSetHeader
from .quorum_cert import QuorumCertificate

BLOCK_PROTOCOL_VERSION = 11  # reference version/version.go block protocol


class BlockIDFlag:
    ABSENT = 1
    COMMIT = 2
    NIL = 3


# --- header ---------------------------------------------------------------


@dataclass
class Header:
    chain_id: str = ""
    height: int = 0
    time_ns: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""
    batch_hash: bytes = b""  # morph: L2 batch hash (types/block.go:366)
    version_block: int = BLOCK_PROTOCOL_VERSION
    version_app: int = 0
    _hash: Optional[bytes] = field(default=None, compare=False, repr=False)

    def hash(self) -> bytes:
        """Merkle root over the 15 encoded header fields (the reference
        hashes 14, types/block.go:494; batch_hash is the 15th here).
        Cached — the consensus hot path compares header hashes per vote;
        mutators (fill_header, the batch-point edit) must reset `_hash`."""
        if not self.validators_hash:
            return b""
        if self._hash is not None:
            return self._hash
        fields = [
            pio.field_varint(1, self.version_block)
            + pio.field_varint(2, self.version_app),
            self.chain_id.encode(),
            pio.write_varint(self.height),
            canonical.encode_timestamp(self.time_ns),
            self.last_block_id.encode(),
            self.last_commit_hash,
            self.data_hash,
            self.validators_hash,
            self.next_validators_hash,
            self.consensus_hash,
            self.app_hash,
            self.last_results_hash,
            self.evidence_hash,
            self.proposer_address,
            self.batch_hash,
        ]
        self._hash = merkle.hash_from_byte_slices(fields)
        return self._hash

    def validate_basic(self) -> None:
        if not self.chain_id or len(self.chain_id) > 50:
            raise ValueError("bad chain id")
        if self.height < 0:
            raise ValueError("negative height")
        self.last_block_id.validate_basic()
        for name in (
            "last_commit_hash",
            "data_hash",
            "validators_hash",
            "next_validators_hash",
            "consensus_hash",
            "last_results_hash",
            "evidence_hash",
        ):
            v = getattr(self, name)
            if v and len(v) != 32:
                raise ValueError(f"wrong {name} size")
        if self.proposer_address and len(self.proposer_address) != 20:
            raise ValueError("wrong proposer address size")

    def encode(self) -> bytes:
        return b"".join(
            [
                pio.field_bytes(1, self.chain_id.encode()),
                pio.field_varint(2, self.height),
                pio.field_varint(3, self.time_ns),
                pio.field_message(4, self.last_block_id.encode()),
                pio.field_bytes(5, self.last_commit_hash),
                pio.field_bytes(6, self.data_hash),
                pio.field_bytes(7, self.validators_hash),
                pio.field_bytes(8, self.next_validators_hash),
                pio.field_bytes(9, self.consensus_hash),
                pio.field_bytes(10, self.app_hash),
                pio.field_bytes(11, self.last_results_hash),
                pio.field_bytes(12, self.evidence_hash),
                pio.field_bytes(13, self.proposer_address),
                pio.field_bytes(14, self.batch_hash),
                pio.field_varint(15, self.version_block),
                pio.field_varint(16, self.version_app),
            ]
        )

    @classmethod
    def decode(cls, data: bytes) -> "Header":
        f = pio.decode_fields(data)

        def g(n, d=b""):
            return f.get(n, [d])[0]

        return cls(
            chain_id=g(1).decode(),
            height=f.get(2, [0])[0],
            time_ns=f.get(3, [0])[0],
            last_block_id=BlockID.decode(g(4)),
            last_commit_hash=g(5),
            data_hash=g(6),
            validators_hash=g(7),
            next_validators_hash=g(8),
            consensus_hash=g(9),
            app_hash=g(10),
            last_results_hash=g(11),
            evidence_hash=g(12),
            proposer_address=g(13),
            batch_hash=g(14),
            version_block=f.get(15, [0])[0],
            version_app=f.get(16, [0])[0],
        )


# --- commit ---------------------------------------------------------------


@dataclass
class CommitSig:
    block_id_flag: int
    validator_address: bytes = b""
    timestamp_ns: int = 0
    signature: bytes = b""
    bls_signature: bytes = b""  # morph: types/block.go:628
    # QC plane: the per-vote BLS signature over the canonical QC message
    # — retained in the commit so a QuorumCertificate can be assembled
    # on demand from any stored commit (types/quorum_cert.assemble_qc)
    qc_signature: bytes = b""

    @classmethod
    def absent(cls) -> "CommitSig":
        return cls(block_id_flag=BlockIDFlag.ABSENT)

    def for_block(self) -> bool:
        return self.block_id_flag == BlockIDFlag.COMMIT

    def is_absent(self) -> bool:
        return self.block_id_flag == BlockIDFlag.ABSENT

    def validate_basic(self) -> None:
        if self.block_id_flag not in (
            BlockIDFlag.ABSENT,
            BlockIDFlag.COMMIT,
            BlockIDFlag.NIL,
        ):
            raise ValueError("unknown block id flag")
        if self.is_absent():
            if self.validator_address or self.signature:
                raise ValueError("absent commit sig with data")
        else:
            if len(self.validator_address) != 20:
                raise ValueError("wrong validator address size")
            if not self.signature or len(self.signature) > 64:
                raise ValueError("bad signature size")

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        """The BlockID this signature actually signed over."""
        if self.for_block():
            return commit_block_id
        return BlockID()

    def encode(self) -> bytes:
        return b"".join(
            [
                pio.field_varint(1, self.block_id_flag),
                pio.field_bytes(2, self.validator_address),
                pio.field_message(
                    3, canonical.encode_timestamp(self.timestamp_ns)
                ),
                pio.field_bytes(4, self.signature),
                pio.field_bytes(5, self.bls_signature),
                pio.field_bytes(6, self.qc_signature),
            ]
        )

    @classmethod
    def decode(cls, data: bytes) -> "CommitSig":
        f = pio.decode_fields(data)
        return cls(
            block_id_flag=f.get(1, [0])[0],
            validator_address=f.get(2, [b""])[0],
            timestamp_ns=canonical.decode_timestamp(f.get(3, [b""])[0]),
            signature=f.get(4, [b""])[0],
            bls_signature=f.get(5, [b""])[0],
            qc_signature=f.get(6, [b""])[0],
        )


@dataclass
class Commit:
    height: int
    round: int
    block_id: BlockID
    signatures: list[CommitSig] = field(default_factory=list)
    _hash: Optional[bytes] = field(default=None, compare=False, repr=False)
    _sb_parts: Optional[dict] = field(default=None, compare=False, repr=False)

    def size(self) -> int:
        return len(self.signatures)

    def _sign_bytes_parts(
        self, chain_id: str, for_block: bool
    ) -> tuple[bytes, bytes]:
        """Cached (prefix, suffix) of the canonical precommit around the
        timestamp field: within one commit every counted signature signs
        the same type/height/round/block_id/chain_id — only field 5 (the
        per-vote timestamp) differs. Batch verification encodes O(vals)
        sign-bytes per commit, and the full encode was the measured host
        bottleneck of the blocksync bulk path (~70 us/sig in r5).

        Like `_hash`, the cache assumes the commit is immutable after
        construction: any mutator of height/round/block_id must reset
        both `_hash` and `_sb_parts` (none exists today)."""
        cache = self._sb_parts
        if cache is None:
            cache = self._sb_parts = {}
        parts = cache.get((chain_id, for_block))
        if parts is None:
            bid = self.block_id if for_block else BlockID()
            parts = canonical.CanonicalVoteEncoder.vote_parts(
                canonical.PRECOMMIT_TYPE,
                self.height,
                self.round,
                canonical.canonical_block_id(
                    bid.hash,
                    bid.part_set_header.total,
                    bid.part_set_header.hash,
                ),
                chain_id,
            )
            cache[(chain_id, for_block)] = parts
        return parts

    def vote_sign_bytes(self, chain_id: str, idx: int) -> bytes:
        """Reconstructs the canonical precommit message signer idx signed
        (reference types/block.go Commit.VoteSignBytes) — the per-signer
        message fed to the TPU batch kernel during commit verification.
        Byte-identical to CanonicalVoteEncoder.vote (pinned by
        tests/test_types.py) but assembled from per-commit cached parts."""
        cs = self.signatures[idx]
        prefix, suffix = self._sign_bytes_parts(chain_id, cs.for_block())
        return canonical.CanonicalVoteEncoder.vote_from_parts(
            prefix, suffix, cs.timestamp_ns
        )

    def hash(self) -> bytes:
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [cs.encode() for cs in self.signatures]
            )
        return self._hash

    def bit_array(self):
        from ..libs.bits import BitArray

        return BitArray.from_bools(
            [not cs.is_absent() for cs in self.signatures]
        )

    def validate_basic(self) -> None:
        if self.height < 0 or self.round < 0:
            raise ValueError("negative height/round")
        if self.height >= 1:
            if self.block_id.is_zero():
                raise ValueError("commit cannot be for nil block")
            if not self.signatures:
                raise ValueError("no signatures in commit")
            for cs in self.signatures:
                cs.validate_basic()

    def encode(self) -> bytes:
        return b"".join(
            [
                pio.field_varint(1, self.height),
                pio.field_varint(2, self.round + 1),
                pio.field_message(3, self.block_id.encode()),
            ]
            + [pio.field_message(4, cs.encode()) for cs in self.signatures]
        )

    @classmethod
    def decode(cls, data: bytes) -> "Commit":
        f = pio.decode_fields(data)
        return cls(
            height=f.get(1, [0])[0],
            round=f.get(2, [1])[0] - 1,
            block_id=BlockID.decode(f.get(3, [b""])[0]),
            signatures=[CommitSig.decode(d) for d in f.get(4, [])],
        )


# --- data (txs + L2 payloads) ---------------------------------------------


@dataclass(frozen=True)
class L2BlockMeta:
    """Opaque per-block metadata from the L2 execution node
    (reference types/block.go:1037 L2BlockMeta)."""

    raw: bytes = b""


@dataclass(frozen=True)
class L2BatchHeader:
    """Opaque sealed-batch header from the L2 node at batch points
    (reference types/block.go:1038 L2BatchHeader)."""

    raw: bytes = b""


@dataclass
class Data:
    txs: list[bytes] = field(default_factory=list)
    l2_block_meta: bytes = b""
    l2_batch_header: bytes = b""
    _hash: Optional[bytes] = field(default=None, compare=False, repr=False)

    def hash(self) -> bytes:
        # domain-separated leaves: txs are \x00-prefixed, the two L2
        # payload leaves always present with their own prefixes — so no
        # tx list can collide with an L2 payload under the same root
        if self._hash is None:
            leaves = [b"\x00" + tx for tx in self.txs] + [
                b"\x01" + self.l2_block_meta,
                b"\x02" + self.l2_batch_header,
            ]
            self._hash = merkle.hash_from_byte_slices(leaves)
        return self._hash

    def encode(self) -> bytes:
        return (
            b"".join(pio.field_bytes(1, b"\x00" + tx) for tx in self.txs)
            + pio.field_bytes(2, self.l2_block_meta)
            + pio.field_bytes(3, self.l2_batch_header)
        )

    @classmethod
    def decode(cls, data: bytes) -> "Data":
        f = pio.decode_fields(data)
        return cls(
            txs=[t[1:] for t in f.get(1, [])],
            l2_block_meta=f.get(2, [b""])[0],
            l2_batch_header=f.get(3, [b""])[0],
        )


# --- block ----------------------------------------------------------------


@dataclass
class Block:
    header: Header
    data: Data = field(default_factory=Data)
    evidence: list = field(default_factory=list)
    last_commit: Optional[Commit] = None
    # QC plane: the aggregate certificate for last_commit's height,
    # carried NEXT TO the full commit (never instead of it on the block
    # wire — legacy consumers keep verifying the N-sig commit; QC
    # consumers verify one pairing). Not covered by any header hash: a
    # QC is self-certifying against the validator set the certified
    # header commits to.
    last_qc: Optional["QuorumCertificate"] = None
    # memoized (part_size, PartSet): chunking + merkle-proving the
    # encoded block is the priciest host hash on the commit/gossip path
    # and callers re-derive it per call (blocksync window + fallback,
    # block_id()); mutators (fill_header, set_batch_point) invalidate
    _part_set: Optional[tuple[int, PartSet]] = field(
        default=None, compare=False, repr=False
    )

    def hash(self) -> bytes:
        return self.header.hash()

    def is_batch_point(self) -> bool:
        """True if this block seals an L2 batch (reference
        types/block.go IsBatchPoint: non-empty BatchHash)."""
        return bool(self.header.batch_hash)

    def set_batch_point(self, batch_hash: bytes, batch_header: bytes) -> None:
        """Mark this block as a batch point (morph decideBatchPoint):
        mutates header.batch_hash + data.l2_batch_header and keeps the
        hash caches coherent — the only sanctioned post-fill mutation."""
        self.header.batch_hash = batch_hash
        self.data.l2_batch_header = batch_header
        self.data._hash = None
        self.header._hash = None
        self._part_set = None
        self.header.data_hash = self.data.hash()

    def fill_header(self) -> None:
        """Computes the derived header hashes from contents
        (reference Block.fillHeader, types/block.go)."""
        self.header._hash = None
        self._part_set = None
        if not self.header.last_commit_hash and self.last_commit is not None:
            self.header.last_commit_hash = self.last_commit.hash()
        if not self.header.data_hash:
            self.header.data_hash = self.data.hash()
        if not self.header.evidence_hash:
            self.header.evidence_hash = merkle.hash_from_byte_slices(
                [ev.encode() for ev in self.evidence]
            )

    def make_part_set(self, part_size: int = 65536) -> PartSet:
        cached = self._part_set
        if cached is not None and cached[0] == part_size:
            return cached[1]
        ps = PartSet.from_data(self.encode(), part_size)
        self._part_set = (part_size, ps)
        return ps

    def block_id(self, part_set: Optional[PartSet] = None) -> BlockID:
        ps = part_set or self.make_part_set()
        return BlockID(hash=self.hash(), part_set_header=ps.header)

    def validate_basic(self) -> None:
        self.header.validate_basic()
        if self.header.height > 1:
            if self.last_commit is None:
                raise ValueError("nil last commit")
            self.last_commit.validate_basic()
        if (
            self.last_commit is not None
            and self.header.last_commit_hash != self.last_commit.hash()
        ):
            raise ValueError("wrong last commit hash")
        if self.last_qc is not None:
            self.last_qc.validate_basic()
            if self.last_qc.height != self.header.height - 1:
                raise ValueError("last qc height mismatch")
            if self.last_qc.block_id != self.header.last_block_id:
                raise ValueError("last qc block id mismatch")
        if self.header.data_hash != self.data.hash():
            raise ValueError("wrong data hash")

    def encode(self) -> bytes:
        from .evidence import encode_evidence_list

        return b"".join(
            [
                pio.field_message(1, self.header.encode()),
                pio.field_message(2, self.data.encode()),
                pio.field_message(3, encode_evidence_list(self.evidence)),
                (
                    pio.field_message(4, self.last_commit.encode())
                    if self.last_commit is not None
                    else b""
                ),
                (
                    pio.field_message(5, self.last_qc.encode())
                    if self.last_qc is not None
                    else b""
                ),
            ]
        )

    @classmethod
    def decode(cls, data: bytes) -> "Block":
        from .evidence import decode_evidence_list

        f = pio.decode_fields(data)
        last_commit = None
        if 4 in f:
            last_commit = Commit.decode(f[4][0])
        last_qc = None
        if 5 in f:
            last_qc = QuorumCertificate.decode(f[5][0])
        return cls(
            header=Header.decode(f[1][0]),
            data=Data.decode(f.get(2, [b""])[0]),
            evidence=decode_evidence_list(f.get(3, [b""])[0]),
            last_commit=last_commit,
            last_qc=last_qc,
        )

    def __repr__(self) -> str:
        return (
            f"Block{{h={self.header.height} "
            f"hash={self.hash().hex()[:12]} txs={len(self.data.txs)}}}"
        )
