"""LightServePlane — the node's serving-plane bundle + in-proc provider.

Node assembly builds one plane per node ([lightserve] config): the
proof cache over the node's own block/state stores and the shared
ServeVerifier. The RPC routes (`light_block`, `signed_header`,
`validator_set` in rpc/core.py) serve from `plane.cache`; in-proc
harnesses (tools/lightserve_bench.py, tests) hand `plane.provider()`
to simulated LightClients so the swarm exercises the identical
assembly/caching path the RPC routes use, minus the HTTP hop.
"""

from __future__ import annotations

from typing import Optional

from ..libs.log import Logger, nop_logger
from ..libs.metrics import LightServeMetrics, default_metrics
from .cache import DEFAULT_CACHE_SIZE, LightBlockCache
from .verifier import (
    DEFAULT_REUSE_WINDOW_NS,
    ServeVerifier,
)


class LocalCacheProvider:
    """light.Provider over the serving plane's cache — what an in-proc
    simulated client syncs against (the RPC-transport equivalent is
    rpc/light_provider.RPCProvider hitting the `light_block` route)."""

    def __init__(self, cache: LightBlockCache, name: str = "lightserve"):
        self.cache = cache
        self._name = name

    async def light_block(self, height: int):
        return self.cache.get(height)

    def id(self) -> str:
        return self._name


class LightServePlane:
    def __init__(
        self,
        block_store,
        state_store,
        chain_id: str,
        cache_size: int = DEFAULT_CACHE_SIZE,
        dedup_window_ns: int = DEFAULT_REUSE_WINDOW_NS,
        verifier=None,
        metrics: Optional[LightServeMetrics] = None,
        logger: Optional[Logger] = None,
    ):
        self.chain_id = chain_id
        self.logger = logger or nop_logger()
        metrics = metrics or default_metrics(LightServeMetrics)
        self.cache = LightBlockCache(
            block_store,
            state_store,
            chain_id=chain_id,
            max_entries=cache_size,
            metrics=metrics,
        )
        self.verifier = ServeVerifier(
            verifier=verifier,
            reuse_window_ns=dedup_window_ns,
            metrics=metrics,
            logger=self.logger,
        )

    def provider(self, name: str = "lightserve") -> LocalCacheProvider:
        return LocalCacheProvider(self.cache, name=name)

    def stats(self) -> dict:
        return {"cache": self.cache.stats(), "verify": self.verifier.stats()}
