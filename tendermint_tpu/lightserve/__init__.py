"""Light-client serving plane (ROADMAP item 4).

The repo always had the CLIENT half of skipping verification (`light/`,
routed through the dispatch scheduler since PR 3); this package is the
SERVER half a full node needs to serve millions of light clients:

- `LightBlockCache` (cache.py): assemble each height's
  header+commit+validator-set proof ONCE from the (write-behind) block
  store and serve it to every client, LRU-bounded and pinned to the
  durable height so a crash/rollback can never leave a stale proof
  cached;
- `ServeVerifier` (verifier.py): accept thousands of concurrent
  skipping-verification requests, dedupe identical (trusted→target)
  hops, and ride the shared commit verifies through the process
  dispatch scheduler's `lightserve` lane so client bisections coalesce
  into shared device rounds instead of per-client programs;
- `LightServePlane` (plane.py): the node-assembly bundle ([lightserve]
  config) the RPC routes (`light_block`/`signed_header`/`validator_set`)
  and the in-proc swarm harness (tools/lightserve_bench.py) serve from.
"""

from .cache import LightBlockCache  # noqa: F401
from .plane import LightServePlane, LocalCacheProvider  # noqa: F401
from .verifier import ServeVerifier  # noqa: F401
