"""LightBlockCache — assemble each height's proof once, serve it to all.

A light client's `light_block(h)` costs the serving node three store
reads (meta, commit, validator set) plus the LightBlock assembly; a
thousand clients bisecting the same chain would repeat that work per
client per height. This cache does the assembly once per height and
serves the shared object.

Admission is pinned to the DURABLE height of the block store:

- the canonical commit for height h lives in block h+1's LastCommit, so
  an entry is only cacheable once h+1 exists — the seen commit at the
  tip may still be superseded by the canonical one and is served fresh,
  never cached;
- under the write-behind store (PR 4) "exists" means DURABLY saved:
  `durable_height` trails the logical height, and a crash replays from
  the durable range — an entry cached above it could outlive a rewind.
  Serving (not caching) reads the pending overlay like every other
  consumer;
- a rollback (`prune_blocks_since`) moves the durable height down;
  cached entries at/above it are dropped on next access instead of
  served stale (the "invalidation pinned to the durable height" rule).

Reference counterpart: none — the reference assembles commit+validators
per RPC request (rpc/core/blocks.go, consensus.go) with no cache.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional

from ..libs.metrics import LightServeMetrics, default_metrics
from ..light.types import LightBlock

DEFAULT_CACHE_SIZE = 1024


class LightBlockCache:
    def __init__(
        self,
        block_store,
        state_store,
        chain_id: str = "",
        max_entries: int = DEFAULT_CACHE_SIZE,
        metrics: Optional[LightServeMetrics] = None,
    ):
        self._block_store = block_store
        self._state_store = state_store
        self.chain_id = chain_id
        self.max_entries = max(1, int(max_entries))
        self._entries: "OrderedDict[int, LightBlock]" = OrderedDict()
        # RPC handlers run on the event loop, the swarm harness and the
        # write-behind worker touch stores from threads — cheap lock
        self._lock = threading.Lock()
        self.metrics = metrics or default_metrics(LightServeMetrics)
        # rollback detector: the durable watermark only ever moves up in
        # normal operation — observing it move DOWN means a rollback
        # happened, and every entry at/above the new watermark may no
        # longer match what the store will re-sync (see get())
        self._durable_seen = 0
        self.hits = 0
        self.misses = 0
        self.assembled = 0

    # --- durability pin -----------------------------------------------------

    def _durable_height(self) -> int:
        """Last height the store guarantees survives a crash: the
        write-behind store's durable watermark, or the plain store's
        height (synchronous saves are always durable)."""
        d = getattr(self._block_store, "durable_height", None)
        return int(d) if d is not None else self._block_store.height

    # --- the one entry point ------------------------------------------------

    def get_compressed(self, height: int = 0) -> Optional[LightBlock]:
        """The QC-compressed proof for `height`: header + validator set
        + QuorumCertificate, NO CommitSigs — the N-CommitSig payload a
        million-client read plane should not be shipping per request
        drops to ~100 bytes + signer bitset. Falls back to the full
        proof on heights without a canonical QC (legacy blocks, the
        tip). Shares the full-proof cache entry: the compressed view is
        a cheap per-request reshape, never a second assembly."""
        lb = self.get(height)
        if lb is None or lb.qc is None:
            return lb
        return LightBlock(lb.header, None, lb.validators, qc=lb.qc)

    def get(self, height: int = 0) -> Optional[LightBlock]:
        """The LightBlock for `height` (0 = the store head), cached when
        its canonical commit is durable, assembled fresh otherwise."""
        h = int(height) or self._block_store.height
        if h <= 0:
            return None
        durable = self._durable_height()
        with self._lock:
            if durable < self._durable_seen:
                # rollback observed: entries at/above the new watermark
                # could outlive a re-synced (different) chain, and once
                # the watermark recovers the per-entry `h < durable`
                # guard below can't tell — drop them now. (A rollback
                # whose dip-and-recover happens with NO intervening
                # access is not observable here; prune_blocks_since is
                # an offline op in practice, where the process restart
                # empties the cache anyway.)
                for stale in [k for k in self._entries if k >= durable]:
                    del self._entries[stale]
                self.metrics.cache_size.set(len(self._entries))
            self._durable_seen = durable
            lb = self._entries.get(h)
            if lb is not None:
                if h < durable:
                    self._entries.move_to_end(h)
                    self.hits += 1
                    self.metrics.cache_hits.inc()
                    return lb
                # rollback below the entry: never serve a proof the
                # store no longer stands behind
                del self._entries[h]
                self.metrics.cache_size.set(len(self._entries))
            self.misses += 1
            self.metrics.cache_misses.inc()
        lb = self._assemble(h)
        if lb is None:
            return None
        # cacheable iff the canonical commit (block h+1) is durable
        if h < durable:
            with self._lock:
                self._entries[h] = lb
                self._entries.move_to_end(h)
                while len(self._entries) > self.max_entries:
                    self._entries.popitem(last=False)
                self.metrics.cache_size.set(len(self._entries))
        return lb

    def _assemble(self, h: int) -> Optional[LightBlock]:
        t0 = time.perf_counter()
        meta = self._block_store.load_block_meta(h)
        if meta is None:
            return None
        # canonical commit first (block h+1's LastCommit); the seen
        # commit only serves the tip, where no canonical one exists yet
        commit = self._block_store.load_block_commit(h)
        if commit is None:
            commit = self._block_store.load_seen_commit(h)
        if commit is None:
            return None
        vals = self._state_store.load_validators(h)
        if vals is None:
            return None
        # canonical QC (block h+1's last_qc) rides the same entry; None
        # on legacy heights and at the tip
        qc = None
        load_qc = getattr(self._block_store, "load_block_qc", None)
        if load_qc is not None:
            qc = load_qc(h)
        self.assembled += 1
        self.metrics.cache_assemble_seconds.observe(
            time.perf_counter() - t0
        )
        return LightBlock(meta.header, commit, vals, qc=qc)

    # --- introspection ------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> dict:
        with self._lock:
            size = len(self._entries)
        return {
            "hits": self.hits,
            "misses": self.misses,
            "assembled": self.assembled,
            "hit_rate": round(self.hit_rate(), 4),
            "size": size,
            "durable_height": self._durable_height(),
        }
