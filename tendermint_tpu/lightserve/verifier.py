"""ServeVerifier — shared-round, deduped skipping verification.

A thousand server-assisted light clients syncing the same chain from
the same trust root all walk the SAME bisection: every one of them asks
to verify the identical (trusted→target) hop. Run naively, that is
N × (trusting-overlap verify + full-power verify) device work for one
distinct answer. This verifier is the serving plane's amortizer:

- **in-flight dedup**: concurrent requests for the same hop share one
  underlying verification — the first request runs it, everyone else
  awaits the shared future;
- **verdict reuse window**: a completed hop verdict (success or a
  VerificationError — including the ErrNewHeaderTooFarAhead that
  drives bisection) is reusable for `reuse_window_ns` of caller `now`.
  The time-dependent checks (trusting period, future-header drift) run
  per requester against the caller's own `now` BEFORE the cache — pure
  and cheap — so the shared verdict is exclusively the now-independent
  part (signatures, trust overlap, hash chain) and a skewed or
  malicious client can't poison the swarm's cache with its clock.
  Non-verification failures (provider/device errors) are never cached;
- **the `lightserve` scheduler lane**: the commit verifies underneath
  distinct hops run in executor threads against a classed dispatch
  adapter, so concurrent DISTINCT hops coalesce into shared device
  rounds through parallel/scheduler.py — below the node's own `light`
  class, so serving external clients never delays consensus, evidence,
  blocksync, or the node's own bisection.

Loop-affine: futures and the dedup maps belong to the event loop the
requests run on (one serving plane per node/harness loop).

Reference counterpart: none — the reference light client verifies per
client, and full nodes have no server-side verify assist at all.
"""

from __future__ import annotations

import asyncio
import functools
from collections import OrderedDict
from typing import Optional

from ..libs.log import Logger, nop_logger
from ..libs.metrics import LightServeMetrics, default_metrics
from ..light.types import LightBlock
from ..light.verifier import (
    DEFAULT_MAX_CLOCK_DRIFT_NS,
    VerificationError,
    _common_checks,
    _verify_commit_full_power,
    verify as _verify,
)

DEFAULT_REUSE_WINDOW_NS = 60 * 1_000_000_000
DEFAULT_MAX_VERDICTS = 4096

_KLASS = "lightserve"


def _commit_digest(commit) -> bytes:
    """The commit's content digest for the verdict-cache key: two
    commits for the same header but different signature sets verify
    differently, so the key must distinguish them. Commit.hash() is the
    memoized merkle root over the signature encodings — on the shared
    cache-served objects the per-request cost is an attribute read.
    QC-compressed proofs (commit=None) digest empty here; their proof
    content is keyed by _qc_digest."""
    return commit.hash() if commit is not None else b""


def _qc_digest(lb) -> bytes:
    """The QuorumCertificate's content digest for the verdict-cache
    key — a qc-compressed proof verifies through a different input set
    (signer bitset + aggregate) than the same header's full commit, so
    the two must not share a verdict entry."""
    qc = getattr(lb, "qc", None)
    return qc.encode() if qc is not None else b""


class ServeVerifier:
    def __init__(
        self,
        verifier=None,
        klass: str = _KLASS,
        reuse_window_ns: int = DEFAULT_REUSE_WINDOW_NS,
        max_verdicts: int = DEFAULT_MAX_VERDICTS,
        metrics: Optional[LightServeMetrics] = None,
        logger: Optional[Logger] = None,
    ):
        self._verifier = verifier
        self.klass = klass
        self.reuse_window_ns = int(reuse_window_ns)
        self.max_verdicts = max(1, int(max_verdicts))
        self.metrics = metrics or default_metrics(LightServeMetrics)
        self.logger = logger or nop_logger()
        self._inflight: dict[tuple, asyncio.Future] = {}
        # key -> (VerificationError-or-None, now_ns the verdict was
        # computed at); bounded LRU
        self._verdicts: "OrderedDict[tuple, tuple]" = OrderedDict()
        self.requests = 0
        self.deduped = 0
        self.executed = 0

    def _dispatch_verifier(self):
        """The commit-verify backend: an explicit verifier when injected
        (tests/bench isolation), else the process scheduler's classed
        adapter — resolved per call so a scheduler installed after
        construction is picked up."""
        if self._verifier is not None:
            return self._verifier
        from ..parallel.scheduler import default_dispatch

        return default_dispatch(self.klass)

    # --- the serving surface ------------------------------------------------

    async def verify_hop(
        self,
        trusted: LightBlock,
        untrusted: LightBlock,
        trusting_period_ns: int,
        now_ns: int,
        max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
    ) -> None:
        """One (trusted→target) verification hop — adjacent or skipping,
        same dispatch as light/verifier.verify. Raises VerificationError
        (incl. ErrNewHeaderTooFarAhead: bisect) exactly like the direct
        call; identical concurrent/recent hops share one verification.

        The time-dependent checks (trusting period, future-header drift)
        run HERE, per requester, against the caller's own `now` — cheap
        and pure, never cached. Only then does the request enter the
        shared cache, so the shared verdict is exclusively the
        now-independent part (signatures, trust overlap, hash chain):
        one clock-skewed — or malicious — client can neither poison the
        swarm with a from-the-future failure verdict nor ride a success
        verdict its own clock should reject.

        The cache key covers EVERY remaining verification input — both
        validator-set hashes and the untrusted commit digest, not just
        the header hashes — so a client submitting the real headers
        with a bogus trusted set (or a garbage commit) caches its
        failure under ITS key, never under the one honest clients
        compute."""
        _common_checks(
            trusted,
            untrusted,
            trusting_period_ns,
            now_ns,
            max_clock_drift_ns,
        )
        key = (
            "hop",
            trusted.header.hash(),
            trusted.validators.hash(),
            untrusted.header.hash(),
            untrusted.validators.hash(),
            _commit_digest(untrusted.commit),
            _qc_digest(untrusted),
            int(trusting_period_ns),
        )
        await self._run(
            key,
            now_ns,
            functools.partial(
                _verify,
                trusted,
                untrusted,
                trusting_period_ns,
                now_ns,
                max_clock_drift_ns,
                verifier=self._dispatch_verifier(),
            ),
            kind="hop",
        )

    async def verify_root(self, lb: LightBlock, now_ns: int = 0) -> None:
        """Trust-root full-power commit verify (client initialize):
        every swarm client pins the same root — one verification. Same
        complete-inputs key rule as verify_hop."""
        key = (
            "root",
            lb.header.hash(),
            lb.validators.hash(),
            _commit_digest(lb.commit),
            _qc_digest(lb),
        )
        await self._run(
            key,
            now_ns,
            functools.partial(
                _verify_commit_full_power,
                lb,
                verifier=self._dispatch_verifier(),
            ),
            kind="root",
        )

    # --- shared execution ---------------------------------------------------

    async def _run(self, key, now_ns, fn, kind: str) -> None:
        self.requests += 1
        self.metrics.verify_requests.inc(kind=kind)
        cached = self._verdicts.get(key)
        if cached is not None:
            outcome, at_ns = cached
            if abs(int(now_ns) - at_ns) <= self.reuse_window_ns:
                self._verdicts.move_to_end(key)
                self.deduped += 1
                self.metrics.verify_deduped.inc(kind=kind)
                if outcome is not None:
                    # shared instance: strip the traceback before each
                    # re-raise, or every reuse APPENDS its propagation
                    # frames to the one object and the LRU pins them
                    raise outcome.with_traceback(None)
                return
            self._verdicts.pop(key, None)
        loop = asyncio.get_running_loop()
        fut = self._inflight.get(key)
        if fut is not None:
            self.deduped += 1
            self.metrics.verify_deduped.inc(kind=kind)
        else:
            fut = loop.create_future()
            self._inflight[key] = fut
            # the verification runs in a VERIFIER-owned task, not the
            # first requester's: any client's sync — including the one
            # that triggered the work — can be cancelled without
            # aborting the verification the other waiters share
            loop.create_task(self._execute(key, now_ns, fn, kind, fut))
        # shield: a waiter's own cancellation detaches it from the
        # shared future without cancelling it
        outcome = await asyncio.shield(fut)
        if outcome is not None:
            raise outcome.with_traceback(None)

    async def _execute(self, key, now_ns, fn, kind: str, fut) -> None:
        outcome: Optional[BaseException] = None
        try:
            try:
                # executor thread: the classed adapter's blocking bridge
                # (scheduler.submit_sync) only engages OFF the loop, and
                # the device round must not freeze other clients
                await asyncio.get_running_loop().run_in_executor(None, fn)
            except VerificationError as e:
                outcome = e
            self.executed += 1
            self.metrics.verify_executed.inc(kind=kind)
            self._verdicts[key] = (outcome, int(now_ns))
            while len(self._verdicts) > self.max_verdicts:
                self._verdicts.popitem(last=False)
        except BaseException as e:
            # non-verification failure (provider/device error, loop
            # teardown): fail every waiter, cache nothing — the next
            # request retries. Failures travel as the future's RESULT
            # so an un-awaited future never logs "exception was never
            # retrieved".
            outcome = (
                e
                if isinstance(e, Exception)
                else RuntimeError(f"serve verification aborted: {e!r}")
            )
        finally:
            self._inflight.pop(key, None)
            if not fut.done():
                fut.set_result(outcome)

    # --- introspection ------------------------------------------------------

    def dedup_rate(self) -> float:
        return self.deduped / self.requests if self.requests else 0.0

    def stats(self) -> dict:
        return {
            "requests": self.requests,
            "deduped": self.deduped,
            "executed": self.executed,
            "dedup_rate": round(self.dedup_rate(), 4),
        }
