"""ABCI over gRPC — the reference's second remote transport.

Reference: abci/client/grpc_client.go + abci/server/grpc_server.go expose
the ABCIApplication service over gRPC next to the socket transport. This
framework keeps its hand-encoded wire (abci/types.py encode_rpc /
encode_result — the same payloads the socket transport frames) and
carries it over grpc.aio with a generic handler: one unary-unary method
per ABCI call under /tendermint_tpu.abci.ABCIApplication/<Method>, bytes
in/out, no protobuf codegen (the framework has none anywhere — see
libs/protoio.py).

Unlike the reference's grpc client (which is fire-and-forget per call and
documents itself as slower than the socket client), calls here are plain
awaited unary RPCs; concurrency discipline comes from the callers (the
proxy layer serializes per connection, as with the socket client).

Gated import: grpcio ships in this image, but everything degrades to a
clear error (not an import crash) if it is absent.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from . import types as abci
from .client import ABCIClientError, LocalClient

try:  # pragma: no cover - exercised by the import itself
    import grpc

    _GRPC_ERR = None
except Exception as e:  # pragma: no cover
    grpc = None
    _GRPC_ERR = e

SERVICE = "tendermint_tpu.abci.ABCIApplication"

# the reference service's method set (abci/types/types.proto service
# ABCIApplication) — used to register generic handlers
METHODS = (
    "echo",
    "info",
    "init_chain",
    "query",
    "check_tx",
    "begin_block",
    "deliver_tx",
    "end_block",
    "commit",
    "list_snapshots",
    "offer_snapshot",
    "load_snapshot_chunk",
    "apply_snapshot_chunk",
)


def _require_grpc() -> None:
    if grpc is None:
        raise ABCIClientError(
            f"grpc transport requires grpcio (import failed: {_GRPC_ERR});"
            " use the socket transport"
        )


def _method_path(method: str) -> str:
    # CamelCase the snake_case method for the wire path, matching the
    # reference's service method names (CheckTx, BeginBlock, ...)
    return "/{}/{}".format(
        SERVICE, "".join(p.capitalize() for p in method.split("_"))
    )


class GRPCServer:
    """ABCI app server over gRPC (reference abci/server/grpc_server.go)."""

    def __init__(self, app: abci.Application,
                 host: str = "127.0.0.1", port: int = 26658):
        _require_grpc()
        self._app = app
        self._host = host
        self.port = port
        self._server: Optional["grpc.aio.Server"] = None
        self._lock = asyncio.Lock()

    def _handler(self, method: str):
        async def unary(request: bytes, context) -> bytes:
            try:
                m, args = abci.decode_rpc(request)
                if m != method:
                    raise ABCIClientError(
                        f"method mismatch: path {method}, payload {m}"
                    )
                fn = getattr(self._app, m)
                # one app, many connections: serialize like LocalClient
                async with self._lock:
                    res = fn(*args)
                    if asyncio.iscoroutine(res):
                        res = await res
                return abci.encode_result(res)
            except Exception as e:
                return abci.encode_error(repr(e))

        return grpc.unary_unary_rpc_method_handler(
            unary,
            request_deserializer=None,  # raw bytes
            response_serializer=None,
        )

    async def start(self) -> None:
        self._server = grpc.aio.server()
        handlers = {
            _method_path(m).rsplit("/", 1)[1]: self._handler(m)
            for m in METHODS
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        self.port = self._server.add_insecure_port(
            f"{self._host}:{self.port}"
        )
        await self._server.start()

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=0.5)


class GRPCClient(LocalClient):
    """ABCI client over gRPC (reference abci/client/grpc_client.go).

    Drop-in for SocketClient: same call surface, same payload encoding.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 26658):
        _require_grpc()
        self._target = f"{host}:{port}"
        self._channel: Optional["grpc.aio.Channel"] = None
        self._stubs: dict = {}

    async def connect(self, retries: int = 20, delay: float = 0.1) -> None:
        self._channel = grpc.aio.insecure_channel(self._target)
        # probe with Echo until the server is up (the socket client
        # retries its TCP connect the same way)
        for i in range(retries):
            try:
                await self.echo("ping")
                return
            except Exception:
                if i == retries - 1:
                    # don't leak the aio channel (its polling task +
                    # socket) on a failed start
                    await self.close()
                    raise
                await asyncio.sleep(delay)

    async def call(self, method: str, *args):
        if self._channel is None:
            raise ABCIClientError("grpc client not connected")
        stub = self._stubs.get(method)
        if stub is None:
            stub = self._channel.unary_unary(
                _method_path(method),
                request_serializer=None,
                response_deserializer=None,
            )
            self._stubs[method] = stub
        try:
            reply = await stub(abci.encode_rpc(method, list(args)))
        except grpc.aio.AioRpcError as e:
            raise ABCIClientError(f"grpc call failed: {e.code()}") from None
        return abci.decode_result(reply)

    async def close(self) -> None:
        if self._channel is not None:
            await self._channel.close()
            self._channel = None


def grpc_client_creator(host: str, port: int):
    """ClientCreator for the grpc transport (proxy/multi_app_conn.py)."""
    from ..proxy.multi_app_conn import ClientCreator

    return ClientCreator(lambda: GRPCClient(host, port))
