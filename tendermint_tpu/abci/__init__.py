"""ABCI — the application/consensus bridge (SURVEY.md layer 5).

Reference: abci/ (~20k LoC, mostly generated protobuf). Here the protocol
is a Python Protocol class plus dataclass request/responses; clients come
in local (in-proc, the reference's local_client.go) and socket (asyncio,
the reference's socket_client.go pipelined pair of routines) flavors.
"""

from .types import (  # noqa: F401
    Application,
    BaseApplication,
    Event,
    ResponseCheckTx,
    ResponseCommit,
    ResponseDeliverTx,
    ResponseInfo,
    ResponseInitChain,
    ResponseQuery,
    Snapshot,
    ValidatorUpdate,
)
