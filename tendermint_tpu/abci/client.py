"""ABCI clients: local (in-proc) and socket (asyncio pipelined).

Reference: abci/client/local_client.go (mutex-serialized direct calls) and
socket_client.go (sendRequestsRoutine :119 / recvResponseRoutine :153 —
async pipelining over a unix/tcp socket with varint-delimited frames).
"""

from __future__ import annotations

import asyncio
from io import BytesIO
from typing import Any, Optional

from ..libs import protoio as pio
from . import types as abci


class ABCIClientError(Exception):
    pass


class LocalClient:
    """In-proc client: one asyncio lock serializes calls, mirroring
    local_client.go's mutex. Sync app methods run directly (they are
    CPU-light); a slow app should use the socket client instead."""

    def __init__(self, app: abci.Application):
        self._app = app
        self._lock = asyncio.Lock()

    async def call(self, method: str, *args) -> Any:
        async with self._lock:
            return getattr(self._app, method)(*args)

    async def echo(self, msg: str) -> str:
        return await self.call("echo", msg)

    async def info(self) -> abci.ResponseInfo:
        return await self.call("info")

    async def init_chain(self, *args) -> abci.ResponseInitChain:
        return await self.call("init_chain", *args)

    async def query(self, *args) -> abci.ResponseQuery:
        return await self.call("query", *args)

    async def check_tx(self, tx: bytes) -> abci.ResponseCheckTx:
        return await self.call("check_tx", tx)

    async def begin_block(self, *args):
        return await self.call("begin_block", *args)

    async def deliver_tx(self, tx: bytes) -> abci.ResponseDeliverTx:
        return await self.call("deliver_tx", tx)

    async def end_block(self, height: int):
        return await self.call("end_block", height)

    async def commit(self) -> abci.ResponseCommit:
        return await self.call("commit")

    async def list_snapshots(self):
        return await self.call("list_snapshots")

    async def offer_snapshot(self, *args):
        return await self.call("offer_snapshot", *args)

    async def load_snapshot_chunk(self, *args) -> bytes:
        return await self.call("load_snapshot_chunk", *args)

    async def apply_snapshot_chunk(self, *args):
        return await self.call("apply_snapshot_chunk", *args)

    async def close(self) -> None:
        pass


class SocketClient(LocalClient):
    """Pipelined socket client: requests are written in order and matched
    to responses FIFO (the reference's reqSent queue)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 26658):
        self._host, self._port = host, port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: asyncio.Queue = asyncio.Queue()
        self._recv_task: Optional[asyncio.Task] = None
        self._lock = asyncio.Lock()

    async def connect(self, retries: int = 20, delay: float = 0.1) -> None:
        last_err: Optional[Exception] = None
        for _ in range(retries):
            try:
                self._reader, self._writer = await asyncio.open_connection(
                    self._host, self._port
                )
                self._recv_task = asyncio.get_running_loop().create_task(
                    self._recv_routine()
                )
                return
            except OSError as e:
                last_err = e
                await asyncio.sleep(delay)
        raise ABCIClientError(f"cannot connect to ABCI server: {last_err}")

    async def _recv_routine(self) -> None:
        try:
            while True:
                frame = await _read_frame(self._reader)
                fut: asyncio.Future = await self._pending.get()
                if not fut.done():
                    try:
                        fut.set_result(abci.decode_result(frame))
                    except Exception as e:  # app returned an error
                        fut.set_exception(e)
        except (asyncio.IncompleteReadError, ConnectionError, EOFError):
            while not self._pending.empty():
                fut = self._pending.get_nowait()
                if not fut.done():
                    fut.set_exception(ABCIClientError("connection closed"))

    async def call(self, method: str, *args) -> Any:
        async with self._lock:
            if self._writer is None:
                raise ABCIClientError("not connected")
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            await self._pending.put(fut)
            payload = abci.encode_rpc(method, list(args))
            self._writer.write(pio.write_uvarint(len(payload)) + payload)
            await self._writer.drain()
        return await fut

    async def close(self) -> None:
        if self._recv_task:
            self._recv_task.cancel()
        if self._writer:
            self._writer.close()


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    shift = 0
    n = 0
    while True:
        b = (await reader.readexactly(1))[0]
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 70:
            raise ABCIClientError("frame length varint too long")
    if n > 1 << 26:
        raise ABCIClientError("frame too large")
    return await reader.readexactly(n)


class SocketServer:
    """ABCI app server (reference abci/server/socket_server.go)."""

    def __init__(self, app: abci.Application, host: str = "127.0.0.1", port: int = 26658):
        self._app = app
        self._host, self._port = host, port
        self._server: Optional[asyncio.AbstractServer] = None

    @property
    def port(self) -> int:
        return self._port

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self._host, self._port
        )
        if self._port == 0:
            self._port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                frame = await _read_frame(reader)
                method, args = abci.decode_rpc(frame)
                try:
                    result = getattr(self._app, method)(*args)
                    out = abci.encode_result(result)
                except Exception as e:
                    out = abci.encode_error(repr(e))
                writer.write(pio.write_uvarint(len(out)) + out)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, EOFError):
            pass
        finally:
            writer.close()
