"""abci-cli — poke an ABCI application server from the command line.

Reference: abci/cmd/abci-cli (echo/info/deliver_tx/check_tx/commit/query
+ the `kvstore` demo server + interactive console). Speaks the framework's
length-framed socket protocol (abci/client.py), so it exercises the same
process boundary a production app server sits behind.
"""

from __future__ import annotations

import asyncio
import shlex
import sys

from .client import SocketClient, SocketServer


def _parse_hex_or_str(s: str) -> bytes:
    if s.startswith("0x"):
        return bytes.fromhex(s[2:])
    return s.encode()


async def _run_one(client: SocketClient, cmd: str, args: list[str]) -> int:
    if cmd == "echo":
        print(await client.echo(args[0] if args else ""))
    elif cmd == "info":
        r = await client.info()
        print(
            f"data={r.data} version={r.version} "
            f"last_block_height={r.last_block_height} "
            f"last_block_app_hash=0x{r.last_block_app_hash.hex()}"
        )
    elif cmd == "deliver_tx":
        r = await client.deliver_tx(_parse_hex_or_str(args[0]))
        print(f"code={r.code} log={r.log!r}")
    elif cmd == "check_tx":
        r = await client.check_tx(_parse_hex_or_str(args[0]))
        print(f"code={r.code} log={r.log!r}")
    elif cmd == "commit":
        r = await client.commit()
        print(f"data=0x{r.data.hex()}")
    elif cmd == "query":
        r = await client.query("/store", _parse_hex_or_str(args[0]), 0, False)
        print(
            f"code={r.code} key={r.key!r} value={r.value!r} "
            f"height={r.height}"
        )
    else:
        print(f"unknown command {cmd!r}", file=sys.stderr)
        return 1
    return 0


async def _amain(args) -> int:
    grpc_mode = getattr(args, "transport", "socket") == "grpc"
    if args.abci_cmd == "kvstore":
        from .kvstore import KVStoreApplication

        if grpc_mode:
            from .grpc_transport import GRPCServer

            srv = GRPCServer(KVStoreApplication(), port=args.port)
        else:
            srv = SocketServer(KVStoreApplication(), port=args.port)
        await srv.start()
        print(
            f"kvstore ABCI server listening on {srv.port} "
            f"({'grpc' if grpc_mode else 'socket'})",
            flush=True,
        )
        try:
            await asyncio.Event().wait()
        except (KeyboardInterrupt, asyncio.CancelledError):
            pass
        await srv.stop()
        return 0

    if grpc_mode:
        from .grpc_transport import GRPCClient

        client = GRPCClient(port=args.port)
    else:
        client = SocketClient(port=args.port)
    await client.connect()
    try:
        if args.abci_cmd == "console":
            print("ABCI console — echo/info/deliver_tx/check_tx/commit/query")
            loop = asyncio.get_running_loop()
            while True:
                line = (
                    await loop.run_in_executor(None, sys.stdin.readline)
                ).strip()
                if not line or line in ("exit", "quit"):
                    break
                parts = shlex.split(line)
                try:
                    await _run_one(client, parts[0], parts[1:])
                except Exception as e:
                    print(f"error: {e}", file=sys.stderr)
            return 0
        return await _run_one(client, args.abci_cmd, args.args)
    finally:
        await client.close()


def cmd_abci(args) -> int:
    from .client import ABCIClientError

    try:
        return asyncio.run(_amain(args))
    except (ConnectionError, ABCIClientError) as e:
        print(f"cannot reach ABCI server: {e}", file=sys.stderr)
        return 1


def register(sub) -> None:
    sp = sub.add_parser(
        "abci-cli", help="poke an ABCI app server (reference abci-cli)"
    )
    sp.add_argument(
        "abci_cmd",
        choices=[
            "echo",
            "info",
            "deliver_tx",
            "check_tx",
            "commit",
            "query",
            "console",
            "kvstore",
        ],
    )
    sp.add_argument("args", nargs="*")
    sp.add_argument("--port", type=int, default=26658)
    sp.add_argument(
        "--transport", choices=["socket", "grpc"], default="socket",
        help="ABCI transport (reference abci-cli --abci)",
    )
    sp.set_defaults(fn=cmd_abci)
