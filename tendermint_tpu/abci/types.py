"""ABCI application interface + request/response types.

Reference: abci/types/application.go:13-32 — the 17-method Application
surface (echo/flush/info lifecycle, init_chain, query, the consensus
connection's begin_block/deliver_tx/end_block/commit, and the snapshot
connection's four methods). The mempool connection is gone in the morph
fork (no mempool; txs come from the L2 node), but check_tx stays on the
interface for app compatibility.
"""

from __future__ import annotations

import base64
import json
from dataclasses import asdict, dataclass, field
from typing import Optional, Protocol

CODE_TYPE_OK = 0


@dataclass
class Event:
    type: str
    attributes: dict[str, str] = field(default_factory=dict)


@dataclass
class ValidatorUpdate:
    pub_key_type: str
    pub_key_data: bytes
    power: int
    # morph QC plane: rotating a validator in with its BLS12-381 G2 key
    # (192 bytes uncompressed) makes it QC-capable from its first height
    # in the set; empty means "no key supplied" — an update to an
    # existing member keeps the key already on record
    bls_pub_key: bytes = b""


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class ResponseInitChain:
    validators: list[ValidatorUpdate] = field(default_factory=list)
    consensus_params: Optional[dict] = None
    app_hash: bytes = b""


@dataclass
class ResponseQuery:
    code: int = CODE_TYPE_OK
    log: str = ""
    key: bytes = b""
    value: bytes = b""
    height: int = 0
    index: int = 0
    proof_ops: list = field(default_factory=list)


@dataclass
class ResponseCheckTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    gas_wanted: int = 0


@dataclass
class ResponseBeginBlock:
    events: list[Event] = field(default_factory=list)


@dataclass
class ResponseDeliverTx:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list[Event] = field(default_factory=list)

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class ResponseEndBlock:
    validator_updates: list[ValidatorUpdate] = field(default_factory=list)
    consensus_param_updates: Optional[dict] = None
    events: list[Event] = field(default_factory=list)


@dataclass
class ResponseCommit:
    data: bytes = b""  # the app hash
    retain_height: int = 0


@dataclass
class Snapshot:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""


@dataclass
class ResponseOfferSnapshot:
    result: str = "ACCEPT"  # ACCEPT | ABORT | REJECT | REJECT_FORMAT | REJECT_SENDER


@dataclass
class ResponseApplySnapshotChunk:
    result: str = "ACCEPT"  # ACCEPT | ABORT | RETRY | RETRY_SNAPSHOT | REJECT_SNAPSHOT
    refetch_chunks: list[int] = field(default_factory=list)
    reject_senders: list[str] = field(default_factory=list)


class Application(Protocol):
    """The 17-method app surface (reference abci/types/application.go)."""

    def echo(self, msg: str) -> str: ...

    def info(self) -> ResponseInfo: ...

    def init_chain(
        self,
        chain_id: str,
        consensus_params: dict,
        validators: list[ValidatorUpdate],
        app_state: dict,
        initial_height: int,
    ) -> ResponseInitChain: ...

    def query(self, path: str, data: bytes, height: int, prove: bool) -> ResponseQuery: ...

    def check_tx(self, tx: bytes) -> ResponseCheckTx: ...

    def begin_block(
        self, header, last_commit_info, byzantine_validators
    ) -> ResponseBeginBlock: ...

    def deliver_tx(self, tx: bytes) -> ResponseDeliverTx: ...

    def end_block(self, height: int) -> ResponseEndBlock: ...

    def commit(self) -> ResponseCommit: ...

    def list_snapshots(self) -> list[Snapshot]: ...

    def offer_snapshot(
        self, snapshot: Snapshot, app_hash: bytes
    ) -> ResponseOfferSnapshot: ...

    def load_snapshot_chunk(
        self, height: int, format: int, chunk: int
    ) -> bytes: ...

    def apply_snapshot_chunk(
        self, index: int, chunk: bytes, sender: str
    ) -> ResponseApplySnapshotChunk: ...


class BaseApplication:
    """No-op defaults (reference abci/types/application.go BaseApplication)."""

    def echo(self, msg: str) -> str:
        return msg

    def info(self) -> ResponseInfo:
        return ResponseInfo()

    def init_chain(
        self, chain_id, consensus_params, validators, app_state, initial_height
    ) -> ResponseInitChain:
        return ResponseInitChain()

    def query(self, path, data, height, prove) -> ResponseQuery:
        return ResponseQuery()

    def check_tx(self, tx) -> ResponseCheckTx:
        return ResponseCheckTx()

    def begin_block(
        self, header, last_commit_info, byzantine_validators
    ) -> ResponseBeginBlock:
        return ResponseBeginBlock()

    def deliver_tx(self, tx) -> ResponseDeliverTx:
        return ResponseDeliverTx()

    def end_block(self, height) -> ResponseEndBlock:
        return ResponseEndBlock()

    def commit(self) -> ResponseCommit:
        return ResponseCommit()

    def list_snapshots(self) -> list[Snapshot]:
        return []

    def offer_snapshot(self, snapshot, app_hash) -> ResponseOfferSnapshot:
        return ResponseOfferSnapshot(result="ABORT")

    def load_snapshot_chunk(self, height, format, chunk) -> bytes:
        return b""

    def apply_snapshot_chunk(self, index, chunk, sender) -> ResponseApplySnapshotChunk:
        return ResponseApplySnapshotChunk(result="ABORT")


# --- wire helpers for the socket client/server ----------------------------


def _to_jsonable(obj):
    if isinstance(obj, bytes):
        return {"__b__": base64.b64encode(obj).decode()}
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _to_jsonable(v) for k, v in obj.items()}
    if hasattr(obj, "__dataclass_fields__"):
        # per-field recursion (NOT asdict, which flattens nested
        # dataclasses into untyped dicts): a Header's BlockID must
        # arrive at the remote app as a BlockID
        return {
            "__dc__": type(obj).__name__,
            "fields": {
                k: _to_jsonable(getattr(obj, k))
                for k in obj.__dataclass_fields__
            },
        }
    return obj


def _from_jsonable(obj):
    if isinstance(obj, dict):
        if "__b__" in obj and len(obj) == 1:
            return base64.b64decode(obj["__b__"])
        if "__dc__" in obj:
            cls = _DATACLASSES[obj["__dc__"]]
            return cls(**_from_jsonable(obj["fields"]))
        return {k: _from_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_from_jsonable(x) for x in obj]
    return obj


_DATACLASSES = {
    c.__name__: c
    for c in (
        Event,
        ValidatorUpdate,
        ResponseInfo,
        ResponseInitChain,
        ResponseQuery,
        ResponseCheckTx,
        ResponseBeginBlock,
        ResponseDeliverTx,
        ResponseEndBlock,
        ResponseCommit,
        Snapshot,
        ResponseOfferSnapshot,
        ResponseApplySnapshotChunk,
    )
}


def _register_request_types() -> None:
    """Request-side dataclasses that cross the remote transports
    (begin_block carries the full Header tree — found driving a real
    node against an external app, r4)."""
    from ..types.block import Header
    from ..types.block_id import BlockID
    from ..types.part_set import PartSetHeader

    for c in (Header, BlockID, PartSetHeader):
        _DATACLASSES[c.__name__] = c


_register_request_types()


def encode_rpc(method: str, args: list) -> bytes:
    return json.dumps({"m": method, "a": _to_jsonable(args)}).encode()


def decode_rpc(data: bytes) -> tuple[str, list]:
    d = json.loads(data.decode())
    return d["m"], _from_jsonable(d["a"])


def encode_result(value) -> bytes:
    return json.dumps({"r": _to_jsonable(value)}).encode()


def decode_result(data: bytes):
    d = json.loads(data.decode())
    if "e" in d:
        raise RuntimeError(d["e"])
    return _from_jsonable(d["r"])


def encode_error(msg: str) -> bytes:
    return json.dumps({"e": msg}).encode()
