"""Example key-value store app (reference abci/example/kvstore/kvstore.go:65).

Transactions are "key=value" byte strings; state is a dict whose app hash
is a deterministic digest over sorted entries. Supports validator updates
via the special "val:<pubkey_hex>!<power>" tx (reference kvstore
PersistentKVStoreApplication) and snapshots for statesync tests.
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional

from . import types as abci


class KVStoreApplication(abci.BaseApplication):
    SNAPSHOT_CHUNK_SIZE = 1024

    def __init__(self):
        self._state: dict[str, str] = {}
        self._height = 0
        self._app_hash = b""
        self._pending_val_updates: list[abci.ValidatorUpdate] = []
        self._validators: dict[str, int] = {}  # pubkey hex -> power
        self._snapshots: dict[int, bytes] = {}
        self._restore_buf: Optional[list[bytes]] = None
        self._compute_app_hash()

    # --- helpers ----------------------------------------------------------

    def _compute_app_hash(self) -> None:
        blob = json.dumps(
            {"kv": self._state, "h": self._height}, sort_keys=True
        ).encode()
        self._app_hash = hashlib.sha256(blob).digest()

    # --- abci -------------------------------------------------------------

    def info(self) -> abci.ResponseInfo:
        return abci.ResponseInfo(
            data="kvstore",
            version="1.0",
            last_block_height=self._height,
            last_block_app_hash=self._app_hash if self._height else b"",
        )

    def init_chain(
        self, chain_id, consensus_params, validators, app_state, initial_height
    ) -> abci.ResponseInitChain:
        for v in validators:
            self._validators[v.pub_key_data.hex()] = v.power
        if app_state:
            self._state.update(
                {str(k): str(v) for k, v in app_state.items()}
            )
        self._compute_app_hash()
        return abci.ResponseInitChain(app_hash=self._app_hash)

    def check_tx(self, tx: bytes) -> abci.ResponseCheckTx:
        if b"=" not in tx and not tx.startswith(b"val:"):
            return abci.ResponseCheckTx(code=1, log="tx must be key=value")
        return abci.ResponseCheckTx()

    def deliver_tx(self, tx: bytes) -> abci.ResponseDeliverTx:
        if tx.startswith(b"val:"):
            try:
                body = tx[4:].decode()
                pubkey_hex, power = body.split("!")
                self._pending_val_updates.append(
                    abci.ValidatorUpdate(
                        "ed25519", bytes.fromhex(pubkey_hex), int(power)
                    )
                )
                self._validators[pubkey_hex] = int(power)
                return abci.ResponseDeliverTx(
                    events=[abci.Event("val_update", {"pubkey": pubkey_hex})]
                )
            except (ValueError, IndexError) as e:
                return abci.ResponseDeliverTx(code=2, log=f"bad val tx: {e}")
        if b"=" in tx:
            k, v = tx.split(b"=", 1)
        else:
            k = v = tx
        self._state[k.decode(errors="replace")] = v.decode(errors="replace")
        return abci.ResponseDeliverTx(
            events=[
                abci.Event(
                    "app", {"creator": "kvstore", "key": k.decode(errors="replace")}
                )
            ]
        )

    def end_block(self, height: int) -> abci.ResponseEndBlock:
        updates, self._pending_val_updates = self._pending_val_updates, []
        return abci.ResponseEndBlock(validator_updates=updates)

    def commit(self) -> abci.ResponseCommit:
        self._height += 1
        self._compute_app_hash()
        self._snapshots[self._height] = json.dumps(
            {"kv": self._state, "h": self._height}, sort_keys=True
        ).encode()
        # keep only recent snapshots
        for h in sorted(self._snapshots):
            if h < self._height - 10:
                del self._snapshots[h]
        return abci.ResponseCommit(data=self._app_hash)

    def query(self, path, data, height, prove) -> abci.ResponseQuery:
        key = data.decode(errors="replace")
        val = self._state.get(key)
        if val is None:
            return abci.ResponseQuery(code=1, log="key not found", key=data)
        return abci.ResponseQuery(
            key=data, value=val.encode(), height=self._height
        )

    # --- snapshots (statesync) -------------------------------------------

    def list_snapshots(self) -> list[abci.Snapshot]:
        out = []
        for h, blob in sorted(self._snapshots.items()):
            chunks = max(
                1,
                (len(blob) + self.SNAPSHOT_CHUNK_SIZE - 1)
                // self.SNAPSHOT_CHUNK_SIZE,
            )
            out.append(
                abci.Snapshot(
                    height=h,
                    format=1,
                    chunks=chunks,
                    hash=hashlib.sha256(blob).digest(),
                )
            )
        return out

    def offer_snapshot(self, snapshot, app_hash) -> abci.ResponseOfferSnapshot:
        if snapshot.format != 1:
            return abci.ResponseOfferSnapshot(result="REJECT_FORMAT")
        self._restore_buf = [b""] * snapshot.chunks
        self._restore_target = snapshot
        return abci.ResponseOfferSnapshot(result="ACCEPT")

    def load_snapshot_chunk(self, height, format, chunk) -> bytes:
        blob = self._snapshots.get(height, b"")
        start = chunk * self.SNAPSHOT_CHUNK_SIZE
        return blob[start : start + self.SNAPSHOT_CHUNK_SIZE]

    def apply_snapshot_chunk(
        self, index, chunk, sender
    ) -> abci.ResponseApplySnapshotChunk:
        if self._restore_buf is None or index >= len(self._restore_buf):
            return abci.ResponseApplySnapshotChunk(result="ABORT")
        self._restore_buf[index] = chunk
        if all(c for c in self._restore_buf) or (
            index == len(self._restore_buf) - 1
        ):
            blob = b"".join(self._restore_buf)
            if hashlib.sha256(blob).digest() != self._restore_target.hash:
                return abci.ResponseApplySnapshotChunk(
                    result="RETRY_SNAPSHOT"
                )
            st = json.loads(blob.decode())
            self._state = st["kv"]
            self._height = st["h"]
            self._compute_app_hash()
            self._restore_buf = None
        return abci.ResponseApplySnapshotChunk(result="ACCEPT")
