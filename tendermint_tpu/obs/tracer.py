"""Span tracer + flight recorder — the node's black box.

The reference node's only window into consensus is the metricsgen
Prometheus set (node/node.go:1062-1065); aggregates answer "how fast on
average" but not "where did height H's 900 ms go". This module adds the
missing axis: a thread-safe fixed-size ring buffer of span records
`{name, t0, dur, height, round, fields}` over `time.perf_counter`,
nestable via contextvars, with near-zero cost when disabled (one
attribute read per call site).

Stdlib only — the tracer is imported by the vote hot path, the WAL, the
p2p layer and the chaos subsystem, none of which may grow a dependency.

Two consumers:

- the `dump_traces` RPC route ships the raw ring plus a Chrome
  `trace_event` JSON export (load it in Perfetto / chrome://tracing);
- the flight recorder view groups the ring into the last N heights'
  step timelines, assigning height-less annotations (chaos faults, WAL
  fsyncs, p2p stalls) to the height whose span window contains them.

Enabling: construct `Tracer(enabled=True)`, flip `.enabled` on the
process-wide `default_tracer()`, or set TM_TPU_TRACE=1 in the
environment before import (bench/soak/CI entry points).
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from collections import deque
from typing import Optional

# ring capacity default: ~6 step spans + a handful of annotations per
# height per node -> 8192 records cover hundreds of heights
DEFAULT_RING_SIZE = 8192

# current span-name stack for parent attribution; contextvars make the
# nesting follow asyncio tasks, not threads
_stack: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "tm_tpu_span_stack", default=()
)


class SpanRecord:
    """One ring entry. `kind` is "span" (has a duration) or "event" (an
    instant annotation). Times are seconds relative to the tracer epoch
    (`Tracer.epoch_wall_ns` anchors them to the wall clock)."""

    __slots__ = ("name", "t0", "dur", "height", "round", "kind", "fields")

    def __init__(self, name, t0, dur, height, round_, kind, fields):
        self.name = name
        self.t0 = t0
        self.dur = dur
        self.height = height
        self.round = round_
        self.kind = kind
        self.fields = fields

    def to_json(self) -> dict:
        out = {
            "name": self.name,
            "t0": round(self.t0, 6),
            "dur": round(self.dur, 6),
            "height": self.height,
            "round": self.round,
            "kind": self.kind,
        }
        if self.fields:
            out["fields"] = self.fields
        return out

    @classmethod
    def from_json(cls, d: dict) -> "SpanRecord":
        return cls(
            d.get("name", ""),
            d.get("t0", 0.0),
            d.get("dur", 0.0),
            d.get("height", 0),
            d.get("round", 0),
            d.get("kind", "span"),
            d.get("fields") or {},
        )


class _Span:
    """Context manager recording one span on exit."""

    __slots__ = ("_tracer", "name", "height", "round", "fields", "_t0", "_tok")

    def __init__(self, tracer, name, height, round_, fields):
        self._tracer = tracer
        self.name = name
        self.height = height
        self.round = round_
        self.fields = fields
        self._t0 = 0.0
        self._tok = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._tok = _stack.set(_stack.get() + (self.name,))
        return self

    def __exit__(self, *exc):
        if self._tok is not None:
            _stack.reset(self._tok)
        self._tracer.add_span(
            self.name,
            self._t0,
            time.perf_counter() - self._t0,
            height=self.height,
            round=self.round,
            **self.fields,
        )
        return False


class _NopSpan:
    """Shared no-op context manager: the disabled-tracer fast path
    allocates nothing per call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOP_SPAN = _NopSpan()


class Tracer:
    """Thread-safe fixed-size ring of SpanRecords."""

    # how often the wall anchor is refreshed (seconds of perf_counter
    # time); perf_counter and the wall clock drift apart on the order of
    # ppm, so minutes-scale re-anchoring bounds the error at micro-
    # seconds while a multi-hour soak against a creation-time anchor
    # would accumulate milliseconds — enough to corrupt cross-node merge
    DEFAULT_REANCHOR_INTERVAL = 300.0

    def __init__(
        self,
        enabled: bool = False,
        ring_size: int = DEFAULT_RING_SIZE,
        reanchor_interval_s: float = DEFAULT_REANCHOR_INTERVAL,
    ):
        self.enabled = enabled
        self._ring: deque[SpanRecord] = deque(maxlen=max(16, ring_size))
        self._lock = threading.Lock()
        # perf_counter epoch all record times are relative to, anchored
        # to the wall clock for cross-process correlation
        self.epoch = time.perf_counter()
        self.epoch_wall_ns = time.time_ns()
        self.reanchor_interval_s = reanchor_interval_s
        self._last_anchor_pc = self.epoch

    # --- recording --------------------------------------------------------

    def span(self, name: str, /, height: int = 0, round: int = 0, **fields):
        """Context manager timing a block; no-op singleton when disabled."""
        if not self.enabled:
            return _NOP_SPAN
        return _Span(self, name, height, round, fields)

    def add_span(
        self,
        name: str,
        t0: float,
        dur: float,
        /,
        height: int = 0,
        round: int = 0,
        **fields,
    ) -> None:
        """Record a span retroactively from an absolute perf_counter t0
        (the consensus step seam measures between transitions and only
        knows the duration after the fact)."""
        if not self.enabled:
            return
        parents = _stack.get()
        if parents:
            fields = dict(fields, parent=parents[-1])
        with self._lock:
            self._maybe_reanchor_locked(time.perf_counter())
            self._ring.append(
                SpanRecord(
                    name, t0 - self.epoch, dur, height, round, "span", fields
                )
            )

    def event(
        self, name: str, /, height: int = 0, round: int = 0, **fields
    ) -> None:
        """Instant annotation (chaos fault, queue-full, peer ban...)."""
        if not self.enabled:
            return
        with self._lock:
            now = time.perf_counter()
            self._maybe_reanchor_locked(now)
            self._ring.append(
                SpanRecord(
                    name,
                    now - self.epoch,
                    0.0,
                    height,
                    round,
                    "event",
                    fields,
                )
            )

    def now(self) -> float:
        """Current time on the tracer's own clock (seconds since epoch)."""
        return time.perf_counter() - self.epoch

    # --- wall-anchor maintenance -----------------------------------------

    def _maybe_reanchor_locked(self, now_pc: float) -> None:
        if (
            self.reanchor_interval_s > 0
            and now_pc - self._last_anchor_pc >= self.reanchor_interval_s
        ):
            self._reanchor_locked(now_pc)

    def _reanchor_locked(self, now_pc: float) -> None:
        # re-derive what epoch_wall_ns SHOULD be given the current
        # perf_counter<->wall relationship; record times (epoch-relative
        # perf_counter) are untouched, only the wall mapping refreshes
        self.epoch_wall_ns = time.time_ns() - int(
            (now_pc - self.epoch) * 1e9
        )
        self._last_anchor_pc = now_pc

    def reanchor(self) -> None:
        """Refresh the monotonic->wall anchor now (normally automatic
        every reanchor_interval_s on the recording path)."""
        with self._lock:
            self._reanchor_locked(time.perf_counter())

    def wall_anchor_age_s(self) -> float:
        """perf_counter seconds since the anchor was last refreshed."""
        return time.perf_counter() - self._last_anchor_pc

    # --- reading ----------------------------------------------------------

    def records(self) -> list[SpanRecord]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)

    # --- exports ----------------------------------------------------------

    def to_chrome_trace(
        self, records: Optional[list[SpanRecord]] = None
    ) -> dict:
        """Chrome trace_event JSON (the dict; json.dumps it for a file
        Perfetto / chrome://tracing loads directly). Spans become complete
        ("X") events, annotations instant ("i") events; each height gets
        its own tid so Perfetto renders one track per height."""
        if records is None:
            records = self.records()
        events = []
        for r in records:
            ev = {
                "name": r.name,
                "ph": "X" if r.kind == "span" else "i",
                "ts": round(r.t0 * 1e6, 1),
                "pid": 1,
                "tid": r.height,
                "args": {"height": r.height, "round": r.round, **r.fields},
            }
            if r.kind == "span":
                ev["dur"] = round(r.dur * 1e6, 1)
            else:
                ev["s"] = "g"  # global-scope instant
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"epoch_wall_ns": self.epoch_wall_ns},
        }

    def flight(self, n_heights: int = 16) -> dict[int, list[dict]]:
        """Flight-recorder view: the last `n_heights` heights' full step
        timelines, each a time-ordered list of record dicts. Records with
        height=0 (WAL fsync, p2p stalls, chaos faults — seams that don't
        know the consensus height) are binned into the height whose span
        window `[first t0, last t0+dur]` contains their timestamp."""
        return flight_snapshot(self.records(), n_heights)


def flight_snapshot(
    records: list[SpanRecord], n_heights: int = 16
) -> dict[int, list[dict]]:
    by_height: dict[int, list[SpanRecord]] = {}
    windows: dict[int, list[float]] = {}  # height -> [min_t0, max_end]
    unassigned: list[SpanRecord] = []
    for r in records:
        if r.height > 0:
            by_height.setdefault(r.height, []).append(r)
            w = windows.setdefault(r.height, [r.t0, r.t0 + r.dur])
            w[0] = min(w[0], r.t0)
            w[1] = max(w[1], r.t0 + r.dur)
        else:
            unassigned.append(r)
    for r in unassigned:
        # prefer the highest height whose window contains the record — a
        # multi-node shared ring has overlapping windows, and the fault
        # belongs to the height that was in progress when it fired
        best = None
        for h, (lo, hi) in windows.items():
            if lo <= r.t0 <= hi and (best is None or h > best):
                best = h
        if best is not None:
            by_height.setdefault(best, []).append(r)
    keep = sorted(by_height)[-n_heights:]
    return {
        h: [r.to_json() for r in sorted(by_height[h], key=lambda r: r.t0)]
        for h in keep
    }


# --- consensus height hint --------------------------------------------------
# The state machine publishes its current (height, round) here on every
# step transition; seams that submit work on the consensus node's behalf
# but never see a height (the remote verify client stamping trace
# context onto UDS submissions) read it back. A plain module tuple —
# atomic under the GIL, one attribute store per step transition. In-proc
# multi-node harnesses share it (last writer wins), which is fine for a
# HINT: the real deployment runs one consensus instance per process, and
# harness nodes track within a height of each other.

_height_hint: tuple = (0, 0)


def set_height_hint(height: int, round_: int = 0) -> None:
    """Publish the consensus height/round in progress (state machine)."""
    global _height_hint
    _height_hint = (height, round_)


def height_hint() -> tuple:
    """(height, round) last published by the consensus state machine;
    (0, 0) before consensus starts."""
    return _height_hint


_default: Optional[Tracer] = None
_default_lock = threading.Lock()


def default_tracer() -> Tracer:
    """Process-wide tracer shared by every subsystem that isn't handed an
    explicit one (batch verifier, WAL, p2p, chaos). Starts enabled iff
    TM_TPU_TRACE=1."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Tracer(
                    enabled=os.environ.get("TM_TPU_TRACE") == "1"
                )
    return _default


def set_default_tracer(tracer: Tracer) -> Tracer:
    """Install `tracer` as the process-wide default (node assembly does
    this so config-driven settings apply to every seam). Returns it."""
    global _default
    with _default_lock:
        _default = tracer
    return tracer
