"""Streaming quantile sketch — fixed-window order statistics.

The pacing controllers (consensus/pacing.py) learn live arrival-tail
distributions from the quorum-lag sensors; the bench family computes
quorum-close p50/p95 from the same math. Both need a quantile estimate
that is

- *streaming*: O(1) per sample, bounded memory — the vote hot path
  feeds it synchronously;
- *windowed*: consensus latency is non-stationary (a link degrades, a
  partition heals), so old samples must age out instead of pinning the
  estimate forever;
- *deterministic*: two identical sample streams must produce identical
  estimates — the pacing determinism test (two nodes replaying the same
  trace must derive the same timeout schedule) rules out randomized
  sketches.

Exact order statistics over a bounded ring satisfy all three (a P²
estimator would too, but its estimates depend on the full history, so a
window bound would have to be bolted on; the ring IS the window). The
sort is amortized: samples append O(1) and the sorted view is rebuilt
lazily per query batch, so a feed-heavy/query-light caller (hundreds of
votes per height, one schedule decision) pays one O(w log w) sort per
decision, w <= window.

The quantile index rule matches `obs.report.pct` (sorted[min(n-1,
int(q*n))]) so a sketch over the full sample list and the ad-hoc list
math agree bit-for-bit.
"""

from __future__ import annotations

from collections import deque
from typing import Iterable, Optional


class StreamingQuantile:
    """Quantiles over the last `window` samples (exact within window)."""

    __slots__ = ("_ring", "_sorted", "count")

    def __init__(self, window: int = 256):
        if window < 1:
            raise ValueError("quantile window must be >= 1")
        self._ring: deque[float] = deque(maxlen=window)
        self._sorted: Optional[list[float]] = None  # lazy cache
        self.count = 0  # total samples ever added (not just windowed)

    @property
    def window(self) -> int:
        return self._ring.maxlen or 0

    def add(self, x: float) -> None:
        self._ring.append(float(x))
        self._sorted = None
        self.count += 1

    def extend(self, xs: Iterable[float]) -> None:
        for x in xs:
            self.add(x)

    def __len__(self) -> int:
        return len(self._ring)

    def _view(self) -> list[float]:
        if self._sorted is None:
            self._sorted = sorted(self._ring)
        return self._sorted

    def quantile(self, q: float) -> float:
        """The q-quantile of the window (0.0 when empty). Same index
        rule as obs.report.pct."""
        xs = self._view()
        if not xs:
            return 0.0
        return xs[min(len(xs) - 1, int(q * len(xs)))]

    def quantiles(self, qs: Iterable[float]) -> list[float]:
        """Several quantiles off one sorted view."""
        return [self.quantile(q) for q in qs]

    def max(self) -> float:
        xs = self._view()
        return xs[-1] if xs else 0.0

    def reset(self) -> None:
        self._ring.clear()
        self._sorted = None
        self.count = 0

    # --- persistence (pacing-tail warm starts) ----------------------------

    def to_list(self) -> list[float]:
        """The windowed samples in arrival order — with `count`, the
        sketch's full restorable state."""
        return list(self._ring)

    def load(self, samples: Iterable[float], count: int = 0) -> None:
        """Restore a persisted window (consensus/pacing.py warm start).
        Replaces the current contents; `count` restores the lifetime
        tally (defaults to the window length so min_samples gating
        still sees the restored evidence)."""
        self._ring.clear()
        for x in samples:
            self._ring.append(float(x))
        self._sorted = None
        self.count = max(int(count), len(self._ring))

    def snapshot(self) -> dict:
        """Summary dict for reports/tests (p50/p95/p99/max/counts)."""
        return {
            "count": self.count,
            "window_fill": len(self._ring),
            "p50": self.quantile(0.5),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
            "max": self.max(),
        }
