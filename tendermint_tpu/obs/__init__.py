"""Observability: span tracing, flight recorder, latency attribution.

The layer every perf PR is judged against. Stdlib only. Module map —
the end-to-end trace path runs top to bottom:

- `tracer.py` — the recording seam: thread-safe span ring over
  perf_counter with a wall anchor, process default via
  `default_tracer()`, plus the consensus `set_height_hint` the remote
  verify client reads when stamping wire trace context.
- `report.py` — single-node analysis over record dicts: per-span
  `attribution`, step-bucket `wall_attribution`, and the exhaustive
  `wall_conservation` audit (every height's wall decomposed into
  mutually-exclusive named buckets — step compute / gossip / timeout
  floor / verify IPC·queue·device / WAL fsync / commit pipeline — with
  the residue booked as `dark_time`).
- `parallel/verify_service.py` (not in this package, but on the path):
  node processes stamp span context onto each UDS submission; the
  service records `verify.queue`/`verify.device`/`verify.service`
  sub-spans under it into its OWN ring, served at GET /dump_traces on
  the stats port.
- `cluster.py` — the merge: per-validator `dump_traces` dumps (NTP
  peer-graph offsets, wall-anchor fallback for nodes — and the verify
  service — outside the graph) onto one timeline; `verify_flow` joins
  client round trips to service sub-spans across the process split.
- `health.py` — live verdicts over the same seams, including the
  `dark_time` detector that pages when conservation finds unowned wall
  time.
- `ledger.py` / `quantile.py` / `profiler.py` — device-cost
  accounting, the streaming quantile sketch, on-demand profiling.
"""

from .cluster import (
    cluster_report,
    estimate_offsets,
    link_latencies,
    merge_records,
    normalize_dump,
    report_text,
    verify_flow,
)
from .health import (
    CRITICAL,
    OK,
    VERDICT_NAMES,
    WARN,
    BurnRateSLO,
    HealthMonitor,
)
from .ledger import (
    DispatchLedger,
    default_ledger,
    set_default_ledger,
)
from .profiler import ProfileCapture, ProfilerUnavailable
from .quantile import StreamingQuantile
from .report import (
    CONSERVATION_BUCKETS,
    CONSERVATION_SCHEMA,
    FAMILY_WALL_SPANS,
    ascii_timeline,
    attribution,
    attribution_table,
    check_conservation,
    conservation_table,
    pacing_decisions,
    side_by_side_timeline,
    wall_attribution,
    wall_conservation,
)
from .tracer import (
    DEFAULT_RING_SIZE,
    SpanRecord,
    Tracer,
    default_tracer,
    flight_snapshot,
    height_hint,
    set_default_tracer,
    set_height_hint,
)

__all__ = [
    "CONSERVATION_BUCKETS",
    "CONSERVATION_SCHEMA",
    "CRITICAL",
    "DEFAULT_RING_SIZE",
    "FAMILY_WALL_SPANS",
    "OK",
    "VERDICT_NAMES",
    "WARN",
    "BurnRateSLO",
    "DispatchLedger",
    "HealthMonitor",
    "ProfileCapture",
    "ProfilerUnavailable",
    "SpanRecord",
    "StreamingQuantile",
    "Tracer",
    "ascii_timeline",
    "attribution",
    "attribution_table",
    "check_conservation",
    "cluster_report",
    "conservation_table",
    "default_ledger",
    "default_tracer",
    "estimate_offsets",
    "flight_snapshot",
    "height_hint",
    "link_latencies",
    "merge_records",
    "normalize_dump",
    "pacing_decisions",
    "report_text",
    "set_default_ledger",
    "set_default_tracer",
    "set_height_hint",
    "side_by_side_timeline",
    "verify_flow",
    "wall_attribution",
    "wall_conservation",
]
