"""Observability: span tracing, flight recorder, latency attribution.

The layer every perf PR is judged against — see tracer.py for the design
notes. Stdlib only."""

from .cluster import (
    cluster_report,
    estimate_offsets,
    link_latencies,
    merge_records,
    normalize_dump,
    report_text,
)
from .health import (
    CRITICAL,
    OK,
    VERDICT_NAMES,
    WARN,
    BurnRateSLO,
    HealthMonitor,
)
from .ledger import (
    DispatchLedger,
    default_ledger,
    set_default_ledger,
)
from .profiler import ProfileCapture, ProfilerUnavailable
from .quantile import StreamingQuantile
from .report import (
    FAMILY_WALL_SPANS,
    ascii_timeline,
    attribution,
    attribution_table,
    pacing_decisions,
    side_by_side_timeline,
    wall_attribution,
)
from .tracer import (
    DEFAULT_RING_SIZE,
    SpanRecord,
    Tracer,
    default_tracer,
    flight_snapshot,
    set_default_tracer,
)

__all__ = [
    "CRITICAL",
    "DEFAULT_RING_SIZE",
    "FAMILY_WALL_SPANS",
    "OK",
    "VERDICT_NAMES",
    "WARN",
    "BurnRateSLO",
    "DispatchLedger",
    "HealthMonitor",
    "ProfileCapture",
    "ProfilerUnavailable",
    "SpanRecord",
    "StreamingQuantile",
    "Tracer",
    "ascii_timeline",
    "attribution",
    "attribution_table",
    "cluster_report",
    "default_ledger",
    "default_tracer",
    "estimate_offsets",
    "flight_snapshot",
    "link_latencies",
    "merge_records",
    "normalize_dump",
    "pacing_decisions",
    "report_text",
    "set_default_ledger",
    "set_default_tracer",
    "side_by_side_timeline",
    "wall_attribution",
]
