"""Observability: span tracing, flight recorder, latency attribution.

The layer every perf PR is judged against — see tracer.py for the design
notes. Stdlib only."""

from .report import ascii_timeline, attribution, attribution_table
from .tracer import (
    DEFAULT_RING_SIZE,
    SpanRecord,
    Tracer,
    default_tracer,
    flight_snapshot,
    set_default_tracer,
)

__all__ = [
    "DEFAULT_RING_SIZE",
    "SpanRecord",
    "Tracer",
    "ascii_timeline",
    "attribution",
    "attribution_table",
    "default_tracer",
    "flight_snapshot",
    "set_default_tracer",
]
