"""Cluster-wide causal trace merge + quorum-latency attribution.

A single node's flight recorder answers "where did height H's time go
HERE"; consensus latency is a distributed property — a slow height is a
slow proposer, a laggy gossip link, or one straggler validator closing
the 2/3 quorum late. This module joins per-validator `dump_traces`
dumps into one timeline and names that bottleneck:

- `estimate_offsets` turns the per-peer NTP tables (timestamped
  ping/pong, p2p/mconn.py) into one clock offset per node relative to a
  reference node. Offsets are summed along the MINIMUM-RTT path through
  the peer graph (Dijkstra), not read off the direct edge: an
  asymmetric-delay link biases its own NTP estimate by delay/2, but a
  clean two-hop path through a third validator doesn't — so one bad
  link can't skew the merge. Nodes with no usable path fall back to the
  raw wall anchors (`epoch_wall_ns`).
- `merge_records` rebases every node's records onto the reference
  node's tracer timeline (annotating each with its node name) so a
  receive on B is directly comparable to the send on A.
- `link_latencies` joins `gossip.send`/`gossip.recv` pairs (matched on
  height/round/type/index + sender) into per-directed-link one-way
  latency estimates.
- `cluster_report` builds the per-height "slowest path" report:
  proposer → proposal gossip per node → per-validator vote arrivals →
  the quorum-closing vote, plus a straggler ranking across heights.

All functions operate on plain dicts (the `dump_traces` response shape)
so they consume RPC responses and JSON files equally. Stdlib only.
"""

from __future__ import annotations

import heapq

from .report import pct

REPORT_SCHEMA = "tm-tpu/cluster-report/v2"

# dumps whose offset came from the NTP peer graph vs the raw wall clock
SOURCE_NTP = "ntp_graph"
SOURCE_WALL = "wall_anchor"
SOURCE_REFERENCE = "reference"


def normalize_dump(doc, name: str = "") -> dict:
    """Accept a `dump_traces` response (optionally wrapped in a JSON-RPC
    {"result": ...} envelope) or a pre-built dump dict and return the
    canonical shape used by every function here."""
    if isinstance(doc, dict) and "result" in doc and isinstance(
        doc["result"], dict
    ):
        doc = doc["result"]
    if not isinstance(doc, dict) or "records" not in doc:
        raise ValueError("unrecognized trace dump shape (no records)")
    node_id = doc.get("node_id", "") or ""
    return {
        "node_id": node_id,
        "name": name or doc.get("moniker") or node_id[:12] or "node",
        "epoch_wall_ns": int(doc.get("epoch_wall_ns", 0)),
        "records": doc["records"],
        "peer_clock": doc.get("peer_clock") or {},
    }


# --- clock-offset estimation ----------------------------------------------


def estimate_offsets(dumps: list[dict], reference: str = "") -> dict:
    """Per-node clock offset (node wall clock minus reference wall
    clock, seconds) via minimum-RTT paths through the peer NTP graph.

    Returns {node_id: {"offset_s", "rtt_s", "hops", "source"}}. The
    reference is `reference` (a node_id) or the first dump's node.
    """
    ids = [d["node_id"] for d in dumps]
    ref = reference or (ids[0] if ids else "")
    # directed measurement edges: A's table entry for B estimates
    # offset(B-A) with confidence ~rtt; B's own table supplies the
    # reverse measurement, and we mirror each edge so a one-sided table
    # (short run, asymmetric sampling) still connects the graph
    edges: dict[str, list[tuple[str, float, float]]] = {i: [] for i in ids}
    known = set(ids)
    for d in dumps:
        src = d["node_id"]
        for dst, info in d["peer_clock"].items():
            if dst not in known or not info:
                continue
            # prefer the min-RTT sample (NTP clock filter: queueing only
            # ever inflates a sample, so the fastest round trip carries
            # the sharpest offset); fall back to the EWMA
            off = info.get("min_rtt_offset_s")
            rtt = info.get("min_rtt_s")
            if off is None or rtt is None:
                off = info.get("offset_s")
                rtt = info.get("rtt_s")
            if off is None or rtt is None or not info.get("samples"):
                continue
            edges[src].append((dst, float(off), max(1e-9, float(rtt))))
            edges[dst].append((src, -float(off), max(1e-9, float(rtt))))

    out = {
        ref: {
            "offset_s": 0.0,
            "rtt_s": 0.0,
            "hops": 0,
            "source": SOURCE_REFERENCE,
        }
    }
    # Dijkstra over cumulative RTT from the reference
    dist: dict[str, float] = {ref: 0.0}
    heap: list[tuple[float, str, float, int]] = [(0.0, ref, 0.0, 0)]
    done: set[str] = set()
    while heap:
        d_rtt, node, off_sum, hops = heapq.heappop(heap)
        if node in done:
            continue
        done.add(node)
        if node != ref:
            out[node] = {
                "offset_s": round(off_sum, 9),
                "rtt_s": round(d_rtt, 9),
                "hops": hops,
                "source": SOURCE_NTP,
            }
        for dst, off, rtt in edges.get(node, ()):
            nd = d_rtt + rtt
            if dst not in dist or nd < dist[dst]:
                dist[dst] = nd
                heapq.heappush(heap, (nd, dst, off_sum + off, hops + 1))
    for i in ids:
        if i not in out:
            # no NTP path: trust the node's wall clock as-is
            out[i] = {
                "offset_s": 0.0,
                "rtt_s": 0.0,
                "hops": 0,
                "source": SOURCE_WALL,
            }
    return out


# --- merge ----------------------------------------------------------------


def merge_records(
    dumps: list[dict], offsets=None, reference: str = ""
) -> tuple[str, dict, list[dict]]:
    """Rebase every dump's records onto the reference node's tracer
    timeline. Returns (reference_node_id, offsets, merged_records);
    each merged record gains `node` (display name) and `node_id`, with
    `t0` in seconds on the reference timeline."""
    if not dumps:
        return "", {}, []
    # display names key the report's offsets/links sections; duplicate
    # monikers (fleet config templates) would silently overwrite one
    # another and pool distinct links' stats — suffix them unique.
    # (In-place: the names are baked into the merged records and must
    # match what cluster_report later reads off the dumps.)
    seen: dict[str, int] = {}
    for d in dumps:
        n = seen.get(d["name"], 0)
        seen[d["name"]] = n + 1
        if n:
            d["name"] = f"{d['name']}#{n + 1}"
    ids = [d["node_id"] for d in dumps]
    ref = reference or ids[0]
    if ref not in ids:
        raise ValueError(
            f"reference {ref!r} is not among the dumps' node ids {ids}"
        )
    if offsets is None:
        offsets = estimate_offsets(dumps, ref)
    ref_dump = next(d for d in dumps if d["node_id"] == ref)
    ref_epoch = ref_dump["epoch_wall_ns"]
    merged = []
    for d in dumps:
        off_ns = offsets.get(d["node_id"], {}).get("offset_s", 0.0) * 1e9
        # node wall = epoch_wall + t0; reference clock = wall - offset
        shift_s = (d["epoch_wall_ns"] - off_ns - ref_epoch) / 1e9
        for r in d["records"]:
            m = dict(r)
            m["t0"] = r.get("t0", 0.0) + shift_s
            m["node"] = d["name"]
            m["node_id"] = d["node_id"]
            merged.append(m)
    merged.sort(key=lambda r: r["t0"])
    return ref, offsets, merged


def to_chrome_trace(merged: list[dict], dumps: list[dict]) -> dict:
    """Chrome trace_event JSON over a merged record list: one pid per
    node (named via process_name metadata), one tid per height — load in
    Perfetto for the cluster-wide timeline."""
    pids = {d["node_id"]: i + 1 for i, d in enumerate(dumps)}
    events = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pids[d["node_id"]],
            "args": {"name": d["name"]},
        }
        for d in dumps
    ]
    for r in merged:
        ev = {
            "name": r.get("name", ""),
            "ph": "X" if r.get("kind") == "span" else "i",
            "ts": round(r["t0"] * 1e6, 1),
            "pid": pids.get(r.get("node_id"), 0),
            "tid": r.get("height", 0),
            "args": {
                "height": r.get("height", 0),
                "round": r.get("round", 0),
                "node": r.get("node", ""),
                **(r.get("fields") or {}),
            },
        }
        if r.get("kind") == "span":
            ev["dur"] = round(r.get("dur", 0.0) * 1e6, 1)
        else:
            ev["s"] = "g"
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# --- causal joins ---------------------------------------------------------


def _gossip_key(r: dict):
    f = r.get("fields") or {}
    return (
        r.get("height", 0),
        r.get("round", 0),
        f.get("type", ""),
        f.get("val", f.get("part", -1)),
    )


def link_latencies(merged: list[dict], dumps: list[dict]) -> list[dict]:
    """Per-directed-link one-way latency from matched gossip send/recv
    pairs, ranked slowest first. A send with peer="*" (broadcast) joins
    every receive that names its node as the source."""
    by_id = {d["node_id"]: d["name"] for d in dumps}
    # first send per (src_node, key); a receive joins on (key, src).
    # Both sides dedup to the FIRST occurrence: gossip re-sends (and the
    # receiver's record of a duplicate arrival) measure retry cadence,
    # not link latency
    sends: dict[tuple, float] = {}
    recvs: dict[tuple, tuple[float, str, str]] = {}
    for r in merged:
        if r.get("name") == "gossip.send":
            k = (r["node_id"], _gossip_key(r))
            if k not in sends or r["t0"] < sends[k]:
                sends[k] = r["t0"]
        elif r.get("name") == "gossip.recv":
            src_id = (r.get("fields") or {}).get("peer", "")
            k = (r["node_id"], src_id, _gossip_key(r))
            if k not in recvs or r["t0"] < recvs[k][0]:
                recvs[k] = (r["t0"], src_id, r["node"])
    pair_lags: dict[tuple[str, str], list[float]] = {}
    for (_, src_id, key), (t_recv, src, dst_name) in recvs.items():
        t_send = sends.get((src_id, key))
        if t_send is None:
            continue
        lag = t_recv - t_send
        if lag < -0.025:
            # the original send predates the ring (evicted/cleared) and
            # this "send" is a later re-gossip of the same message —
            # joining it would count a large negative lag. The cutoff
            # sits beyond any plausible clock-rebase error (the offset
            # estimator is good to ~±10 ms worst-case), so moderately
            # negative lags on fast links survive into the stats
            # instead of silently deleting the link.
            continue
        pair_lags.setdefault(
            (by_id.get(src, src[:12]), dst_name), []
        ).append(lag)
    out = []
    for (src, dst), lags in pair_lags.items():
        out.append(
            {
                "src": src,
                "dst": dst,
                # min is the propagation-delay estimate (the NTP filter
                # trick: queueing and receiver-side processing only ever
                # ADD to a sample); median/p95 fold congestion in
                "min_lag_ms": round(min(lags) * 1e3, 3),
                "median_lag_ms": round(pct(lags, 0.5) * 1e3, 3),
                "p95_lag_ms": round(pct(lags, 0.95) * 1e3, 3),
                "samples": len(lags),
            }
        )
    out.sort(key=lambda e: (-e["min_lag_ms"], -e["median_lag_ms"]))
    return out


# --- the per-height slowest path ------------------------------------------


def height_paths(merged: list[dict], n_heights: int = 16) -> dict[int, dict]:
    """Per-height slowest-path decomposition over merged records:
    proposer send -> per-node proposal receipt -> per-node precommit
    quorum close (with the closing validator)."""
    heights: dict[int, list[dict]] = {}
    for r in merged:
        h = r.get("height", 0)
        if h > 0:
            heights.setdefault(h, []).append(r)
    out: dict[int, dict] = {}
    for h in sorted(heights)[-n_heights:]:
        rows = heights[h]
        prop_sends = [
            r
            for r in rows
            if r["name"] == "gossip.send"
            and (r.get("fields") or {}).get("type") == "proposal"
        ]
        prop_recvs = [
            r
            for r in rows
            if r["name"] == "gossip.recv"
            and (r.get("fields") or {}).get("type") == "proposal"
        ]
        t_prop = min(
            (r["t0"] for r in prop_sends),
            default=min((r["t0"] for r in prop_recvs), default=None),
        )
        proposer = min(prop_sends, key=lambda r: r["t0"])["node"] if (
            prop_sends
        ) else ""
        gossip = {}
        for r in prop_recvs:
            if t_prop is None:
                break
            lag = round((r["t0"] - t_prop) * 1e3, 3)
            if r["node"] not in gossip or lag < gossip[r["node"]]:
                gossip[r["node"]] = lag
        closes = [
            r
            for r in rows
            if r["name"] == "quorum.close"
            and (r.get("fields") or {}).get("type") == "precommit"
        ]
        quorum = {}
        for r in closes:
            f = r.get("fields") or {}
            cur = quorum.get(r["node"])
            if cur is None or r["t0"] > cur["t"]:
                quorum[r["node"]] = {
                    "t": r["t0"],
                    "closer_index": f.get("closer", -1),
                    "close_lag_ms": f.get("lag_ms", 0.0),
                    "round": r.get("round", 0),
                }
        slowest = None
        if quorum:
            name = max(quorum, key=lambda n: quorum[n]["t"])
            q = quorum[name]
            slowest = {
                "node": name,
                "closer_index": q["closer_index"],
                "close_lag_ms": q["close_lag_ms"],
                "commit_wait_ms": (
                    round((q["t"] - t_prop) * 1e3, 3)
                    if t_prop is not None
                    else None
                ),
            }
        out[h] = {
            "proposer": proposer,
            "proposal_gossip_ms": gossip,
            "quorum_close": {
                n: {k: v for k, v in q.items() if k != "t"}
                for n, q in quorum.items()
            },
            "slowest": slowest,
        }
    return out


def straggler_ranking(merged: list[dict]) -> list[dict]:
    """Across all heights: which validator's vote closes the precommit
    quorum, how often, and with what lag — the committee's stragglers,
    worst first."""
    closed: dict[int, list[float]] = {}
    arrivals: dict[int, list[float]] = {}
    n_closes = 0
    for r in merged:
        f = r.get("fields") or {}
        if f.get("type") != "precommit":
            continue
        if r.get("name") == "quorum.close":
            closed.setdefault(int(f.get("closer", -1)), []).append(
                float(f.get("lag_ms", 0.0))
            )
            n_closes += 1
        elif r.get("name") == "quorum.vote":
            arrivals.setdefault(int(f.get("val", -1)), []).append(
                float(f.get("lag_ms", 0.0))
            )
    out = []
    for val in sorted(set(closed) | set(arrivals)):
        lags = closed.get(val, [])
        out.append(
            {
                "validator_index": val,
                "quorum_closes": len(lags),
                "close_share": round(len(lags) / max(1, n_closes), 3),
                "median_close_lag_ms": round(pct(lags, 0.5), 3),
                "median_arrival_lag_ms": round(
                    pct(arrivals.get(val, []), 0.5), 3
                ),
            }
        )
    out.sort(
        key=lambda e: (-e["quorum_closes"], -e["median_arrival_lag_ms"])
    )
    return out


def verify_flow(merged: list[dict]) -> dict:
    """Cross-process verify attribution: join each node's client-side
    `verify.ipc` round-trip span with the verify-SERVICE's
    `verify.queue`/`verify.device`/`verify.service` sub-spans recorded
    under the same wire trace context (matched on origin + request id).
    The service dump merges on the raw-wall-anchor fallback (it sits
    outside the p2p NTP graph), so the join uses DURATIONS, never
    cross-ring timestamps: wire overhead = client RTT minus the
    service-observed handle time, which both clocks agree on.

    Returns per-height rows plus an aggregate — the verify slice of the
    wall-conservation story across the process split."""
    svc: dict[tuple, dict] = {}
    for r in merged:
        name = r.get("name", "")
        if name not in ("verify.queue", "verify.device", "verify.service"):
            continue
        f = r.get("fields") or {}
        key = (f.get("origin", ""), f.get("req", -1))
        sub = svc.setdefault(key, {})
        # ACCUMULATE: a submission larger than the scheduler's
        # max_batch dispatches as several device rounds, each recording
        # its own queue/device sub-span under the same (origin, req) —
        # last-write-wins would drop all but one round's time
        sub[name] = sub.get(name, 0.0) + r.get("dur", 0.0)
    heights: dict[int, dict] = {}
    joined = 0
    for r in merged:
        if r.get("name") != "verify.ipc":
            continue
        f = r.get("fields") or {}
        key = (f.get("origin", ""), f.get("req", -1))
        sub = svc.get(key, {})
        rtt = r.get("dur", 0.0)
        service = sub.get("verify.service", 0.0)
        row = heights.setdefault(
            r.get("height", 0),
            {
                "submissions": 0,
                "joined": 0,
                "rows": 0,
                "ipc_ms": 0.0,
                "queue_ms": 0.0,
                "device_ms": 0.0,
                "wire_ms": 0.0,
            },
        )
        row["submissions"] += 1
        row["rows"] += int(f.get("n", 0))
        row["ipc_ms"] += rtt * 1e3
        if sub:
            joined += 1
            row["joined"] += 1
            row["queue_ms"] += sub.get("verify.queue", 0.0) * 1e3
            row["device_ms"] += sub.get("verify.device", 0.0) * 1e3
            row["wire_ms"] += max(0.0, rtt - service) * 1e3
    for row in heights.values():
        for k in ("ipc_ms", "queue_ms", "device_ms", "wire_ms"):
            row[k] = round(row[k], 3)
    return {
        "submissions": sum(r["submissions"] for r in heights.values()),
        "joined": joined,
        "heights": {str(h): heights[h] for h in sorted(heights)},
    }


def wall_anchor_offsets(dumps: list[dict]) -> dict:
    """All-zero offsets (source wall_anchor): trust each node's wall
    clock as ground truth. The right merge basis for in-proc harnesses
    (soak, tests) where every node shares one clock — NTP estimation
    would import a chaos-delayed link's bias into known-exact anchors."""
    return {
        d["node_id"]: {
            "offset_s": 0.0,
            "rtt_s": 0.0,
            "hops": 0,
            "source": SOURCE_WALL,
        }
        for d in dumps
    }


def cluster_report(
    dumps: list[dict],
    reference: str = "",
    n_heights: int = 16,
    offsets=None,
    merge=None,
) -> dict:
    """The one artifact: offsets + per-height slowest path + link and
    straggler rankings. `dumps` are normalize_dump() outputs. `offsets`
    overrides the NTP estimation (e.g. wall_anchor_offsets); `merge`
    reuses a precomputed merge_records() triple."""
    if merge is None:
        merge = merge_records(dumps, offsets=offsets, reference=reference)
    ref, offsets, merged = merge
    names = {d["node_id"]: d["name"] for d in dumps}
    return {
        "schema": REPORT_SCHEMA,
        "reference": names.get(ref, ref),
        "nodes": [
            {
                "name": d["name"],
                "node_id": d["node_id"],
                "records": len(d["records"]),
            }
            for d in dumps
        ],
        "offsets": {
            names.get(nid, nid): info for nid, info in offsets.items()
        },
        "heights": {
            str(h): path
            for h, path in height_paths(merged, n_heights).items()
        },
        "links": link_latencies(merged, dumps),
        "stragglers": straggler_ranking(merged),
        # cross-process verify attribution (empty when no verify-service
        # dump / traced submissions are in the merge)
        "verify_flow": verify_flow(merged),
    }


def report_text(report: dict) -> str:
    """Human rendering of a cluster_report: per-height slowest path +
    the link/straggler rankings."""
    lines = [
        f"cluster report (reference {report['reference']}, "
        f"{len(report['nodes'])} nodes)"
    ]
    for n in report["nodes"]:
        off = report["offsets"].get(n["name"], {})
        lines.append(
            f"  {n['name']:<12} offset {off.get('offset_s', 0.0) * 1e3:+8.3f} ms"
            f"  ({off.get('source', '?')}, {n['records']} records)"
        )
    lines.append("")
    lines.append(
        f"  {'height':>6} {'proposer':<12} {'slowest node':<12} "
        f"{'closer':>6} {'close_lag_ms':>12} {'commit_wait_ms':>14}"
    )
    for h in sorted(report["heights"], key=int):
        p = report["heights"][h]
        s = p.get("slowest") or {}
        lines.append(
            f"  {h:>6} {p.get('proposer') or '?':<12} "
            f"{s.get('node', '?'):<12} {s.get('closer_index', -1):>6} "
            f"{s.get('close_lag_ms', 0.0):>12.2f} "
            f"{(s.get('commit_wait_ms') or 0.0):>14.2f}"
        )
    if report["links"]:
        lines.append("")
        lines.append("  slowest links (one-way, from matched gossip pairs):")
        for e in report["links"][:8]:
            lines.append(
                f"    {e['src']:<12} -> {e['dst']:<12} "
                f"min {e['min_lag_ms']:>8.2f} ms  "
                f"median {e['median_lag_ms']:>8.2f} ms  "
                f"p95 {e['p95_lag_ms']:>8.2f} ms  ({e['samples']} msgs)"
            )
    vf = report.get("verify_flow") or {}
    if vf.get("submissions"):
        lines.append("")
        lines.append(
            f"  verify flow ({vf['submissions']} traced submissions, "
            f"{vf['joined']} joined to service sub-spans):"
        )
        lines.append(
            f"    {'height':>6} {'subs':>5} {'rows':>6} {'ipc_ms':>9} "
            f"{'queue_ms':>9} {'device_ms':>9} {'wire_ms':>9}"
        )
        for h in sorted(vf["heights"], key=int):
            r = vf["heights"][h]
            lines.append(
                f"    {h:>6} {r['submissions']:>5} {r['rows']:>6} "
                f"{r['ipc_ms']:>9.2f} {r['queue_ms']:>9.2f} "
                f"{r['device_ms']:>9.2f} {r['wire_ms']:>9.2f}"
            )
    if report["stragglers"]:
        lines.append("")
        lines.append("  quorum-closing stragglers (precommit):")
        for s in report["stragglers"][:8]:
            lines.append(
                f"    val {s['validator_index']:>3}  closed "
                f"{s['quorum_closes']:>3}x ({s['close_share'] * 100:.0f}%)  "
                f"median close lag {s['median_close_lag_ms']:>8.2f} ms  "
                f"median arrival {s['median_arrival_lag_ms']:>8.2f} ms"
            )
    return "\n".join(lines)
