"""Latency attribution + ASCII timelines over tracer dumps.

`attribution` answers "where does a height's time go" in aggregate
(p50/p95 per span name); `ascii_timeline` renders one run's flight
recorder as a per-height step table — the artifact soak.py ships with a
diverging seed and tools/trace_report.py renders from a dump file.

Operates on plain record dicts (`SpanRecord.to_json()` shape) so it can
consume a `dump_traces` RPC response or a JSON file equally.
"""

from __future__ import annotations

from .tracer import SpanRecord, flight_snapshot

# consensus step spans in canonical order (state_machine Step enum)
STEP_ORDER = (
    "cs.new_height",
    "cs.new_round",
    "cs.propose",
    "cs.prevote",
    "cs.prevote_wait",
    "cs.precommit",
    "cs.precommit_wait",
    "cs.commit",
)


def _pct(xs: list[float], q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def attribution(records: list[dict]) -> dict:
    """Per-span-name p50/p95/max duration (ms) + count over span records.
    The bench/soak artifacts attach this so a throughput scalar comes
    with its breakdown."""
    durs: dict[str, list[float]] = {}
    heights = set()
    for r in records:
        if r.get("kind") != "span":
            continue
        durs.setdefault(r["name"], []).append(r.get("dur", 0.0) * 1e3)
        if r.get("height"):
            heights.add(r["height"])

    def key(name: str):
        return (
            STEP_ORDER.index(name) if name in STEP_ORDER else len(STEP_ORDER),
            name,
        )

    return {
        "heights": len(heights),
        "steps": {
            name: {
                "count": len(ds),
                "p50_ms": round(_pct(ds, 0.5), 3),
                "p95_ms": round(_pct(ds, 0.95), 3),
                "max_ms": round(max(ds), 3),
            }
            for name, ds in sorted(durs.items(), key=lambda kv: key(kv[0]))
        },
    }


def ascii_timeline(records: list[dict], n_heights: int = 16) -> str:
    """Per-height step-timeline table. Spans show offset + duration from
    the height's first record; events render as `!` annotations at their
    offset — a chaos partition lands visibly inside the height it hit."""
    recs = [SpanRecord.from_json(r) for r in records]
    flight = flight_snapshot(recs, n_heights)
    if not flight:
        return "(no trace records)"
    lines = []
    for h in sorted(flight):
        rows = flight[h]
        t_base = min(r["t0"] for r in rows)
        t_end = max(r["t0"] + r.get("dur", 0.0) for r in rows)
        lines.append(
            f"height {h}  ({(t_end - t_base) * 1e3:.1f} ms, "
            f"{len(rows)} records)"
        )
        lines.append(f"  {'span':<28} {'t+ms':>9} {'dur_ms':>9}")
        for r in rows:
            off = (r["t0"] - t_base) * 1e3
            if r["kind"] == "span":
                lines.append(
                    f"  {r['name']:<28} {off:>9.2f} "
                    f"{r.get('dur', 0.0) * 1e3:>9.2f}"
                )
            else:
                extra = ""
                if r.get("fields"):
                    extra = " " + ",".join(
                        f"{k}={v}" for k, v in sorted(r["fields"].items())
                    )
                lines.append(f"  ! {r['name']:<26} {off:>9.2f}{extra}")
    return "\n".join(lines)


def attribution_table(records: list[dict]) -> str:
    """The attribution dict rendered as an aligned text table."""
    att = attribution(records)
    lines = [
        f"latency attribution over {att['heights']} heights",
        f"  {'span':<28} {'count':>6} {'p50_ms':>9} {'p95_ms':>9} "
        f"{'max_ms':>9}",
    ]
    for name, s in att["steps"].items():
        lines.append(
            f"  {name:<28} {s['count']:>6} {s['p50_ms']:>9.2f} "
            f"{s['p95_ms']:>9.2f} {s['max_ms']:>9.2f}"
        )
    return "\n".join(lines)
