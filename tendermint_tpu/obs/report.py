"""Latency attribution + ASCII timelines over tracer dumps.

`attribution` answers "where does a height's time go" in aggregate
(p50/p95 per span name); `ascii_timeline` renders one run's flight
recorder as a per-height step table — the artifact soak.py ships with a
diverging seed and tools/trace_report.py renders from a dump file.

Operates on plain record dicts (`SpanRecord.to_json()` shape) so it can
consume a `dump_traces` RPC response or a JSON file equally.
"""

from __future__ import annotations

from .tracer import SpanRecord, flight_snapshot

# consensus step spans in canonical order (state_machine Step enum)
STEP_ORDER = (
    "cs.new_height",
    "cs.new_round",
    "cs.propose",
    "cs.prevote",
    "cs.prevote_wait",
    "cs.precommit",
    "cs.precommit_wait",
    "cs.commit",
)


def pct(xs: list[float], q: float) -> float:
    """Index-based percentile (0 on empty) — the one implementation the
    attribution tables, cluster reports, and bench artifacts share."""
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


_pct = pct


def attribution(records: list[dict]) -> dict:
    """Per-span-name p50/p95/max duration (ms) + count over span records.
    The bench/soak artifacts attach this so a throughput scalar comes
    with its breakdown."""
    durs: dict[str, list[float]] = {}
    heights = set()
    for r in records:
        if r.get("kind") != "span":
            continue
        durs.setdefault(r["name"], []).append(r.get("dur", 0.0) * 1e3)
        if r.get("height"):
            heights.add(r["height"])

    def key(name: str):
        return (
            STEP_ORDER.index(name) if name in STEP_ORDER else len(STEP_ORDER),
            name,
        )

    return {
        "heights": len(heights),
        "steps": {
            name: {
                "count": len(ds),
                "p50_ms": round(_pct(ds, 0.5), 3),
                "p95_ms": round(_pct(ds, 0.95), 3),
                "max_ms": round(max(ds), 3),
            }
            for name, ds in sorted(durs.items(), key=lambda kv: key(kv[0]))
        },
    }


# wall-per-height attribution buckets (tools/pacing_report.py + the
# consensus_pacing/committee_scale/sequencer_stream bench families).
# For the consensus family the cs.* step spans partition a height's
# wall clock by construction (each closes at the transition to the
# next), so bucketing THEM — not the nested exec/store spans, which
# would double-count — splits wall time into:
#   floor   — steps that exist to wait out a timeout window
#   gossip  — steps spent waiting on peers (proposal parts, votes)
#   compute — the decision/finalize step itself
# The sequencer family maps the post-upgrade streaming plane's seq.*
# spans (broadcast_reactor.py) the same way: parked fallback waits are
# the floor, catchup/fan-out the gossip bucket, apply/verify compute.
# committee_scale nets run the same cs.* state machine, so they share
# the consensus classification.
WALL_FLOOR_SPANS = frozenset(
    {"cs.new_height", "cs.prevote_wait", "cs.precommit_wait"}
)
WALL_GOSSIP_SPANS = frozenset({"cs.propose", "cs.prevote", "cs.precommit"})
WALL_COMPUTE_SPANS = frozenset({"cs.commit", "cs.new_round"})

# family name -> (floor, gossip, compute) span sets. "consensus" also
# serves the committee_scale bench family; "sequencer" covers the
# BlockV2 streaming plane (heights there are V2/L2 heights).
FAMILY_WALL_SPANS: dict[str, tuple[frozenset, frozenset, frozenset]] = {
    "consensus": (WALL_FLOOR_SPANS, WALL_GOSSIP_SPANS, WALL_COMPUTE_SPANS),
    "sequencer": (
        frozenset({"seq.park"}),
        frozenset({"seq.broadcast", "seq.sync_gap"}),
        frozenset({"seq.apply"}),
    ),
}


def wall_attribution(
    records: list[dict], n_heights: int = 64, family: str = "consensus"
) -> dict:
    """Per-height wall-clock attribution: how much of each height went
    to the timeout floor vs gossip waits vs compute, from one node's
    trace records (SpanRecord.to_json dicts). `family` selects the span
    classification (FAMILY_WALL_SPANS); `other` is the residue of
    the height window not covered by step spans (ring-boundary effects,
    records from other subsystems widening the window)."""
    try:
        floor_spans, gossip_spans, compute_spans = FAMILY_WALL_SPANS[family]
    except KeyError:
        raise ValueError(
            f"unknown wall-attribution family {family!r}; known: "
            f"{sorted(FAMILY_WALL_SPANS)}"
        ) from None
    recs = [SpanRecord.from_json(r) for r in records]
    flight = flight_snapshot(recs, n_heights)
    heights: dict[int, dict] = {}
    for h, rows in flight.items():
        t0 = min(r["t0"] for r in rows)
        t1 = max(r["t0"] + r.get("dur", 0.0) for r in rows)
        wall = t1 - t0
        buckets = {"floor": 0.0, "gossip": 0.0, "compute": 0.0}
        for r in rows:
            if r["kind"] != "span":
                continue
            name = r["name"]
            if name in floor_spans:
                buckets["floor"] += r.get("dur", 0.0)
            elif name in gossip_spans:
                buckets["gossip"] += r.get("dur", 0.0)
            elif name in compute_spans:
                buckets["compute"] += r.get("dur", 0.0)
        covered = sum(buckets.values())
        heights[h] = {
            "wall_ms": round(wall * 1e3, 3),
            "floor_ms": round(buckets["floor"] * 1e3, 3),
            "gossip_ms": round(buckets["gossip"] * 1e3, 3),
            "compute_ms": round(buckets["compute"] * 1e3, 3),
            "other_ms": round(max(0.0, wall - covered) * 1e3, 3),
        }
    if not heights:
        return {"heights": {}, "aggregate": {}}
    walls = [v["wall_ms"] for v in heights.values()]
    floor = sum(v["floor_ms"] for v in heights.values())
    gossip = sum(v["gossip_ms"] for v in heights.values())
    compute = sum(v["compute_ms"] for v in heights.values())
    total = sum(walls)
    return {
        "heights": heights,
        "aggregate": {
            "n_heights": len(heights),
            "wall_ms_p50": round(pct(walls, 0.5), 3),
            "wall_ms_p95": round(pct(walls, 0.95), 3),
            "wall_ms_max": round(max(walls), 3),
            "floor_share": round(floor / total, 4) if total else 0.0,
            "gossip_share": round(gossip / total, 4) if total else 0.0,
            "compute_share": round(compute / total, 4) if total else 0.0,
        },
    }


# --- wall-clock conservation ------------------------------------------------
#
# wall_attribution above answers "how do the STEP spans split a height";
# wall_conservation answers the stricter question ROADMAP item 4 needs:
# does EVERY slice of a height's measured wall clock have a name? The
# decomposition is mutually exclusive and exhaustive by construction —
# each elementary time segment of the height window is assigned to
# exactly one bucket by a priority sweep — so the buckets plus the
# `dark_time` residue sum to the measured wall exactly (plus the
# explicitly booked `pipeline_overlap_ms` under height pipelining), and
# unexplained latency can never hide inside an "other" that also
# absorbs known overlap error.

CONSERVATION_SCHEMA = "tm-tpu/wall-conservation/v1"

# carve buckets, HIGHEST priority first: a segment covered by several
# span families is charged to the first bucket here that claims it.
# Device time outranks queue wait (the queue_wait span of a round ends
# where its device span begins, but service-side merges can overlap),
# and the client-observed IPC round trip ranks below both so that when
# service sub-spans are present (one merged timeline) the RTT only
# keeps the wire/serialization slice the service can't see.
CONSERVATION_CARVES: tuple[tuple[str, frozenset], ...] = (
    ("verify_device", frozenset({"scheduler.device_round", "verify.device"})),
    ("verify_queue", frozenset({"scheduler.queue_wait", "verify.queue"})),
    ("verify_ipc", frozenset({"verify.ipc"})),
    ("wal_fsync", frozenset({"wal.fsync", "wal.group_fsync"})),
    (
        "commit_pipeline",
        frozenset({"commit.pipeline_wait", "store.save_block"}),
    ),
)

CONSERVATION_BUCKETS = tuple(
    [name for name, _ in CONSERVATION_CARVES]
    + ["floor", "gossip", "compute", "dark_time"]
)

# QC-chained height pipelining (PERF_ANALYSIS §22): height H's
# background finalization — the durability barrier, the apply, the block
# save, the QC pre-assembly, and any consumer blocking on them — runs
# while the state machine's step spans already tile height H+1. Those
# H-tagged spans fall OUTSIDE H's own step window; their out-of-window
# portions are still charged to their carve bucket AND booked as
# `pipeline_overlap_ms`, so per height sum(buckets) == wall + overlap
# (shared wall is attributed to exactly ONE height — the one whose step
# spans tile it — and the overlap credit names the work that rode along
# under it).
OVERLAP_CARVE_OF: dict[str, str] = {
    "wal.pipeline_barrier": "wal_fsync",
    "commit.pipeline_wait": "commit_pipeline",
    "store.save_block": "commit_pipeline",
    "exec.apply_block": "commit_pipeline",
    "commit.qc_assemble": "commit_pipeline",
}

_STEP_SPANS = frozenset(STEP_ORDER)

# derived lookups for the sweep (pure functions of the carve table)
_CARVE_PRIO = {name: i for i, (name, _) in enumerate(CONSERVATION_CARVES)}
_CARVE_OF = {
    span: name for name, spans in CONSERVATION_CARVES for span in spans
}


def _step_bucket(name: str) -> str:
    if name in WALL_FLOOR_SPANS:
        return "floor"
    if name in WALL_GOSSIP_SPANS:
        return "gossip"
    return "compute"


def wall_conservation(records: list[dict], n_heights: int = 64) -> dict:
    """Per-height exhaustive wall-clock decomposition. The height window
    is the span of its cs.* step records (they tile the height by
    construction: `_new_step` closes each at the transition to the
    next); carve spans — verify IPC/queue/device, WAL fsync, the commit
    pipeline wait — claim their segments out of the containing step's
    bucket, the step classification (floor/gossip/compute) takes what
    remains, and any segment covered by NO span at all lands in
    `dark_time`. Out-of-window portions of the height's own background
    spans (OVERLAP_CARVE_OF — pipelined finalization running under a
    neighbor height) are charged to their bucket and booked as
    `pipeline_overlap_ms`. Invariant: sum(buckets) == wall +
    pipeline_overlap per height (float eps; overlap is 0 without
    pipelining, restoring the strict identity); the `conserved` flag in
    the aggregate attests it was checked.
    Accepts record dicts (dump files, RPC responses) or SpanRecord
    objects directly (the health plane's per-tick pull skips the
    serialize/deserialize round trip)."""
    recs = [
        r if isinstance(r, SpanRecord) else SpanRecord.from_json(r)
        for r in records
    ]
    flight = flight_snapshot(recs, n_heights)
    heights: dict[int, dict] = {}
    conserved = True
    for h, rows in flight.items():
        steps = [
            r
            for r in rows
            if r["kind"] == "span" and r["name"] in _STEP_SPANS
        ]
        if not steps:
            continue
        w0 = min(r["t0"] for r in steps)
        w1 = max(r["t0"] + r.get("dur", 0.0) for r in steps)
        wall = w1 - w0
        if wall <= 0:
            continue
        # (start, end, bucket, priority) clipped to the window
        intervals: list[tuple[float, float, str, int]] = []
        for r in rows:
            if r["kind"] != "span":
                continue
            bucket = _CARVE_OF.get(r["name"])
            if bucket is None:
                continue
            s = max(w0, r["t0"])
            e = min(w1, r["t0"] + r.get("dur", 0.0))
            if e > s:
                intervals.append((s, e, bucket, _CARVE_PRIO[bucket]))
        base = len(CONSERVATION_CARVES)
        for r in steps:
            s = max(w0, r["t0"])
            e = min(w1, r["t0"] + r.get("dur", 0.0))
            if e > s:
                intervals.append((s, e, _step_bucket(r["name"]), base))
        # priority sweep over elementary segments: every edge point
        # starts a segment owned by the highest-priority cover (or dark)
        edges = sorted(
            {w0, w1}
            | {iv[0] for iv in intervals}
            | {iv[1] for iv in intervals}
        )
        buckets = {name: 0.0 for name in CONSERVATION_BUCKETS}
        for a, b in zip(edges, edges[1:]):
            cover = [iv for iv in intervals if iv[0] <= a and iv[1] >= b]
            if cover:
                buckets[min(cover, key=lambda iv: iv[3])[2]] += b - a
            else:
                buckets["dark_time"] += b - a
        # out-of-window portions of this height's background spans:
        # pipelined finalization running under a neighbor height's wall.
        # Same priority-sweep discipline so overlapping background spans
        # (pipeline_wait covering apply_block) book each slice once.
        over_iv: list[tuple[float, float, str, int]] = []
        for r in rows:
            if r["kind"] != "span":
                continue
            bucket = OVERLAP_CARVE_OF.get(r["name"])
            if bucket is None:
                continue
            s, e = r["t0"], r["t0"] + r.get("dur", 0.0)
            for os_, oe in ((s, min(e, w0)), (max(s, w1), e)):
                if oe > os_:
                    over_iv.append((os_, oe, bucket, _CARVE_PRIO[bucket]))
        overlap = 0.0
        if over_iv:
            oedges = sorted(
                {iv[0] for iv in over_iv} | {iv[1] for iv in over_iv}
            )
            for a, b in zip(oedges, oedges[1:]):
                cover = [iv for iv in over_iv if iv[0] <= a and iv[1] >= b]
                if cover:
                    buckets[min(cover, key=lambda iv: iv[3])[2]] += b - a
                    overlap += b - a
        total = sum(buckets.values())
        if abs(total - (wall + overlap)) > 1e-6 * max(1.0, wall):
            conserved = False
        heights[h] = {
            "wall_ms": round(wall * 1e3, 3),
            **{
                f"{name}_ms": round(v * 1e3, 3)
                for name, v in buckets.items()
            },
            "pipeline_overlap_ms": round(overlap * 1e3, 3),
            "dark_fraction": round(buckets["dark_time"] / wall, 4),
        }
    if not heights:
        return {
            "schema": CONSERVATION_SCHEMA,
            "heights": {},
            "aggregate": {},
        }
    walls = [v["wall_ms"] for v in heights.values()]
    total_wall = sum(walls)
    shares = {
        f"{name}_share": round(
            sum(v[f"{name}_ms"] for v in heights.values()) / total_wall, 4
        )
        for name in CONSERVATION_BUCKETS
    }
    return {
        "schema": CONSERVATION_SCHEMA,
        "heights": heights,
        "aggregate": {
            "n_heights": len(heights),
            "wall_ms_p50": round(pct(walls, 0.5), 3),
            "wall_ms_p95": round(pct(walls, 0.95), 3),
            "wall_ms_max": round(max(walls), 3),
            **shares,
            "pipeline_overlap_share": round(
                sum(v["pipeline_overlap_ms"] for v in heights.values())
                / total_wall,
                4,
            ),
            "dark_fraction": shares["dark_time_share"],
            "dark_fraction_max": max(
                v["dark_fraction"] for v in heights.values()
            ),
            "conserved": conserved,
        },
    }


def check_conservation(block: dict, tolerance: float = 0.002) -> list[str]:
    """Schema validation for a wall_conservation block (bench artifacts,
    tools/bench_trend.py): every height's buckets must sum to its wall
    within `tolerance` (fractional), and the aggregate must carry the
    dark_fraction fields. Under height pipelining buckets may exceed the
    wall, but only by the explicitly booked `pipeline_overlap_ms` —
    unbooked excess is still a violation. Pre-pipelining artifacts carry
    no overlap key, which reads as 0.0: their check is unchanged.
    Returns a list of violation strings (empty = valid)."""
    errs: list[str] = []
    if not isinstance(block, dict):
        return ["wall_conservation is not an object"]
    agg = block.get("aggregate")
    if not isinstance(agg, dict):
        return ["wall_conservation.aggregate missing"]
    if not agg:
        return []  # empty capture: nothing to conserve
    for key in ("dark_fraction", "n_heights"):
        if key not in agg:
            errs.append(f"aggregate.{key} missing")
    for h, row in (block.get("heights") or {}).items():
        wall = row.get("wall_ms")
        if wall is None:
            errs.append(f"height {h}: wall_ms missing")
            continue
        covered = sum(
            row.get(f"{name}_ms", 0.0) for name in CONSERVATION_BUCKETS
        )
        expected = wall + row.get("pipeline_overlap_ms", 0.0)
        if wall > 0 and abs(covered - expected) > tolerance * wall:
            errs.append(
                f"height {h}: buckets sum to {covered:.3f} ms != wall "
                f"{wall:.3f} ms + overlap "
                f"{row.get('pipeline_overlap_ms', 0.0):.3f} ms"
            )
    return errs


def conservation_table(cons: dict) -> str:
    """The wall_conservation dict as an aligned text table."""
    agg = cons.get("aggregate") or {}
    if not agg:
        return "(no step spans in dump — conservation needs cs.* records)"
    overlap_share = agg.get("pipeline_overlap_share", 0.0)
    head = (
        f"wall-clock conservation over {agg['n_heights']} heights "
        f"(dark {agg['dark_fraction']:.1%}, worst height "
        f"{agg['dark_fraction_max']:.1%}"
    )
    head += (
        f", pipelined overlap {overlap_share:.1%})" if overlap_share else ")"
    )
    cols = list(CONSERVATION_BUCKETS) + ["pipeline_overlap"]
    lines = [
        head,
        "  shares: "
        + "  ".join(
            f"{name} {agg.get(f'{name}_share', 0.0):.1%}"
            for name in cols
        ),
        f"  {'height':>8} {'wall_ms':>9} "
        + " ".join(f"{n[:9]:>9}" for n in cols),
    ]
    for h in sorted(cons.get("heights") or {}, key=int):
        v = cons["heights"][h]
        lines.append(
            f"  {h:>8} {v['wall_ms']:>9.2f} "
            + " ".join(f"{v.get(f'{n}_ms', 0.0):>9.2f}" for n in cols)
        )
    return "\n".join(lines)


def pacing_decisions(records: list[dict]) -> dict:
    """Per-step learned-vs-static summary from `pacing.decision` trace
    events (consensus/pacing.py emits one per step per height)."""
    by_step: dict[str, list[dict]] = {}
    for r in records:
        if r.get("name") != "pacing.decision":
            continue
        f = r.get("fields") or {}
        step = f.get("step")
        if step:
            by_step.setdefault(step, []).append(f)
    out = {}
    for step, rows in by_step.items():
        eff = [float(x.get("effective_ms", 0.0)) for x in rows]
        learned = [float(x.get("learned_ms", 0.0)) for x in rows]
        out[step] = {
            "decisions": len(rows),
            "static_ms": float(rows[-1].get("static_ms", 0.0)),
            "learned_ms_last": learned[-1] if learned else 0.0,
            "effective_ms_p50": round(pct(eff, 0.5), 3),
            "effective_ms_last": eff[-1] if eff else 0.0,
            "backoff_last": float(rows[-1].get("backoff", 0.0)),
        }
    return out


def ascii_timeline(records: list[dict], n_heights: int = 16) -> str:
    """Per-height step-timeline table. Spans show offset + duration from
    the height's first record; events render as `!` annotations at their
    offset — a chaos partition lands visibly inside the height it hit."""
    recs = [SpanRecord.from_json(r) for r in records]
    flight = flight_snapshot(recs, n_heights)
    if not flight:
        return "(no trace records)"
    lines = []
    for h in sorted(flight):
        rows = flight[h]
        t_base = min(r["t0"] for r in rows)
        t_end = max(r["t0"] + r.get("dur", 0.0) for r in rows)
        lines.append(
            f"height {h}  ({(t_end - t_base) * 1e3:.1f} ms, "
            f"{len(rows)} records)"
        )
        lines.append(f"  {'span':<28} {'t+ms':>9} {'dur_ms':>9}")
        for r in rows:
            off = (r["t0"] - t_base) * 1e3
            if r["kind"] == "span":
                lines.append(
                    f"  {r['name']:<28} {off:>9.2f} "
                    f"{r.get('dur', 0.0) * 1e3:>9.2f}"
                )
            else:
                extra = ""
                if r.get("fields"):
                    extra = " " + ",".join(
                        f"{k}={v}" for k, v in sorted(r["fields"].items())
                    )
                lines.append(f"  ! {r['name']:<26} {off:>9.2f}{extra}")
    return "\n".join(lines)


def side_by_side_timeline(
    named_records: dict[str, list[dict]], n_heights: int = 16
) -> str:
    """Multi-node rendering: per height, one row per span name with one
    duration column per node — a slow step on ONE validator stands out
    against the same step's duration on its peers. Events render as a
    per-node annotation count. `named_records` maps a display name (file
    stem, moniker) to that node's record-dict list."""
    nodes = list(named_records)
    flights = {
        n: flight_snapshot(
            [SpanRecord.from_json(r) for r in named_records[n]], n_heights
        )
        for n in nodes
    }
    heights = sorted(set().union(*(set(f) for f in flights.values())))[
        -n_heights:
    ]
    if not heights:
        return "(no trace records)"
    w = max(9, max(len(n) for n in nodes) + 1)
    lines = []
    for h in heights:
        lines.append(f"height {h}")
        lines.append(
            f"  {'span (dur_ms)':<28} "
            + " ".join(f"{n:>{w}}" for n in nodes)
        )
        # span rows: union of names, ordered by first appearance time
        order: dict[str, float] = {}
        durs: dict[str, dict[str, float]] = {}
        events: dict[str, int] = {n: 0 for n in nodes}
        for n in nodes:
            for r in flights[n].get(h, []):
                if r["kind"] != "span":
                    events[n] += 1
                    continue
                order.setdefault(r["name"], r["t0"])
                # a repeated span name (round retries) sums its durations
                durs.setdefault(r["name"], {}).setdefault(n, 0.0)
                durs[r["name"]][n] += r.get("dur", 0.0)
        for name in sorted(order, key=order.get):
            cells = [
                (
                    f"{durs[name][n] * 1e3:>{w}.2f}"
                    if n in durs.get(name, {})
                    else f"{'-':>{w}}"
                )
                for n in nodes
            ]
            lines.append(f"  {name:<28} " + " ".join(cells))
        if any(events.values()):
            cells = [f"{events[n]:>{w}}" for n in nodes]
            lines.append(f"  {'! annotations':<28} " + " ".join(cells))
    return "\n".join(lines)


def attribution_table(records: list[dict]) -> str:
    """The attribution dict rendered as an aligned text table."""
    att = attribution(records)
    lines = [
        f"latency attribution over {att['heights']} heights",
        f"  {'span':<28} {'count':>6} {'p50_ms':>9} {'p95_ms':>9} "
        f"{'max_ms':>9}",
    ]
    for name, s in att["steps"].items():
        lines.append(
            f"  {name:<28} {s['count']:>6} {s['p50_ms']:>9.2f} "
            f"{s['p95_ms']:>9.2f} {s['max_ms']:>9.2f}"
        )
    return "\n".join(lines)
