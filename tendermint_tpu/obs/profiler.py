"""On-demand profiling hooks — device trace + sampled event-loop
profile, armed over RPC (`profile_start`/`profile_stop`).

The first live TPU tunnel session (ROADMAP item 1) must be minable
without a redeploy: when real-silicon anomalies show up mid-capture,
the operator starts a bounded profile against the RUNNING node, pulls
the artifacts from `data/profiles/`, and keeps serving. Two captures
per session:

- **device trace**: `jax.profiler.start_trace(dir)` when the jax
  profiler is importable and startable — guarded, CPU-backend tolerant
  (the CPU backend records a host-side XPlane trace; a missing/broken
  profiler degrades to a structured `{"enabled": false, "error": ...}`
  in the session record, never an exception out of the RPC);
- **sampled event-loop profile**: a daemon thread samples the event
  loop thread's stack (`sys._current_frames()`) on a fixed interval
  and aggregates identical stacks — the PR 9/11 finding is that the
  event LOOP, not the device, is the binding resource past ~32
  validators, and `tm_event_loop_lag_seconds` says THAT it's slow
  while this says WHERE. Written as JSON (stack -> sample count,
  hottest first) at stop.

One session at a time (a second start is a caller error, surfaced as a
structured RPC error by rpc/core). Stdlib except the guarded jax
import; no clock reads outside the session driver itself — session
ids come from a monotonic counter, not wall time.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import Counter
from typing import Optional


class ProfilerUnavailable(RuntimeError):
    """The requested capture cannot run (already active / not active /
    device profiler required but missing). rpc/core maps this to a
    structured JSON-RPC error."""


class _StackSampler(threading.Thread):
    """Samples one thread's Python stack on a fixed interval."""

    def __init__(self, target_thread_id: int, interval_s: float):
        super().__init__(name="obs/profile-sampler", daemon=True)
        self.target_thread_id = target_thread_id
        self.interval_s = interval_s
        self.samples = 0
        self.stacks: Counter = Counter()
        # NOT named _stop: Thread._stop is a real (private) CPython
        # method that join() calls — shadowing it with an Event breaks
        # every join with "'Event' object is not callable"
        self._halt = threading.Event()

    def run(self) -> None:
        while not self._halt.wait(self.interval_s):
            frame = sys._current_frames().get(self.target_thread_id)
            if frame is None:
                continue
            stack = []
            depth = 0
            while frame is not None and depth < 64:
                code = frame.f_code
                stack.append(
                    f"{os.path.basename(code.co_filename)}:"
                    f"{frame.f_lineno}:{code.co_name}"
                )
                frame = frame.f_back
                depth += 1
            # innermost-first; key on the tuple so identical stacks fold
            self.stacks[tuple(stack)] += 1
            self.samples += 1

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=2.0)


class ProfileCapture:
    """One-at-a-time profiling sessions writing into `out_dir`
    (data/profiles under the node home). `start()` returns the session
    record; `stop()` finalizes it with artifact paths + the loop
    profile's top stacks."""

    def __init__(
        self,
        out_dir: str,
        sample_interval_s: float = 0.01,
        logger=None,
    ):
        self.out_dir = out_dir
        self.sample_interval_s = sample_interval_s
        self.logger = logger
        self._lock = threading.Lock()
        self._session: Optional[dict] = None
        self._sampler: Optional[_StackSampler] = None
        self._device_tracing = False
        self._next_id = 1

    @property
    def active(self) -> bool:
        return self._session is not None

    # --- session lifecycle -----------------------------------------------

    def start(self, label: str = "", device: bool = True) -> dict:
        """Arm a session. `device=False` skips the jax trace (loop
        profile only). Raises ProfilerUnavailable when a session is
        already running."""
        with self._lock:
            if self._session is not None:
                raise ProfilerUnavailable(
                    f"profile session {self._session['id']!r} already "
                    "running; call profile_stop first"
                )
            sid = f"profile_{self._next_id:04d}"
            self._next_id += 1
            session_dir = os.path.join(self.out_dir, sid)
            os.makedirs(session_dir, exist_ok=True)
            device_state = {"enabled": False}
            if device:
                device_state = self._start_device_trace(session_dir)
            sampler = _StackSampler(
                threading.get_ident(), self.sample_interval_s
            )
            sampler.start()
            self._sampler = sampler
            self._session = {
                "id": sid,
                "label": label,
                "dir": session_dir,
                "t_start": time.monotonic(),
                "device_trace": device_state,
                "loop_sample_interval_s": self.sample_interval_s,
            }
            out = dict(self._session)
            out.pop("t_start")
            return out

    def stop(self) -> dict:
        """Disarm; returns the finalized session record with artifact
        paths. Raises ProfilerUnavailable when nothing is running."""
        with self._lock:
            session = self._session
            if session is None:
                raise ProfilerUnavailable(
                    "no profile session running; call profile_start first"
                )
            self._session = None
            sampler, self._sampler = self._sampler, None
        session["duration_s"] = round(
            time.monotonic() - session.pop("t_start"), 3
        )
        if self._device_tracing:
            session["device_trace"] = dict(
                session["device_trace"], **self._stop_device_trace()
            )
        if sampler is not None:
            sampler.stop()
            session["loop_profile"] = self._write_loop_profile(
                session["dir"], sampler
            )
        return session

    # --- device trace (guarded jax) ---------------------------------------

    def _start_device_trace(self, session_dir: str) -> dict:
        try:
            import jax

            jax.profiler.start_trace(session_dir)
        except Exception as e:  # missing jax, no backend, double-trace
            if self.logger is not None:
                self.logger.error(
                    "device trace unavailable", err=repr(e)
                )
            return {"enabled": False, "error": repr(e)[:400]}
        self._device_tracing = True
        return {"enabled": True, "dir": session_dir}

    def _stop_device_trace(self) -> dict:
        self._device_tracing = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:
            if self.logger is not None:
                self.logger.error(
                    "device trace stop failed", err=repr(e)
                )
            return {"stop_error": repr(e)[:400]}
        return {}

    # --- loop profile -----------------------------------------------------

    @staticmethod
    def _write_loop_profile(session_dir: str, sampler: _StackSampler) -> dict:
        top = [
            {"count": count, "stack": list(stack)}
            for stack, count in sampler.stacks.most_common(64)
        ]
        doc = {
            "samples": sampler.samples,
            "interval_s": sampler.interval_s,
            "stacks": top,
        }
        path = os.path.join(session_dir, "loop_profile.json")
        try:
            with open(path, "w") as f:
                json.dump(doc, f, indent=1)
        except OSError:
            path = ""
        return {
            "samples": sampler.samples,
            "path": path,
            "top_stacks": top[:8],
        }
