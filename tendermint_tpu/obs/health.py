"""Live health plane — streaming SLO monitors over the recording seams.

PR 2/5/7 built the *recording* stack (flight ring, cluster tracing,
quorum/wall attribution) but nothing watches those streams while the
node runs: the `health` RPC was a stub returning `{}`, and "which plane
degraded" stayed an archaeology question over dump files. This module
closes the loop in-process:

- **Detectors** turn the existing metric/trace seams into boolean
  good/bad event streams: consensus round churn and stalled rounds
  (commit cadence vs the static-timeout ceiling), quorum-lag anomalies
  (the PR 5 arrival-lag sensor vs a good-sample baseline tail),
  scheduler saturation (queue depth vs dispatch progress), WAL fsync
  latency drift, the sequencer receipt->applied SLO (PR 10's 96 ms p95
  as the default target), the lightserve cache hit-rate floor, peer
  flap, an event-loop lag probe (a monotonic heartbeat task — the
  PR 9 finding that live nets go event-loop-bound above ~32 validators,
  measured instead of inferred), and the dark_time conservation
  watchdog (per-height wall time with NO instrumented owner, from
  obs.report.wall_conservation over the bound flight ring).

- **Burn-rate SLOs** (the SRE multiwindow pattern) roll each detector's
  event stream into ok/warn/critical: burn = bad_fraction /
  error_budget over a short and a long window; warn/critical require
  BOTH windows above threshold, so a single bad sample can't page and a
  recovered incident un-pages as the short window drains.

- **Incidents**: every verdict transition lands a `health.incident`
  event in the tracer ring — a flight dump now carries *why* (detector,
  threshold, observed value) next to *what* (the step timeline) — and
  increments `tm_health_incidents_total`.

- **Gauges**: `tm_health_status{subsystem=}` (0/1/2) and
  `tm_slo_burn_rate{slo=}` export the rolled-up state for scraping.

Determinism: every feed and every verdict takes an explicit event-time
`t`; nothing in the detector/SLO math reads a clock. The async runtime
(`HealthMonitor.start`) is a thin driver that samples the bound seams
on an interval and stamps `time.monotonic()` — unit tests feed
synthetic streams with synthetic clocks and get identical state.
Stdlib only, like the rest of `obs/`.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Callable, Optional

from .quantile import StreamingQuantile
from .tracer import default_tracer

# verdict levels (gauge values of tm_health_status)
OK, WARN, CRITICAL = 0, 1, 2
VERDICT_NAMES = {OK: "ok", WARN: "warn", CRITICAL: "critical"}

# incident event name in the tracer ring (rides dump_traces unchanged)
INCIDENT_EVENT = "health.incident"


class BurnRateSLO:
    """Multi-window error-budget burn over a timestamped event stream.

    `objective` is the target good fraction (0.99 -> 1% error budget);
    `burn(t, w)` = bad_fraction_in_window / (1 - objective), so 1.0
    means the budget is being consumed exactly at its sustainable rate.
    The verdict requires BOTH the short and the long window to burn
    past the threshold: the long window carries severity, the short
    window confirms the problem is still live (the standard multiwindow
    multi-burn-rate alerting shape)."""

    __slots__ = (
        "name",
        "objective",
        "short_window",
        "long_window",
        "warn_burn",
        "crit_burn",
        "min_events",
        "_events",
        "_bad",
        "_total",
        "_short",
        "_sbad",
        "_stotal",
    )

    def __init__(
        self,
        name: str,
        objective: float = 0.99,
        short_window: float = 30.0,
        long_window: float = 300.0,
        warn_burn: float = 1.0,
        crit_burn: float = 6.0,
        min_events: int = 4,
    ):
        if not (0.0 < objective < 1.0):
            raise ValueError("slo objective must be in (0, 1)")
        if short_window <= 0 or long_window < short_window:
            raise ValueError("slo windows must satisfy 0 < short <= long")
        self.name = name
        self.objective = objective
        self.short_window = short_window
        self.long_window = long_window
        self.warn_burn = warn_burn
        self.crit_burn = crit_burn
        self.min_events = max(1, min_events)
        # (t, bad_count, total_count), pruned past the long window;
        # rolling (bad, total) sums per window keep burn()/verdict()
        # O(1) — these run synchronously in the consensus commit path,
        # and at committee scale the long deque holds tens of
        # thousands of per-vote entries a rescan per commit can't
        # afford (the event loop is the scarce resource per PR 9)
        self._events: deque[tuple[float, int, int]] = deque()
        self._bad = 0
        self._total = 0
        self._short: deque[tuple[float, int, int]] = deque()
        self._sbad = 0
        self._stotal = 0

    def observe(self, t: float, bad: int, total: int = 1) -> None:
        """Record `bad` failures out of `total` events at time t."""
        if total <= 0:
            return
        b, n = max(0, int(bad)), int(total)
        self._events.append((t, b, n))
        self._bad += b
        self._total += n
        self._short.append((t, b, n))
        self._sbad += b
        self._stotal += n
        self._prune(t)

    def _prune(self, t: float) -> None:
        horizon = t - self.long_window
        ev = self._events
        while ev and ev[0][0] < horizon:
            _, b, n = ev.popleft()
            self._bad -= b
            self._total -= n
        horizon = t - self.short_window
        ev = self._short
        while ev and ev[0][0] < horizon:
            _, b, n = ev.popleft()
            self._sbad -= b
            self._stotal -= n

    def _window(self, t: float, window: float) -> tuple[int, int]:
        self._prune(t)
        if window >= self.long_window:
            return self._bad, self._total
        if window == self.short_window:
            return self._sbad, self._stotal
        lo = t - window
        bad = total = 0
        for ts, b, n in self._events:
            if ts >= lo:
                bad += b
                total += n
        return bad, total

    def burn(self, t: float, window: Optional[float] = None) -> float:
        """Error-budget burn rate over the window (long by default)."""
        bad, total = self._window(t, window or self.long_window)
        if total == 0:
            return 0.0
        budget = 1.0 - self.objective
        return (bad / total) / budget

    def verdict(self, t: float) -> int:
        self._prune(t)
        _, long_total = self._window(t, self.long_window)
        if long_total < self.min_events:
            return OK
        long_burn = self.burn(t, self.long_window)
        short_burn = self.burn(t, self.short_window)
        if long_burn >= self.crit_burn and short_burn >= self.crit_burn:
            return CRITICAL
        if long_burn >= self.warn_burn and short_burn >= self.warn_burn:
            return WARN
        return OK

    def snapshot(self, t: float) -> dict:
        bad, total = self._window(t, self.long_window)
        return {
            "objective": self.objective,
            "events": total,
            "bad": bad,
            "burn_long": round(self.burn(t, self.long_window), 3),
            "burn_short": round(self.burn(t, self.short_window), 3),
        }


class Detector:
    """One named failure mode of one subsystem. Subclasses feed their
    SLO from seam-specific samples; `verdict(t)` combines the SLO state
    with any direct condition (`_direct(t)`, e.g. a hard stall)."""

    subsystem = "node"
    name = "detector"

    def __init__(self, slo: BurnRateSLO):
        self.slo = slo
        # last observed value + the threshold it was judged against,
        # for incident payloads; last_bad is the most recent OFFENDING
        # observation — an escalating incident must carry the value
        # that tripped it, not whatever good sample arrived after
        self.last_value: float = 0.0
        self.last_bad: float = 0.0
        self.last_threshold: float = 0.0

    def _direct(self, t: float) -> int:
        """Directly-observable verdict floor (no burn math); OK default."""
        return OK

    def verdict(self, t: float) -> int:
        return max(self._direct(t), self.slo.verdict(t))

    def snapshot(self, t: float) -> dict:
        out = self.slo.snapshot(t)
        out["value"] = round(self.last_value, 6)
        out["last_bad"] = round(self.last_bad, 6)
        out["threshold"] = round(self.last_threshold, 6)
        return out

    def _observe(self, t: float, value: float, bad: bool) -> None:
        """Book one judged sample: SLO event + value/last_bad fields."""
        self.last_value = value
        if bad:
            self.last_bad = value
        self.slo.observe(t, bad=1 if bad else 0)


class RoundChurnDetector(Detector):
    """Consensus heights that needed rounds > 0. A healthy committee
    commits at round 0; churn means timeouts fired or the proposer was
    partitioned — exactly the PR 7 back-off signal, now rolled into a
    verdict instead of a controller nudge."""

    subsystem = "consensus"
    name = "round_churn"

    def observe_height(self, t: float, round_: int) -> None:
        self._observe(t, float(round_), bad=round_ > 0)


class StalledRoundDetector(Detector):
    """No height committed within the ceiling — the one condition that
    must page directly (a burn window over zero events never fires).
    `ceiling_s` defaults to stall_factor x the static round-0 schedule
    (propose + prevote + precommit + commit waits): the adaptive
    controllers only ever tighten BELOW that, so a net that blows past
    it is stalled regardless of pacing state. Also feeds the SLO with
    per-height commit intervals judged against near_stall_fraction x
    the ceiling — a lower bar than the page, so repeated near-stalls
    warn BEFORE the hard stall pages (at the ceiling itself the direct
    check is already critical and the SLO tier would be redundant)."""

    subsystem = "consensus"
    name = "stalled_round"

    def __init__(
        self,
        slo: BurnRateSLO,
        ceiling_s: float,
        near_stall_fraction: float = 0.5,
    ):
        super().__init__(slo)
        self.ceiling_s = ceiling_s
        self.near_stall_fraction = near_stall_fraction
        self.last_threshold = ceiling_s
        self._last_commit_t: Optional[float] = None

    def arm(self, t: float) -> None:
        """Start the stall clock (monitor start / consensus start)."""
        if self._last_commit_t is None:
            self._last_commit_t = t

    def observe_height(self, t: float) -> None:
        if self._last_commit_t is not None:
            interval = t - self._last_commit_t
            near = self.ceiling_s * self.near_stall_fraction
            self._observe(t, interval, bad=interval > near)
        self._last_commit_t = t

    def _direct(self, t: float) -> int:
        if self._last_commit_t is None:
            return OK
        elapsed = t - self._last_commit_t
        if elapsed > self.ceiling_s:
            self.last_value = elapsed
            self.last_bad = elapsed
            return CRITICAL
        return OK


class QuorumLagDetector(Detector):
    """Arrival-lag anomaly: each accepted vote's lag behind the round's
    first vote (the PR 5 sensor, fed synchronously from HeightVoteSet)
    is judged against a learned good-sample tail. Two asymmetries keep
    the baseline honest:

    - the first `min_baseline` samples are LEARNING-ONLY (admitted,
      never judged): an anomaly call needs a baseline first, and the
      in-proc gossip plane's genuine clean tail is ~100 ms p95
      (tick-paced vote trickle, measured on the 4-validator harness) —
      judging against the static floor during warmup false-flags half
      the clean stream;
    - after warmup the baseline only ingests samples BELOW the current
      threshold — a persistent straggler keeps flagging instead of
      teaching the detector that its lag is normal (the pacing
      controller intentionally learns that tail; the health plane's
      job is to say it changed)."""

    subsystem = "consensus"
    name = "quorum_lag"

    def __init__(
        self,
        slo: BurnRateSLO,
        floor_s: float = 0.025,
        margin: float = 2.0,
        baseline_window: int = 512,
        min_baseline: int = 32,
    ):
        super().__init__(slo)
        self.floor_s = floor_s
        self.margin = margin
        self.min_baseline = min_baseline
        self._baseline = StreamingQuantile(window=baseline_window)

    def threshold(self) -> float:
        if len(self._baseline) < self.min_baseline:
            return self.floor_s
        return max(self.floor_s, self.margin * self._baseline.quantile(0.95))

    def observe_lag(self, t: float, lag_s: float) -> None:
        if len(self._baseline) < self.min_baseline:
            # warmup: learn the committee's clean arrival spread before
            # judging anything against it
            self._baseline.add(lag_s)
            self.last_value = lag_s
            return
        thr = self.threshold()
        self.last_threshold = thr
        bad = lag_s > thr
        self._observe(t, lag_s, bad=bad)
        if not bad:
            self._baseline.add(lag_s)

    def snapshot(self, t: float) -> dict:
        out = super().snapshot(t)
        out["baseline_p95"] = round(self._baseline.quantile(0.95), 6)
        return out


class SchedulerSaturationDetector(Detector):
    """Verify-scheduler saturation: a queue that stays deep across
    samples while dispatch rounds keep filling their buckets means the
    device can't drain the offered load (the r04-class symptom from the
    inside). One sample per monitor tick: bad when depth >= the
    saturation floor AND the interval made no dispatch progress or the
    last dispatch was essentially full."""

    subsystem = "scheduler"
    name = "scheduler_saturation"

    def __init__(
        self,
        slo: BurnRateSLO,
        depth_floor: int = 256,
        fill_floor: float = 0.95,
    ):
        super().__init__(slo)
        self.depth_floor = depth_floor
        self.fill_floor = fill_floor
        self.last_threshold = float(depth_floor)

    def observe_sample(
        self,
        t: float,
        queue_depth: float,
        fill_ratio: float,
        dispatches_delta: int,
    ) -> None:
        saturated = queue_depth >= self.depth_floor and (
            dispatches_delta == 0 or fill_ratio >= self.fill_floor
        )
        self._observe(t, queue_depth, bad=saturated)


class FillEfficiencyDetector(Detector):
    """Dispatch fill-efficiency floor over the device-cost ledger
    (obs/ledger.py): each monitor tick with meaningful dispatch volume
    computes interval fill = rows-requested / rows-dispatched; fill
    under the floor is a bad event. A scheduler sustaining 10%-full
    buckets is paying the device for padding — a ladder/mesh_min_rows/
    max_batch misconfiguration the health plane should page on, not a
    bench-archaeology finding. `min_rows` gates the judgement: a small
    committee's vote rounds (a handful of rows padded to the 8-bucket)
    are a latency choice, not waste worth paging over."""

    subsystem = "scheduler"
    name = "fill_efficiency"

    def __init__(
        self,
        slo: BurnRateSLO,
        floor: float = 0.1,
        min_rows: int = 256,
    ):
        super().__init__(slo)
        self.floor = floor
        self.min_rows = min_rows
        self.last_threshold = floor

    def observe_interval(
        self, t: float, rows_requested: float, rows_dispatched: float
    ) -> None:
        if rows_dispatched < self.min_rows:
            return  # idle / small-round interval: nothing to judge
        fill = rows_requested / rows_dispatched
        self._observe(t, fill, bad=fill < self.floor)


class LatencyDriftDetector(Detector):
    """Latency drift against a learned good baseline (WAL fsync is the
    canonical instance: a degrading disk shows up as the interval-mean
    fsync latency drifting off its long-run median). Fed interval
    means derived from histogram deltas; bad when the mean exceeds
    drift_factor x the baseline median AND an absolute floor (noise on
    an idle WAL can't flag)."""

    subsystem = "wal"
    name = "wal_fsync_drift"

    def __init__(
        self,
        slo: BurnRateSLO,
        drift_factor: float = 4.0,
        abs_floor_s: float = 0.001,
        baseline_window: int = 256,
        min_baseline: int = 8,
    ):
        super().__init__(slo)
        self.drift_factor = drift_factor
        self.abs_floor_s = abs_floor_s
        self.min_baseline = min_baseline
        self._baseline = StreamingQuantile(window=baseline_window)

    def threshold(self) -> float:
        if len(self._baseline) < self.min_baseline:
            return float("inf")
        return max(
            self.abs_floor_s,
            self.drift_factor * self._baseline.quantile(0.5),
        )

    def observe_mean(self, t: float, mean_s: float) -> None:
        thr = self.threshold()
        self.last_threshold = thr if thr != float("inf") else 0.0
        bad = mean_s > thr
        self._observe(t, mean_s, bad=bad)
        if not bad:
            self._baseline.add(mean_s)


class IpcRoundTripDetector(Detector):
    """Verify-service IPC health ([scheduler] remote_socket nodes): the
    RemoteVerifyScheduler's cumulative submit->verdict accounting
    (`ipc_stats()`) is pulled per tick and judged two ways:

    - the interval-mean round trip drifts off a learned good-sample
      median (same asymmetry as the WAL fsync detector: bad intervals
      never teach the baseline) — a wedged-but-open service, a
      saturated device plane, or a socket path rerouted through a slow
      filesystem all show up here BEFORE heights visibly inflate;
    - every local-degrade fallback in the interval is a bad event
      outright: the client never hangs and never drops a verdict, so
      degrades are invisible to liveness — burn-rate on them is how a
      dying service pages instead of silently billing every verify to
      the local CPU."""

    subsystem = "scheduler"
    name = "ipc_round_trip"

    def __init__(
        self,
        slo: BurnRateSLO,
        drift_factor: float = 4.0,
        abs_floor_s: float = 0.002,
        baseline_window: int = 256,
        min_baseline: int = 8,
    ):
        super().__init__(slo)
        self.drift_factor = drift_factor
        self.abs_floor_s = abs_floor_s
        self.min_baseline = min_baseline
        self._baseline = StreamingQuantile(window=baseline_window)

    def threshold(self) -> float:
        if len(self._baseline) < self.min_baseline:
            return float("inf")
        return max(
            self.abs_floor_s,
            self.drift_factor * self._baseline.quantile(0.5),
        )

    def observe_interval(
        self,
        t: float,
        mean_rtt_s: Optional[float] = None,
        degrades: int = 0,
    ) -> None:
        if degrades > 0:
            # degraded submissions carry no RTT — each is its own bad
            # event (last_bad stays the offending RTT if one was seen)
            self.slo.observe(t, bad=degrades, total=degrades)
        if mean_rtt_s is None:
            return
        thr = self.threshold()
        self.last_threshold = thr if thr != float("inf") else 0.0
        bad = mean_rtt_s > thr
        self._observe(t, mean_rtt_s, bad=bad)
        if not bad:
            self._baseline.add(mean_rtt_s)


class LatencySLODetector(Detector):
    """Fixed-target latency SLO over histogram-delta observations: the
    sequencer receipt->applied plane targets PR 10's measured 96 ms p95
    (objective 0.95 with target_s 0.1 == "95% of applies inside
    100 ms"). `target_s` snaps to the histogram's nearest bucket
    boundary >= the configured target, since bucket counts are the
    only resolution a pull seam has."""

    subsystem = "sequencer"
    name = "sequencer_apply_slo"

    def __init__(self, slo: BurnRateSLO, target_s: float = 0.1):
        super().__init__(slo)
        self.target_s = target_s
        self.last_threshold = target_s

    def observe_counts(self, t: float, bad: int, total: int) -> None:
        if total <= 0:
            return
        self.last_value = bad / total
        if bad:
            self.last_bad = self.last_value
        self.slo.observe(t, bad=bad, total=total)


class HitRateFloorDetector(Detector):
    """Cache hit-rate floor (lightserve proof cache: PR 8 measured
    0.998 at 1000 clients; sustained misses mean the durable pin is
    regressing heights or clients outrun the chain). Fed hit/miss
    COUNT DELTAS per sample; the SLO objective IS the floor."""

    subsystem = "lightserve"
    name = "lightserve_hit_rate"

    def __init__(self, slo: BurnRateSLO):
        super().__init__(slo)
        # the objective IS the floor — incidents must carry the bar
        self.last_threshold = slo.objective

    def observe_counts(self, t: float, hits: int, misses: int) -> None:
        total = hits + misses
        if total <= 0:
            return
        self.last_value = hits / total
        if misses:
            self.last_bad = self.last_value
        self.slo.observe(t, bad=misses, total=total)


class PeerFlapDetector(Detector):
    """Peer-count churn: each monitor tick where the connected-peer
    count DROPPED is a bad event. Steady shrinkage or connect/drop
    cycling both show up; a stable (even small) peer set stays ok."""

    subsystem = "p2p"
    name = "peer_flap"

    def __init__(self, slo: BurnRateSLO):
        super().__init__(slo)
        self._last_count: Optional[int] = None

    def observe_count(self, t: float, count: int) -> None:
        prev = self._last_count
        self._last_count = count
        if prev is None:
            self.last_value = float(count)
            return
        # the bar a drop violated is the peer count it dropped FROM;
        # like last_bad, it must survive recovery ticks so a later
        # incident carries the offending pair
        if count < prev:
            self.last_threshold = float(prev)
        self._observe(t, float(count), bad=count < prev)


class DarkTimeDetector(Detector):
    """Wall-clock conservation watchdog: every committed height's wall
    time decomposes into named buckets (obs.report.wall_conservation —
    step compute, gossip wait, timeout floor, verify IPC/queue/device,
    WAL fsync, commit pipeline) with the residue booked as `dark_time`.
    A height whose dark fraction exceeds the floor is a bad event: some
    slice of latency has NO instrumented owner — a new blocking seam, a
    starved event loop between step transitions, a span that stopped
    being recorded. The whole point of the conservation invariant is
    that such time can no longer hide; this detector is the part that
    pages about it. Fed per-height from the bound tracer's ring on the
    monitor tick (skipping heights already judged)."""

    subsystem = "consensus"
    name = "dark_time"

    def __init__(self, slo: BurnRateSLO, floor: float = 0.05):
        super().__init__(slo)
        self.floor = floor
        self.last_threshold = floor

    def observe_height(self, t: float, dark_fraction: float) -> None:
        self._observe(t, dark_fraction, bad=dark_fraction > self.floor)


class EventLoopLagDetector(Detector):
    """Event-loop scheduling lag: the heartbeat task measures how late
    the loop runs a due callback. PR 9 showed live nets above ~32
    validators saturate the loop long before the CPU — this makes that
    regime a verdict (warn at sustained lag over the threshold) rather
    than an inference from wall-clock anomalies."""

    subsystem = "runtime"
    name = "event_loop_lag"

    def __init__(self, slo: BurnRateSLO, lag_warn_s: float = 0.05):
        super().__init__(slo)
        self.lag_warn_s = lag_warn_s
        self.last_threshold = lag_warn_s

    def observe_lag(self, t: float, lag_s: float) -> None:
        self._observe(t, lag_s, bad=lag_s > self.lag_warn_s)


class HealthMonitor:
    """The node's live health plane: owns the detectors, samples the
    bound pull seams on a tick, receives the consensus push seams
    (HeightVoteSet/state machine feed it like they feed the pacing
    controller), rolls verdicts up per subsystem, and emits incidents
    into the tracer ring + the tm_health_* gauges.

    Wiring: node assembly constructs one from `[health]` config and
    binds seams (`bind_*`); the in-proc harnesses construct one
    directly and drive `sample(t)` by hand. All feeds accept an
    explicit `t`; when omitted the monitor stamps `self.clock()`
    (time.monotonic)."""

    def __init__(
        self,
        interval: float = 1.0,
        heartbeat_interval: float = 0.25,
        short_window: float = 30.0,
        long_window: float = 300.0,
        stall_ceiling_s: float = 60.0,
        quorum_lag_floor_s: float = 0.025,
        quorum_lag_margin: float = 2.0,
        scheduler_depth_floor: int = 256,
        fill_floor: float = 0.1,
        fill_min_rows: int = 256,
        fsync_drift_factor: float = 4.0,
        ipc_drift_factor: float = 4.0,
        sequencer_apply_target_s: float = 0.1,
        cache_hit_floor: float = 0.9,
        loop_lag_warn_s: float = 0.05,
        dark_time_floor: float = 0.05,
        tracer=None,
        metrics=None,
        process_metrics=None,
        logger=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.tracer = default_tracer() if tracer is None else tracer
        self.metrics = metrics  # libs.metrics.HealthMetrics or None
        self.process_metrics = process_metrics  # ProcessMetrics or None
        self.logger = logger
        self.clock = clock
        self.interval = interval
        self.heartbeat_interval = heartbeat_interval

        def slo(name, objective, **kw):
            kw.setdefault("short_window", short_window)
            kw.setdefault("long_window", long_window)
            return BurnRateSLO(name, objective=objective, **kw)

        self.round_churn = RoundChurnDetector(
            # 1 churned height in 10 burns the budget at exactly 1x
            slo("round_churn", objective=0.9)
        )
        self.stalled_round = StalledRoundDetector(
            slo("stalled_round", objective=0.9), ceiling_s=stall_ceiling_s
        )
        self.quorum_lag = QuorumLagDetector(
            # the signal is SPARSE: a straggling validator's lag is
            # phase-absorbed on the vote types where the whole
            # committee waited on it (everyone's precommit shifts
            # together when its prevote was the late one), so one
            # straggler of 4 shows up on ~10% of pre-quorum arrivals
            # (measured on the chaos harness) — a 5% budget puts that
            # at ~2x burn -> warn, far under the 6x critical gate,
            # while the clean stream (bounded tick-quantized spread,
            # zero samples past 2x its own p95) burns ~0
            slo("quorum_lag", objective=0.95, min_events=8),
            floor_s=quorum_lag_floor_s,
            margin=quorum_lag_margin,
        )
        self.scheduler_saturation = SchedulerSaturationDetector(
            slo("scheduler_saturation", objective=0.8),
            depth_floor=scheduler_depth_floor,
        )
        self.fill_efficiency = FillEfficiencyDetector(
            slo("fill_efficiency", objective=0.8),
            floor=fill_floor,
            min_rows=fill_min_rows,
        )
        self.wal_fsync_drift = LatencyDriftDetector(
            slo("wal_fsync_drift", objective=0.8),
            drift_factor=fsync_drift_factor,
        )
        self.ipc_round_trip = IpcRoundTripDetector(
            slo("ipc_round_trip", objective=0.8),
            drift_factor=ipc_drift_factor,
        )
        self.sequencer_apply = LatencySLODetector(
            slo("sequencer_apply_slo", objective=0.95, min_events=16),
            target_s=sequencer_apply_target_s,
        )
        self.lightserve_hit_rate = HitRateFloorDetector(
            slo(
                "lightserve_hit_rate",
                objective=cache_hit_floor,
                min_events=32,
            )
        )
        self.peer_flap = PeerFlapDetector(
            slo("peer_flap", objective=0.8)
        )
        self.event_loop_lag = EventLoopLagDetector(
            slo("event_loop_lag", objective=0.9, min_events=8),
            lag_warn_s=loop_lag_warn_s,
        )
        self.dark_time = DarkTimeDetector(
            # 1 unconserved height in 10 burns the budget at exactly 1x
            slo("dark_time", objective=0.9, min_events=4),
            floor=dark_time_floor,
        )
        self.detectors: dict[str, Detector] = {
            d.name: d
            for d in (
                self.round_churn,
                self.stalled_round,
                self.dark_time,
                self.quorum_lag,
                self.scheduler_saturation,
                self.fill_efficiency,
                self.wal_fsync_drift,
                self.ipc_round_trip,
                self.sequencer_apply,
                self.lightserve_hit_rate,
                self.peer_flap,
                self.event_loop_lag,
            )
        }
        self._last_verdicts: dict[str, int] = {
            name: OK for name in self.detectors
        }
        self.incidents: deque[dict] = deque(maxlen=256)
        # pull-seam bindings + last-seen cumulative counts for deltas
        self._scheduler_metrics = None
        self._ledger = None
        self._remote_scheduler = None
        self._wal_hist = None
        self._sequencer_hist = None
        self._lightserve_metrics = None
        self._switch = None
        self._conservation_tracer = None
        self._dark_seen_height = 0
        self._cum: dict[str, float] = {}
        self._tasks: list[asyncio.Task] = []
        self._running = False

    @classmethod
    def from_config(cls, hc, stall_ceiling_s: float, **kw) -> "HealthMonitor":
        """Build from a config.HealthConfig section; `stall_ceiling_s`
        comes from the consensus timeouts (the caller knows the static
        round-0 schedule)."""
        return cls(
            interval=hc.interval,
            heartbeat_interval=hc.heartbeat_interval,
            short_window=hc.short_window,
            long_window=hc.long_window,
            stall_ceiling_s=stall_ceiling_s,
            quorum_lag_floor_s=hc.quorum_lag_floor,
            quorum_lag_margin=hc.quorum_lag_margin,
            scheduler_depth_floor=hc.scheduler_depth_floor,
            fill_floor=hc.fill_floor,
            fill_min_rows=hc.fill_min_rows,
            fsync_drift_factor=hc.fsync_drift_factor,
            ipc_drift_factor=getattr(hc, "ipc_drift_factor", 4.0),
            sequencer_apply_target_s=hc.sequencer_apply_target,
            cache_hit_floor=hc.cache_hit_floor,
            loop_lag_warn_s=hc.loop_lag_warn,
            dark_time_floor=getattr(hc, "dark_time_floor", 0.05),
            **kw,
        )

    # --- push seams (consensus, same shape as the pacing feeds) ----------

    def observe_vote_arrival(
        self, vote_type: int, lag_s: float, t: Optional[float] = None
    ) -> None:
        """Fed synchronously by HeightVoteSet on every accepted
        pre-quorum vote (the PR 5 arrival-lag sensor)."""
        self.quorum_lag.observe_lag(
            self.clock() if t is None else t, lag_s
        )

    def observe_round_advance(
        self, height: int, round_: int, t: Optional[float] = None
    ) -> None:
        # round advances are judged at commit time (observe_height_
        # committed carries the final round); nothing to book here yet,
        # but the hook keeps the seam symmetric with PacingController
        # for harnesses that want to drive churn directly
        del height, round_, t

    def observe_height_committed(
        self, height: int, round_: int, t: Optional[float] = None
    ) -> None:
        now = self.clock() if t is None else t
        self.round_churn.observe_height(now, round_)
        self.stalled_round.observe_height(now)
        self._evaluate(now)

    def observe_loop_lag(
        self, lag_s: float, t: Optional[float] = None
    ) -> None:
        self.event_loop_lag.observe_lag(
            self.clock() if t is None else t, lag_s
        )
        if self.process_metrics is not None:
            self.process_metrics.event_loop_lag.observe(lag_s)

    # --- pull-seam bindings ----------------------------------------------

    def bind_scheduler(self, scheduler_metrics) -> None:
        self._scheduler_metrics = scheduler_metrics

    def bind_ledger(self, ledger) -> None:
        """obs.ledger.DispatchLedger (or anything with totals()): the
        fill-efficiency floor detector reads interval deltas of
        rows-requested/rows-dispatched."""
        self._ledger = ledger

    def bind_remote_scheduler(self, remote) -> None:
        """parallel.verify_service.RemoteVerifyScheduler (or anything
        with `ipc_stats()` returning cumulative rtt_count/rtt_sum_s/
        degrades): the ipc_round_trip detector reads interval deltas —
        mean RTT judged vs a learned baseline, degrades bad outright."""
        self._remote_scheduler = remote

    def bind_wal(self, fsync_histogram) -> None:
        """consensus_metrics.wal_fsync_seconds (or any Histogram)."""
        self._wal_hist = fsync_histogram

    def bind_sequencer(self, apply_latency_histogram) -> None:
        self._sequencer_hist = apply_latency_histogram

    def bind_lightserve(self, lightserve_metrics) -> None:
        self._lightserve_metrics = lightserve_metrics

    def bind_switch(self, switch) -> None:
        self._switch = switch

    def bind_tracer(self, tracer) -> None:
        """obs.tracer.Tracer (the node's flight ring): each tick the
        dark_time detector runs the wall-conservation audit
        (obs.report.wall_conservation) over recent records and judges
        every COMPLETED height not yet seen — the in-progress height's
        window is still growing, so it is never judged early. No-ops
        while the tracer is disabled (no records, nothing to conserve)."""
        self._conservation_tracer = tracer

    # --- sampling ---------------------------------------------------------

    def _delta(self, key: str, cum: float) -> Optional[float]:
        """Interval delta of a cumulative counter; None on the FIRST
        sample (no baseline yet — callers must skip the observation, a
        fabricated 0.0 reads as "no progress" and false-flags, e.g. a
        legitimately busy scheduler queue on the first tick)."""
        prev = self._cum.get(key)
        self._cum[key] = cum
        if prev is None:
            return None
        return max(0.0, cum - prev)

    @staticmethod
    def _hist_above(series: dict, threshold: float) -> tuple[int, int]:
        """(count_above_threshold, total) from one Histogram.series()
        snapshot, using the nearest bucket boundary >= threshold."""
        total = series["count"]
        below = 0
        for b, c in zip(series["buckets"], series["counts"]):
            if b >= threshold:
                below = c  # cumulative count <= b
                break
        else:
            below = total
        return max(0, total - below), total

    def sample(self, t: Optional[float] = None) -> None:
        """One pull pass over every bound seam, then re-evaluate. Each
        seam pull is guarded independently: one bad seam (a bound
        metrics object changing shape) must not starve the seams bound
        after it — or the end-of-tick evaluation — while the RPC keeps
        saying monitored:true."""
        now = self.clock() if t is None else t
        for seam, pull in (
            ("scheduler", self._pull_scheduler),
            ("ledger", self._pull_ledger),
            ("remote_scheduler", self._pull_remote_scheduler),
            ("wal", self._pull_wal),
            ("sequencer", self._pull_sequencer),
            ("lightserve", self._pull_lightserve),
            ("p2p", self._pull_switch),
            ("conservation", self._pull_conservation),
        ):
            try:
                pull(now)
            except Exception as e:
                if self.logger is not None:
                    self.logger.error(
                        "health seam pull failed", seam=seam, err=str(e)
                    )
        self._evaluate(now)

    def _pull_scheduler(self, now: float) -> None:
        sm = self._scheduler_metrics
        if sm is None:
            return
        depth = sm.queue_depth.total()
        fill = sm.batch_fill_ratio.value()
        ddisp = self._delta("sched_dispatches", sm.dispatches.value())
        if ddisp is not None:
            self.scheduler_saturation.observe_sample(
                now, depth, fill, int(ddisp)
            )

    def _pull_ledger(self, now: float) -> None:
        led = self._ledger
        if led is None:
            return
        totals = led.totals()
        dreq = self._delta("ledger_req", totals["rows_requested"])
        ddisp = self._delta("ledger_disp", totals["rows_dispatched"])
        if dreq is not None and ddisp is not None and ddisp > 0:
            self.fill_efficiency.observe_interval(now, dreq, ddisp)

    def _pull_remote_scheduler(self, now: float) -> None:
        remote = self._remote_scheduler
        if remote is None:
            return
        stats = remote.ipc_stats()
        dcount = self._delta("ipc_rtt_count", stats["rtt_count"])
        dsum = self._delta("ipc_rtt_sum", stats["rtt_sum_s"])
        ddeg = self._delta("ipc_degrades", stats["degrades"])
        if dcount is None or dsum is None or ddeg is None:
            return  # first sample: baseline only
        self.ipc_round_trip.observe_interval(
            now,
            mean_rtt_s=(dsum / dcount) if dcount > 0 else None,
            degrades=int(ddeg),
        )

    def _pull_wal(self, now: float) -> None:
        if self._wal_hist is None:
            return
        s = self._wal_hist.series()
        dcount = self._delta("wal_count", s["count"])
        dsum = self._delta("wal_sum", s["sum"])
        if dcount is not None and dsum is not None and dcount > 0:
            self.wal_fsync_drift.observe_mean(now, dsum / dcount)

    def _pull_sequencer(self, now: float) -> None:
        if self._sequencer_hist is None:
            return
        s = self._sequencer_hist.series()
        bad, total = self._hist_above(s, self.sequencer_apply.target_s)
        dbad = self._delta("seq_bad", bad)
        dtotal = self._delta("seq_total", total)
        if dbad is not None and dtotal is not None and dtotal > 0:
            self.sequencer_apply.observe_counts(
                now, int(dbad), int(dtotal)
            )

    def _pull_lightserve(self, now: float) -> None:
        lm = self._lightserve_metrics
        if lm is None:
            return
        dh = self._delta("ls_hits", lm.cache_hits.value())
        dm = self._delta("ls_misses", lm.cache_misses.value())
        if dh is not None and dm is not None and (dh or dm):
            self.lightserve_hit_rate.observe_counts(
                now, int(dh), int(dm)
            )

    def _pull_switch(self, now: float) -> None:
        if self._switch is not None:
            self.peer_flap.observe_count(now, len(self._switch.peers))

    def _pull_conservation(self, now: float) -> None:
        tr = self._conservation_tracer
        if tr is None or not getattr(tr, "enabled", False):
            return
        from .report import wall_conservation

        # SpanRecords pass straight through (no to_json round trip on
        # the tick path), pre-filtered to heights not yet judged —
        # heightless records (WAL fsyncs, scheduler rounds) are kept
        # for window binning; ones belonging to already-judged heights
        # find no window in the filtered set and drop out
        seen = self._dark_seen_height
        cons = wall_conservation(
            [
                r
                for r in tr.records()
                if r.height == 0 or r.height > seen
            ],
            n_heights=8,
        )
        heights = cons.get("heights") or {}
        if not heights:
            return
        tip = max(heights)
        for h in sorted(heights):
            # the tip height's window is still growing — judge only
            # completed heights, each exactly once
            if h >= tip or h <= self._dark_seen_height:
                continue
            self._dark_seen_height = h
            self.dark_time.observe_height(
                now, heights[h]["dark_fraction"]
            )

    # --- verdict roll-up + incident emission ------------------------------

    def _evaluate(self, t: float) -> None:
        # self-arm the stall clock on the first evaluation pass: the
        # harnesses (soak/chaos) never call start(), and a node that
        # stalls before its first commit must still page once the
        # ceiling elapses from when the plane first looked
        self.stalled_round.arm(t)
        for name, det in self.detectors.items():
            v = det.verdict(t)
            prev = self._last_verdicts[name]
            if v != prev:
                self._last_verdicts[name] = v
                self._incident(t, det, prev, v)
        if self.metrics is not None:
            for sub, v in self._rollup().items():
                self.metrics.status.set(v, subsystem=sub)
            for name, det in self.detectors.items():
                self.metrics.burn_rate.set(det.slo.burn(t), slo=name)

    def _incident(self, t: float, det: Detector, prev: int, new: int) -> None:
        snap = det.snapshot(t)
        # an escalation carries the OFFENDING observation; a recovery
        # carries the current (healthy) reading
        value = snap["last_bad"] if new > prev else snap["value"]
        rec = {
            "t": round(t, 3),
            "detector": det.name,
            "subsystem": det.subsystem,
            "from": VERDICT_NAMES[prev],
            "to": VERDICT_NAMES[new],
            "value": value,
            "threshold": snap["threshold"],
            "burn": snap["burn_long"],
        }
        self.incidents.append(rec)
        self.tracer.event(
            INCIDENT_EVENT,
            subsystem=det.subsystem,
            slo=det.name,
            to=VERDICT_NAMES[new],
            value=value,
            threshold=snap["threshold"],
            burn=snap["burn_long"],
            # same key as the dump_health incident list — a tool
            # joining the two surfaces must not need two spellings
            **{"from": VERDICT_NAMES[prev]},
        )
        if self.metrics is not None:
            self.metrics.incidents.inc(subsystem=det.subsystem)
        if self.logger is not None:
            log = (
                self.logger.error
                if new == CRITICAL
                else self.logger.info
            )
            log(
                "health verdict transition",
                detector=det.name,
                subsystem=det.subsystem,
                to=VERDICT_NAMES[new],
            )

    def _rollup(self) -> dict:
        """subsystem -> max CACHED verdict over its detectors (no
        re-evaluation; _evaluate's gauge pass rides this)."""
        out: dict[str, int] = {}
        for name, det in self.detectors.items():
            v = self._last_verdicts[name]
            out[det.subsystem] = max(out.get(det.subsystem, OK), v)
        return out

    def subsystem_verdicts(self, t: Optional[float] = None) -> dict:
        """subsystem -> max verdict over its detectors, re-evaluated at
        `t` (clock() when omitted) so direct conditions — a hard stall
        emits no events for the cached state to have seen — surface on
        every query, not just after the next feed."""
        self._evaluate(self.clock() if t is None else t)
        return self._rollup()

    def status(self, t: Optional[float] = None) -> int:
        subs = self.subsystem_verdicts(t)
        return max(subs.values()) if subs else OK

    def verdict(self, t: Optional[float] = None) -> dict:
        """The structured verdict the health/dump_health RPCs serve."""
        now = self.clock() if t is None else t
        # re-check direct conditions (a stall must surface even when
        # nothing feeds events)
        self._evaluate(now)
        subs: dict[str, dict] = {}
        for name, det in self.detectors.items():
            entry = subs.setdefault(
                det.subsystem, {"status": VERDICT_NAMES[OK], "detectors": {}}
            )
            v = self._last_verdicts[name]
            entry["detectors"][name] = {
                "status": VERDICT_NAMES[v],
                **det.snapshot(now),
            }
        rollup = self._rollup()
        for sub, v in rollup.items():
            subs[sub]["status"] = VERDICT_NAMES[v]
        code = max(rollup.values()) if rollup else OK
        return {
            "status": VERDICT_NAMES[code],
            "code": code,
            "subsystems": subs,
            "incidents": list(self.incidents)[-32:],
        }

    # --- async runtime ----------------------------------------------------

    async def start(self) -> None:
        if self._running:
            return
        self._running = True
        self.stalled_round.arm(self.clock())
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._run(), name="health/sample"),
            loop.create_task(self._heartbeat(), name="health/heartbeat"),
        ]

    async def stop(self) -> None:
        self._running = False
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
            except Exception as e:
                # a crashed watchdog task must not fail dark
                if self.logger is not None:
                    self.logger.error(
                        "health task died", task=t.get_name(), err=str(e)
                    )
        self._tasks.clear()

    def _sample_guarded(self) -> None:
        # seam pulls are individually guarded inside sample(); this
        # outer guard keeps an _evaluate/rollup crash from killing the
        # sampling loop — the watchdog plane failing dark while the
        # RPC keeps saying monitored:true is the exact failure mode
        # it exists to prevent
        try:
            self.sample()
        except Exception as e:
            if self.logger is not None:
                self.logger.error("health sample failed", err=str(e))

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            self._sample_guarded()

    async def _heartbeat(self) -> None:
        """The event-loop lag probe: schedule a sleep, measure the
        overshoot. Lag is how late the loop got back to a due callback
        — the direct observable of an event-loop-bound node."""
        loop = asyncio.get_running_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(self.heartbeat_interval)
            lag = max(0.0, loop.time() - t0 - self.heartbeat_interval)
            try:
                self.observe_loop_lag(lag)
            except Exception as e:
                if self.logger is not None:
                    self.logger.error("health heartbeat failed", err=str(e))
