"""Device-cost ledger — per-class accelerator accounting for the
unified dispatch scheduler.

The paper's premise is that committee crypto is the dominant cost and
the accelerator is the scarce resource, yet until this module the stack
could count dispatches and shapes (crypto/shape_registry) but not say
WHICH subsystem spent WHICH device milliseconds at WHAT fill
efficiency. The ledger closes that: `parallel/scheduler.py` records
every coalesced round here as a structured entry, and the ledger rolls
the stream into:

- **per-class device-time shares**: a round's device-execute seconds
  are attributed to its submitter classes proportionally to the rows
  each class contributed (an fn-lane round is single-class and books
  whole). This is the accounting substrate the verify-as-a-service
  topology (ROADMAP item 2) bills against — a multi-tenant scheduler
  is un-debuggable and un-fair without it;
- **fill-efficiency distributions**: per-round fill = rows-requested /
  rows-dispatched (the padded bucket). A saturated scheduler running
  10%-full buckets is a misconfiguration (mesh_min_rows / ladder /
  max_batch), and fill is the knob that prices it;
- **padding-waste totals**: dispatched-minus-requested rows — device
  work bought and thrown away, the direct cost of shape discipline;
- **requests-per-dispatch amortization**: submissions merged per round,
  cumulative and bucketed by round size, so the amortization curve
  (tools/device_report.py) shows where coalescing actually pays.

Determinism and shape follow `obs/health.py`: every entry takes an
explicit event time `t` (the scheduler stamps its own perf_counter
values); nothing here reads a clock. Stdlib only, thread-safe (the
scheduler records from its event loop; bench/RPC/soak read from
other threads).

Accounting truth lives in the CUMULATIVE totals, which never cap; the
bounded entry ring is a recent-detail view (the RPC dump's `entries`,
and the fill percentiles, which are computed over retained entries).
The scheduler's `dispatch_log` deque is telemetry only — PR 8 already
hit its 1024-cap reading stats from it; read this ledger instead.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from .report import pct

# entry ring default: enough to hold several bench families' worth of
# rounds; totals are exact regardless
DEFAULT_ENTRY_RING = 4096


class _ClassAccount:
    __slots__ = (
        "rows", "device_seconds", "queue_wait_seconds", "rounds",
        "submissions",
    )

    def __init__(self):
        self.rows = 0
        self.device_seconds = 0.0
        self.queue_wait_seconds = 0.0
        self.rounds = 0
        self.submissions = 0

    def to_json(self) -> dict:
        return {
            "rows": self.rows,
            "device_seconds": round(self.device_seconds, 6),
            "queue_wait_seconds": round(self.queue_wait_seconds, 6),
            "rounds": self.rounds,
            "submissions": self.submissions,
        }


class DispatchLedger:
    """Structured record of every coalesced scheduler round + rolling
    per-class/per-bucket accounting. One per process by default
    (`default_ledger()`, the shape-registry pattern); tests isolate
    with their own instance."""

    def __init__(self, max_entries: int = DEFAULT_ENTRY_RING):
        self._lock = threading.Lock()
        self._entries: deque[dict] = deque(maxlen=max(1, int(max_entries)))
        self._seq = 0  # id of the NEXT entry; monotonic, never reused
        # cumulative totals (never capped — the accounting truth)
        self._rounds = 0
        self._fn_rounds = 0
        self._sharded_rounds = 0
        self._rows_requested = 0  # sig rounds only (fn rows below)
        self._rows_dispatched = 0  # padded bucket rows, sig rounds only
        self._fn_rows = 0
        self._submissions = 0
        self._device_seconds = 0.0
        self._queue_wait_seconds = 0.0
        self._host_prep_seconds = 0.0
        self._per_class: dict[str, _ClassAccount] = {}
        # engine -> {rounds, submissions, rows_requested,
        # rows_dispatched, device_seconds}: the honest
        # requests-per-dispatch axis. The GLOBAL rpd is structurally
        # diluted by one-submission fn rounds (every bls_agg round is
        # exactly one submission by construction), so the coalescing
        # claim reads per-engine — "sig" is the coalesced ed25519
        # plane, named engines (bls_agg, qc_verify, secp_recover) and
        # anonymous "fn" closures each get their own row
        self._per_engine: dict[str, dict] = {}
        # bucket -> {rounds, rows_requested, submissions}: the
        # amortization curve's x-axis (bounded by the ladder + its
        # multiples, not by traffic)
        self._by_bucket: dict[int, dict] = {}

    # --- recording (scheduler's event loop) ------------------------------

    def record_round(
        self,
        t: float,
        *,
        class_rows: dict,
        requested: int,
        dispatched: int,
        devices: int = 1,
        submissions: int = 1,
        class_subs: Optional[dict] = None,
        queue_wait_s: float = 0.0,
        class_queue_wait: Optional[dict] = None,
        host_prep_s: float = 0.0,
        device_s: float = 0.0,
        engine: str = "sig",
    ) -> None:
        """Book one device round. `class_rows` maps submitter class ->
        rows it contributed (requested, pre-padding); `requested` is
        their sum, `dispatched` the padded bucket actually sent to the
        device (== requested for fn-lane rounds, which pad internally).
        `t` is the caller's event time for the dispatch start — the
        ledger never reads a clock. `class_subs`/`class_queue_wait`
        optionally map class -> merged-submission count / summed
        enqueue->dispatch wait."""
        requested = int(requested)
        dispatched = max(int(dispatched), requested)
        # every engine other than the coalesced ed25519 plane is an
        # fn-lane round (anonymous closures book as "fn"; wire engines
        # carry their name) — its rows/fill live on the fn axis
        fn = engine != "sig"
        fill = (requested / dispatched) if dispatched else 0.0
        # normalize the optional per-class maps once: a single-class
        # round's submissions/wait belong to that class even when the
        # caller didn't spell it out — recording (cumulative AND entry)
        # then uses one rule, so span rebuilds match the totals
        if class_subs is None:
            class_subs = (
                {next(iter(class_rows)): int(submissions)}
                if len(class_rows) == 1 else {}
            )
        if class_queue_wait is None:
            class_queue_wait = (
                {next(iter(class_rows)): queue_wait_s}
                if len(class_rows) == 1 else {}
            )
        entry = {
            "seq": 0,  # patched under the lock
            "t": round(t, 6),
            "engine": engine,
            "classes": sorted(class_rows),
            "rows": {k: int(v) for k, v in class_rows.items()},
            "subs": {k: int(v) for k, v in class_subs.items()},
            "wait": {
                k: round(v, 6) for k, v in class_queue_wait.items()
            },
            "requested": requested,
            "dispatched": dispatched,
            "fill": round(fill, 4),
            "devices": int(devices),
            "sharded": devices > 1,
            "submissions": int(submissions),
            "queue_wait_s": round(queue_wait_s, 6),
            "host_prep_s": round(host_prep_s, 6),
            "device_s": round(device_s, 6),
        }
        with self._lock:
            entry["seq"] = self._seq
            self._seq += 1
            self._entries.append(entry)
            self._rounds += 1
            if fn:
                self._fn_rounds += 1
                self._fn_rows += requested
            else:
                self._rows_requested += requested
                self._rows_dispatched += dispatched
            eng = self._per_engine.get(engine)
            if eng is None:
                eng = self._per_engine[engine] = {
                    "rounds": 0, "submissions": 0, "rows_requested": 0,
                    "rows_dispatched": 0, "device_seconds": 0.0,
                }
            eng["rounds"] += 1
            eng["submissions"] += int(submissions)
            eng["rows_requested"] += requested
            eng["rows_dispatched"] += dispatched
            eng["device_seconds"] += device_s
            if devices > 1:
                self._sharded_rounds += 1
            self._submissions += int(submissions)
            self._device_seconds += device_s
            self._queue_wait_seconds += queue_wait_s
            self._host_prep_seconds += host_prep_s
            for klass, rows in class_rows.items():
                acct = self._per_class.get(klass)
                if acct is None:
                    acct = self._per_class[klass] = _ClassAccount()
                acct.rows += int(rows)
                acct.rounds += 1
                # device time attributed by row share (fn/single-class
                # rounds book whole: rows == requested)
                if requested > 0:
                    acct.device_seconds += device_s * (rows / requested)
                acct.queue_wait_seconds += class_queue_wait.get(klass, 0.0)
                acct.submissions += int(class_subs.get(klass, 0))
            if not fn:
                b = self._by_bucket.get(dispatched)
                if b is None:
                    b = self._by_bucket[dispatched] = {
                        "rounds": 0, "rows_requested": 0, "submissions": 0,
                    }
                b["rounds"] += 1
                b["rows_requested"] += requested
                b["submissions"] += int(submissions)

    # --- reading ----------------------------------------------------------

    def totals(self) -> dict:
        """Cumulative scalar totals (the health plane's pull seam reads
        interval deltas of these)."""
        with self._lock:
            return {
                "seq": self._seq,
                "rounds": self._rounds,
                "fn_rounds": self._fn_rounds,
                "sharded_rounds": self._sharded_rounds,
                "rows_requested": self._rows_requested,
                "rows_dispatched": self._rows_dispatched,
                "fn_rows": self._fn_rows,
                "submissions": self._submissions,
                "device_seconds": self._device_seconds,
                "queue_wait_seconds": self._queue_wait_seconds,
                "host_prep_seconds": self._host_prep_seconds,
            }

    def mark(self) -> dict:
        """Opaque position for `summary(since=...)` — bench families
        bracket a run with mark()/summary() the way they bracket the
        shape registry with snapshot()/delta()."""
        return self.totals()

    def entries(self, since_seq: int = 0, limit: int = 0) -> list[dict]:
        """Retained entries with seq >= since_seq (ring-bounded; the
        newest `limit` when limit > 0)."""
        with self._lock:
            out = [e for e in self._entries if e["seq"] >= since_seq]
        if limit > 0:
            out = out[-limit:]
        return out

    def summary(self, since: Optional[dict] = None) -> dict:
        """The `device_cost` block: per-class device-seconds/rows/share,
        fill-efficiency p50/p95, padding-waste rows, and the
        requests-per-dispatch amortization — over the whole ledger, or
        the span since a `mark()` when given.

        Totals in the block are EXACT over the span (cumulative-counter
        deltas). The fill percentiles and per-bucket curve come from
        retained ring entries; `fill_window_truncated` flags a span
        whose older rounds aged out of the ring."""
        now = self.totals()
        base = since or {}
        since_seq = int(base.get("seq", 0))
        span = self.entries(since_seq=since_seq)
        # fill percentiles are a SIG-plane distribution: fn engines'
        # internal buckets are honest now, but blending a 0.59-full
        # bls_agg aggregate with a 0.95-full ed25519 bucket prices
        # nothing — each plane reads its own axis (per_engine below)
        sig_fills = sorted(e["fill"] for e in span if e["engine"] == "sig")
        rounds = now["rounds"] - base.get("rounds", 0)
        fn_rounds = now["fn_rounds"] - base.get("fn_rounds", 0)
        requested = now["rows_requested"] - base.get("rows_requested", 0)
        dispatched = now["rows_dispatched"] - base.get("rows_dispatched", 0)
        submissions = now["submissions"] - base.get("submissions", 0)
        device_s = now["device_seconds"] - base.get("device_seconds", 0.0)
        per_class: dict[str, dict] = {}
        per_engine: dict[str, dict] = {}
        if since is None:
            with self._lock:
                per_class = {
                    k: v.to_json() for k, v in self._per_class.items()
                }
                per_engine = {
                    k: dict(v) for k, v in self._per_engine.items()
                }
        else:
            # span view: rebuild per-class from retained entries (exact
            # when the ring held the whole span; flagged below when not)
            accts: dict[str, _ClassAccount] = {}
            for e in span:
                e_req = e["requested"] or 1
                for klass, rows in e["rows"].items():
                    acct = accts.setdefault(klass, _ClassAccount())
                    acct.rows += rows
                    acct.rounds += 1
                    acct.device_seconds += e["device_s"] * (rows / e_req)
                    acct.submissions += e["subs"].get(klass, 0)
                    acct.queue_wait_seconds += e["wait"].get(klass, 0.0)
            per_class = {k: v.to_json() for k, v in accts.items()}
            for e in span:
                eng = per_engine.setdefault(
                    e["engine"],
                    {"rounds": 0, "submissions": 0, "rows_requested": 0,
                     "rows_dispatched": 0, "device_seconds": 0.0},
                )
                eng["rounds"] += 1
                eng["submissions"] += e["submissions"]
                eng["rows_requested"] += e["requested"]
                eng["rows_dispatched"] += e["dispatched"]
                eng["device_seconds"] += e["device_s"]
        for entry in per_class.values():
            entry["device_share"] = round(
                entry["device_seconds"] / device_s, 4
            ) if device_s > 0 else 0.0
        for eng in per_engine.values():
            eng["device_seconds"] = round(eng["device_seconds"], 6)
            eng["fill_ratio"] = round(
                eng["rows_requested"] / eng["rows_dispatched"], 4
            ) if eng["rows_dispatched"] else 0.0
            eng["requests_per_dispatch"] = round(
                eng["submissions"] / eng["rounds"], 3
            ) if eng["rounds"] else 0.0
        by_bucket: dict[int, dict] = {}
        for e in span:
            if e["engine"] != "sig":
                continue
            b = by_bucket.setdefault(
                e["dispatched"],
                {"rounds": 0, "rows_requested": 0, "submissions": 0},
            )
            b["rounds"] += 1
            b["rows_requested"] += e["requested"]
            b["submissions"] += e["submissions"]
        return {
            "rounds": rounds,
            "fn_rounds": fn_rounds,
            "sharded_rounds": (
                now["sharded_rounds"] - base.get("sharded_rounds", 0)
            ),
            "rows_requested": requested,
            "rows_dispatched": dispatched,
            "fn_rows": now["fn_rows"] - base.get("fn_rows", 0),
            "padding_rows": max(0, dispatched - requested),
            "fill_ratio": round(requested / dispatched, 4) if dispatched
            else 0.0,
            "fill_ratio_p50": round(pct(sig_fills, 0.50), 4),
            "fill_ratio_p95": round(pct(sig_fills, 0.95), 4),
            "requests_per_dispatch": round(submissions / rounds, 3)
            if rounds else 0.0,
            "per_engine": dict(sorted(per_engine.items())),
            "device_seconds": round(device_s, 6),
            "queue_wait_seconds": round(
                now["queue_wait_seconds"]
                - base.get("queue_wait_seconds", 0.0), 6
            ),
            "host_prep_seconds": round(
                now["host_prep_seconds"]
                - base.get("host_prep_seconds", 0.0), 6
            ),
            "per_class": dict(sorted(per_class.items())),
            "by_bucket": {
                str(b): v for b, v in sorted(by_bucket.items())
            },
            "fill_window_truncated": len(span) < rounds,
        }


_default: Optional[DispatchLedger] = None
_default_lock = threading.Lock()


def default_ledger() -> DispatchLedger:
    """Process-wide ledger every VerifyScheduler records into unless
    handed an explicit one (tests isolate with their own instance) —
    the default-shape-registry pattern, so bench/soak capture every
    scheduler in the process with one mark()/summary() pair."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = DispatchLedger()
    return _default


def set_default_ledger(
    ledger: Optional[DispatchLedger],
) -> Optional[DispatchLedger]:
    """Install `ledger` as the process default (None resets to a fresh
    one on next access)."""
    global _default
    with _default_lock:
        _default = ledger
    return ledger
