"""SQL event sink — the reference's psql indexer sink over DB-API.

Reference: state/indexer/sink/psql/{psql.go,schema.sql}. Same relational
schema (blocks / tx_results / events / attributes + the three views) and
the same write paths (IndexBlockEvents, IndexTxEvents with idempotent
re-index). The Go sink hard-binds github.com/lib/pq; this one speaks
PEP 249 against stdlib sqlite3 (the tested backend — this image ships no
postgres driver). Running it against PostgreSQL additionally needs the
reference's own schema.sql (SERIAL keys; this module's DDL uses sqlite's
AUTOINCREMENT spelling) and an insert-returning strategy in place of
cursor.lastrowid — left to a deployment that has a driver to test
against, and flagged loudly here rather than shipped untested.

The sink is append-only and stores the full event stream relationally so
external indexers can query it with plain SQL (the reference's stated
purpose — psql.go:1-35); it deliberately implements NO search API
(backport.go returns errors for search, as does this class).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from .txindex import TxResult


_SCHEMA_SQLITE = """
CREATE TABLE IF NOT EXISTS blocks (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  height     BIGINT NOT NULL,
  chain_id   VARCHAR NOT NULL,
  created_at BIGINT NOT NULL,
  UNIQUE (height, chain_id)
);
CREATE INDEX IF NOT EXISTS idx_blocks_height_chain
  ON blocks(height, chain_id);
CREATE TABLE IF NOT EXISTS tx_results (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id   BIGINT NOT NULL REFERENCES blocks(rowid),
  tx_index   INTEGER NOT NULL,
  created_at BIGINT NOT NULL,
  tx_hash    VARCHAR NOT NULL,
  tx_result  BLOB NOT NULL,
  UNIQUE (block_id, tx_index)
);
CREATE TABLE IF NOT EXISTS events (
  rowid    INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id BIGINT NOT NULL REFERENCES blocks(rowid),
  tx_id    BIGINT NULL REFERENCES tx_results(rowid),
  type     VARCHAR NOT NULL
);
CREATE TABLE IF NOT EXISTS attributes (
  event_id      BIGINT NOT NULL REFERENCES events(rowid),
  key           VARCHAR NOT NULL,
  composite_key VARCHAR NOT NULL,
  value         VARCHAR NULL,
  UNIQUE (event_id, key)
);
CREATE VIEW IF NOT EXISTS event_attributes AS
  SELECT block_id, tx_id, type, key, composite_key, value
  FROM events LEFT JOIN attributes ON (events.rowid = attributes.event_id);
CREATE VIEW IF NOT EXISTS block_events AS
  SELECT blocks.rowid as block_id, height, chain_id, type, key,
         composite_key, value
  FROM blocks JOIN event_attributes
    ON (blocks.rowid = event_attributes.block_id)
  WHERE event_attributes.tx_id IS NULL;
CREATE VIEW IF NOT EXISTS tx_events AS
  SELECT height, tx_index, chain_id, type, key, composite_key, value,
         tx_results.created_at
  FROM blocks JOIN tx_results ON (blocks.rowid = tx_results.block_id)
  JOIN event_attributes ON (tx_results.rowid = event_attributes.tx_id)
  WHERE event_attributes.tx_id IS NOT NULL;
"""


class SQLEventSink:
    def __init__(
        self,
        connect: Optional[Callable] = None,
        chain_id: str = "",
        paramstyle: str = "?",
    ):
        """connect: zero-arg factory returning a PEP 249 connection
        (default: in-memory sqlite3; see module docstring for what a
        postgres deployment must adapt)."""
        if connect is None:
            import sqlite3

            db = sqlite3.connect(":memory:")
            connect = lambda: db  # noqa: E731
        self._conn = connect()
        self._p = paramstyle
        self.chain_id = chain_id
        cur = self._conn.cursor()
        cur.executescript(_SCHEMA_SQLITE) if hasattr(
            cur, "executescript"
        ) else [
            cur.execute(stmt)
            for stmt in _SCHEMA_SQLITE.split(";")
            if stmt.strip()
        ]
        self._conn.commit()

    def _q(self, sql: str) -> str:
        return sql.replace("?", self._p) if self._p != "?" else sql

    def _block_rowid(self, cur, height: int) -> int:
        cur.execute(
            self._q(
                "SELECT rowid FROM blocks WHERE height = ? AND chain_id = ?"
            ),
            (height, self.chain_id),
        )
        row = cur.fetchone()
        if row is None:
            raise KeyError(f"block {height} not indexed")
        return row[0]

    # --- write paths (psql.go IndexBlockEvents / IndexTxEvents) -----------

    def index_block(self, height: int, events: list) -> None:
        """events: [(type, [(key, value), ...]), ...]. Idempotent per
        (height, chain_id) — a replayed block does not duplicate rows
        (psql.go:103 ON CONFLICT DO NOTHING shape)."""
        cur = self._conn.cursor()
        cur.execute(
            self._q(
                "SELECT rowid FROM blocks WHERE height = ? AND chain_id = ?"
            ),
            (height, self.chain_id),
        )
        if cur.fetchone() is not None:
            return
        cur.execute(
            self._q(
                "INSERT INTO blocks (height, chain_id, created_at) "
                "VALUES (?, ?, ?)"
            ),
            (height, self.chain_id, time.time_ns()),
        )
        block_id = cur.lastrowid
        self._insert_events(cur, block_id, None, events)
        self._conn.commit()

    def index_tx(self, result: TxResult, events: list) -> None:
        cur = self._conn.cursor()
        block_id = self._block_rowid(cur, result.height)
        cur.execute(
            self._q(
                "SELECT rowid FROM tx_results "
                "WHERE block_id = ? AND tx_index = ?"
            ),
            (block_id, result.index),
        )
        if cur.fetchone() is not None:
            return
        import hashlib

        cur.execute(
            self._q(
                "INSERT INTO tx_results "
                "(block_id, tx_index, created_at, tx_hash, tx_result) "
                "VALUES (?, ?, ?, ?, ?)"
            ),
            (
                block_id,
                result.index,
                time.time_ns(),
                hashlib.sha256(result.tx).hexdigest().upper(),
                result.encode(),
            ),
        )
        tx_id = cur.lastrowid
        self._insert_events(cur, block_id, tx_id, events)
        self._conn.commit()

    def _insert_events(self, cur, block_id, tx_id, events) -> None:
        for etype, attrs in events:
            cur.execute(
                self._q(
                    "INSERT INTO events (block_id, tx_id, type) "
                    "VALUES (?, ?, ?)"
                ),
                (block_id, tx_id, etype),
            )
            event_id = cur.lastrowid
            for k, v in attrs:
                cur.execute(
                    self._q(
                        "INSERT INTO attributes "
                        "(event_id, key, composite_key, value) "
                        "VALUES (?, ?, ?, ?)"
                    ),
                    (event_id, k, f"{etype}.{k}", v),
                )

    # --- the sink exposes no search (reference backport.go) ---------------

    def search_txs(self, *_a, **_kw):
        raise NotImplementedError(
            "the SQL sink does not implement search; query it with SQL"
        )

    search_blocks = search_txs

    def close(self) -> None:
        self._conn.close()
