"""State — the deterministic per-height consensus state value.

Reference: state/state.go (`State` struct): everything needed to validate
and execute the next block — last block info, three validator-set
generations (last/current/next), consensus params, app hash. Immutable by
convention: `next_state` in the executor builds a fresh copy.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

from ..libs import protoio as pio
from ..types.block import Block
from ..types.block_id import BlockID
from ..types.genesis import GenesisDoc
from ..types.params import ConsensusParams
from ..types.validator_set import ValidatorSet

INIT_STATE_VERSION = 1


@dataclass
class State:
    chain_id: str = ""
    initial_height: int = 1

    last_block_height: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_block_time_ns: int = 0

    # validators[h] signs block h; next_validators is for h+1
    # (reference state.go: NextValidators / Validators / LastValidators)
    validators: Optional[ValidatorSet] = None
    next_validators: Optional[ValidatorSet] = None
    last_validators: Optional[ValidatorSet] = None
    last_height_validators_changed: int = 0

    consensus_params: ConsensusParams = field(default_factory=ConsensusParams)
    last_height_consensus_params_changed: int = 0

    last_results_hash: bytes = b""
    app_hash: bytes = b""

    def is_empty(self) -> bool:
        return self.validators is None

    def copy(self) -> "State":
        return State(
            chain_id=self.chain_id,
            initial_height=self.initial_height,
            last_block_height=self.last_block_height,
            last_block_id=self.last_block_id,
            last_block_time_ns=self.last_block_time_ns,
            validators=self.validators.copy() if self.validators else None,
            next_validators=(
                self.next_validators.copy() if self.next_validators else None
            ),
            last_validators=(
                self.last_validators.copy() if self.last_validators else None
            ),
            last_height_validators_changed=self.last_height_validators_changed,
            consensus_params=self.consensus_params,
            last_height_consensus_params_changed=(
                self.last_height_consensus_params_changed
            ),
            last_results_hash=self.last_results_hash,
            app_hash=self.app_hash,
        )

    @classmethod
    def from_genesis(cls, genesis: GenesisDoc) -> "State":
        """MakeGenesisState (reference state.go)."""
        val_set = genesis.validator_set()
        return cls(
            chain_id=genesis.chain_id,
            initial_height=genesis.initial_height,
            last_block_height=0,
            last_block_id=BlockID(),
            last_block_time_ns=genesis.genesis_time_ns,
            validators=val_set,
            next_validators=val_set.copy_increment_proposer_priority(1),
            last_validators=ValidatorSet.empty(),
            last_height_validators_changed=genesis.initial_height,
            consensus_params=genesis.consensus_params,
            last_height_consensus_params_changed=genesis.initial_height,
            app_hash=genesis.app_hash,
        )

    def make_block_validate(
        self, block: Block, verifier=None, use_qc=False, qc_engine=None
    ) -> None:
        """Stateful block validation (reference state/validation.go
        validateBlock): header fields must chain from this state.
        `verifier` routes the LastCommit signature check (a device
        dispatch) — callers off the event loop pass a scheduler-classed
        adapter so the dispatch coalesces instead of stalling the
        consensus loop. With `use_qc` ([consensus] quorum_certificates)
        a block carrying a QuorumCertificate proves its LastCommit with
        ONE aggregate pairing check instead of N signature rows — the
        WAL-replay and blocksync revalidation paths ride this same
        method, so catchup replay gets the flat-cost check too."""
        block.validate_basic()
        h = block.header
        if h.chain_id != self.chain_id:
            raise ValueError("wrong chain id")
        expected_height = (
            self.initial_height
            if self.last_block_height == 0
            else self.last_block_height + 1
        )
        if h.height != expected_height:
            raise ValueError(
                f"wrong height: got {h.height}, want {expected_height}"
            )
        if h.last_block_id != self.last_block_id:
            raise ValueError("wrong last block id")
        if h.validators_hash != self.validators.hash():
            raise ValueError("wrong validators hash")
        if h.next_validators_hash != self.next_validators.hash():
            raise ValueError("wrong next validators hash")
        if h.consensus_hash != self.consensus_params.hash():
            raise ValueError("wrong consensus params hash")
        if h.app_hash != self.app_hash:
            raise ValueError("wrong app hash")
        if h.last_results_hash != self.last_results_hash:
            raise ValueError("wrong last results hash")
        if not self.validators.has_address(h.proposer_address):
            raise ValueError("proposer not in validator set")
        if self.last_block_height > 0:
            # LastCommit must verify against the validators of height-1
            if block.last_commit is None:
                raise ValueError("nil last commit")
            if (
                use_qc
                and block.last_qc is not None
                and self.last_validators.qc_capable()
            ):
                # the carried commit must still be the SHAPE legacy
                # consumers will verify — size/height/block_id against
                # the certified decision (a byzantine proposer pairing
                # a valid aggregate with a malformed commit would
                # otherwise split the chain from every full-commit
                # verifier); the signature ROWS are what the aggregate
                # replaces (trust model: PERF_ANALYSIS §21)
                self.last_validators._check_commit_shape(
                    self.last_block_id,
                    self.last_block_height,
                    block.last_commit,
                )
                self.last_validators.verify_commit_qc(
                    self.chain_id,
                    self.last_block_id,
                    self.last_block_height,
                    block.last_qc,
                    engine=qc_engine,
                )
            else:
                self.last_validators.verify_commit_light(
                    self.chain_id,
                    self.last_block_id,
                    self.last_block_height,
                    block.last_commit,
                    verifier=verifier,
                )
        if h.time_ns <= self.last_block_time_ns and self.last_block_height > 0:
            raise ValueError("block time must be monotonically increasing")

    # --- encoding ---------------------------------------------------------

    def encode(self) -> bytes:
        import json
        from dataclasses import asdict

        params_blob = json.dumps(
            self.consensus_params.to_json(), sort_keys=True
        ).encode()
        return b"".join(
            [
                pio.field_varint(1, INIT_STATE_VERSION),
                pio.field_bytes(2, self.chain_id.encode()),
                pio.field_varint(3, self.initial_height),
                pio.field_varint(4, self.last_block_height),
                pio.field_message(5, self.last_block_id.encode()),
                pio.field_varint(6, self.last_block_time_ns),
                pio.field_message(7, self.validators.encode()),
                pio.field_message(8, self.next_validators.encode()),
                pio.field_message(9, self.last_validators.encode()),
                pio.field_varint(10, self.last_height_validators_changed),
                pio.field_bytes(11, params_blob),
                pio.field_varint(
                    12, self.last_height_consensus_params_changed
                ),
                pio.field_bytes(13, self.last_results_hash),
                pio.field_bytes(14, self.app_hash),
            ]
        )

    @classmethod
    def decode(cls, data: bytes) -> "State":
        import json

        f = pio.decode_fields(data)
        params = ConsensusParams.from_json(
            json.loads(f.get(11, [b"{}"])[0].decode())
        )
        return cls(
            chain_id=f.get(2, [b""])[0].decode(),
            initial_height=f.get(3, [1])[0],
            last_block_height=f.get(4, [0])[0],
            last_block_id=BlockID.decode(f.get(5, [b""])[0]),
            last_block_time_ns=f.get(6, [0])[0],
            validators=ValidatorSet.decode(f[7][0]),
            next_validators=ValidatorSet.decode(f[8][0]),
            last_validators=ValidatorSet.decode(f[9][0]),
            last_height_validators_changed=f.get(10, [0])[0],
            consensus_params=params,
            last_height_consensus_params_changed=f.get(12, [0])[0],
            last_results_hash=f.get(13, [b""])[0],
            app_hash=f.get(14, [b""])[0],
        )
