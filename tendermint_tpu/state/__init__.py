"""State layer: the replicated state value, its durable store, and the
block executor (SURVEY.md layer 4 + the app/execution bridge glue)."""

from .state import State  # noqa: F401
from .store import StateStore  # noqa: F401
