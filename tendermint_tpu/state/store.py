"""StateStore — durable state, validator sets and params keyed by height.

Reference: state/store.go:50 (Store iface: state, ABCI responses,
validator sets, consensus params) + rollback support (state/rollback.go,
rewind.go).
"""

from __future__ import annotations

import json
import struct
from typing import Optional

from ..libs import protoio as pio
from ..store.kv import KV
from ..types.params import ConsensusParams
from ..types.validator_set import ValidatorSet
from .state import State

_STATE = b"stateKey"
_VALS = b"validatorsKey:"
_PARAMS = b"consensusParamsKey:"
_ABCI = b"abciResponsesKey:"


def _hk(prefix: bytes, height: int) -> bytes:
    return prefix + struct.pack(">q", height)


class StateStore:
    """Persists state at each height. Validator sets are stored at the
    height they become effective (validators for height h stored at h)."""

    def __init__(self, db: KV):
        self._db = db

    # --- state ------------------------------------------------------------

    def load(self) -> Optional[State]:
        raw = self._db.get(_STATE)
        return State.decode(raw) if raw else None

    def save(self, state: State) -> None:
        """Persist state + the validator/params records for the upcoming
        height (reference state/store.go save)."""
        next_height = (
            state.initial_height
            if state.last_block_height == 0
            else state.last_block_height + 1
        )
        sets = [
            (_STATE, state.encode()),
            (
                _hk(_VALS, next_height + 1),
                state.next_validators.encode(),
            ),
            (
                _hk(_PARAMS, next_height),
                json.dumps(
                    state.consensus_params.to_json(), sort_keys=True
                ).encode(),
            ),
        ]
        if state.last_block_height == 0:
            # bootstrap: validators for the initial height
            sets.append((_hk(_VALS, next_height), state.validators.encode()))
        self._db.write_batch(sets, [])

    def bootstrap(self, state: State) -> None:
        self.save(state)

    # --- validator sets ---------------------------------------------------

    def load_validators(self, height: int) -> Optional[ValidatorSet]:
        raw = self._db.get(_hk(_VALS, height))
        return ValidatorSet.decode(raw) if raw else None

    # --- consensus params -------------------------------------------------

    def load_consensus_params(self, height: int) -> Optional[ConsensusParams]:
        raw = self._db.get(_hk(_PARAMS, height))
        return ConsensusParams.from_json(json.loads(raw.decode())) if raw else None

    # --- abci responses (results) ----------------------------------------

    def save_abci_responses(self, height: int, responses_blob: bytes) -> None:
        self._db.set(_hk(_ABCI, height), responses_blob)

    def load_abci_responses(self, height: int) -> Optional[bytes]:
        return self._db.get(_hk(_ABCI, height))

    # --- pruning / rollback ----------------------------------------------

    def prune_states(self, retain_height: int, from_height: int = 1) -> None:
        deletes = []
        for h in range(from_height, retain_height):
            deletes.append(_hk(_VALS, h))
            deletes.append(_hk(_PARAMS, h))
            deletes.append(_hk(_ABCI, h))
        self._db.write_batch([], deletes)

    def rollback(self, block_store) -> State:
        """Roll the state back one height (reference state/rollback.go):
        reconstruct state at height-1 from the stores. Requires the block
        store to still have the block at the rollback height."""
        cur = self.load()
        if cur is None:
            raise ValueError("no state to roll back")
        rollback_height = cur.last_block_height
        if rollback_height <= 0:
            raise ValueError("cannot roll back genesis state")
        prev_height = rollback_height - 1
        block = block_store.load_block_meta(rollback_height)
        if block is None:
            raise ValueError("block at rollback height not found")
        prev_block = block_store.load_block_meta(prev_height)
        if prev_block is None and prev_height > 0:
            raise ValueError("block before rollback height not found")

        validators = self.load_validators(rollback_height)
        next_validators = self.load_validators(rollback_height + 1)
        last_validators = self.load_validators(prev_height)
        params = self.load_consensus_params(rollback_height)
        if validators is None or next_validators is None:
            raise ValueError("validator sets for rollback not found")

        rolled = State(
            chain_id=cur.chain_id,
            initial_height=cur.initial_height,
            last_block_height=prev_height,
            last_block_id=block.header.last_block_id,
            last_block_time_ns=(
                prev_block.header.time_ns if prev_block else 0
            ),
            validators=validators,
            next_validators=next_validators,
            last_validators=(
                last_validators
                if last_validators is not None
                else ValidatorSet.empty()
            ),
            last_height_validators_changed=cur.last_height_validators_changed,
            consensus_params=params or cur.consensus_params,
            last_height_consensus_params_changed=(
                cur.last_height_consensus_params_changed
            ),
            last_results_hash=block.header.last_results_hash,
            app_hash=block.header.app_hash,
        )
        self._db.set(_STATE, rolled.encode())
        return rolled
