"""BlockExecutor — proposal creation and the ApplyBlock pipeline.

Reference: state/execution.go — CreateProposalBlock :107 (txs pulled from
the L2 node via the notifier; no mempool), ProcessProposal/ValidateBlock
:179/:207, ApplyBlock :220-288 (validate → ABCI exec → ExecBlockOnL2Node
:390-429 → updateState :590 → ABCI Commit :363 → evidence update → save),
and the L2-driven validator-set diffing :309-360.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from typing import Optional

from ..abci import types as abci
from ..crypto import merkle
from ..l2node.l2node import BlockData, BlsData, L2Node
from ..libs import fail
from ..libs.log import Logger, nop_logger
from ..store.block_store import BlockStore
from ..types.block import Block, BlockIDFlag, Commit, Data, Header
from ..types.block_id import BlockID
from ..types.evidence import evidence_hash
from ..types.validator import Validator, pubkey_from_type
from .state import State
from .store import StateStore


@dataclass
class ABCIResponses:
    """Per-height execution results (reference state/execution.go
    ABCIResponses): deliver_tx results feed last_results_hash."""

    deliver_txs: list[abci.ResponseDeliverTx] = field(default_factory=list)
    end_block: Optional[abci.ResponseEndBlock] = None
    begin_block: Optional[abci.ResponseBeginBlock] = None
    # the MERGED (L2-over-app) validator updates apply_block actually
    # used — round-tripped so crash recovery from a saved-responses
    # record rebuilds the identical next validator set
    val_updates: list = field(default_factory=list)
    param_updates: Optional[dict] = None

    def results_hash(self) -> bytes:
        leaves = [
            bytes([r.code & 0xFF]) + r.data for r in self.deliver_txs
        ]
        return merkle.hash_from_byte_slices(leaves)

    def encode(self) -> bytes:
        return json.dumps(
            {
                "deliver_txs": [
                    {
                        "code": r.code,
                        "data": r.data.hex(),
                        "log": r.log,
                        "events": [
                            {"type": e.type, "attributes": e.attributes}
                            for e in r.events
                        ],
                    }
                    for r in self.deliver_txs
                ],
                "val_updates": [
                    # 4th column (BLS pubkey) only when carried, so
                    # pre-QC records decode byte-identically
                    [u[0], u[1].hex(), u[2]]
                    + ([u[3].hex()] if len(u) > 3 and u[3] else [])
                    for u in self.val_updates
                ],
                "param_updates": self.param_updates,
            }
        ).encode()

    @classmethod
    def decode(cls, data: bytes) -> "ABCIResponses":
        obj = json.loads(data.decode())
        out = cls()
        for r in obj.get("deliver_txs", []):
            out.deliver_txs.append(
                abci.ResponseDeliverTx(
                    code=r.get("code", 0),
                    data=bytes.fromhex(r.get("data", "")),
                    log=r.get("log", ""),
                    events=[
                        abci.Event(e["type"], e.get("attributes", {}))
                        for e in r.get("events", [])
                    ],
                )
            )
        out.val_updates = [
            (row[0], bytes.fromhex(row[1]), row[2], bytes.fromhex(row[3]))
            if len(row) > 3
            else (row[0], bytes.fromhex(row[1]), row[2])
            for row in obj.get("val_updates", [])
        ]
        out.param_updates = obj.get("param_updates")
        if out.param_updates is not None:
            # _update_state reads param updates off end_block
            out.end_block = abci.ResponseEndBlock(
                consensus_param_updates=out.param_updates
            )
        return out


class BlockExecutor:
    def __init__(
        self,
        state_store: StateStore,
        block_store: BlockStore,
        proxy_app_consensus,  # abci client (consensus connection)
        l2_node: L2Node,
        event_bus=None,
        evidence_pool=None,
        logger: Optional[Logger] = None,
        qc_enabled: bool = False,
    ):
        self._state_store = state_store
        self._block_store = block_store
        self._app = proxy_app_consensus
        self._l2 = l2_node
        self._event_bus = event_bus
        self._evpool = evidence_pool
        self.logger = logger or nop_logger()
        # QC plane ([consensus] quorum_certificates): blocks carrying a
        # QuorumCertificate validate their LastCommit with one aggregate
        # pairing check — live validation, blocksync revalidation and
        # WAL-replay apply all funnel through validate_block
        self.qc_enabled = qc_enabled

    # --- proposal ---------------------------------------------------------

    def create_proposal_block(
        self,
        height: int,
        state: State,
        last_commit: Commit | None,
        proposer_address: bytes,
        block_data: BlockData,
        time_ns: int,
    ) -> Block:
        """Builds the proposal from L2-provided block data
        (reference CreateProposalBlock :107)."""
        evidence = (
            self._evpool.pending_evidence(
                state.consensus_params.evidence.max_bytes
            )
            if self._evpool
            else []
        )
        header = Header(
            chain_id=state.chain_id,
            height=height,
            time_ns=time_ns,
            last_block_id=state.last_block_id,
            validators_hash=state.validators.hash(),
            next_validators_hash=state.next_validators.hash(),
            consensus_hash=state.consensus_params.hash(),
            app_hash=state.app_hash,
            last_results_hash=state.last_results_hash,
            proposer_address=proposer_address,
        )
        block = Block(
            header=header,
            data=Data(
                txs=list(block_data.txs),
                l2_block_meta=block_data.l2_block_meta,
                l2_batch_header=block_data.l2_batch_header,
            ),
            evidence=evidence,
            last_commit=last_commit,
        )
        block.fill_header()
        return block

    # --- validation -------------------------------------------------------

    def validate_block(
        self, state: State, block: Block, verifier=None, qc_engine=None
    ) -> None:
        """Stateful validation incl. evidence (reference ValidateBlock :207)."""
        state.make_block_validate(
            block,
            verifier=verifier,
            use_qc=self.qc_enabled,
            qc_engine=qc_engine,
        )
        if self._evpool:
            for ev in block.evidence:
                self._evpool.check_evidence(ev, state)

    async def validate_block_off_loop(
        self, state: State, block: Block, klass: str = "consensus"
    ) -> None:
        """validate_block with its LastCommit device verify moved OFF
        the event loop (the PR 9 follow-up): the check runs in an
        executor thread against a scheduler-classed adapter, so a
        proposal's commit-light dispatch coalesces with in-flight vote
        rounds instead of stalling the consensus loop for a full device
        round (the vote path made this move in PR 3). `klass` is the
        caller's priority class — the live consensus path uses the
        default, blocksync backfill passes "blocksync" so a catchup
        flood never queues at live-vote priority. Raises exactly what
        validate_block raises."""
        from ..parallel.scheduler import default_dispatch
        from ..types.quorum_cert import qc_dispatch

        verifier = default_dispatch(klass)
        qc_engine = qc_dispatch(klass) if self.qc_enabled else None
        await asyncio.get_running_loop().run_in_executor(
            None, self.validate_block, state, block, verifier, qc_engine
        )

    def process_proposal(self, state: State, block: Block) -> bool:
        """CheckBlockData against the L2 node (reference ProcessProposal
        :179 → l2.CheckBlockData — the prevote gate)."""
        return self._l2.check_block_data(
            block.data.txs, block.data.l2_block_meta
        )

    # --- apply ------------------------------------------------------------

    async def apply_block(
        self,
        state: State,
        block_id: BlockID,
        block: Block,
        bls_datas: Optional[list[BlsData]] = None,
        verify_klass: str = "consensus",
    ) -> State:
        """The commit pipeline (reference ApplyBlock :220-288)."""
        await self.validate_block_off_loop(state, block, klass=verify_klass)

        abci_responses = await self._exec_block_on_app(state, block)
        fail.fail_point()  # crash between app exec and L2 delivery

        val_updates = self._exec_block_on_l2(block, bls_datas or [])
        fail.fail_point()  # crash between L2 delivery and state update

        # merge validator updates: L2-driven (morph) takes precedence,
        # else the app's end_block updates (upstream behavior)
        if not val_updates and abci_responses.end_block is not None:
            val_updates = [
                (
                    u.pub_key_type,
                    u.pub_key_data,
                    u.power,
                    getattr(u, "bls_pub_key", b""),
                )
                for u in abci_responses.end_block.validator_updates
            ]

        new_state = self._update_state(
            state, block_id, block, abci_responses, val_updates
        )

        # persist the responses — WITH the merged validator/param
        # updates — BEFORE the app commit: if the (possibly background,
        # commit-pipelined) apply crashes after the app commits but
        # before the state save, the handshake rebuilds the identical
        # state record from these instead of double-executing the block
        # (Handshaker → update_state_from_responses)
        abci_responses.val_updates = list(val_updates)
        if (
            abci_responses.end_block is not None
            and abci_responses.end_block.consensus_param_updates
        ):
            abci_responses.param_updates = (
                abci_responses.end_block.consensus_param_updates
            )
        self._state_store.save_abci_responses(
            block.header.height, abci_responses.encode()
        )
        # durable block BEFORE app commit: with the write-behind store,
        # block H's save may still be queued — if the app committed
        # while the block was lost in a crash, restart would see
        # app_height > store_height, a state no replay path can fill
        # (re-driving H would double-execute it on the app). After this
        # barrier the durable order is always block >= app >= state,
        # and every crash window lands on an existing recovery path.
        # Normally a no-op (the save landed while txs executed); awaited
        # off-loop so a backlogged disk never stalls the event loop.
        await asyncio.get_running_loop().run_in_executor(
            None, self._block_store.wait_durable, block.header.height
        )
        # ABCI Commit → app hash for the NEXT block
        res = await self._app.commit()
        fail.fail_point()  # crash after app commit, before state save
        new_state.app_hash = res.data

        self._state_store.save(new_state)
        fail.fail_point()  # crash after state save

        if self._evpool:
            self._evpool.update(new_state, block.evidence)
        if res.retain_height > 0:
            try:
                # off-loop: pruning scans/deletes KV ranges and (on the
                # write-behind store) barriers on queued saves
                def _prune(h=res.retain_height):
                    self._block_store.prune_blocks(h)
                    self._state_store.prune_states(h)

                await asyncio.get_running_loop().run_in_executor(
                    None, _prune
                )
            except ValueError:
                pass

        if self._event_bus is not None:
            await self._event_bus.publish_new_block(block)
            await self._event_bus.publish_new_block_header(block.header)
            for i, tx in enumerate(block.data.txs):
                from ..crypto import tmhash

                r = abci_responses.deliver_txs[i]
                await self._event_bus.publish_tx(
                    block.header.height,
                    tmhash.sum(tx),
                    tx,
                    {
                        f"{e.type}.{k}": [v]
                        for e in r.events
                        for k, v in e.attributes.items()
                    },
                )
        return new_state

    async def _exec_block_on_app(
        self, state: State, block: Block
    ) -> ABCIResponses:
        last_commit_info = self._make_last_commit_info(state, block)
        byz = [
            {"height": ev.height(), "type": type(ev).__name__}
            for ev in block.evidence
        ]
        responses = ABCIResponses()
        responses.begin_block = await self._app.begin_block(
            block.header, last_commit_info, byz
        )
        for tx in block.data.txs:
            responses.deliver_txs.append(await self._app.deliver_tx(tx))
        responses.end_block = await self._app.end_block(block.header.height)
        return responses

    def _make_last_commit_info(self, state: State, block: Block):
        if block.last_commit is None or block.header.height == state.initial_height:
            return {"round": 0, "votes": []}
        # the signers are the validators of height-1 — during handshake
        # replay that is NOT state.last_validators (the handshake-time
        # set), so prefer the height-indexed store record
        vals = self._state_store.load_validators(block.header.height - 1)
        if vals is None:
            vals = state.last_validators
        votes = []
        for i, cs in enumerate(block.last_commit.signatures):
            val = vals.get_by_index(i) if vals else None
            if val is None:
                continue
            votes.append(
                {
                    "address": val.address,
                    "power": val.voting_power,
                    "signed_last_block": not cs.is_absent(),
                }
            )
        return {"round": block.last_commit.round, "votes": votes}

    def _exec_block_on_l2(
        self, block: Block, bls_datas: list[BlsData]
    ) -> list:
        """DeliverBlock + CommitBatch/PackCurrentBlock
        (reference ExecBlockOnL2Node :390-429)."""
        val_updates, _param_updates = self._l2.deliver_block(
            block.header.height,
            block.hash(),
            block.data.txs,
            block.data.l2_block_meta,
        )
        block_bytes = block.encode()
        if block.header.batch_hash:
            self._l2.commit_batch(block_bytes, bls_datas)
        else:
            self._l2.pack_current_block(block_bytes)
        return val_updates or []

    def _update_state(
        self,
        state: State,
        block_id: BlockID,
        block: Block,
        abci_responses: ABCIResponses,
        val_updates: list,
    ) -> State:
        """Builds the next State value (reference updateState :590)."""
        next_validators = state.next_validators.copy()
        last_height_vals_changed = state.last_height_validators_changed
        if val_updates:
            # rows are (type, data, power) or, QC plane, a 4th element:
            # the BLS pubkey riding the L2/end_block rotation
            changes = [
                Validator(
                    pubkey_from_type(u[0], u[1]),
                    u[2],
                    bls_pub_key=u[3] if len(u) > 3 else b"",
                )
                for u in val_updates
            ]
            next_validators.update_with_change_set(changes)
            last_height_vals_changed = block.header.height + 1 + 1

        params = state.consensus_params
        last_height_params_changed = state.last_height_consensus_params_changed
        if (
            abci_responses.end_block is not None
            and abci_responses.end_block.consensus_param_updates
        ):
            params = params.update(
                abci_responses.end_block.consensus_param_updates
            )
            last_height_params_changed = block.header.height + 1

        next_validators.increment_proposer_priority(1)
        return State(
            chain_id=state.chain_id,
            initial_height=state.initial_height,
            last_block_height=block.header.height,
            last_block_id=block_id,
            last_block_time_ns=block.header.time_ns,
            validators=state.next_validators.copy(),
            next_validators=next_validators,
            last_validators=state.validators.copy(),
            last_height_validators_changed=last_height_vals_changed,
            consensus_params=params,
            last_height_consensus_params_changed=last_height_params_changed,
            last_results_hash=abci_responses.results_hash(),
            app_hash=state.app_hash,  # replaced after ABCI Commit
        )

    def update_state_from_responses(
        self,
        state: State,
        block_id: BlockID,
        block: Block,
        responses: ABCIResponses,
        app_hash: bytes,
    ) -> State:
        """Handshake path for 'app committed, state save lost' (the
        window the pipelined background apply widens): rebuild and
        persist the state record from the height's SAVED ABCI responses
        and the app's reported hash, without double-executing the block
        against the app or re-delivering it to the L2 node (both already
        have it — apply order puts app commit after L2 delivery). The
        responses blob carries the merged validator/param updates apply
        actually used (saved pre-commit), so validator-change heights
        rebuild the identical next set (reference analog: mock-app
        replayBlock, replay.go:414-440)."""
        new_state = self._update_state(
            state, block_id, block, responses, responses.val_updates
        )
        new_state.app_hash = app_hash
        self._state_store.save(new_state)
        return new_state

    async def exec_commit_block(self, state: State, block: Block) -> bytes:
        """Replay helper: execute a stored block against the app without
        state bookkeeping (reference ExecCommitBlock :715)."""
        await self._exec_block_on_app(state, block)
        res = await self._app.commit()
        return res.data
