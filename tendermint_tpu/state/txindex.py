"""Tx + block event indexing.

Reference: state/txindex/ (TxIndexer iface, kv sink, IndexerService
subscribing to the EventBus — node/node.go:296-347) and state/indexer/
(BlockIndexer). Serves /tx, /tx_search, /block_search RPC queries.

Index layout (kv):
  tx hash        : "th/"  + tx_hash            -> TxResult blob
  tx event       : "te/"  + key=value/height/i -> tx_hash
  block event    : "be/"  + key=value/height   -> b""
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Optional

from ..crypto import tmhash
from ..libs import protoio as pio

_TX_HASH = b"th/"
_TX_EVENT = b"te/"
_BLOCK_EVENT = b"be/"


@dataclass
class TxResult:
    """Reference abci.TxResult (indexed per DeliverTx)."""

    height: int = 0
    index: int = 0
    tx: bytes = b""
    code: int = 0
    log: str = ""
    events: list = field(default_factory=list)  # (type, {k: v})

    def encode(self) -> bytes:
        out = (
            pio.field_varint(1, self.height)
            + pio.field_varint(2, self.index)
            + pio.field_bytes(3, self.tx)
            + pio.field_varint(4, self.code)
            + pio.field_bytes(5, self.log.encode())
        )
        for etype, attrs in self.events:
            body = pio.field_bytes(1, etype.encode())
            for k, v in attrs.items():
                body += pio.field_bytes(
                    2, pio.field_bytes(1, str(k).encode()) + pio.field_bytes(2, str(v).encode())
                )
            out += pio.field_bytes(6, body)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "TxResult":
        t = cls()
        for num, _wt, val in pio.iter_fields(data):
            if num == 1:
                t.height = val
            elif num == 2:
                t.index = val
            elif num == 3:
                t.tx = val
            elif num == 4:
                t.code = val
            elif num == 5:
                t.log = val.decode()
            elif num == 6:
                etype = ""
                attrs = {}
                for n2, _w2, v2 in pio.iter_fields(val):
                    if n2 == 1:
                        etype = v2.decode()
                    elif n2 == 2:
                        kv = pio.decode_fields(v2)
                        attrs[kv[1][0].decode()] = kv[2][0].decode()
                t.events.append((etype, attrs))
        return t


def _event_key(etype: str, k: str, v: str) -> str:
    return f"{etype}.{k}={v}"


class KVIndexer:
    """kv tx/block indexer (reference state/txindex/kv/kv.go)."""

    def __init__(self, kv):
        self._kv = kv

    # --- writing ------------------------------------------------------------

    def index_tx(self, result: TxResult) -> None:
        h = tmhash.sum(result.tx)
        self._kv.set(_TX_HASH + h, result.encode())
        for etype, attrs in result.events:
            for k, v in attrs.items():
                key = (
                    _TX_EVENT
                    + _event_key(etype, k, v).encode()
                    + b"/"
                    + result.height.to_bytes(8, "big")
                    + result.index.to_bytes(4, "big")
                )
                self._kv.set(key, h)

    def index_block(self, height: int, events: list) -> None:
        for etype, attrs in events:
            for k, v in attrs.items():
                key = (
                    _BLOCK_EVENT
                    + _event_key(etype, k, v).encode()
                    + b"/"
                    + height.to_bytes(8, "big")
                )
                self._kv.set(key, b"")

    # --- queries ------------------------------------------------------------

    def get_tx(self, tx_hash: bytes) -> Optional[TxResult]:
        data = self._kv.get(_TX_HASH + tx_hash)
        return TxResult.decode(data) if data is not None else None

    def search_txs(self, event_query: str, limit: int = 100) -> list[TxResult]:
        """event_query: "type.key=value" (the reference's query language
        subset used by tx_search)."""
        prefix = _TX_EVENT + event_query.encode() + b"/"
        out = []
        for _k, h in self._kv.iterate(prefix, prefix + b"\xff" * 13):
            tx = self.get_tx(h)
            if tx is not None:
                out.append(tx)
            if len(out) >= limit:
                break
        return out

    def search_blocks(self, event_query: str, limit: int = 100) -> list[int]:
        prefix = _BLOCK_EVENT + event_query.encode() + b"/"
        out = []
        for k, _v in self._kv.iterate(prefix, prefix + b"\xff" * 9):
            out.append(int.from_bytes(k[len(prefix):], "big"))
            if len(out) >= limit:
                break
        return out


class IndexerService:
    """Subscribes to the event bus and feeds the indexer
    (reference state/txindex/indexer_service.go: one subscription for tx
    events, one for new-block events, drained by a background task)."""

    SUBSCRIBER = "IndexerService"

    def __init__(self, indexer: KVIndexer, event_bus):
        self.indexer = indexer
        self.event_bus = event_bus
        self._tasks: list[asyncio.Task] = []
        # per-height tx counter to recover the tx index within its block
        self._height_counts: dict[int, int] = {}

    async def start(self) -> None:
        from ..types.event_bus import (
            EventNewBlock,
            EventTx,
            query_for_event,
        )

        tx_sub = self.event_bus.subscribe(
            self.SUBSCRIBER + "/tx", query_for_event(EventTx)
        )
        blk_sub = self.event_bus.subscribe(
            self.SUBSCRIBER + "/block", query_for_event(EventNewBlock)
        )
        self._tasks = [
            asyncio.create_task(self._drain_tx(tx_sub)),
            asyncio.create_task(self._drain_block(blk_sub)),
        ]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks = []

    @staticmethod
    def _events_from_bus(events: dict) -> list:
        """Flattened "type.key" -> [v] bus attributes back to event tuples."""
        out: dict[str, dict] = {}
        for k, vals in events.items():
            if "." not in k:
                continue
            etype, attr = k.split(".", 1)
            if etype in ("tm", "tx"):  # bus bookkeeping keys
                continue
            for v in vals:
                out.setdefault(etype, {})[attr] = v
        return [(etype, attrs) for etype, attrs in out.items()]

    async def _drain_tx(self, sub) -> None:
        while True:
            msg = await sub.next()
            height, tx_hash, tx = msg.data
            idx = self._height_counts.get(height, 0)
            self._height_counts[height] = idx + 1
            self._height_counts = {
                h: c for h, c in self._height_counts.items()
                if h >= height - 2
            }
            self.indexer.index_tx(
                TxResult(
                    height=height,
                    index=idx,
                    tx=tx,
                    events=self._events_from_bus(msg.events),
                )
            )

    async def _drain_block(self, sub) -> None:
        while True:
            msg = await sub.next()
            block = msg.data
            self.indexer.index_block(
                block.header.height, self._events_from_bus(msg.events)
            )
