"""Batched BLS12-381 G2 arithmetic on TPU — the pubkey-aggregation kernel.

SURVEY.md §2.2 row "BLS12-381 pairing / aggregate verify", second half:
aggregate-signature verification aggregates N public keys with N-1 G2
additions (crypto/bls_signatures.aggregate_public_keys; reference
blssignatures/bls_signatures.go:138-149 does the same point-add loop in
G1/G2). ops/bls_g1.py covers the G1 signature side; this module is the
G2 side — the same masked Jacobian formulas lifted to Fp2, with the
field layer coming from ops/vecfield.py (the parameterized form of
bls_g1's radix-2^8 scheme) and Fp2 = Fp[u]/(u^2 + 1) as Karatsuba over
limb pairs.

Representation: an Fp2 element is [..., 2, 48] (c0, c1); a G2 point is
[..., 3, 2, 48] Jacobian (X, Y, Z), infinity = Z == 0. Matches the host
oracle crypto/bls12_381.py (g2_add/g2_double) value-for-value after
canonicalization.

Routing contract (same as aggregate_signatures / ops/bls_g1): the
native C++ batch-affine sum leads where available; this kernel takes
over when the native library is unavailable (no compiler) or the
deployment pins aggregation on-device — the mesh-scale path, paying a
one-time compile. The exact serial host loop remains the final
fallback (crypto/bls_signatures.aggregate_public_keys).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import vecfield

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
NLIMBS = 48

fe = vecfield.make_field(P, NLIMBS)


# --- Fp2 = Fp[u]/(u^2 + 1), elements [..., 2, 48] -------------------------


def f2_from_host(c) -> np.ndarray:
    return np.stack([fe.from_int(c[0]), fe.from_int(c[1])])


def f2_to_host(x) -> tuple:
    arr = np.asarray(canonical2_jit(jnp.asarray(x)))
    return (fe.to_int(arr[..., 0, :]), fe.to_int(arr[..., 1, :]))


def f2_zeros(shape=()) -> jnp.ndarray:
    return jnp.zeros((*shape, 2, NLIMBS), dtype=jnp.int32)


def f2_add(a, b):
    return jnp.stack(
        [
            fe.add(a[..., 0, :], b[..., 0, :]),
            fe.add(a[..., 1, :], b[..., 1, :]),
        ],
        axis=-2,
    )


def f2_sub(a, b):
    return jnp.stack(
        [
            fe.sub(a[..., 0, :], b[..., 0, :]),
            fe.sub(a[..., 1, :], b[..., 1, :]),
        ],
        axis=-2,
    )


def f2_mul(a, b):
    """Karatsuba: 3 base-field muls."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    b0, b1 = b[..., 0, :], b[..., 1, :]
    t0 = fe.mul(a0, b0)
    t1 = fe.mul(a1, b1)
    m = fe.mul(fe.add(a0, a1), fe.add(b0, b1))
    return jnp.stack(
        [fe.sub(t0, t1), fe.sub(fe.sub(m, t0), t1)], axis=-2
    )


def f2_sqr(a):
    """(a0+a1)(a0-a1), 2*a0*a1 — 2 base-field muls."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    c0 = fe.mul(fe.add(a0, a1), fe.sub(a0, a1))
    c1 = fe.mul_small(fe.mul(a0, a1), 2)
    return jnp.stack([c0, c1], axis=-2)


def f2_mul_small(a, k: int):
    return jnp.stack(
        [fe.mul_small(a[..., 0, :], k), fe.mul_small(a[..., 1, :], k)],
        axis=-2,
    )


def f2_is_zero(a):
    return fe.is_zero(a[..., 0, :]) & fe.is_zero(a[..., 1, :])


def f2_canonical(a):
    return jnp.stack(
        [fe.canonical(a[..., 0, :]), fe.canonical(a[..., 1, :])], axis=-2
    )


canonical2_jit = jax.jit(f2_canonical)


# --- G2 (Jacobian over Fp2) ------------------------------------------------


def g2_identity(shape=()) -> jnp.ndarray:
    z = np.zeros((*shape, 3, 2, NLIMBS), dtype=np.int32)
    z[..., 1, 0, 0] = 1  # Y = 1 + 0u
    return jnp.asarray(z)


def g2_from_host(p) -> np.ndarray:
    return np.stack([f2_from_host(c) for c in p])


def g2_to_host(pt) -> tuple:
    return tuple(f2_to_host(np.asarray(pt)[i]) for i in range(3))


def g2_is_inf(p: jnp.ndarray) -> jnp.ndarray:
    return f2_is_zero(p[..., 2, :, :])


def g2_double(p: jnp.ndarray) -> jnp.ndarray:
    x, y, z = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    a = f2_sqr(x)
    b = f2_sqr(y)
    c = f2_sqr(b)
    xb = f2_add(x, b)
    d = f2_mul_small(f2_sub(f2_sub(f2_sqr(xb), a), c), 2)
    e = f2_mul_small(a, 3)
    f = f2_sqr(e)
    x3 = f2_sub(f, f2_mul_small(d, 2))
    y3 = f2_sub(f2_mul(e, f2_sub(d, x3)), f2_mul_small(c, 8))
    z3 = f2_mul_small(f2_mul(y, z), 2)
    bad = f2_is_zero(y) | f2_is_zero(z)
    out = jnp.stack([x3, y3, z3], axis=-3)
    return jnp.where(
        bad[..., None, None, None], g2_identity(x.shape[:-2]), out
    )


def g2_add(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Branch-free complete addition (masks for inf/equal/opposite),
    mirroring ops/bls_g1.g1_add one tower level up."""
    x1, y1, z1 = p[..., 0, :, :], p[..., 1, :, :], p[..., 2, :, :]
    x2, y2, z2 = q[..., 0, :, :], q[..., 1, :, :], q[..., 2, :, :]
    z1z1 = f2_sqr(z1)
    z2z2 = f2_sqr(z2)
    u1 = f2_mul(x1, z2z2)
    u2 = f2_mul(x2, z1z1)
    s1 = f2_mul(f2_mul(y1, z2), z2z2)
    s2 = f2_mul(f2_mul(y2, z1), z1z1)
    h = f2_sub(u2, u1)
    same_x = f2_is_zero(h)
    r2 = f2_sub(s2, s1)
    same_y = f2_is_zero(r2)
    h2 = f2_mul_small(h, 2)
    i = f2_sqr(h2)
    j = f2_mul(h, i)
    rr = f2_mul_small(r2, 2)
    v = f2_mul(u1, i)
    x3 = f2_sub(f2_sub(f2_sqr(rr), j), f2_mul_small(v, 2))
    y3 = f2_sub(
        f2_mul(rr, f2_sub(v, x3)), f2_mul_small(f2_mul(s1, j), 2)
    )
    z3 = f2_mul(
        f2_sub(f2_sub(f2_sqr(f2_add(z1, z2)), z1z1), z2z2), h
    )
    added = jnp.stack([x3, y3, z3], axis=-3)

    doubled = g2_double(p)
    p_inf = f2_is_zero(z1)
    q_inf = f2_is_zero(z2)
    out = added
    ident = g2_identity(x1.shape[:-2])
    out = jnp.where((same_x & ~same_y)[..., None, None, None], ident, out)
    out = jnp.where((same_x & same_y)[..., None, None, None], doubled, out)
    out = jnp.where(q_inf[..., None, None, None], p, out)
    out = jnp.where(p_inf[..., None, None, None], q, out)
    return out


g2_add_jit = jax.jit(g2_add)
g2_double_jit = jax.jit(g2_double)


def g2_aggregate_sharded(points, mesh) -> jnp.ndarray:
    """Point sum over a device mesh (G2/pubkey twin of
    ops/bls_g1.g1_aggregate_sharded): local tree per shard + an
    XOR-butterfly ppermute all-reduce with g2_add as the combiner —
    see ops/shard_reduce.py."""
    from . import shard_reduce

    return shard_reduce.aggregate_sharded(
        points, mesh, g2_add, np.asarray(g2_identity()), (3, 2, NLIMBS)
    )


def g2_aggregate(points: jnp.ndarray) -> jnp.ndarray:
    """Tree-reduce [B, 3, 2, 48] -> [3, 2, 48]: the device form of the
    aggregate_public_keys point-add loop (bls_signatures.go:138-149 in
    G2); log2(B) batched add levels, each level through the one jitted
    g2_add per shape (same compile-bounding rationale as g1_aggregate)."""
    b = points.shape[0]
    nb = 1 << max(1, (b - 1).bit_length())
    if nb != b:
        pad = jnp.broadcast_to(
            g2_identity(), (nb - b, 3, 2, NLIMBS)
        ).astype(points.dtype)
        points = jnp.concatenate([points, pad], axis=0)
    while points.shape[0] > 1:
        points = g2_add_jit(points[0::2], points[1::2])
    return points[0]
