"""Batched edwards25519 group arithmetic on TPU (JAX, limb vectors).

Role (SURVEY.md §2.2 row "ed25519 verify"): the reference verifies votes one
at a time through golang.org/x/crypto ed25519 (crypto/ed25519/ed25519.go:148-162
in /root/reference). Here the whole group layer is data-parallel: a point is a
``[..., 4, 32] int32`` array (X, Y, Z, T extended homogeneous coordinates, each
a radix-2^8 field element from ``ops.field25519``), and every operation maps
over arbitrary leading batch axes. No data-dependent control flow: failures
(bad decompression, wrong sign) come back as boolean masks, so a batch of
signatures is one straight-line XLA program that `vmap`/`shard_map` can tile
across a TPU mesh.

Formula choices (tpu-first):
- unified add: add-2008-hwcd-3 for a=-1 (complete — identity/doubling safe,
  so table entries need no special-casing),
- dedicated double: ref10 shape, 4S+4M,
- fixed-base scalar mult: 64x16 precomputed radix-16 table of the basepoint
  (no doublings at all — 63 batched gathers+adds),
- variable-base scalar mult: per-element 16-entry window table (14 adds) +
  256 doublings + 64 gather-adds, MSB-first.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import field25519 as fe
from ..crypto import ed25519 as host

NLIMBS = fe.NLIMBS

# 2*d mod p as a field constant (edwards d from the host reference impl).
_D = host.D
_D2 = (2 * host.D) % host.P
_SQRT_M1 = host.SQRT_M1


def _const(x: int) -> jnp.ndarray:
    return jnp.asarray(fe.from_int(x))


# --- representation -------------------------------------------------------


def identity(shape=()) -> jnp.ndarray:
    """The neutral element (0, 1, 1, 0) broadcast to [*shape, 4, 32]."""
    z = np.zeros((*shape, 4, NLIMBS), dtype=np.int32)
    z[..., 1, 0] = 1  # Y = 1
    z[..., 2, 0] = 1  # Z = 1
    return jnp.asarray(z)


def from_host_point(p: host.Point) -> np.ndarray:
    """Host helper: python-int extended point -> [4, 32] limbs."""
    return np.stack([fe.from_int(c) for c in p])


def neg(p: jnp.ndarray) -> jnp.ndarray:
    """-(X, Y, Z, T) = (-X, Y, Z, -T)."""
    x, y, z, t = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    return jnp.stack([fe.neg(x), y, z, fe.neg(t)], axis=-2)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """cond ? a : b with cond of shape [...] broadcast over (4, 32)."""
    return jnp.where(cond[..., None, None], a, b)


# --- group law ------------------------------------------------------------


def add(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Complete unified addition (add-2008-hwcd-3, a=-1)."""
    x1, y1, z1, t1 = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    x2, y2, z2, t2 = q[..., 0, :], q[..., 1, :], q[..., 2, :], q[..., 3, :]
    a = fe.mul(fe.sub(y1, x1), fe.sub(y2, x2))
    b = fe.mul(fe.add(y1, x1), fe.add(y2, x2))
    c = fe.mul(fe.mul(t1, t2), jnp.asarray(fe.from_int(_D2)))
    d = fe.mul_small(fe.mul(z1, z2), 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return jnp.stack(
        [fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h)], axis=-2
    )


def double(p: jnp.ndarray) -> jnp.ndarray:
    """Dedicated doubling (ref10 ge_p2_dbl shape), 4S+4M."""
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    xx = fe.sqr(x1)
    yy = fe.sqr(y1)
    b = fe.mul_small(fe.sqr(z1), 2)
    aa = fe.sqr(fe.add(x1, y1))
    y3 = fe.add(yy, xx)  # YY + XX
    z3 = fe.sub(yy, xx)  # YY - XX
    x3 = fe.sub(aa, y3)  # 2XY
    t3 = fe.sub(b, z3)  # 2ZZ - (YY - XX)
    return jnp.stack(
        [fe.mul(x3, t3), fe.mul(y3, z3), fe.mul(z3, t3), fe.mul(x3, y3)],
        axis=-2,
    )


# --- encoding -------------------------------------------------------------


def compress(p: jnp.ndarray) -> jnp.ndarray:
    """Canonical 32-byte encoding: y with the sign(x) bit on top. [..., 32] u8."""
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    zinv = fe.invert(z)
    xa = fe.canonical(fe.mul(x, zinv))
    ya = fe.canonical(fe.mul(y, zinv))
    sign = xa[..., 0] & 1
    ya = ya.at[..., 31].add(sign << 7)
    return ya.astype(jnp.uint8)


def decompress(b: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Batched point decompression.

    b: [..., 32] uint8. Returns (point [..., 4, 32], valid [...] bool).
    Rejects (mask False): y >= p (non-canonical), x^2 with no square root,
    x = 0 with sign bit set. Mirrors the host oracle `_recover_x`
    (crypto/ed25519 semantics of the reference, crypto/ed25519/ed25519.go).
    """
    b = b.astype(jnp.int32)
    sign = b[..., 31] >> 7
    y = b.at[..., 31].add(-(sign << 7))  # clear bit 255
    # canonical check: y < p (limb-wise compare against P, big-endian scan)
    p_l = jnp.asarray(fe.P_LIMBS)
    diff = y - p_l
    nz = diff != 0
    idx = (NLIMBS - 1) - jnp.argmax(nz[..., ::-1], axis=-1)
    ms = jnp.take_along_axis(diff, idx[..., None], axis=-1)[..., 0]
    y_lt_p = jnp.where(jnp.any(nz, axis=-1), ms < 0, False)

    yy = fe.sqr(y)
    u = fe.sub(yy, fe.ones(y.shape[:-1]))  # y^2 - 1
    v = fe.add(fe.mul(yy, _const(_D)), fe.ones(y.shape[:-1]))  # d y^2 + 1
    # x = u v^3 (u v^7)^((p-5)/8)  — one exponentiation, then fixups.
    v3 = fe.mul(fe.sqr(v), v)
    v7 = fe.mul(fe.sqr(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow22523(fe.mul(u, v7)))
    vx2 = fe.mul(v, fe.sqr(x))
    ok_direct = fe.eq(vx2, u)
    ok_flipped = fe.eq(vx2, fe.neg(u))
    x = fe.select(ok_flipped, fe.mul(x, _const(_SQRT_M1)), x)
    has_root = ok_direct | ok_flipped

    x_is_zero = fe.is_zero(x)
    sign_ok = ~(x_is_zero & (sign == 1))
    # conditional negate to match the sign bit
    x = fe.select((fe.parity(x) != sign) & ~x_is_zero, fe.neg(x), x)

    valid = y_lt_p & has_root & sign_ok
    pt = jnp.stack([x, y, fe.ones(y.shape[:-1]), fe.mul(x, y)], axis=-2)
    return pt, valid


# --- scalars --------------------------------------------------------------


def nibbles(scalar_bytes: jnp.ndarray) -> jnp.ndarray:
    """[..., 32] u8 little-endian scalar -> [..., 64] int32 radix-16 digits
    (least-significant first)."""
    s = scalar_bytes.astype(jnp.int32)
    lo = s & 15
    hi = s >> 4
    return jnp.stack([lo, hi], axis=-1).reshape(*s.shape[:-1], 64)


# --- fixed-base table (basepoint) -----------------------------------------

_BASE_TABLE_NP: np.ndarray | None = None


def _base_table() -> np.ndarray:
    """T[i, j] = [j * 16^i]B as [64, 16, 4, 32] int32, built on host once."""
    global _BASE_TABLE_NP
    if _BASE_TABLE_NP is None:
        rows = []
        row = [host.IDENTITY]
        for j in range(1, 16):
            row.append(host.point_add(row[-1], host.BASEPOINT))
        for _ in range(64):
            rows.append([from_host_point(p) for p in row])
            row = [
                host.point_double(
                    host.point_double(host.point_double(host.point_double(p)))
                )
                for p in row
            ]
        _BASE_TABLE_NP = np.asarray(rows, dtype=np.int32)
    return _BASE_TABLE_NP


def scalar_mult_base(scalar_bytes: jnp.ndarray) -> jnp.ndarray:
    """[s]B for s: [..., 32] u8 (little-endian, < 2^256). No doublings:
    sum over 64 radix-16 digit rows of the precomputed basepoint table."""
    digs = nibbles(scalar_bytes)  # [..., 64] LSB-first
    table = jnp.asarray(_base_table())  # [64, 16, 4, 32]

    def body(i, acc):
        row = jax.lax.dynamic_index_in_dim(table, i, keepdims=False)
        entry = jnp.take(row, digs[..., i], axis=0)  # [..., 4, 32]
        return add(acc, entry)

    return jax.lax.fori_loop(0, 64, body, identity(digs.shape[:-1]))


def scalar_mult_var(scalar_bytes: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """[s]P batched variable-base: per-element radix-16 window table.

    scalar_bytes: [..., 32] u8; p: [..., 4, 32]. 14 adds for the table,
    then 64 iterations of (4 doublings + gather + add), MSB-first.
    """
    digs = nibbles(scalar_bytes)  # [..., 64]
    batch_shape = digs.shape[:-1]

    # window table [..., 16, 4, 32]: 0, P, 2P, ..., 15P
    entries = [identity(batch_shape), p]
    for _ in range(14):
        entries.append(add(entries[-1], p))
    table = jnp.stack(entries, axis=-3)

    def body(i, acc):
        acc = double(double(double(double(acc))))
        dig = digs[..., 63 - i]  # MSB-first
        entry = jnp.take_along_axis(
            table, dig[..., None, None, None], axis=-3
        ).squeeze(-3)
        return add(acc, entry)

    return jax.lax.fori_loop(0, 64, body, identity(batch_shape))


def double_scalar_mult_base(
    s_bytes: jnp.ndarray, k_bytes: jnp.ndarray, a: jnp.ndarray
) -> jnp.ndarray:
    """[s]B + [k]A — the ed25519 verification combination."""
    return add(scalar_mult_base(s_bytes), scalar_mult_var(k_bytes, a))
