"""Batched edwards25519 group arithmetic on TPU (JAX, limb vectors).

Role (SURVEY.md §2.2 row "ed25519 verify"): the reference verifies votes one
at a time through golang.org/x/crypto ed25519 (crypto/ed25519/ed25519.go:148-162
in /root/reference). Here the whole group layer is data-parallel: a point is a
``[..., 4, 32] int32`` array (X, Y, Z, T extended homogeneous coordinates, each
a radix-2^8 field element from ``ops.field25519``), and every operation maps
over arbitrary leading batch axes. No data-dependent control flow: failures
(bad decompression, wrong sign) come back as boolean masks, so a batch of
signatures is one straight-line XLA program that `vmap`/`shard_map` can tile
across a TPU mesh.

v2 structure (this file's key TPU-first trick): every group operation packs
its four independent field multiplications into ONE batched `fe.mul` over a
stacked [..., 4, 32] operand — the backend sees 4x fewer, 4x larger ops
(dispatch/compile cost drops ~4x; the arithmetic is identical). Addends use
ref10's *cached* form (Y-X, Y+X, 2d*T, 2Z) so a complete addition is exactly
2 packed multiplications:

    add:    [A,B,C,D] = mul([Y1-X1, Y1+X1, T1, Z1], cached)
            [X3,Y3,Z3,T3] = mul([E,G,F,E], [F,H,G,H])
    double: [XX,YY,ZZ,AA] = sqr([X, Y, Z, X+Y])
            [X3,Y3,Z3,T3] = mul([x,y,z,x], [t,z,t,y])

Formula provenance: add-2008-hwcd-3 (complete, a=-1) and ref10 ge_p2_dbl.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import field25519 as fe
from ..crypto import ed25519 as host

NLIMBS = fe.NLIMBS

_D = host.D
_D2 = (2 * host.D) % host.P
_SQRT_M1 = host.SQRT_M1


def _const(x: int) -> jnp.ndarray:
    return jnp.asarray(fe.from_int(x))


# --- representation -------------------------------------------------------


def identity(shape=()) -> jnp.ndarray:
    """The neutral element (0, 1, 1, 0) broadcast to [*shape, 4, 32]."""
    z = np.zeros((*shape, 4, NLIMBS), dtype=np.int32)
    z[..., 1, 0] = 1  # Y = 1
    z[..., 2, 0] = 1  # Z = 1
    return jnp.asarray(z)


def from_host_point(p: host.Point) -> np.ndarray:
    """Host helper: python-int extended point -> [4, 32] limbs."""
    return np.stack([fe.from_int(c) for c in p])


def from_host_point_cached(p: host.Point) -> np.ndarray:
    """Host helper: python-int extended point -> cached [4, 32] limbs."""
    x, y, z, t = p
    P = host.P
    return np.stack(
        [
            fe.from_int((y - x) % P),
            fe.from_int((y + x) % P),
            fe.from_int(t * _D2 % P),
            fe.from_int(2 * z % P),
        ]
    )


def neg(p: jnp.ndarray) -> jnp.ndarray:
    """-(X, Y, Z, T) = (-X, Y, Z, -T)."""
    x, y, z, t = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    return jnp.stack([fe.neg(x), y, z, fe.neg(t)], axis=-2)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """cond ? a : b with cond of shape [...] broadcast over (4, 32)."""
    return jnp.where(cond[..., None, None], a, b)


def to_cached(p: jnp.ndarray) -> jnp.ndarray:
    """Extended -> cached (Y-X, Y+X, 2d*T, 2Z); one packed mul.

    The packed mul computes [2d*T, 2*Z] alongside nothing else (2 lanes
    padded) — callers converting whole tables amortize it over the entry
    axis instead.
    """
    x, y, z, t = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    batch = p.shape[:-2]
    ab = jnp.stack([t, z], axis=-2)
    cd = jnp.stack(
        [
            jnp.broadcast_to(_const(_D2), (*batch, NLIMBS)),
            jnp.broadcast_to(jnp.asarray(fe.from_int(2)), (*batch, NLIMBS)),
        ],
        axis=-2,
    )
    td2_z2 = fe.mul(ab, cd)
    return jnp.stack(
        [fe.sub(y, x), fe.add(y, x), td2_z2[..., 0, :], td2_z2[..., 1, :]],
        axis=-2,
    )


# --- group law (packed) ---------------------------------------------------


def add_cached(p: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    """Complete unified addition p + c with c in cached form.

    2 packed muls (add-2008-hwcd-3 with the 2d*T / 2Z factors folded into
    the cached operand, as ref10 ge_add)."""
    x1, y1, z1, t1 = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    lhs = jnp.stack([fe.sub(y1, x1), fe.add(y1, x1), t1, z1], axis=-2)
    abcd = fe.mul(lhs, c)
    a, b = abcd[..., 0, :], abcd[..., 1, :]
    cc, d = abcd[..., 2, :], abcd[..., 3, :]
    e = fe.sub(b, a)
    f = fe.sub(d, cc)
    g = fe.add(d, cc)
    h = fe.add(b, a)
    lo = jnp.stack([e, g, f, e], axis=-2)
    hi = jnp.stack([f, h, g, h], axis=-2)
    return fe.mul(lo, hi)


def add(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Complete unified addition of two extended points."""
    return add_cached(p, to_cached(q))


def double(p: jnp.ndarray) -> jnp.ndarray:
    """Dedicated doubling (ref10 ge_p2_dbl shape); 2 packed muls."""
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    sq_in = jnp.stack([x1, y1, z1, fe.add(x1, y1)], axis=-2)
    sq = fe.mul(sq_in, sq_in)
    xx, yy, zz, aa = (
        sq[..., 0, :],
        sq[..., 1, :],
        sq[..., 2, :],
        sq[..., 3, :],
    )
    y3 = fe.add(yy, xx)
    z3 = fe.sub(yy, xx)
    x3 = fe.sub(aa, y3)
    t3 = fe.sub(fe.mul_small(zz, 2), z3)
    lo = jnp.stack([x3, y3, z3, x3], axis=-2)
    hi = jnp.stack([t3, z3, t3, y3], axis=-2)
    return fe.mul(lo, hi)


# --- encoding -------------------------------------------------------------


def compress(p: jnp.ndarray) -> jnp.ndarray:
    """Canonical 32-byte encoding: y with the sign(x) bit on top. [..., 32] u8.

    For a plain batch of points ([B, 4, 32]) the Z inversions use
    Montgomery's trick (`fe.invert_many`): one Fermat inversion for the
    whole batch instead of one per element."""
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    zinv = fe.invert_many(z) if p.ndim == 3 else fe.invert(z)
    xy = fe.mul(jnp.stack([x, y], axis=-2), zinv[..., None, :])
    xa = fe.canonical(xy[..., 0, :])
    ya = fe.canonical(xy[..., 1, :])
    sign = xa[..., 0] & 1
    ya = ya.at[..., 31].add(sign << 7)
    return ya.astype(jnp.uint8)


def decompress(b: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Batched point decompression.

    b: [..., 32] uint8. Returns (point [..., 4, 32], valid [...] bool).
    Rejects (mask False): y >= p (non-canonical), x^2 with no square root,
    x = 0 with sign bit set. Mirrors the host oracle `_recover_x`
    (crypto/ed25519 semantics of the reference, crypto/ed25519/ed25519.go).
    """
    b = b.astype(jnp.int32)
    sign = b[..., 31] >> 7
    y = b.at[..., 31].add(-(sign << 7))  # clear bit 255
    # canonical check: y < p (limb-wise compare against P, big-endian scan)
    p_l = jnp.asarray(fe.P_LIMBS)
    diff = y - p_l
    nz = diff != 0
    idx = (NLIMBS - 1) - jnp.argmax(nz[..., ::-1], axis=-1)
    ms = jnp.take_along_axis(diff, idx[..., None], axis=-1)[..., 0]
    y_lt_p = jnp.where(jnp.any(nz, axis=-1), ms < 0, False)

    yy = fe.sqr(y)
    u = fe.sub(yy, fe.ones(y.shape[:-1]))  # y^2 - 1
    v = fe.add(fe.mul(yy, _const(_D)), fe.ones(y.shape[:-1]))  # d y^2 + 1
    # x = u v^3 (u v^7)^((p-5)/8)  — one exponentiation, then fixups.
    v3 = fe.mul(fe.sqr(v), v)
    v7 = fe.mul(fe.sqr(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow22523(fe.mul(u, v7)))
    vx2 = fe.mul(v, fe.sqr(x))
    ok_direct = fe.eq(vx2, u)
    ok_flipped = fe.eq(vx2, fe.neg(u))
    x = fe.select(ok_flipped, fe.mul(x, _const(_SQRT_M1)), x)
    has_root = ok_direct | ok_flipped

    x_is_zero = fe.is_zero(x)
    sign_ok = ~(x_is_zero & (sign == 1))
    # conditional negate to match the sign bit
    x = fe.select((fe.parity(x) != sign) & ~x_is_zero, fe.neg(x), x)

    valid = y_lt_p & has_root & sign_ok
    pt = jnp.stack([x, y, fe.ones(y.shape[:-1]), fe.mul(x, y)], axis=-2)
    return pt, valid


# --- scalars --------------------------------------------------------------


def nibbles(scalar_bytes: jnp.ndarray) -> jnp.ndarray:
    """[..., 32] u8 little-endian scalar -> [..., 64] int32 radix-16 digits
    (least-significant first)."""
    s = scalar_bytes.astype(jnp.int32)
    lo = s & 15
    hi = s >> 4
    return jnp.stack([lo, hi], axis=-1).reshape(*s.shape[:-1], 64)


# --- window tables --------------------------------------------------------


def window_table(p: jnp.ndarray) -> jnp.ndarray:
    """Per-element radix-16 window table in cached form:
    [..., 16, 4, 32] = cached(0, P, 2P, ..., 15P).

    Built with 14 adds + one packed to_cached over the entry axis; this is
    also the unit the BatchVerifier caches per validator pubkey (the same
    validators sign every height — SURVEY.md §3.3)."""
    entries = [identity(p.shape[:-2]), p]
    for _ in range(14):
        entries.append(add(entries[-1], p))
    ext = jnp.stack(entries, axis=-3)  # [..., 16, 4, 32]
    return to_cached(ext)


def _select_entry(table: jnp.ndarray, dig: jnp.ndarray) -> jnp.ndarray:
    """table: [..., 16, 4, 32] cached; dig: [...] in [0, 16).

    Accepts narrow-dtype tables (the persistent caches store canonical
    uint8 limbs — 4x less gather traffic / cache memory); the widen back
    to int32 fuses into the consuming add."""
    return jnp.take_along_axis(
        table, dig[..., None, None, None], axis=-3
    ).squeeze(-3).astype(jnp.int32)


# --- fixed-base table (basepoint) -----------------------------------------

_BASE_TABLE_NP: np.ndarray | None = None


def _base_table() -> np.ndarray:
    """T[i, j] = cached([j * 256^i]B) as [32, 256, 4, 32] int32 (host,
    once). Radix-256: the scalar's bytes ARE the digits, and [s]B is 32
    cached adds (vs 64 for radix-16) — the table is host-precomputed so
    the wider window costs only one-time build and 4 MiB of constants."""
    global _BASE_TABLE_NP
    if _BASE_TABLE_NP is None:
        rows = []
        base = host.BASEPOINT
        for _ in range(32):
            row = [host.IDENTITY]
            for _ in range(255):
                row.append(host.point_add(row[-1], base))
            rows.append([from_host_point_cached(p) for p in row])
            for _ in range(8):
                base = host.point_double(base)
        # canonical host values < 256: uint8 storage (1 MiB, not 4)
        _BASE_TABLE_NP = np.asarray(rows, dtype=np.uint8)
    return _BASE_TABLE_NP


def scalar_mult_base(scalar_bytes: jnp.ndarray) -> jnp.ndarray:
    """[s]B for s: [..., 32] u8 (little-endian, < 2^256). No doublings:
    sum over the 32 byte-digit rows of the precomputed basepoint table.

    The host-built table limbs are canonical (< 256), so it ships to the
    device as uint8 (1 MiB instead of 4); the loop accumulator round-trips
    through int16 at iteration boundaries (loose limbs < 2^9) — both
    bit-exact, both halving the traffic the executor bills per iteration
    (PERF_ANALYSIS.md)."""
    digs = scalar_bytes.astype(jnp.int32)  # [..., 32] LSB-first bytes
    table = jnp.asarray(_base_table())  # [32, 256, 4, 32] uint8

    def body(i, acc):
        row = jax.lax.dynamic_index_in_dim(table, i, keepdims=False)
        entry = jnp.take(row, digs[..., i], axis=0)  # [..., 4, 32] u8
        return add_cached(
            acc.astype(jnp.int32), entry.astype(jnp.int32)
        ).astype(jnp.int16)

    init = identity(digs.shape[:-1]).astype(jnp.int16)
    return jax.lax.fori_loop(0, 32, body, init).astype(jnp.int32)


def big_window_table(p: jnp.ndarray) -> jnp.ndarray:
    """Per-element fixed-window table T[i, j] = cached([j * 16^i]P):
    [..., 64, 16, 4, 32] int32 (512 KiB per element in loose form; the
    persistent caches store it canonicalized as uint8, 128 KiB/key).

    The doubling-free analogue of `_base_table` for a *variable* base: with
    it, [k]P is 64 cached adds and zero doublings (`scalar_mult_var_bigtable`)
    — the same shape the reference's serial verify can never reach because it
    processes one signature at a time (crypto/ed25519/ed25519.go:148-162 in
    /root/reference). Build cost (≈63×4 packed doublings over the 16-entry
    axis) amortizes over a validator's lifetime: consensus re-verifies the
    same pubkeys every height (SURVEY.md §3.3).
    """
    batch = p.shape[:-2]
    # row of extended points [..., 16, 4, 32]: 0, P, ..., 15P
    entries = [identity(batch), p]
    for _ in range(14):
        entries.append(add(entries[-1], p))
    row = jnp.stack(entries, axis=-3)

    # rows[i] = [16^i] * row (63 scan steps; the last row is emitted
    # without paying a final wasted doubling round)
    def scan_body(row, _):
        nxt = double(double(double(double(row))))
        return nxt, to_cached(row)

    last, rows = jax.lax.scan(scan_body, row, None, length=63)
    rows = jnp.concatenate([rows, to_cached(last)[None]], axis=0)
    # rows: [64, ..., 16, 4, 32] -> [..., 64, 16, 4, 32]
    return jnp.moveaxis(rows, 0, -4)


def scalar_mult_var_bigtable(
    scalar_bytes: jnp.ndarray, table: jnp.ndarray
) -> jnp.ndarray:
    """[s]P from a prebuilt fixed-window table ([..., 64, 16, 4, 32]).

    64 cached adds, no doublings — 2 packed muls per digit vs the 10 of
    `scalar_mult_var_table`."""
    digs = nibbles(scalar_bytes)  # [..., 64] LSB-first
    batch_shape = digs.shape[:-1]

    def body(i, acc):
        row = jax.lax.dynamic_index_in_dim(
            table, i, axis=table.ndim - 4, keepdims=False
        )  # [..., 16, 4, 32]
        return add_cached(acc, _select_entry(row, digs[..., i]))

    return jax.lax.fori_loop(0, 64, body, identity(batch_shape))


def scalar_mult_var_bigcache(
    scalar_bytes: jnp.ndarray,  # [B, 32] u8
    tables_cache: jnp.ndarray,  # [cap, 64, 16, 4, 32] fixed-window tables
    idx: jnp.ndarray,  # [B] int32 row index into the cache
) -> jnp.ndarray:
    """[s]·T[idx] against a shared device-resident table cache.

    Gathers one window-row slice per iteration ([cap, 16, 4, 32] sliced,
    then a [B]-gather of the selected digit entries) so the full per-key
    per-key tables are never materialized per batch element.

    Measured dead end (r3, keep for the record): splitting the 64
    sequential window-adds into C independent chains + a log-tree merge
    (depth 64 -> 64/C + log2 C) REGRESSED 3x on the harness executor
    (B=8192: 137 ms -> 402 ms) — the per-step multi-axis gather
    tables[idx, w, dig] over [B, C] lowers to a generalized gather far
    costlier than this loop's slice + single-axis gather. Latency here is
    gather-bound, not dispatch-depth-bound; revisit only with a Pallas
    kernel that keeps the window tables in VMEM."""
    digs = nibbles(scalar_bytes)  # [B, 64] LSB-first

    def body(i, acc16):
        row = jax.lax.dynamic_index_in_dim(
            tables_cache, i, axis=1, keepdims=False
        )  # [cap, 16, 4, 32]
        ent = row[idx, digs[..., i]].astype(jnp.int32)  # [B, 4, 32]
        return add_cached(acc16.astype(jnp.int32), ent).astype(jnp.int16)

    init = identity(digs.shape[:-1]).astype(jnp.int16)
    return jax.lax.fori_loop(0, 64, body, init).astype(jnp.int32)


def scalar_mult_var_bigcache_mxu(
    scalar_bytes: jnp.ndarray,  # [B, 32] u8
    tables_cache: jnp.ndarray,  # [cap, 64, 16, 4, 32] fixed-window tables
    idx: jnp.ndarray,  # [B] int32 row index into the cache
) -> jnp.ndarray:
    """scalar_mult_var_bigcache with the per-window gather recast as a
    ONE-HOT MATMUL — the MXU-native formulation of a table lookup.

    Per window w, the selected entry is
        onehot[b, idx[b]*16 + digs[b,w]] @ tables[:, w].reshape(cap*16, 128)
    i.e. a [B, cap*16] x [cap*16, 128] f32 matmul whose left operand has
    one 1 per row. Exactness: persistent-cache tables are canonical uint8
    limbs (< 256) and in-batch tables are loose (< 2^9) — either way any
    value < 2^24 is exact in f32; bf16 would NOT be safe.
    On MXU silicon this turns the generalized gather — the measured
    bottleneck of the fori_loop path — into systolic-array work the chip
    is built for; on this harness's executor (~0.1 TFLOP/s effective) the
    extra FLOPs dominate instead, so BatchVerifier selects it only when
    TM_TPU_MXU_GATHER=1. Verified bit-identical to the gather path in
    tests/test_ops_curve25519.py.
    """
    digs = nibbles(scalar_bytes)  # [B, 64] LSB-first
    cap = tables_cache.shape[0]
    flat = tables_cache.astype(jnp.float32).reshape(cap, 64, 16, 128)

    def body(i, acc):
        tab_w = jax.lax.dynamic_index_in_dim(
            flat, i, axis=1, keepdims=False
        ).reshape(cap * 16, 128)
        sel = idx * 16 + digs[..., i]  # [B] combined row index
        onehot = (
            sel[:, None] == jnp.arange(cap * 16, dtype=jnp.int32)[None, :]
        ).astype(jnp.float32)
        ent = (
            jnp.dot(onehot, tab_w, precision=jax.lax.Precision.HIGHEST)
            .astype(jnp.int32)
            .reshape(-1, 4, 32)
        )
        return add_cached(acc, ent)

    return jax.lax.fori_loop(0, 64, body, identity(digs.shape[:-1]))


def scalar_mult_var_table(
    scalar_bytes: jnp.ndarray, table: jnp.ndarray
) -> jnp.ndarray:
    """[s]P from a prebuilt cached window table ([..., 16, 4, 32]).

    64 iterations of (4 doublings + select + add_cached), MSB-first —
    10 packed muls per iteration."""
    digs = nibbles(scalar_bytes)  # [..., 64]
    batch_shape = digs.shape[:-1]

    def body(i, acc16):
        acc = double(double(double(double(acc16.astype(jnp.int32)))))
        dig = digs[..., 63 - i]  # MSB-first
        # int16 at the loop boundary: loose limbs < 2^9 make the
        # round-trip exact, and halve the materialized carry traffic
        return add_cached(acc, _select_entry(table, dig)).astype(jnp.int16)

    init = identity(batch_shape).astype(jnp.int16)
    return jax.lax.fori_loop(0, 64, body, init).astype(jnp.int32)


def scalar_mult_var(scalar_bytes: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """[s]P batched variable-base (builds the window table first)."""
    return scalar_mult_var_table(scalar_bytes, window_table(p))


def double_scalar_mult_base(
    s_bytes: jnp.ndarray, k_bytes: jnp.ndarray, a: jnp.ndarray
) -> jnp.ndarray:
    """[s]B + [k]A — the ed25519 verification combination."""
    return add(scalar_mult_base(s_bytes), scalar_mult_var(k_bytes, a))


def double_scalar_mult_base_table(
    s_bytes: jnp.ndarray, k_bytes: jnp.ndarray, a_table: jnp.ndarray
) -> jnp.ndarray:
    """[s]B + [k]A with A's window table prebuilt (the cached-pubkey hot
    path: no decompression, no table build — SURVEY.md §3.3's workload
    re-verifies the same validators every height)."""
    return add(
        scalar_mult_base(s_bytes),
        scalar_mult_var_table(k_bytes, a_table),
    )
