"""Batched SHA-512 on TPU + the ed25519 challenge reduction mod L.

SURVEY.md §2.2 row "SHA-512": the per-signature challenge
k = SHA-512(R || A || M) mod L was the last host-side per-item loop in
the verify pipeline (ops/ed25519_batch.py takes prehashed k). This
kernel computes it on device: TPUs have no 64-bit lanes, so a 64-bit
word is an (hi, lo) uint32 pair; rotations/shifts are static-index
pair shuffles and additions carry via unsigned compare.

Same ragged-batch convention as ops/sha256.py: host-prepadded
[B, NBLK*128] buffers + per-row block counts, masked state updates.

`challenge_batch` = SHA-512 + exact reduction mod L (canonical — the
cofactorless check must use k mod L bit-for-bit like the host oracle
crypto/ed25519.py:challenge; a k' ≡ k (mod L) but > L would diverge on
adversarial keys with small-order components).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

L = (1 << 252) + 27742317777372353535851937790883648493

_K64 = [
    0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F,
    0xE9B5DBA58189DBBC, 0x3956C25BF348B538, 0x59F111F1B605D019,
    0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118, 0xD807AA98A3030242,
    0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
    0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235,
    0xC19BF174CF692694, 0xE49B69C19EF14AD2, 0xEFBE4786384F25E3,
    0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65, 0x2DE92C6F592B0275,
    0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
    0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F,
    0xBF597FC7BEEF0EE4, 0xC6E00BF33DA88FC2, 0xD5A79147930AA725,
    0x06CA6351E003826F, 0x142929670A0E6E70, 0x27B70A8546D22FFC,
    0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
    0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6,
    0x92722C851482353B, 0xA2BFE8A14CF10364, 0xA81A664BBC423001,
    0xC24B8B70D0F89791, 0xC76C51A30654BE30, 0xD192E819D6EF5218,
    0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
    0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99,
    0x34B0BCB5E19B48A8, 0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB,
    0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3, 0x748F82EE5DEFB2FC,
    0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
    0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915,
    0xC67178F2E372532B, 0xCA273ECEEA26619C, 0xD186B8C721C0C207,
    0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178, 0x06F067AA72176FBA,
    0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
    0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC,
    0x431D67C49C100D4C, 0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A,
    0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
]
_KH = np.array([k >> 32 for k in _K64], dtype=np.uint32)
_KL = np.array([k & 0xFFFFFFFF for k in _K64], dtype=np.uint32)

_H0_64 = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B,
    0xA54FF53A5F1D36F1, 0x510E527FADE682D1, 0x9B05688C2B3E6C1F,
    0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]
_H0H = np.array([h >> 32 for h in _H0_64], dtype=np.uint32)
_H0L = np.array([h & 0xFFFFFFFF for h in _H0_64], dtype=np.uint32)


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(jnp.uint32)
    return ah + bh + carry, lo


def _rotr64(h, l, n: int):
    if n == 0:
        return h, l
    if n < 32:
        return (h >> n) | (l << (32 - n)), (l >> n) | (h << (32 - n))
    if n == 32:
        return l, h
    m = n - 32
    return (l >> m) | (h << (32 - m)), (h >> m) | (l << (32 - m))


def _shr64(h, l, n: int):
    if n < 32:
        return h >> n, (l >> n) | (h << (32 - n))
    return jnp.zeros_like(h), h >> (n - 32)


def _xor3(a, b, c):
    return a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1]


def _use_scan_rounds() -> bool:
    """Pick the compression-loop structure by backend at trace time.

    The straight-line 80-round body is right for the TPU executor
    (PERF_ANALYSIS §1: deep fused elementwise chains are ~free there,
    while loop iterations are billed per iteration). But the XLA:CPU
    pipeline on a 1-core CI box takes HOURS on the ~5k-op unrolled body
    (measured r5: `challenge_batch` alone exceeded 15 min of compile;
    the fused verify program exceeded 100 min — vs seconds for the scan
    form). Identical uint32 math either way; tests/test_ops_sha pins
    the active form against hashlib, tests/test_ops_sha pins the two
    forms against each other in eager mode, and the TPU form is
    exercised by every bench/production run on the chip.

    TM_TPU_SHA_SCAN=0/1 overrides the backend heuristic — the heuristic
    reads the PROCESS-wide default backend, so hashing pinned to CPU on
    a TPU host (e.g. under jax.default_device) would otherwise pick the
    unrolled body and hit the slow CPU compile."""
    import os

    forced = os.environ.get("TM_TPU_SHA_SCAN")
    if forced is not None:
        return forced == "1"
    return jax.default_backend() == "cpu"


def _compress512_scan(sh, sl, wh, wl):
    """Scan-form SHA-512 compression (see _use_scan_rounds). Bit-exact
    with _compress512: same schedule recurrence and round function,
    expressed as two lax.scans (~60-op bodies) instead of straight-line
    code."""
    # message schedule: roll a 16-word window, emitting w16..w79
    def sched_step(win, _):
        h16, l16 = win  # [..., 16] each; index 0 == w[i-16]
        s0 = _xor3(
            _rotr64(h16[..., 1], l16[..., 1], 1),
            _rotr64(h16[..., 1], l16[..., 1], 8),
            _shr64(h16[..., 1], l16[..., 1], 7),
        )
        s1 = _xor3(
            _rotr64(h16[..., 14], l16[..., 14], 19),
            _rotr64(h16[..., 14], l16[..., 14], 61),
            _shr64(h16[..., 14], l16[..., 14], 6),
        )
        h, l = _add64(h16[..., 0], l16[..., 0], s0[0], s0[1])
        h, l = _add64(h, l, h16[..., 9], l16[..., 9])
        h, l = _add64(h, l, s1[0], s1[1])
        nwh = jnp.concatenate([h16[..., 1:], h[..., None]], axis=-1)
        nwl = jnp.concatenate([l16[..., 1:], l[..., None]], axis=-1)
        return (nwh, nwl), (h, l)

    _, (eh, el) = jax.lax.scan(sched_step, (wh, wl), None, length=64)
    # full 80-word schedule on a leading axis: [80, ...]
    ws_h = jnp.concatenate([jnp.moveaxis(wh, -1, 0), eh], axis=0)
    ws_l = jnp.concatenate([jnp.moveaxis(wl, -1, 0), el], axis=0)

    def round_step(regs, x):
        a, b, c, d, e, f, g, hh = regs
        w_h, w_l, k_h, k_l = x
        s1 = _xor3(_rotr64(*e, 14), _rotr64(*e, 18), _rotr64(*e, 41))
        ch = (
            (e[0] & f[0]) ^ (~e[0] & g[0]),
            (e[1] & f[1]) ^ (~e[1] & g[1]),
        )
        t1 = _add64(*hh, *s1)
        t1 = _add64(*t1, *ch)
        t1 = _add64(*t1, k_h, k_l)
        t1 = _add64(*t1, w_h, w_l)
        s0 = _xor3(_rotr64(*a, 28), _rotr64(*a, 34), _rotr64(*a, 39))
        maj = (
            (a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
            (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]),
        )
        t2 = _add64(*s0, *maj)
        return (
            _add64(*t1, *t2),
            a,
            b,
            c,
            _add64(*d, *t1),
            e,
            f,
            g,
        ), None

    regs0 = tuple((sh[..., i], sl[..., i]) for i in range(8))
    xs = (ws_h, ws_l, jnp.asarray(_KH), jnp.asarray(_KL))
    outs, _ = jax.lax.scan(round_step, regs0, xs)
    oh = jnp.stack(
        [_add64(*outs[i], sh[..., i], sl[..., i])[0] for i in range(8)],
        axis=-1,
    )
    ol = jnp.stack(
        [_add64(*outs[i], sh[..., i], sl[..., i])[1] for i in range(8)],
        axis=-1,
    )
    return oh, ol


def _compress512(sh, sl, wh, wl):
    """One SHA-512 compression. sh/sl: [..., 8]; wh/wl: [..., 16]."""
    if _use_scan_rounds():
        return _compress512_scan(sh, sl, wh, wl)
    whs = [wh[..., i] for i in range(16)]
    wls = [wl[..., i] for i in range(16)]
    for i in range(16, 80):
        s0 = _xor3(
            _rotr64(whs[i - 15], wls[i - 15], 1),
            _rotr64(whs[i - 15], wls[i - 15], 8),
            _shr64(whs[i - 15], wls[i - 15], 7),
        )
        s1 = _xor3(
            _rotr64(whs[i - 2], wls[i - 2], 19),
            _rotr64(whs[i - 2], wls[i - 2], 61),
            _shr64(whs[i - 2], wls[i - 2], 6),
        )
        h, l = _add64(whs[i - 16], wls[i - 16], s0[0], s0[1])
        h, l = _add64(h, l, whs[i - 7], wls[i - 7])
        h, l = _add64(h, l, s1[0], s1[1])
        whs.append(h)
        wls.append(l)

    regs = [(sh[..., i], sl[..., i]) for i in range(8)]
    a, b, c, d, e, f, g, hh = regs
    kh = jnp.asarray(_KH)
    kl = jnp.asarray(_KL)
    for i in range(80):
        s1 = _xor3(
            _rotr64(*e, 14), _rotr64(*e, 18), _rotr64(*e, 41)
        )
        ch = (e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1])
        t1 = _add64(*hh, *s1)
        t1 = _add64(*t1, *ch)
        t1 = _add64(*t1, kh[i], kl[i])
        t1 = _add64(*t1, whs[i], wls[i])
        s0 = _xor3(
            _rotr64(*a, 28), _rotr64(*a, 34), _rotr64(*a, 39)
        )
        maj = (
            (a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
            (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]),
        )
        t2 = _add64(*s0, *maj)
        hh, g, f = g, f, e
        e = _add64(*d, *t1)
        d, c, b = c, b, a
        a = _add64(*t1, *t2)
    outs = [a, b, c, d, e, f, g, hh]
    oh = jnp.stack(
        [_add64(*outs[i], sh[..., i], sl[..., i])[0] for i in range(8)],
        axis=-1,
    )
    ol = jnp.stack(
        [_add64(*outs[i], sh[..., i], sl[..., i])[1] for i in range(8)],
        axis=-1,
    )
    return oh, ol


def _bytes_to_words64(blocks_u8):
    """[..., N*8] u8 big-endian -> ([..., N] hi u32, [..., N] lo u32)."""
    b = blocks_u8.astype(jnp.uint32)
    shp = b.shape[:-1] + (b.shape[-1] // 8, 8)
    b = b.reshape(shp)
    hi = (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]
    lo = (b[..., 4] << 24) | (b[..., 5] << 16) | (b[..., 6] << 8) | b[..., 7]
    return hi, lo


def sha512_batch(data: jnp.ndarray, n_blocks: jnp.ndarray) -> jnp.ndarray:
    """data: [B, NBLK*128] u8 prepadded; n_blocks: [B] int32.
    Returns [B, 64] u8 digests (big-endian words, standard encoding)."""
    nblk = data.shape[-1] // 128
    wh, wl = _bytes_to_words64(data)  # [B, NBLK*16] each
    sh = jnp.broadcast_to(
        jnp.asarray(_H0H), (*data.shape[:-1], 8)
    ).astype(jnp.uint32)
    sl = jnp.broadcast_to(
        jnp.asarray(_H0L), (*data.shape[:-1], 8)
    ).astype(jnp.uint32)

    def body(i, st):
        h, l = st
        bh = jax.lax.dynamic_slice_in_dim(wh, i * 16, 16, axis=-1)
        bl = jax.lax.dynamic_slice_in_dim(wl, i * 16, 16, axis=-1)
        nh, nl = _compress512(h, l, bh, bl)
        active = (i < n_blocks)[..., None]
        return jnp.where(active, nh, h), jnp.where(active, nl, l)

    sh, sl = jax.lax.fori_loop(0, nblk, body, (sh, sl))
    # interleave hi/lo back to bytes
    words = jnp.stack([sh, sl], axis=-1).reshape(*sh.shape[:-1], 16)
    w = words[..., None]
    out = jnp.concatenate(
        [(w >> 24), (w >> 16), (w >> 8), w], axis=-1
    ) & jnp.uint32(0xFF)
    return out.reshape(*sh.shape[:-1], 64).astype(jnp.uint8)


def pad_messages(msgs: list[bytes], prefix_pairs=None) -> tuple:
    """Host helper: SHA-512 pad each message into one [B, NBLK*128]
    buffer + [B] block counts. `prefix_pairs[i]` (optional bytes) is
    prepended to msgs[i] — the verify path passes R||A per row.

    NBLK is bucketed to a power of two so the fused verify program
    compiles for a handful of shapes, not one per max-length class
    (shape discipline as in crypto/batch_verifier.BUCKETS)."""
    full = [
        (prefix_pairs[i] if prefix_pairs else b"") + m
        for i, m in enumerate(msgs)
    ]
    lens = [len(f) for f in full]
    needed = max(1, max((l + 17 + 127) // 128 for l in lens))
    nblk = 1
    while nblk < needed:
        nblk *= 2
    buf = np.zeros((len(full), nblk * 128), dtype=np.uint8)
    counts = np.zeros(len(full), dtype=np.int32)
    for i, f in enumerate(full):
        l = len(f)
        buf[i, :l] = np.frombuffer(f, dtype=np.uint8)
        buf[i, l] = 0x80
        blocks = (l + 17 + 127) // 128
        bits = l * 8
        buf[i, blocks * 128 - 8 : blocks * 128] = np.frombuffer(
            bits.to_bytes(8, "big"), dtype=np.uint8
        )
        counts[i] = blocks
    return buf, counts


sha512_batch_jit = jax.jit(sha512_batch)


# --- reduction mod L -------------------------------------------------------

NLIMBS = 32


def _limbs_of(x: int, n: int = NLIMBS) -> np.ndarray:
    return np.array(
        [int(b) for b in x.to_bytes(n, "little")], dtype=np.int32
    )


# T[i] = 2^(8*(32+i)) mod L as 32 radix-2^8 limbs — the fold table for
# bytes 32.. of a little-endian integer.
_T_FOLD = np.stack(
    [_limbs_of(pow(2, 8 * (32 + i), L)) for i in range(NLIMBS)]
)
_L_LIMBS = _limbs_of(L)


def _scan_carry(x):
    """Exact base-256 carry over the limb axis (signed-safe)."""
    xt = jnp.moveaxis(x, -1, 0)

    def step(carry, limb):
        v = limb + carry
        c = v >> 8
        return c, v - (c << 8)

    top, limbs = jax.lax.scan(step, jnp.zeros_like(xt[0]), xt)
    return jnp.moveaxis(limbs, 0, -1), top


def reduce_mod_l(digest: jnp.ndarray) -> jnp.ndarray:
    """[B, 64] u8 SHA-512 digest (little-endian integer, ed25519
    convention) -> [B, 32] u8 canonical k = digest mod L."""
    d = digest.astype(jnp.int32)
    lo, hi = d[..., :NLIMBS], d[..., NLIMBS:]
    t = jnp.asarray(_T_FOLD)
    # byte-fold: value(lo) + hi @ T  (products < 2^16, cols < 2^21)
    acc = lo + jnp.matmul(hi, t)
    # repeated normalize+fold until the carry out of 2^256 dies. Each
    # fold shrinks the excess by ~2^-3 (2^256 mod L ≈ 2^253): the worst
    # case 2^270 walks 267.3 → 264.6 → … → <2^256.5 in 5 rounds; rounds
    # 6-8 settle the top∈{0,1} boundary (a 1-carry fold lands < 2^254).
    for _ in range(8):
        limbs, top = _scan_carry(acc)  # top = value >> 256
        acc = limbs + top[..., None] * t[0][None, :]
    limbs, top = _scan_carry(acc)
    # top == 0 now (bound chain above); final exact reduction: q = (top
    # nibble) - 1 cautious estimate, then one conditional subtract.
    t_nib = limbs[..., 31] >> 4
    q = jnp.maximum(t_nib - 1, 0)
    l_l = jnp.asarray(_L_LIMBS)
    limbs, _ = _scan_carry(limbs - q[..., None] * l_l[None, :])
    # if still >= L subtract once more (big-endian compare)
    diff = limbs - l_l
    nz = diff != 0
    idx = (NLIMBS - 1) - jnp.argmax(nz[..., ::-1], axis=-1)
    ms = jnp.take_along_axis(diff, idx[..., None], axis=-1)[..., 0]
    geq = jnp.where(jnp.any(nz, axis=-1), ms > 0, True)
    limbs, _ = _scan_carry(limbs - geq[..., None] * l_l[None, :])
    return limbs.astype(jnp.uint8)


def challenge_batch(data: jnp.ndarray, n_blocks: jnp.ndarray) -> jnp.ndarray:
    """Fused device challenge: prepadded R||A||M buffers -> [B, 32]
    canonical k = SHA-512(R||A||M) mod L (little-endian bytes), ready
    for ops.ed25519_batch's k_bytes input."""
    return reduce_mod_l(sha512_batch(data, n_blocks))


challenge_batch_jit = jax.jit(challenge_batch)
