"""Batched ed25519 verification kernel (JAX → XLA → TPU).

The TPU replacement for the reference's serial per-vote loop
(types/vote_set.go:205 → crypto/ed25519/ed25519.go:148-162 in
/root/reference): one straight-line program that verifies B signatures at
once and returns an accept bitmap. No early exit, no branches — rejects are
masks, which is the TPU-friendly replacement for the reference's
``return false`` paths.

Two paths:
- `verify_prehashed`: generic — decompresses each pubkey and builds its
  window table in-batch.
- `verify_prehashed_table`: the consensus hot path — takes prebuilt cached
  window tables for the pubkeys (the same validators sign every height, so
  the BatchVerifier builds each validator's table once and re-uses it;
  skips decompression + table construction, ~40% of the generic work).

The kernel takes *prehashed* challenges: k = SHA-512(R || A || M) mod L is
computed by the caller (host today — the per-vote message is ragged while
everything in here is fixed-shape). The s < L range check is likewise a
host-computed input mask (`s_ok`).

Verification equation (cofactorless, matching Go x/crypto semantics):
    [s]B == R + [k]A   ⇔   encode([s]B + [k](-A)) == R_bytes
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import curve25519 as curve
from . import field25519 as fe


def verify_prehashed(
    pubkeys: jnp.ndarray,  # [B, 32] uint8
    r_bytes: jnp.ndarray,  # [B, 32] uint8 (first half of each signature)
    s_bytes: jnp.ndarray,  # [B, 32] uint8 (second half; caller checks < L)
    k_bytes: jnp.ndarray,  # [B, 32] uint8 (SHA-512(R||A||M) mod L)
    s_ok: jnp.ndarray,  # [B] bool (host-side s < L check)
) -> jnp.ndarray:
    """Returns [B] bool accept bitmap."""
    a_point, a_valid = curve.decompress(pubkeys)
    q = curve.double_scalar_mult_base(s_bytes, k_bytes, curve.neg(a_point))
    encoded = curve.compress(q)
    r_match = jnp.all(encoded == r_bytes, axis=-1)
    return a_valid & s_ok & r_match


def neg_pubkey_table(pubkeys: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Build cached window tables for -A per pubkey.

    pubkeys: [N, 32] u8 -> (tables [N, 16, 4, 32] u8, valid [N] bool).
    One-time per validator; the verify path then runs table-only. Entries
    are canonicalized so the persistent cache stores uint8 limbs — 4x
    less cache memory and gather traffic than loose int32, bit-exact
    (canonicalization is value-preserving mod p; the group ops accept
    any loose input)."""
    a_point, a_valid = curve.decompress(pubkeys)
    table = curve.window_table(curve.neg(a_point))
    return fe.to_bytes(table), a_valid


def verify_prehashed_table(
    tables: jnp.ndarray,  # [B, 16, 4, 32] cached window tables of -A
    table_valid: jnp.ndarray,  # [B] bool (pubkey decompressed OK)
    r_bytes: jnp.ndarray,  # [B, 32] uint8
    s_bytes: jnp.ndarray,  # [B, 32] uint8
    k_bytes: jnp.ndarray,  # [B, 32] uint8
    s_ok: jnp.ndarray,  # [B] bool
) -> jnp.ndarray:
    """Returns [B] bool accept bitmap (cached-pubkey hot path)."""
    q = curve.double_scalar_mult_base_table(s_bytes, k_bytes, tables)
    encoded = curve.compress(q)
    r_match = jnp.all(encoded == r_bytes, axis=-1)
    return table_valid & s_ok & r_match


def neg_pubkey_bigtable(
    pubkeys: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fixed-window tables for -A per pubkey: doubling-free verification.

    pubkeys: [N, 32] u8 -> (tables [N, 64, 16, 4, 32] u8, valid [N] bool).
    128 KiB per key (canonical uint8 limbs — see neg_pubkey_table); built
    once per validator (SURVEY.md §3.3 — the same validators sign every
    height), after which each verify is 128 cached adds and zero
    doublings.
    """
    a_point, a_valid = curve.decompress(pubkeys)
    table = curve.big_window_table(curve.neg(a_point))
    return fe.to_bytes(table), a_valid


def verify_prehashed_bigcache(
    tables_cache: jnp.ndarray,  # [cap, 64, 16, 4, 32] shared table cache
    table_valid: jnp.ndarray,  # [B] bool (row's pubkey decompressed OK)
    idx: jnp.ndarray,  # [B] int32 row index into the cache
    r_bytes: jnp.ndarray,  # [B, 32] uint8
    s_bytes: jnp.ndarray,  # [B, 32] uint8
    k_bytes: jnp.ndarray,  # [B, 32] uint8
    s_ok: jnp.ndarray,  # [B] bool
) -> jnp.ndarray:
    """The BatchVerifier steady-state path: doubling-free, cache-resident."""
    q = curve.add(
        curve.scalar_mult_base(s_bytes),
        curve.scalar_mult_var_bigcache(k_bytes, tables_cache, idx),
    )
    encoded = curve.compress(q)
    r_match = jnp.all(encoded == r_bytes, axis=-1)
    return table_valid & s_ok & r_match


def verify_prehashed_bigcache_mxu(
    tables_cache: jnp.ndarray,
    table_valid: jnp.ndarray,
    idx: jnp.ndarray,
    r_bytes: jnp.ndarray,
    s_bytes: jnp.ndarray,
    k_bytes: jnp.ndarray,
    s_ok: jnp.ndarray,
) -> jnp.ndarray:
    """verify_prehashed_bigcache with the table lookups as one-hot MXU
    matmuls (curve.scalar_mult_var_bigcache_mxu) — the real-silicon
    variant; select via TM_TPU_MXU_GATHER=1 (see the kernel docstring)."""
    q = curve.add(
        curve.scalar_mult_base(s_bytes),
        curve.scalar_mult_var_bigcache_mxu(k_bytes, tables_cache, idx),
    )
    encoded = curve.compress(q)
    r_match = jnp.all(encoded == r_bytes, axis=-1)
    return table_valid & s_ok & r_match


def verify_msgs_bigcache(
    tables_cache: jnp.ndarray,  # [cap, 64, 16, 4, 32] shared table cache
    table_valid: jnp.ndarray,  # [B] bool
    idx: jnp.ndarray,  # [B] int32 row index into the cache
    r_bytes: jnp.ndarray,  # [B, 32] uint8
    s_bytes: jnp.ndarray,  # [B, 32] uint8
    msg_buf: jnp.ndarray,  # [B, NBLK*128] uint8 prepadded R||A||M
    n_blocks: jnp.ndarray,  # [B] int32 SHA-512 block counts
    s_ok: jnp.ndarray,  # [B] bool
) -> jnp.ndarray:
    """Fully-fused bulk path: the challenge k = SHA-512(R||A||M) mod L is
    computed on device (ops/sha512.challenge_batch) instead of on one host
    thread — the bulk-replay shape (SURVEY.md §3.4) where per-sig host
    hashing would cap throughput."""
    from . import sha512

    k_bytes = sha512.challenge_batch(msg_buf, n_blocks)
    return verify_prehashed_bigcache(
        tables_cache, table_valid, idx, r_bytes, s_bytes, k_bytes, s_ok
    )


verify_prehashed_jit = jax.jit(verify_prehashed)
verify_prehashed_table_jit = jax.jit(verify_prehashed_table)
