"""Batched SHA-256 on TPU (merkle leaf/inner hashing).

SURVEY.md §2.2 row "SHA-256 / tmhash": the reference leans on stdlib
SHA-NI assembly (crypto/merkle/hash.go); bulk workloads here (hashing
thousands of merkle leaves / tx hashes per block) run as one fixed-shape
XLA program over uint32 lanes instead of a host loop.

Layout: messages are host-prepadded (`pad_messages`) into [B, NBLK*64]
buffers + a per-row active-block count. The kernel runs the compression
function over all NBLK blocks with a masked state update, so rows whose
message ended early keep their digest — ragged batches in one static
shape. Padded-length buckets keep NBLK small (one bucket per power of
two of blocks in practice).

`merkle_leaf_hash` / `merkle_inner_hash` mirror crypto/merkle.py's
RFC 6962 domain separation (leaf 0x00 / inner 0x01) so a device-built
tree equals the host tree byte-for-byte.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
        0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
        0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
        0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
        0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
        0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
        0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
        0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
        0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
        0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
        0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)


def _rotr(x, n):
    return (x >> n) | (x << (32 - n))


def _compress_scan(state, block_words):
    """Scan-form compression (see ops/sha512._use_scan_rounds: the
    straight-line body is right for the TPU executor but hour-class to
    compile on a 1-core XLA:CPU box). Bit-exact with _compress."""

    def sched_step(win, _):
        # win: [..., 16], index 0 == w[i-16]
        s0 = _rotr(win[..., 1], 7) ^ _rotr(win[..., 1], 18) ^ (
            win[..., 1] >> 3
        )
        s1 = _rotr(win[..., 14], 17) ^ _rotr(win[..., 14], 19) ^ (
            win[..., 14] >> 10
        )
        nw = win[..., 0] + s0 + win[..., 9] + s1
        return (
            jnp.concatenate([win[..., 1:], nw[..., None]], axis=-1),
            nw,
        )

    _, ext = jax.lax.scan(sched_step, block_words, None, length=48)
    ws = jnp.concatenate(
        [jnp.moveaxis(block_words, -1, 0), ext], axis=0
    )  # [64, ...]

    def round_step(regs, x):
        a, b, c, d, e, f, g, h = regs
        w_i, k_i = x
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k_i + w_i
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g), None

    regs0 = tuple(state[..., i] for i in range(8))
    outs, _ = jax.lax.scan(round_step, regs0, (ws, jnp.asarray(_K)))
    return state + jnp.stack(outs, axis=-1)


def _use_scan_rounds() -> bool:
    """Same backend/env heuristic as ops/sha512._use_scan_rounds (see
    its docstring for the measured rationale); defined locally so the
    two hash modules stay import-independent."""
    import os

    forced = os.environ.get("TM_TPU_SHA_SCAN")
    if forced is not None:
        return forced == "1"
    return jax.default_backend() == "cpu"


def _compress(state, block_words):
    """state: [..., 8] u32; block_words: [..., 16] u32 -> [..., 8] u32."""
    if _use_scan_rounds():
        return _compress_scan(state, block_words)
    # message schedule
    w = [block_words[..., i] for i in range(16)]
    for i in range(16, 64):
        s0 = _rotr(w[i - 15], 7) ^ _rotr(w[i - 15], 18) ^ (w[i - 15] >> 3)
        s1 = _rotr(w[i - 2], 17) ^ _rotr(w[i - 2], 19) ^ (w[i - 2] >> 10)
        w.append(w[i - 16] + s0 + w[i - 7] + s1)
    a, b, c, d, e, f, g, h = [state[..., i] for i in range(8)]
    k = jnp.asarray(_K)
    for i in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k[i] + w[i]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2
    new = jnp.stack([a, b, c, d, e, f, g, h], axis=-1)
    return state + new


def _bytes_to_words(blocks_u8):
    """[..., N*4] u8 big-endian -> [..., N] u32."""
    b = blocks_u8.astype(jnp.uint32)
    shp = b.shape[:-1] + (b.shape[-1] // 4, 4)
    b = b.reshape(shp)
    return (
        (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]
    )


def _words_to_bytes(words):
    """[..., N] u32 -> [..., N*4] u8 big-endian."""
    w = words[..., None]
    out = jnp.concatenate(
        [(w >> 24), (w >> 16), (w >> 8), w], axis=-1
    ) & jnp.uint32(0xFF)
    return out.reshape(*words.shape[:-1], words.shape[-1] * 4).astype(
        jnp.uint8
    )


def sha256_batch(data: jnp.ndarray, n_blocks: jnp.ndarray) -> jnp.ndarray:
    """data: [B, NBLK*64] u8 prepadded; n_blocks: [B] int32 (>=1).
    Returns [B, 32] u8 digests."""
    nblk = data.shape[-1] // 64
    words = _bytes_to_words(data)  # [B, NBLK*16]
    state = jnp.broadcast_to(
        jnp.asarray(_H0), (*data.shape[:-1], 8)
    ).astype(jnp.uint32)

    def body(i, st):
        blk = jax.lax.dynamic_slice_in_dim(words, i * 16, 16, axis=-1)
        new = _compress(st, blk)
        active = (i < n_blocks)[..., None]
        return jnp.where(active, new, st)

    state = jax.lax.fori_loop(0, nblk, body, state)
    return _words_to_bytes(state)


def pad_messages(msgs: list[bytes], prefix: bytes = b"") -> tuple:
    """Host helper: SHA-256 pad `prefix+m` for each m into one fixed
    [B, NBLK*64] buffer + [B] block counts."""
    lens = [len(prefix) + len(m) for m in msgs]
    nblk = max(1, max((l + 9 + 63) // 64 for l in lens))
    buf = np.zeros((len(msgs), nblk * 64), dtype=np.uint8)
    counts = np.zeros(len(msgs), dtype=np.int32)
    for i, m in enumerate(msgs):
        full = prefix + m
        l = len(full)
        buf[i, :l] = np.frombuffer(full, dtype=np.uint8)
        buf[i, l] = 0x80
        bits = l * 8
        blocks = (l + 9 + 63) // 64
        buf[i, blocks * 64 - 8 : blocks * 64] = np.frombuffer(
            bits.to_bytes(8, "big"), dtype=np.uint8
        )
        counts[i] = blocks
    return buf, counts


sha256_batch_jit = jax.jit(sha256_batch)


# --- RFC 6962 merkle on device --------------------------------------------


def merkle_leaf_hash(leaves: jnp.ndarray) -> jnp.ndarray:
    """[B, N] u8 fixed-size leaves -> [B, 32] u8 SHA-256(0x00 || leaf).
    (crypto/merkle.py leaf rule; one block as long as N <= 54.)"""
    b, n = leaves.shape
    total = 1 + n
    assert total + 9 <= 64, "fixed-size device path: leaf must fit a block"
    buf = jnp.zeros((b, 64), dtype=jnp.uint8)
    buf = buf.at[:, 0].set(0)
    buf = buf.at[:, 1 : 1 + n].set(leaves)
    buf = buf.at[:, total].set(0x80)
    bits = total * 8
    buf = buf.at[:, 56:64].set(
        jnp.asarray(
            np.frombuffer(bits.to_bytes(8, "big"), dtype=np.uint8)
        )
    )
    return sha256_batch(buf, jnp.ones(b, dtype=jnp.int32))


def merkle_inner_hash(left: jnp.ndarray, right: jnp.ndarray) -> jnp.ndarray:
    """[B, 32] x [B, 32] -> [B, 32] SHA-256(0x01 || l || r) (2 blocks)."""
    b = left.shape[0]
    buf = jnp.zeros((b, 128), dtype=jnp.uint8)
    buf = buf.at[:, 0].set(1)
    buf = buf.at[:, 1:33].set(left)
    buf = buf.at[:, 33:65].set(right)
    buf = buf.at[:, 65].set(0x80)
    bits = 65 * 8
    buf = buf.at[:, 120:128].set(
        jnp.asarray(
            np.frombuffer(bits.to_bytes(8, "big"), dtype=np.uint8)
        )
    )
    return sha256_batch(buf, jnp.full(b, 2, dtype=jnp.int32))


def merkle_root_pow2(leaves: jnp.ndarray) -> jnp.ndarray:
    """Full RFC 6962 tree for a power-of-two batch of fixed-size leaves:
    [B, N] u8 -> [32] u8 root. Level-by-level device folds (the
    unbalanced general case stays host-side in crypto/merkle.py)."""
    b = leaves.shape[0]
    assert b & (b - 1) == 0, "device tree fold requires power-of-two leaves"
    level = merkle_leaf_hash(leaves)
    while level.shape[0] > 1:
        level = merkle_inner_hash(level[0::2], level[1::2])
    return level[0]
