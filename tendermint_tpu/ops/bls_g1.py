"""Batched BLS12-381 G1 arithmetic on TPU — the aggregation kernel.

SURVEY.md §2.2 row "BLS12-381 pairing / aggregate verify": the host
scheme (crypto/bls_signatures.py, ref blssignatures/bls_signatures.go:
129-149) aggregates N signatures with N-1 G1 point additions. This
kernel does the additions as a device tree reduction: [B, 3, 48]
Jacobian points halve per level, log2(B) batched levels instead of a
serial host loop. Pairings stay on host (2 per aggregate verify,
independent of N) — the N-proportional work is exactly this kernel.

Design mirrors ops/field25519.py: radix-2^8 limbs (48 for the 381-bit
prime) in int32 lanes, loose invariant limbs < 2^9, carry passes with a
vector wrap (2^384 ≡ F0 (mod p) is a 48-limb constant, not a scalar —
the wrap is carry × F0 instead of carry × 38). Every control decision
(infinity, doubling, opposite points) is a mask — one straight-line XLA
program, `vmap`/`shard_map`-tileable like the ed25519 kernel.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
NLIMBS = 48


def _limbs_of(x: int, n: int = NLIMBS) -> np.ndarray:
    return np.array([int(b) for b in x.to_bytes(n, "little")], dtype=np.int32)


P_LIMBS = _limbs_of(P)
# fold table: F[i] = 2^(8*(48+i)) mod p, for folding conv columns >= 48
_F_FOLD = np.stack([_limbs_of(pow(2, 8 * (48 + i), P)) for i in range(NLIMBS + 2)])
_F0 = _F_FOLD[0]
# additive bias ≡ 0 (mod p) with all limbs >= 2048 — keeps `sub` limb
# differences non-negative for loose (< 2^11) subtrahends. 128p
# (~2^387.7) decomposed non-canonically with limbs in [2048, 4095+],
# leftover 2^384-weight digits folded through F0.
_BIAS_INT = 128 * P


def _bias_limbs() -> np.ndarray:
    """Non-canonical digits of 128p with limbs 0..47 in [2048, 2303]:
    write 128p = 2048·(2^384-1)/255 + REM and give every low limb its
    2048 floor plus REM's ordinary base-256 digit (< 256)."""
    floor_sum = 2048 * ((1 << 384) - 1) // 255  # value of all-2048 limbs
    rem = _BIAS_INT - floor_sum
    assert rem >= 0
    out = np.zeros(NLIMBS + 1, dtype=np.int64)
    out[NLIMBS] = rem >> 384
    rem &= (1 << 384) - 1
    digits = rem.to_bytes(NLIMBS, "little")
    for i in range(NLIMBS):
        out[i] = 2048 + digits[i]
    assert all(2048 <= int(x) <= 2303 for x in out[:NLIMBS])
    return out


_BIAS_RAW = _bias_limbs()
_BIAS_TOP = int(_BIAS_RAW[NLIMBS])
_BIAS = (_BIAS_RAW[:NLIMBS] + _BIAS_TOP * _F0.astype(np.int64)).astype(
    np.int32
)
assert (
    sum(int(v) << (8 * i) for i, v in enumerate(_BIAS)) % P == 0
), "bias must be ≡ 0 mod p"
# the 2-pass bound in sub()/neg() needs bias limbs < 2^14: then
# a + bias - b < 2^14.2, pass 1 leaves < 2^14.4, pass 2 < 2^11.
assert _BIAS.max() < (1 << 14), "sub()'s 2-pass carry bound needs this"


def from_int(x: int) -> np.ndarray:
    return _limbs_of(x % P)


def to_int(limbs) -> int:
    arr = np.asarray(limbs, dtype=np.int64)
    return int(sum(int(v) << (8 * i) for i, v in enumerate(arr.tolist())))


def zeros(shape=()) -> jnp.ndarray:
    return jnp.zeros((*shape, NLIMBS), dtype=jnp.int32)


def ones(shape=()) -> jnp.ndarray:
    z = np.zeros((*shape, NLIMBS), dtype=np.int32)
    z[..., 0] = 1
    return jnp.asarray(z)


def _carry_pass(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry pass; the top carry wraps via F0 (2^384 mod p).

    Unlike field25519's scalar-38 wrap (Crandall prime), F0 is a full
    48-limb vector, so a big top carry re-injects big values into every
    limb and convergence is ~3 bits of top-carry per pass (F0's own top
    limb is < 32). The loose invariant here is therefore limbs < 2^11
    (conv stays int32-safe: 48 products of < 2^22 -> < 2^27.6), reached
    after the pass counts used below — bounds pinned empirically by
    tests/test_ops_bls_g1.py's worst-case stress."""
    c = x >> 8
    r = x - (c << 8)
    wrap = jnp.concatenate(
        [jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1
    )
    return r + wrap + c[..., -1:] * jnp.asarray(_F0)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _carry_pass(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    x = a + jnp.asarray(_BIAS) - b
    x = _carry_pass(x)
    return _carry_pass(x)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    x = jnp.asarray(_BIAS) - a
    x = _carry_pass(x)
    return _carry_pass(x)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """48x48 limb convolution, fold by the 2^384-mod-p table, carry.

    Columns: 48-term sums of < 2^18 products -> < 2^23.6 (int32-safe).
    The fold normalizes hi columns to bytes first (scan), then one
    [.., 48+2] @ F matmul brings everything under 48 limbs."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)[:-1]
    out = jnp.zeros((*shape, 2 * NLIMBS - 1), dtype=jnp.int32)
    for i in range(NLIMBS):
        out = out.at[..., i : i + NLIMBS].add(a[..., i : i + 1] * b)
    # exact scan-carry the full 95 columns -> strict bytes + 2 top limbs
    limbs, top = _scan_carry(out)  # top < 2^16
    t_lo = top & 255
    t_hi = top >> 8
    hi_bytes = jnp.concatenate(
        [limbs[..., NLIMBS:], t_lo[..., None], t_hi[..., None]], axis=-1
    )  # [..., 49]: conv cols 48..94 (weights F[0..46]) + carry bytes
    # of col 94's scan-out (weights F[47], F[48])
    folded = limbs[..., :NLIMBS] + jnp.matmul(
        hi_bytes, jnp.asarray(_F_FOLD[: NLIMBS + 1])
    )
    x = folded  # cols < 256 + 50*2^16 < 2^22.7
    for _ in range(5):
        x = _carry_pass(x)
    return x


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    assert 0 <= k <= 1 << 14
    x = a * k
    x = _carry_pass(x)
    x = _carry_pass(x)
    return _carry_pass(x)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(cond[..., None], a, b)


def _scan_carry(x: jnp.ndarray):
    xt = jnp.moveaxis(x, -1, 0)

    def step(carry, limb):
        v = limb + carry
        c = v >> 8
        return c, v - (c << 8)

    top, limbs = jax.lax.scan(step, jnp.zeros_like(xt[0]), xt)
    return jnp.moveaxis(limbs, 0, -1), top


# floor(2^392 / p): quotient estimator for the final subtraction —
# q ≈ (top 16 bits of value) * _MU >> 24 underestimates value//p by <= 2.
_MU = (1 << 392) // P


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Loose -> canonical limbs in [0, p)."""
    limbs, top = _scan_carry(x)
    # fold 2^384-weight carries until gone (top < 2^4 for loose input;
    # each fold multiplies the excess by ~2^-3)
    for _ in range(4):
        limbs = limbs + top[..., None] * jnp.asarray(_F0)
        limbs, top = _scan_carry(limbs)
    # value < 2^384 now (~13.9 p): estimate q = value // p from the top
    # 16 bits, subtract q*p, then at most 2 conditional subtracts.
    p_l = jnp.asarray(P_LIMBS)
    t16 = (limbs[..., 47] << 8) | limbs[..., 46]
    q = jnp.maximum((t16 * _MU) >> 24, 0)
    limbs, _ = _scan_carry(limbs - q[..., None] * p_l)
    for _ in range(3):
        diff = limbs - p_l
        nz = diff != 0
        idx = (NLIMBS - 1) - jnp.argmax(nz[..., ::-1], axis=-1)
        ms = jnp.take_along_axis(diff, idx[..., None], axis=-1)[..., 0]
        geq = jnp.where(jnp.any(nz, axis=-1), ms > 0, True)
        limbs = limbs - p_l * geq[..., None].astype(jnp.int32)
        limbs, _ = _scan_carry(limbs)
    return limbs


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(x) == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(a) == canonical(b), axis=-1)


# --- G1 (Jacobian) ---------------------------------------------------------
#
# point: [..., 3, 48] (X, Y, Z); infinity = Z == 0. Formulas match the
# host oracle (crypto/bls12_381.py g1_add/g1_double: dbl-2009-l and
# add-2007-bl shapes) so device results equal host results limb-wise
# after canonicalization.


def g1_identity(shape=()) -> jnp.ndarray:
    z = np.zeros((*shape, 3, NLIMBS), dtype=np.int32)
    z[..., 1, 0] = 1  # (0, 1, 0)
    return jnp.asarray(z)


def g1_from_host(p) -> np.ndarray:
    return np.stack([from_int(c) for c in p])


def g1_to_host(pt) -> tuple:
    arr = np.asarray(canonical_jit(jnp.asarray(pt)))
    return tuple(to_int(arr[i]) for i in range(3))


def g1_is_inf(p: jnp.ndarray) -> jnp.ndarray:
    return is_zero(p[..., 2, :])


def g1_double(p: jnp.ndarray) -> jnp.ndarray:
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    a = sqr(x)
    b = sqr(y)
    c = sqr(b)
    xb = add(x, b)
    d = mul_small(sub(sub(sqr(xb), a), c), 2)
    e = mul_small(a, 3)
    f = sqr(e)
    x3 = sub(f, mul_small(d, 2))
    y3 = sub(mul(e, sub(d, x3)), mul_small(c, 8))
    z3 = mul_small(mul(y, z), 2)
    # y == 0 (order-2 would-be point; not on G1 but stay branch-free and
    # match the host: result = identity)
    bad = is_zero(y) | is_zero(z)
    out = jnp.stack([x3, y3, z3], axis=-2)
    return jnp.where(bad[..., None, None], g1_identity(x.shape[:-1]), out)


def g1_add(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Branch-free complete addition: handles inf, equal, and opposite
    inputs via masks (host oracle: crypto/bls12_381.py g1_add)."""
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    x2, y2, z2 = q[..., 0, :], q[..., 1, :], q[..., 2, :]
    z1z1 = sqr(z1)
    z2z2 = sqr(z2)
    u1 = mul(x1, z2z2)
    u2 = mul(x2, z1z1)
    s1 = mul(mul(y1, z2), z2z2)
    s2 = mul(mul(y2, z1), z1z1)
    h = sub(u2, u1)
    same_x = is_zero(h)
    r2 = sub(s2, s1)
    same_y = is_zero(r2)
    h2 = mul_small(h, 2)
    i = sqr(h2)
    j = mul(h, i)
    rr = mul_small(r2, 2)
    v = mul(u1, i)
    x3 = sub(sub(sqr(rr), j), mul_small(v, 2))
    y3 = sub(mul(rr, sub(v, x3)), mul_small(mul(s1, j), 2))
    z3 = mul(sub(sub(sqr(add(z1, z2)), z1z1), z2z2), h)
    added = jnp.stack([x3, y3, z3], axis=-2)

    doubled = g1_double(p)
    p_inf = is_zero(z1)
    q_inf = is_zero(z2)
    # precedence: p inf -> q; q inf -> p; same x and y -> double;
    # same x, opposite y -> identity; else -> added
    out = added
    ident = g1_identity(x1.shape[:-1])
    out = jnp.where((same_x & ~same_y)[..., None, None], ident, out)
    out = jnp.where((same_x & same_y)[..., None, None], doubled, out)
    out = jnp.where(q_inf[..., None, None], p, out)
    out = jnp.where(p_inf[..., None, None], q, out)
    return out


g1_add_jit = jax.jit(g1_add)


def g1_aggregate(points: jnp.ndarray) -> jnp.ndarray:
    """Tree-reduce [B, 3, 48] -> [3, 48]: sum of all points in log2(B)
    batched add levels (the device form of AggregateSignatures'
    point-add loop, bls_signatures.go:138-149). B padded to a power of
    two with identity. Each level reuses the ONE jitted g1_add (per
    level shape) rather than inlining the whole tree into a single
    program — a 128-leaf tree would otherwise trace ~2000 field muls
    into one giant compile."""
    b = points.shape[0]
    nb = 1 << max(1, (b - 1).bit_length())
    if nb != b:
        pad = jnp.broadcast_to(
            g1_identity(), (nb - b, 3, NLIMBS)
        ).astype(points.dtype)
        points = jnp.concatenate([points, pad], axis=0)
    while points.shape[0] > 1:
        points = g1_add_jit(points[0::2], points[1::2])
    return points[0]


g1_aggregate_jit = g1_aggregate  # levels are jitted internally
g1_double_jit = jax.jit(g1_double)
mul_jit = jax.jit(mul)
canonical_jit = jax.jit(canonical)


def g1_aggregate_sharded(points, mesh) -> jnp.ndarray:
    """Point sum over a device mesh: local tree per shard + an explicit
    XOR-butterfly ppermute all-reduce with g1_add as the combiner (see
    ops/shard_reduce.py for why shard_map, not jit-with-shardings)."""
    from . import shard_reduce

    return shard_reduce.aggregate_sharded(
        points, mesh, g1_add, np.asarray(g1_identity()), (3, NLIMBS)
    )
