"""Device BLS12-381 pairing: batched Miller loop + final exponentiation.

The last SURVEY §2.2 row ("hash-to-curve + MSM on TPU, pairing on host
initially, THEN MOVE" — §7.3(2)): the reference's aggregate-signature
verification is a 2-pairing product check per batch point
(/root/reference/blssignatures/bls_signatures.go:114-171, via the kilic
engine); ops/bls_g1.py / ops/bls_g2.py cover the aggregation halves and
this module moves the pairing itself onto the device.

Design (mirrors the host-validated algebra of crypto/bls12_381.py and the
inversion-free structure of native/bls12_381.cpp, re-shaped for XLA):

- Field layer: ops/vecfield.py radix-2^8 limbs with the "matmul"
  convolution (bit-exact vs the slice scheme; ~5x fewer HLO ops per mul —
  this program traces hundreds of muls per scan body, so graph size, not
  op count, is the binding constraint).
- Fp2 [., 2, 48]; Fp12 as the flat sextic Fp2[w]/(w^6 - xi) [., 6, 2, 48]
  (same tower as the host module — NOT the kilc/blst 2-3-2 tower).
- Miller loop: `lax.scan` over the static X_ABS bit program. T is kept in
  Jacobian coordinates; lines are scaled by their denominators (2YZ^3 for
  doubling, Z·lambda for addition — native/bls12_381.cpp:1105-1205), an
  Fp2 factor that the easy part of the final exponentiation kills, so NO
  field inversions run inside the loop (a device Fermat inversion is a
  ~760-step chain — inadmissible per bit).
- Pairs are processed NPAIRS=2 at a time (the aggregate-verify shape)
  batched over a leading B axis; a product over more pairs rides the
  multiplicativity of the Miller value: chunk outputs are f12-multiplied
  before ONE shared final exponentiation, exactly like the native
  64-chunk flush (native/bls12_381.cpp:1262-1290).
- Final exponentiation: easy part via conj·inv + frobenius; hard part via
  the BLS12 chain (computing the CUBE of the ate pairing, same as host),
  with Granger–Scott cyclotomic squaring inside the x-exponentiations.
- Compile bounding: the loop-heavy stages are SEPARATE jits (miller,
  x-exponentiation, f12 mul/inv/frobenius) composed from Python — ~10
  extra dispatches per check, which the dispatch-cost model prices at
  ~1 s on this executor (PERF_ANALYSIS §1) against a one-shot jit whose
  single graph would be ~100k HLO ops and an hour-class compile.

Routed from crypto/bls_signatures._pairing_is_one behind
TM_TPU_BLS_PAIRING_DEVICE=1 (the secp/PERF_ANALYSIS §6 real-silicon
gating pattern); the native C++ then the host bigint path remain the
default tiers. Bit-exactness vs crypto/bls12_381.pairing is pinned by
tests/test_ops_bls_pairing.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import vecfield
from ..crypto import bls12_381 as host

P = host.P
X_ABS = host.X_ABS
NLIMBS = 48
NPAIRS = 2  # pairs per miller chunk: the aggregate-verify check shape

fe = vecfield.make_field(P, NLIMBS, mul_style="matmul")

# static bit programs (MSB first)
_XBITS_TAIL = np.array(
    [int(b) for b in bin(X_ABS)[3:]], dtype=np.int32
)  # miller: T starts at Q, leading bit consumed
_XBITS_ALL = np.array(
    [int(b) for b in bin(X_ABS)[2:]], dtype=np.int32
)  # exponentiation: r starts at one


# --- Fp2 ------------------------------------------------------------------


def f2_from_host(c) -> np.ndarray:
    return np.stack([fe.from_int(c[0] % P), fe.from_int(c[1] % P)])


def f2_to_host(x) -> tuple:
    arr = np.asarray(x)
    return (fe.to_int(arr[..., 0, :]) % P, fe.to_int(arr[..., 1, :]) % P)


def f2_one(shape=()) -> jnp.ndarray:
    z = np.zeros((*shape, 2, NLIMBS), dtype=np.int32)
    z[..., 0, 0] = 1
    return jnp.asarray(z)


def f2_add(a, b):
    return jnp.stack(
        [fe.add(a[..., 0, :], b[..., 0, :]), fe.add(a[..., 1, :], b[..., 1, :])],
        axis=-2,
    )


def f2_sub(a, b):
    return jnp.stack(
        [fe.sub(a[..., 0, :], b[..., 0, :]), fe.sub(a[..., 1, :], b[..., 1, :])],
        axis=-2,
    )


def f2_neg(a):
    return jnp.stack(
        [fe.neg(a[..., 0, :]), fe.neg(a[..., 1, :])], axis=-2
    )


def f2_conj(a):
    return jnp.stack(
        [a[..., 0, :], fe.neg(a[..., 1, :])], axis=-2
    )


def f2_mul(a, b):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    b0, b1 = b[..., 0, :], b[..., 1, :]
    t0 = fe.mul(a0, b0)
    t1 = fe.mul(a1, b1)
    m = fe.mul(fe.add(a0, a1), fe.add(b0, b1))
    return jnp.stack([fe.sub(t0, t1), fe.sub(fe.sub(m, t0), t1)], axis=-2)


def f2_sqr(a):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    c0 = fe.mul(fe.add(a0, a1), fe.sub(a0, a1))
    c1 = fe.mul_small(fe.mul(a0, a1), 2)
    return jnp.stack([c0, c1], axis=-2)


def f2_mul_small(a, k: int):
    return jnp.stack(
        [fe.mul_small(a[..., 0, :], k), fe.mul_small(a[..., 1, :], k)],
        axis=-2,
    )


def f2_mul_xi(a):
    """(c0 + c1 u)(1 + u) = (c0 - c1) + (c0 + c1) u."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    return jnp.stack([fe.sub(a0, a1), fe.add(a0, a1)], axis=-2)


def f2_scale_fp(a, k):
    """Fp2 times an Fp element k [..., 48]."""
    return jnp.stack(
        [fe.mul(a[..., 0, :], k), fe.mul(a[..., 1, :], k)], axis=-2
    )


def f2_inv(a):
    """1/(a0 + a1 u) = (a0 - a1 u)/(a0^2 + a1^2); one Fermat chain."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    norm = fe.add(fe.mul(a0, a0), fe.mul(a1, a1))
    ni = fe.invert(norm)
    return jnp.stack(
        [fe.mul(a0, ni), fe.mul(fe.neg(a1), ni)], axis=-2
    )


def f2_canonical(a):
    return jnp.stack(
        [fe.canonical(a[..., 0, :]), fe.canonical(a[..., 1, :])], axis=-2
    )


# --- Fp12 = Fp2[w]/(w^6 - xi), elements [..., 6, 2, 48] -------------------
#
# Accumulation discipline: the 11 convolution columns are summed with RAW
# int32 adds (no per-add carry pass — each term is a mul/sqr output whose
# limbs the 5-pass mul tail keeps small, so ≤7 raw terms stay far from
# int32 range), then ONE 3-pass renormalization per column restores the
# loose invariant before the xi-fold's fe.sub (whose bias decomposition
# needs subtrahend limbs ≤ 2048). Chained f2_add would instead grow the
# limbs past the bias headroom after ~3 links. Bounds are pinned by the
# worst-case stress test in tests/test_ops_bls_pairing.py.


def _f2_renorm(a):
    x0, x1 = a[..., 0, :], a[..., 1, :]
    for _ in range(3):
        x0 = fe._carry_pass(x0)
        x1 = fe._carry_pass(x1)
    return jnp.stack([x0, x1], axis=-2)


def _combine_columns(acc):
    """11 raw-sum columns -> 6 coefficients with the w^6 = xi fold.
    None columns (sparse products never touch them) contribute nothing."""
    out = []
    for k in range(6):
        c = _f2_renorm(acc[k])
        if k + 6 <= 10 and acc[k + 6] is not None:
            c = f2_add(c, f2_mul_xi(_f2_renorm(acc[k + 6])))
        out.append(c)
    return jnp.stack(out, axis=-3)


def f12_one(shape=()) -> jnp.ndarray:
    z = np.zeros((*shape, 6, 2, NLIMBS), dtype=np.int32)
    z[..., 0, 0, 0] = 1
    return jnp.asarray(z)


def f12_from_host(a) -> np.ndarray:
    return np.stack([f2_from_host(c) for c in a])


def f12_to_host(x) -> tuple:
    """x: ONE Fp12 [6, 2, 48] -> host coefficient tuple."""
    arr = np.asarray(canonical12_jit(jnp.asarray(x)))
    return tuple(f2_to_host(arr[i]) for i in range(6))


def f12_mul(a, b):
    acc = [None] * 11
    for i in range(6):
        ai = a[..., i, :, :]
        for j in range(6):
            m = f2_mul(ai, b[..., j, :, :])
            acc[i + j] = m if acc[i + j] is None else acc[i + j] + m
    return _combine_columns(acc)


def f12_sqr(a):
    """Symmetric schoolbook: 6 Fp2 squarings + 15 doubled cross muls
    (57 base muls vs f12_mul's 108)."""
    acc = [None] * 11

    def put(k, v):
        acc[k] = v if acc[k] is None else acc[k] + v

    for i in range(6):
        ai = a[..., i, :, :]
        put(2 * i, f2_sqr(ai))
        for j in range(i + 1, 6):
            put(i + j, f2_mul_small(f2_mul(ai, a[..., j, :, :]), 2))
    return _combine_columns(acc)


def f12_conj(a):
    """w -> -w (= frobenius^6)."""
    return jnp.stack(
        [
            a[..., 0, :, :],
            f2_neg(a[..., 1, :, :]),
            a[..., 2, :, :],
            f2_neg(a[..., 3, :, :]),
            a[..., 4, :, :],
            f2_neg(a[..., 5, :, :]),
        ],
        axis=-3,
    )


def f12_mul_line(a, l0, l2, l3):
    """Sparse multiply by a line l = l0 + l2 w^2 + l3 w^3 (18 Fp2 muls)."""
    acc = [None] * 11

    def put(k, v):
        acc[k] = v if acc[k] is None else acc[k] + v

    for i in range(6):
        ai = a[..., i, :, :]
        put(i, f2_mul(ai, l0))
        put(i + 2, f2_mul(ai, l2))
        put(i + 3, f2_mul(ai, l3))
    return _combine_columns(acc)


# frobenius twists gamma_i = xi^(i(p-1)/6), from the host-validated table
_GAMMA_DEV = np.stack([f2_from_host(g) for g in host._GAMMA])


def f12_frob(a):
    g = jnp.asarray(_GAMMA_DEV)
    return jnp.stack(
        [
            f2_mul(f2_conj(a[..., i, :, :]), g[i])
            for i in range(6)
        ],
        axis=-3,
    )


def _f6_inv(a0, a1, a2):
    """Fp6 = Fp2[v]/(v^3 - xi) inversion (native/bls12_381.cpp f6_inv)."""
    c0 = f2_sub(f2_sqr(a0), f2_mul_xi(f2_mul(a1, a2)))
    c1 = f2_sub(f2_mul_xi(f2_sqr(a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    t = f2_add(
        f2_mul_xi(f2_add(f2_mul(a1, c2), f2_mul(a2, c1))),
        f2_mul(a0, c0),
    )
    ti = f2_inv(t)
    return f2_mul(c0, ti), f2_mul(c1, ti), f2_mul(c2, ti)


def f12_inv(a):
    """Via the even subalgebra: n = a·conj(a) lives in Fp6 = Fp2[w^2]
    (odd-w coefficients are ≡ 0 mod p and dropped)."""
    ac = f12_conj(a)
    n = f12_mul(a, ac)
    i0, i1, i2 = _f6_inv(
        n[..., 0, :, :], n[..., 2, :, :], n[..., 4, :, :]
    )
    zero = jnp.zeros_like(i0)
    n12 = jnp.stack([i0, zero, i1, zero, i2, zero], axis=-3)
    return f12_mul(ac, n12)


# --- Granger–Scott cyclotomic squaring ------------------------------------


def _f4_sqr(a, b):
    """(a + b z)^2 over Fp4 = Fp2[z]/(z^2 - xi): (a^2 + xi b^2, 2ab)."""
    t0 = f2_sqr(a)
    t1 = f2_sqr(b)
    o1 = f2_sub(f2_sub(f2_sqr(f2_add(a, b)), t0), t1)
    o0 = f2_add(t0, f2_mul_xi(t1))
    return o0, o1


def f12_cyclo_sqr(a):
    """ONLY valid in the cyclotomic subgroup (unitary after the easy part);
    3 Fp4 squarings + the GS recombination (native/bls12_381.cpp:603-650)."""
    c = [a[..., i, :, :] for i in range(6)]
    A0, A1 = _f4_sqr(c[0], c[3])
    B0, B1 = _f4_sqr(c[1], c[4])
    C0, C1 = _f4_sqr(c[2], c[5])

    def comb(tre, tim, are, aim):
        hre = f2_add(f2_mul_small(f2_sub(tre, are), 2), tre)
        him = f2_add(f2_mul_small(f2_add(tim, aim), 2), tim)
        return hre, him

    o0, o3 = comb(A0, A1, c[0], c[3])
    o2, o5 = comb(B0, B1, c[2], c[5])
    re = f2_mul_xi(C1)
    o1 = f2_add(f2_mul_small(f2_add(re, c[1]), 2), re)
    o4 = f2_add(f2_mul_small(f2_sub(C0, c[4]), 2), C0)
    return jnp.stack([o0, o1, o2, o3, o4, o5], axis=-3)


# --- Miller loop ----------------------------------------------------------


def _dbl_step(X, Y, Z, xp, yp):
    """Line coefficients scaled by 2YZ^3 + Jacobian doubling
    (native miller_dbl_step). xp/yp are Fp limb arrays broadcast over
    the Fp2 component axes of the line."""
    A = f2_sqr(X)
    B = f2_sqr(Y)
    C = f2_sqr(B)
    D = f2_mul_small(f2_sub(f2_sub(f2_sqr(f2_add(X, B)), A), C), 2)
    E = f2_mul_small(A, 3)
    F = f2_sqr(E)
    Zsq = f2_sqr(Z)
    l0 = f2_sub(f2_sub(f2_mul(E, X), B), B)
    l2 = f2_neg(f2_scale_fp(f2_mul(E, Zsq), xp))
    Z3 = f2_mul_small(f2_mul(Y, Z), 2)
    l3 = f2_scale_fp(f2_mul(Z3, Zsq), yp)
    X3 = f2_sub(f2_sub(F, D), D)
    Y3 = f2_sub(f2_mul(E, f2_sub(D, X3)), f2_mul_small(C, 8))
    return l0, l2, l3, X3, Y3, Z3


def _add_step(X, Y, Z, xq, yq, xp, yp):
    """Line through T and Q scaled by Z·lambda + mixed Jacobian T+Q
    (native miller_add_step)."""
    Zsq = f2_sqr(Z)
    Zcu = f2_mul(Zsq, Z)
    theta = f2_sub(Y, f2_mul(yq, Zcu))
    lam = f2_sub(X, f2_mul(xq, Zsq))
    Zlam = f2_mul(Z, lam)
    l0 = f2_sub(f2_mul(theta, xq), f2_mul(Zlam, yq))
    l2 = f2_neg(f2_scale_fp(theta, xp))
    l3 = f2_scale_fp(Zlam, yp)
    h = f2_neg(lam)
    i = f2_mul_small(f2_sqr(h), 4)
    j = f2_mul(h, i)
    r = f2_mul_small(f2_neg(theta), 2)
    v = f2_mul(X, i)
    X3 = f2_sub(f2_sub(f2_sub(f2_sqr(r), j), v), v)
    Y3 = f2_sub(f2_mul(r, f2_sub(v, X3)), f2_mul_small(f2_mul(Y, j), 2))
    Z3 = f2_mul_small(f2_mul(Z, h), 2)
    return l0, l2, l3, X3, Y3, Z3


def _fold_lines(f, l0, l2, l3, valid):
    """Multiply f by each pair's line; invalid pairs fold the identity
    line (l0=1, l2=l3=0)."""
    one = f2_one(l0.shape[:-4] or ())
    zero = jnp.zeros_like(l0[..., 0, :, :])
    for i in range(NPAIRS):
        m = valid[..., i, None, None]
        li0 = jnp.where(m, l0[..., i, :, :], one)
        li2 = jnp.where(m, l2[..., i, :, :], zero)
        li3 = jnp.where(m, l3[..., i, :, :], zero)
        f = f12_mul_line(f, li0, li2, li3)
    return f


def _miller(xp, yp, xq, yq, valid):
    """prod over valid pairs of f_{|x|,Q_i}(P_i), conjugated for x < 0.

    xp/yp: [B, NPAIRS, 48] G1 affine; xq/yq: [B, NPAIRS, 2, 48] G2 affine
    twist coords; valid: [B, NPAIRS] bool. Returns f12 [B, 6, 2, 48].
    """
    bshape = xp.shape[:-2]
    f = f12_one(bshape)
    X, Y = xq, yq
    Z = jnp.broadcast_to(
        f2_one(), (*bshape, NPAIRS, 2, NLIMBS)
    ).astype(jnp.int32)

    def body(carry, flag):
        f, X, Y, Z = carry
        f = f12_sqr(f)
        l0, l2, l3, X, Y, Z = _dbl_step(X, Y, Z, xp, yp)
        f = _fold_lines(f, l0, l2, l3, valid)

        def do_add(op):
            f, X, Y, Z = op
            l0, l2, l3, X2, Y2, Z2 = _add_step(X, Y, Z, xq, yq, xp, yp)
            return _fold_lines(f, l0, l2, l3, valid), X2, Y2, Z2

        f, X, Y, Z = jax.lax.cond(
            flag == 1, do_add, lambda op: op, (f, X, Y, Z)
        )
        return (f, X, Y, Z), None

    (f, _, _, _), _ = jax.lax.scan(
        body, (f, X, Y, Z), jnp.asarray(_XBITS_TAIL)
    )
    return f12_conj(f)


# --- final exponentiation (composed from bounded jits) --------------------


def _exp_xabs_cyclo(a):
    """a^|x| with Granger–Scott squaring (a unitary/cyclotomic)."""

    def body(r, bit):
        r = f12_cyclo_sqr(r)
        return jnp.where(bit == 1, f12_mul(r, a), r), None

    r, _ = jax.lax.scan(body, f12_one(a.shape[:-3]), jnp.asarray(_XBITS_ALL))
    return r


def _exp_x_signed(a):
    """a^x for the negative BLS parameter (conj == inverse, unitary)."""
    return f12_conj(_exp_xabs_cyclo(a))


def _easy_part(f):
    """f^((p^6-1)(p^2+1)) — lands in the cyclotomic subgroup."""
    f1 = f12_mul(f12_conj(f), f12_inv(f))
    return f12_mul(f12_frob(f12_frob(f1)), f1)


def _eq_one(f):
    c = f12_canonical(f)
    return jnp.all(c == f12_one(f.shape[:-3]).astype(c.dtype), axis=(-3, -2, -1))


def f12_canonical(a):
    return jnp.stack(
        [f2_canonical(a[..., i, :, :]) for i in range(6)], axis=-3
    )


miller_jit = jax.jit(_miller)
easy_part_jit = jax.jit(_easy_part)
exp_x_signed_jit = jax.jit(_exp_x_signed)
f12_mul_jit = jax.jit(f12_mul)
frob_jit = jax.jit(f12_frob)
frob2_jit = jax.jit(lambda a: f12_frob(f12_frob(a)))
cube_jit = jax.jit(lambda a: f12_mul(f12_cyclo_sqr(a), a))
eq_one_jit = jax.jit(_eq_one)
canonical12_jit = jax.jit(f12_canonical)


def _hard_part(f):
    """f^(3(p^4-p^2+1)/r) via the BLS12 chain (host final_exponentiation /
    native final_exponentiation): (x-1)^2 (x+p) (x^2+p^2-1) + 3. Python
    composition of the jitted stages — f must be unitary (easy part done)."""
    a = f12_mul_jit(exp_x_signed_jit(f), f12_conj(f))
    a = f12_mul_jit(exp_x_signed_jit(a), f12_conj(a))
    b = f12_mul_jit(exp_x_signed_jit(a), frob_jit(a))
    c = f12_mul_jit(
        f12_mul_jit(exp_x_signed_jit(exp_x_signed_jit(b)), frob2_jit(b)),
        f12_conj(b),
    )
    return f12_mul_jit(c, cube_jit(f))


def final_exponentiation(f):
    return _hard_part(easy_part_jit(f))


# --- host-facing API ------------------------------------------------------


def _prepare_pairs(pairs):
    """Host Jacobian pairs -> padded device chunks.

    Returns (xp, yp, xq, yq, valid) numpy arrays shaped for miller_jit,
    with infinity pairs dropped (their factor is 1, matching the host
    miller_loop) and the chunk count padded to a power of two to bound
    the compile-shape family.
    """
    prepared = []
    for gp, gq in pairs:
        pa = host.g1_to_affine(gp)
        qa = host.g2_to_affine(gq)
        if pa is None or qa is None:
            continue
        prepared.append((pa, qa))
    n = len(prepared)
    nchunks = max(1, -(-n // NPAIRS))
    nchunks = 1 << (nchunks - 1).bit_length()
    xp = np.zeros((nchunks, NPAIRS, NLIMBS), dtype=np.int32)
    yp = np.zeros_like(xp)
    xq = np.zeros((nchunks, NPAIRS, 2, NLIMBS), dtype=np.int32)
    yq = np.zeros_like(xq)
    valid = np.zeros((nchunks, NPAIRS), dtype=bool)
    for k, (pa, qa) in enumerate(prepared):
        b, i = divmod(k, NPAIRS)
        xp[b, i] = fe.from_int(pa[0])
        yp[b, i] = fe.from_int(pa[1])
        xq[b, i] = f2_from_host(qa[0])
        yq[b, i] = f2_from_host(qa[1])
        valid[b, i] = True
    return xp, yp, xq, yq, valid


def pairing_value(pairs) -> tuple:
    """prod e(P_i, Q_i) as host Fp12 coefficients (the CUBE of the ate
    pairing, same normalization as crypto/bls12_381.pairing)."""
    xp, yp, xq, yq, valid = _prepare_pairs(pairs)
    if not valid.any():
        return tuple((1 if i == 0 else 0, 0) for i in range(6))
    f = miller_jit(*(jnp.asarray(a) for a in (xp, yp, xq, yq, valid)))
    # chunk outputs multiply before the one final exponentiation
    while f.shape[0] > 1:
        f = f12_mul_jit(f[0::2], f[1::2])
    return f12_to_host(final_exponentiation(f)[0])


def check_pairs(pairs) -> bool:
    """prod e(P_i, Q_i) == 1 — the verification primitive, on device."""
    xp, yp, xq, yq, valid = _prepare_pairs(pairs)
    if not valid.any():
        return True
    f = miller_jit(*(jnp.asarray(a) for a in (xp, yp, xq, yq, valid)))
    while f.shape[0] > 1:
        f = f12_mul_jit(f[0::2], f[1::2])
    return bool(np.asarray(eq_one_jit(final_exponentiation(f)))[0])
