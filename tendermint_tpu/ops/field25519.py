"""GF(2^255-19) arithmetic for TPU: batched, radix-2^8 limbs, int32 lanes.

Design notes (TPU-first, not a port — the reference uses x/crypto's 64-bit
assembly field ops, crypto/ed25519/ed25519.go:148-162 in /root/reference):

- A field element is ``[..., 32] int32``: 32 little-endian limbs of 8 bits.
  Radix 2^8 is chosen so that (a) encoded byte strings ARE the limb vector,
  (b) limb products fit comfortably in int32 (no 64-bit multiplies — TPUs
  have no native int64), and (c) a future Pallas kernel can feed the limbs
  to the MXU as int8 operands with int32 accumulation.
- "Loose" invariant: every public op accepts and returns limbs in [0, 2^9).
  Products then satisfy: conv term < 2^18, 32-term column sum < 2^23, and
  after the fold by 38 (2^256 ≡ 38 mod p) columns stay < 39*2^23 < 2^28.3,
  inside int32.
- Carries are vectorized shift-add passes (4 passes restore the loose
  invariant after a multiply — see bound chain in `_carry_pass`); the exact
  sequential carry (lax.scan over the 32 limbs) is reserved for
  canonicalization, which only happens at batch boundaries.
- No data-dependent control flow: everything is select/mask based, so the
  whole verifier jits to one XLA program.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

NLIMBS = 32
P = 2**255 - 19

# canonical limbs of p: [237, 255 x30, 127]
P_LIMBS = np.array(
    [int(b) for b in P.to_bytes(32, "little")], dtype=np.int32
)
# 8p = 2^258 - 152 decomposed non-canonically as [872, 1020 x31]:
#   872 + 1020 * (2^256 - 2^8)/255 = 2^258 - 152.
# Used as the additive bias in `sub` so limb-wise differences stay
# non-negative for any loose (< 2^9 ≤ 1020/2) subtrahend.
_BIAS_8P = np.full(NLIMBS, 1020, dtype=np.int32)
_BIAS_8P[0] = 872
assert sum(int(v) << (8 * i) for i, v in enumerate(_BIAS_8P)) % P == 0


def from_int(x: int) -> np.ndarray:
    """Host helper: Python int -> limb vector (numpy, canonical)."""
    return np.array(
        [int(b) for b in (x % P).to_bytes(32, "little")], dtype=np.int32
    )


def to_int(limbs) -> int:
    """Host helper: limb vector -> Python int (no reduction)."""
    arr = np.asarray(limbs, dtype=np.int64)
    return int(sum(int(v) << (8 * i) for i, v in enumerate(arr.tolist())))


def zeros(shape=()) -> jnp.ndarray:
    return jnp.zeros((*shape, NLIMBS), dtype=jnp.int32)


def ones(shape=()) -> jnp.ndarray:
    z = np.zeros((*shape, NLIMBS), dtype=np.int32)
    z[..., 0] = 1
    return jnp.asarray(z)


def constant(x: int, shape=()) -> jnp.ndarray:
    """Broadcast a Python-int field constant to [..., 32] limbs."""
    base = from_int(x)
    return jnp.broadcast_to(jnp.asarray(base), (*shape, NLIMBS))


def _carry_pass(x: jnp.ndarray) -> jnp.ndarray:
    """One vectorized carry pass with the mod-p wrap (2^256 ≡ 38).

    Bound chain after `mul`'s fold (columns < 2^28.3):
      pass1: limbs < 2^20.4 (limb0 < 2^25.6)
      pass2: limbs < 2^17.7
      pass3: limbs < 2^10.3
      pass4: limbs < 294 < 2^9   -> loose invariant restored.
    """
    c = x >> 8
    r = x - (c << 8)
    wrap = jnp.concatenate([c[..., 31:] * 38, c[..., :31]], axis=-1)
    return r + wrap


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a + b; loose in, loose out (sum < 2^10, one pass -> < 370)."""
    return _carry_pass(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b via the 8p bias; loose in, loose out (< 446 after one pass)."""
    return _carry_pass(a + jnp.asarray(_BIAS_8P) - b)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return _carry_pass(jnp.asarray(_BIAS_8P) - a)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """16x32 mixed-radix limb convolution + fold by 38 + carry passes.

    One operand is repacked on the fly into 16 limbs of 16 bits
    (a16_i = a_{2i} + 256*a_{2i+1}), halving the multiply count vs the
    straight 32x32 schoolbook while every product still fits int32:
      a16_i < 2^9 + 256*(2^9-1) < 2^17.01 (loose 8-bit limbs < 2^9)
      a16_i * b_j < 2^26.01, column sum of <=16 terms < 2^30.01 < int32.
    A plain (wrap-free) carry pass brings columns under 2^22.4 so the
    fold by 38 (2^256 = 38 mod p) stays in int32; the standard 4-pass
    chain then restores the loose invariant (fold < 2^27.7, below the
    2^28.3 the chain was verified for).
    """
    shape = jnp.broadcast_shapes(a.shape, b.shape)[:-1]
    a = jnp.broadcast_to(a, (*shape, NLIMBS))
    a16 = a[..., 0::2] + (a[..., 1::2] << 8)  # [..., 16]
    out = jnp.zeros((*shape, 63), dtype=jnp.int32)
    for i in range(16):
        out = out.at[..., 2 * i : 2 * i + NLIMBS].add(a16[..., i : i + 1] * b)
    # wrap-free carry: conv columns end at 2*15+31 = 61, so the carry out
    # of column 61 lands in the zero column 62 and nothing is lost
    c = out >> 8
    r = out - (c << 8)
    out = r + jnp.concatenate(
        [jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1
    )
    lo = out[..., :NLIMBS]
    hi = out[..., NLIMBS:]
    folded = lo.at[..., :31].add(hi * 38)
    x = folded
    for _ in range(4):
        x = _carry_pass(x)
    return x


def sqr(x: jnp.ndarray) -> jnp.ndarray:
    return mul(x, x)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a * k for small non-negative int k.

    Bound: loose input (< 2^9) * k must survive three carry passes back to
    the loose invariant, which holds for k <= 2^17 (products < 2^26, well
    inside int32; pass chain verified numerically at the worst case).
    """
    assert 0 <= k <= 1 << 17, "mul_small constant out of verified range"
    x = a * k
    x = _carry_pass(x)
    x = _carry_pass(x)
    return _carry_pass(x)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """cond ? a : b, limb-wise; cond is [...] bool broadcast over limbs."""
    return jnp.where(cond[..., None], a, b)


def _sqr_n(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """x^(2^n) via lax.fori_loop (keeps the traced graph small)."""
    return jax.lax.fori_loop(0, n, lambda _, v: mul(v, v), x)


def _pow_2_250_minus_1(z: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """z^(2^250 - 1) — shared prefix of the inversion/sqrt chains (ref10)."""
    z2 = sqr(z)
    z9 = mul(sqr(sqr(z2)), z)
    z11 = mul(z9, z2)
    z2_5_0 = mul(sqr(z11), z9)  # z^(2^5-1)
    z2_10_0 = mul(_sqr_n(z2_5_0, 5), z2_5_0)
    z2_20_0 = mul(_sqr_n(z2_10_0, 10), z2_10_0)
    z2_40_0 = mul(_sqr_n(z2_20_0, 20), z2_20_0)
    z2_50_0 = mul(_sqr_n(z2_40_0, 10), z2_10_0)
    z2_100_0 = mul(_sqr_n(z2_50_0, 50), z2_50_0)
    z2_200_0 = mul(_sqr_n(z2_100_0, 100), z2_100_0)
    z2_250_0 = mul(_sqr_n(z2_200_0, 50), z2_50_0)
    return z2_250_0, z11


def invert(z: jnp.ndarray) -> jnp.ndarray:
    """z^(p-2) = z^(2^255 - 21). Returns 0 for z = 0."""
    z2_250_0, z11 = _pow_2_250_minus_1(z)
    return mul(_sqr_n(z2_250_0, 5), z11)


def pow22523(z: jnp.ndarray) -> jnp.ndarray:
    """z^((p-5)/8) = z^(2^252 - 3), used by sqrt-ratio in decompression."""
    z2_250_0, _ = _pow_2_250_minus_1(z)
    return mul(_sqr_n(z2_250_0, 2), z)


def invert_many(z: jnp.ndarray) -> jnp.ndarray:
    """Batched inversion of [B, 32] via Montgomery's trick.

    Parallel prefix/suffix product scans + ONE Fermat inversion of the
    total product: inv(z_i) = prefix_{i-1} * suffix_{i+1} * inv(total).
    ~7 batch-muls of work instead of the 265 of per-element `invert`
    (the compress stage's cost drops accordingly). Rows equal to zero
    invert to 0, matching `invert` — and are masked to 1 inside the
    product chain so one zero row cannot poison the whole batch.
    """
    zero_mask = is_zero(z)
    safe = select(zero_mask, ones(z.shape[:-1]), z)
    prefix = jax.lax.associative_scan(mul, safe, axis=0)
    suffix = jax.lax.associative_scan(mul, safe, axis=0, reverse=True)
    total_inv = invert(prefix[-1])
    one_row = ones((1,))
    excl_p = jnp.concatenate([one_row, prefix[:-1]], axis=0)
    excl_s = jnp.concatenate([suffix[1:], one_row], axis=0)
    inv = mul(mul(excl_p, excl_s), jnp.broadcast_to(total_inv, z.shape))
    return select(zero_mask, zeros(z.shape[:-1]), inv)


def _scan_carry(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact sequential carry over the limb axis (no wrap).

    Returns (strict limbs in [0, 255], top carry = value >> 256).
    Works for signed inputs too (borrows propagate as negative carries).
    """
    xt = jnp.moveaxis(x, -1, 0)  # [32, ...]

    def step(carry, limb):
        v = limb + carry
        c = v >> 8
        return c, v - (c << 8)

    top, limbs = jax.lax.scan(step, jnp.zeros_like(xt[0]), xt)
    return jnp.moveaxis(limbs, 0, -1), top


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Freeze a loose element to its canonical limbs in [0, p).

    Only used at batch boundaries (encoding, equality); costs a few
    lax.scan passes over the 32 limbs.
    """
    # 1. exact carry; fold top carry K (V = K*2^256 + V0 ≡ V0 + 38K).
    limbs, top = _scan_carry(x)
    limbs = limbs.at[..., 0].add(top * 38)
    limbs, top = _scan_carry(limbs)  # top == 0 now (V0 + 38K < 2^256 + 114)
    limbs = limbs.at[..., 0].add(top * 38)
    # 2. fold bit 255: V = q*2^255 + W ≡ W + 19q.
    q = limbs[..., 31] >> 7
    limbs = limbs.at[..., 31].add(-(q << 7))
    limbs = limbs.at[..., 0].add(q * 19)
    limbs, _ = _scan_carry(limbs)
    q = limbs[..., 31] >> 7
    limbs = limbs.at[..., 31].add(-(q << 7))
    limbs = limbs.at[..., 0].add(q * 19)  # cannot ripple: W < 134 here if q=1
    # 3. now V < 2^255; subtract p once if V >= p.
    p_l = jnp.asarray(P_LIMBS)
    diff = limbs - p_l
    # most-significant nonzero difference decides >=
    nz = diff != 0
    # index of the highest nonzero limb (0 if none)
    idx = (NLIMBS - 1) - jnp.argmax(nz[..., ::-1], axis=-1)
    ms = jnp.take_along_axis(diff, idx[..., None], axis=-1)[..., 0]
    any_nz = jnp.any(nz, axis=-1)
    geq = jnp.where(any_nz, ms > 0, True)  # equal -> subtract to get 0
    limbs = limbs - p_l * geq[..., None].astype(jnp.int32)
    limbs, _ = _scan_carry(limbs)
    return limbs


def to_bytes(x: jnp.ndarray) -> jnp.ndarray:
    """Canonical little-endian 32-byte encoding as [..., 32] uint8."""
    return canonical(x).astype(jnp.uint8)


def from_bytes(b: jnp.ndarray) -> jnp.ndarray:
    """[..., 32] uint8 little-endian bytes -> loose limbs (identity map)."""
    return b.astype(jnp.int32)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Canonical equality: [...] bool."""
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(canonical(x) == 0, axis=-1)


def parity(x: jnp.ndarray) -> jnp.ndarray:
    """Low bit of the canonical value (the ed25519 sign bit source)."""
    return canonical(x)[..., 0] & 1
