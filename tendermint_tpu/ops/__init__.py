"""Device-plane kernels (JAX/XLA, Pallas where it pays).

The reference's compute-heavy primitives (SURVEY.md §2.2) re-designed for TPU:
batched ed25519 verification (field/curve arithmetic over 2^255-19, SHA-512,
double-scalar multiplication), batched SHA-2, BLS12-381. Everything operates
on fixed-shape batches, is `jit`/`vmap`/`shard_map` friendly, and uses int32
lane arithmetic (radix-2^8 limbs) so it compiles natively on TPU (no 64-bit
integer ops).
"""
