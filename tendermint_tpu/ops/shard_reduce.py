"""Mesh-wide point aggregation: shard_map + explicit XOR-butterfly.

Why not `jax.jit(in_shardings=...)` over the halving tree: the GSPMD
partitioner has to propagate shardings through the strided slices and
95-step carry scans of the limb arithmetic, and on the wide Fp2 forms
that is pathological — observed on the 8-device CPU mesh as an
XLA-compiler segfault for the inlined 3-level G2 tree and a >40-minute
compile for even ONE sharded G2 add level. `shard_map` sidesteps the
partitioner entirely: each device compiles a small LOCAL program (its
shard's reduction tree) and the cross-device combine is an explicit
`lax.ppermute` butterfly — the collective rides ICI, exactly the
SURVEY §2.3 design, and the compile cost is log2 small adds.

The butterfly requires power-of-two axis sizes (every practical mesh
here; parallel/mesh.py builds 2^k axes). After log2(size) rounds of
`x += ppermute(x, i ^ step)` every shard holds the full sum, so the
result is read from shard 0.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35 exposes it at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

# the "don't verify replication" kwarg was renamed check_rep -> check_vma
import inspect

try:
    _CHECK_KW = (
        "check_vma"
        if "check_vma" in inspect.signature(_shard_map).parameters
        else "check_rep"
    )
except (TypeError, ValueError):  # pragma: no cover
    _CHECK_KW = "check_rep"

_CACHE: dict = {}


def aggregate_sharded(points, mesh, add_fn, identity, trailing_shape):
    """Sum [B, *trailing_shape] int32 points over the mesh -> one point.

    add_fn must be a batched complete point addition; identity the
    numpy identity point of shape trailing_shape."""
    n = int(mesh.devices.size)
    for ax in mesh.axis_names:
        size = int(mesh.shape[ax])
        assert size & (size - 1) == 0, (
            f"butterfly all-reduce needs power-of-two axes, got "
            f"{ax}={size}"
        )
    b = points.shape[0]
    per = max(1, -(-b // n))
    per = 1 << (per - 1).bit_length()
    nb = per * n
    pts = np.asarray(points)
    if nb != b:
        pad = np.broadcast_to(
            identity, (nb - b, *trailing_shape)
        ).astype(pts.dtype)
        pts = np.concatenate([pts, pad], axis=0)

    spec = P(mesh.axis_names)
    key = (mesh, nb, add_fn)
    fn = _CACHE.get(key)
    if fn is None:

        def local(p):
            # p: [per, *trailing] — this shard's slice
            while p.shape[0] > 1:
                p = add_fn(p[0::2], p[1::2])
            x = p
            for ax in mesh.axis_names:
                size = int(mesh.shape[ax])
                step = 1
                while step < size:
                    perm = [(i, i ^ step) for i in range(size)]
                    x = add_fn(x, jax.lax.ppermute(x, ax, perm))
                    step *= 2
            return x

        fn = jax.jit(
            _shard_map(
                local,
                mesh=mesh,
                in_specs=spec,
                out_specs=spec,
                **{_CHECK_KW: False},
            )
        )
        _CACHE[key] = fn
    out = fn(jax.device_put(pts, NamedSharding(mesh, spec)))
    return jnp.asarray(np.asarray(out)[0])
