"""Batched secp256k1 ECDSA verification on TPU (SURVEY §2.2 row
"secp256k1 verify").

The device side of the split crypto/secp_native.py already uses for the
native host path: the host does the cheap bignum work (signature
parsing, low-S check, u1 = z/s, u2 = r/s mod n, pubkey decompression —
each pubkey's affine coordinates cacheable per validator) and the
device verifies B signatures at once by computing R_i = u1_i*G + u2_i*Q_i
as a joint Straus ladder and checking x(R_i) mod n == r_i, all as one
straight-line XLA program with mask-based control flow — the same shape
as the ed25519 kernel (ops/ed25519_batch.py).

Field arithmetic comes from ops/vecfield.py (radix-2^8 int32 limbs,
p = 2^256 - 2^32 - 977); the curve is y^2 = x^3 + 7 (a = 0), Jacobian
coordinates, dbl-2009-l / add-2007-bl formulas matching the host oracle
(crypto/secp256k1.py) limb-for-limb after canonicalization.

On this harness's executor the native host batch (~2k sigs/s) and this
kernel trade places depending on batch size; the BatchVerifier routes
secp rows here only when TM_TPU_SECP_DEVICE=1 (real-silicon design,
same gating philosophy as TM_TPU_MXU_GATHER — see PERF_ANALYSIS.md).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import vecfield

P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

fe = vecfield.make_field(P, 32)
NLIMBS = fe.NLIMBS

# n as byte limbs for the mod-n comparison (n < p < 2n, so
# x mod n ∈ {x, x - n})
_N_LIMBS = np.array([int(b) for b in N.to_bytes(32, "little")], dtype=np.int32)


# --- Jacobian group law (a = 0) -------------------------------------------


def identity(shape=()) -> jnp.ndarray:
    z = np.zeros((*shape, 3, NLIMBS), dtype=np.int32)
    z[..., 1, 0] = 1  # (0, 1, 0)
    return jnp.asarray(z)


def from_affine_host(x: int, y: int) -> np.ndarray:
    return np.stack([fe.from_int(x), fe.from_int(y), fe.from_int(1)])


def is_inf(p: jnp.ndarray) -> jnp.ndarray:
    return fe.is_zero(p[..., 2, :])


def double(p: jnp.ndarray) -> jnp.ndarray:
    x, y, z = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    a = fe.sqr(x)
    b = fe.sqr(y)
    c = fe.sqr(b)
    xb = fe.add(x, b)
    d = fe.mul_small(fe.sub(fe.sub(fe.sqr(xb), a), c), 2)
    e = fe.mul_small(a, 3)
    f = fe.sqr(e)
    x3 = fe.sub(f, fe.mul_small(d, 2))
    y3 = fe.sub(fe.mul(e, fe.sub(d, x3)), fe.mul_small(c, 8))
    z3 = fe.mul_small(fe.mul(y, z), 2)
    bad = fe.is_zero(y) | fe.is_zero(z)
    out = jnp.stack([x3, y3, z3], axis=-2)
    return jnp.where(bad[..., None, None], identity(p.shape[:-2]), out)


def add_points(p: jnp.ndarray, q: jnp.ndarray) -> jnp.ndarray:
    """Complete masked addition (add-2007-bl + doubling/infinity masks,
    mirroring ops/bls_g1.g1_add)."""
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    x2, y2, z2 = q[..., 0, :], q[..., 1, :], q[..., 2, :]
    z1z1 = fe.sqr(z1)
    z2z2 = fe.sqr(z2)
    u1 = fe.mul(x1, z2z2)
    u2 = fe.mul(x2, z1z1)
    s1 = fe.mul(fe.mul(y1, z2), z2z2)
    s2 = fe.mul(fe.mul(y2, z1), z1z1)
    h = fe.sub(u2, u1)
    r = fe.mul_small(fe.sub(s2, s1), 2)
    same_x = fe.is_zero(h)
    same_y = fe.is_zero(fe.sub(s2, s1))
    i = fe.sqr(fe.mul_small(h, 2))
    j = fe.mul(h, i)
    v = fe.mul(u1, i)
    x3 = fe.sub(fe.sub(fe.sqr(r), j), fe.mul_small(v, 2))
    y3 = fe.sub(
        fe.mul(r, fe.sub(v, x3)), fe.mul_small(fe.mul(s1, j), 2)
    )
    z3 = fe.mul_small(fe.mul(fe.mul(z1, z2), h), 2)
    gen = jnp.stack([x3, y3, z3], axis=-2)
    p_inf = is_inf(p)
    q_inf = is_inf(q)
    dbl = double(p)
    out = jnp.where((same_x & same_y)[..., None, None], dbl, gen)
    out = jnp.where(
        (same_x & ~same_y & ~p_inf & ~q_inf)[..., None, None],
        identity(out.shape[:-2]),
        out,
    )
    out = jnp.where(p_inf[..., None, None], q, out)
    out = jnp.where(q_inf[..., None, None], p, out)
    return out


# --- scalar digits ---------------------------------------------------------


def nibbles(scalar_bytes: jnp.ndarray) -> jnp.ndarray:
    """[..., 32] u8 big-endian scalar -> [..., 64] radix-16 digits,
    most-significant first."""
    s = scalar_bytes.astype(jnp.int32)
    hi = s >> 4
    lo = s & 15
    return jnp.stack([hi, lo], axis=-1).reshape(*s.shape[:-1], 64)


# --- G table (host, once) --------------------------------------------------

_G_TABLE_NP: np.ndarray | None = None


def _g_table() -> np.ndarray:
    """T[d] = [d]G affine-as-jacobian for d in 0..15 ([16, 3, 32]); the
    shared doubling chain of the ladder supplies the 16^j weights."""
    global _G_TABLE_NP
    if _G_TABLE_NP is None:
        from ..crypto import secp256k1 as host

        rows = [np.zeros((3, NLIMBS), dtype=np.int32)]
        rows[0][1][0] = 1  # identity (0,1,0)
        for d in range(1, 16):
            x, y = host._to_affine(host._jmul(d, (GX, GY, 1)))
            rows.append(from_affine_host(x, y))
        _G_TABLE_NP = np.stack(rows)
    return _G_TABLE_NP


def _select_entry(table: jnp.ndarray, dig: jnp.ndarray) -> jnp.ndarray:
    """table: [..., 16, 3, 32]; dig: [...] in [0, 16)."""
    return jnp.take_along_axis(
        table, dig[..., None, None, None], axis=-3
    ).squeeze(-3)


# --- the verify kernel -----------------------------------------------------


def verify_prehashed(
    qx: jnp.ndarray,  # [B, 32] i32 limbs: pubkey affine x
    qy: jnp.ndarray,  # [B, 32] i32 limbs: pubkey affine y
    u1: jnp.ndarray,  # [B, 32] u8 big-endian: z/s mod n
    u2: jnp.ndarray,  # [B, 32] u8 big-endian: r/s mod n
    r_bytes: jnp.ndarray,  # [B, 32] u8 big-endian signature r
    ok_in: jnp.ndarray,  # [B] bool host-side pre-checks (parse, low-S)
) -> jnp.ndarray:
    """[B] bool accept bitmap: x(u1*G + u2*Q) mod n == r."""
    B = qx.shape[0]
    q = jnp.stack([qx, qy, jnp.broadcast_to(fe.ones(), qx.shape)], axis=-2)
    # per-element radix-16 window table of Q: even entries by doubling
    # (cheaper and a shallower dependency chain than a 14-deep add
    # chain), odd entries by one add each
    entries: list = [None] * 16
    entries[0] = identity((B,))
    entries[1] = q
    for d in range(2, 16):
        if d % 2 == 0:
            entries[d] = double(entries[d // 2])
        else:
            entries[d] = add_points(entries[d - 1], q)
    qtab = jnp.stack(entries, axis=-3)  # [B, 16, 3, 32]
    gtab = jnp.asarray(_g_table())  # [16, 3, 32]

    d1 = nibbles(u1)  # G digits, MSB first
    d2 = nibbles(u2)  # Q digits

    def body(i, acc):
        acc = double(double(double(double(acc))))
        acc = add_points(acc, _select_entry(qtab, d2[..., i]))
        acc = add_points(acc, jnp.take(gtab, d1[..., i], axis=0))
        return acc

    rpt = jax.lax.fori_loop(0, 64, body, identity((B,)))
    # x(R) = X / Z^2; batched inversion via the Montgomery trick
    zinv = fe.invert_many(rpt[..., 2, :])
    x_aff = fe.canonical(fe.mul(rpt[..., 0, :], fe.sqr(zinv)))
    # mod n: x < p < 2n, so x mod n is x or x - n. The wrapped branch
    # must require x >= n (scan borrow top == 0), or a pattern match on
    # the 2^256-wrapped negative difference could false-accept.
    r_le = r_bytes[..., ::-1].astype(jnp.int32)  # to little-endian limbs
    direct = jnp.all(x_aff == r_le, axis=-1)
    x_min_n, borrow = fe._scan_carry(x_aff - jnp.asarray(_N_LIMBS))
    wrapped = (borrow == 0) & jnp.all(x_min_n == r_le, axis=-1)
    # reject R at infinity (Z == 0 -> zinv == 0 -> x_aff == 0 could
    # false-match r == 0, but r >= 1 is host-checked; still mask it)
    return ok_in & ~is_inf(rpt) & (direct | wrapped)


verify_prehashed_jit = jax.jit(verify_prehashed)
