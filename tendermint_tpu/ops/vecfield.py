"""Parameterized radix-2^8 prime-field arithmetic for TPU kernels.

The generalization of ops/bls_g1.py's field scheme (see that module for
the fully-derived instance with per-step bound commentary): limbs of 8
bits in int32 lanes, loose invariant limbs < 2^11, carry passes whose
top carry wraps through the vector constant F0 = 2^(8*NLIMBS) mod p,
multiplication as a full limb convolution folded by the table
F[i] = 2^(8*(NLIMBS+i)) mod p, and canonicalization via a Barrett-style
quotient estimate. Works for any prime whose loose-conv columns stay
int32-safe: NLIMBS products of < 2^22 requires NLIMBS < 2^9 — true for
every curve field here.

Instantiated by ops/secp256k1_kernel.py (p = 2^256 - 2^32 - 977);
ops/bls_g1.py predates the factory and keeps its in-file derivation as
documentation. Bounds are pinned by per-instance worst-case stress
tests (tests/test_ops_secp.py, tests/test_ops_bls_g1.py).
"""

from __future__ import annotations

from types import SimpleNamespace

import numpy as np

import jax
import jax.numpy as jnp


def make_field(
    P: int, nlimbs: int, mul_style: str = "slices"
) -> SimpleNamespace:
    """mul_style selects how the limb convolution inside `mul` is built:

    - "slices" (default): NLIMBS shifted slice-adds — the original scheme,
      kept for the existing kernels so their compiled artifacts stay valid.
    - "matmul": one outer product + one 0/1 fold matmul. Identical column
      sums (bit-exact; the conv is a reordering of the same int32 adds,
      bounds unchanged: ≤ NLIMBS · 2^22 < 2^31) but ~5x fewer HLO ops per
      mul — chosen by graph-size-bound consumers (the pairing kernel
      traces hundreds of muls per scan body).
    """
    NLIMBS = nlimbs

    def _limbs_of(x: int, n: int = NLIMBS) -> np.ndarray:
        return np.array(
            [int(b) for b in x.to_bytes(n, "little")], dtype=np.int32
        )

    P_LIMBS = _limbs_of(P)
    F_FOLD = np.stack(
        [_limbs_of(pow(2, 8 * (NLIMBS + i), P)) for i in range(NLIMBS + 2)]
    )
    F0 = F_FOLD[0]

    # additive bias ≡ 0 (mod p) with every limb >= 2048: keeps `sub`
    # limb-wise non-negative for loose (< 2^11) subtrahends (the 128p
    # decomposition trick of ops/bls_g1.py, generalized). Construction:
    # write 128p = [2048 in every limb] + remainder; the remainder's
    # 2^(8*NLIMBS) overflow folds through F0 (2^(8*NLIMBS) ≡ F0 mod p),
    # preserving the value mod p. Limbs stay < 2^15 (top ≤ 128, F0
    # limbs < 256), which two carry passes after `sub` bring back under
    # the loose invariant.
    def _bias_limbs() -> np.ndarray:
        base_val = sum(2048 << (8 * i) for i in range(NLIMBS))
        rest = 128 * P - base_val
        assert rest > 0
        top = rest >> (8 * NLIMBS)
        db = (rest - (top << (8 * NLIMBS))).to_bytes(NLIMBS, "little")
        out = np.array(
            [2048 + db[i] for i in range(NLIMBS)], dtype=np.int64
        )
        out += int(top) * F0.astype(np.int64)
        assert all(2048 <= int(v) < (1 << 15) for v in out)
        assert sum(int(v) << (8 * i) for i, v in enumerate(out)) % P == 0
        return out.astype(np.int32)

    BIAS = _bias_limbs()

    MU = (1 << (8 * NLIMBS + 8)) // P

    def from_int(x: int) -> np.ndarray:
        return _limbs_of(x % P)

    def to_int(limbs) -> int:
        arr = np.asarray(limbs, dtype=np.int64)
        return int(sum(int(v) << (8 * i) for i, v in enumerate(arr.tolist())))

    def zeros(shape=()) -> jnp.ndarray:
        return jnp.zeros((*shape, NLIMBS), dtype=jnp.int32)

    def ones(shape=()) -> jnp.ndarray:
        z = np.zeros((*shape, NLIMBS), dtype=np.int32)
        z[..., 0] = 1
        return jnp.asarray(z)

    def _carry_pass(x: jnp.ndarray) -> jnp.ndarray:
        c = x >> 8
        r = x - (c << 8)
        wrap = jnp.concatenate(
            [jnp.zeros_like(c[..., :1]), c[..., :-1]], axis=-1
        )
        return r + wrap + c[..., -1:] * jnp.asarray(F0)

    def add(a, b):
        return _carry_pass(a + b)

    def sub(a, b):
        x = a + jnp.asarray(BIAS) - b
        x = _carry_pass(x)
        return _carry_pass(x)

    def neg(a):
        x = jnp.asarray(BIAS) - a
        x = _carry_pass(x)
        return _carry_pass(x)

    def _scan_carry(x):
        xt = jnp.moveaxis(x, -1, 0)

        def step(carry, limb):
            v = limb + carry
            c = v >> 8
            return c, v - (c << 8)

        top, limbs = jax.lax.scan(step, jnp.zeros_like(xt[0]), xt)
        return jnp.moveaxis(limbs, 0, -1), top

    if mul_style == "matmul":
        _CONV = np.zeros((NLIMBS * NLIMBS, 2 * NLIMBS - 1), dtype=np.int32)
        for i in range(NLIMBS):
            for j in range(NLIMBS):
                _CONV[i * NLIMBS + j, i + j] = 1

    def mul(a, b):
        shape = jnp.broadcast_shapes(a.shape, b.shape)[:-1]
        if mul_style == "matmul":
            aa = jnp.broadcast_to(a, (*shape, NLIMBS))
            bb = jnp.broadcast_to(b, (*shape, NLIMBS))
            outer = aa[..., :, None] * bb[..., None, :]
            out = jnp.matmul(
                outer.reshape(*shape, NLIMBS * NLIMBS), jnp.asarray(_CONV)
            )
        else:
            out = jnp.zeros((*shape, 2 * NLIMBS - 1), dtype=jnp.int32)
            for i in range(NLIMBS):
                out = out.at[..., i : i + NLIMBS].add(a[..., i : i + 1] * b)
        limbs, top = _scan_carry(out)
        t_lo = top & 255
        t_hi = top >> 8
        hi_bytes = jnp.concatenate(
            [limbs[..., NLIMBS:], t_lo[..., None], t_hi[..., None]], axis=-1
        )
        folded = limbs[..., :NLIMBS] + jnp.matmul(
            hi_bytes, jnp.asarray(F_FOLD[: NLIMBS + 1])
        )
        x = folded
        for _ in range(5):
            x = _carry_pass(x)
        return x

    def sqr(a):
        return mul(a, a)

    def mul_small(a, k: int):
        assert 0 <= k <= 1 << 14
        x = a * k
        x = _carry_pass(x)
        x = _carry_pass(x)
        return _carry_pass(x)

    def select(cond, a, b):
        return jnp.where(cond[..., None], a, b)

    def canonical(x):
        limbs, top = _scan_carry(x)
        for _ in range(4):
            limbs = limbs + top[..., None] * jnp.asarray(F0)
            limbs, top = _scan_carry(limbs)
        p_l = jnp.asarray(P_LIMBS)
        t16 = (limbs[..., NLIMBS - 1] << 8) | limbs[..., NLIMBS - 2]
        q = jnp.maximum((t16 * MU) >> 24, 0)
        limbs, _ = _scan_carry(limbs - q[..., None] * p_l)
        for _ in range(3):
            diff = limbs - p_l
            nz = diff != 0
            idx = (NLIMBS - 1) - jnp.argmax(nz[..., ::-1], axis=-1)
            ms = jnp.take_along_axis(diff, idx[..., None], axis=-1)[..., 0]
            geq = jnp.where(jnp.any(nz, axis=-1), ms > 0, True)
            limbs = limbs - p_l * geq[..., None].astype(jnp.int32)
            limbs, _ = _scan_carry(limbs)
        return limbs

    def is_zero(x):
        return jnp.all(canonical(x) == 0, axis=-1)

    def eq(a, b):
        return jnp.all(canonical(a) == canonical(b), axis=-1)

    def invert_many(z):
        """Batched inversion over axis 0: Montgomery trick — prefix and
        suffix product scans + ONE Fermat chain for the total (mirrors
        field25519.invert_many). Zero rows invert to zero."""
        zero = is_zero(z)
        safe = select(zero, ones(z.shape[:-1]), z)
        prefix = jax.lax.associative_scan(mul, safe, axis=0)
        suffix = jax.lax.associative_scan(mul, safe, axis=0, reverse=True)
        total_inv = invert(prefix[-1])
        one_row = ones((1,))
        excl_p = jnp.concatenate([one_row, prefix[:-1]], axis=0)
        excl_s = jnp.concatenate([suffix[1:], one_row], axis=0)
        inv = mul(mul(excl_p, excl_s), jnp.broadcast_to(total_inv, z.shape))
        return select(zero, zeros(z.shape[:-1]), inv)

    # Fermat inversion: z^(p-2), square-and-multiply with the exponent
    # bits as a device constant and a fori_loop body of one sqr + one
    # masked mul — a statically-unrolled chain would trace ~2*bits(p)
    # muls (crypto primes have dense exponents) and blow up compile
    # time; the loop graph is ~80 ops regardless of the prime.
    _E_BITS_ARR = np.array(
        [
            (P - 2) >> i & 1
            for i in range((P - 2).bit_length() - 2, -1, -1)
        ],
        dtype=np.int32,
    )

    def invert(z):
        bits = jnp.asarray(_E_BITS_ARR)

        def body(i, r):
            r = sqr(r)
            mz = mul(r, z)
            return jnp.where((bits[i] == 1)[..., None], mz, r)

        return jax.lax.fori_loop(0, len(_E_BITS_ARR), body, z)

    return SimpleNamespace(
        P=P,
        NLIMBS=NLIMBS,
        P_LIMBS=P_LIMBS,
        F0=F0,
        BIAS=BIAS,
        from_int=from_int,
        to_int=to_int,
        zeros=zeros,
        ones=ones,
        add=add,
        sub=sub,
        neg=neg,
        mul=mul,
        sqr=sqr,
        mul_small=mul_small,
        select=select,
        canonical=canonical,
        is_zero=is_zero,
        eq=eq,
        invert=invert,
        invert_many=invert_many,
        _carry_pass=_carry_pass,
        _scan_carry=_scan_carry,
    )
