"""Validator signing (SURVEY.md layer 8, reference privval/ ~1.7k LoC):
file-backed signer with double-sign protection + remote signer protocol."""

from .file_pv import FilePV  # noqa: F401
