"""Remote signer protocol — keep validator keys in a separate process.

Reference: privval/signer_client.go (node side), signer_listener_endpoint
/ signer_dialer_endpoint, signer_requestHandler.go, retry wrapper
retry_signer_client.go. Topology matches the reference: the NODE listens
(SignerListenerEndpoint), the SIGNER dials in (SignerDialerEndpoint) so
the key machine needs no open ports. Frames are uvarint-delimited JSON.
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

from ..libs import protoio as pio
from ..types.proposal import Proposal
from ..types.vote import Vote


async def _read_frame(reader: asyncio.StreamReader) -> bytes:
    shift = n = 0
    while True:
        b = (await reader.readexactly(1))[0]
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return await reader.readexactly(n)


def _write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(pio.write_uvarint(len(payload)) + payload)


class RemoteSignerError(Exception):
    pass


class SignerListenerEndpoint:
    """Node side: listens for the signer's inbound connection and forwards
    sign requests over it. Implements the PrivValidator surface via the
    async `client()` — consensus uses SignerClient below."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host, self._port = host, port
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn: Optional[tuple] = None
        self._conn_ready = asyncio.Event()
        self._lock = asyncio.Lock()

    @property
    def port(self) -> int:
        return self._port

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connect, self._host, self._port
        )
        if self._port == 0:
            self._port = self._server.sockets[0].getsockname()[1]

    async def _on_connect(self, reader, writer) -> None:
        # returning keeps the streams open; we hold the references
        self._conn = (reader, writer)
        self._conn_ready.set()

    async def wait_for_signer(self, timeout: float = 5.0) -> None:
        await asyncio.wait_for(self._conn_ready.wait(), timeout)

    async def request(self, msg: dict, timeout: float = 5.0) -> dict:
        async with self._lock:
            if self._conn is None:
                raise RemoteSignerError("no signer connected")
            reader, writer = self._conn
            _write_frame(writer, json.dumps(msg).encode())
            await writer.drain()
            resp = json.loads(
                (await asyncio.wait_for(_read_frame(reader), timeout)).decode()
            )
            if "error" in resp:
                raise RemoteSignerError(resp["error"])
            return resp

    async def stop(self) -> None:
        if self._conn is not None:
            self._conn[1].close()
            self._conn = None
        if self._server:
            self._server.close()
            await self._server.wait_closed()


class SignerClient:
    """Async PrivValidator over a listener endpoint (reference
    privval/signer_client.go). Consensus awaits these."""

    def __init__(self, endpoint: SignerListenerEndpoint):
        self._ep = endpoint
        self._pub_key = None

    async def get_pub_key(self):
        if self._pub_key is None:
            from ..crypto import ed25519

            resp = await self._ep.request({"m": "pub_key"})
            self._pub_key = ed25519.PubKey(bytes.fromhex(resp["pub_key"]))
        return self._pub_key

    async def sign_vote(self, chain_id: str, vote: Vote) -> None:
        resp = await self._ep.request(
            {"m": "sign_vote", "chain_id": chain_id, "vote": vote.encode().hex()}
        )
        signed = Vote.decode(bytes.fromhex(resp["vote"]))
        vote.signature = signed.signature
        vote.timestamp_ns = signed.timestamp_ns
        vote.bls_signature = signed.bls_signature
        vote.qc_signature = signed.qc_signature

    async def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        resp = await self._ep.request(
            {
                "m": "sign_proposal",
                "chain_id": chain_id,
                "proposal": proposal.encode().hex(),
            }
        )
        signed = Proposal.decode(bytes.fromhex(resp["proposal"]))
        proposal.signature = signed.signature
        proposal.timestamp_ns = signed.timestamp_ns

    async def ping(self) -> bool:
        resp = await self._ep.request({"m": "ping"})
        return resp.get("pong", False)


class SignerServer:
    """Signer side: dials the node and serves sign requests from a local
    PrivValidator (reference signer_dialer_endpoint + request handler)."""

    def __init__(self, pv, host: str, port: int):
        self._pv = pv
        self._host, self._port = host, port
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        reader, writer = await asyncio.open_connection(self._host, self._port)
        self._writer = writer
        self._task = asyncio.get_running_loop().create_task(
            self._serve(reader, writer)
        )

    async def _serve(self, reader, writer) -> None:
        try:
            while True:
                req = json.loads((await _read_frame(reader)).decode())
                try:
                    resp = self._handle(req)
                except Exception as e:
                    resp = {"error": repr(e)}
                _write_frame(writer, json.dumps(resp).encode())
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass

    def _handle(self, req: dict) -> dict:
        m = req["m"]
        if m == "ping":
            return {"pong": True}
        if m == "pub_key":
            return {"pub_key": self._pv.get_pub_key().data.hex()}
        if m == "sign_vote":
            vote = Vote.decode(bytes.fromhex(req["vote"]))
            self._pv.sign_vote(req["chain_id"], vote)
            return {"vote": vote.encode().hex()}
        if m == "sign_proposal":
            prop = Proposal.decode(bytes.fromhex(req["proposal"]))
            self._pv.sign_proposal(req["chain_id"], prop)
            return {"proposal": prop.encode().hex()}
        raise RemoteSignerError(f"unknown method {m}")

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
        if getattr(self, "_writer", None) is not None:
            self._writer.close()
