"""FilePV — file-backed validator signer with double-sign protection.

Reference: privval/file.go — two files: the key (FilePVKey) and the
last-sign state (FilePVLastSignState :75-148). The HRS monotonic guard
(`CheckHRS` :92) refuses to sign at a lower (height, round, step); at the
SAME HRS it re-signs only if the sign-bytes differ solely by timestamp, in
which case it returns the PREVIOUS signature and timestamp
(:401-434 checkVotesOnlyDifferByTimestamp) — crash-safe idempotent signing.
"""

from __future__ import annotations

import json
import os
import tempfile
from dataclasses import dataclass, field
from typing import Optional

from ..crypto import ed25519
from ..types import canonical
from ..types.proposal import Proposal
from ..types.vote import Vote, VoteType

STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_VOTE_STEP = {
    VoteType.PREVOTE: STEP_PREVOTE,
    VoteType.PRECOMMIT: STEP_PRECOMMIT,
}


class DoubleSignError(Exception):
    pass


def _atomic_write(path: str, data: str) -> None:
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


@dataclass
class LastSignState:
    height: int = 0
    round: int = 0
    step: int = 0
    signature: bytes = b""
    sign_bytes: bytes = b""

    def check_hrs(self, height: int, round_: int, step: int) -> bool:
        """Returns True if this exact HRS was already signed (caller must
        then check sign-bytes); raises on regression (reference CheckHRS
        privval/file.go:92)."""
        if self.height > height:
            raise DoubleSignError(f"height regression: {self.height} > {height}")
        if self.height == height:
            if self.round > round_:
                raise DoubleSignError(
                    f"round regression at height {height}: "
                    f"{self.round} > {round_}"
                )
            if self.round == round_:
                if self.step > step:
                    raise DoubleSignError(
                        f"step regression at {height}/{round_}: "
                        f"{self.step} > {step}"
                    )
                if self.step == step:
                    if not self.sign_bytes:
                        raise DoubleSignError("no sign bytes for same HRS")
                    return True
        return False


class FilePV:
    def __init__(
        self,
        priv_key: ed25519.PrivKey,
        key_path: str,
        state_path: str,
        last_state: Optional[LastSignState] = None,
    ):
        self.priv_key = priv_key
        self._key_path = key_path
        self._state_path = state_path
        self.last_state = last_state or LastSignState()

    # --- persistence ------------------------------------------------------

    @classmethod
    def generate(cls, key_path: str, state_path: str) -> "FilePV":
        pv = cls(ed25519.PrivKey.generate(), key_path, state_path)
        pv.save()
        return pv

    @classmethod
    def load_or_generate(cls, key_path: str, state_path: str) -> "FilePV":
        if os.path.exists(key_path):
            return cls.load(key_path, state_path)
        return cls.generate(key_path, state_path)

    @classmethod
    def load(cls, key_path: str, state_path: str) -> "FilePV":
        with open(key_path) as f:
            kd = json.load(f)
        priv = ed25519.PrivKey(bytes.fromhex(kd["priv_key"]))
        st = LastSignState()
        if os.path.exists(state_path):
            with open(state_path) as f:
                sd = json.load(f)
            st = LastSignState(
                height=sd["height"],
                round=sd["round"],
                step=sd["step"],
                signature=bytes.fromhex(sd.get("signature", "")),
                sign_bytes=bytes.fromhex(sd.get("sign_bytes", "")),
            )
        return cls(priv, key_path, state_path, st)

    def save(self) -> None:
        pub = self.priv_key.public_key()
        _atomic_write(
            self._key_path,
            json.dumps(
                {
                    "address": pub.address().hex(),
                    "pub_key": pub.data.hex(),
                    "priv_key": self.priv_key.seed.hex(),
                },
                indent=2,
            ),
        )
        self._save_state()

    def _save_state(self) -> None:
        st = self.last_state
        _atomic_write(
            self._state_path,
            json.dumps(
                {
                    "height": st.height,
                    "round": st.round,
                    "step": st.step,
                    "signature": st.signature.hex(),
                    "sign_bytes": st.sign_bytes.hex(),
                },
                indent=2,
            ),
        )

    # --- PrivValidator ----------------------------------------------------

    def get_pub_key(self) -> ed25519.PubKey:
        return self.priv_key.public_key()

    def sign_vote(self, chain_id: str, vote: Vote) -> None:
        step = _VOTE_STEP[vote.type]
        sign_bytes = vote.sign_bytes(chain_id)
        same_hrs = self.last_state.check_hrs(vote.height, vote.round, step)
        if same_hrs:
            if sign_bytes == self.last_state.sign_bytes:
                vote.signature = self.last_state.signature
                return
            prev_ts = _timestamp_from_vote_sign_bytes(
                self.last_state.sign_bytes
            )
            if (
                prev_ts is not None
                and _strip_vote_timestamp(sign_bytes)
                == _strip_vote_timestamp(self.last_state.sign_bytes)
            ):
                # differs only by timestamp: reuse previous sig + timestamp
                vote.timestamp_ns = prev_ts
                vote.signature = self.last_state.signature
                return
            raise DoubleSignError(
                "conflicting vote data at the same height/round/step"
            )
        sig = self.priv_key.sign(sign_bytes)
        self.last_state = LastSignState(
            vote.height, vote.round, step, sig, sign_bytes
        )
        self._save_state()  # persist BEFORE releasing the signature
        vote.signature = sig

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        sign_bytes = proposal.sign_bytes(chain_id)
        same_hrs = self.last_state.check_hrs(
            proposal.height, proposal.round, STEP_PROPOSE
        )
        if same_hrs:
            if sign_bytes == self.last_state.sign_bytes:
                proposal.signature = self.last_state.signature
                return
            prev_ts = _timestamp_from_proposal_sign_bytes(
                self.last_state.sign_bytes
            )
            if (
                prev_ts is not None
                and _strip_proposal_timestamp(sign_bytes)
                == _strip_proposal_timestamp(self.last_state.sign_bytes)
            ):
                proposal.timestamp_ns = prev_ts
                proposal.signature = self.last_state.signature
                return
            raise DoubleSignError(
                "conflicting proposal data at the same height/round"
            )
        sig = self.priv_key.sign(sign_bytes)
        self.last_state = LastSignState(
            proposal.height, proposal.round, STEP_PROPOSE, sig, sign_bytes
        )
        self._save_state()
        proposal.signature = sig


# --- sign-bytes timestamp surgery -----------------------------------------
# Canonical votes/proposals are delimited proto messages; the timestamp is
# an embedded message field. To compare "same except timestamp" we re-encode
# with the timestamp field zeroed.

from io import BytesIO

from ..libs import protoio as pio


def _strip_field(sign_bytes: bytes, field_num: int) -> Optional[bytes]:
    try:
        body = pio.read_delimited(BytesIO(sign_bytes))
        out = b""
        for fnum, wt, val in pio.iter_fields(body):
            if fnum == field_num:
                continue
            if wt == pio.WIRE_BYTES:
                out += pio.field_message(fnum, val)
            elif wt == pio.WIRE_FIXED64:
                out += pio.field_sfixed64(fnum, val)
            else:
                out += pio.tag(fnum, wt) + pio.write_varint(val)
        return out
    except (EOFError, ValueError):
        return None


def _extract_ts(sign_bytes: bytes, field_num: int) -> Optional[int]:
    try:
        body = pio.read_delimited(BytesIO(sign_bytes))
        f = pio.decode_fields(body)
        if field_num not in f:
            return None
        return canonical.decode_timestamp(f[field_num][0])
    except (EOFError, ValueError):
        return None


def _strip_vote_timestamp(sb: bytes) -> Optional[bytes]:
    return _strip_field(sb, 5)  # CanonicalVote.timestamp = field 5


def _timestamp_from_vote_sign_bytes(sb: bytes) -> Optional[int]:
    return _extract_ts(sb, 5)


def _strip_proposal_timestamp(sb: bytes) -> Optional[bytes]:
    return _strip_field(sb, 6)  # CanonicalProposal.timestamp = field 6


def _timestamp_from_proposal_sign_bytes(sb: bytes) -> Optional[int]:
    return _extract_ts(sb, 6)
