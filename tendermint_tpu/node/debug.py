"""Profiling/debug HTTP server (reference node/node.go:969-975 pprof).

The reference mounts Go's net/http/pprof on `config.RPC.PprofListenAddress`.
The equivalents here, one GET route each:

- `/debug/pprof/profile?seconds=N` — cProfile the event-loop thread for N
  seconds, return pstats text (pprof CPU profile analog).
- `/debug/pprof/goroutine`        — every thread stack + asyncio task
  stack (goroutine dump analog; pairs with libs.sync's watchdog).
- `/debug/pprof/heap`             — tracemalloc top allocations.
- `/debug/jax/trace?seconds=N`    — capture a JAX profiler trace (the
  device-plane profiler the reference has no counterpart for) into
  `<home>/traces/`, return the path; view with tensorboard/xprof.

`tendermint_tpu debug dump` (cmd/tendermint/commands/debug in the
reference) snapshots all of these plus `/status` into a directory.
"""

from __future__ import annotations

import asyncio
import cProfile
import io
import pstats
import sys
import time
import traceback
from typing import Optional

from ..libs.service import Service


def thread_and_task_dump() -> str:
    from ..libs.sync import dump_all_stacks

    out = io.StringIO()
    out.write(dump_all_stacks())
    out.write("\n")
    try:
        for task in asyncio.all_tasks():
            out.write(f"--- task {task.get_name()} ---\n")
            for f in task.get_stack(limit=20):
                traceback.print_stack(f, limit=1, file=out)
    except RuntimeError:
        pass
    return out.getvalue()


class DebugServer(Service):
    def __init__(self, host: str, port: int, trace_dir: str = "/tmp"):
        super().__init__("debug")
        self.host = host
        self.port = port
        self.trace_dir = trace_dir
        self._server: Optional[asyncio.AbstractServer] = None

    async def on_start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self.logger.info("pprof listening", addr=f"{self.host}:{self.port}")

    async def on_stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    async def _handle(self, reader, writer) -> None:
        try:
            req = await reader.readline()
            while (await reader.readline()) not in (b"\r\n", b"\n", b""):
                pass
            parts = req.decode().split(" ")
            target = parts[1] if len(parts) > 1 else "/"
            path, _, query = target.partition("?")
            params = {}
            for kv in query.split("&"):
                k, _, v = kv.partition("=")
                if k:
                    params[k] = v
            body, ctype = await self._route(path, params)
            writer.write(
                b"HTTP/1.1 200 OK\r\nContent-Type: " + ctype.encode()
                + b"\r\nContent-Length: " + str(len(body)).encode()
                + b"\r\nConnection: close\r\n\r\n" + body
            )
            await writer.drain()
        except Exception as e:  # debug surface: report, never crash the node
            try:
                msg = str(e).encode()
                writer.write(
                    b"HTTP/1.1 500 Internal\r\nContent-Length: "
                    + str(len(msg)).encode() + b"\r\n\r\n" + msg
                )
                await writer.drain()
            except Exception:
                pass
        finally:
            writer.close()

    async def _route(self, path: str, params: dict) -> tuple[bytes, str]:
        if path == "/debug/pprof/goroutine":
            return thread_and_task_dump().encode(), "text/plain"
        if path == "/debug/pprof/heap":
            return (await self._heap()).encode(), "text/plain"
        if path == "/debug/pprof/profile":
            secs = min(float(params.get("seconds", 1)), 60.0)
            return (await self._profile(secs)).encode(), "text/plain"
        if path == "/debug/jax/trace":
            secs = min(float(params.get("seconds", 1)), 60.0)
            return (await self._jax_trace(secs)).encode(), "text/plain"
        if path in ("/", "/debug/pprof"):
            return (
                b"routes: /debug/pprof/{profile,goroutine,heap}, "
                b"/debug/jax/trace",
                "text/plain",
            )
        raise ValueError(f"unknown debug route {path!r}")

    @staticmethod
    async def _heap() -> str:
        import tracemalloc

        started_here = not tracemalloc.is_tracing()
        if started_here:
            tracemalloc.start()
            await asyncio.sleep(0.1)  # let allocations accrue; non-blocking
        snap = tracemalloc.take_snapshot()
        if started_here:
            # don't leave per-allocation tracing overhead on a live node
            tracemalloc.stop()
        stats = snap.statistics("lineno")[:50]
        return "\n".join(str(s) for s in stats)

    @staticmethod
    async def _profile(secs: float) -> str:
        """Profile the loop thread: cProfile can't attach to a running
        loop from outside, so sample by running the profiler around a
        sleep ON the loop — captures everything the loop executes."""
        prof = cProfile.Profile()
        prof.enable()
        await asyncio.sleep(secs)
        prof.disable()
        s = io.StringIO()
        pstats.Stats(prof, stream=s).sort_stats("cumulative").print_stats(60)
        return s.getvalue()

    async def _jax_trace(self, secs: float) -> str:
        import os

        import jax

        path = os.path.join(self.trace_dir, f"jax-trace-{int(time.time())}")
        jax.profiler.start_trace(path)
        await asyncio.sleep(secs)
        jax.profiler.stop_trace()
        return path
