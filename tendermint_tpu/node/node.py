"""Node — dependency-injection assembly of every service.

Reference: node/node.go:775-1038 (NewNode wiring order: DBs → state →
proxyApp → eventBus+indexer → privval → handshake → evidence → blockExec →
blocksync → consensus → statesync → transport/switch/addrbook/PEX →
sequencer components), OnStart :1041-1109 (RPC → prometheus → transport →
switch → dial peers → statesync), OnStop :1112, sequencer switch :1612.
"""

from __future__ import annotations

import asyncio
import os
from typing import Optional

from ..abci.client import LocalClient
from ..blocksync.reactor import BlocksyncReactor
from ..config import Config
from ..consensus.reactor import ConsensusReactor
from ..consensus.state_machine import ConsensusState
from ..consensus.wal import WAL
from ..crypto import secp256k1
from ..evidence import EvidencePool, EvidenceReactor
from ..libs.log import Logger, default_logger
from ..libs.service import Service
from ..p2p.key import NodeKey
from ..p2p.node_info import NodeInfo
from ..p2p.pex import AddrBook, PEXReactor
from ..p2p.switch import Switch
from ..p2p.transport import MultiplexTransport, NetAddress
from ..privval.file_pv import FilePV
from ..proxy.multi_app_conn import AppConns
from ..sequencer import (
    BlockBroadcastReactor,
    LocalSigner,
    StateV2,
    StaticSequencerVerifier,
)
from ..state.execution import BlockExecutor
from ..state.state import State
from ..state.store import StateStore
from ..statesync import StateSyncReactor
from ..store.block_store import BlockStore
from ..store.kv import MemKV, SqliteKV
from ..types.event_bus import EventBus
from ..types.genesis import GenesisDoc


def init_files(config: Config, logger: Optional[Logger] = None) -> GenesisDoc:
    """`tendermint init` (reference cmd/tendermint/commands/init.go):
    generate node key, privval files, and a single-validator genesis."""
    logger = logger or default_logger()
    config.ensure_dirs()
    nk = NodeKey.load_or_generate(config.node_key_file)
    pv = FilePV.load_or_generate(
        config.priv_validator_key_file, config.priv_validator_state_file
    )
    from ..crypto import bls_signatures as bls

    bls.load_or_gen_bls_key(config.bls_key_file)
    gen_path = config.genesis_file
    if os.path.exists(gen_path):
        doc = GenesisDoc.from_file(gen_path)
        logger.info("found existing genesis", path=gen_path)
    else:
        from ..types.genesis import GenesisValidator
        import time

        doc = GenesisDoc(
            chain_id=config.base.chain_id or "test-chain-%06x" % (
                int.from_bytes(os.urandom(3), "big")
            ),
            genesis_time_ns=time.time_ns(),
            validators=[
                GenesisValidator(
                    "ed25519", pv.get_pub_key().data, 10
                )
            ],
        )
        doc.validate_and_complete()
        doc.save_as(gen_path)
        logger.info("generated genesis", path=gen_path, chain_id=doc.chain_id)
    logger.info("node id", id=nk.id)
    return doc


class _ConnProxy:
    """Delegates the Application call surface to one named AppConns
    connection (available after proxy_app.start()); the delegated
    methods are async client methods — every consumer in the tree
    (executor, handshaker, syncer, statesync reactor, rpc core) awaits
    coroutine results."""

    def __init__(self, conns, name: str):
        self._conns = conns
        self._name = name

    def __getattr__(self, item):
        conn = getattr(self._conns, self._name)
        if conn is None:
            raise RuntimeError(
                f"proxy app connection {self._name!r} not started"
            )
        return getattr(conn, item)


class Node(Service):
    """One running node over a local ABCI app + (mock or real) L2 node."""

    def __init__(
        self,
        config: Config,
        app=None,
        l2_node=None,
        genesis: Optional[GenesisDoc] = None,
        logger: Optional[Logger] = None,
    ):
        logger = logger or default_logger()
        super().__init__("node", logger)
        self.config = config
        config.ensure_dirs()

        # --- identity / keys (node.go:100-129) ---
        self.node_key = NodeKey.load_or_generate(config.node_key_file)
        self.priv_validator = FilePV.load_or_generate(
            config.priv_validator_key_file, config.priv_validator_state_file
        )

        # --- BLS dual-signing key (node.go:106-113: the reference loads
        # blssignatures.KeyFile at startup and refuses to run without it).
        # Loaded (or generated, like the other key files) so the assembled
        # node actually dual-signs batch-point precommits.
        from ..crypto import aead, bls_native, secp_native
        from ..crypto import bls_signatures as bls

        # build/load the native crypto NOW, not on the event loop
        # mid-consensus (the first call may invoke g++ for seconds);
        # aead backs every p2p secret-connection frame
        bls_native.native_lib()
        secp_native.native_lib()
        aead._native_lib()
        # persistent XLA compile cache under the node home: table-build
        # and verify programs compile once per machine, not once per
        # process restart. jax is already imported by this module's
        # import chain, so env vars would be silently ignored — use
        # jax.config directly (bench.py/conftest.py can use env vars
        # because they run before any jax import).
        try:
            import jax as _jax

            # machine-level shared dir (content-addressed, multi-process
            # safe): the multiprocess testnets and every node on a host
            # amortize the same table-build/verify compiles. An explicit
            # JAX_COMPILATION_CACHE_DIR in the environment wins.
            from ..crypto._native_build import _host_tag

            # per-host-ISA subdir: XLA:CPU AOT entries embed host
            # instructions; a cross-host entry on a shared dir is a
            # SIGILL/segfault, not a cache miss (libs/jax_cache.py)
            cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or (
                os.path.join(
                    os.path.expanduser("~"),
                    ".cache",
                    "tendermint_tpu",
                    "jax_cache",
                    _host_tag(),
                )
            )
            _jax.config.update("jax_compilation_cache_dir", cache_dir)
            _jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1
            )
            _jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1
            )
        except Exception:
            pass  # cache is an optimization; never block node startup
        # export the fused device-SHA-512 knob before the first
        # default_verifier() constructs the process-wide verifier
        if config.base.device_challenge_min > 0:
            os.environ.setdefault(
                "TM_TPU_DEVICE_CHALLENGE_MIN",
                str(config.base.device_challenge_min),
            )
        # multi-host runtime: join the jax distributed service so
        # jax.devices() is global and the dcn mesh axis can span hosts
        # (the XLA-collective analog of the reference's cross-host NCCL/
        # MPI plane; SURVEY §2.3 / §5 distributed comm backend)
        if config.tpu.coordinator_address:
            try:
                import jax as _jax2

                _jax2.distributed.initialize(
                    coordinator_address=config.tpu.coordinator_address,
                    num_processes=config.tpu.num_processes,
                    process_id=config.tpu.process_id,
                )
            except Exception as e:
                # a single-host deployment with a stale coordinator line
                # must still boot — the mesh then covers local devices
                self.logger.error(
                    f"jax.distributed.initialize failed: {e}; "
                    "continuing single-process"
                )
        # [tpu] mesh axes -> env, so the process-wide default_verifier()
        # (constructed lazily by whichever reactor first verifies) builds
        # the sharded verifier per config (parallel/mesh.py).
        # [scheduler] mesh_enable is the one-knob version: shard the
        # verify plane over ALL local devices (ici=0); explicit [tpu]
        # axes win when both are set (setdefault ordering below).
        if config.tpu.ici_parallelism != 1 or config.tpu.dcn_parallelism != 1:
            os.environ.setdefault(
                "TM_TPU_ICI_PARALLELISM", str(config.tpu.ici_parallelism)
            )
            os.environ.setdefault(
                "TM_TPU_DCN_PARALLELISM", str(config.tpu.dcn_parallelism)
            )
            if config.tpu.mesh_backend:
                os.environ.setdefault(
                    "TM_TPU_MESH_BACKEND", config.tpu.mesh_backend
                )
        if config.scheduler.mesh_enable:
            os.environ.setdefault("TM_TPU_ICI_PARALLELISM", "0")
            if config.tpu.mesh_backend:
                os.environ.setdefault(
                    "TM_TPU_MESH_BACKEND", config.tpu.mesh_backend
                )
        # mesh_min_rows governs the sharded/replicated split of every
        # mesh verifier in the process (latency floor for tiny rounds)
        os.environ.setdefault(
            "TM_TPU_MESH_MIN_ROWS", str(config.scheduler.mesh_min_rows)
        )
        self.bls_key = bls.load_or_gen_bls_key(config.bls_key_file)
        self.bls_signer = bls.signer_for(
            bls.priv_key_from_bytes(self.bls_key.priv_key)
        )

        # --- genesis + state (node.go:797-805) ---
        self.genesis = genesis or GenesisDoc.from_file(config.genesis_file)

        def make_kv(name: str):
            if config.base.db_backend == "memory":
                return MemKV()
            return SqliteKV(os.path.join(config.db_dir, f"{name}.db"))

        # --- observability handles (node.go:1062; needed by the stores
        # below, so built before them) ---
        from ..libs.metrics import ConsensusMetrics, default_registry
        from .. import obs

        self.metrics_registry = default_registry()
        # flight recorder: installed as the process default so every seam
        # without an explicit handle (batch verifier, p2p conns, chaos)
        # lands in the SAME timeline as the consensus step spans
        self.tracer = obs.set_default_tracer(
            obs.Tracer(
                enabled=(
                    config.instrumentation.trace
                    or os.environ.get("TM_TPU_TRACE") == "1"
                ),
                ring_size=config.instrumentation.trace_ring_size,
            )
        )
        consensus_metrics = ConsensusMetrics(self.metrics_registry)
        # on-demand profiling hooks (obs/profiler.py): armed over the
        # profile_start/profile_stop RPC routes, artifacts land in
        # data/profiles — a live TPU session is minable without a
        # redeploy. Construction is free; nothing runs until armed.
        self.profiler = obs.ProfileCapture(
            config.path("data/profiles"), logger=self.logger
        )

        # --- live health plane (obs/health.py): streaming detectors
        # over the seams below; built BEFORE consensus so the arrival-
        # lag/commit push feeds wire straight in. Pull seams bind after
        # their owners exist; the sampling loop starts in on_start.
        self.health_monitor = None
        if config.health.enable:
            from ..libs.metrics import (
                HealthMetrics,
                ProcessMetrics,
                default_metrics,
            )

            # the static round-0 schedule is the stall ceiling's base:
            # adaptive pacing only ever tightens BELOW it
            static_round0 = (
                config.consensus.timeout_propose
                + config.consensus.timeout_prevote
                + config.consensus.timeout_precommit
                + config.consensus.timeout_commit
            )
            self.health_monitor = obs.HealthMonitor.from_config(
                config.health,
                stall_ceiling_s=config.health.stall_factor * static_round0,
                tracer=self.tracer,
                metrics=default_metrics(HealthMetrics),
                process_metrics=default_metrics(ProcessMetrics),
                logger=self.logger,
            )
            self.health_monitor.bind_wal(
                consensus_metrics.wal_fsync_seconds
            )
            # wall-clock conservation: the dark_time detector audits
            # the flight ring per committed height (no-op while the
            # tracer is disabled)
            self.health_monitor.bind_tracer(self.tracer)

        self.state_store = StateStore(make_kv("state"))
        if config.commit_pipeline.enable:
            # write-behind persistence: saves ride a worker thread
            from ..store.block_store import WriteBehindBlockStore

            self.block_store = WriteBehindBlockStore(
                make_kv("blockstore"),
                max_inflight=config.commit_pipeline.max_inflight,
                metrics=consensus_metrics,
                tracer=self.tracer,
            )
        else:
            self.block_store = BlockStore(make_kv("blockstore"))
        state = self.state_store.load()
        if state is None:
            state = State.from_genesis(self.genesis)
            self.state_store.bootstrap(state)

        # --- app + L2 (PROCESS BOUNDARY in production; in-proc here) ---
        if app is None:
            from ..abci.kvstore import KVStoreApplication

            app = KVStoreApplication()
        if l2_node is None:
            from ..l2node.mock import MockL2Node

            l2_node = MockL2Node()
        self.l2_node = l2_node
        if config.base.proxy_app:
            # external app process (reference node.go proxy.DefaultClient
            # Creator): socket or grpc per config.base.abci. ALL app
            # traffic rides the three named proxy connections — the
            # executor/handshake on `consensus`, rpc queries on `query`,
            # statesync serving on `snapshot` (reference
            # proxy/multi_app_conn.go:24-28).
            addr = config.base.proxy_app.removeprefix("tcp://")
            host, _, port_s = addr.rpartition(":")
            if not host or not port_s.isdigit():
                raise ValueError(
                    f"proxy_app must be [tcp://]host:port, got "
                    f"{config.base.proxy_app!r}"
                )
            if config.base.abci == "grpc":
                from ..abci.grpc_transport import grpc_client_creator

                creator = grpc_client_creator(host, int(port_s))
            else:
                from ..proxy.multi_app_conn import remote_client_creator

                creator = remote_client_creator(host, int(port_s))
            self.proxy_app = AppConns(creator)
            self.app = _ConnProxy(self.proxy_app, "query")
            self.app_client = _ConnProxy(self.proxy_app, "consensus")
            self._snapshot_app = _ConnProxy(self.proxy_app, "snapshot")
        else:
            from ..proxy.multi_app_conn import local_client_creator

            self.app = app
            self.app_client = LocalClient(app)
            self._snapshot_app = app
            self.proxy_app = AppConns(local_client_creator(app))

        # --- event bus + indexer (node.go:287-347) ---
        self.event_bus = EventBus()
        self.indexer_service = None
        if config.tx_index.indexer == "kv":
            try:
                from ..state.txindex import IndexerService, KVIndexer

                self.indexer = KVIndexer(make_kv("txindex"))
                self.indexer_service = IndexerService(
                    self.indexer, self.event_bus
                )
            except ImportError:
                self.indexer = None

        # --- evidence (node.go:403) ---
        self.evidence_pool = EvidencePool(
            make_kv("evidence"), self.state_store, self.block_store
        )

        # --- light-client serving plane (tendermint_tpu/lightserve) ---
        # cached light_block/signed_header/validator_set proof routes
        # over the node's own stores + the shared-round ServeVerifier;
        # rpc/core.py exposes the routes iff this exists
        self.lightserve = None
        if config.lightserve.enable:
            from ..lightserve import LightServePlane

            self.lightserve = LightServePlane(
                self.block_store,
                self.state_store,
                self.genesis.chain_id,
                cache_size=config.lightserve.cache_size,
                dedup_window_ns=int(config.lightserve.dedup_window * 1e9),
                logger=self.logger,
            )
            if self.health_monitor is not None:
                self.health_monitor.bind_lightserve(
                    self.lightserve.cache.metrics
                )

        # --- executor (node.go:883) ---
        self.block_executor = BlockExecutor(
            self.state_store,
            self.block_store,
            self.app_client,
            l2_node,
            event_bus=self.event_bus,
            evidence_pool=self.evidence_pool,
            logger=self.logger,
            qc_enabled=config.consensus.quorum_certificates,
        )

        # --- sequencer components (node.go:1007-1032) ---
        seq_signer = None
        if config.sequencer.sequencer_key_file:
            with open(config.path(config.sequencer.sequencer_key_file)) as f:
                key = secp256k1.PrivKey.from_bytes(
                    bytes.fromhex(f.read().strip())
                )
            seq_signer = LocalSigner(key)
        allowed = [
            bytes.fromhex(a.strip().removeprefix("0x"))
            for a in config.sequencer.sequencer_addresses.split(",")
            if a.strip()
        ]
        if seq_signer and not allowed:
            allowed = [seq_signer.address()]
        self.sequencer_verifier = StaticSequencerVerifier(allowed)
        self.state_v2 = StateV2(
            l2_node,
            block_interval=config.sequencer.block_interval,
            signer=seq_signer,
            verifier=self.sequencer_verifier,
            logger=self.logger,
        )
        self.sequencer_reactor = BlockBroadcastReactor(
            self.state_v2, self.sequencer_verifier, wait_sync=True,
            logger=self.logger,
            apply_interval=config.sequencer.apply_interval,
            sync_interval=config.sequencer.sync_interval,
            catchup_window=config.sequencer.catchup_window,
            tracer=self.tracer,
        )
        if self.health_monitor is not None:
            self.health_monitor.bind_sequencer(
                self.sequencer_reactor.metrics.apply_latency
            )

        # --- consensus (node.go:460-501) ---
        # unified verification dispatch scheduler: every subsystem's
        # device-verify path funnels through parallel/scheduler's
        # default_dispatch(), so installing one here captures the vote
        # batcher, blocksync replay, light bisection and evidence checks.
        # The bucket-ladder override must land BEFORE the first
        # default_verifier() dispatch (the registry owns pad sizes).
        self.verify_scheduler = None
        # the ladder governs pad buckets for EVERY verifier through the
        # process shape registry, scheduler routing or not — apply it
        # outside the enable gate
        ladder = config.scheduler.ladder()
        if ladder is not None:
            from ..crypto.shape_registry import configure_default

            configure_default(ladder)
        if config.scheduler.enable and config.scheduler.remote_socket:
            # split-brain deployment ([scheduler] remote_socket): a
            # standalone verify-service process owns the device plane;
            # this node is a CLIENT whose submissions coalesce with the
            # rest of the rack's (parallel/verify_service.py). The
            # device-side fill/saturation seams live on the SERVICE
            # (its own /metrics + dump_dispatch_ledger); this node's
            # health plane watches the IPC round trip + degrades
            # instead.
            from ..parallel.scheduler import set_default_scheduler
            from ..parallel.verify_service import RemoteVerifyScheduler

            self.verify_scheduler = set_default_scheduler(
                RemoteVerifyScheduler(
                    config.path(config.scheduler.remote_socket),
                    logger=self.logger,
                    tracer=self.tracer,
                    # wire trace context names this node as the
                    # submitter in the service's sub-spans
                    origin=self.node_key.id[:16],
                )
            )
            self.logger.info(
                "verify plane: remote service client",
                socket=config.path(config.scheduler.remote_socket),
            )
            if self.health_monitor is not None:
                self.health_monitor.bind_remote_scheduler(
                    self.verify_scheduler
                )
        elif config.scheduler.enable:
            from ..parallel.scheduler import (
                VerifyScheduler,
                set_default_scheduler,
            )

            self.verify_scheduler = set_default_scheduler(
                VerifyScheduler(
                    max_batch=config.scheduler.max_batch,
                    logger=self.logger,
                    dispatch_log_size=config.scheduler.dispatch_log_size,
                )
            )
            if self.health_monitor is not None:
                self.health_monitor.bind_scheduler(
                    self.verify_scheduler.metrics
                )
                # fill-efficiency floor reads the device-cost ledger
                self.health_monitor.bind_ledger(
                    self.verify_scheduler.ledger
                )
        # commit pipeline (consensus/commit_pipeline.py): group-commit
        # WAL + write-behind block store + background apply. All three
        # are wired together — replay semantics are designed for the
        # trio, and half a pipeline buys latency without the overlap.
        self.commit_pipeline = None
        if config.commit_pipeline.enable:
            from ..consensus.commit_pipeline import CommitPipeline
            from ..consensus.wal import GroupCommitWAL

            wal = GroupCommitWAL(
                config.wal_file,
                metrics=consensus_metrics,
                tracer=self.tracer,
                flush_interval=config.commit_pipeline.flush_interval,
            )
            self.commit_pipeline = CommitPipeline(
                metrics=consensus_metrics,
                tracer=self.tracer,
                logger=self.logger,
            )
        else:
            wal = WAL(
                config.wal_file, metrics=consensus_metrics,
                tracer=self.tracer,
            )
        self.wal = wal
        # adaptive pacing (consensus/pacing.py): the node owns the
        # controller so the debug/RPC surface can snapshot it; the
        # state machine would self-construct an identical one from the
        # config, but explicit wiring keeps ownership visible alongside
        # the commit pipeline and scheduler
        sm_config = config.consensus.to_state_machine_config()
        self.pacing = None
        if config.consensus.adaptive_timeouts:
            from ..consensus.pacing import PacingController

            self.pacing = PacingController.from_config(
                sm_config, metrics=consensus_metrics, tracer=self.tracer
            )
            # learned tails live next to the WAL: same durability
            # domain, wiped by the same data reset
            self.pacing.persist_path = config.wal_file + ".pacing.json"
            self.logger.info(
                "adaptive consensus pacing enabled",
                tail_q=config.consensus.adaptive_tail_quantile,
                min_factor=config.consensus.adaptive_min_factor,
            )
        self.consensus = ConsensusState(
            sm_config,
            state,
            self.block_executor,
            self.block_store,
            l2_node,
            priv_validator=self.priv_validator,
            bls_signer=self.bls_signer,
            event_bus=self.event_bus,
            wal=wal,
            upgrade_height=config.consensus.switch_height,
            on_upgrade=self._switch_to_sequencer_mode,
            evidence_pool=self.evidence_pool,
            metrics=consensus_metrics,
            tracer=self.tracer,
            logger=self.logger,
            commit_pipeline=self.commit_pipeline,
            pacing=self.pacing,
            health=self.health_monitor,
        )
        self.consensus_reactor = ConsensusReactor(
            self.consensus,
            logger=self.logger,
            vote_batch=config.consensus.vote_batch_gossip,
            vote_batch_max=config.consensus.vote_batch_max,
            digest_interval=config.consensus.digest_interval,
            vote_forward_fanout=config.consensus.vote_forward_fanout,
        )

        # --- blocksync (node.go:435-458) ---
        self.blocksync_reactor = BlocksyncReactor(
            state,
            self.block_executor,
            self.block_store,
            l2_node,
            on_caught_up=self._switch_to_consensus,
            upgrade_height=config.consensus.switch_height,
            on_upgrade=self._switch_to_sequencer_mode,
            logger=self.logger,
            active=False,  # started explicitly when peers are configured
            qc_enabled=config.consensus.quorum_certificates,
        )

        # --- statesync reactor (node.go:916) ---
        self.statesync_reactor = StateSyncReactor(
            self._snapshot_app, syncer=None, logger=self.logger
        )

        # --- p2p (node.go:929-967) ---
        transport = None
        sw = None

        def node_info() -> NodeInfo:
            return NodeInfo(
                node_id=self.node_key.id,
                listen_addr=self._listen_addr(),
                network=self.genesis.chain_id,
                channels=sw.channels() if sw else b"",
                moniker=config.base.moniker,
            )

        transport = MultiplexTransport(self.node_key, node_info)
        sw = Switch(
            transport,
            logger=self.logger,
            send_rate=config.p2p.send_rate,
            recv_rate=config.p2p.recv_rate,
            ping_interval=config.p2p.ping_interval,
        )
        self.transport = transport
        self.switch = sw
        sw.add_reactor("consensus", self.consensus_reactor)
        sw.add_reactor("blocksync", self.blocksync_reactor)
        sw.add_reactor("evidence", EvidenceReactor(self.evidence_pool, self.logger))
        sw.add_reactor("statesync", self.statesync_reactor)
        sw.add_reactor("sequencer", self.sequencer_reactor)
        if config.p2p.pex:
            self.addr_book = AddrBook(
                config.addr_book_file, our_id=self.node_key.id
            )
            sw.add_reactor("pex", PEXReactor(self.addr_book))
        if self.health_monitor is not None:
            self.health_monitor.bind_switch(sw)

        # --- rpc + metrics ---
        self.rpc_server = None
        self.metrics_server = None
        self.debug_server = None

    # --- helpers ------------------------------------------------------------

    def _listen_addr(self) -> str:
        host, port = self._parse_laddr(self.config.p2p.laddr)
        lp = getattr(self.transport, "listen_port", None) or port
        return f"{host}:{lp}"

    @staticmethod
    def _parse_laddr(laddr: str) -> tuple[str, int]:
        s = laddr.removeprefix("tcp://")
        host, _, port = s.rpartition(":")
        return host or "127.0.0.1", int(port or 0)

    # --- mode switches (node.go:1612-1632) -----------------------------------

    async def _switch_to_sequencer_mode(self, state) -> None:
        self.logger.info(
            "switching to sequencer mode", height=state.last_block_height
        )
        if hasattr(self.l2_node, "seed_v2_height"):
            # the mock L2 needs its v2 chain aligned to the BFT height;
            # a real geth already is
            self.l2_node.seed_v2_height(state.last_block_height)
        await self.sequencer_reactor.start_sequencer_routines()

    async def _switch_to_consensus(self, state) -> None:
        self.logger.info(
            "blocksync caught up; starting consensus",
            height=state.last_block_height,
        )
        self.consensus.state = state
        try:
            # skip WAL catchup ONLY when blocksync actually advanced state
            # past the WAL's last end-height barrier (reference
            # SwitchToConsensus(state, blocksSynced > 0)); a restart that
            # synced nothing must still replay in-flight WAL messages —
            # that replay restores the POL lock that prevents double-signs
            synced = self.blocksync_reactor.blocks_applied > 0
            await self.consensus.start(skip_wal_catchup=synced)
        except Exception as e:
            # the switch-over runs inside blocksync's pool task — an
            # exception here must not die silently (that failure mode
            # presented as a live-looking node that never participates)
            self.logger.error(
                "consensus start failed after blocksync", err=repr(e)
            )
            raise

    # --- lifecycle (node.go:1041-1112) ---------------------------------------

    async def on_start(self) -> None:
        # (re)arm table warms for this process lifetime (the default
        # verifier — and its shutdown flag — is shared process-wide)
        ev = getattr(self.consensus.verifier, "shutdown_event", None)
        if ev is not None:
            ev.clear()
        # verification dispatch service first: the moment any reactor
        # verifies, its classed dispatch should coalesce (until started,
        # default_dispatch degrades to direct dispatch — still correct)
        if self.verify_scheduler is not None:
            await self.verify_scheduler.start()
        if self.health_monitor is not None:
            await self.health_monitor.start()
        await self.proxy_app.start()
        if self.indexer_service is not None:
            await self.indexer_service.start()
        # handshake/replay: sync app + L2 with the block store
        from ..consensus.replay import Handshaker

        hs = Handshaker(
            self.state_store,
            self.block_store,
            self.genesis,
            self.block_executor,
            logger=self.logger,
        )
        state = await hs.handshake(self.consensus.state)
        self.consensus.state = state
        self.blocksync_reactor.state = state

        # rpc
        if self.config.rpc.laddr:
            from ..rpc.server import RPCServer

            host, port = self._parse_laddr(self.config.rpc.laddr)
            self.rpc_server = RPCServer(self, host, port)
            await self.rpc_server.start()
        # pprof/debug (reference node.go:969-975)
        if self.config.rpc.pprof_laddr:
            from .debug import DebugServer

            host, port = self._parse_laddr(self.config.rpc.pprof_laddr)
            self.debug_server = DebugServer(
                host or "127.0.0.1",
                port,
                trace_dir=os.path.join(self.config.root_dir, "traces"),
            )
            await self.debug_server.start()
        # metrics
        if self.config.instrumentation.prometheus:
            from ..libs.metrics import MetricsServer

            host, port = self._parse_laddr(
                self.config.instrumentation.prometheus_listen_addr
            )
            self.metrics_server = MetricsServer(
                self.metrics_registry, host or "0.0.0.0", port
            )
            await self.metrics_server.start()

        # pre-build the validator table cache off the critical path (the
        # steady-state vote path then never pays decompression/table cost)
        vals = self.consensus.state.validators
        if (
            vals is not None
            and hasattr(self.consensus.verifier, "warm")
            and not os.environ.get("TM_TPU_SKIP_WARM")
        ):
            pubs = [v.pub_key.data for v in vals.validators]
            ktypes = [
                getattr(v.pub_key, "type_name", "ed25519")
                for v in vals.validators
            ]
            # NON-daemon thread with an abort flag: a daemon thread
            # force-terminated mid-XLA-compile at interpreter exit
            # crashes the process (SIGSEGV/SIGABRT — found r4 driving a
            # short-lived node). on_stop sets the flag and joins; the
            # interpreter then waits out at most one chunk compile. The
            # verifier-level shutdown_event also covers the bulk warms
            # blocksync/light launch via the executor.
            import threading as _threading

            self._warm_abort = self.consensus.verifier.shutdown_event

            def _warm_startup(
                verifier=self.consensus.verifier,
                abort=self._warm_abort,
            ):
                verifier.warm(pubs, key_types=ktypes, abort=abort)
                # ahead-of-time bucket-ladder prewarm (the §10 fix for
                # per-shape program loads landing mid-height): compile/
                # load every verify program the ladder dispatches, then
                # persist the manifest so operators can see what a
                # restart pays (tools/prewarm.py builds/verifies the
                # same artifact standalone)
                if not self.config.scheduler.prewarm or abort.is_set():
                    return
                try:
                    entries = verifier.prewarm_buckets(abort=abort)
                    from ..crypto.shape_registry import (
                        default_shape_registry,
                    )
                    import json as _json
                    import time as _time

                    manifest = {
                        "created_unix": int(_time.time()),
                        "ladder": list(default_shape_registry().ladder),
                        # the mesh topology the ladder was loaded for:
                        # tools/prewarm.py --verify fails loudly when a
                        # restarted node's live mesh disagrees (a wrong
                        # topology would recompile on the hot path)
                        "device_count": getattr(
                            verifier, "mesh_devices", 1
                        ),
                        "mesh_min_rows": getattr(
                            verifier, "_mesh_min_rows", 0
                        ),
                        "mesh_backend": os.environ.get(
                            "TM_TPU_MESH_BACKEND", ""
                        ),
                        "entries": entries,
                    }
                    path = self.config.path(
                        self.config.scheduler.prewarm_manifest
                    )
                    os.makedirs(os.path.dirname(path), exist_ok=True)
                    with open(path, "w") as f:
                        _json.dump(manifest, f, indent=1)
                    self.logger.info(
                        "verify-program prewarm complete",
                        programs=len(entries),
                        seconds=round(
                            sum(e["seconds"] for e in entries), 1
                        ),
                        manifest=path,
                    )
                except Exception as e:  # prewarm is an optimization
                    self.logger.error("bucket prewarm failed", err=repr(e))

            self._warm_thread = _threading.Thread(
                target=_warm_startup,
                name="verifier-warm",
            )
            self._warm_thread.start()

        try:
            # p2p
            host, port = self._parse_laddr(self.config.p2p.laddr)
            await self.transport.listen(host, port)
            if self.config.p2p.upnp:
                # best-effort NAT mapping of the real listen port
                # (reference node.go getUPNPExternalAddress); failure
                # leaves the node listening unmapped
                from ..p2p import upnp as _upnp

                self._upnp_gateway = await _upnp.map_listen_port(
                    self.transport.listen_port, logger=self.logger
                )
            await self.switch.start()
            peers = [
                NetAddress.parse(p)
                for p in self.config.p2p.peer_list(
                    self.config.p2p.persistent_peers
                )
            ]
            if peers:
                self.switch.dial_peers_async(peers, persistent=True)

            # consensus (blocksync/statesync first when configured)
            if self.config.statesync.enable:
                self.spawn(self._run_statesync())
            elif peers and self.config.blocksync.enable:
                self.blocksync_reactor.start_sync()
            else:
                await self.consensus.start()
        except BaseException:
            # failed startup (busy p2p port, bad peer string, ...):
            # Service.start will not call on_stop, and the non-daemon
            # warm thread would otherwise hold the interpreter open for
            # the whole multi-chunk build at exit
            ev = getattr(self.consensus.verifier, "shutdown_event", None)
            if ev is not None:
                ev.set()
            if self.verify_scheduler is not None:
                await self.verify_scheduler.stop()
            if self.health_monitor is not None:
                await self.health_monitor.stop()
            raise

    async def _run_statesync(self) -> None:
        """Bootstrap from a snapshot, then hand off to consensus
        (node.go:1088-1106 startStateSync)."""
        from ..statesync.syncer import Syncer
        from ..statesync.stateprovider import LightClientStateProvider
        from ..light.client import LightClient, TrustOptions
        from ..light.store import LightStore
        from ..rpc.light_provider import RPCProvider

        servers = [
            s.strip()
            for s in self.config.statesync.rpc_servers.split(",")
            if s.strip()
        ]
        providers = [RPCProvider(self.genesis.chain_id, s) for s in servers]
        lc = LightClient(
            self.genesis.chain_id,
            TrustOptions(
                int(self.config.statesync.trust_period * 1e9),
                self.config.statesync.trust_height,
                bytes.fromhex(self.config.statesync.trust_hash),
            ),
            providers[0],
            providers[1:],
            LightStore(MemKV()),
            logger=self.logger,
        )
        provider = LightClientStateProvider(
            lc, consensus_params=self.consensus.state.consensus_params
        )
        syncer = Syncer(
            self._snapshot_app,
            provider,
            self.statesync_reactor.request_chunk,
            logger=self.logger,
        )
        self.statesync_reactor.syncer = syncer
        state, commit = await syncer.sync_any(
            discovery_time=self.config.statesync.discovery_time
        )
        self.statesync_reactor.syncer = None
        self.state_store.bootstrap(state)
        self.block_store.save_seen_commit(state.last_block_height, commit)
        self.consensus.state = state
        # statesync jumped state far past any WAL content (same skipWAL
        # rationale as the blocksync switch-over)
        await self.consensus.start(skip_wal_catchup=True)

    async def on_stop(self) -> None:
        # stop ALL in-flight table warms (the startup thread AND the
        # bulk warms blocksync/light run in the executor) — see
        # BatchVerifier.shutdown_event
        ev = getattr(self.consensus.verifier, "shutdown_event", None)
        if ev is not None:
            ev.set()
        t = getattr(self, "_warm_thread", None)
        if t is not None and t.is_alive():
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, t.join, 120.0)
        if self.consensus.is_running:
            await self.consensus.stop()
        if self.sequencer_reactor.sequencer_started:
            await self.sequencer_reactor.on_stop()
        await self.switch.stop()
        # pipeline teardown AFTER the reactors: a still-active blocksync
        # may save/apply right up to switch.stop — only once nothing can
        # write do we drain the write-behind save queue and stop the WAL
        # flush thread
        self.block_store.stop()
        # unconditional: the plain WAL's close is flush+fd-close; the
        # group WAL's additionally drains and joins its flush thread
        self.wal.close()
        # after the reactors: queued verify work drains (futures resolve),
        # then later submissions degrade to direct dispatch
        if self.verify_scheduler is not None:
            await self.verify_scheduler.stop()
        if self.health_monitor is not None:
            await self.health_monitor.stop()
        # an armed profile session must not outlive the node: stop it so
        # the loop-profile artifact lands and the sampler thread exits
        if getattr(self, "profiler", None) is not None and self.profiler.active:
            try:
                self.profiler.stop()
            except Exception as e:
                self.logger.error("profile stop at shutdown failed",
                                  err=repr(e))
        if self.rpc_server is not None:
            await self.rpc_server.stop()
        if self.metrics_server is not None:
            await self.metrics_server.stop()
        if self.debug_server is not None:
            await self.debug_server.stop()
        if self.indexer_service is not None:
            await self.indexer_service.stop()
        if getattr(self, "_upnp_gateway", None) is not None:
            from ..p2p import upnp as _upnp

            await _upnp.unmap_listen_port(
                self._upnp_gateway, self.transport.listen_port,
                logger=self.logger,
            )
        await self.proxy_app.stop()
