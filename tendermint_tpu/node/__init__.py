"""Node assembly (reference node/node.go)."""

from .node import Node, init_files

__all__ = ["Node", "init_files"]
