"""ChaosNetwork — partitions, blackholes, and link policies over a
running p2p mesh.

Operates on live `p2p/switch.py` Switches through two seams added for
chaos (and usable by any test harness):

- `MultiplexTransport.conn_wrapper`: wraps every upgraded connection, so
  the link model shapes all reactor traffic without touching reactors.
- `Switch.conn_gate`: a predicate consulted before a peer is added; the
  controller installs one that enforces the current partition/blackhole
  view, covering both inbound accepts and outbound dials (including the
  persistent redial loop).

Partitions are NAMED so a scenario can apply/heal them declaratively:
`partition("split", [["n0","n1"],["n2","n3"]])` severs existing
cross-group connections and blocks new ones; `heal("split")` removes the
rule and kicks the persistent redial machinery so the mesh reconverges.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..libs.log import Logger, nop_logger
from ..obs import default_tracer
from .link import ChaosConn, FaultTrace, LinkPolicy, link_rng


class ChaosNetwork:
    def __init__(self, seed: int = 0, logger: Optional[Logger] = None):
        self.seed = seed
        self.logger = logger or nop_logger()
        self.trace = FaultTrace()
        self._nodes: dict[str, object] = {}  # name -> NodeHandle
        self._default_policy = LinkPolicy()
        # (src_name, dst_name) -> LinkPolicy, directional
        self._link_policies: dict[tuple[str, str], LinkPolicy] = {}
        self._partitions: dict[str, list[set[str]]] = {}  # name -> groups
        self._blackholes: set[str] = set()

    # --- installation -----------------------------------------------------

    def install(self, handle) -> None:
        """Attach chaos to one node BEFORE its transport starts accepting.
        `handle` is a chaos.scenario.NodeHandle."""
        self._nodes[handle.name] = handle
        handle.transport.conn_wrapper = self._make_wrapper(handle)
        handle.switch.conn_gate = self._make_gate(handle)
        # deterministic dial jitter: every retry schedule replays per seed
        handle.switch.dial_rng = link_rng(self.seed, "dial", handle.name)

    def _make_wrapper(self, handle):
        def wrap(peer_id: str, conn):
            src = handle.name
            dst = self._name_for(peer_id) or peer_id[:12]
            # always wrap (even when the current policy is a noop): the
            # policy is re-resolved per message, so a mid-scenario
            # set_link/set_default_policy reshapes LIVE connections
            return ChaosConn(
                conn,
                self._policy_for(src, dst),
                link_rng(self.seed, src, dst),
                link_id=f"{src}>{dst}",
                trace=self.trace,
                policy_fn=lambda: self._policy_for(src, dst),
            )

        return wrap

    def _make_gate(self, handle):
        def gate(peer_id: str) -> bool:
            other = self._name_for(peer_id)
            if other is None:
                return True  # not a chaos-managed node
            return self.allowed(handle.name, other)

        return gate

    def _name_for(self, node_id: str) -> Optional[str]:
        for name, h in self._nodes.items():
            if h.node_key.id == node_id:
                return name
        return None

    def _policy_for(self, src: str, dst: str) -> LinkPolicy:
        return self._link_policies.get((src, dst), self._default_policy)

    # --- link policies ----------------------------------------------------

    def set_default_policy(self, policy: LinkPolicy) -> None:
        """Policy for every link without an explicit override. Takes
        effect immediately, including on live connections (each wrapped
        conn re-resolves its policy per message)."""
        self._default_policy = policy

    def set_link_policy(
        self,
        a: str,
        b: str,
        policy: LinkPolicy,
        reverse: Optional[LinkPolicy] = None,
    ) -> None:
        """Shape a->b with `policy`; b->a gets `reverse` (or the same
        policy — pass LinkPolicy() for a clean return path)."""
        self._link_policies[(a, b)] = policy
        self._link_policies[(b, a)] = reverse if reverse is not None else policy

    # --- partitions / blackholes -----------------------------------------

    def allowed(self, a: str, b: str) -> bool:
        if a in self._blackholes or b in self._blackholes:
            return False
        for groups in self._partitions.values():
            ga = gb = None
            for i, g in enumerate(groups):
                if a in g:
                    ga = i
                if b in g:
                    gb = i
            if ga is not None and gb is not None and ga != gb:
                return False
        return True

    async def partition(self, name: str, groups: list[list[str]]) -> None:
        """Apply a named partition: nodes in different groups cannot
        communicate until `heal(name)`."""
        self._partitions[name] = [set(g) for g in groups]
        self.trace.add("net", "partition", name, sorted(map(sorted, groups)))
        # fault injections land in the same timeline as the step spans:
        # the flight recorder bins this into the height in progress
        default_tracer().event(
            "chaos.partition",
            name=name,
            groups="|".join(",".join(sorted(g)) for g in groups),
        )
        await self._enforce()

    async def blackhole(self, node: str) -> None:
        """Isolate one node from everyone (per-peer blackhole)."""
        self._blackholes.add(node)
        self.trace.add("net", "blackhole", node)
        default_tracer().event("chaos.blackhole", node=node)
        await self._enforce()

    async def heal(self, name: Optional[str] = None) -> None:
        """Remove one named partition (or all partitions and blackholes)
        and kick redials so the mesh reconverges."""
        if name is None:
            self._partitions.clear()
            self._blackholes.clear()
        else:
            self._partitions.pop(name, None)
            self._blackholes.discard(name)
        self.trace.add("net", "heal", name or "*")
        default_tracer().event("chaos.heal", name=name or "*")
        for h in self._nodes.values():
            if h.switch.is_running:
                h.switch.redial_persistent()

    async def _enforce(self) -> None:
        """Drop live connections that the current view forbids."""
        for name, h in self._nodes.items():
            if not h.switch.is_running:
                continue
            for peer in list(h.switch.peers.values()):
                other = self._name_for(peer.id)
                if other is not None and not self.allowed(name, other):
                    await h.switch.stop_peer_gracefully(peer)
        # let in-flight recv callbacks observe the closed conns
        await asyncio.sleep(0)
