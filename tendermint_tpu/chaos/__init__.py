"""Deterministic chaos subsystem — seeded fault injection for the p2p
mesh plus graceful degradation for the accelerator backend.

The reference engine's e2e runner perturbs networks ad-hoc (kill,
disconnect, byte fuzzing); this package turns those hacks into an owned,
replayable subsystem:

- `link`    — seeded per-link network shaping (latency+jitter, drop,
              duplicate, reorder, bandwidth) interposed at the transport
              connection layer, so every reactor runs through it
              unmodified.
- `network` — a controller over running switches: named partitions,
              per-peer blackholes, link policy installation, heal.
- `scenario`— declarative seeded timelines (at height/time X: partition,
              kill, restart, skew clocks, heal) executed against in-proc
              multi-node networks; one seed replays the whole fault plan.
- `backend_guard` — bounded-time accelerator backend probes so perf
              capture degrades to a structured JSON artifact + CPU
              fallback instead of hanging when the TPU tunnel dies.

Env knobs: TM_TPU_CHAOS_SEED (default scenario seed),
TM_TPU_BACKEND_GUARD_TIMEOUT (probe bound, seconds).
"""

from .link import ChaosConn, FaultTrace, LinkPolicy, link_rng
from .network import ChaosNetwork
from .scenario import NodeHandle, Scenario, ScenarioRunner, Step, random_scenario
from .backend_guard import BackendStatus, fallback_artifact, probe_backend

__all__ = [
    "BackendStatus",
    "ChaosConn",
    "ChaosNetwork",
    "FaultTrace",
    "LinkPolicy",
    "NodeHandle",
    "Scenario",
    "ScenarioRunner",
    "Step",
    "fallback_artifact",
    "link_rng",
    "probe_backend",
    "random_scenario",
]
