"""Declarative seeded chaos scenarios over in-proc multi-node networks.

A `Scenario` is a seed plus a timeline of `Step`s, each fired once when
its trigger (committed height or elapsed seconds) is reached:

    Scenario(seed=7, steps=[
        Step(at_height=2, action="partition",
             params={"name": "split", "groups": [["n0","n1"],["n2","n3"]]}),
        Step(at_time=6.0, action="heal", params={"name": "split"}),
        Step(at_height=5, action="clock_skew",
             params={"node": "n1", "scale": 1.5}),
    ])

`ScenarioRunner` executes the timeline against `NodeHandle`s (the in-proc
consensus + p2p bundles the test harness builds), with ALL randomness —
link shaping, dial jitter, randomized step parameters — derived from the
single scenario seed, so a failing CI run is replayed locally by seed
alone (README §chaos). The resolved timeline is logged to the shared
`FaultTrace` before execution starts; two runs with one seed produce a
byte-identical plan trace.

Env knobs: TM_TPU_CHAOS_SEED overrides the default seed used by
`default_seed()` (soak + CI entry points).
"""

from __future__ import annotations

import asyncio
import os
import random
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional

from ..libs.log import Logger, nop_logger
from ..obs import default_tracer
from .link import LinkPolicy
from .network import ChaosNetwork


def default_seed() -> int:
    return int(os.environ.get("TM_TPU_CHAOS_SEED", "0"))


@dataclass
class NodeHandle:
    """One in-proc node as the scenario runner sees it."""

    name: str
    cs: object  # ConsensusState
    node_key: object  # p2p NodeKey
    transport: object  # MultiplexTransport
    switch: object  # Switch
    block_store: object = None
    alive: bool = True
    # rebuilds transport/switch/reactor for this handle after a kill and
    # reconnects it (harness-specific); awaited by the "restart" action
    restart_fn: Optional[Callable[["NodeHandle", ChaosNetwork], Awaitable[None]]] = None

    def height(self) -> int:
        bs = self.block_store
        if bs is None:
            bs = getattr(self.cs, "block_store", None)
        return bs.height if bs is not None else 0


@dataclass
class Step:
    action: str  # partition|heal|blackhole|kill|restart|set_link|clock_skew
    at_height: Optional[int] = None  # fire when any live node commits this
    at_time: Optional[float] = None  # or when this many seconds elapsed
    after: Optional[int] = None  # and only once step[after] has fired
    # with NO at_height/at_time the step is due immediately (gated only
    # by `after`, if set)
    params: dict = field(default_factory=dict)

    def resolved(self, idx: int) -> tuple:
        return (
            "plan",
            idx,
            self.action,
            self.at_height,
            self.at_time,
            self.after,
            sorted(self.params.items(), key=lambda kv: kv[0]),
        )


@dataclass
class Scenario:
    seed: int
    steps: list[Step] = field(default_factory=list)
    default_policy: Optional[LinkPolicy] = None


class ScenarioRunner:
    def __init__(
        self,
        nodes: list[NodeHandle],
        scenario: Scenario,
        logger: Optional[Logger] = None,
    ):
        self.nodes = {h.name: h for h in nodes}
        self.scenario = scenario
        self.logger = logger or nop_logger()
        self.net = ChaosNetwork(seed=scenario.seed, logger=self.logger)
        if scenario.default_policy is not None:
            self.net.set_default_policy(scenario.default_policy)
        for h in nodes:
            self.net.install(h)
        self._fired: set[int] = set()

    @property
    def trace(self):
        return self.net.trace

    def plan_jsonl(self) -> bytes:
        """The resolved scenario plan as canonical JSONL — the seeded,
        replayable part of the fault trace. Byte-identical across runs
        with the same seed (per-link message decisions additionally
        depend on live traffic volume and live in the full trace)."""
        import json

        return b"\n".join(
            json.dumps(list(e), separators=(",", ":")).encode()
            for e in self.trace.entries
            if e[0] in ("scenario", "plan")
        )

    def live_nodes(self) -> list[NodeHandle]:
        return [h for h in self.nodes.values() if h.alive]

    def max_height(self) -> int:
        return max((h.height() for h in self.live_nodes()), default=0)

    def height_trace(self) -> dict[str, list[int]]:
        """Per-node committed-height sequence (1..h). The determinism
        suite compares these across same-seed runs."""
        return {
            name: list(range(1, h.height() + 1))
            for name, h in sorted(self.nodes.items())
        }

    async def run(
        self, until_height: int, timeout: float = 120.0
    ) -> dict[str, list[int]]:
        """Execute the timeline until every LIVE node commits
        `until_height` (and all steps have fired), then return the
        committed-height trace. Raises TimeoutError on stall."""
        # log the fully resolved plan first: this is the replayable part
        # of the fault trace — byte-identical for a given seed
        self.trace.add("scenario", "seed", self.scenario.seed)
        for i, step in enumerate(self.scenario.steps):
            self.trace.add(*step.resolved(i))

        start = time.monotonic()
        while True:
            elapsed = time.monotonic() - start
            if elapsed > timeout:
                raise TimeoutError(
                    f"scenario stalled at height {self.max_height()} "
                    f"({len(self._fired)}/{len(self.scenario.steps)} steps "
                    f"fired, seed={self.scenario.seed})"
                )
            h = self.max_height()
            for i, step in enumerate(self.scenario.steps):
                if i in self._fired:
                    continue
                due = (
                    (step.at_height is not None and h >= step.at_height)
                    or (step.at_time is not None and elapsed >= step.at_time)
                    # trigger-less steps are due immediately (typically
                    # gated only by `after`)
                    or (step.at_height is None and step.at_time is None)
                )
                if step.after is not None and step.after not in self._fired:
                    due = False  # dependency hasn't fired yet
                if due:
                    self._fired.add(i)
                    self.trace.add("fire", i, step.action)
                    default_tracer().event(
                        f"chaos.fire.{step.action}", height=h, step=i
                    )
                    await self._execute(step)
            if len(self._fired) == len(self.scenario.steps):
                live = self.live_nodes()
                if live and all(n.height() >= until_height for n in live):
                    return self.height_trace()
            await asyncio.sleep(0.05)

    async def _execute(self, step: Step) -> None:
        p = step.params
        if step.action == "partition":
            await self.net.partition(p.get("name", "p"), p["groups"])
        elif step.action == "heal":
            await self.net.heal(p.get("name"))
        elif step.action == "blackhole":
            await self.net.blackhole(p["node"])
        elif step.action == "kill":
            h = self.nodes[p["node"]]
            h.alive = False
            await h.cs.stop()
            await h.switch.stop()
        elif step.action == "restart":
            h = self.nodes[p["node"]]
            if h.restart_fn is None:
                raise ValueError(f"node {h.name} has no restart_fn")
            await h.restart_fn(h, self.net)
            h.alive = True
        elif step.action == "set_link":
            policy = LinkPolicy(**p.get("policy", {}))
            if "a" in p:
                rev = p.get("reverse")
                self.net.set_link_policy(
                    p["a"],
                    p["b"],
                    policy,
                    LinkPolicy(**rev) if rev is not None else None,
                )
            else:
                self.net.set_default_policy(policy)
        elif step.action == "clock_skew":
            self.nodes[p["node"]].cs.ticker.set_scale(p["scale"])
        else:
            raise ValueError(f"unknown chaos action {step.action!r}")


def random_scenario(
    seed: int, node_names: list[str], max_heal_time: float = 8.0
) -> Scenario:
    """A bounded randomized scenario drawn entirely from `seed` — the
    soak loop's generator. Mixes a mild latency/drop storm with either a
    2|2-style partition/heal or a node blackhole/heal, so every iteration
    exercises divergence + reconvergence."""
    rng = random.Random(seed)
    storm = LinkPolicy(
        latency_s=rng.uniform(0.0, 0.02),
        jitter_s=rng.uniform(0.0, 0.03),
        drop=rng.uniform(0.0, 0.05),
        duplicate=rng.uniform(0.0, 0.05),
    )
    steps: list[Step] = []
    names = list(node_names)
    rng.shuffle(names)
    heal_at = rng.uniform(3.0, max_heal_time)
    if rng.random() < 0.5 and len(names) >= 4:
        half = len(names) // 2
        steps.append(
            Step(
                action="partition",
                at_height=rng.randint(1, 3),
                params={
                    "name": "soak-split",
                    "groups": [names[:half], names[half:]],
                },
            )
        )
        steps.append(
            Step(action="heal", at_time=heal_at, params={"name": "soak-split"})
        )
    else:
        steps.append(
            Step(
                action="blackhole",
                at_height=rng.randint(1, 3),
                params={"node": names[0]},
            )
        )
        steps.append(Step(action="heal", at_time=heal_at))
    if rng.random() < 0.3:
        steps.append(
            Step(
                action="clock_skew",
                at_height=rng.randint(2, 4),
                params={"node": names[-1], "scale": rng.uniform(0.8, 1.5)},
            )
        )
    return Scenario(seed=seed, steps=steps, default_policy=storm)
