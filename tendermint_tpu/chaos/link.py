"""Seeded link model — network shaping at the connection layer.

`ChaosConn` wraps the SecretConnection a `MultiplexTransport` hands to the
MConnection, so every reactor (consensus gossip, blocksync, statesync,
evidence, sequencer broadcast) is shaped without modification. Shaping
happens OUTSIDE the AEAD: writes are dropped/delayed before encryption and
reads after decryption, so the nonce counters never desync — unlike
`p2p/fuzz.py`, a dropped message here does not kill the connection.

Faults are applied at MESSAGE granularity: MConnection chops a message
into packets tagged (channel, eof); ChaosConn buffers a channel's packets
until eof and then makes ONE seeded decision for the whole message. This
keeps the per-channel reassembly buffers coherent (dropping or reordering
a mid-message packet would corrupt every later message on that channel).

Determinism: each link direction owns a `random.Random` derived from
(seed, src_id, dst_id), so the decision stream for a link depends only on
the seed and the number of messages sent over it — replaying the same
message sequence yields a byte-identical fault trace (see
tests/test_chaos.py::test_link_trace_deterministic).
"""

from __future__ import annotations

import asyncio
import hashlib
import heapq
import json
import random
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class LinkPolicy:
    """Per-direction shaping knobs for one link.

    Asymmetric links are expressed by installing different policies for
    the A->B and B->A directions (ChaosNetwork.set_link_policy).
    """

    latency_s: float = 0.0  # base one-way delay
    jitter_s: float = 0.0  # uniform [0, jitter_s) added per message
    drop: float = 0.0  # P(message silently dropped)
    duplicate: float = 0.0  # P(message delivered twice)
    reorder: float = 0.0  # P(message held back past later traffic)
    reorder_extra_s: float = 0.05  # hold-back amount for reordered msgs
    bandwidth_bps: int = 0  # serialization cap in bytes/s; 0 = infinite

    def is_noop(self) -> bool:
        return (
            self.latency_s == 0.0
            and self.jitter_s == 0.0
            and self.drop == 0.0
            and self.duplicate == 0.0
            and self.reorder == 0.0
            and self.bandwidth_bps == 0
        )


def link_rng(seed: int, src_id: str, dst_id: str) -> random.Random:
    """Deterministic RNG for one link DIRECTION, independent of dial
    order or connection timing."""
    h = hashlib.sha256(
        b"tm-tpu-chaos:%d:%s>%s" % (seed, src_id.encode(), dst_id.encode())
    ).digest()
    return random.Random(int.from_bytes(h[:8], "big"))


class FaultTrace:
    """Append-only record of chaos decisions, serializable for replay
    comparison. Entries are plain tuples so `to_jsonl()` is byte-stable
    across runs."""

    def __init__(self):
        self.entries: list[tuple] = []

    def add(self, *entry) -> None:
        self.entries.append(entry)

    def to_jsonl(self) -> bytes:
        return b"\n".join(
            json.dumps(list(e), separators=(",", ":")).encode()
            for e in self.entries
        )

    def __len__(self) -> int:
        return len(self.entries)


@dataclass
class _Scheduled:
    due: float
    seq: int
    frames: list[bytes] = field(default_factory=list)

    def __lt__(self, other: "_Scheduled") -> bool:
        return (self.due, self.seq) < (other.due, other.seq)


class ChaosConn:
    """Connection wrapper applying a seeded LinkPolicy to the write
    direction. Exposes the SecretConnection surface MConnection uses
    (write/read/close) and passes everything else through."""

    def __init__(
        self,
        conn,
        policy: LinkPolicy,
        rng: random.Random,
        link_id: str = "",
        trace: Optional[FaultTrace] = None,
        policy_fn=None,
    ):
        self._conn = conn
        # policy_fn (when given) is re-resolved per message, so a
        # mid-scenario set_link/set_default_policy reshapes LIVE
        # connections, not just ones established afterwards
        self._policy = policy
        self._policy_fn = policy_fn
        self._rng = rng
        self.link_id = link_id
        self.trace = trace if trace is not None else FaultTrace()
        self._partial: dict[int, list[bytes]] = {}  # channel -> frames
        self._raw_mid: set[int] = set()  # channels mid-message on fast path
        self._heap: list[_Scheduled] = []
        self._seq = 0
        self._msg_seq = 0
        self._wakeup = asyncio.Event()
        self._busy_until = 0.0  # bandwidth serialization horizon
        self._order_floor = 0.0  # FIFO floor for non-reordered messages
        self._pump_task: Optional[asyncio.Task] = None
        self._pump_busy = False  # pump is mid-message on the wire
        self._closed = False

    @property
    def policy(self) -> LinkPolicy:
        return self._policy_fn() if self._policy_fn is not None else self._policy

    # --- write side (shaped) ---------------------------------------------

    async def write(self, data: bytes) -> None:
        if len(data) < 2:  # not an mconn packet; pass through
            await self._conn.write(data)
            return
        ch_id, eof = data[0], data[1] == 1
        if ch_id in self._raw_mid:
            # a message that began on the noop fast path finishes raw even
            # if the policy changed mid-message — mixing paths would split
            # its frames across the heap and corrupt channel reassembly
            if eof:
                self._raw_mid.discard(ch_id)
            await self._conn.write(data)
            return
        if (
            ch_id not in self._partial
            and not self._heap
            and not self._pump_busy
            and self.policy.is_noop()
        ):
            # fast path only when nothing is queued or mid-flush in the
            # pump: a raw write racing the pump's frame loop would
            # interleave two messages' frames and corrupt reassembly
            if not eof:
                self._raw_mid.add(ch_id)
            await self._conn.write(data)
            return
        frames = self._partial.setdefault(ch_id, [])
        frames.append(data)
        if not eof:
            return
        del self._partial[ch_id]
        await self._dispatch_message(ch_id, frames)

    async def _dispatch_message(self, ch_id: int, frames: list[bytes]) -> None:
        p = self.policy
        rng = self._rng
        msg = self._msg_seq
        self._msg_seq += 1
        size = sum(len(f) for f in frames)

        if p.drop and rng.random() < p.drop:
            self.trace.add(self.link_id, msg, ch_id, "drop", size)
            from ..obs import default_tracer

            default_tracer().event(
                "chaos.drop", link=self.link_id, ch=ch_id, bytes=size
            )
            return
        delay = p.latency_s
        if p.jitter_s:
            delay += rng.random() * p.jitter_s
        dup = bool(p.duplicate) and rng.random() < p.duplicate
        reordered = bool(p.reorder) and rng.random() < p.reorder

        loop = asyncio.get_running_loop()
        now = loop.time()
        if p.bandwidth_bps > 0:
            start = max(now, self._busy_until)
            self._busy_until = start + size / p.bandwidth_bps
            due = self._busy_until + delay
        else:
            due = now + delay
        if reordered:
            due += p.reorder_extra_s
        else:
            # preserve FIFO among non-reordered messages
            due = max(due, self._order_floor)
            self._order_floor = due
        self.trace.add(
            self.link_id,
            msg,
            ch_id,
            "deliver",
            size,
            round(delay, 6),
            int(dup),
            int(reordered),
        )
        copies = 2 if dup else 1
        for _ in range(copies):
            heapq.heappush(self._heap, _Scheduled(due, self._seq, frames))
            self._seq += 1
        self._ensure_pump()
        self._wakeup.set()

    def _ensure_pump(self) -> None:
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.get_running_loop().create_task(
                self._pump()
            )

    async def _pump(self) -> None:
        try:
            while not self._closed:
                if not self._heap:
                    self._wakeup.clear()
                    await self._wakeup.wait()
                    continue
                loop = asyncio.get_running_loop()
                head = self._heap[0]
                wait = head.due - loop.time()
                if wait > 0:
                    # a newly scheduled earlier message can preempt
                    self._wakeup.clear()
                    try:
                        await asyncio.wait_for(self._wakeup.wait(), wait)
                    except asyncio.TimeoutError:
                        pass
                    continue
                item = heapq.heappop(self._heap)
                self._pump_busy = True
                try:
                    for frame in item.frames:
                        await self._conn.write(frame)
                finally:
                    self._pump_busy = False
        except asyncio.CancelledError:
            raise
        except Exception:
            # connection died underneath us; MConnection's own recv/send
            # routines surface the error — the pump just stops shaping
            pass

    # --- read side (pass-through) ----------------------------------------

    async def read(self) -> bytes:
        return await self._conn.read()

    async def read_exactly(self, n: int) -> bytes:
        return await self._conn.read_exactly(n)

    def close(self) -> None:
        self._closed = True
        if self._pump_task is not None:
            self._pump_task.cancel()
        self._wakeup.set()
        self._conn.close()

    def __getattr__(self, name):
        return getattr(self._conn, name)
