"""Backend-outage detection and graceful degradation for perf capture.

Round-4 failure mode: with the axon tunnel endpoint dead, ANY `import
jax` in a process whose PYTHONPATH carries the tunnel's plugin site hangs
forever in PJRT plugin discovery — `bench.py` produced rc=124/rc=1
artifacts (a traceback tail after a 25-minute hang) instead of data.

This module bounds the damage: `probe_backend` initializes jax in a
SUBPROCESS with a hard timeout and classifies the outcome, so drivers can
(a) skip or (b) fall back to a CPU capture, and always emit a structured
`{"rc","error","backend","fallback"}` JSON artifact.

Env knobs:
  TM_TPU_BACKEND_GUARD_TIMEOUT  probe bound in seconds (default 120)
"""

from __future__ import annotations

import os
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Optional

# stderr markers that mean "infrastructure outage", not a code regression
_TUNNEL_MARKERS = ("Unable to initialize backend", "axon", "libtpu")

DEFAULT_PROBE_TIMEOUT = float(os.environ.get("TM_TPU_BACKEND_GUARD_TIMEOUT", "120"))


@dataclass
class BackendStatus:
    available: bool
    backend: Optional[str] = None  # platform name when available
    rc: int = 0  # probe subprocess return code (124 = timeout)
    error: str = ""  # classified failure detail
    kind: str = "ok"  # ok | tunnel_down | timeout | backend_error

    def as_dict(self) -> dict:
        return {
            "available": self.available,
            "backend": self.backend,
            "rc": self.rc,
            "error": self.error,
            "kind": self.kind,
        }


def sanitized_env(
    base: Optional[dict] = None, platform: Optional[str] = None
) -> dict:
    """Environment with the tunnel's jax plugin site stripped from
    PYTHONPATH (its discovery is what hangs when the endpoint is down),
    optionally pinned to a platform via JAX_PLATFORMS."""

    def is_tunnel_path(p: str) -> bool:
        return any(
            seg.startswith(".axon") or seg in ("axon_site", "axon")
            for seg in p.split(os.sep)
        )

    env = dict(base if base is not None else os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and not is_tunnel_path(p)
    )
    if platform is not None:
        env["JAX_PLATFORMS"] = platform
    return env


def classify_failure(stderr: str, rc: int) -> str:
    if rc == 124:
        return "timeout"
    if any(m in stderr for m in _TUNNEL_MARKERS):
        return "tunnel_down"
    return "backend_error"


def probe_backend(
    platform: Optional[str] = None,
    timeout_s: float = DEFAULT_PROBE_TIMEOUT,
    env: Optional[dict] = None,
    probe_cmd: Optional[list[str]] = None,
) -> BackendStatus:
    """Initialize jax in a bounded-time child and report what happened.

    `platform=None` probes whatever backend the ambient environment
    selects (the TPU tunnel in the perf harness); `platform="cpu"` probes
    the sanitized CPU fallback. `probe_cmd` overrides the child command
    (tests inject hang/failure behaviors without touching jax).
    """
    cmd = probe_cmd or [
        sys.executable,
        "-c",
        "import jax; print(jax.default_backend())",
    ]
    child_env = env
    if child_env is None:
        child_env = (
            sanitized_env(platform=platform) if platform else dict(os.environ)
        )
    try:
        proc = subprocess.run(
            cmd,
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=child_env,
        )
    except subprocess.TimeoutExpired:
        return BackendStatus(
            available=False,
            rc=124,
            error=f"jax init exceeded {timeout_s:.0f}s (hang)",
            kind="timeout",
        )
    if proc.returncode == 0 and proc.stdout.strip():
        return BackendStatus(
            available=True, backend=proc.stdout.strip().splitlines()[-1]
        )
    reason = proc.stderr.strip()[-800:] or f"rc={proc.returncode}"
    return BackendStatus(
        available=False,
        rc=proc.returncode,
        error=reason,
        kind=classify_failure(proc.stderr, proc.returncode),
    )


def meta_block(live: bool = True) -> dict:
    """Provenance stamp for every BENCH/MULTICHIP artifact: which
    backend, device count and jax produced the numbers. The r04-r06
    regression class was a sanitized CPU fallback silently recorded as
    the bench row — with the meta block a fallback row is detectable
    after the fact even if the fallback flags are lost. live=False
    builds the stamp WITHOUT importing jax (the failure paths, where a
    jax init may hang)."""
    if live:
        import jax

        return {
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "jax_version": jax.__version__,
        }
    try:
        from importlib.metadata import version

        jv = version("jax")
    except Exception:
        jv = None
    return {"backend": None, "device_count": 0, "jax_version": jv}


def fallback_artifact(
    status: BackendStatus,
    fallback: str = "none",
    extra: Optional[dict] = None,
) -> dict:
    """The structured artifact shape every guarded capture emits on
    degradation: always parseable, never a raw traceback tail."""
    out = {
        "rc": status.rc,
        "error": status.error,
        "backend": status.backend,
        "fallback": fallback,
        "kind": status.kind,
        "ok": status.available,
    }
    if extra:
        out.update(extra)
    return out
