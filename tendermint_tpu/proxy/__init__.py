"""Proxy — the node's three named ABCI connections.

Reference: proxy/multi_app_conn.go:24-28 — consensus/query/snapshot
connections (the mempool connection was removed along with the mempool).
A ClientCreator abstracts local vs remote apps (proxy/client.go).
"""

from .multi_app_conn import AppConns, ClientCreator, local_client_creator  # noqa: F401
