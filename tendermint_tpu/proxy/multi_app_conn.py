"""AppConns — consensus/query/snapshot ABCI connections.

Reference: proxy/multi_app_conn.go:24-28 + proxy/client.go ClientCreator.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from ..abci import types as abci
from ..abci.client import LocalClient, SocketClient


class ClientCreator:
    """Creates one ABCI client per named connection."""

    def __init__(self, factory: Callable[[], object]):
        self._factory = factory

    async def new_client(self):
        client = self._factory()
        if hasattr(client, "connect"):  # socket and grpc remote clients
            await client.connect()
        return client


def local_client_creator(app: abci.Application) -> ClientCreator:
    """All three connections share the app; LocalClient's lock serializes
    (the reference's local client shares one mutex across connections)."""
    shared = LocalClient(app)
    return ClientCreator(lambda: shared)


def remote_client_creator(host: str, port: int) -> ClientCreator:
    return ClientCreator(lambda: SocketClient(host, port))


class AppConns:
    def __init__(self, creator: ClientCreator):
        self._creator = creator
        self.consensus = None
        self.query = None
        self.snapshot = None

    async def start(self) -> None:
        self.consensus = await self._creator.new_client()
        self.query = await self._creator.new_client()
        self.snapshot = await self._creator.new_client()

    async def stop(self) -> None:
        for c in (self.consensus, self.query, self.snapshot):
            if c is not None:
                await c.close()
