"""StateV2 — centralized sequencer block production after the upgrade.

Reference: sequencer/state_v2.go — timer-driven `produceBlockRoutine`
(:127-206): RequestBlockDataV2(parent) → sign(block.Hash) → ApplyBlockV2 →
queue for broadcast. The asyncio shape replaces the goroutine+ticker with
one production task; `apply_block` stays the single serialized entry point
for both self-produced and gossiped blocks (:229-243).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..libs.log import Logger
from ..libs.service import Service
from ..types.block_v2 import BlockV2
from .signer import Signer

DEFAULT_BLOCK_INTERVAL = 3.0  # seconds (state_v2.go:16)


class StateV2(Service):
    def __init__(
        self,
        l2_node,
        block_interval: float = DEFAULT_BLOCK_INTERVAL,
        signer: Optional[Signer] = None,
        verifier=None,
        logger: Optional[Logger] = None,
    ):
        super().__init__("stateV2", logger)
        self.l2_node = l2_node
        self.signer = signer
        self.verifier = verifier
        self.block_interval = (
            block_interval if block_interval > 0 else DEFAULT_BLOCK_INTERVAL
        )
        self.sequencer_mode = signer is not None
        self.latest_block: Optional[BlockV2] = None
        self._apply_lock = asyncio.Lock()
        self.broadcast_queue: asyncio.Queue[BlockV2] = asyncio.Queue(100)

    # --- lifecycle ----------------------------------------------------------

    async def on_start(self) -> None:
        self.latest_block = self.l2_node.get_latest_block_v2()
        active = (
            self.sequencer_mode and self.signer.is_active_sequencer()
        )
        self.logger.info(
            "StateV2 initialized",
            latest_height=self.latest_block.number,
            sequencer_mode=self.sequencer_mode,
            is_active_sequencer=active,
        )
        if active:
            self.spawn(self._produce_block_routine())

    async def on_stop(self) -> None:
        pass

    # --- block production (state_v2.go:127-206) ------------------------------

    async def _produce_block_routine(self) -> None:
        self.logger.info(
            "starting block production", interval=self.block_interval
        )
        while self.is_running:
            await asyncio.sleep(self.block_interval)
            try:
                await self.produce_block()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.logger.error("failed to produce block", err=str(e))

    async def produce_block(self) -> Optional[BlockV2]:
        parent_hash = self.latest_block.hash
        block, _collected_l1 = self.l2_node.request_block_data_v2(parent_hash)
        block.signature = self.signer.sign(block.hash)
        await self.apply_block(block)
        try:
            self.broadcast_queue.put_nowait(block)
        except asyncio.QueueFull:
            self.logger.error(
                "broadcast queue full, dropping block", number=block.number
            )
        self.logger.debug(
            "block produced", number=block.number, txs=len(block.transactions)
        )
        return block

    # --- application (unified entry point, state_v2.go:229-243) --------------

    async def apply_block(self, block: BlockV2) -> None:
        async with self._apply_lock:
            self.l2_node.apply_block_v2(block)
            self.latest_block = block

    # --- queries -------------------------------------------------------------

    def latest_height(self) -> int:
        return self.latest_block.number if self.latest_block else 0

    def get_block_by_number(self, number: int) -> Optional[BlockV2]:
        return self.l2_node.get_block_by_number(number)

    def is_sequencer_mode(self) -> bool:
        return self.sequencer_mode
