"""Sequencer signer + verifier ports.

Reference: sequencer/interfaces.go:17-29 — `Signer` (Sign(data),
Address(), IsActiveSequencer()) and `SequencerVerifier`
(IsSequencer(addr)). The reference signs with go-ethereum ECDSA
(recoverable, 65 bytes) over the 32-byte block hash.
"""

from __future__ import annotations

from typing import Iterable, Protocol, runtime_checkable

from ..crypto import secp256k1


class ErrInvalidSignature(Exception):
    """Block signature verification failed (sequencer/interfaces.go:12)."""


@runtime_checkable
class Signer(Protocol):
    def sign(self, data: bytes) -> bytes: ...

    def address(self) -> bytes: ...

    def is_active_sequencer(self) -> bool: ...


@runtime_checkable
class SequencerVerifier(Protocol):
    def is_sequencer(self, addr: bytes) -> bool: ...


class LocalSigner:
    """In-process secp256k1 signer (the reference's production signer talks
    to an external keystore; tests and single-binary deployments use this)."""

    def __init__(self, priv: secp256k1.PrivKey, active: bool = True):
        self._priv = priv
        self._active = active
        pt = secp256k1.decompress_point(priv.public_key().data)
        self._address = secp256k1.eth_address(pt)

    def sign(self, data: bytes) -> bytes:
        return secp256k1.eth_sign(data, self._priv.secret)

    def address(self) -> bytes:
        return self._address

    def is_active_sequencer(self) -> bool:
        return self._active


class StaticSequencerVerifier:
    """Fixed allow-list verifier (the reference resolves sequencers from an
    L1 contract; the port is the same `IsSequencer(addr)` question)."""

    def __init__(self, addresses: Iterable[bytes]):
        self._allowed = {bytes(a) for a in addresses}

    def is_sequencer(self, addr: bytes) -> bool:
        return bytes(addr) in self._allowed
