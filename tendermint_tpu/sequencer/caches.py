"""Bounded caches for sequencer-mode gossip and sync.

Reference: sequencer/block_cache.go (BlockRingBuffer), pending_cache.go
(PendingBlockCache with longest-chain selection), hash_set.go (HashSet /
PeerHashSet dedupe with FIFO eviction). Capacities mirror
broadcast_reactor.go:29-34.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

from ..types.block_v2 import BlockV2

MAX_PENDING_BLOCKS = 500
MAX_PENDING_HEIGHT_AHEAD = 100
MAX_PENDING_HEIGHT_BEHIND = 20


class BlockRingBuffer:
    """Fixed-capacity ring of recently applied blocks, indexed by height
    (reference sequencer/block_cache.go)."""

    def __init__(self, capacity: int = 1000):
        self._capacity = capacity
        self._by_height: OrderedDict[int, BlockV2] = OrderedDict()

    def add(self, block: BlockV2) -> None:
        self._by_height[block.number] = block
        self._by_height.move_to_end(block.number)
        while len(self._by_height) > self._capacity:
            self._by_height.popitem(last=False)

    def get_by_height(self, height: int) -> Optional[BlockV2]:
        return self._by_height.get(height)

    def __len__(self) -> int:
        return len(self._by_height)


class HashSet:
    """Bounded seen-set with FIFO eviction (reference sequencer/hash_set.go)."""

    def __init__(self, capacity: int = 2000):
        self._capacity = capacity
        self._items: OrderedDict[bytes, None] = OrderedDict()

    def add(self, h: bytes) -> bool:
        """Add; returns True if it was ALREADY present (duplicate)."""
        if h in self._items:
            return True
        self._items[h] = None
        while len(self._items) > self._capacity:
            self._items.popitem(last=False)
        return False

    def discard(self, h: bytes) -> None:
        self._items.pop(h, None)

    def __contains__(self, h: bytes) -> bool:
        return h in self._items

    def __len__(self) -> int:
        return len(self._items)


class PeerHashSet:
    """Per-peer bounded sent-set (reference sequencer/hash_set.go
    PeerHashSet; capacity per broadcast_reactor.go:33)."""

    def __init__(self, capacity_per_peer: int = 500):
        self._capacity = capacity_per_peer
        self._peers: dict[str, HashSet] = {}

    def add_peer(self, peer_id: str) -> None:
        self._peers.setdefault(peer_id, HashSet(self._capacity))

    def remove_peer(self, peer_id: str) -> None:
        self._peers.pop(peer_id, None)

    def add(self, peer_id: str, h: bytes) -> None:
        self._peers.setdefault(peer_id, HashSet(self._capacity)).add(h)

    def contains(self, peer_id: str, h: bytes) -> bool:
        s = self._peers.get(peer_id)
        return s is not None and h in s


class PendingBlockCache:
    """Blocks that cannot be applied yet: future blocks, unverified-signer
    blocks, and recent past blocks for reorg (reference
    sequencer/pending_cache.go)."""

    def __init__(self):
        self._blocks: dict[bytes, BlockV2] = {}
        self._by_parent: dict[bytes, list[BlockV2]] = {}

    def add(self, block: BlockV2, local_height: int) -> bool:
        min_h = max(0, local_height - MAX_PENDING_HEIGHT_BEHIND)
        max_h = local_height + MAX_PENDING_HEIGHT_AHEAD
        if not (min_h <= block.number <= max_h):
            return False
        if len(self._blocks) >= MAX_PENDING_BLOCKS:
            return False
        if block.hash in self._blocks:
            return False
        self._blocks[block.hash] = block
        self._by_parent.setdefault(block.parent_hash, []).append(block)
        return True

    def get(self, h: bytes) -> Optional[BlockV2]:
        return self._blocks.get(h)

    def get_children(self, parent_hash: bytes) -> list[BlockV2]:
        return list(self._by_parent.get(parent_hash, ()))

    def get_longest_chain(
        self, parent_hash: bytes, _visited: Optional[set] = None
    ) -> list[BlockV2]:
        """Longest pending chain rooted at parent_hash, in apply order
        (reference pending_cache.go GetLongestChain). Hash/parent links are
        attacker-controlled wire fields, so traversal carries a visited set
        — a crafted 2-block cycle must not recurse unboundedly."""
        visited = _visited if _visited is not None else {parent_hash}
        longest: list[BlockV2] = []
        for child in self._by_parent.get(parent_hash, ()):
            if child.hash in visited:
                continue
            chain = [child] + self.get_longest_chain(
                child.hash, visited | {child.hash}
            )
            if len(chain) > len(longest):
                longest = chain
        return longest

    def remove(self, h: bytes) -> None:
        """Drop one pending block by hash (a forged copy that failed
        signature/apply must free its slot, or the genuine block of the
        same hash could never re-enter — add() dedupes by hash)."""
        block = self._blocks.pop(h, None)
        if block is None:
            return
        sibs = self._by_parent.get(block.parent_hash)
        if sibs:
            sibs[:] = [b for b in sibs if b.hash != h]
            if not sibs:
                del self._by_parent[block.parent_hash]

    def prune_below(self, height: int) -> None:
        for h, block in list(self._blocks.items()):
            if block.number <= height:
                del self._blocks[h]
                sibs = self._by_parent.get(block.parent_hash)
                if sibs:
                    sibs[:] = [b for b in sibs if b.hash != h]
                    if not sibs:
                        del self._by_parent[block.parent_hash]

    def size(self) -> int:
        return len(self._blocks)
