"""Sequencer mode — post-upgrade centralized block production.

Reference: sequencer/ (state_v2.go, broadcast_reactor.go, block_cache.go,
pending_cache.go, hash_set.go, interfaces.go). At UpgradeBlockHeight the
node stops BFT consensus and switches to this mode: a single sequencer
produces ECDSA-signed BlockV2 blocks on a timer, gossips them over a
dedicated channel pair, and followers verify-recover the signer address
and apply.
"""

from .caches import BlockRingBuffer, HashSet, PeerHashSet, PendingBlockCache
from .signer import LocalSigner, StaticSequencerVerifier
from .state_v2 import StateV2
from .verify import SequencerVerifyBatcher
from .broadcast_reactor import (
    BLOCK_BROADCAST_CHANNEL,
    SEQUENCER_SYNC_CHANNEL,
    BlockBroadcastReactor,
)

__all__ = [
    "BlockRingBuffer",
    "HashSet",
    "PeerHashSet",
    "PendingBlockCache",
    "LocalSigner",
    "StaticSequencerVerifier",
    "SequencerVerifyBatcher",
    "StateV2",
    "BlockBroadcastReactor",
    "BLOCK_BROADCAST_CHANNEL",
    "SEQUENCER_SYNC_CHANNEL",
]
