"""BlockBroadcastReactor — sequencer-mode block gossip + sync catchup.

Reference: sequencer/broadcast_reactor.go. Two channels:
- 0x50 broadcast (signature-verified BlockV2 gossip, :24-25),
- 0x51 sync (BlockRequest / BlockResponseV2 / NoBlockResponse, no
  signature verification — blocks fetched by request are trusted via the
  hash-linked chain, :26).

Shape: the sequencer node drains StateV2's broadcast queue and gossips;
follower nodes run an apply/sync routine. The reference (and the first
port) drove that routine on fixed 10-second polling ticks; this plane is
EVENT-DRIVEN (PERF_ANALYSIS §17):

- apply/sync wake on block receipt, pending-cache insertion, peer
  status/arrival/departure and NoBlockResponse — the configured
  apply/sync intervals survive only as a fallback tick;
- catchup keeps a window of up to `catchup_window` missing-height
  requests in flight (each response refills the window) instead of one
  thresholded burst per 10 s cycle, and `requested_heights` entries
  expire on NoBlockResponse / peer departure / TTL instead of
  accumulating for the life of the node;
- fan-out is encode-once (BlockV2.encode memoization) and
  backpressure-aware: a peer whose 0x50 send queue is full is skipped
  and revisited by a drain task instead of stalling the broadcast loop
  behind the slowest subscriber;
- follower-side ECDSA signature checks ride SequencerVerifyBatcher:
  off the event loop, bursts coalesced into single fn-lane rounds
  through parallel/scheduler under the `sequencer` class.
"""

from __future__ import annotations

import asyncio
import random
import time
from collections import OrderedDict, deque
from typing import Optional

from ..libs import protoio as pio
from ..libs.log import Logger
from ..libs.metrics import SequencerMetrics, default_metrics
from ..obs import default_tracer
from ..p2p.mconn import ChannelDescriptor
from ..p2p.switch import Reactor
from ..p2p.transport import Peer
from ..types.block_v2 import BlockV2
from .caches import (
    MAX_PENDING_HEIGHT_BEHIND,
    BlockRingBuffer,
    HashSet,
    PeerHashSet,
    PendingBlockCache,
)
from .signer import ErrInvalidSignature, SequencerVerifier
from .state_v2 import StateV2
from .verify import SequencerVerifyBatcher

BLOCK_BROADCAST_CHANNEL = 0x50
SEQUENCER_SYNC_CHANNEL = 0x51

SMALL_GAP_THRESHOLD = 20
RECENT_BLOCKS_CAPACITY = 1000
SEEN_BLOCKS_CAPACITY = 2000
PEER_SENT_CAPACITY = 500
APPLY_INTERVAL = 10.0
SYNC_INTERVAL = 10.0
# missing-height requests kept in flight during catchup (the window
# refills as responses land; [sequencer] catchup_window)
CATCHUP_WINDOW = 64
# deferred fan-out entries held per congested peer before the oldest
# drop (a dropped subscriber catches up on the 0x51 sync channel)
FANOUT_PENDING_CAP = 64
# cadence of the deferred-fan-out drain pass (only runs while some
# peer's 0x50 queue was full)
FANOUT_REVISIT_INTERVAL = 0.05
# receipt-timestamp map bound (apply-latency attribution)
_RECV_TIMES_CAP = 4096

# message kinds (field 1)
_BLOCK_RESPONSE_V2 = 1
_BLOCK_REQUEST = 2
_NO_BLOCK_RESPONSE = 3
_STATUS = 4  # height advertisement (the reference reuses blocksync's pool)


def _enc(kind: int, height: int = 0, block: Optional[BlockV2] = None) -> bytes:
    out = pio.field_varint(1, kind)
    if height:
        out += pio.field_varint(2, height)
    if block is not None:
        out += pio.field_bytes(3, block.encode())
    return out


def _dec(data: bytes) -> tuple[int, int, Optional[BlockV2]]:
    kind = height = 0
    block = None
    for num, _wt, val in pio.iter_fields(data):
        if num == 1:
            kind = val
        elif num == 2:
            height = val
        elif num == 3:
            block = BlockV2.decode(val)
    return kind, height, block


class BlockBroadcastReactor(Reactor):
    def __init__(
        self,
        state_v2: StateV2,
        verifier: Optional[SequencerVerifier] = None,
        wait_sync: bool = False,
        logger: Optional[Logger] = None,
        apply_interval: float = APPLY_INTERVAL,
        sync_interval: float = SYNC_INTERVAL,
        catchup_window: int = CATCHUP_WINDOW,
        metrics: Optional[SequencerMetrics] = None,
        tracer=None,
    ):
        super().__init__("BlockBroadcast")
        self.state_v2 = state_v2
        self.verifier = verifier if verifier is not None else state_v2.verifier
        self.wait_sync = wait_sync
        self.recent_blocks = BlockRingBuffer(RECENT_BLOCKS_CAPACITY)
        self.pending_cache = PendingBlockCache()
        self.seen_blocks = HashSet(SEEN_BLOCKS_CAPACITY)
        self.peer_sent = PeerHashSet(PEER_SENT_CAPACITY)
        self.peer_heights: dict[str, int] = {}
        # heights we asked for on the sync channel; unsolicited sync
        # responses are dropped (the unauthenticated channel must not let
        # an arbitrary peer extend our chain unprompted). Entries map
        # height -> (peer_id, monotonic request time) so NoBlockResponse,
        # peer departure and a TTL can expire them — the original set
        # accumulated unanswered heights for the life of the node.
        self.requested_heights: dict[int, tuple[str, float]] = {}
        self._apply_lock = asyncio.Lock()
        self.sequencer_started = False
        self._tasks: list[asyncio.Task] = []
        self.logger = (logger or state_v2.logger).with_fields(
            module="broadcastReactor"
        )
        self.metrics = metrics or default_metrics(SequencerMetrics)
        # seq.* spans: park (floor) / broadcast + sync_gap (gossip) /
        # apply (compute) — the sequencer family's wall-attribution
        # seam (obs.report.FAMILY_WALL_SPANS["sequencer"]). Heights on
        # these spans are V2 (L2) heights. is-None check: an empty
        # Tracer is falsy (it has __len__)
        self.tracer = default_tracer() if tracer is None else tracer
        # fallback tick intervals ([sequencer] apply_interval /
        # sync_interval): the event-driven wakeups below do the real
        # pacing; these only bound how stale a missed edge can get
        self.apply_interval = apply_interval
        self.sync_interval = sync_interval
        self.catchup_window = max(1, int(catchup_window))
        # silent-peer request expiry (NoBlockResponse and departures
        # expire immediately; this covers a peer that just never answers)
        self.request_ttl = max(1.0, float(sync_interval))
        self._wakeup = asyncio.Event()
        # off-loop coalesced ECDSA checks (sequencer/verify.py)
        self.verify_batcher = SequencerVerifyBatcher(
            self.verifier, logger=self.logger
        )
        # receipt time per block hash -> apply-latency attribution
        self._recv_times: dict[bytes, float] = {}
        # recent receipt->applied latencies, seconds (bench harness)
        self.apply_latencies: deque[float] = deque(maxlen=4096)
        # deferred fan-out: peer id -> ordered {hash: block} awaiting a
        # send-queue slot; drained by _fanout_revisit_routine
        self._fanout_pending: dict[str, OrderedDict[bytes, BlockV2]] = {}
        self._fanout_wakeup = asyncio.Event()
        self._fanout_task: Optional[asyncio.Task] = None

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(
                id=BLOCK_BROADCAST_CHANNEL, priority=6, send_queue_capacity=1000
            ),
            ChannelDescriptor(
                id=SEQUENCER_SYNC_CHANNEL, priority=5, send_queue_capacity=1000
            ),
        ]

    # --- lifecycle (broadcast_reactor.go:96-129) ----------------------------

    async def on_start(self) -> None:
        if not self.wait_sync:
            await self.start_sequencer_routines()

    async def start_sequencer_routines(self) -> None:
        """Start production/apply routines; called at upgrade or after
        blocksync catches up past the upgrade height (:104-125)."""
        if self.sequencer_started:
            self.logger.error("sequencer routines already started")
            return
        self.wait_sync = False
        if not self.state_v2.is_running:
            await self.state_v2.start()
        if self.state_v2.is_sequencer_mode():
            self._tasks.append(
                asyncio.create_task(self._broadcast_routine())
            )
        else:
            self._tasks.append(asyncio.create_task(self._apply_routine()))
        self.sequencer_started = True

    async def on_stop(self) -> None:
        if self._fanout_task is not None:
            self._tasks.append(self._fanout_task)
            self._fanout_task = None
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        self.verify_batcher.stop()
        if self.state_v2.is_running:
            await self.state_v2.stop()

    async def add_peer(self, peer: Peer) -> None:
        self.peer_sent.add_peer(peer.id)
        # advertise our height so peers can catch up from us
        peer.try_send(
            SEQUENCER_SYNC_CHANNEL,
            _enc(_STATUS, height=self.state_v2.latest_height()),
        )
        # a fresh peer may close our gap: let the sync pass look
        self._wakeup.set()

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        self.peer_sent.remove_peer(peer.id)
        self.peer_heights.pop(peer.id, None)
        dropped = self._fanout_pending.pop(peer.id, None)
        if dropped:
            self.metrics.fanout_dropped.inc(len(dropped))
        # in-flight requests to the departed peer will never be answered
        stale = [
            h
            for h, (pid, _t) in self.requested_heights.items()
            if pid == peer.id
        ]
        for h in stale:
            del self.requested_heights[h]
        if stale:
            self.metrics.requests_expired.inc(len(stale))
            self._wakeup.set()

    # --- receive (broadcast_reactor.go:146-205) ------------------------------

    async def receive(self, channel_id: int, peer: Peer, msg: bytes) -> None:
        try:
            kind, height, block = _dec(msg)
        except Exception as e:
            self.logger.error("bad sequencer msg", err=str(e))
            await self.switch.stop_peer_for_error(peer, "bad sequencer msg")
            return
        if channel_id == BLOCK_BROADCAST_CHANNEL:
            if kind == _BLOCK_RESPONSE_V2 and block is not None:
                await self._on_block_v2(block, peer, verify_sig=True)
        elif channel_id == SEQUENCER_SYNC_CHANNEL:
            if kind == _BLOCK_REQUEST:
                await self._on_block_request(height, peer)
            elif kind == _BLOCK_RESPONSE_V2 and block is not None:
                # Sync responses are signature-verified like broadcasts.
                # (The reference skips verification here as a shortcut, but
                # every sequencer block IS signed, and an unverified path
                # would let whichever peer answered a request — or pushed
                # an unsolicited response — extend our chain with forged
                # blocks. Requested heights only bypass the seen-dedup.)
                requested = block.number in self.requested_heights
                self.requested_heights.pop(block.number, None)
                await self._on_block_v2(
                    block, peer, verify_sig=True, dedup=not requested
                )
                if requested:
                    # window slot freed: the sync pass may request more
                    self._wakeup.set()
            elif kind == _STATUS:
                prev = self.peer_heights.get(peer.id, 0)
                self.peer_heights[peer.id] = height
                if height > prev:
                    self._wakeup.set()
            elif kind == _NO_BLOCK_RESPONSE:
                self._on_no_block(height, peer)

    def _on_no_block(self, height: int, peer: Peer) -> None:
        """The asked peer cannot serve `height`: expire the in-flight
        request (it would otherwise linger until TTL) and clamp our view
        of the peer below the failed height so the re-request lands on
        someone else."""
        entry = self.requested_heights.get(height)
        if entry is None or entry[0] != peer.id:
            return
        del self.requested_heights[height]
        self.metrics.requests_expired.inc()
        if self.peer_heights.get(peer.id, 0) >= height:
            self.peer_heights[peer.id] = height - 1
        self._wakeup.set()

    # --- routines -----------------------------------------------------------

    async def _broadcast_routine(self) -> None:
        """Sequencer side: drain StateV2's queue, gossip (:215-227)."""
        while True:
            block = await self.state_v2.broadcast_queue.get()
            self.recent_blocks.add(block)
            self.metrics.blocks_broadcast.inc()
            self.metrics.height.set(block.number)
            self._advertise_height(block.number)
            self._gossip_block(block, from_peer="")

    async def _apply_routine(self) -> None:
        """Follower side: event-driven pending-cache drain + gap check.
        Wakes on receipt/insertion/status edges (self._wakeup); the
        configured intervals remain only as a fallback tick."""
        fallback = max(0.01, min(self.apply_interval, self.sync_interval))
        while True:
            t_park = time.perf_counter()
            try:
                await asyncio.wait_for(self._wakeup.wait(), timeout=fallback)
            except asyncio.TimeoutError:
                pass
            self._wakeup.clear()
            if self.tracer.enabled:
                # the parked wait is the streaming plane's "floor":
                # event-driven wakeups keep it at the inter-block gap,
                # the polled design pinned it at the fallback tick
                self.tracer.add_span(
                    "seq.park",
                    t_park,
                    time.perf_counter() - t_park,
                    height=self.state_v2.latest_height() + 1,
                )
            try:
                await self.try_apply_from_cache()
                await self.check_sync_gap()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # the apply/sync loop must survive transient peer errors
                self.logger.error("apply routine error", err=str(e))

    # --- core logic (broadcast_reactor.go:251-316) ---------------------------

    def _note_received(self, block: BlockV2) -> None:
        if block.hash in self._recv_times:
            return
        self._recv_times[block.hash] = time.perf_counter()
        while len(self._recv_times) > _RECV_TIMES_CAP:
            self._recv_times.pop(next(iter(self._recv_times)))

    async def _on_block_v2(
        self, block: BlockV2, src: Peer, verify_sig: bool, dedup: bool = True
    ) -> None:
        if self.seen_blocks.add(block.hash) and dedup:
            return  # broadcast dedup; requested sync responses bypass dedup
        self._note_received(block)
        self.peer_sent.add(src.id, block.hash)
        self.peer_heights[src.id] = max(
            self.peer_heights.get(src.id, 0), block.number
        )
        local_height = self.state_v2.latest_height()
        if self._is_next_block(block):
            if verify_sig:
                # off-loop coalesced ECDSA round (burst -> one dispatch);
                # verified OUTSIDE the apply lock so concurrent receives
                # coalesce instead of serializing on it
                ok = await self.verify_batcher.submit_item(block)
                if not ok:
                    # un-poison dedup: a forged copy arriving first must
                    # not make us drop the genuine broadcast of this
                    # hash later
                    self.seen_blocks.discard(block.hash)
                    self.logger.error(
                        "invalid block signature", number=block.number
                    )
                    return
            try:
                await self.apply_block(block, verify_sig=False)
            except Exception as e:
                # un-poison on content/apply failures too: the signature
                # covers only the 32-byte hash, so a relayed copy with
                # tampered contents passes the signature check but fails
                # in the execution layer — the genuine copy of this hash
                # must still be acceptable later
                self.seen_blocks.discard(block.hash)
                self.logger.error(
                    "apply failed", number=block.number, err=str(e)
                )
                return
            if verify_sig:
                self._gossip_block(block, from_peer=src.id)
            # applying may unlock pending children immediately
            await self.try_apply_from_cache()
        elif verify_sig:
            if self.pending_cache.add(block, local_height):
                self.metrics.pending_blocks.set(self.pending_cache.size())
                # the parent may already be in flight on the sync plane
                self._wakeup.set()

    async def try_apply_from_cache(self) -> None:
        """Apply the longest pending chain on top of the head (:318-349).
        The whole chain's signatures verify as ONE coalesced off-loop
        round before any apply."""
        current = self.state_v2.latest_block
        if current is not None:
            chain = self.pending_cache.get_longest_chain(current.hash)
            verdicts = (
                await self.verify_batcher.submit_items(chain)
                if chain
                else []
            )
            for block, ok in zip(chain, verdicts):
                if not ok:
                    # same un-poisoning as the broadcast path, plus the
                    # pending slot: a forged copy must not block the
                    # genuine block of this hash from ever re-entering
                    self.seen_blocks.discard(block.hash)
                    self.pending_cache.remove(block.hash)
                    self.logger.error(
                        "invalid pending block signature",
                        number=block.number,
                    )
                    break
                if not self._is_next_block(block):
                    break
                try:
                    await self.apply_block(block, verify_sig=False)
                except Exception as e:
                    self.seen_blocks.discard(block.hash)
                    self.pending_cache.remove(block.hash)
                    self.logger.error(
                        "apply from cache failed",
                        number=block.number,
                        err=str(e),
                    )
                    break
        local_height = self.state_v2.latest_height()
        if local_height > MAX_PENDING_HEIGHT_BEHIND:
            self.pending_cache.prune_below(
                local_height - MAX_PENDING_HEIGHT_BEHIND
            )
        self.metrics.pending_blocks.set(self.pending_cache.size())

    async def check_sync_gap(self) -> None:
        """Keep a window of missing-height requests in flight when we're
        far behind (:351-383). Landed/stale/expired entries leave the
        window; the freed budget is re-requested immediately."""
        local_height = self.state_v2.latest_height()
        now = time.monotonic()
        live = set(self.switch.peers) if self.switch is not None else set()
        expired = 0
        for h in list(self.requested_heights):
            pid, t = self.requested_heights[h]
            if h <= local_height:
                del self.requested_heights[h]  # landed (or passed by)
            elif pid not in live or now - t > self.request_ttl:
                del self.requested_heights[h]
                expired += 1
        if expired:
            self.metrics.requests_expired.inc(expired)
        max_peer_height = max(self.peer_heights.values(), default=0)
        if max_peer_height - local_height <= SMALL_GAP_THRESHOLD:
            return
        t0 = time.perf_counter()
        await self._request_missing_blocks(local_height + 1, max_peer_height)
        if self.tracer.enabled:
            self.tracer.add_span(
                "seq.sync_gap",
                t0,
                time.perf_counter() - t0,
                height=local_height + 1,
                behind=max_peer_height - local_height,
            )

    async def _request_missing_blocks(self, start: int, end: int) -> None:
        peers = list(self.switch.peers.values()) if self.switch else []
        if not peers:
            return
        budget = self.catchup_window - len(self.requested_heights)
        if budget <= 0:
            return
        now = time.monotonic()
        for height in range(start, end + 1):
            if budget <= 0:
                break
            if height in self.requested_heights:
                continue
            peer = self._find_peer_with_height(peers, height)
            if peer is None:
                continue
            self.requested_heights[height] = (peer.id, now)
            self.metrics.catchup_requests.inc()
            peer.try_send(
                SEQUENCER_SYNC_CHANNEL, _enc(_BLOCK_REQUEST, height=height)
            )
            budget -= 1

    def _find_peer_with_height(self, peers, height: int):
        n = len(peers)
        start = random.randrange(n)
        for i in range(n):
            peer = peers[(start + i) % n]
            if self.peer_heights.get(peer.id, 0) >= height:
                return peer
        return None

    def _is_next_block(self, block: BlockV2) -> bool:
        current = self.state_v2.latest_block
        if current is None:
            return block.number == self.state_v2.latest_height() + 1
        return (
            block.number == current.number + 1
            and block.parent_hash == current.hash
        )

    async def apply_block(self, block: BlockV2, verify_sig: bool) -> None:
        """Verify + apply atomically (:389-420)."""
        async with self._apply_lock:
            t0 = time.perf_counter()
            if verify_sig and not self._verify_signature(block):
                raise ErrInvalidSignature(str(block.number))
            current = self.state_v2.latest_block
            if current is not None and block.parent_hash != current.hash:
                raise ValueError("parent mismatch")
            await self.state_v2.apply_block(block)
            if self.tracer.enabled:
                self.tracer.add_span(
                    "seq.apply",
                    t0,
                    time.perf_counter() - t0,
                    height=block.number,
                )
            self.recent_blocks.add(block)
            self._advertise_height(block.number)
            self.metrics.blocks_applied.inc()
            self.metrics.height.set(block.number)
            t_recv = self._recv_times.pop(block.hash, None)
            if t_recv is not None:
                lat = time.perf_counter() - t_recv
                self.metrics.apply_latency.observe(lat)
                self.apply_latencies.append(lat)
            self.logger.debug(
                "applied block", number=block.number, verify_sig=verify_sig
            )

    def _verify_signature(self, block: BlockV2) -> bool:
        """Recover signer address, check against the sequencer set
        (:422-455). Synchronous path — the gossip/sync receive planes
        use the coalesced off-loop verify_batcher instead."""
        if not block.signature:
            return False
        addr = block.recover_signer()
        if addr is None:
            return False
        if self.verifier is None:
            return False
        return self.verifier.is_sequencer(addr)

    # --- gossip (broadcast_reactor.go:457-511) -------------------------------

    def _gossip_block(self, block: BlockV2, from_peer: str) -> None:
        """Encode-once fan-out: ONE BlockV2 serialization (memoized on
        the block) framed into one wire message shared by every peer
        send. Congested peers defer instead of dropping or stalling."""
        if self.switch is None:
            return
        t0 = time.perf_counter()
        sends = 0
        msg = None  # framed lazily: zero eligible peers = zero encodes
        for peer in list(self.switch.peers.values()):
            if peer.id == from_peer:
                continue
            if self.peer_sent.contains(peer.id, block.hash):
                continue
            if msg is None:
                msg = _enc(_BLOCK_RESPONSE_V2, block=block)
            self._send_or_defer(peer, block, msg)
            sends += 1
        if sends and self.tracer.enabled:
            self.tracer.add_span(
                "seq.broadcast",
                t0,
                time.perf_counter() - t0,
                height=block.number,
                peers=sends,
            )

    def _send_or_defer(
        self,
        peer: Peer,
        block: BlockV2,
        msg: Optional[bytes] = None,
        defer: bool = True,
    ) -> bool:
        """try_send with skip-and-revisit backpressure: a full 0x50
        queue (the p2p send_queue_* signal) defers the block to the
        revisit drain instead of blocking the fan-out on one slow
        subscriber. The revisit drain itself calls with defer=False —
        the block is already at that peer's pending head."""
        headroom = getattr(peer, "queue_headroom", None)
        if headroom is None or headroom(BLOCK_BROADCAST_CHANNEL) > 0:
            if msg is None:
                msg = _enc(_BLOCK_RESPONSE_V2, block=block)
            if peer.try_send(BLOCK_BROADCAST_CHANNEL, msg):
                self.peer_sent.add(peer.id, block.hash)
                self.metrics.fanout_sends.inc()
                return True
        if defer:
            self._defer_fanout(peer.id, block)
        return False

    def _defer_fanout(self, peer_id: str, block: BlockV2) -> None:
        pending = self._fanout_pending.setdefault(peer_id, OrderedDict())
        if block.hash in pending:
            return
        pending[block.hash] = block
        self.metrics.fanout_deferred.inc()
        while len(pending) > FANOUT_PENDING_CAP:
            pending.popitem(last=False)
            self.metrics.fanout_dropped.inc()
        if self._fanout_task is None or self._fanout_task.done():
            self._fanout_task = asyncio.get_running_loop().create_task(
                self._fanout_revisit_routine()
            )
        self._fanout_wakeup.set()

    async def _fanout_revisit_routine(self) -> None:
        """Drain deferred fan-out as congested peers free queue slots.
        Parks when nothing is deferred; per-peer head-of-line order is
        preserved (a subscriber applies blocks in chain order anyway)."""
        while True:
            if not self._fanout_pending:
                self._fanout_wakeup.clear()
                await self._fanout_wakeup.wait()
            await asyncio.sleep(FANOUT_REVISIT_INTERVAL)
            if self.switch is None:
                continue
            floor = (
                self.state_v2.latest_height() - MAX_PENDING_HEIGHT_BEHIND
            )
            for peer_id in list(self._fanout_pending):
                pending = self._fanout_pending.get(peer_id)
                if pending is None:
                    continue
                peer = self.switch.peers.get(peer_id)
                if peer is None:
                    del self._fanout_pending[peer_id]
                    self.metrics.fanout_dropped.inc(len(pending))
                    continue
                while pending:
                    h, block = next(iter(pending.items()))
                    if block.number <= floor:
                        # too stale to push; the peer's own sync plane
                        # is the catch-up path now
                        pending.popitem(last=False)
                        self.metrics.fanout_dropped.inc()
                        continue
                    if self.peer_sent.contains(peer_id, h):
                        pending.popitem(last=False)
                        continue
                    if not self._send_or_defer(peer, block, defer=False):
                        break  # still congested
                    pending.popitem(last=False)
                if not pending:
                    self._fanout_pending.pop(peer_id, None)

    def _advertise_height(self, height: int) -> None:
        if self.switch is None:
            return
        msg = _enc(_STATUS, height=height)
        for peer in list(self.switch.peers.values()):
            peer.try_send(SEQUENCER_SYNC_CHANNEL, msg)

    async def _on_block_request(self, height: int, src: Peer) -> None:
        """Serve a block from the recent cache or the L2 node (:513-540)."""
        block = self.recent_blocks.get_by_height(height)
        if block is None:
            block = self.state_v2.get_block_by_number(height)
        if block is None:
            src.try_send(
                SEQUENCER_SYNC_CHANNEL, _enc(_NO_BLOCK_RESPONSE, height=height)
            )
            return
        src.try_send(SEQUENCER_SYNC_CHANNEL, _enc(_BLOCK_RESPONSE_V2, block=block))
