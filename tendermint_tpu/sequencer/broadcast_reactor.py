"""BlockBroadcastReactor — sequencer-mode block gossip + sync catchup.

Reference: sequencer/broadcast_reactor.go. Two channels:
- 0x50 broadcast (signature-verified BlockV2 gossip, :24-25),
- 0x51 sync (BlockRequest / BlockResponseV2 / NoBlockResponse, no
  signature verification — blocks fetched by request are trusted via the
  hash-linked chain, :26).

Shape: the sequencer node drains StateV2's broadcast queue and gossips;
follower nodes run an apply/sync routine that periodically drains the
pending cache and requests missing heights when the gap to the best peer
exceeds `SMALL_GAP_THRESHOLD` (:321-383).
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional

from ..libs import protoio as pio
from ..libs.log import Logger
from ..p2p.mconn import ChannelDescriptor
from ..p2p.switch import Reactor
from ..p2p.transport import Peer
from ..types.block_v2 import BlockV2
from .caches import (
    MAX_PENDING_HEIGHT_BEHIND,
    BlockRingBuffer,
    HashSet,
    PeerHashSet,
    PendingBlockCache,
)
from .signer import ErrInvalidSignature, SequencerVerifier
from .state_v2 import StateV2

BLOCK_BROADCAST_CHANNEL = 0x50
SEQUENCER_SYNC_CHANNEL = 0x51

SMALL_GAP_THRESHOLD = 20
RECENT_BLOCKS_CAPACITY = 1000
SEEN_BLOCKS_CAPACITY = 2000
PEER_SENT_CAPACITY = 500
APPLY_INTERVAL = 10.0
SYNC_INTERVAL = 10.0

# message kinds (field 1)
_BLOCK_RESPONSE_V2 = 1
_BLOCK_REQUEST = 2
_NO_BLOCK_RESPONSE = 3
_STATUS = 4  # height advertisement (the reference reuses blocksync's pool)


def _enc(kind: int, height: int = 0, block: Optional[BlockV2] = None) -> bytes:
    out = pio.field_varint(1, kind)
    if height:
        out += pio.field_varint(2, height)
    if block is not None:
        out += pio.field_bytes(3, block.encode())
    return out


def _dec(data: bytes) -> tuple[int, int, Optional[BlockV2]]:
    kind = height = 0
    block = None
    for num, _wt, val in pio.iter_fields(data):
        if num == 1:
            kind = val
        elif num == 2:
            height = val
        elif num == 3:
            block = BlockV2.decode(val)
    return kind, height, block


class BlockBroadcastReactor(Reactor):
    def __init__(
        self,
        state_v2: StateV2,
        verifier: Optional[SequencerVerifier] = None,
        wait_sync: bool = False,
        logger: Optional[Logger] = None,
    ):
        super().__init__("BlockBroadcast")
        self.state_v2 = state_v2
        self.verifier = verifier if verifier is not None else state_v2.verifier
        self.wait_sync = wait_sync
        self.recent_blocks = BlockRingBuffer(RECENT_BLOCKS_CAPACITY)
        self.pending_cache = PendingBlockCache()
        self.seen_blocks = HashSet(SEEN_BLOCKS_CAPACITY)
        self.peer_sent = PeerHashSet(PEER_SENT_CAPACITY)
        self.peer_heights: dict[str, int] = {}
        # heights we asked for on the sync channel; unsolicited sync
        # responses are dropped (the unauthenticated channel must not let
        # an arbitrary peer extend our chain unprompted)
        self.requested_heights: set[int] = set()
        self._apply_lock = asyncio.Lock()
        self.sequencer_started = False
        self._tasks: list[asyncio.Task] = []
        self.logger = (logger or state_v2.logger).with_fields(
            module="broadcastReactor"
        )
        # test hooks
        self.apply_interval = APPLY_INTERVAL
        self.sync_interval = SYNC_INTERVAL

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(
                id=BLOCK_BROADCAST_CHANNEL, priority=6, send_queue_capacity=1000
            ),
            ChannelDescriptor(
                id=SEQUENCER_SYNC_CHANNEL, priority=5, send_queue_capacity=1000
            ),
        ]

    # --- lifecycle (broadcast_reactor.go:96-129) ----------------------------

    async def on_start(self) -> None:
        if not self.wait_sync:
            await self.start_sequencer_routines()

    async def start_sequencer_routines(self) -> None:
        """Start production/apply routines; called at upgrade or after
        blocksync catches up past the upgrade height (:104-125)."""
        if self.sequencer_started:
            self.logger.error("sequencer routines already started")
            return
        self.wait_sync = False
        if not self.state_v2.is_running:
            await self.state_v2.start()
        if self.state_v2.is_sequencer_mode():
            self._tasks.append(
                asyncio.create_task(self._broadcast_routine())
            )
        else:
            self._tasks.append(asyncio.create_task(self._apply_routine()))
        self.sequencer_started = True

    async def on_stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        if self.state_v2.is_running:
            await self.state_v2.stop()

    async def add_peer(self, peer: Peer) -> None:
        self.peer_sent.add_peer(peer.id)
        # advertise our height so peers can catch up from us
        peer.try_send(
            SEQUENCER_SYNC_CHANNEL,
            _enc(_STATUS, height=self.state_v2.latest_height()),
        )

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        self.peer_sent.remove_peer(peer.id)
        self.peer_heights.pop(peer.id, None)

    # --- receive (broadcast_reactor.go:146-205) ------------------------------

    async def receive(self, channel_id: int, peer: Peer, msg: bytes) -> None:
        try:
            kind, height, block = _dec(msg)
        except Exception as e:
            self.logger.error("bad sequencer msg", err=str(e))
            await self.switch.stop_peer_for_error(peer, "bad sequencer msg")
            return
        if channel_id == BLOCK_BROADCAST_CHANNEL:
            if kind == _BLOCK_RESPONSE_V2 and block is not None:
                await self._on_block_v2(block, peer, verify_sig=True)
        elif channel_id == SEQUENCER_SYNC_CHANNEL:
            if kind == _BLOCK_REQUEST:
                await self._on_block_request(height, peer)
            elif kind == _BLOCK_RESPONSE_V2 and block is not None:
                # Sync responses are signature-verified like broadcasts.
                # (The reference skips verification here as a shortcut, but
                # every sequencer block IS signed, and an unverified path
                # would let whichever peer answered a request — or pushed
                # an unsolicited response — extend our chain with forged
                # blocks. Requested heights only bypass the seen-dedup.)
                requested = block.number in self.requested_heights
                self.requested_heights.discard(block.number)
                await self._on_block_v2(
                    block, peer, verify_sig=True, dedup=not requested
                )
            elif kind == _STATUS:
                self.peer_heights[peer.id] = height
            # _NO_BLOCK_RESPONSE: nothing to do (logged by reference too)

    # --- routines -----------------------------------------------------------

    async def _broadcast_routine(self) -> None:
        """Sequencer side: drain StateV2's queue, gossip (:215-227)."""
        while True:
            block = await self.state_v2.broadcast_queue.get()
            self.recent_blocks.add(block)
            self._advertise_height(block.number)
            self._gossip_block(block, from_peer="")

    async def _apply_routine(self) -> None:
        """Follower side: periodic pending-cache drain + gap check
        (:229-249)."""
        apply_t = sync_t = 0.0
        tick = min(self.apply_interval, self.sync_interval, 0.5)
        while True:
            await asyncio.sleep(tick)
            apply_t += tick
            sync_t += tick
            try:
                if apply_t >= self.apply_interval:
                    apply_t = 0.0
                    await self.try_apply_from_cache()
                if sync_t >= self.sync_interval:
                    sync_t = 0.0
                    await self.check_sync_gap()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # the apply/sync loop must survive transient peer errors
                self.logger.error("apply routine error", err=str(e))

    # --- core logic (broadcast_reactor.go:251-316) ---------------------------

    async def _on_block_v2(
        self, block: BlockV2, src: Peer, verify_sig: bool, dedup: bool = True
    ) -> None:
        if self.seen_blocks.add(block.hash) and dedup:
            return  # broadcast dedup; requested sync responses bypass dedup
        self.peer_sent.add(src.id, block.hash)
        self.peer_heights[src.id] = max(
            self.peer_heights.get(src.id, 0), block.number
        )
        local_height = self.state_v2.latest_height()
        if self._is_next_block(block):
            try:
                await self.apply_block(block, verify_sig)
            except ErrInvalidSignature:
                # un-poison dedup: a forged copy arriving first must not
                # make us drop the genuine broadcast of this hash later
                self.seen_blocks.discard(block.hash)
                self.logger.error(
                    "invalid block signature", number=block.number
                )
                return
            except Exception as e:
                # also un-poison on content/apply failures: the signature
                # covers only the 32-byte hash, so a relayed copy with
                # tampered contents passes _verify_signature but fails in
                # the execution layer — the genuine copy of this hash must
                # still be acceptable later
                self.seen_blocks.discard(block.hash)
                self.logger.error(
                    "apply failed", number=block.number, err=str(e)
                )
                return
            if verify_sig:
                self._gossip_block(block, from_peer=src.id)
            # applying may unlock pending children immediately
            await self.try_apply_from_cache()
        elif verify_sig:
            self.pending_cache.add(block, local_height)

    async def try_apply_from_cache(self) -> None:
        """Apply the longest pending chain on top of the head (:318-349)."""
        current = self.state_v2.latest_block
        if current is None:
            return
        chain = self.pending_cache.get_longest_chain(current.hash)
        for block in chain:
            if not self._is_next_block(block):
                break
            try:
                await self.apply_block(block, verify_sig=True)
            except Exception as e:
                self.logger.error(
                    "apply from cache failed", number=block.number, err=str(e)
                )
                break
        local_height = self.state_v2.latest_height()
        if local_height > MAX_PENDING_HEIGHT_BEHIND:
            self.pending_cache.prune_below(
                local_height - MAX_PENDING_HEIGHT_BEHIND
            )

    async def check_sync_gap(self) -> None:
        """Request missing blocks when we're far behind (:351-383)."""
        local_height = self.state_v2.latest_height()
        self.requested_heights = {
            h for h in self.requested_heights if h > local_height
        }
        max_peer_height = max(self.peer_heights.values(), default=0)
        if max_peer_height - local_height <= SMALL_GAP_THRESHOLD:
            return
        await self._request_missing_blocks(local_height + 1, max_peer_height)

    async def _request_missing_blocks(self, start: int, end: int) -> None:
        peers = list(self.switch.peers.values()) if self.switch else []
        if not peers:
            return
        # bound per cycle like the reference (smallGapThreshold per cycle)
        for height in range(start, min(end, start + SMALL_GAP_THRESHOLD) + 1):
            peer = self._find_peer_with_height(peers, height)
            if peer is None:
                continue
            self.requested_heights.add(height)
            peer.try_send(
                SEQUENCER_SYNC_CHANNEL, _enc(_BLOCK_REQUEST, height=height)
            )

    def _find_peer_with_height(self, peers, height: int):
        n = len(peers)
        start = random.randrange(n)
        for i in range(n):
            peer = peers[(start + i) % n]
            if self.peer_heights.get(peer.id, 0) >= height:
                return peer
        return None

    def _is_next_block(self, block: BlockV2) -> bool:
        current = self.state_v2.latest_block
        if current is None:
            return block.number == self.state_v2.latest_height() + 1
        return (
            block.number == current.number + 1
            and block.parent_hash == current.hash
        )

    async def apply_block(self, block: BlockV2, verify_sig: bool) -> None:
        """Verify + apply atomically (:389-420)."""
        async with self._apply_lock:
            if verify_sig and not self._verify_signature(block):
                raise ErrInvalidSignature(str(block.number))
            current = self.state_v2.latest_block
            if current is not None and block.parent_hash != current.hash:
                raise ValueError("parent mismatch")
            await self.state_v2.apply_block(block)
            self.recent_blocks.add(block)
            self._advertise_height(block.number)
            self.logger.info(
                "applied block", number=block.number, verify_sig=verify_sig
            )

    def _verify_signature(self, block: BlockV2) -> bool:
        """Recover signer address, check against the sequencer set
        (:422-455)."""
        if not block.signature:
            return False
        addr = block.recover_signer()
        if addr is None:
            return False
        if self.verifier is None:
            return False
        return self.verifier.is_sequencer(addr)

    # --- gossip (broadcast_reactor.go:457-511) -------------------------------

    def _gossip_block(self, block: BlockV2, from_peer: str) -> None:
        if self.switch is None:
            return
        msg = _enc(_BLOCK_RESPONSE_V2, block=block)
        for peer in list(self.switch.peers.values()):
            if peer.id == from_peer:
                continue
            if self.peer_sent.contains(peer.id, block.hash):
                continue
            if peer.try_send(BLOCK_BROADCAST_CHANNEL, msg):
                self.peer_sent.add(peer.id, block.hash)

    def _advertise_height(self, height: int) -> None:
        if self.switch is None:
            return
        msg = _enc(_STATUS, height=height)
        for peer in list(self.switch.peers.values()):
            peer.try_send(SEQUENCER_SYNC_CHANNEL, msg)

    async def _on_block_request(self, height: int, src: Peer) -> None:
        """Serve a block from the recent cache or the L2 node (:513-540)."""
        block = self.recent_blocks.get_by_height(height)
        if block is None:
            block = self.state_v2.get_block_by_number(height)
        if block is None:
            src.try_send(
                SEQUENCER_SYNC_CHANNEL, _enc(_NO_BLOCK_RESPONSE, height=height)
            )
            return
        src.try_send(SEQUENCER_SYNC_CHANNEL, _enc(_BLOCK_RESPONSE_V2, block=block))
