"""Off-loop, coalesced BlockV2 signature verification.

Follower-side ECDSA checks (recover the eth address from the 65-byte
signature over the 32-byte block hash, membership-check it against the
sequencer set) used to run synchronously inside `_on_block_v2` — ON the
event loop, one recover per block. A burst of incoming BlockV2s (catchup
windows, post-heal floods) paid one loop stall per block.

`SequencerVerifyBatcher` rides the shared MicroBatcher machinery: the
burst accumulates while the previous round is in flight, and each round
runs as ONE fn-lane submission through `parallel/scheduler.py` under the
`sequencer` priority class — off the event loop, serialized against the
device rounds of every other verify caller, visible in the scheduler's
dispatch log/round spans like any other class.

Reference counterpart: none — the reference recovers serially inside
onBlockV2 (sequencer/broadcast_reactor.go:251-316).
"""

from __future__ import annotations

from typing import Optional

from ..consensus.microbatch import MicroBatcher
from ..libs.log import Logger


class SequencerVerifyBatcher(MicroBatcher):
    """Verdicts are booleans: True = signed by an allowed sequencer.
    error_verdict=False — a verifier failure rejects the block, which
    only drops the message (the block stays re-receivable; the seen-set
    un-poisoning in the reactor covers the retry)."""

    def __init__(
        self,
        verifier,
        logger: Optional[Logger] = None,
        max_batch: int = 256,
    ):
        super().__init__(
            max_batch=max_batch, logger=logger, error_verdict=False
        )
        self.verifier = verifier

    def _check(self, blocks: list) -> list[bool]:
        verifier = self.verifier
        out = []
        for block in blocks:
            if verifier is None or not block.signature:
                out.append(False)
                continue
            addr = block.recover_signer()
            out.append(addr is not None and verifier.is_sequencer(addr))
        return out

    def _verify_items(self, blocks: list) -> list[bool]:
        # runs on the micro-batcher's executor thread: submit the whole
        # chunk as one scheduler fn-lane round (degrades to a direct
        # call when no scheduler is installed/running)
        from ..parallel.scheduler import default_scheduler

        sched = default_scheduler()
        if sched is not None:
            return sched.submit_fn_sync(
                blocks, self._check, klass="sequencer",
                engine="secp_recover",
            )
        return self._check(blocks)
