"""RPC core — the route table over node internals.

Reference: rpc/core/routes.go:10-43 (the morph fork's table: the mempool
broadcast routes are deleted along with the mempool) + rpc/core/*.go
handlers reading the node environment (node/node.go:1174-1200). Bytes are
hex-encoded in results (the reference mixes hex and base64; hex
throughout keeps the surface predictable).

No gRPC API route: the fork's rpc/grpc surface is Ping-only after the
mempool removal (rpc/grpc/api.go:10-13 — BroadcastTx went with the
mempool), and `health` over JSON-RPC/websocket is this framework's
equivalent liveness probe. The ABCI process boundary (the load-bearing
RPC in the reference) is covered by abci/client.py's socket protocol +
abci-cli.
"""

from __future__ import annotations

import json
from typing import Optional

from ..types.event_bus import Query


def _from_hex(value, what: str = "hash", required: bool = False) -> bytes:
    """Parse a hex string param (optional 0x prefix) into bytes, raising a
    clean JSON-RPC invalid-params error instead of a bare ValueError.
    `required` distinguishes a mandatory param (tx hash, evidence) from a
    genuinely optional one (header_by_hash's empty lookup)."""
    if not value:
        if required:
            from .server import RPCError

            raise RPCError(-32602, f"missing required param: {what}")
        return b""
    s = value[2:] if isinstance(value, str) and value.startswith("0x") else value
    try:
        return bytes.fromhex(s)
    except (ValueError, TypeError):
        from .server import RPCError

        raise RPCError(-32602, f"invalid {what}: not hex") from None


def _hex(b: bytes) -> str:
    return b.hex().upper()


def _seq_started(node) -> bool:
    return bool(
        getattr(
            getattr(node, "sequencer_reactor", None),
            "sequencer_started",
            False,
        )
    )


class RPCCore:
    def __init__(self, node):
        self.node = node

    # --- route table (reference routes.go:10-43) ----------------------------

    def routes(self) -> dict:
        return {
            # info
            "health": self.health,
            "status": self.status,
            "net_info": self.net_info,
            "blockchain": self.blockchain,
            "genesis": self.genesis,
            "genesis_chunked": self.genesis_chunked,
            "header": self.header,
            "header_by_hash": self.header_by_hash,
            "block": self.block,
            "block_by_hash": self.block_by_hash,
            "block_results": self.block_results,
            "commit": self.commit,
            "validators": self.validators,
            "consensus_state": self.consensus_state,
            "dump_consensus_state": self.dump_consensus_state,
            # light-client serving plane (tendermint_tpu/lightserve):
            # cached proof routes, present when the node assembled one
            **(
                {
                    "light_block": self.light_block,
                    "signed_header": self.signed_header,
                    "validator_set": self.validator_set,
                }
                if getattr(self.node, "lightserve", None) is not None
                else {}
            ),
            "dump_traces": self.dump_traces,
            "dump_health": self.dump_health,
            "dump_dispatch_ledger": self.dump_dispatch_ledger,
            # on-demand profiling hooks (obs/profiler.py), present when
            # the node assembled a ProfileCapture
            **(
                {
                    "profile_start": self.profile_start,
                    "profile_stop": self.profile_stop,
                }
                if getattr(self.node, "profiler", None) is not None
                else {}
            ),
            "consensus_params": self.consensus_params,
            "tx": self.tx,
            "tx_search": self.tx_search,
            "block_search": self.block_search,
            # abci
            "abci_info": self.abci_info,
            "abci_query": self.abci_query,
            # evidence
            "broadcast_evidence": self.broadcast_evidence,
            # help
            "help": lambda: {"routes": sorted(self.routes())},
            # unsafe (gated on config rpc.unsafe, reference routes.go:46-50)
            **(
                {
                    "dial_seeds": self.dial_seeds,
                    "dial_peers": self.dial_peers,
                }
                if getattr(self.node.config.rpc, "unsafe", False)
                else {}
            ),
        }

    # --- handlers ------------------------------------------------------------

    def health(self) -> dict:
        """Liveness + health verdict (the reference's `health` returns
        `{}`; readiness tooling needs the verdict, not just an open
        socket). `status` is the monitor roll-up — "ok" when the live
        health plane is disabled, so probes against a minimal node
        don't read "disabled" as unhealthy; `monitored` disambiguates."""
        from ..obs.health import VERDICT_NAMES

        n = self.node
        monitor = getattr(n, "health_monitor", None)
        bs = n.block_store
        return {
            "node_id": getattr(getattr(n, "node_key", None), "id", ""),
            "latest_block_height": bs.height,
            "catching_up": not (
                n.consensus.is_running or _seq_started(n)
            ),
            "monitored": monitor is not None,
            "status": (
                VERDICT_NAMES[monitor.status()]
                if monitor is not None
                else "ok"
            ),
        }

    def dump_health(self) -> dict:
        """The full health-plane verdict: per-subsystem/per-detector
        SLO burn-rate state + the recent incident log (the structured
        form of the `health.incident` events in dump_traces)."""
        monitor = getattr(self.node, "health_monitor", None)
        if monitor is None:
            return {"enabled": False}
        out = monitor.verdict()
        out["enabled"] = True
        return out

    def dump_dispatch_ledger(self, entries=None, **_kw) -> dict:
        """Device-cost ledger (obs/ledger.py): per-class device-seconds
        and shares, fill-efficiency distribution, padding-waste totals,
        requests-per-dispatch amortization, plus the newest structured
        round entries (`entries` param, default 128) and the
        shape-registry counters the totals reconcile against."""
        from ..crypto.shape_registry import default_shape_registry
        from ..obs.ledger import default_ledger

        sched = getattr(self.node, "verify_scheduler", None)
        ledger = sched.ledger if sched is not None else default_ledger()
        try:
            n = int(entries) if entries is not None else 128
        except (TypeError, ValueError):
            from .server import RPCError

            raise RPCError(
                -32602, "invalid entries: not an integer"
            ) from None
        return {
            "enabled": sched is not None,
            "summary": ledger.summary(),
            # entries <= 0 means "summary only" (ledger.entries treats
            # limit 0 as unlimited, which is the opposite of what a
            # caller asking for zero entries wants)
            "entries": ledger.entries(limit=n) if n > 0 else [],
            "shape_registry": default_shape_registry().snapshot(),
        }

    def profile_start(self, label="", device=True, **_kw) -> dict:
        """Arm an on-demand profiling session: a jax device trace
        (guarded, CPU-backend tolerant — unavailability is reported
        structurally inside `device_trace`, not an error) plus a
        sampled event-loop profile, both landing under data/profiles.
        A second start while one runs is a structured error."""
        from ..obs.profiler import ProfilerUnavailable

        try:
            started = self.node.profiler.start(
                label=str(label or ""),
                device=device not in (False, "false", "0", 0),
            )
        except ProfilerUnavailable as e:
            from .server import RPCError

            raise RPCError(-32000, f"profiler unavailable: {e}") from None
        return {"started": True, **started}

    def profile_stop(self, **_kw) -> dict:
        """Disarm the running session; returns artifact paths + the
        loop profile's hottest stacks. No session running is a
        structured error (the profiler-unavailable path)."""
        from ..obs.profiler import ProfilerUnavailable

        try:
            session = self.node.profiler.stop()
        except ProfilerUnavailable as e:
            from .server import RPCError

            raise RPCError(-32000, f"profiler unavailable: {e}") from None
        return {"stopped": True, **session}

    def status(self) -> dict:
        n = self.node
        bs = n.block_store
        latest_h = bs.height
        meta = bs.load_block_meta(latest_h) if latest_h else None
        pv_pub = n.priv_validator.get_pub_key()
        return {
            "node_info": {
                "id": n.node_key.id,
                "listen_addr": n._listen_addr(),
                "network": n.genesis.chain_id,
                "moniker": n.config.base.moniker,
            },
            "sync_info": {
                "latest_block_height": latest_h,
                "latest_block_hash": _hex(meta.block_id.hash) if meta else "",
                "latest_app_hash": _hex(meta.header.app_hash) if meta else "",
                "latest_block_time": meta.header.time_ns if meta else 0,
                # a post-upgrade sequencer-mode node is NOT catching up:
                # BFT is stopped by design (readiness tooling gates on
                # this — it must not drain every upgraded node forever)
                "catching_up": not (
                    n.consensus.is_running or _seq_started(n)
                ),
                # morph: post-upgrade sequencer mode (StateV2); height is
                # the V2 (L2) chain head this node has applied
                "sequencer_mode": _seq_started(n),
                "v2_height": (
                    n.state_v2.latest_height()
                    if getattr(n, "state_v2", None) is not None
                    else 0
                ),
            },
            "validator_info": {
                "address": _hex(pv_pub.address()),
                "pub_key": _hex(pv_pub.data),
                "voting_power": self._own_power(pv_pub),
            },
        }

    def _own_power(self, pub) -> int:
        vals = self.node.consensus.state.validators
        if vals is None:
            return 0
        _, val = vals.get_by_address(pub.address())
        return val.voting_power if val else 0

    def net_info(self) -> dict:
        sw = self.node.switch
        return {
            "listening": True,
            "n_peers": len(sw.peers),
            "peers": [
                {
                    "node_info": {
                        "id": p.id,
                        "listen_addr": p.node_info.listen_addr,
                        "moniker": p.node_info.moniker,
                    },
                    "is_outbound": p.outbound,
                    "remote_ip": p.socket_addr.host,
                }
                for p in sw.peers.values()
            ],
        }

    def blockchain(self, minHeight=None, maxHeight=None, **_kw) -> dict:
        bs = self.node.block_store
        max_h = int(maxHeight) if maxHeight else bs.height
        max_h = min(max_h, bs.height)
        min_h = max(int(minHeight) if minHeight else 1, bs.base)
        min_h = max(min_h, max_h - 19)  # reference caps at 20 metas
        metas = []
        for h in range(max_h, min_h - 1, -1):
            m = bs.load_block_meta(h)
            if m:
                metas.append(self._meta_json(m))
        return {"last_height": bs.height, "block_metas": metas}

    def genesis(self) -> dict:
        return {"genesis": self.node.genesis.to_json()}

    def dial_seeds(self, seeds=None, **_kw) -> dict:
        """Unsafe: dial the given seed addresses (reference routes.go:48)."""
        return self._dial(seeds or [], persistent=False)

    def dial_peers(self, peers=None, persistent=False, **_kw) -> dict:
        """Unsafe: dial the given peer addresses (reference routes.go:49)."""
        return self._dial(peers or [], persistent=bool(persistent))

    def _dial(self, addrs, persistent: bool) -> dict:
        from ..p2p.transport import NetAddress

        if isinstance(addrs, str):
            addrs = [a for a in addrs.split(",") if a]
        parsed = [NetAddress.parse(a) for a in addrs]
        self.node.switch.dial_peers_async(parsed, persistent=persistent)
        return {"log": f"dialing {len(parsed)} addresses"}

    def genesis_chunked(self, chunk=None, **_kw) -> dict:
        """Genesis split into base64 chunks (reference rpc/core/net.go
        GenesisChunked; routes.go:22) for large genesis documents."""
        import base64
        import json as _json

        data = _json.dumps(self.node.genesis.to_json()).encode()
        size = 16 * 1024
        chunks = [data[i : i + size] for i in range(0, len(data), size)] or [
            b""
        ]
        idx = int(chunk) if chunk is not None else 0
        if not (0 <= idx < len(chunks)):
            from .server import RPCError

            raise RPCError(
                -32000,
                f"chunk {idx} out of range (total {len(chunks)})",
            )
        return {
            "chunk": idx,
            "total": len(chunks),
            "data": base64.b64encode(chunks[idx]).decode(),
        }

    def header(self, height=None, **_kw) -> dict:
        """Block header only (reference routes.go:27)."""
        bs = self.node.block_store
        h = int(height) if height else bs.height
        meta = bs.load_block_meta(h)
        if meta is None:
            from .server import RPCError

            raise RPCError(-32000, f"no header at height {h}")
        return {"header": self._header_json(meta.header)}

    def header_by_hash(self, hash=None, **_kw) -> dict:
        """Block header by block hash (reference routes.go:28)."""
        bs = self.node.block_store
        h_bytes = _from_hex(hash)
        blk = bs.load_block_by_hash(h_bytes)
        if blk is None:
            from .server import RPCError

            raise RPCError(-32000, "header not found")
        return {"header": self._header_json(blk.header)}

    def block(self, height=None, **_kw) -> dict:
        bs = self.node.block_store
        h = int(height) if height else bs.height
        blk = bs.load_block(h)
        if blk is None:
            from .server import RPCError

            raise RPCError(-32000, f"no block at height {h}")
        meta = bs.load_block_meta(h)
        return {
            "block_id": self._bid_json(meta.block_id),
            "block": self._block_json(blk),
        }

    def block_by_hash(self, hash=None, **_kw) -> dict:
        bs = self.node.block_store
        h_bytes = _from_hex(hash)
        blk = bs.load_block_by_hash(h_bytes)
        if blk is None:
            from .server import RPCError

            raise RPCError(-32000, "block not found")
        meta = bs.load_block_meta(blk.header.height)
        return {
            "block_id": self._bid_json(meta.block_id),
            "block": self._block_json(blk),
        }

    def block_results(self, height=None, **_kw) -> dict:
        ss = self.node.state_store
        bs = self.node.block_store
        h = int(height) if height else bs.height
        raw = ss.load_abci_responses(h)
        if raw is None:
            from .server import RPCError

            raise RPCError(-32000, f"no results for height {h}")
        from ..state.execution import ABCIResponses

        resp = ABCIResponses.decode(raw)
        return {
            "height": h,
            "txs_results": [
                {"code": r.code, "data": _hex(r.data), "log": r.log,
                 "events": [
                     {"type": e.type, "attributes": e.attributes}
                     for e in r.events
                 ]}
                for r in resp.deliver_txs
            ],
        }

    def commit(self, height=None, **_kw) -> dict:
        bs = self.node.block_store
        h = int(height) if height else bs.height
        blk = bs.load_block(h)
        commit = bs.load_seen_commit(h) if h == bs.height else None
        if commit is None:
            nxt = bs.load_block(h + 1)
            commit = nxt.last_commit if nxt else bs.load_seen_commit(h)
        if blk is None or commit is None:
            from .server import RPCError

            raise RPCError(-32000, f"no commit at height {h}")
        return {
            "signed_header": {
                "header": self._header_json(blk.header),
                "commit": self._commit_json(commit),
            },
            "canonical": True,
        }

    # one page of validators per response (reference rpc/core/env.go
    # validatePerPage: per_page defaults to 30, capped at 100) — large
    # committees paginate instead of one unbounded response
    _VALS_PER_PAGE_DEFAULT = 100
    _VALS_PER_PAGE_MAX = 100

    def _paginate_validators(self, vals, h: int, page, per_page) -> dict:
        from .server import RPCError

        try:
            page = int(page) if page is not None else 1
            per_page = (
                int(per_page)
                if per_page is not None
                else self._VALS_PER_PAGE_DEFAULT
            )
        except (TypeError, ValueError):
            raise RPCError(-32602, "invalid page/per_page") from None
        per_page = max(1, min(per_page, self._VALS_PER_PAGE_MAX))
        total = vals.size()
        pages = max(1, -(-total // per_page))
        if not (1 <= page <= pages):
            raise RPCError(
                -32602, f"page {page} out of range (1..{pages})"
            )
        lo = (page - 1) * per_page
        window = vals.validators[lo : lo + per_page]
        return {
            "block_height": h,
            "validators": [self._validator_json(v) for v in window],
            "count": len(window),
            "total": total,
            "page": page,
            "per_page": per_page,
        }

    @staticmethod
    def _validator_json(v) -> dict:
        return {
            "address": _hex(v.address),
            "pub_key": _hex(v.pub_key.data),
            "pub_key_type": getattr(v.pub_key, "type_name", "ed25519"),
            "voting_power": v.voting_power,
            "proposer_priority": v.proposer_priority,
            **(
                {"bls_pub_key": _hex(v.bls_pub_key)}
                if v.bls_pub_key
                else {}
            ),
        }

    def validators(self, height=None, page=None, per_page=None, **_kw) -> dict:
        ss = self.node.state_store
        h = int(height) if height else self.node.block_store.height
        vals = ss.load_validators(h)
        if vals is None:
            from .server import RPCError

            raise RPCError(-32000, f"no validators at height {h}")
        return self._paginate_validators(vals, h, page, per_page)

    # --- light-client serving plane (tendermint_tpu/lightserve) -------------

    def _lightserve_block(self, height, compressed=False):
        from .server import RPCError

        h = int(height) if height else 0
        cache = self.node.lightserve.cache
        lb = cache.get_compressed(h) if compressed else cache.get(h)
        if lb is None:
            raise RPCError(
                -32000, f"no light block at height {h or 'latest'}"
            )
        return lb

    def _signed_header_json(self, lb) -> dict:
        return {
            "header": self._header_json(lb.header),
            "commit": (
                self._commit_json(lb.commit)
                if lb.commit is not None
                else None
            ),
        }

    def light_block(self, height=None, proof=None, **_kw) -> dict:
        """The full proof for one height — signed header + validator set
        assembled once by the LightBlockCache and served to every
        client (one round trip instead of commit + validators).
        `proof="qc"` requests the QC-compressed shape: the N-CommitSig
        payload is dropped and the QuorumCertificate alone proves the
        header (capability negotiation at the RPC layer — legacy
        clients never send the param and keep the full commit; heights
        without a canonical QC fall back to the full proof)."""
        if proof not in (None, "", "full", "qc"):
            from .server import RPCError

            raise RPCError(-32602, f"unknown proof format {proof!r}")
        lb = self._lightserve_block(height, compressed=proof == "qc")
        return {
            "light_block": {
                "signed_header": self._signed_header_json(lb),
                **(
                    {"qc": self._qc_json(lb.qc)}
                    if lb.qc is not None
                    else {}
                ),
                # the FULL set, un-paginated: this IS the proof — a
                # partial set could never re-hash to validators_hash
                "validator_set": {
                    "validators": [
                        self._validator_json(v)
                        for v in lb.validators.validators
                    ],
                    "total": lb.validators.size(),
                },
            }
        }

    def signed_header(self, height=None, **_kw) -> dict:
        """Header + commit only (clients that track the set themselves)."""
        lb = self._lightserve_block(height)
        return {
            "signed_header": self._signed_header_json(lb),
            "canonical": True,
        }

    def validator_set(self, height=None, page=None, per_page=None,
                      **_kw) -> dict:
        """The validator set backing a light block, paginated — served
        from the proof cache (the `validators` route reads the state
        store per request instead)."""
        lb = self._lightserve_block(height)
        return self._paginate_validators(lb.validators, lb.height, page,
                                         per_page)

    def consensus_state(self) -> dict:
        cs = self.node.consensus
        rs = cs.rs
        return {
            "round_state": {
                "height": rs.height,
                "round": rs.round,
                "step": int(rs.step),
                "proposal": rs.proposal is not None,
                "locked_round": rs.locked_round,
                "valid_round": rs.valid_round,
            }
        }

    def dump_consensus_state(self) -> dict:
        out = self.consensus_state()
        out["peers"] = [
            {"node_address": p.id} for p in self.node.switch.peers.values()
        ]
        return out

    def dump_traces(self, format=None, heights=None, **_kw) -> dict:
        """Flight-recorder dump (tendermint_tpu/obs). Formats:
        - default: the raw span ring + the last-N-heights flight view,
          plus the node's identity and per-peer clock table so
          tools/cluster_trace.py can merge dumps from several validators
          onto one timeline;
        - format=chrome: a Chrome trace_event JSON object — save
          `result.trace` to a file and load it in Perfetto."""
        from .. import obs

        # is-None check: an empty Tracer is falsy (it defines __len__),
        # so `or` would discard a node's injected-but-quiet ring and
        # dump the (possibly unrelated) process default instead — the
        # PR 4 falsy-tracer bug class
        tracer = getattr(self.node, "tracer", None)
        if tracer is None:
            tracer = obs.default_tracer()
        records = tracer.records()
        if format == "chrome":
            return {
                "enabled": tracer.enabled,
                "trace": tracer.to_chrome_trace(records),
            }
        try:
            n = int(heights) if heights else 16
        except (TypeError, ValueError):
            from .server import RPCError

            raise RPCError(-32602, "invalid heights: not an integer") from None
        if n <= 0:
            n = 16  # flight_snapshot slices [-n:]; non-positive would
            # return everything instead of nothing
        recs = [r.to_json() for r in records]
        return {
            "enabled": tracer.enabled,
            "epoch_wall_ns": tracer.epoch_wall_ns,
            "node_id": getattr(
                getattr(self.node, "node_key", None), "id", ""
            ),
            "moniker": getattr(
                getattr(getattr(self.node, "config", None), "base", None),
                "moniker",
                "",
            ),
            "peer_clock": self._peer_clock(),
            "records": recs,
            "flight": {
                str(h): rows
                for h, rows in obs.flight_snapshot(records, n).items()
            },
            "attribution": obs.attribution(recs),
            # the per-height conservation audit: named buckets + the
            # dark_time residue the health plane alarms on
            "conservation": self._conservation_json(recs, n),
        }

    @staticmethod
    def _conservation_json(recs: list, n: int) -> dict:
        from .. import obs

        cons = obs.wall_conservation(recs, n)
        # string height keys like the flight view (JSON object keys)
        cons["heights"] = {
            str(h): row for h, row in cons["heights"].items()
        }
        return cons

    def _peer_clock(self) -> dict:
        sw = getattr(self.node, "switch", None)
        return sw.peer_clock_table() if sw is not None else {}

    def consensus_params(self, height=None, **_kw) -> dict:
        state = self.node.consensus.state
        cp = state.consensus_params
        return {
            "block_height": int(height) if height else state.last_block_height,
            "consensus_params": {
                "block": {"max_bytes": cp.block.max_bytes},
                "evidence": {
                    "max_age_num_blocks": cp.evidence.max_age_num_blocks,
                    "max_age_duration": cp.evidence.max_age_duration_ns,
                    "max_bytes": cp.evidence.max_bytes,
                },
                "batch": {
                    "blocks_interval": cp.batch.blocks_interval,
                    "timeout": cp.batch.timeout_ns,
                },
            },
        }

    def tx(self, hash=None, prove=False, **_kw) -> dict:
        idx = getattr(self.node, "indexer", None)
        if idx is None:
            from .server import RPCError

            raise RPCError(-32000, "tx indexing is disabled")
        res = idx.get_tx(_from_hex(hash, required=True))
        if res is None:
            from .server import RPCError

            raise RPCError(-32000, "tx not found")
        return self._tx_result_json(res, hash)

    def tx_search(self, query="", page=1, per_page=30, **_kw) -> dict:
        idx = getattr(self.node, "indexer", None)
        if idx is None:
            from .server import RPCError

            raise RPCError(-32000, "tx indexing is disabled")
        results = idx.search_txs(query, limit=int(per_page))
        return {
            "txs": [
                self._tx_result_json(r, None) for r in results
            ],
            "total_count": len(results),
        }

    def block_search(self, query="", page=1, per_page=30, **_kw) -> dict:
        idx = getattr(self.node, "indexer", None)
        if idx is None:
            from .server import RPCError

            raise RPCError(-32000, "tx indexing is disabled")
        heights = idx.search_blocks(query, limit=int(per_page))
        bs = self.node.block_store
        blocks = []
        for h in heights:
            m = bs.load_block_meta(h)
            if m:
                blocks.append(self._meta_json(m))
        return {"blocks": blocks, "total_count": len(blocks)}

    async def abci_info(self) -> dict:
        import asyncio as _aio

        info = self.node.app.info()
        if _aio.iscoroutine(info):  # external app via proxy connection
            info = await info
        return {
            "response": {
                "data": info.data,
                "version": info.version,
                "last_block_height": info.last_block_height,
                "last_block_app_hash": _hex(info.last_block_app_hash),
            }
        }

    async def abci_query(self, path="", data="", height=0, prove=False, **_kw):
        import asyncio as _aio

        res = self.node.app.query(
            path, _from_hex(data, "data"), int(height), bool(prove)
        )
        if _aio.iscoroutine(res):  # external app via proxy connection
            res = await res
        return {
            "response": {
                "code": res.code,
                "log": res.log,
                "key": _hex(res.key),
                "value": _hex(res.value),
                "height": res.height,
            }
        }

    def broadcast_evidence(self, evidence="", **_kw) -> dict:
        from ..types.evidence import decode_evidence

        ev = decode_evidence(_from_hex(evidence, "evidence", required=True))
        self.node.evidence_pool.add_evidence(ev)
        return {"hash": _hex(ev.hash())}

    # --- event subscriptions (websocket) -------------------------------------

    def subscribe_ws(self, client_id, query_str: str):
        return self.node.event_bus.subscribe(
            f"ws-{client_id}", Query(query_str)
        )

    def unsubscribe_ws(self, client_id, query_str: str) -> None:
        try:
            self.node.event_bus.unsubscribe(
                f"ws-{client_id}", Query(query_str)
            )
        except Exception:
            pass

    def encode_event(self, msg) -> dict:
        """Best-effort JSON encoding of a bus message's data payload."""
        data = msg.data
        from ..types.block import Block, Header

        if isinstance(data, Block):
            return {"type": "block", "value": self._block_json(data)}
        if isinstance(data, Header):
            return {"type": "header", "value": self._header_json(data)}
        if isinstance(data, tuple) and len(data) == 3:
            height, tx_hash, tx = data
            return {
                "type": "tx",
                "value": {
                    "height": height,
                    "hash": _hex(tx_hash),
                    "tx": _hex(tx),
                },
            }
        return {"type": type(data).__name__, "value": repr(data)}

    # --- json helpers ---------------------------------------------------------

    @staticmethod
    def _bid_json(bid) -> dict:
        return {
            "hash": _hex(bid.hash),
            "parts": {
                "total": bid.part_set_header.total,
                "hash": _hex(bid.part_set_header.hash),
            },
        }

    def _header_json(self, h) -> dict:
        return {
            "chain_id": h.chain_id,
            "height": h.height,
            "time": h.time_ns,
            "last_block_id": self._bid_json(h.last_block_id),
            "last_commit_hash": _hex(h.last_commit_hash),
            "data_hash": _hex(h.data_hash),
            "validators_hash": _hex(h.validators_hash),
            "next_validators_hash": _hex(h.next_validators_hash),
            "consensus_hash": _hex(h.consensus_hash),
            "app_hash": _hex(h.app_hash),
            "last_results_hash": _hex(h.last_results_hash),
            "evidence_hash": _hex(h.evidence_hash),
            "proposer_address": _hex(h.proposer_address),
            "batch_hash": _hex(h.batch_hash),
            "version": {"block": h.version_block, "app": h.version_app},
            "hash": _hex(h.hash()),
        }

    def _commit_json(self, c) -> dict:
        return {
            "height": c.height,
            "round": c.round,
            "block_id": self._bid_json(c.block_id),
            "signatures": [
                {
                    "block_id_flag": int(s.block_id_flag),
                    "validator_address": _hex(s.validator_address),
                    "timestamp": s.timestamp_ns,
                    "signature": _hex(s.signature),
                    "bls_signature": _hex(s.bls_signature),
                    "qc_signature": _hex(s.qc_signature),
                }
                for s in c.signatures
            ],
        }

    def _qc_json(self, qc) -> dict:
        return {
            "height": qc.height,
            "round": qc.round,
            "block_id": self._bid_json(qc.block_id),
            "signers_size": qc.signers.size,
            "signers": _hex(qc.signers.to_bytes()),
            "agg_signature": _hex(qc.agg_signature),
        }

    def _block_json(self, b) -> dict:
        return {
            "header": self._header_json(b.header),
            "data": {
                "txs": [_hex(tx) for tx in b.data.txs],
                "l2_block_meta": _hex(b.data.l2_block_meta),
                "l2_batch_header": _hex(b.data.l2_batch_header),
            },
            "evidence": [_hex(ev.encode()) for ev in b.evidence],
            "last_commit": self._commit_json(b.last_commit)
            if b.last_commit
            else None,
        }

    def _meta_json(self, m) -> dict:
        return {
            "block_id": self._bid_json(m.block_id),
            "block_size": m.block_size,
            "header": self._header_json(m.header),
            "num_txs": m.num_txs,
        }

    def _tx_result_json(self, r, tx_hash) -> dict:
        from ..crypto import tmhash

        return {
            "hash": tx_hash or _hex(tmhash.sum(r.tx)),
            "height": r.height,
            "index": r.index,
            "tx_result": {
                "code": r.code,
                "log": r.log,
                "events": [
                    {"type": t, "attributes": attrs} for t, attrs in r.events
                ],
            },
            "tx": _hex(r.tx),
        }
