"""RPC clients: HTTP (keep-alive JSON-RPC), websocket events, local.

Reference: rpc/client/http (HTTP + websocket event subscriptions),
rpc/client/local (in-proc, wraps the core directly). The method surface
mirrors the core route table (rpc/core/routes.go:10-43); every route is
reachable via `call`, with named helpers for the common ones.
"""

from __future__ import annotations

import asyncio
import json
import os
import struct
from typing import Any, AsyncIterator, Optional


def _split_addr(addr: str) -> tuple[str, int]:
    s = addr
    for prefix in ("tcp://", "http://", "ws://"):
        s = s.removeprefix(prefix)
    s = s.split("/", 1)[0]
    host, _, port = s.rpartition(":")
    return host or "127.0.0.1", int(port)


class RPCClientError(RuntimeError):
    """RuntimeError subclass: pre-consolidation callers catch
    (ConnectionError, RuntimeError, OSError) around RPC calls."""

    def __init__(self, code: int, message: str):
        super().__init__(f"rpc error {code}: {message}")
        self.code = code
        self.message = message


class _NamedRoutes:
    """Named helpers shared by every client flavor."""

    async def call(self, method: str, **params) -> Any:
        raise NotImplementedError

    async def status(self):
        return await self.call("status")

    async def health(self):
        """Liveness + monitor verdict: {node_id, latest_block_height,
        catching_up, monitored, status} (rpc/core.py health — no longer
        the reference's empty dict)."""
        return await self.call("health")

    async def dump_health(self):
        """Full health-plane dump: per-subsystem detector SLO state +
        recent incidents (obs/health.HealthMonitor.verdict())."""
        return await self.call("dump_health")

    async def net_info(self):
        return await self.call("net_info")

    async def genesis(self):
        return await self.call("genesis")

    async def block(self, height: Optional[int] = None):
        return await self.call("block", height=height)

    async def block_by_hash(self, hash_hex: str):
        return await self.call("block_by_hash", hash=hash_hex)

    async def block_results(self, height: Optional[int] = None):
        return await self.call("block_results", height=height)

    async def blockchain(self, min_height: int, max_height: int):
        return await self.call(
            "blockchain", minHeight=min_height, maxHeight=max_height
        )

    async def commit(self, height: Optional[int] = None):
        return await self.call("commit", height=height)

    async def validators(self, height: Optional[int] = None, **kw):
        return await self.call("validators", height=height, **kw)

    async def consensus_state(self):
        return await self.call("consensus_state")

    async def consensus_params(self, height: Optional[int] = None):
        return await self.call("consensus_params", height=height)

    async def abci_info(self):
        return await self.call("abci_info")

    async def abci_query(self, path: str, data: str, height: int = 0,
                         prove: bool = False):
        return await self.call(
            "abci_query", path=path, data=data, height=height, prove=prove
        )

    async def tx(self, hash_hex: str, prove: bool = False):
        return await self.call("tx", hash=hash_hex, prove=prove)

    async def tx_search(self, query: str, page: int = 1, per_page: int = 30):
        return await self.call(
            "tx_search", query=query, page=page, per_page=per_page
        )

    async def block_search(self, query: str, page: int = 1,
                           per_page: int = 30):
        return await self.call(
            "block_search", query=query, page=page, per_page=per_page
        )

    async def broadcast_evidence(self, evidence_json: str):
        return await self.call("broadcast_evidence", evidence=evidence_json)


class HTTPClient(_NamedRoutes):
    """JSON-RPC 2.0 over a persistent HTTP/1.1 connection.

    Unlike the one-shot client in rpc/light_provider.py this keeps the
    connection alive across calls (the reference http client pools too).
    """

    def __init__(self, addr: str):
        self.host, self.port = _split_addr(addr)
        self._id = 0
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()

    async def _ensure(self) -> None:
        if self._writer is None or self._writer.is_closing():
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port
            )

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None

    async def call(self, method: str, **params) -> Any:
        params = {k: v for k, v in params.items() if v is not None}
        self._id += 1
        payload = json.dumps(
            {"jsonrpc": "2.0", "id": self._id, "method": method,
             "params": params}
        ).encode()
        async with self._lock:
            for attempt in (0, 1):  # one retry on a dead keep-alive conn
                await self._ensure()
                try:
                    self._writer.write(
                        b"POST / HTTP/1.1\r\nHost: rpc\r\n"
                        b"Content-Type: application/json\r\n"
                        b"Content-Length: " + str(len(payload)).encode()
                        + b"\r\n\r\n" + payload
                    )
                    await self._writer.drain()
                    status = await self._reader.readline()
                    if not status:
                        raise ConnectionError("closed")
                    if b"200" not in status:
                        # the rest of the response is unread: drop the
                        # connection or the next call reads stale bytes
                        await self.close()
                        raise RPCClientError(
                            -32000, f"http error: {status.decode().strip()}"
                        )
                    headers = {}
                    while True:
                        line = await self._reader.readline()
                        if line in (b"\r\n", b"\n", b""):
                            break
                        k, _, v = line.decode().partition(":")
                        headers[k.strip().lower()] = v.strip()
                    n = int(headers.get("content-length", 0))
                    body = await self._reader.readexactly(n) if n else b""
                    break
                except (ConnectionError, asyncio.IncompleteReadError):
                    await self.close()
                    if attempt:
                        raise
        resp = json.loads(body)
        if "error" in resp and resp["error"]:
            e = resp["error"]
            raise RPCClientError(e.get("code", -1), e.get("message", ""))
        return resp.get("result")


class LocalClient(_NamedRoutes):
    """In-proc client over the node's RPCCore (reference rpc/client/local)."""

    def __init__(self, node):
        from .core import RPCCore

        self.core = RPCCore(node)

    async def call(self, method: str, **params) -> Any:
        params = {k: v for k, v in params.items() if v is not None}
        fn = self.core.routes().get(method)
        if fn is None:
            raise RPCClientError(-32601, f"method {method!r} not found")
        res = fn(**params)
        if asyncio.iscoroutine(res):
            res = await res
        return res

    async def subscribe(self, query: str):
        return self.core.subscribe_ws(id(self), query)

    async def unsubscribe(self, query: str) -> None:
        self.core.unsubscribe_ws(id(self), query)


class WSClient:
    """Websocket event-subscription client (reference rpc/client/http ws).

    subscribe(query) -> async iterator of event payloads. Regular RPC
    calls also work over the socket (the server dispatches non-subscribe
    methods through the same handler).
    """

    def __init__(self, addr: str):
        self.host, self.port = _split_addr(addr)
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._id = 0
        self._pending: dict[Any, asyncio.Future] = {}
        self._events: dict[str, asyncio.Queue] = {}
        self._pump_task: Optional[asyncio.Task] = None

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port
        )
        key = "dGhlIHNhbXBsZSBub25jZQ=="
        self._writer.write(
            (
                f"GET /websocket HTTP/1.1\r\nHost: {self.host}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        await self._writer.drain()
        status = await self._reader.readline()
        if b"101" not in status:
            raise ConnectionError(f"ws upgrade refused: {status!r}")
        while True:
            line = await self._reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
        self._pump_task = asyncio.get_running_loop().create_task(
            self._pump(), name="ws-client/pump"
        )

    async def close(self) -> None:
        if self._pump_task:
            self._pump_task.cancel()
        if self._writer:
            self._writer.close()

    async def _send_frame(self, data: bytes, opcode: int = 1) -> None:
        mask = os.urandom(4)
        n = len(data)
        header = bytes([0x80 | opcode])  # FIN | opcode
        if n < 126:
            header += bytes([0x80 | n])
        elif n < 1 << 16:
            header += bytes([0x80 | 126]) + struct.pack(">H", n)
        else:
            header += bytes([0x80 | 127]) + struct.pack(">Q", n)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(data))
        self._writer.write(header + mask + masked)
        await self._writer.drain()

    async def _send(self, obj: dict) -> None:
        await self._send_frame(json.dumps(obj).encode())

    async def _read_msg(self) -> Optional[bytes]:
        message = b""
        while True:
            try:
                h = await self._reader.readexactly(2)
            except (asyncio.IncompleteReadError, ConnectionError):
                return None
            fin, opcode = h[0] & 0x80, h[0] & 0x0F
            n = h[1] & 0x7F
            if n == 126:
                n = struct.unpack(">H", await self._reader.readexactly(2))[0]
            elif n == 127:
                n = struct.unpack(">Q", await self._reader.readexactly(8))[0]
            payload = await self._reader.readexactly(n)
            if opcode == 8:  # close
                return None
            if opcode == 9:  # ping -> masked pong with same payload
                await self._send_frame(payload, opcode=0xA)
                continue
            if opcode == 10:  # pong: control frame, not message data
                continue
            message += payload
            if fin:
                return message

    async def _pump(self) -> None:
        while True:
            raw = await self._read_msg()
            if raw is None:
                for fut in self._pending.values():
                    if not fut.done():
                        fut.set_exception(ConnectionError("ws closed"))
                return
            try:
                msg = json.loads(raw)
            except json.JSONDecodeError:
                continue
            rid = msg.get("id")
            if isinstance(rid, str) and rid.endswith("#event"):
                q = msg.get("result", {}).get("query", "")
                queue = self._events.get(q)
                if queue is not None:
                    queue.put_nowait(msg["result"])
                continue
            fut = self._pending.pop(rid, None)
            if fut is not None and not fut.done():
                fut.set_result(msg)

    async def call(self, method: str, **params) -> Any:
        self._id += 1
        rid = self._id
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            await self._send(
                {"jsonrpc": "2.0", "id": rid, "method": method,
                 "params": params}
            )
            resp = await asyncio.wait_for(fut, 30)
        finally:
            self._pending.pop(rid, None)
        if resp.get("error"):
            e = resp["error"]
            raise RPCClientError(e.get("code", -1), e.get("message", ""))
        return resp.get("result")

    async def subscribe(self, query: str) -> AsyncIterator[dict]:
        """Subscribe and yield `{"query", "data", "events"}` payloads."""
        queue: asyncio.Queue = asyncio.Queue()
        self._events[query] = queue
        await self.call("subscribe", query=query)

        async def gen():
            while True:
                yield await queue.get()

        return gen()

    async def unsubscribe(self, query: str) -> None:
        self._events.pop(query, None)
        await self.call("unsubscribe", query=query)
