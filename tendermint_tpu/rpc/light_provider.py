"""RPC-backed light-block provider.

Reference: light/provider/http (provider over rpc/client/http). Fetches
the proof for a height from a node's RPC and assembles a LightBlock.
Against a lightserve-enabled node (tendermint_tpu/lightserve) that is
ONE `light_block` round trip to the proof cache; against a legacy node
it falls back to `commit` + `validators`, paginating the validator set
(the route serves at most one 100-entry page — a >100 committee fetched
as a single page would silently truncate and never re-hash to
validators_hash). Transient transport failures retry with bounded
exponential backoff before the provider reports "no block".

The JSON-RPC transport is rpc/client.HTTPClient (one client
implementation package-wide); `RPCClient` remains as its historical
alias here.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from .client import HTTPClient as RPCClient, RPCClientError  # noqa: F401

# bounded retry-with-backoff on transient provider failures: attempts
# sleep base * 2^i between tries (the chain keeps producing while we
# wait, so give up fast — the client's primary-replacement logic is the
# real recovery path)
DEFAULT_RETRIES = 3
DEFAULT_BACKOFF_BASE_S = 0.05
DEFAULT_BACKOFF_MAX_S = 1.0

_VALS_PAGE = 100
# hard ceiling on rows accepted from one provider: providers are
# UNTRUSTED (a light client's whole threat model), and a malicious
# `total` must bound to a few hundred round trips, not a billion — any
# real committee fits, and an oversized set fails validators_hash anyway
_VALS_MAX = 20_000


def header_from_json(hdr: dict):
    """Parse a header from its RPC JSON form (rpc/core._header_json) and
    return a types.Header whose .hash() is recomputed locally — callers
    verifying untrusted responses must never trust a supplied hash."""
    from ..types.block import Header
    from ..types.block_id import BlockID
    from ..types.part_set import PartSetHeader

    return Header(
        chain_id=hdr["chain_id"],
        height=hdr["height"],
        time_ns=hdr["time"],
        last_block_id=BlockID(
            hash=bytes.fromhex(hdr["last_block_id"]["hash"]),
            part_set_header=PartSetHeader(
                hdr["last_block_id"]["parts"]["total"],
                bytes.fromhex(hdr["last_block_id"]["parts"]["hash"]),
            ),
        ),
        last_commit_hash=bytes.fromhex(hdr.get("last_commit_hash", "")),
        data_hash=bytes.fromhex(hdr.get("data_hash", "")),
        validators_hash=bytes.fromhex(hdr["validators_hash"]),
        next_validators_hash=bytes.fromhex(hdr["next_validators_hash"]),
        consensus_hash=bytes.fromhex(hdr["consensus_hash"]),
        app_hash=bytes.fromhex(hdr["app_hash"]),
        last_results_hash=bytes.fromhex(hdr["last_results_hash"]),
        evidence_hash=bytes.fromhex(hdr["evidence_hash"]),
        proposer_address=bytes.fromhex(hdr["proposer_address"]),
        batch_hash=bytes.fromhex(hdr.get("batch_hash", "")),
        **(
            {
                "version_block": int(hdr["version"]["block"]),
                "version_app": int(hdr["version"]["app"]),
            }
            if "version" in hdr
            else {}
        ),
    )


def commit_from_json(cm: dict):
    """Parse a commit from its RPC JSON form (rpc/core._commit_json)."""
    from ..types.block import BlockIDFlag, Commit, CommitSig  # noqa: F401
    from ..types.block_id import BlockID
    from ..types.part_set import PartSetHeader

    return Commit(
        height=cm["height"],
        round=cm["round"],
        block_id=BlockID(
            hash=bytes.fromhex(cm["block_id"]["hash"]),
            part_set_header=PartSetHeader(
                cm["block_id"]["parts"]["total"],
                bytes.fromhex(cm["block_id"]["parts"]["hash"]),
            ),
        ),
        signatures=[
            CommitSig(
                block_id_flag=s["block_id_flag"],
                validator_address=bytes.fromhex(s["validator_address"]),
                timestamp_ns=s["timestamp"],
                signature=bytes.fromhex(s["signature"]),
                bls_signature=bytes.fromhex(s.get("bls_signature", "")),
                qc_signature=bytes.fromhex(s.get("qc_signature", "")),
            )
            for s in cm["signatures"]
        ],
    )


def qc_from_json(q: dict):
    """Parse a QuorumCertificate from its RPC JSON form
    (rpc/core._qc_json)."""
    from ..libs.bits import BitArray
    from ..types.block_id import BlockID
    from ..types.part_set import PartSetHeader
    from ..types.quorum_cert import QuorumCertificate

    return QuorumCertificate(
        height=q["height"],
        round=q["round"],
        block_id=BlockID(
            hash=bytes.fromhex(q["block_id"]["hash"]),
            part_set_header=PartSetHeader(
                q["block_id"]["parts"]["total"],
                bytes.fromhex(q["block_id"]["parts"]["hash"]),
            ),
        ),
        signers=BitArray.from_bytes(
            int(q["signers_size"]), bytes.fromhex(q["signers"])
        ),
        agg_signature=bytes.fromhex(q["agg_signature"]),
    )


def validators_from_json(rows: list):
    """Parse validator rows from their RPC JSON form into a
    ValidatorSet (rpc/core._validator_json)."""
    from ..types.validator import Validator, pubkey_from_type
    from ..types.validator_set import ValidatorSet

    return ValidatorSet(
        [
            Validator(
                pubkey_from_type(
                    val.get("pub_key_type", "ed25519"),
                    bytes.fromhex(val["pub_key"]),
                ),
                val["voting_power"],
                val.get("proposer_priority", 0),
                bls_pub_key=bytes.fromhex(val.get("bls_pub_key", "")),
            )
            for val in rows
        ]
    )


class RPCProvider:
    """light.Provider over a node's RPC (reference light/provider/http)."""

    def __init__(
        self,
        chain_id: str,
        addr: str,
        max_retries: int = DEFAULT_RETRIES,
        backoff_base_s: float = DEFAULT_BACKOFF_BASE_S,
        backoff_max_s: float = DEFAULT_BACKOFF_MAX_S,
        sleep=asyncio.sleep,
    ):
        self.chain_id = chain_id
        self.client = RPCClient(addr)
        self._addr = addr
        self.max_retries = max(1, int(max_retries))
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self._sleep = sleep
        # None = unknown; probed on first fetch, latched False against
        # pre-lightserve servers so every later fetch goes straight to
        # the commit+validators fallback
        self._has_light_block: Optional[bool] = None
        self.retries = 0  # transient retries performed (observability)

    def id(self) -> str:
        return self._addr

    async def _call_retry(self, method: str, **params):
        """One RPC call with bounded retry-with-backoff on TRANSIENT
        transport failures. Server-answered errors (RPCClientError:
        unknown method, no block at height) are not transient and
        surface immediately."""
        last: Optional[BaseException] = None
        for attempt in range(self.max_retries):
            if attempt:
                self.retries += 1
                await self._sleep(
                    min(
                        self.backoff_base_s * (2 ** (attempt - 1)),
                        self.backoff_max_s,
                    )
                )
            try:
                return await self.client.call(method, **params)
            except RPCClientError:
                raise
            except (
                ConnectionError,
                OSError,
                asyncio.TimeoutError,
                asyncio.IncompleteReadError,
            ) as e:
                last = e
                # no close() here: HTTPClient already tears down dead
                # connections under ITS lock, and the provider is shared
                # by concurrent witness fetches — an unlocked close from
                # one call's retry path would kill a sibling's in-flight
                # connection
        raise last if last is not None else ConnectionError("rpc failed")

    async def _fetch_validator_rows(self, height) -> list:
        """Every page of the validator set (the route caps a response at
        100 rows; pre-pagination servers return everything and report
        total == len, terminating after one page)."""
        rows: list = []
        page = 1
        max_pages = -(-_VALS_MAX // _VALS_PAGE)
        while True:
            v = await self._call_retry(
                "validators",
                height=height,
                page=page,
                per_page=_VALS_PAGE,
            )
            got = v.get("validators", [])
            rows.extend(got)
            total = min(int(v.get("total", len(rows))), _VALS_MAX)
            if len(rows) >= total or not got or page >= max_pages:
                return rows
            page += 1

    async def light_block(self, height: int):
        from ..light.types import LightBlock

        try:
            if self._has_light_block is not False:
                try:
                    res = await self._call_retry(
                        "light_block", height=height if height else None
                    )
                    self._has_light_block = True
                    lb = res["light_block"]
                    cm = lb["signed_header"].get("commit")
                    return LightBlock(
                        header_from_json(lb["signed_header"]["header"]),
                        commit_from_json(cm) if cm else None,
                        validators_from_json(
                            lb["validator_set"]["validators"]
                        ),
                        qc=(
                            qc_from_json(lb["qc"]) if lb.get("qc") else None
                        ),
                    )
                except RPCClientError as e:
                    if e.code == -32601:  # legacy node: no serving plane
                        self._has_light_block = False
                    else:
                        return None  # answered: no block at that height
            c = await self._call_retry(
                "commit", height=height if height else None
            )
            rows = await self._fetch_validator_rows(
                height if height else c["signed_header"]["header"]["height"]
            )
        except RPCClientError:
            return None  # server answered: nothing at that height
        except (ConnectionError, RuntimeError, OSError, EOFError):
            # transport dead after retries (EOFError covers
            # asyncio.IncompleteReadError: a server dying mid-response
            # body must report "no block", not leak the exception)
            return None
        return LightBlock(
            header_from_json(c["signed_header"]["header"]),
            commit_from_json(c["signed_header"]["commit"]),
            validators_from_json(rows),
        )
