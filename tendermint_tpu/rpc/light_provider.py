"""RPC-backed light-block provider.

Reference: light/provider/http (provider over rpc/client/http). Fetches
signed header + commit + validator set for a height from a node's RPC and
assembles a LightBlock. The JSON-RPC transport is rpc/client.HTTPClient
(one client implementation package-wide); `RPCClient` remains as its
historical alias here.
"""

from __future__ import annotations

from typing import Optional

from .client import HTTPClient as RPCClient  # noqa: F401 (re-export)


def header_from_json(hdr: dict):
    """Parse a header from its RPC JSON form (rpc/core._header_json) and
    return a types.Header whose .hash() is recomputed locally — callers
    verifying untrusted responses must never trust a supplied hash."""
    from ..types.block import Header
    from ..types.block_id import BlockID
    from ..types.part_set import PartSetHeader

    return Header(
        chain_id=hdr["chain_id"],
        height=hdr["height"],
        time_ns=hdr["time"],
        last_block_id=BlockID(
            hash=bytes.fromhex(hdr["last_block_id"]["hash"]),
            part_set_header=PartSetHeader(
                hdr["last_block_id"]["parts"]["total"],
                bytes.fromhex(hdr["last_block_id"]["parts"]["hash"]),
            ),
        ),
        last_commit_hash=bytes.fromhex(hdr.get("last_commit_hash", "")),
        data_hash=bytes.fromhex(hdr.get("data_hash", "")),
        validators_hash=bytes.fromhex(hdr["validators_hash"]),
        next_validators_hash=bytes.fromhex(hdr["next_validators_hash"]),
        consensus_hash=bytes.fromhex(hdr["consensus_hash"]),
        app_hash=bytes.fromhex(hdr["app_hash"]),
        last_results_hash=bytes.fromhex(hdr["last_results_hash"]),
        evidence_hash=bytes.fromhex(hdr["evidence_hash"]),
        proposer_address=bytes.fromhex(hdr["proposer_address"]),
        batch_hash=bytes.fromhex(hdr.get("batch_hash", "")),
        **(
            {
                "version_block": int(hdr["version"]["block"]),
                "version_app": int(hdr["version"]["app"]),
            }
            if "version" in hdr
            else {}
        ),
    )


class RPCProvider:
    """light.Provider over a node's RPC (reference light/provider/http)."""

    def __init__(self, chain_id: str, addr: str):
        self.chain_id = chain_id
        self.client = RPCClient(addr)
        self._addr = addr

    def id(self) -> str:
        return self._addr

    async def light_block(self, height: int):
        from ..light.types import LightBlock
        from ..types.block import Commit
        from ..types.block_id import BlockID
        from ..types.part_set import PartSetHeader
        from ..types.block import BlockIDFlag, CommitSig
        from ..types.validator import Validator, pubkey_from_type
        from ..types.validator_set import ValidatorSet

        try:
            c = await self.client.call(
                "commit", height=height if height else None
            )
            v = await self.client.call(
                "validators", height=height if height else None
            )
        except (ConnectionError, RuntimeError, OSError):
            return None
        hdr = c["signed_header"]["header"]
        cm = c["signed_header"]["commit"]
        header = header_from_json(hdr)
        commit = Commit(
            height=cm["height"],
            round=cm["round"],
            block_id=BlockID(
                hash=bytes.fromhex(cm["block_id"]["hash"]),
                part_set_header=PartSetHeader(
                    cm["block_id"]["parts"]["total"],
                    bytes.fromhex(cm["block_id"]["parts"]["hash"]),
                ),
            ),
            signatures=[
                CommitSig(
                    block_id_flag=s["block_id_flag"],
                    validator_address=bytes.fromhex(s["validator_address"]),
                    timestamp_ns=s["timestamp"],
                    signature=bytes.fromhex(s["signature"]),
                    bls_signature=bytes.fromhex(s.get("bls_signature", "")),
                )
                for s in cm["signatures"]
            ],
        )
        vals = ValidatorSet(
            [
                Validator(
                    pubkey_from_type(
                        val.get("pub_key_type", "ed25519"),
                        bytes.fromhex(val["pub_key"]),
                    ),
                    val["voting_power"],
                    val.get("proposer_priority", 0),
                )
                for val in v["validators"]
            ]
        )
        return LightBlock(header, commit, vals)
