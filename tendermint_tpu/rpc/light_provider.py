"""RPC-backed light-block provider + minimal JSON-RPC client.

Reference: light/provider/http (provider over rpc/client/http). Fetches
signed header + commit + validator set for a height from a node's RPC and
assembles a LightBlock.
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional


class RPCClient:
    """Minimal JSON-RPC over HTTP POST client (reference rpc/client/http)."""

    def __init__(self, addr: str):
        # addr: "host:port" or "tcp://host:port" or "http://host:port"
        s = addr
        for prefix in ("tcp://", "http://"):
            s = s.removeprefix(prefix)
        host, _, port = s.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self._id = 0

    async def call(self, method: str, **params):
        self._id += 1
        payload = json.dumps(
            {
                "jsonrpc": "2.0",
                "id": self._id,
                "method": method,
                "params": params,
            }
        ).encode()
        reader, writer = await asyncio.open_connection(self.host, self.port)
        try:
            writer.write(
                b"POST / HTTP/1.1\r\nHost: rpc\r\n"
                b"Content-Type: application/json\r\nContent-Length: "
                + str(len(payload)).encode()
                + b"\r\nConnection: close\r\n\r\n"
                + payload
            )
            await writer.drain()
            # parse response
            status = await reader.readline()
            if b"200" not in status:
                raise ConnectionError(f"rpc http error: {status!r}")
            n = 0
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                if line.lower().startswith(b"content-length:"):
                    n = int(line.split(b":", 1)[1])
            body = await reader.readexactly(n) if n else await reader.read()
            resp = json.loads(body)
            if resp.get("error"):
                raise RuntimeError(f"rpc error: {resp['error']}")
            return resp["result"]
        finally:
            writer.close()


class RPCProvider:
    """light.Provider over a node's RPC (reference light/provider/http)."""

    def __init__(self, chain_id: str, addr: str):
        self.chain_id = chain_id
        self.client = RPCClient(addr)
        self._addr = addr

    def id(self) -> str:
        return self._addr

    async def light_block(self, height: int):
        from ..light.types import LightBlock
        from ..types.block import Commit, Header
        from ..types.block_id import BlockID
        from ..types.part_set import PartSetHeader
        from ..types.block import BlockIDFlag, CommitSig
        from ..types.validator import Validator, pubkey_from_type
        from ..types.validator_set import ValidatorSet

        try:
            c = await self.client.call(
                "commit", height=height if height else None
            )
            v = await self.client.call(
                "validators", height=height if height else None
            )
        except (ConnectionError, RuntimeError, OSError):
            return None
        hdr = c["signed_header"]["header"]
        cm = c["signed_header"]["commit"]
        header = Header(
            chain_id=hdr["chain_id"],
            height=hdr["height"],
            time_ns=hdr["time"],
            last_block_id=BlockID(
                hash=bytes.fromhex(hdr["last_block_id"]["hash"]),
                part_set_header=PartSetHeader(
                    hdr["last_block_id"]["parts"]["total"],
                    bytes.fromhex(hdr["last_block_id"]["parts"]["hash"]),
                ),
            ),
            validators_hash=bytes.fromhex(hdr["validators_hash"]),
            next_validators_hash=bytes.fromhex(hdr["next_validators_hash"]),
            consensus_hash=bytes.fromhex(hdr["consensus_hash"]),
            app_hash=bytes.fromhex(hdr["app_hash"]),
            last_results_hash=bytes.fromhex(hdr["last_results_hash"]),
            evidence_hash=bytes.fromhex(hdr["evidence_hash"]),
            proposer_address=bytes.fromhex(hdr["proposer_address"]),
            batch_hash=bytes.fromhex(hdr.get("batch_hash", "")),
        )
        commit = Commit(
            height=cm["height"],
            round=cm["round"],
            block_id=BlockID(
                hash=bytes.fromhex(cm["block_id"]["hash"]),
                part_set_header=PartSetHeader(
                    cm["block_id"]["parts"]["total"],
                    bytes.fromhex(cm["block_id"]["parts"]["hash"]),
                ),
            ),
            signatures=[
                CommitSig(
                    block_id_flag=s["block_id_flag"],
                    validator_address=bytes.fromhex(s["validator_address"]),
                    timestamp_ns=s["timestamp"],
                    signature=bytes.fromhex(s["signature"]),
                    bls_signature=bytes.fromhex(s.get("bls_signature", "")),
                )
                for s in cm["signatures"]
            ],
        )
        vals = ValidatorSet(
            [
                Validator(
                    pubkey_from_type(
                        val.get("pub_key_type", "ed25519"),
                        bytes.fromhex(val["pub_key"]),
                    ),
                    val["voting_power"],
                    val.get("proposer_priority", 0),
                )
                for val in v["validators"]
            ]
        )
        return LightBlock(header, commit, vals)
