"""JSON-RPC 2.0 server: HTTP POST, GET-with-query, and websocket.

Reference: rpc/jsonrpc/server/ (http_json_handler, ws_handler :29) with
the core route table of rpc/core/routes.go:10-43 — minus the mempool
broadcast routes, which this fork deletes (no mempool; txs come from the
L2 node). Implemented on raw asyncio (no external HTTP dependency): a
minimal HTTP/1.1 parser, JSON-RPC dispatch, and RFC 6455 websocket
upgrade for event subscriptions.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import struct
import time
from typing import Any, Optional
from urllib.parse import parse_qs, urlparse

from ..libs.metrics import RPCMetrics, default_metrics
from ..libs.service import Service
from .core import RPCCore

_WS_MAGIC = b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


class RPCError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


class RPCServer(Service):
    def __init__(self, node, host: str = "127.0.0.1", port: int = 26657,
                 core=None):
        super().__init__("rpc", getattr(node, "logger", None))
        self.core = core if core is not None else RPCCore(node)
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._ws_tasks: set[asyncio.Task] = set()
        self._conns: set[asyncio.StreamWriter] = set()

    async def on_start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self.logger.info("rpc listening", addr=f"{self.host}:{self.port}")

    async def on_stop(self) -> None:
        if self._server:
            self._server.close()
        # keep-alive clients hold connections open indefinitely; close
        # them or wait_closed() (which awaits handler completion since
        # py3.12) never returns
        for w in list(self._conns):
            w.close()
        for t in list(self._ws_tasks):
            t.cancel()
        if self._server:
            await self._server.wait_closed()

    # --- http plumbing ------------------------------------------------------

    async def _handle_conn(self, reader, writer) -> None:
        self._conns.add(writer)
        try:
            while True:
                req_line = await reader.readline()
                if not req_line:
                    return
                try:
                    method, target, _version = (
                        req_line.decode().strip().split(" ", 2)
                    )
                except ValueError:
                    return
                headers = {}
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode().partition(":")
                    headers[k.strip().lower()] = v.strip()
                if headers.get("upgrade", "").lower() == "websocket":
                    await self._handle_ws(reader, writer, headers)
                    return
                body = b""
                try:
                    n = int(headers.get("content-length", 0))
                except ValueError:
                    break  # malformed header: drop the connection
                if n < 0 or n > (1 << 24):
                    break
                if n:
                    body = await reader.readexactly(n)
                resp = await self._dispatch_http(method, target, body)
                payload = json.dumps(resp).encode()
                writer.write(
                    b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                    b"Content-Length: " + str(len(payload)).encode()
                    + b"\r\nConnection: keep-alive\r\n\r\n" + payload
                )
                await writer.drain()
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            asyncio.CancelledError,
        ):
            pass
        finally:
            self._conns.discard(writer)
            writer.close()

    async def _dispatch_http(self, method: str, target: str, body: bytes):
        if method == "POST" and body:
            try:
                req = json.loads(body)
            except (json.JSONDecodeError, UnicodeDecodeError, ValueError):
                # invalid UTF-8 raises UnicodeDecodeError, not
                # JSONDecodeError (fuzz finding) — both are parse errors
                return _err(None, -32700, "parse error")
            if isinstance(req, list):
                return [await self._call_one(r) for r in req]
            return await self._call_one(req)
        # GET style: /method?param=value (reference uri handlers)
        try:
            u = urlparse(target)
            name = u.path.lstrip("/")
            params = {
                k: v[0] for k, v in parse_qs(u.query).items()
            }
        except (ValueError, UnicodeDecodeError):
            # urlparse raises on hostile targets ("Invalid IPv6 URL")
            return _err(None, -32700, "parse error")
        return await self._call_one(
            {"jsonrpc": "2.0", "id": -1, "method": name or "help",
             "params": params}
        )

    async def _call_one(self, req) -> dict:
        # hostile-input guards (fuzz target): a JSON body is not
        # necessarily an object, and method/params not necessarily the
        # right shapes — answer with JSON-RPC errors, never raise
        if not isinstance(req, dict):
            return _err(None, -32600, "invalid request: not an object")
        rid = req.get("id", -1)
        name = req.get("method", "")
        if not isinstance(name, str):
            return _err(rid, -32600, "invalid request: bad method")
        params = req.get("params") or {}
        if isinstance(params, list):
            params = {str(i): p for i, p in enumerate(params)}
        if not isinstance(params, dict):
            return _err(rid, -32602, "invalid params: not an object")
        fn = self.core.routes().get(name)
        metrics = default_metrics(RPCMetrics)
        if fn is None:
            # unknown methods share one label so a hostile client can't
            # explode the metric's cardinality
            metrics.request_errors.inc(method="_unknown")
            return _err(rid, -32601, f"method {name!r} not found")
        metrics.requests.inc(method=name)
        t0 = time.perf_counter()
        try:
            res = fn(**params)
            if asyncio.iscoroutine(res):
                res = await res
            return {"jsonrpc": "2.0", "id": rid, "result": res}
        except RPCError as e:
            metrics.request_errors.inc(method=name)
            return _err(rid, e.code, e.message)
        except TypeError as e:
            metrics.request_errors.inc(method=name)
            return _err(rid, -32602, f"invalid params: {e}")
        except Exception as e:
            metrics.request_errors.inc(method=name)
            return _err(rid, -32603, f"internal error: {e}")
        finally:
            metrics.request_duration.observe(
                time.perf_counter() - t0, method=name
            )

    # --- websocket (reference ws_handler :29) --------------------------------

    async def _handle_ws(self, reader, writer, headers) -> None:
        key = headers.get("sec-websocket-key", "")
        accept = base64.b64encode(
            hashlib.sha1(key.encode() + _WS_MAGIC).digest()
        ).decode()
        writer.write(
            b"HTTP/1.1 101 Switching Protocols\r\nUpgrade: websocket\r\n"
            b"Connection: Upgrade\r\nSec-WebSocket-Accept: "
            + accept.encode() + b"\r\n\r\n"
        )
        await writer.drain()
        subs: dict[str, Any] = {}
        send_lock = asyncio.Lock()

        async def send_json(obj) -> None:
            data = json.dumps(obj).encode()
            async with send_lock:
                writer.write(_ws_frame(data))
                await writer.drain()

        async def pump(query_str, sub):
            while True:
                msg = await sub.next()
                await send_json(
                    {
                        "jsonrpc": "2.0",
                        "id": f"{query_str}#event",
                        "result": {
                            "query": query_str,
                            "data": self.core.encode_event(msg),
                            "events": msg.events,
                        },
                    }
                )

        try:
            while True:
                data = await _ws_read(reader)
                if data is None:
                    break
                try:
                    req = json.loads(data)
                except (json.JSONDecodeError, UnicodeDecodeError,
                        ValueError):
                    continue
                if not isinstance(req, dict):
                    continue
                name = req.get("method", "")
                params = req.get("params") or {}
                rid = req.get("id", -1)
                # hostile-shape guards, mirroring _call_one: params must
                # be an object and the query a string, or the branches
                # below raise out of the connection task
                if not isinstance(params, dict):
                    await send_json(
                        _err(rid, -32602, "invalid params: not an object")
                    )
                    continue
                if name == "subscribe":
                    q = params.get("query", "")
                    if not isinstance(q, str):
                        await send_json(
                            _err(rid, -32602, "invalid query")
                        )
                        continue
                    try:
                        sub = self.core.subscribe_ws(id(writer), q)
                    except Exception as e:
                        await send_json(_err(rid, -32603, str(e)))
                        continue
                    t = asyncio.create_task(pump(q, sub))
                    self._ws_tasks.add(t)
                    subs[q] = (sub, t)
                    await send_json(
                        {"jsonrpc": "2.0", "id": rid, "result": {}}
                    )
                elif name == "unsubscribe":
                    q = params.get("query", "")
                    if not isinstance(q, str):
                        await send_json(
                            _err(rid, -32602, "invalid query")
                        )
                        continue
                    ent = subs.pop(q, None)
                    if ent:
                        ent[1].cancel()
                        self.core.unsubscribe_ws(id(writer), q)
                    await send_json(
                        {"jsonrpc": "2.0", "id": rid, "result": {}}
                    )
                else:
                    await send_json(await self._call_one(req))
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            for q, (sub, t) in subs.items():
                t.cancel()
                self.core.unsubscribe_ws(id(writer), q)
            writer.close()


def _err(rid, code, message) -> dict:
    return {
        "jsonrpc": "2.0",
        "id": rid,
        "error": {"code": code, "message": message},
    }


def _ws_frame(payload: bytes, opcode: int = 1) -> bytes:
    header = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        header += bytes([n])
    elif n < 1 << 16:
        header += bytes([126]) + struct.pack(">H", n)
    else:
        header += bytes([127]) + struct.pack(">Q", n)
    return header + payload


async def _ws_read(reader) -> Optional[bytes]:
    """One complete (possibly fragmented) text/binary message; None on
    close."""
    message = b""
    while True:
        try:
            h = await reader.readexactly(2)
        except asyncio.IncompleteReadError:
            return None
        fin = h[0] & 0x80
        opcode = h[0] & 0x0F
        masked = h[1] & 0x80
        n = h[1] & 0x7F
        if n == 126:
            n = struct.unpack(">H", await reader.readexactly(2))[0]
        elif n == 127:
            n = struct.unpack(">Q", await reader.readexactly(8))[0]
        mask = await reader.readexactly(4) if masked else b"\x00" * 4
        payload = await reader.readexactly(n)
        if masked:
            payload = bytes(
                b ^ mask[i % 4] for i, b in enumerate(payload)
            )
        if opcode == 8:  # close
            return None
        if opcode == 9:  # ping -> implicit pong not required for tests
            continue
        message += payload
        if fin:
            return message
