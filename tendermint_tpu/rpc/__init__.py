"""JSON-RPC API (reference rpc/)."""
