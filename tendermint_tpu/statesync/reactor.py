"""StateSync reactor — snapshot/chunk exchange over channels 0x60/0x61.

Reference: statesync/reactor.go. Serves snapshots from the local app
(ListSnapshots/LoadSnapshotChunk) to bootstrapping peers and feeds
discovered snapshots + received chunks into an active Syncer.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..abci.types import Snapshot
from ..libs import protoio as pio
from ..libs.log import Logger, nop_logger
from ..p2p.mconn import ChannelDescriptor
from ..p2p.switch import Reactor
from ..p2p.transport import Peer
from .chunks import Chunk
from .syncer import Syncer

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61
RECENT_SNAPSHOTS = 10  # reference reactor.go:24

_SNAPSHOTS_REQ = 1
_SNAPSHOTS_RESP = 2
_CHUNK_REQ = 3
_CHUNK_RESP = 4


def _enc(kind: int, **f) -> bytes:
    out = pio.field_varint(1, kind)
    for num, key in (
        (2, "height"),
        (3, "format"),
        (4, "chunks"),
        (7, "index"),
    ):
        if key in f:
            out += pio.field_varint(num, f[key])
    for num, key in ((5, "hash"), (6, "metadata"), (8, "chunk")):
        if key in f:
            out += pio.field_bytes(num, f[key])
    if f.get("missing"):
        out += pio.field_varint(9, 1)
    return out


def _dec(data: bytes) -> dict:
    out = {}
    names = {
        1: "kind", 2: "height", 3: "format", 4: "chunks",
        5: "hash", 6: "metadata", 7: "index", 8: "chunk", 9: "missing",
    }
    for num, _wt, val in pio.iter_fields(data):
        if num in names:
            out[names[num]] = val
    return out


class StateSyncReactor(Reactor):
    def __init__(
        self,
        app_snapshot_conn,
        syncer: Optional[Syncer] = None,
        logger: Optional[Logger] = None,
    ):
        super().__init__("StateSync")
        self._app = app_snapshot_conn
        self.syncer = syncer  # set while a sync is in progress
        self.logger = logger or nop_logger()

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(id=SNAPSHOT_CHANNEL, priority=5),
            ChannelDescriptor(id=CHUNK_CHANNEL, priority=3),
        ]

    async def add_peer(self, peer: Peer) -> None:
        # ask every new peer for its snapshots (reference reactor.go AddPeer)
        if self.syncer is not None:
            peer.try_send(SNAPSHOT_CHANNEL, _enc(_SNAPSHOTS_REQ))

    def request_chunk(self, peer, height: int, format: int, index: int) -> None:
        """The syncer's chunk-request hook."""
        peer.try_send(
            CHUNK_CHANNEL,
            _enc(_CHUNK_REQ, height=height, format=format, index=index),
        )

    async def receive(self, channel_id: int, peer: Peer, msg: bytes) -> None:
        try:
            f = _dec(msg)
            kind = f.get("kind", 0)
        except Exception as e:
            self.logger.error("bad statesync msg", err=str(e))
            await self.switch.stop_peer_for_error(peer, "bad statesync msg")
            return
        if channel_id == SNAPSHOT_CHANNEL:
            if kind == _SNAPSHOTS_REQ:
                await self._serve_snapshots(peer)
            elif kind == _SNAPSHOTS_RESP and self.syncer is not None:
                snap = Snapshot(
                    height=f.get("height", 0),
                    format=f.get("format", 0),
                    chunks=f.get("chunks", 0),
                    hash=f.get("hash", b""),
                    metadata=f.get("metadata", b""),
                )
                self.syncer.add_snapshot(peer, snap)
        elif channel_id == CHUNK_CHANNEL:
            if kind == _CHUNK_REQ:
                await self._serve_chunk(peer, f)
            elif kind == _CHUNK_RESP and self.syncer is not None:
                if not f.get("missing"):
                    self.syncer.add_chunk(
                        Chunk(
                            height=f.get("height", 0),
                            format=f.get("format", 0),
                            index=f.get("index", 0),
                            chunk=f.get("chunk", b""),
                            sender=peer.id,
                        )
                    )

    async def _serve_snapshots(self, peer: Peer) -> None:
        """ListSnapshots from the app, newest first (reference :150-180)."""
        res = self._app.list_snapshots()
        if asyncio.iscoroutine(res):
            res = await res
        snaps = sorted(res, key=lambda s: s.height, reverse=True)
        for s in snaps[:RECENT_SNAPSHOTS]:
            peer.try_send(
                SNAPSHOT_CHANNEL,
                _enc(
                    _SNAPSHOTS_RESP,
                    height=s.height,
                    format=s.format,
                    chunks=s.chunks,
                    hash=s.hash,
                    metadata=s.metadata,
                ),
            )

    async def _serve_chunk(self, peer: Peer, f: dict) -> None:
        res = self._app.load_snapshot_chunk(
            f.get("height", 0), f.get("format", 0), f.get("index", 0)
        )
        if asyncio.iscoroutine(res):
            res = await res
        if res is None:
            peer.try_send(
                CHUNK_CHANNEL,
                _enc(
                    _CHUNK_RESP,
                    height=f.get("height", 0),
                    format=f.get("format", 0),
                    index=f.get("index", 0),
                    missing=True,
                ),
            )
            return
        peer.try_send(
            CHUNK_CHANNEL,
            _enc(
                _CHUNK_RESP,
                height=f.get("height", 0),
                format=f.get("format", 0),
                index=f.get("index", 0),
                chunk=res,
            ),
        )
