"""Snapshot syncer — restore app state from peer-served snapshots.

Reference: statesync/syncer.go. `sync_any` (:141) picks the best
discovered snapshot; `sync` (:237): verify the app hash via the state
provider → OfferSnapshot to the app (:318) → fetch chunks from peers in
parallel (:411) while applying them in order (:354) → verify the restored
app hash → hand back (state, commit) for node bootstrap.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..abci.types import Snapshot
from ..libs.log import Logger, nop_logger
from .chunks import Chunk, ChunkQueue
from .stateprovider import StateProvider


class SyncError(Exception):
    pass


class ErrNoSnapshots(SyncError):
    pass


class ErrAbort(SyncError):
    pass


class _RetrySnapshot(SyncError):
    pass


class _RejectSnapshot(SyncError):
    pass


class _RejectFormat(SyncError):
    pass


class _RejectSender(SyncError):
    pass


CHUNK_FETCH_TIMEOUT = 10.0
CHUNK_REQUEST_FANOUT = 4
# a peer whose chunks keep failing is dropped from the snapshot's pool
# after this many strikes — the statesync mirror of the blocksync
# request-timeout ban (blocksync/pool.py _timeout_peer)
CHUNK_PEER_MAX_FAILURES = 3
# how many CHUNK_FETCH_TIMEOUT expiries to ride through (rotating the
# stalled chunk to another peer each time) before giving up on the
# snapshot — one silent peer must not sink an otherwise healthy pool
CHUNK_FETCH_MAX_TIMEOUTS = 4


@dataclass
class _DiscoveredSnapshot:
    snapshot: Snapshot
    peers: list = field(default_factory=list)  # peers advertising it
    trusted_app_hash: bytes = b""

    def key(self):
        s = self.snapshot
        return (s.height, s.format, s.hash)


class Syncer:
    def __init__(
        self,
        app_snapshot_conn,  # abci client (snapshot connection)
        state_provider: StateProvider,
        request_chunk: Callable,  # (peer, height, format, index) -> None
        logger: Optional[Logger] = None,
    ):
        self._app = app_snapshot_conn
        self._provider = state_provider
        self._request_chunk = request_chunk
        self.logger = logger or nop_logger()
        self._snapshots: dict = {}  # key -> _DiscoveredSnapshot
        self._rejected: set = set()
        self._rejected_formats: set = set()
        self._rejected_peers: set = set()
        self._chunks: Optional[ChunkQueue] = None
        self._current: Optional[_DiscoveredSnapshot] = None
        self._new_snapshot = asyncio.Event()

    # --- discovery (reference snapshots.go pool) ----------------------------

    def add_snapshot(self, peer, snapshot: Snapshot) -> bool:
        if peer.id in self._rejected_peers:
            return False
        if snapshot.format in self._rejected_formats:
            return False
        key = (snapshot.height, snapshot.format, snapshot.hash)
        if key in self._rejected:
            return False
        d = self._snapshots.get(key)
        if d is None:
            d = _DiscoveredSnapshot(snapshot)
            self._snapshots[key] = d
            self._new_snapshot.set()
        if peer not in d.peers:
            d.peers.append(peer)
        return True

    def add_chunk(self, chunk: Chunk) -> bool:
        if self._chunks is None or self._current is None:
            return False
        s = self._current.snapshot
        if (chunk.height, chunk.format) != (s.height, s.format):
            return False
        return self._chunks.add(chunk)

    def _best(self) -> Optional[_DiscoveredSnapshot]:
        """Highest height, then most peers (reference snapshots.go Best)."""
        candidates = [
            d
            for k, d in self._snapshots.items()
            if k not in self._rejected
            and d.snapshot.format not in self._rejected_formats
        ]
        if not candidates:
            return None
        return max(
            candidates, key=lambda d: (d.snapshot.height, len(d.peers))
        )

    # --- main loop (reference SyncAny :141) ----------------------------------

    async def sync_any(self, discovery_time: float = 2.0):
        """Returns (state, commit). Raises ErrNoSnapshots/ErrAbort."""
        snapshot: Optional[_DiscoveredSnapshot] = None
        chunks: Optional[ChunkQueue] = None
        while True:
            if snapshot is None:
                snapshot = self._best()
                chunks = None
            if snapshot is None:
                if discovery_time <= 0:
                    raise ErrNoSnapshots()
                self.logger.info("discovering snapshots", t=discovery_time)
                try:
                    await asyncio.wait_for(
                        self._new_snapshot.wait(), discovery_time
                    )
                except asyncio.TimeoutError:
                    pass
                self._new_snapshot.clear()
                continue
            if chunks is None:
                chunks = ChunkQueue(snapshot.snapshot.chunks)
            try:
                return await self.sync(snapshot, chunks)
            except _RetrySnapshot:
                self.logger.info("retrying snapshot")
                continue
            except (_RejectSnapshot, asyncio.TimeoutError):
                self._rejected.add(snapshot.key())
            except _RejectFormat:
                self._rejected_formats.add(snapshot.snapshot.format)
            except _RejectSender:
                for p in snapshot.peers:
                    self._rejected_peers.add(p.id)
                    if self._chunks:
                        self._chunks.discard_sender(p.id)
                self._rejected.add(snapshot.key())
            chunks.close()
            snapshot = None
            chunks = None

    # --- one snapshot (reference Sync :237) -----------------------------------

    async def sync(self, d: _DiscoveredSnapshot, chunks: ChunkQueue):
        self._current = d
        self._chunks = chunks
        chunks.metrics.syncing.set(1)
        chunks.metrics.snapshot_height.set(d.snapshot.height)
        try:
            # trusted app hash from the light-client state provider
            d.trusted_app_hash = await self._provider.app_hash(
                d.snapshot.height
            )
            await self._offer_snapshot(d)
            fetcher = asyncio.create_task(self._fetch_chunks(d, chunks))
            try:
                await self._apply_chunks(d, chunks)
            finally:
                fetcher.cancel()
                try:
                    await fetcher
                except (asyncio.CancelledError, Exception):
                    pass
            # verify the restored app against the trusted hash
            info = await self._app_info()
            if info.last_block_app_hash != d.trusted_app_hash:
                raise _RejectSnapshot(
                    "restored app hash does not match trusted hash"
                )
            if info.last_block_height != d.snapshot.height:
                raise _RejectSnapshot("restored app at wrong height")
            state = await self._provider.state(d.snapshot.height)
            commit = await self._provider.commit(d.snapshot.height)
            self.logger.info(
                "snapshot restored", height=d.snapshot.height
            )
            return state, commit
        finally:
            chunks.metrics.syncing.set(0)
            self._current = None
            self._chunks = None

    async def _app_info(self):
        res = self._app.info()
        if asyncio.iscoroutine(res):
            res = await res
        return res

    async def _offer_snapshot(self, d: _DiscoveredSnapshot) -> None:
        res = self._app.offer_snapshot(d.snapshot, d.trusted_app_hash)
        if asyncio.iscoroutine(res):
            res = await res
        result = res.result
        if result == "ACCEPT":
            return
        if result == "ABORT":
            raise ErrAbort()
        if result == "REJECT":
            raise _RejectSnapshot()
        if result == "REJECT_FORMAT":
            raise _RejectFormat()
        if result == "REJECT_SENDER":
            raise _RejectSender()
        raise SyncError(f"unknown offer result {result}")

    async def _fetch_chunks(
        self, d: _DiscoveredSnapshot, chunks: ChunkQueue
    ) -> None:
        """Request chunk allocations from peers round-robin (:411),
        rotating a retried chunk away from the peer whose copy failed."""
        next_peer = 0
        failures: dict[str, int] = {}
        while not chunks.complete:
            index = chunks.allocate()
            if index is None:
                await asyncio.sleep(0.05)
                continue
            avoid = chunks.last_sender(index)
            if avoid:
                # one strike per failed fetch, charged to the peer whose
                # copy failed (NOT the chunk's cumulative retry count —
                # that would charge every earlier peer's failure to
                # whichever peer failed last)
                failures[avoid] = failures.get(avoid, 0) + 1
                if failures[avoid] >= CHUNK_PEER_MAX_FAILURES and len(
                    d.peers
                ) > 1:
                    d.peers = [p for p in d.peers if p.id != avoid]
                    self.logger.info(
                        "dropping failing statesync peer", peer=avoid
                    )
            candidates = [
                p for p in d.peers if p.id not in self._rejected_peers
            ] or d.peers
            pool = [p for p in candidates if p.id != avoid] or candidates
            peer = pool[next_peer % len(pool)]
            next_peer += 1
            chunks.note_request(index, peer.id)
            self._request_chunk(
                peer, d.snapshot.height, d.snapshot.format, index
            )
            await asyncio.sleep(0)

    async def _apply_chunks(
        self, d: _DiscoveredSnapshot, chunks: ChunkQueue
    ) -> None:
        """Apply in order, honoring the app's retry/reject verdicts (:354)."""
        applied = 0
        timeouts = 0
        while applied < chunks.num_chunks:
            chunk = chunks.get(applied)
            if chunk is None:
                if not await chunks.wait_for_chunk(CHUNK_FETCH_TIMEOUT):
                    # the peer holding the next needed chunk went silent:
                    # put the chunk back for refetch (charged to the peer
                    # note_request recorded) so the fetcher rotates to
                    # another peer, instead of one dead peer sinking the
                    # whole snapshot
                    timeouts += 1
                    if timeouts > CHUNK_FETCH_MAX_TIMEOUTS:
                        raise asyncio.TimeoutError("chunk fetch timed out")
                    self.logger.info(
                        "chunk fetch timed out; rotating", chunk=applied
                    )
                    chunks.retry(applied)
                continue
            res = self._app.apply_snapshot_chunk(
                chunk.index, chunk.chunk, chunk.sender
            )
            if asyncio.iscoroutine(res):
                res = await res
            for idx in res.refetch_chunks:
                chunks.retry(idx)
            for sender in res.reject_senders:
                if sender:
                    self._rejected_peers.add(sender)
                    for idx in chunks.discard_sender(sender):
                        chunks.retry(idx, sender)
            result = res.result
            if result == "ACCEPT":
                applied += 1
            elif result == "ABORT":
                raise ErrAbort()
            elif result == "RETRY":
                chunks.retry(chunk.index, chunk.sender)
            elif result == "RETRY_SNAPSHOT":
                raise _RetrySnapshot()
            elif result == "REJECT_SNAPSHOT":
                raise _RejectSnapshot()
            else:
                raise SyncError(f"unknown apply result {result}")
