"""State provider — trusted state for a snapshot height.

Reference: statesync/stateprovider.go:39-205. The light client verifies
headers at H, H+1, H+2 and the provider assembles the consensus State the
node resumes from (validators at H+1, next validators from H+2, app hash
from H+1 — the snapshot height mapping at :150-175).
"""

from __future__ import annotations

from typing import Optional, Protocol, runtime_checkable

import asyncio

from ..light.client import ErrNoProviderBlock, LightClient
from ..state.state import State
from ..types.block import Commit
from ..types.params import ConsensusParams


@runtime_checkable
class StateProvider(Protocol):
    async def app_hash(self, height: int) -> bytes: ...

    async def commit(self, height: int) -> Commit: ...

    async def state(self, height: int) -> State: ...


class LightClientStateProvider:
    def __init__(
        self,
        light_client: LightClient,
        initial_height: int = 1,
        consensus_params: Optional[ConsensusParams] = None,
    ):
        self._lc = light_client
        self._initial_height = initial_height
        # the reference fetches consensus params over RPC from the primary
        # (:185-200); here they are supplied by the caller (genesis doc or
        # RPC-backed provider)
        self._params = consensus_params or ConsensusParams()

    async def _verify_retry(self, height: int):
        """verify_light_block_at_height with a bounded wait for heights
        the chain hasn't produced YET: verifying the freshest snapshot at
        H needs headers H+1 and H+2, which land one block interval later.
        The reference's light provider blocks until the primary has the
        height (light/provider/http retry loop); here: up to ~20 s.
        Genuine verification failures re-raise immediately."""
        delay = 0.5
        for _ in range(10):
            try:
                return await self._lc.verify_light_block_at_height(height)
            except ErrNoProviderBlock:
                await asyncio.sleep(delay)
                delay = min(delay * 1.5, 4.0)
        return await self._lc.verify_light_block_at_height(height)

    async def app_hash(self, height: int) -> bytes:
        """App hash FOR height lives in the header at height+1 (:100-120).
        Also pre-verifies height+2, needed by state() later."""
        header = await self._verify_retry(height + 1)
        await self._verify_retry(height + 2)
        return header.header.app_hash

    async def commit(self, height: int) -> Commit:
        lb = await self._verify_retry(height)
        return lb.commit

    async def state(self, height: int) -> State:
        """Assemble State for resuming after the snapshot (:135-205)."""
        last = await self._verify_retry(height)
        current = await self._verify_retry(height + 1)
        nxt = await self._verify_retry(height + 2)
        return State(
            chain_id=self._lc.chain_id,
            initial_height=self._initial_height,
            last_block_height=last.height,
            last_block_time_ns=last.header.time_ns,
            last_block_id=last.commit.block_id,
            app_hash=current.header.app_hash,
            last_results_hash=current.header.last_results_hash,
            last_validators=last.validators,
            validators=current.validators,
            next_validators=nxt.validators,
            last_height_validators_changed=nxt.height,
            consensus_params=self._params,
            last_height_consensus_params_changed=current.height,
        )
