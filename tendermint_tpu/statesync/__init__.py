"""Statesync — snapshot-based node bootstrap.

Reference: statesync/ (syncer.go, reactor.go, chunks.go,
stateprovider.go). A fresh node discovers snapshots from peers over
channel 0x60, offers them to the local app, fetches chunks over 0x61,
and builds its consensus state from light-client-verified headers.
"""

from .chunks import ChunkQueue
from .reactor import CHUNK_CHANNEL, SNAPSHOT_CHANNEL, StateSyncReactor
from .stateprovider import LightClientStateProvider, StateProvider
from .syncer import Syncer

__all__ = [
    "ChunkQueue",
    "StateSyncReactor",
    "SNAPSHOT_CHANNEL",
    "CHUNK_CHANNEL",
    "LightClientStateProvider",
    "StateProvider",
    "Syncer",
]
