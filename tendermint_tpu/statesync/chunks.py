"""Chunk queue for an in-flight snapshot restore.

Reference: statesync/chunks.go — the reference spools chunks to a temp
dir; chunks here are small enough to keep in memory (the app re-chunks
however it likes). Tracks allocation (which chunk is being fetched from
which peer), arrival, and retry/refetch.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Optional


@dataclass
class Chunk:
    height: int
    format: int
    index: int
    chunk: bytes
    sender: str = ""


class ChunkQueue:
    def __init__(self, num_chunks: int):
        self.num_chunks = num_chunks
        self._chunks: dict[int, Chunk] = {}
        self._allocated: dict[int, str] = {}  # index -> peer fetching it
        self._event = asyncio.Event()
        self._closed = False

    def allocate(self) -> Optional[int]:
        """Next chunk index to fetch, or None if all allocated/done."""
        for i in range(self.num_chunks):
            if i not in self._chunks and i not in self._allocated:
                self._allocated[i] = ""
                return i
        return None

    def add(self, chunk: Chunk) -> bool:
        """Returns False for duplicates/out-of-range."""
        if self._closed:
            return False
        if not (0 <= chunk.index < self.num_chunks):
            return False
        if chunk.index in self._chunks:
            return False
        self._chunks[chunk.index] = chunk
        self._allocated.pop(chunk.index, None)
        self._event.set()
        return True

    def get(self, index: int) -> Optional[Chunk]:
        return self._chunks.get(index)

    def retry(self, index: int) -> None:
        """Put a chunk back for refetching (app asked for a refetch)."""
        self._chunks.pop(index, None)
        self._allocated.pop(index, None)

    def discard_sender(self, peer_id: str) -> list[int]:
        """Drop all chunks from a rejected sender; returns their indexes."""
        dropped = []
        for i, c in list(self._chunks.items()):
            if c.sender == peer_id:
                del self._chunks[i]
                dropped.append(i)
        return dropped

    @property
    def complete(self) -> bool:
        return len(self._chunks) == self.num_chunks

    async def wait_for_chunk(self, timeout: float = 10.0) -> bool:
        """Wait until some chunk arrives (or timeout); clears the event."""
        try:
            await asyncio.wait_for(self._event.wait(), timeout)
            self._event.clear()
            return True
        except asyncio.TimeoutError:
            return False

    def close(self) -> None:
        self._closed = True
