"""Chunk queue for an in-flight snapshot restore.

Reference: statesync/chunks.go — the reference spools chunks to a temp
dir; chunks here are small enough to keep in memory (the app re-chunks
however it likes). Tracks allocation (which chunk is being fetched from
which peer), arrival, and retry/refetch.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Optional

from ..libs.metrics import StateSyncMetrics, default_metrics
from ..obs import default_tracer

# per-chunk refetch backoff: the seed refetched immediately from the same
# pool, hammering a bad peer in a tight loop; failed chunks now wait
# BASE·2ⁿ (capped) before they are allocatable again, mirroring the
# blocksync peer-ban escalation (blocksync/pool.py _timeout_peer)
RETRY_BACKOFF_BASE = 0.1
RETRY_BACKOFF_CAP = 5.0


@dataclass
class Chunk:
    height: int
    format: int
    index: int
    chunk: bytes
    sender: str = ""


class ChunkQueue:
    def __init__(self, num_chunks: int, now=time.monotonic):
        self.num_chunks = num_chunks
        self._now = now
        self._chunks: dict[int, Chunk] = {}
        self._allocated: dict[int, str] = {}  # index -> peer fetching it
        self._retries: dict[int, int] = {}  # index -> failed attempts
        self._retry_at: dict[int, float] = {}  # index -> earliest refetch
        self._last_sender: dict[int, str] = {}  # index -> last failing peer
        self._requested_at: dict[int, float] = {}  # index -> request time
        self.metrics = default_metrics(StateSyncMetrics)
        self._event = asyncio.Event()
        self._closed = False

    def allocate(self) -> Optional[int]:
        """Next chunk index to fetch, or None if all allocated/done/
        backing off."""
        now = self._now()
        for i in range(self.num_chunks):
            if i in self._chunks or i in self._allocated:
                continue
            if self._retry_at.get(i, 0.0) > now:
                continue
            self._allocated[i] = ""
            return i
        return None

    def note_request(self, index: int, peer_id: str) -> None:
        """Record which peer was asked for an allocated chunk, so a
        timeout-driven retry can rotate away from it."""
        if index in self._allocated:
            self._allocated[index] = peer_id
            self._requested_at[index] = self._now()

    def add(self, chunk: Chunk) -> bool:
        """Returns False for duplicates/out-of-range."""
        if self._closed:
            return False
        if not (0 <= chunk.index < self.num_chunks):
            return False
        if chunk.index in self._chunks:
            return False
        self._chunks[chunk.index] = chunk
        self._allocated.pop(chunk.index, None)
        self.metrics.chunks_fetched.inc()
        req_t = self._requested_at.pop(chunk.index, 0.0)
        if req_t:
            latency = self._now() - req_t
            self.metrics.chunk_response_seconds.observe(latency)
            default_tracer().event(
                "statesync.chunk_received",
                index=chunk.index,
                peer=chunk.sender[:12],
                latency_ms=round(latency * 1e3, 2),
            )
        self._event.set()
        return True

    def get(self, index: int) -> Optional[Chunk]:
        return self._chunks.get(index)

    def retry(self, index: int, sender: str = "") -> None:
        """Put a chunk back for refetching (app asked for a refetch,
        or the fetch timed out) with exponential backoff. `sender` is
        the peer the failed copy came from; the fetcher rotates away
        from it on the refetch."""
        failing = sender or (
            self._chunks[index].sender
            if index in self._chunks
            else self._allocated.get(index, "")
        )
        self._chunks.pop(index, None)
        self._allocated.pop(index, None)
        self._requested_at.pop(index, None)
        self.metrics.chunk_retries.inc()
        n = self._retries.get(index, 0)
        self._retries[index] = n + 1
        self._retry_at[index] = self._now() + min(
            RETRY_BACKOFF_CAP, RETRY_BACKOFF_BASE * (2**n)
        )
        if failing:
            self._last_sender[index] = failing

    def retries(self, index: int) -> int:
        return self._retries.get(index, 0)

    def last_sender(self, index: int) -> str:
        """The peer whose copy of this chunk last failed ("" if none)."""
        return self._last_sender.get(index, "")

    def discard_sender(self, peer_id: str) -> list[int]:
        """Drop all chunks from a rejected sender; returns their indexes."""
        dropped = []
        for i, c in list(self._chunks.items()):
            if c.sender == peer_id:
                del self._chunks[i]
                dropped.append(i)
        return dropped

    @property
    def complete(self) -> bool:
        return len(self._chunks) == self.num_chunks

    async def wait_for_chunk(self, timeout: float = 10.0) -> bool:
        """Wait until some chunk arrives (or timeout); clears the event."""
        try:
            await asyncio.wait_for(self._event.wait(), timeout)
            self._event.clear()
            return True
        except asyncio.TimeoutError:
            return False

    def close(self) -> None:
        self._closed = True
