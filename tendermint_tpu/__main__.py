"""CLI — `python -m tendermint_tpu <command>`.

Reference: cmd/tendermint/main.go:16-48 (cobra command tree): init, start,
testnet, rollback, reset, gen-validator, gen-node-key, show-node-id,
show-validator, version.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys

from .config import Config
from .version import (
    BLOCK_PROTOCOL_VERSION,
    P2P_PROTOCOL_VERSION,
    TMCORE_SEM_VER,
)


def _load_config(args) -> Config:
    cfg = Config.load(args.home)
    cfg.root_dir = args.home
    return cfg


def cmd_init(args) -> int:
    from .node import init_files

    cfg = _load_config(args)
    if args.chain_id:
        cfg.base.chain_id = args.chain_id
    init_files(cfg)
    cfg.save()
    print(f"initialized node in {args.home}")
    return 0


def cmd_start(args) -> int:
    from .node import Node

    cfg = _load_config(args)
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    if args.p2p_laddr:
        cfg.p2p.laddr = args.p2p_laddr
    if args.persistent_peers:
        cfg.p2p.persistent_peers = args.persistent_peers
    if args.switch_height:
        cfg.consensus.switch_height = args.switch_height
    node = Node(cfg)

    async def run():
        await node.start()
        try:
            await asyncio.Event().wait()  # run until interrupted
        except asyncio.CancelledError:
            pass
        finally:
            await node.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        print("\nshutting down")
    return 0


def cmd_testnet(args) -> int:
    """Generate a local N-validator testnet layout
    (reference cmd/tendermint/commands/testnet.go)."""
    import time

    from .p2p.key import NodeKey
    from .privval.file_pv import FilePV
    from .types.genesis import GenesisDoc, GenesisValidator

    n = args.v
    base = args.output
    os.makedirs(base, exist_ok=True)
    nodes = []
    for i in range(n):
        home = os.path.join(base, f"node{i}")
        cfg = Config()
        cfg.root_dir = home
        cfg.ensure_dirs()
        nk = NodeKey.load_or_generate(cfg.node_key_file)
        pv = FilePV.load_or_generate(
            cfg.priv_validator_key_file, cfg.priv_validator_state_file
        )
        nodes.append((home, cfg, nk, pv))
    doc = GenesisDoc(
        chain_id=args.chain_id or "testnet-%06x" % (int(time.time()) & 0xFFFFFF),
        genesis_time_ns=time.time_ns(),
        validators=[
            GenesisValidator("ed25519", pv.get_pub_key().data, 10)
            for _, _, _, pv in nodes
        ],
    )
    doc.validate_and_complete()
    peers = ",".join(
        f"{nk.id}@127.0.0.1:{26656 + 10 * i}"
        for i, (_, _, nk, _) in enumerate(nodes)
    )
    for i, (home, cfg, nk, pv) in enumerate(nodes):
        doc.save_as(cfg.genesis_file)
        cfg.p2p.laddr = f"tcp://127.0.0.1:{26656 + 10 * i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{26657 + 10 * i}"
        cfg.p2p.persistent_peers = peers
        cfg.save()
    print(f"wrote {n}-node testnet to {base} (chain {doc.chain_id})")
    return 0


def cmd_rollback(args) -> int:
    """Roll back one height of state (reference rollback.go)."""
    cfg = _load_config(args)
    from .store.kv import SqliteKV
    from .state.store import StateStore
    from .store.block_store import BlockStore

    ss = StateStore(SqliteKV(os.path.join(cfg.db_dir, "state.db")))
    bs = BlockStore(SqliteKV(os.path.join(cfg.db_dir, "blockstore.db")))
    state = ss.rollback(bs)
    if args.hard:
        bs.prune_blocks_since(state.last_block_height + 1)
    print(
        f"rolled back to height {state.last_block_height} "
        f"(app hash {state.app_hash.hex()})"
    )
    return 0


def cmd_reset(args) -> int:
    """unsafe-reset-all: wipe data, keep config (reference reset.go)."""
    cfg = _load_config(args)
    data = cfg.db_dir
    if os.path.isdir(data):
        shutil.rmtree(data)
    os.makedirs(data, exist_ok=True)
    # reset privval state (keep the key)
    st = cfg.priv_validator_state_file
    if os.path.exists(st):
        os.remove(st)
    print(f"reset {data}")
    return 0


def cmd_gen_validator(args) -> int:
    from .crypto import ed25519

    k = ed25519.PrivKey.generate()
    print(
        json.dumps(
            {
                "pub_key": k.public_key().data.hex(),
                "priv_key_seed": k.seed.hex(),
                "address": k.public_key().address().hex(),
            },
            indent=2,
        )
    )
    return 0


def cmd_gen_node_key(args) -> int:
    from .p2p.key import NodeKey

    nk = NodeKey.generate()
    print(json.dumps({"id": nk.id}, indent=2))
    return 0


def cmd_show_node_id(args) -> int:
    from .p2p.key import NodeKey

    cfg = _load_config(args)
    nk = NodeKey.load_or_generate(cfg.node_key_file)
    print(nk.id)
    return 0


def cmd_show_validator(args) -> int:
    from .privval.file_pv import FilePV

    cfg = _load_config(args)
    pv = FilePV.load_or_generate(
        cfg.priv_validator_key_file, cfg.priv_validator_state_file
    )
    pub = pv.get_pub_key()
    print(
        json.dumps(
            {"pub_key": pub.data.hex(), "address": pub.address().hex()}
        )
    )
    return 0


def cmd_version(args) -> int:
    print(
        f"tendermint-tpu {TMCORE_SEM_VER} "
        f"(block protocol {BLOCK_PROTOCOL_VERSION}, "
        f"p2p protocol {P2P_PROTOCOL_VERSION})"
    )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tendermint_tpu",
        description="TPU-native tendermint (morph fork capabilities)",
    )
    p.add_argument(
        "--home", default=os.environ.get("TMHOME", os.path.expanduser("~/.tendermint_tpu")),
        help="node home directory",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("init", help="initialize config/genesis/keys")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run the node")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr", default="")
    sp.add_argument("--p2p.laddr", dest="p2p_laddr", default="")
    sp.add_argument(
        "--p2p.persistent_peers", dest="persistent_peers", default=""
    )
    sp.add_argument(
        "--consensus.switchHeight",
        dest="switch_height",
        type=int,
        default=0,
        help="sequencer-mode upgrade height (reference upgrade/upgrade.go)",
    )
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("testnet", help="generate a local testnet")
    sp.add_argument("--v", type=int, default=4)
    sp.add_argument("--output", default="./mytestnet")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_testnet)

    sp = sub.add_parser("rollback", help="roll back one height")
    sp.add_argument("--hard", action="store_true")
    sp.set_defaults(fn=cmd_rollback)

    sp = sub.add_parser("unsafe-reset-all", help="wipe chain data")
    sp.set_defaults(fn=cmd_reset)

    sp = sub.add_parser("gen-validator", help="generate a validator key")
    sp.set_defaults(fn=cmd_gen_validator)

    sp = sub.add_parser("gen-node-key", help="generate a node key")
    sp.set_defaults(fn=cmd_gen_node_key)

    sp = sub.add_parser("show-node-id", help="print this node's p2p id")
    sp.set_defaults(fn=cmd_show_node_id)

    sp = sub.add_parser("show-validator", help="print this node's validator")
    sp.set_defaults(fn=cmd_show_validator)

    sp = sub.add_parser("version", help="print version")
    sp.set_defaults(fn=cmd_version)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
