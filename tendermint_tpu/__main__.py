"""CLI — `python -m tendermint_tpu <command>`.

Reference: cmd/tendermint/main.go:16-48 (cobra command tree): init, start,
testnet, rollback, reset, gen-validator, gen-node-key, show-node-id,
show-validator, version.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys

from .config import Config
from .libs import cli as libs_cli
from .version import (
    BLOCK_PROTOCOL_VERSION,
    P2P_PROTOCOL_VERSION,
    TMCORE_SEM_VER,
)


def _load_config(args) -> Config:
    cfg = Config.load(args.home)
    cfg.root_dir = args.home
    return cfg


def cmd_init(args) -> int:
    from .node import init_files

    cfg = _load_config(args)
    if args.chain_id:
        cfg.base.chain_id = args.chain_id
    init_files(cfg)
    cfg.save()
    print(f"initialized node in {args.home}")
    return 0


def cmd_start(args) -> int:
    from .node import Node

    cfg = _load_config(args)
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    if args.p2p_laddr:
        cfg.p2p.laddr = args.p2p_laddr
    if args.persistent_peers:
        cfg.p2p.persistent_peers = args.persistent_peers
    if args.switch_height:
        cfg.consensus.switch_height = args.switch_height
    node = Node(cfg)

    async def run():
        await node.start()
        try:
            await asyncio.Event().wait()  # run until interrupted
        except asyncio.CancelledError:
            pass
        finally:
            await node.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        print("\nshutting down")
    return 0


def cmd_testnet(args) -> int:
    """Generate a local N-validator testnet layout
    (reference cmd/tendermint/commands/testnet.go)."""
    import time

    from .p2p.key import NodeKey
    from .privval.file_pv import FilePV
    from .types.genesis import GenesisDoc, GenesisValidator

    n = args.v
    base = args.output
    os.makedirs(base, exist_ok=True)
    nodes = []
    for i in range(n):
        home = os.path.join(base, f"node{i}")
        cfg = Config()
        cfg.root_dir = home
        cfg.ensure_dirs()
        nk = NodeKey.load_or_generate(cfg.node_key_file)
        pv = FilePV.load_or_generate(
            cfg.priv_validator_key_file, cfg.priv_validator_state_file
        )
        nodes.append((home, cfg, nk, pv))
    doc = GenesisDoc(
        chain_id=args.chain_id or "testnet-%06x" % (int(time.time()) & 0xFFFFFF),
        genesis_time_ns=time.time_ns(),
        validators=[
            GenesisValidator("ed25519", pv.get_pub_key().data, 10)
            for _, _, _, pv in nodes
        ],
    )
    doc.validate_and_complete()
    peers = ",".join(
        f"{nk.id}@127.0.0.1:{26656 + 10 * i}"
        for i, (_, _, nk, _) in enumerate(nodes)
    )
    for i, (home, cfg, nk, pv) in enumerate(nodes):
        doc.save_as(cfg.genesis_file)
        cfg.p2p.laddr = f"tcp://127.0.0.1:{26656 + 10 * i}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{26657 + 10 * i}"
        cfg.p2p.persistent_peers = peers
        cfg.save()
    print(f"wrote {n}-node testnet to {base} (chain {doc.chain_id})")
    return 0


def cmd_rollback(args) -> int:
    """Roll back one height of state (reference rollback.go)."""
    cfg = _load_config(args)
    from .store.kv import SqliteKV
    from .state.store import StateStore
    from .store.block_store import BlockStore

    ss = StateStore(SqliteKV(os.path.join(cfg.db_dir, "state.db")))
    bs = BlockStore(SqliteKV(os.path.join(cfg.db_dir, "blockstore.db")))
    state = ss.rollback(bs)
    if args.hard:
        # remove the rolled-back block too (prune_blocks_since removes
        # blocks ABOVE the argument)
        bs.prune_blocks_since(state.last_block_height)
    print(
        f"rolled back to height {state.last_block_height} "
        f"(app hash {state.app_hash.hex()})"
    )
    return 0


def cmd_reset(args) -> int:
    """unsafe-reset-all: wipe data, keep config (reference reset.go)."""
    cfg = _load_config(args)
    data = cfg.db_dir
    if os.path.isdir(data):
        shutil.rmtree(data)
    os.makedirs(data, exist_ok=True)
    # reset privval state (keep the key)
    st = cfg.priv_validator_state_file
    if os.path.exists(st):
        os.remove(st)
    print(f"reset {data}")
    return 0


def cmd_gen_validator(args) -> int:
    from .crypto import ed25519

    k = ed25519.PrivKey.generate()
    print(
        json.dumps(
            {
                "pub_key": k.public_key().data.hex(),
                "priv_key_seed": k.seed.hex(),
                "address": k.public_key().address().hex(),
            },
            indent=2,
        )
    )
    return 0


def cmd_gen_node_key(args) -> int:
    from .p2p.key import NodeKey

    nk = NodeKey.generate()
    print(json.dumps({"id": nk.id}, indent=2))
    return 0


def cmd_show_node_id(args) -> int:
    from .p2p.key import NodeKey

    cfg = _load_config(args)
    nk = NodeKey.load_or_generate(cfg.node_key_file)
    print(nk.id)
    return 0


def cmd_show_validator(args) -> int:
    from .privval.file_pv import FilePV

    cfg = _load_config(args)
    pv = FilePV.load_or_generate(
        cfg.priv_validator_key_file, cfg.priv_validator_state_file
    )
    pub = pv.get_pub_key()
    print(
        json.dumps(
            {"pub_key": pub.data.hex(), "address": pub.address().hex()}
        )
    )
    return 0


def cmd_light(args) -> int:
    """Run a light-client proxy (reference cmd light.go + light/proxy)."""
    from .crypto._native_build import preload_in_background
    from .light.client import LightClient, TrustOptions
    from .light.proxy import LightProxy
    from .light.store import LightStore
    from .rpc.light_provider import RPCProvider
    from .store.kv import SqliteKV

    # warm the native crypto libs off-thread: first-use otherwise pays
    # a synchronous g++ compile inline on the verify path
    preload_in_background()

    os.makedirs(args.home, exist_ok=True)
    store = LightStore(SqliteKV(os.path.join(args.home, "light.db")))
    trust = None
    if args.trusted_height and args.trusted_hash:
        trust = TrustOptions(
            int(args.trust_period * 1e9),
            args.trusted_height,
            bytes.fromhex(args.trusted_hash),
        )
    lc = LightClient(
        args.chain_id,
        trust,
        RPCProvider(args.chain_id, args.primary),
        [RPCProvider(args.chain_id, w) for w in args.witnesses.split(",") if w],
        store,
        sequential=args.sequential,
    )
    host, _, port = args.laddr.removeprefix("tcp://").rpartition(":")
    proxy = LightProxy(lc, args.primary, host or "127.0.0.1", int(port))

    async def run():
        await proxy.start()
        print(f"light proxy for {args.chain_id} on {args.laddr}")
        try:
            await asyncio.Event().wait()
        finally:
            await proxy.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_replay(args) -> int:
    """Print (and in --console mode, step through) the consensus WAL
    (reference replay_file.go: RunReplayFile)."""
    from .consensus.wal import WAL

    cfg = _load_config(args)
    wal_path = cfg.wal_file
    if not os.path.exists(wal_path):
        print(f"no WAL at {wal_path}")
        return 1
    wal = WAL(wal_path)
    msgs = wal.search_for_end_height(0) or []
    count = 0
    for rec in msgs:
        count += 1
        print(f"#{count} {rec!r}")
        if args.console:
            input("  <enter> for next> ")
    print(f"replayed {count} WAL records")
    return 0


def cmd_rewind(args) -> int:
    """Rewind state + blocks to --height (reference rewind.go)."""
    cfg = _load_config(args)
    from .state.store import StateStore
    from .store.block_store import BlockStore
    from .store.kv import SqliteKV

    ss = StateStore(SqliteKV(os.path.join(cfg.db_dir, "state.db")))
    bs = BlockStore(SqliteKV(os.path.join(cfg.db_dir, "blockstore.db")))
    state = ss.load()
    if state is None:
        print("no state to rewind")
        return 1
    target = args.height
    while state.last_block_height > target:
        state = ss.rollback(bs)
    bs.prune_blocks_since(state.last_block_height)
    print(f"rewound to height {state.last_block_height}")
    return 0


def cmd_compact(args) -> int:
    """VACUUM the sqlite stores (reference compact.go's goleveldb
    compaction)."""
    import sqlite3

    cfg = _load_config(args)
    for name in ("state.db", "blockstore.db", "evidence.db", "tx_index.db"):
        path = os.path.join(cfg.db_dir, name)
        if os.path.exists(path):
            before = os.path.getsize(path)
            conn = sqlite3.connect(path)
            conn.execute("VACUUM")
            conn.close()
            print(f"{name}: {before} -> {os.path.getsize(path)} bytes")
    return 0


def cmd_reindex_event(args) -> int:
    """Rebuild the tx/block index from stored blocks + ABCI responses
    (reference reindex_event.go)."""
    cfg = _load_config(args)
    from .state.execution import ABCIResponses
    from .state.store import StateStore
    from .state.txindex import KVIndexer, TxResult
    from .store.block_store import BlockStore
    from .store.kv import SqliteKV

    ss = StateStore(SqliteKV(os.path.join(cfg.db_dir, "state.db")))
    bs = BlockStore(SqliteKV(os.path.join(cfg.db_dir, "blockstore.db")))
    ix = KVIndexer(SqliteKV(os.path.join(cfg.db_dir, "tx_index.db")))
    start = args.start_height or bs.base
    end = args.end_height or bs.height
    n_tx = 0
    for h in range(start, end + 1):
        blk = bs.load_block(h)
        if blk is None:
            continue
        raw = ss.load_abci_responses(h)
        results = ABCIResponses.decode(raw).deliver_txs if raw else []
        # block-level (begin/end-block) events are not persisted in
        # ABCIResponses, so only tx events can be rebuilt offline
        for i, tx in enumerate(blk.data.txs):
            res = results[i] if i < len(results) else None
            ix.index_tx(
                TxResult(
                    height=h,
                    index=i,
                    tx=tx,
                    code=res.code if res else 0,
                    log=res.log if res else "",
                    events=[
                        (e.type, e.attributes) for e in res.events
                    ] if res else [],
                )
            )
            n_tx += 1
    print(f"reindexed heights [{start},{end}]: {n_tx} txs")
    return 0


def cmd_debug_dump(args) -> int:
    """Snapshot node debug state to a directory (reference
    cmd/tendermint/commands/debug: dump)."""
    from .rpc.light_provider import RPCClient

    os.makedirs(args.output, exist_ok=True)

    async def run() -> int:
        rpc = RPCClient(args.rpc_laddr)
        for method in (
            "status",
            "net_info",
            "consensus_state",
            "dump_consensus_state",
        ):
            try:
                res = await rpc.call(method)
            except Exception as e:
                res = {"error": str(e)}
            with open(os.path.join(args.output, f"{method}.json"), "w") as f:
                json.dump(res, f, indent=2)
        if args.pprof_laddr:
            host, _, port = (
                args.pprof_laddr.removeprefix("tcp://").rpartition(":")
            )
            for route in ("goroutine", "heap"):
                try:
                    reader, writer = await asyncio.open_connection(
                        host or "127.0.0.1", int(port)
                    )
                    writer.write(
                        f"GET /debug/pprof/{route} HTTP/1.1\r\n"
                        f"Host: x\r\n\r\n".encode()
                    )
                    await writer.drain()
                    data = await reader.read()
                    writer.close()
                    body = data.split(b"\r\n\r\n", 1)[-1]
                    with open(
                        os.path.join(args.output, f"{route}.txt"), "wb"
                    ) as f:
                        f.write(body)
                except (ConnectionError, OSError) as e:
                    print(f"pprof {route}: {e}")
        return 0

    rc = asyncio.run(run())
    print(f"wrote debug dump to {args.output}")
    return rc


def cmd_probe_upnp(args) -> int:
    """Discover the UPnP gateway and exercise a full map/unmap round
    trip on a probe port (reference probe_upnp.go)."""
    from .p2p.upnp import UPnPError, discover

    try:
        gw = discover()
    except UPnPError as e:
        print(f"no UPnP gateway found ({e})")
        return 1
    print(f"UPnP gateway: {gw.service_type} at {gw.control_url}")
    try:
        print(f"external IP: {gw.get_external_ip()}")
        probe_port = 26699
        gw.add_port_mapping(probe_port, probe_port)
        print(f"mapped probe port {probe_port} -> OK")
        gw.delete_port_mapping(probe_port)
        print("unmapped probe port -> OK")
    except UPnPError as e:
        print(f"gateway found but mapping failed: {e}")
        return 1
    return 0


def cmd_probe_tpu(args) -> int:
    """Show the device plane as the node would see it: backend, device
    inventory, and the mesh the [tpu] config section resolves to —
    the operator's first stop when sharded verification doesn't engage."""
    from .config import Config

    cfg = Config.load(args.home)
    t = cfg.tpu
    print(
        f"[tpu] ici_parallelism={t.ici_parallelism} "
        f"dcn_parallelism={t.dcn_parallelism} "
        f"mesh_backend={t.mesh_backend or '(default)'}"
    )
    import jax

    try:
        devs = jax.devices(t.mesh_backend or None)
    except Exception as e:
        print(f"backend unavailable: {e}")
        return 1
    print(f"backend: {jax.default_backend()}, {len(devs)} device(s)")
    for d in devs[:16]:
        print(f"  {d.id}: {d.device_kind} (process {d.process_index})")
    if len(devs) > 16:
        print(f"  ... and {len(devs) - 16} more")
    from .parallel import build_mesh

    try:
        mesh = build_mesh(
            t.ici_parallelism, t.dcn_parallelism, t.mesh_backend
        )
    except ValueError as e:
        print(f"mesh: UNSATISFIABLE ({e})")
        return 1
    if mesh is None:
        print("mesh: none (single-device verification path)")
    else:
        print(
            f"mesh: axes {dict(mesh.shape)} -> batch dim shards over "
            f"{mesh.devices.size} devices"
        )
    return 0


def cmd_verify_service(args) -> int:
    """Run the standalone verify-service process: one device-owning
    scheduler serving a whole committee over UDS IPC
    (parallel/verify_service.py, ROADMAP verify-as-a-service)."""
    from .libs.log import default_logger
    from .parallel.verify_service import run_service

    return run_service(
        args.socket,
        max_batch=args.max_batch,
        stats_port=args.stats_port if args.stats_port >= 0 else None,
        prewarm=args.prewarm,
        logger=default_logger(),
        ready_fd=args.ready_fd if args.ready_fd >= 0 else None,
        trace=args.trace,
    )


def cmd_version(args) -> int:
    print(
        f"tendermint-tpu {TMCORE_SEM_VER} "
        f"(block protocol {BLOCK_PROTOCOL_VERSION}, "
        f"p2p protocol {P2P_PROTOCOL_VERSION})"
    )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tendermint_tpu",
        description="TPU-native tendermint (morph fork capabilities)",
    )
    p.add_argument(
        "--home", default=libs_cli.default_home(), help="node home directory"
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("init", help="initialize config/genesis/keys")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run the node")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr", default="")
    sp.add_argument("--p2p.laddr", dest="p2p_laddr", default="")
    sp.add_argument(
        "--p2p.persistent_peers", dest="persistent_peers", default=""
    )
    sp.add_argument(
        "--consensus.switchHeight",
        dest="switch_height",
        type=int,
        default=0,
        help="sequencer-mode upgrade height (reference upgrade/upgrade.go)",
    )
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("testnet", help="generate a local testnet")
    sp.add_argument("--v", type=int, default=4)
    sp.add_argument("--output", default="./mytestnet")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_testnet)

    sp = sub.add_parser("rollback", help="roll back one height")
    sp.add_argument("--hard", action="store_true")
    sp.set_defaults(fn=cmd_rollback)

    sp = sub.add_parser("unsafe-reset-all", help="wipe chain data")
    sp.set_defaults(fn=cmd_reset)

    sp = sub.add_parser("gen-validator", help="generate a validator key")
    sp.set_defaults(fn=cmd_gen_validator)

    sp = sub.add_parser("gen-node-key", help="generate a node key")
    sp.set_defaults(fn=cmd_gen_node_key)

    sp = sub.add_parser("show-node-id", help="print this node's p2p id")
    sp.set_defaults(fn=cmd_show_node_id)

    sp = sub.add_parser("show-validator", help="print this node's validator")
    sp.set_defaults(fn=cmd_show_validator)

    sp = sub.add_parser("light", help="run a light-client proxy")
    sp.add_argument("chain_id")
    sp.add_argument("-p", "--primary", required=True,
                    help="primary RPC addr")
    sp.add_argument("-w", "--witnesses", default="",
                    help="comma-separated witness RPC addrs")
    sp.add_argument("--laddr", default="tcp://127.0.0.1:8888")
    sp.add_argument("--trusted-height", type=int, default=0)
    sp.add_argument("--trusted-hash", default="")
    sp.add_argument("--trust-period", type=float, default=168 * 3600.0,
                    help="seconds")
    sp.add_argument("--sequential", action="store_true")
    sp.set_defaults(fn=cmd_light)

    sp = sub.add_parser("replay", help="print the consensus WAL")
    sp.set_defaults(fn=cmd_replay, console=False)

    sp = sub.add_parser(
        "replay-console", help="step through the consensus WAL"
    )
    sp.set_defaults(fn=cmd_replay, console=True)

    from .abci.cli import register as register_abci_cli

    register_abci_cli(sub)

    sp = sub.add_parser("rewind", help="rewind state+blocks to a height")
    sp.add_argument("--height", type=int, required=True)
    sp.set_defaults(fn=cmd_rewind)

    sp = sub.add_parser("compact", help="compact the sqlite stores")
    sp.set_defaults(fn=cmd_compact)

    sp = sub.add_parser(
        "reindex-event", help="rebuild the tx/block event index"
    )
    sp.add_argument("--start-height", type=int, default=0)
    sp.add_argument("--end-height", type=int, default=0)
    sp.set_defaults(fn=cmd_reindex_event)

    sp = sub.add_parser("debug", help="debug utilities")
    dsub = sp.add_subparsers(dest="debug_cmd", required=True)
    dp = dsub.add_parser("dump", help="snapshot node state to a dir")
    dp.add_argument("--rpc-laddr", default="tcp://127.0.0.1:26657")
    dp.add_argument("--pprof-laddr", default="")
    dp.add_argument("--output", default="./debug-dump")
    dp.set_defaults(fn=cmd_debug_dump)

    sp = sub.add_parser("probe-upnp", help="probe for a UPnP gateway")
    sp.set_defaults(fn=cmd_probe_upnp)

    sp = sub.add_parser(
        "probe-tpu", help="show devices + the [tpu] config mesh"
    )
    sp.set_defaults(fn=cmd_probe_tpu)

    sp = sub.add_parser(
        "verify-service",
        help="run a standalone verify-service process (the device-"
        "owning scheduler N nodes submit to over a unix socket; point "
        "nodes at it with [scheduler] remote_socket)",
    )
    sp.add_argument(
        "--socket", required=True, help="unix-domain socket path to serve"
    )
    sp.add_argument("--max-batch", type=int, default=16384)
    sp.add_argument(
        "--stats-port",
        type=int,
        default=-1,
        help="TCP port for GET /metrics + /dump_dispatch_ledger "
        "(0 = ephemeral, -1 = disabled)",
    )
    sp.add_argument(
        "--prewarm",
        action="store_true",
        help="AOT-load the bucket-ladder verify programs before serving",
    )
    sp.add_argument(
        "--ready-fd",
        type=int,
        default=-1,
        help="fd that gets one JSON readiness line once the socket "
        "accepts (harness use)",
    )
    sp.add_argument(
        "--trace",
        action="store_true",
        help="record queue/dispatch/device sub-spans for traced client "
        "submissions into a service-side flight ring (served at "
        "GET /dump_traces on --stats-port; TM_TPU_TRACE=1 also enables)",
    )
    sp.set_defaults(fn=cmd_verify_service)

    sp = sub.add_parser("version", help="print version")
    sp.set_defaults(fn=cmd_version)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
