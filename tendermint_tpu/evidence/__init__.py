"""Evidence subsystem: pool + gossip reactor + verification.

Reference: evidence/ (pool.go, reactor.go, verify.go). Byzantine behavior
(duplicate votes, light-client attacks) is captured, verified against
historical validator sets, gossiped, proposed into blocks, and marked
committed/expired.
"""

from .pool import EvidencePool
from .reactor import EVIDENCE_CHANNEL, EvidenceReactor
from .verify import verify_duplicate_vote, verify_light_client_attack

__all__ = [
    "EvidencePool",
    "EvidenceReactor",
    "EVIDENCE_CHANNEL",
    "verify_duplicate_vote",
    "verify_light_client_attack",
]
