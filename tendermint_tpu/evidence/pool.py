"""EvidencePool — pending/committed evidence over a KV store.

Reference: evidence/pool.go. Lifecycle: AddEvidence (verify + persist
pending, :134) → PendingEvidence (proposal inclusion, :87) → Update on
commit (mark committed + expire old, :105) → gossiped by the reactor via
the pending list. ReportConflictingVotes (:179) receives equivocations
straight from the consensus vote path through a buffer that is drained on
the next Update (processConsensusBuffer :459) so evidence construction
uses the post-commit state.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..libs.log import Logger, nop_logger
from ..libs.metrics import EvidenceMetrics, default_metrics
from ..obs import default_tracer
from ..state.state import State
from ..types.evidence import (
    DuplicateVoteEvidence,
    LightClientAttackEvidence,
    decode_evidence,
)
from ..types.vote import Vote
from .verify import verify_duplicate_vote, verify_light_client_attack

_PENDING = b"\x00"
_COMMITTED = b"\x01"


class BenignEvidenceError(ValueError):
    """Evidence we cannot judge or no longer care about — NOT an attack.

    Raised when verification fails for reasons local to this node (missing
    historical data because we are behind or pruned, or the evidence aged
    out between the sender's sweep and ours). The reactor must not punish
    peers for these (reference evidence/reactor.go only disconnects on
    ErrInvalidEvidence)."""


def _key(prefix: bytes, height: int, ev_hash: bytes) -> bytes:
    return prefix + height.to_bytes(8, "big") + ev_hash


class EvidencePool:
    def __init__(
        self,
        kv,
        state_store,
        block_store,
        verifier=None,
        logger: Optional[Logger] = None,
    ):
        self._kv = kv
        self._state_store = state_store
        self._block_store = block_store
        self._verifier = verifier
        self.logger = logger or nop_logger()
        self._lock = threading.Lock()
        self._state: Optional[State] = state_store.load()
        # (voteA, voteB) equivocations reported by consensus, drained on
        # the next Update (reference consensusBuffer, pool.go:459-541)
        self._consensus_buffer: list[tuple[Vote, Vote]] = []
        # in-order pending cache for gossip/proposal (reference clist)
        self._pending: dict[bytes, object] = {}
        self.metrics = default_metrics(EvidenceMetrics)
        self._load_pending()
        self.metrics.pool_size.set(len(self._pending))

    # --- queries ------------------------------------------------------------

    def pending_evidence(self, max_bytes: int = 1 << 20) -> list:
        """Evidence for proposal inclusion, size-capped (reference :87)."""
        out, total = [], 0
        with self._lock:
            for ev in self._pending.values():
                sz = len(ev.encode())
                if total + sz > max_bytes:
                    break
                out.append(ev)
                total += sz
        return out

    def size(self) -> int:
        with self._lock:
            return len(self._pending)

    @property
    def state(self) -> Optional[State]:
        return self._state

    # --- ingestion ----------------------------------------------------------

    def add_evidence(self, ev) -> None:
        """Verify + persist pending evidence (reference AddEvidence :134).
        Idempotent: pending/committed duplicates are no-ops."""
        with self._lock:
            if ev.hash() in self._pending:
                return
            if self._is_committed(ev):
                return
        ev.validate_basic()
        self.verify(ev)
        self._add_pending(ev)
        self.logger.info("verified new evidence", height=ev.height())

    def report_conflicting_votes(self, vote_a: Vote, vote_b: Vote) -> None:
        """Equivocation from the consensus vote path (reference :179)."""
        with self._lock:
            self._consensus_buffer.append((vote_a, vote_b))

    def check_evidence(self, ev, state: Optional[State] = None) -> None:
        """Validate committed-block evidence (reference CheckEvidence :192):
        already-pending evidence is known-good; otherwise verify now —
        against the caller's state when given (block validation/replay must
        judge age relative to the block being validated, not the pool's
        possibly-newer head)."""
        with self._lock:
            if ev.hash() in self._pending:
                return
            if self._is_committed(ev):
                raise ValueError("evidence was already committed")
        ev.validate_basic()
        self.verify(ev, state=state)

    # --- verification (reference verify.go:19-117) ---------------------------

    def verify(self, ev, state: Optional[State] = None) -> None:
        state = state if state is not None else self._state
        if state is None:
            raise ValueError("evidence pool has no state")
        height = state.last_block_height
        params = state.consensus_params.evidence
        age_blocks = height - ev.height()

        meta = self._block_store.load_block_meta(ev.height())
        if meta is None:
            raise BenignEvidenceError(f"don't have header #{ev.height()}")
        ev_time = meta.header.time_ns
        if ev.timestamp_ns != ev_time:
            raise ValueError(
                "evidence time differs from the block it is associated with"
            )
        age_ns = state.last_block_time_ns - ev_time
        if (
            age_ns > params.max_age_duration_ns
            and age_blocks > params.max_age_num_blocks
        ):
            raise BenignEvidenceError(f"evidence from height {ev.height()} is too old")

        if isinstance(ev, DuplicateVoteEvidence):
            vals = self._state_store.load_validators(ev.height())
            if vals is None:
                raise BenignEvidenceError(f"no validator set at height {ev.height()}")
            verify_duplicate_vote(
                ev, state.chain_id, vals, verifier=self._verifier
            )
        elif isinstance(ev, LightClientAttackEvidence):
            common_vals = self._state_store.load_validators(ev.height())
            if common_vals is None:
                raise BenignEvidenceError(f"no validator set at height {ev.height()}")
            # the trusted header to differ from is the one at the
            # CONFLICTING block's height (lunatic attacks have
            # common_height < conflicting height; reference verify.go:60-90)
            from ..types.block import Header

            try:
                conflict_header = Header.decode(ev.conflicting_header)
            except Exception as e:
                raise ValueError(
                    f"malformed light-client-attack evidence: {e}"
                ) from e
            conflict_h = conflict_header.height
            trusted = (
                meta
                if conflict_h == ev.height()
                else self._block_store.load_block_meta(conflict_h)
            )
            if trusted is None:
                # forward lunatic attack: the forged header sits above our
                # head (or at a pruned height) — judge against our latest
                # header instead (reference verify.go:76-90)
                latest_h = self._block_store.height()
                trusted = self._block_store.load_block_meta(latest_h)
                if trusted is None:
                    raise BenignEvidenceError(f"don't have header #{conflict_h}")
                if trusted.header.time_ns < conflict_header.time_ns:
                    raise ValueError(
                        "latest block time is before conflicting block time"
                    )
            verify_light_client_attack(
                ev,
                common_vals,
                trusted.block_id.hash,
                state.chain_id,
                verifier=self._verifier,
            )
        else:
            raise ValueError(f"unrecognized evidence type {type(ev)}")

    # --- commit-time update (reference Update :105) --------------------------

    def update(self, state: State, committed_evidence: list) -> None:
        self._state = state
        self._mark_committed(committed_evidence)
        self._process_consensus_buffer(state)
        self._remove_expired(state)

    def _process_consensus_buffer(self, state: State) -> None:
        with self._lock:
            buf, self._consensus_buffer = self._consensus_buffer, []
        for vote_a, vote_b in buf:
            vals = self._state_store.load_validators(vote_a.height)
            meta = self._block_store.load_block_meta(vote_a.height)
            if vals is None or meta is None:
                self.logger.error(
                    "dropping equivocation: missing historical data",
                    height=vote_a.height,
                )
                continue
            _, val = vals.get_by_address(vote_a.validator_address)
            if val is None:
                continue
            ev = DuplicateVoteEvidence.from_votes(
                vote_a,
                vote_b,
                vals.total_voting_power(),
                val.voting_power,
                meta.header.time_ns,
            )
            try:
                self.add_evidence(ev)
            except ValueError as e:
                self.logger.error("dropping equivocation", err=str(e))

    # --- storage ------------------------------------------------------------

    def _add_pending(self, ev) -> None:
        with self._lock:
            self._kv.set(_key(_PENDING, ev.height(), ev.hash()), ev.encode())
            self._pending[ev.hash()] = ev
            self.metrics.pool_added.inc()
            self.metrics.pool_size.set(len(self._pending))
        default_tracer().event(
            "evidence.added", height=ev.height(), type=type(ev).__name__
        )

    def _mark_committed(self, evs: list) -> None:
        with self._lock:
            for ev in evs:
                self._kv.set(_key(_COMMITTED, ev.height(), ev.hash()), b"\x01")
                self._kv.delete(_key(_PENDING, ev.height(), ev.hash()))
                self._pending.pop(ev.hash(), None)
            if evs:
                self.metrics.pool_committed.inc(len(evs))
            self.metrics.pool_size.set(len(self._pending))

    def _is_committed(self, ev) -> bool:
        return self._kv.get(_key(_COMMITTED, ev.height(), ev.hash())) is not None

    def _remove_expired(self, state: State) -> None:
        params = state.consensus_params.evidence
        with self._lock:
            for h, ev in list(self._pending.items()):
                age_blocks = state.last_block_height - ev.height()
                age_ns = state.last_block_time_ns - ev.timestamp_ns
                if (
                    age_ns > params.max_age_duration_ns
                    and age_blocks > params.max_age_num_blocks
                ):
                    self._kv.delete(_key(_PENDING, ev.height(), ev.hash()))
                    del self._pending[h]
            self.metrics.pool_size.set(len(self._pending))

    def _load_pending(self) -> None:
        for k, v in self._kv.iterate(_PENDING, _COMMITTED):
            ev = decode_evidence(v)
            self._pending[ev.hash()] = ev
