"""EvidenceReactor — gossip pending evidence over channel 0x38.

Reference: evidence/reactor.go — `EvidenceChannel = 0x38` (:15),
per-peer `broadcastEvidenceRoutine` walking the pool's pending list
(:104-150), Receive → AddEvidence (:80-100); peers sending invalid
evidence are stopped.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..libs import protoio as pio
from ..libs.log import Logger, nop_logger
from ..p2p.mconn import ChannelDescriptor
from ..p2p.switch import Reactor
from ..p2p.transport import Peer
from ..types.evidence import decode_evidence
from .pool import BenignEvidenceError, EvidencePool

EVIDENCE_CHANNEL = 0x38
_BROADCAST_INTERVAL = 0.5  # reference: peerRetryMessageIntervalMS-ish pacing


def _enc_list(evs: list) -> bytes:
    return b"".join(pio.field_bytes(1, ev.encode()) for ev in evs)


def _dec_list(data: bytes) -> list:
    return [
        decode_evidence(val)
        for num, _wt, val in pio.iter_fields(data)
        if num == 1
    ]


class EvidenceReactor(Reactor):
    def __init__(self, pool: EvidencePool, logger: Optional[Logger] = None):
        super().__init__("Evidence")
        self.pool = pool
        self.logger = logger or nop_logger()
        self._peer_tasks: dict[str, asyncio.Task] = {}

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=EVIDENCE_CHANNEL, priority=6)]

    async def add_peer(self, peer: Peer) -> None:
        self._peer_tasks[peer.id] = asyncio.create_task(
            self._broadcast_routine(peer)
        )

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        t = self._peer_tasks.pop(peer.id, None)
        if t:
            t.cancel()

    async def on_stop(self) -> None:
        for t in self._peer_tasks.values():
            t.cancel()
        self._peer_tasks.clear()

    async def receive(self, channel_id: int, peer: Peer, msg: bytes) -> None:
        try:
            evs = _dec_list(msg)
        except Exception as e:
            self.logger.error("bad evidence msg", err=str(e))
            await self.switch.stop_peer_for_error(peer, "bad evidence msg")
            return
        for ev in evs:
            try:
                self.pool.add_evidence(ev)
            except BenignEvidenceError as e:
                # we are behind / pruned / the evidence just aged out —
                # never punish a peer for evidence we can't judge (the
                # reference only disconnects on ErrInvalidEvidence,
                # evidence/reactor.go:87-99)
                self.logger.info("cannot verify evidence", err=str(e))
                continue
            except ValueError as e:
                self.logger.info(
                    "peer sent invalid evidence", peer=peer.id, err=str(e)
                )
                await self.switch.stop_peer_for_error(
                    peer, f"invalid evidence: {e}"
                )
                return

    async def _broadcast_routine(self, peer: Peer) -> None:
        """Periodically send our full pending list to the peer; the pool
        dedupes on the receiving side (reference walks a clist with
        per-element waiting; the polling shape is equivalent for the small
        evidence volumes involved)."""
        sent: set[bytes] = set()
        while True:
            try:
                pending = self.pool.pending_evidence()
                fresh = [ev for ev in pending if ev.hash() not in sent]
                if fresh:
                    if peer.try_send(EVIDENCE_CHANNEL, _enc_list(fresh)):
                        sent.update(ev.hash() for ev in fresh)
                await asyncio.sleep(_BROADCAST_INTERVAL)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.logger.error("evidence broadcast error", err=str(e))
                await asyncio.sleep(_BROADCAST_INTERVAL)
