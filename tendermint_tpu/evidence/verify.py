"""Evidence verification rules.

Reference: evidence/verify.go — `verify` (:19, age/time checks + dispatch),
`VerifyDuplicateVote` (:162), `VerifyLightClientAttack` (:113). Signature
checks ride the TPU batch verifier (both conflicting votes in one batch;
the reference verifies them serially one at a time).
"""

from __future__ import annotations

from typing import Optional

from ..crypto.batch_verifier import BatchVerifier, SigItem
from ..types.evidence import DuplicateVoteEvidence, LightClientAttackEvidence
from ..types.validator_set import ValidatorSet


def _evidence_dispatch(verifier):
    """Default evidence signature checks onto the process dispatch
    scheduler under the evidence class (just below consensus priority —
    conflicting votes are consensus-relevant but must not delay live
    vote rounds)."""
    if verifier is not None:
        return verifier
    from ..parallel.scheduler import default_dispatch

    return default_dispatch("evidence")


def verify_duplicate_vote(
    ev: DuplicateVoteEvidence,
    chain_id: str,
    val_set: ValidatorSet,
    verifier: Optional[BatchVerifier] = None,
) -> None:
    """Raises on invalid evidence (reference VerifyDuplicateVote :162)."""
    idx, val = val_set.get_by_address(ev.vote_a.validator_address)
    if val is None:
        raise ValueError(
            f"address {ev.vote_a.validator_address.hex()} was not a "
            f"validator at height {ev.height()}"
        )
    a, b = ev.vote_a, ev.vote_b
    if (a.height, a.round, a.type) != (b.height, b.round, b.type):
        raise ValueError("h/r/s does not match")
    if a.validator_address != b.validator_address:
        raise ValueError("validator addresses do not match")
    if a.block_id.key() == b.block_id.key():
        raise ValueError("block IDs are the same — not a real duplicate vote")
    if val.pub_key.address() != a.validator_address:
        raise ValueError("address doesn't match pubkey")
    if val.voting_power != ev.validator_power:
        raise ValueError("validator power does not match")
    if val_set.total_voting_power() != ev.total_voting_power:
        raise ValueError("total voting power does not match")

    verifier = _evidence_dispatch(verifier)
    key_type = getattr(val.pub_key, "type_name", "ed25519")
    ok = verifier.verify(
        [
            SigItem(
                val.pub_key.data, a.sign_bytes(chain_id), a.signature,
                key_type=key_type,
            ),
            SigItem(
                val.pub_key.data, b.sign_bytes(chain_id), b.signature,
                key_type=key_type,
            ),
        ]
    )
    if not ok[0]:
        raise ValueError("invalid signature on vote A")
    if not ok[1]:
        raise ValueError("invalid signature on vote B")


def verify_light_client_attack(
    ev: LightClientAttackEvidence,
    common_vals: ValidatorSet,
    trusted_header_hash: bytes,
    chain_id: str,
    verifier: Optional[BatchVerifier] = None,
) -> None:
    """Reference VerifyLightClientAttack (:113):
    - >1/3 of the common validator set signed the conflicting block
      (VerifyCommitLightTrusting),
    - 2/3+ of the conflicting set signed it (VerifyCommitLight),
    - the conflicting header hash differs from our trusted one.
    """
    from ..types.block import Commit, Header

    try:
        header = Header.decode(ev.conflicting_header)
        commit = Commit.decode(ev.conflicting_commit)
        conflicting_vals = ValidatorSet.decode(ev.conflicting_validators)
    except Exception as e:
        # decode failures (EOFError from truncated protos, etc.) must surface
        # as invalid-evidence ValueErrors: this path is reachable from a
        # byzantine proposer via block validation and must never crash the
        # consensus step
        raise ValueError(f"malformed light-client-attack evidence: {e}") from e

    # the commit must actually be FOR the conflicting header — otherwise a
    # real commit for the canonical block + a fabricated header would pass
    # (the reference binds them via SignedHeader.ValidateBasic)
    if commit.block_id.hash != header.hash():
        raise ValueError("conflicting commit does not sign the conflicting header")
    if commit.height != header.height:
        raise ValueError("conflicting commit height mismatch")

    if header.hash() == trusted_header_hash:
        raise ValueError("conflicting block matches the trusted header")

    verifier = _evidence_dispatch(verifier)
    common_vals.verify_commit_light_trusting(
        chain_id, commit, 1, 3, verifier=verifier
    )
    conflicting_vals.verify_commit_light(
        chain_id, commit.block_id, header.height, commit, verifier=verifier
    )
