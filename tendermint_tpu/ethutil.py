"""Ethereum interop utilities: RLP + legacy transactions.

Reference: ethutil/ (util.go EncodeTransactions/DecodeTxs,
transaction.go GetSender/RlpFieldsToLegacyTx, hex/). The L2 bridge moves
RLP-encoded legacy txs between the consensus node and the execution
node; sender recovery uses EIP-155 v-values with keccak + secp256k1
public-key recovery.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .crypto import secp256k1
from .crypto.keccak import keccak256

# --- RLP -------------------------------------------------------------------


def rlp_encode(item) -> bytes:
    """item: bytes | int | list (nested)."""
    if isinstance(item, int):
        if item == 0:
            payload = b""
        else:
            payload = item.to_bytes((item.bit_length() + 7) // 8, "big")
        return rlp_encode(payload)
    if isinstance(item, (bytes, bytearray)):
        item = bytes(item)
        if len(item) == 1 and item[0] < 0x80:
            return item
        return _rlp_len(len(item), 0x80) + item
    if isinstance(item, (list, tuple)):
        body = b"".join(rlp_encode(x) for x in item)
        return _rlp_len(len(body), 0xC0) + body
    raise TypeError(f"cannot rlp-encode {type(item)}")


def _rlp_len(n: int, offset: int) -> bytes:
    if n < 56:
        return bytes([offset + n])
    nb = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([offset + 55 + len(nb)]) + nb


def rlp_decode(data: bytes):
    """Returns (item, remaining). Raises ValueError on malformed input."""
    if not data:
        raise ValueError("empty rlp")
    b0 = data[0]
    if b0 < 0x80:
        return data[:1], data[1:]
    if b0 < 0xB8:
        n = b0 - 0x80
        if len(data) < 1 + n:
            raise ValueError("truncated rlp string")
        return data[1 : 1 + n], data[1 + n :]
    if b0 < 0xC0:
        ln = b0 - 0xB7
        n = int.from_bytes(data[1 : 1 + ln], "big")
        start = 1 + ln
        if len(data) < start + n:
            raise ValueError("truncated rlp long string")
        return data[start : start + n], data[start + n :]
    if b0 < 0xF8:
        n = b0 - 0xC0
        body, rest = data[1 : 1 + n], data[1 + n :]
        if len(body) < n:
            raise ValueError("truncated rlp list")
        return _decode_list(body), rest
    ln = b0 - 0xF7
    n = int.from_bytes(data[1 : 1 + ln], "big")
    start = 1 + ln
    body, rest = data[start : start + n], data[start + n :]
    if len(body) < n:
        raise ValueError("truncated rlp long list")
    return _decode_list(body), rest


def _decode_list(body: bytes) -> list:
    out = []
    while body:
        item, body = rlp_decode(body)
        out.append(item)
    return out


def _to_int(b: bytes) -> int:
    return int.from_bytes(b, "big") if b else 0


# --- legacy transactions ----------------------------------------------------


@dataclass
class LegacyTx:
    """Pre-EIP-1559 transaction (reference RlpFieldsToLegacyTx,
    transaction.go:35)."""

    nonce: int = 0
    gas_price: int = 0
    gas: int = 0
    to: bytes = b""  # 20 bytes or empty (contract creation)
    value: int = 0
    data: bytes = b""
    v: int = 0
    r: int = 0
    s: int = 0

    def encode(self) -> bytes:
        return rlp_encode(
            [
                self.nonce,
                self.gas_price,
                self.gas,
                self.to,
                self.value,
                self.data,
                self.v,
                self.r,
                self.s,
            ]
        )

    @classmethod
    def decode(cls, data: bytes) -> tuple["LegacyTx", bytes]:
        fields, rest = rlp_decode(data)
        if not isinstance(fields, list) or len(fields) != 9:
            raise ValueError("not a legacy tx")
        return (
            cls(
                nonce=_to_int(fields[0]),
                gas_price=_to_int(fields[1]),
                gas=_to_int(fields[2]),
                to=fields[3],
                value=_to_int(fields[4]),
                data=fields[5],
                v=_to_int(fields[6]),
                r=_to_int(fields[7]),
                s=_to_int(fields[8]),
            ),
            rest,
        )

    def chain_id(self) -> int:
        """EIP-155 chain id from v (0 for pre-155 txs)."""
        if self.v in (27, 28):
            return 0
        return (self.v - 35) // 2

    def signing_hash(self) -> bytes:
        cid = self.chain_id()
        if cid == 0:
            payload = [
                self.nonce, self.gas_price, self.gas,
                self.to, self.value, self.data,
            ]
        else:
            payload = [
                self.nonce, self.gas_price, self.gas,
                self.to, self.value, self.data, cid, 0, 0,
            ]
        return keccak256(rlp_encode(payload))

    def hash(self) -> bytes:
        return keccak256(self.encode())

    def sender(self) -> Optional[bytes]:
        """Recover the 20-byte sender address (reference GetSender,
        transaction.go:11)."""
        if self.v in (27, 28):
            rec_id = self.v - 27
        else:
            rec_id = (self.v - 35) % 2
        sig65 = (
            self.r.to_bytes(32, "big")
            + self.s.to_bytes(32, "big")
            + bytes([rec_id])
        )
        return secp256k1.eth_recover_address(self.signing_hash(), sig65)

    def sign(self, secret: int, chain_id: int) -> None:
        """EIP-155 sign in place."""
        self.v = 35 + 2 * chain_id  # placeholder for hash computation
        payload = [
            self.nonce, self.gas_price, self.gas,
            self.to, self.value, self.data, chain_id, 0, 0,
        ]
        digest = keccak256(rlp_encode(payload))
        sig = secp256k1.eth_sign(digest, secret)
        self.r = int.from_bytes(sig[:32], "big")
        self.s = int.from_bytes(sig[32:64], "big")
        self.v = 35 + 2 * chain_id + sig[64]


def encode_transactions(txs: list[LegacyTx]) -> bytes:
    """Concatenated RLP (reference EncodeTransactions, util.go:22)."""
    return b"".join(tx.encode() for tx in txs)


def decode_txs(data: bytes) -> list[LegacyTx]:
    """Parse concatenated RLP txs (reference DecodeTxs, util.go:116)."""
    out = []
    while data:
        tx, data = LegacyTx.decode(data)
        out.append(tx)
    return out
