------------------------- MODULE ConsensusSafety -------------------------
(***************************************************************************)
(* Safety core of the consensus voting rules as implemented by             *)
(* tendermint_tpu/consensus/state_machine.py: POL locking (:660-725),      *)
(* the 2/3 precommit commit rule, and unlock-on-higher-POL.                *)
(*                                                                         *)
(* Reference counterpart: spec/consensus/consensus-paper/ (the arXiv       *)
(* algorithm) + spec/ivy-proofs/.  This module re-states the two safety    *)
(* invariants the implementation relies on; it is NOT a full protocol      *)
(* model (timeouts and the proposer function are abstracted away — they    *)
(* affect liveness, not safety).                                           *)
(*                                                                         *)
(* Status: machine-checked in CI. tests/test_model_safety.py explores     *)
(* the full reachable space of the 4-validator/3-round/2-value instance   *)
(* with an explicit-state BFS (no TLC/Apalache in the image) and asserts  *)
(* Agreement; the NoLaterVotes guard below was ADDED because that check   *)
(* found a genuine violation in the module as first written.              *)
(***************************************************************************)

EXTENDS Integers, FiniteSets

CONSTANTS
  VALIDATORS,   \* identities, equal voting power (power sums abstract to counts)
  FAULTY,       \* byzantine subset; < 1/3 assumed for the theorems
  ROUNDS,       \* 0..Rmax
  VALUES        \* proposable block values (+ Nil below)

Nil == CHOOSE v : v \notin VALUES

Honest == VALIDATORS \ FAULTY

TwoThirds(S) == 3 * Cardinality(S) > 2 * Cardinality(VALIDATORS)

VARIABLES
  prevotes,    \* [ROUNDS -> [VALIDATORS -> VALUES \union {Nil}]] partial
  precommits,  \* same shape
  locked       \* [VALIDATORS -> [val: VALUES \union {Nil}, round: Int]]

vars == <<prevotes, precommits, locked>>

NoVote == CHOOSE v : v \notin VALUES \union {Nil}

Init ==
  /\ prevotes   = [r \in ROUNDS |-> [v \in VALIDATORS |-> NoVote]]
  /\ precommits = [r \in ROUNDS |-> [v \in VALIDATORS |-> NoVote]]
  /\ locked     = [v \in VALIDATORS |-> [val |-> Nil, round |-> -1]]

PolkaAt(r, val) ==
  TwoThirds({v \in VALIDATORS : prevotes[r][v] = val})

(***************************************************************************)
(* Honest-validator rules (state_machine.py):                              *)
(*  - a locked validator prevotes only its lock, unless a polka at a      *)
(*    higher round releases it (_enter_prevote + POL unlock :660-725);    *)
(*  - precommit val at r only on a polka for val at r (_on_prevote_added);*)
(*  - precommitting sets the lock to (val, r).                            *)
(* Faulty validators vote arbitrarily (including equivocation, modeled    *)
(* by overwriting).                                                        *)
(***************************************************************************)

(* Round monotonicity: an honest validator participates in increasing   *)
(* rounds (state_machine.py advances rs.round monotonically within a    *)
(* height).  This is a SAFETY-relevant guard, not a liveness detail:    *)
(* without it the r4 machine check (tests/test_model_safety.py) finds a *)
(* genuine Agreement violation — an honest validator prevotes val B at  *)
(* round 1 BEFORE acting in round 0, then locks A at round 0; the       *)
(* round-1 polka for B later satisfies the unlock guard of a second     *)
(* A-locked validator, and B reaches quorum at round 2 while A's        *)
(* round-0 decision stands.                                             *)
NoLaterVotes(v, r) ==
  \A r2 \in ROUNDS : r2 > r =>
    prevotes[r2][v] = NoVote /\ precommits[r2][v] = NoVote

HonestPrevote(v, r, val) ==
  /\ v \in Honest
  /\ prevotes[r][v] = NoVote
  /\ NoLaterVotes(v, r)
  /\ \/ locked[v].val = Nil
     \/ locked[v].val = val
     \/ \E pr \in ROUNDS :
          pr > locked[v].round /\ pr < r /\ PolkaAt(pr, val)
  /\ prevotes' = [prevotes EXCEPT ![r][v] = val]
  /\ UNCHANGED <<precommits, locked>>

HonestPrecommit(v, r, val) ==
  /\ v \in Honest
  /\ precommits[r][v] = NoVote
  /\ NoLaterVotes(v, r)
  /\ val \in VALUES => PolkaAt(r, val)
  /\ precommits' = [precommits EXCEPT ![r][v] = val]
  /\ locked' =
       IF val \in VALUES
       THEN [locked EXCEPT ![v] = [val |-> val, round |-> r]]
       ELSE locked
  /\ UNCHANGED prevotes

ByzantineVote(v, r, val) ==
  /\ v \in FAULTY
  /\ \/ prevotes'   = [prevotes EXCEPT ![r][v] = val] /\ UNCHANGED precommits
     \/ precommits' = [precommits EXCEPT ![r][v] = val] /\ UNCHANGED prevotes
  /\ UNCHANGED locked

Next ==
  \E v \in VALIDATORS, r \in ROUNDS, val \in VALUES \union {Nil} :
    HonestPrevote(v, r, val) \/ HonestPrecommit(v, r, val)
      \/ ByzantineVote(v, r, val)

Spec == Init /\ [][Next]_vars

(***************************************************************************)
(* Theorems (the invariants state_machine.py's commit rule rests on)       *)
(***************************************************************************)

Decided(r, val) ==
  val \in VALUES /\ TwoThirds({v \in VALIDATORS : precommits[r][v] = val})

FaultAssumption == 3 * Cardinality(FAULTY) < Cardinality(VALIDATORS)

(* Agreement: two decisions — at any rounds — are for the same value.     *)
(* The quorum-intersection argument: two 2/3 quorums share an honest      *)
(* validator, whose lock forces later prevotes.                           *)
Agreement ==
  FaultAssumption =>
    \A r1, r2 \in ROUNDS, v1, v2 \in VALUES :
      (Decided(r1, v1) /\ Decided(r2, v2)) => v1 = v2

(* No honest equivocation: an honest validator casts at most one prevote  *)
(* and one precommit per round (vote_set.py ConflictingVoteError guards   *)
(* this at the wire; here it is structural — votes are never overwritten  *)
(* for honest v).                                                          *)
HonestNoEquivocation ==
  \A v \in Honest, r \in ROUNDS :
    /\ prevotes[r][v] # NoVote => prevotes'[r][v] = prevotes[r][v]
    /\ precommits[r][v] # NoVote => precommits'[r][v] = precommits[r][v]

=============================================================================
