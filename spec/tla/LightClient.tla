--------------------------- MODULE LightClient ---------------------------
(***************************************************************************)
(* Light-client skipping verification (bisection), as implemented by       *)
(* tendermint_tpu/light/client.py and verifier.py.                         *)
(*                                                                         *)
(* Reference counterpart: spec/light-client/verification/                  *)
(* Lightclient_003_draft.tla in the reference repo (re-specified from the  *)
(* implementation here, not copied).                                       *)
(*                                                                         *)
(* The model makes FORGERY representable: the attacker may present, at any *)
(* height, a fake header with an arbitrary validator set, carrying         *)
(* signatures only from FAULTY validators (honest validators sign only the *)
(* real chain's header at their height).  Soundness = the client never     *)
(* stores a fake header.  The r4 machine check                             *)
(* (tests/test_model_light.py) explores this module's 4-height/4-validator *)
(* instance exhaustively and validates itself by re-finding the known      *)
(* attacks when the next-validators continuity check, the 1/3-of-trusted   *)
(* check, or the <1/3-faulty assumption is dropped.                        *)
(***************************************************************************)

EXTENDS Integers, FiniteSets

CONSTANTS
  HEIGHTS,        \* chain heights, e.g. 1..Hmax
  VALIDATORS,     \* universe of validator identities
  FAULTY,         \* subset of VALIDATORS that may equivocate/forge
  ROOT            \* the subjectively trusted initial height

ASSUME ROOT \in HEIGHTS

(* The real chain: per height, the committed validator set and the       *)
(* next-validators commitment.                                            *)
CONSTANTS ChainVals, ChainNextVals
ASSUME ChainVals \in [HEIGHTS -> SUBSET VALIDATORS]
ASSUME ChainNextVals \in [HEIGHTS -> SUBSET VALIDATORS]

(* A header the client may be shown: the real one at h, or a forgery     *)
(* with attacker-chosen validator sets.                                   *)
Headers ==
  [kind: {"real"}, h: HEIGHTS]
    \union
  [kind: {"fake"}, h: HEIGHTS, vals: SUBSET VALIDATORS,
   nextVals: SUBSET VALIDATORS]

HVals(hd) ==
  IF hd.kind = "real" THEN ChainVals[hd.h] ELSE hd.vals
HNextVals(hd) ==
  IF hd.kind = "real" THEN ChainNextVals[hd.h] ELSE hd.nextVals

(* Who can sign header hd: honest validators sign ONLY the real header   *)
(* at their height; faulty ones sign anything.                            *)
Signers(hd) ==
  IF hd.kind = "real"
  THEN SUBSET (ChainVals[hd.h] \union FAULTY)
  ELSE SUBSET FAULTY

TwoThirds(S, Of) == 3 * Cardinality(S \intersect Of) > 2 * Cardinality(Of)
OneThird(S, Of)  == 3 * Cardinality(S \intersect Of) >= Cardinality(Of)

VARIABLES
  trustedStore    \* set of Headers the client has accepted

vars == <<trustedStore>>

(* Time is elided: for SOUNDNESS the trusting period only removes        *)
(* verification capability, so "always inside the period" is the         *)
(* attack-maximal over-approximation.  (Expiry matters for liveness,     *)
(* which this module does not claim.)                                    *)

(* verify_adjacent (light/verifier.py): h -> h+1 requires the new        *)
(* header's validator set to MATCH the trusted header's next-validators  *)
(* commitment (hash continuity), plus 2/3 of that set signing.           *)
AdjacentOK(th, nh) ==
  /\ nh.h = th.h + 1
  /\ HVals(nh) = HNextVals(th)
  /\ \E s \in Signers(nh) : TwoThirds(s, HVals(nh))

(* verify_non_adjacent (skipping): 1/3 of the TRUSTED header's next      *)
(* validators must have signed the new header (trust intersection), plus *)
(* 2/3 of the new header's own set (light/verifier.py; reference         *)
(* verifier.go:58).                                                      *)
NonAdjacentOK(th, nh) ==
  /\ nh.h > th.h + 1
  /\ \E s \in Signers(nh) :
       /\ OneThird(s, HNextVals(th))
       /\ TwoThirds(s, HVals(nh))

Init == trustedStore = {[kind |-> "real", h |-> ROOT]}

VerifyStep ==
  \E th \in trustedStore, nh \in Headers :
    /\ nh \notin trustedStore
    /\ AdjacentOK(th, nh) \/ NonAdjacentOK(th, nh)
    /\ trustedStore' = trustedStore \union {nh}

Spec == Init /\ [][VerifyStep]_vars

(* Failure model: faulty validators are a minority below 1/3 in every    *)
(* validator set the real chain committed.                               *)
FaultAssumption ==
  \A h \in HEIGHTS :
    /\ 3 * Cardinality(FAULTY \intersect ChainVals[h])
         < Cardinality(ChainVals[h])
    /\ 3 * Cardinality(FAULTY \intersect ChainNextVals[h])
         < Cardinality(ChainNextVals[h])

(* Soundness: every stored header is the real chain's header.            *)
StoreSound ==
  FaultAssumption => \A hd \in trustedStore : hd.kind = "real"

=============================================================================
