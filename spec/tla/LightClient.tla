--------------------------- MODULE LightClient ---------------------------
(***************************************************************************)
(* Light-client skipping verification (bisection), as implemented by       *)
(* tendermint_tpu/light/client.py and verifier.py.                         *)
(*                                                                         *)
(* Reference counterpart: spec/light-client/verification/                  *)
(* Lightclient_003_draft.tla in the reference repo (re-specified from the  *)
(* implementation here, not copied).  The property of interest is the      *)
(* core soundness argument: if every header the client stores was either   *)
(* (a) the trusted root or (b) accepted by ValidAndVerified against an     *)
(* already-stored header inside the trusting period, then — under the      *)
(* failure model that less than 1/3 of any validator set the client        *)
(* trusts is faulty — every stored header is a header the main chain      *)
(* actually produced.                                                      *)
(*                                                                         *)
(* Status: syntax-complete TLA+, NOT model-checked in this build           *)
(* environment (no TLC/Apalache in the image — see spec/tla/README.md).    *)
(***************************************************************************)

EXTENDS Integers, FiniteSets

CONSTANTS
  HEIGHTS,        \* set of chain heights, e.g. 1..Hmax
  VALIDATORS,     \* universe of validator identities
  FAULTY,         \* subset of VALIDATORS that may equivocate/forge
  TRUSTING_PERIOD,\* duration (abstract time units)
  TARGET          \* the height the client wants

ASSUME TARGET \in HEIGHTS

(* The real chain: one header per height; abstracted as the validator    *)
(* sets and times the honest chain committed.                            *)
CONSTANTS ChainVals, ChainNextVals, ChainTime
ASSUME ChainVals \in [HEIGHTS -> SUBSET VALIDATORS]
ASSUME ChainNextVals \in [HEIGHTS -> SUBSET VALIDATORS]
ASSUME ChainTime \in [HEIGHTS -> Nat]

VARIABLES
  now,            \* wall-clock time at the client
  trustedStore,   \* set of heights the client has accepted
  state           \* "working" | "finishedSuccess" | "finishedFail"

vars == <<now, trustedStore, state>>

(***************************************************************************)
(* Header/commit abstraction.  A commit for height h carries signatures    *)
(* from a set of validators; honest validators only sign the real chain's  *)
(* header at h, so a forged header's signers are a subset of FAULTY.       *)
(***************************************************************************)

\* voting power abstracted to cardinality (the implementation sums powers;
\* types/validator_set.py:253-)
TwoThirds(S, Of) == 3 * Cardinality(S) > 2 * Cardinality(Of)
OneThird(S, Of)  == 3 * Cardinality(S) >= Cardinality(Of)

InTrustingPeriod(h) == now < ChainTime[h] + TRUSTING_PERIOD

(* verify_adjacent (light/verifier.py): sequential step h -> h+1 checks   *)
(* next_validators_hash continuity + 2/3 of the NEW header's own set.     *)
AdjacentOK(th, nh) ==
  /\ nh = th + 1
  /\ InTrustingPeriod(th)
  /\ \E signers \in SUBSET (ChainVals[nh] \union FAULTY) :
        TwoThirds(signers \intersect ChainVals[nh], ChainVals[nh])

(* verify_non_adjacent (skipping): 1/3 of the TRUSTED set must have      *)
(* signed the new header (the trust intersection), plus 2/3 of the new   *)
(* header's own set (light/verifier.py; reference verifier.go:58).       *)
NonAdjacentOK(th, nh) ==
  /\ nh > th + 1
  /\ InTrustingPeriod(th)
  /\ \E signers \in SUBSET (ChainVals[nh] \union FAULTY) :
        /\ OneThird(signers \intersect ChainNextVals[th], ChainNextVals[th])
        /\ TwoThirds(signers \intersect ChainVals[nh], ChainVals[nh])

(***************************************************************************)
(* Transitions                                                             *)
(***************************************************************************)

Init ==
  /\ now \in Nat
  /\ trustedStore = {CHOOSE h \in HEIGHTS : TRUE}  \* the subjective root
  /\ state = "working"

VerifyStep ==
  /\ state = "working"
  /\ \E th \in trustedStore, nh \in HEIGHTS :
       /\ nh \notin trustedStore
       /\ AdjacentOK(th, nh) \/ NonAdjacentOK(th, nh)
       /\ trustedStore' = trustedStore \union {nh}
  /\ UNCHANGED <<now, state>>

AdvanceTime ==
  /\ now' \in {t \in Nat : t > now}
  /\ UNCHANGED <<trustedStore, state>>

Finish ==
  /\ state = "working"
  /\ \/ /\ TARGET \in trustedStore
        /\ state' = "finishedSuccess"
     \/ /\ \A th \in trustedStore : ~InTrustingPeriod(th)
        /\ state' = "finishedFail"
  /\ UNCHANGED <<now, trustedStore>>

Next == VerifyStep \/ AdvanceTime \/ Finish

Spec == Init /\ [][Next]_vars

(***************************************************************************)
(* Properties                                                              *)
(***************************************************************************)

(* Failure model: in any set the client relies on, faulty validators are  *)
(* less than 1/3 (the standard Tendermint assumption within the trusting  *)
(* period).                                                                *)
FaultAssumption ==
  \A h \in HEIGHTS :
    3 * Cardinality(FAULTY \intersect ChainVals[h])
      < Cardinality(ChainVals[h])

(* Soundness: a forged header (one whose honest signers are empty) can    *)
(* only be accepted if FAULTY alone musters the required thresholds —     *)
(* excluded by FaultAssumption.  Stated as: every stored height's         *)
(* accepting signer set contained at least one honest validator of the    *)
(* real chain's set for that height.                                      *)
StoreSound ==
  FaultAssumption =>
    \A h \in trustedStore :
      \E v \in ChainVals[h] \ FAULTY : TRUE

(* Termination-shape liveness (checked under fairness of VerifyStep):     *)
(* the client either reaches TARGET or runs out of trusting period.       *)
EventuallyDone == <>(state # "working")

=============================================================================
