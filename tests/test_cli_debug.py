"""CLI commands (rewind/compact/reindex-event/replay/testnet) + the
pprof debug server (reference cmd/tendermint + node.go:969-975)."""

import asyncio
import json
import os

from tendermint_tpu.__main__ import main
from tendermint_tpu.node.node import Node, init_files

from .test_node import make_test_config


def _run_chain(tmp_path, heights=3, **cfg_kw):
    cfg = make_test_config(tmp_path, **cfg_kw)
    cfg.base.db_backend = "sqlite"  # the CLI operates on on-disk stores
    init_files(cfg)
    cfg.save()
    node = Node(cfg)

    async def run():
        await node.start()
        await node.consensus.wait_for_height(heights, timeout=60)
        await node.stop()

    asyncio.run(run())
    return cfg


def test_rewind_compact_reindex_replay(tmp_path, capsys):
    cfg = _run_chain(tmp_path, heights=4)
    home = ["--home", str(tmp_path)]

    # replay prints WAL records
    assert main(home + ["replay"]) == 0
    out = capsys.readouterr().out
    assert "replayed" in out and "WAL records" in out

    # reindex rebuilds the tx index from stored blocks
    assert main(home + ["reindex-event"]) == 0
    assert "reindexed heights" in capsys.readouterr().out

    # compact VACUUMs the stores
    assert main(home + ["compact"]) == 0
    assert "blockstore.db" in capsys.readouterr().out

    # rewind drops back to height 2
    assert main(home + ["rewind", "--height", "2"]) == 0
    assert "rewound to height 2" in capsys.readouterr().out
    from tendermint_tpu.store.block_store import BlockStore
    from tendermint_tpu.store.kv import SqliteKV

    bs = BlockStore(SqliteKV(os.path.join(cfg.db_dir, "blockstore.db")))
    assert bs.height == 2


def test_testnet_files(tmp_path, capsys):
    out_dir = str(tmp_path / "net")
    assert main(
        ["--home", str(tmp_path), "testnet", "--v", "3", "--output", out_dir]
    ) == 0
    for i in range(3):
        home = os.path.join(out_dir, f"node{i}")
        assert os.path.exists(os.path.join(home, "config", "genesis.json"))
        assert os.path.exists(os.path.join(home, "config", "config.toml"))
    # all genesis docs identical
    docs = {
        open(os.path.join(out_dir, f"node{i}", "config", "genesis.json"))
        .read()
        for i in range(3)
    }
    assert len(docs) == 1


def test_debug_server_endpoints(tmp_path):
    cfg = make_test_config(tmp_path)
    cfg.rpc.pprof_laddr = "tcp://127.0.0.1:0"
    init_files(cfg)
    node = Node(cfg)

    async def fetch(port, path):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
        await writer.drain()
        data = await reader.read()
        writer.close()
        return data

    async def run():
        await node.start()
        await node.consensus.wait_for_height(2, timeout=60)
        port = node.debug_server.port

        dump = await fetch(port, "/debug/pprof/goroutine")
        assert b"200 OK" in dump
        assert b"consensus/receive" in dump or b"thread" in dump

        heap = await fetch(port, "/debug/pprof/heap")
        assert b"200 OK" in heap

        prof = await fetch(port, "/debug/pprof/profile?seconds=0.2")
        assert b"200 OK" in prof and b"cumulative" in prof

        bad = await fetch(port, "/debug/nope")
        assert b"500" in bad

        await node.stop()

    asyncio.run(run())


def test_abci_cli_against_kvstore_server():
    """abci-cli parity (reference abci/cmd/abci-cli): spawn the kvstore
    app server as a SEPARATE process, drive echo/deliver_tx/commit/query
    through the CLI over the socket protocol."""
    import socket as socket_mod
    import subprocess
    import sys
    import time

    s = socket_mod.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    srv = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "tendermint_tpu",
            "abci-cli",
            "kvstore",
            "--port",
            str(port),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
    )
    try:
        # wait for the listening line (bounded: readline on a pipe blocks
        # forever if the server hangs pre-print)
        import select

        ready, _, _ = select.select([srv.stdout], [], [], 60)
        assert ready, "kvstore server never printed its listening line"
        line = srv.stdout.readline().decode()
        assert "listening" in line, line

        def cli(*args):
            return subprocess.run(
                [
                    sys.executable,
                    "-m",
                    "tendermint_tpu",
                    "abci-cli",
                    *args,
                    "--port",
                    str(port),
                ],
                capture_output=True,
                timeout=60,
            )

        r = cli("echo", "hello-abci")
        assert r.returncode == 0 and b"hello-abci" in r.stdout
        r = cli("deliver_tx", "mykey=myvalue")
        assert r.returncode == 0 and b"code=0" in r.stdout
        r = cli("commit")
        assert r.returncode == 0 and b"data=0x" in r.stdout
        r = cli("query", "mykey")
        assert r.returncode == 0 and b"myvalue" in r.stdout
        r = cli("info")
        assert r.returncode == 0 and b"kvstore" in r.stdout
    finally:
        srv.kill()
        srv.wait(timeout=10)


def test_loadtime_run_and_report():
    """tools/loadtime parity (reference test/loadtime + runner/benchmark.go):
    burst load through the L2 feed, then a latency/interval report read
    back from the block store."""
    import asyncio
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "loadtime",
        os.path.join(os.path.dirname(__file__), "..", "tools", "loadtime.py"),
    )
    lt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lt)

    rep = asyncio.run(lt.run_load(blocks=4, rate=10))
    assert rep["blocks"] >= 4
    assert rep["txs"] >= 30
    assert rep["tx_per_s"] > 0
    assert rep["tx_latency_ms"]["avg"] > 0
    assert rep["block_interval_s"]["max"] >= rep["block_interval_s"]["min"]
    # tx round-trip helpers
    tx = lt.make_tx(7)
    assert lt.parse_tx_time(tx) is not None
    assert lt.parse_tx_time(b"garbage") is None
