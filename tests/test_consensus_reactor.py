"""Consensus over real p2p: switches + reactors + encrypted transport."""

import asyncio

import pytest

from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.node_info import NodeInfo
from tendermint_tpu.p2p.switch import Switch
from tendermint_tpu.p2p.transport import MultiplexTransport, NetAddress

from .helpers import make_genesis, make_validators
from .test_consensus import make_node

NETWORK = "reactor-chain"


def build_p2p_node(vs, pv, genesis, **node_kwargs):
    cs, app, l2, bs, ss = make_node(vs, pv, genesis, **node_kwargs)
    nk = NodeKey.generate()
    transport = None
    sw = None

    def node_info():
        return NodeInfo(
            node_id=nk.id,
            listen_addr=f"127.0.0.1:{transport.listen_port}",
            network=NETWORK,
            channels=sw.channels() if sw else b"",
        )

    transport = MultiplexTransport(nk, node_info)
    sw = Switch(transport)
    reactor = ConsensusReactor(cs)
    sw.add_reactor("consensus", reactor)
    return cs, nk, transport, sw


async def connect_full_mesh(nodes):
    for i, (_, nk_i, t_i, sw_i) in enumerate(nodes):
        for j, (_, nk_j, t_j, sw_j) in enumerate(nodes):
            if j <= i:
                continue
            await sw_i.dial_peer(
                NetAddress(nk_j.id, "127.0.0.1", t_j.listen_port)
            )


def test_consensus_over_p2p():
    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)

    async def run():
        nodes = [build_p2p_node(vs, pv, genesis) for pv in pvs]
        for cs, nk, t, sw in nodes:
            await t.listen()
            await sw.start()
        await connect_full_mesh(nodes)
        for cs, *_ in nodes:
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(3, timeout=60) for cs, *_ in nodes)
        )
        hashes = {
            cs.block_store.load_block(3).hash() for cs, *_ in nodes
        }
        assert len(hashes) == 1, "nodes disagree over p2p"
        for cs, nk, t, sw in nodes:
            await cs.stop()
            await sw.stop()

    asyncio.run(run())


def test_late_node_catches_up_via_gossip():
    """Node 3 joins after the net reached height 3; the reactor's catchup
    gossip (block parts from the store + reconstructed commit votes) must
    bring it to the current height (reference gossipDataRoutine :628)."""
    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)

    async def run():
        nodes = [build_p2p_node(vs, pv, genesis) for pv in pvs]
        early = nodes[:3]
        late = nodes[3]
        for cs, nk, t, sw in early:
            await t.listen()
            await sw.start()
        await connect_full_mesh(early)
        for cs, *_ in early:
            await cs.start()
        await asyncio.gather(
            *(cs.wait_for_height(3, timeout=60) for cs, *_ in early)
        )
        # now bring up the late node and connect it
        cs_l, nk_l, t_l, sw_l = late
        await t_l.listen()
        await sw_l.start()
        for _, nk, t, sw in early:
            await sw_l.dial_peer(NetAddress(nk.id, "127.0.0.1", t.listen_port))
        await cs_l.start()
        await cs_l.wait_for_height(3, timeout=60)
        assert cs_l.state.last_block_height >= 3
        b3_late = cs_l.block_store.load_block(3)
        b3_early = early[0][0].block_store.load_block(3)
        assert b3_late.hash() == b3_early.hash()
        for cs, nk, t, sw in nodes:
            await cs.stop()
            await sw.stop()

    asyncio.run(run())


def test_batch_point_bls_over_p2p_uses_aggregate_batcher():
    """4-validator net over real encrypted p2p with every 2nd block a
    batch point: precommits carry real BLS12-381 dual-signatures, the
    REACTOR's aggregate micro-batcher pre-verifies them (2 pairings per
    burst — consensus/bls_batcher.py), and every node's L2 receives
    CommitBatch with >=2/3 BLS data."""
    from tendermint_tpu.crypto import bls_signatures as bls
    from tendermint_tpu.l2node.mock import MockL2Node

    from .test_consensus import _bls_setup

    vs, pvs = make_validators(4)
    genesis = make_genesis(vs)
    registry, signers = _bls_setup(pvs)

    async def run():
        nodes = []
        for pv, signer in zip(pvs, signers):
            l2 = MockL2Node(
                batch_blocks_interval=2,
                bls_verifier=registry.verifier(),
                bls_batch_verifier=registry.batch_verifier(),
            )
            nodes.append(
                build_p2p_node(vs, pv, genesis, l2=l2, bls_signer=signer)
            )
        for cs, nk, t, sw in nodes:
            await t.listen()
            await sw.start()
        await connect_full_mesh(nodes)
        for cs, *_ in nodes:
            await cs.start()
        # height 2 is the first batch point (interval=2)
        await asyncio.gather(
            *(cs.wait_for_height(3, timeout=120) for cs, *_ in nodes)
        )
        batcher_batches = [
            list(sw.reactors["consensus"].bls_batcher.batch_sizes)
            for _, _, _, sw in nodes
        ]
        for cs, nk, t, sw in nodes:
            await cs.stop()
            await sw.stop()

        # every node's L2 committed the batch with >=2/3 BLS signatures
        for cs, *_ in nodes:
            assert cs.l2.committed_batches, "no batch committed"
            batch_hash, bls_datas = cs.l2.committed_batches[0]
            assert len(bls_datas) >= 3
            pubs, sigs = [], []
            for d in bls_datas:
                _, val = cs.state.validators.get_by_address(d.signer)
                pubs.append(registry._by_tm[bytes(val.pub_key.data)])
                sigs.append(bls.g1_from_bytes(d.signature))
            assert bls.verify_aggregated_same_message(
                bls.aggregate_signatures(sigs), batch_hash, pubs
            )
        # the aggregate path actually ran: some reactor batched BLS checks
        assert any(b for b in batcher_batches), (
            "no BLS verifications went through the reactor micro-batcher"
        )

    asyncio.run(run())
