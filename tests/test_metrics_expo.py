"""Metrics exposition golden tests: Prometheus text format v0.0.4
validity (HELP/TYPE ordering, cumulative `le` monotonicity, label-value
escaping), the Registry kind-collision guard, labeled histograms, and
the /metrics server's path/verb handling."""

import os
import asyncio
import re

import pytest

from tendermint_tpu.libs.metrics import (
    Counter,
    Gauge,
    HealthMetrics,
    Histogram,
    MetricsServer,
    ProcessMetrics,
    Registry,
)

pytestmark = pytest.mark.obs


# --- registry kind collisions ---------------------------------------------


def test_registry_kind_collision_raises():
    reg = Registry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")
    with pytest.raises(TypeError):
        reg.histogram("x")


def test_registry_gauge_then_counter_raises():
    # Gauge subclasses Counter: an isinstance check would wrongly allow
    # counter("y") to return the Gauge
    reg = Registry()
    reg.gauge("y")
    with pytest.raises(TypeError):
        reg.counter("y")


def test_registry_same_kind_returns_same_object():
    reg = Registry()
    c1 = reg.counter("z", "help")
    c2 = reg.counter("z")
    assert c1 is c2
    h1 = reg.histogram("hh", labels=("step",))
    assert reg.histogram("hh") is h1


# --- exposition format -----------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.e+infINF]+$"
)


def _build_golden_registry() -> Registry:
    reg = Registry(namespace="tm")
    c = reg.counter("requests_total", "Requests", labels=("method",))
    c.inc(3, method="status")
    c.inc(method='we"ird\\path\nx')  # exercises label escaping
    g = reg.gauge("height", "Height")
    g.set(42)
    h = reg.histogram(
        "step_seconds",
        "Step durations",
        buckets=(0.1, 1.0, float("inf")),
        labels=("step",),
    )
    h.observe(0.05, step="propose")
    h.observe(0.5, step="propose")
    h.observe(2.0, step="prevote")
    return reg


def test_exposition_help_type_ordering_and_samples():
    body = _build_golden_registry().render()
    lines = body.strip().splitlines()
    seen_types: dict[str, str] = {}
    current = None
    for ln in lines:
        if ln.startswith("# HELP "):
            current = ln.split()[2]
            # HELP must precede TYPE for each metric family
            assert current not in seen_types
        elif ln.startswith("# TYPE "):
            name, kind = ln.split()[2:4]
            assert name == current, "TYPE must follow its HELP line"
            seen_types[name] = kind
        else:
            # sample lines parse and belong to an announced family
            assert _SAMPLE_RE.match(ln), f"unparseable sample: {ln!r}"
            base = ln.split("{")[0].split(" ")[0]
            family = re.sub(r"_(bucket|sum|count)$", "", base)
            assert family in seen_types or base in seen_types
    assert seen_types == {
        "tm_requests_total": "counter",
        "tm_height": "gauge",
        "tm_step_seconds": "histogram",
    }


def test_exposition_label_escaping():
    body = _build_golden_registry().render()
    assert 'method="we\\"ird\\\\path\\nx"' in body
    # no raw newline may survive inside a label value
    for ln in body.splitlines():
        assert not ln.endswith("\\")


def test_histogram_le_cumulative_monotonic():
    body = _build_golden_registry().render()
    # collect bucket counts per label-series, in render order
    series: dict[str, list[float]] = {}
    for ln in body.splitlines():
        m = re.match(r"tm_step_seconds_bucket\{step=\"(\w+)\",le=\"([^\"]+)\"\} (\S+)", ln)
        if m:
            series.setdefault(m.group(1), []).append(float(m.group(3)))
    assert set(series) == {"propose", "prevote"}
    for name, counts in series.items():
        assert counts == sorted(counts), f"{name} buckets not cumulative"
    # +Inf bucket equals _count
    assert series["propose"][-1] == 2
    assert series["prevote"][-1] == 1
    assert "tm_step_seconds_count{step=\"propose\"} 2" in body
    assert "tm_step_seconds_sum{step=\"propose\"} 0.55" in body


def test_labeled_histogram_counts():
    h = Histogram("h", "", buckets=(1, float("inf")), labels=("step",))
    h.observe(0.5, step="a")
    h.observe(0.5, step="a")
    h.observe(3.0, step="b")
    assert h.count(step="a") == 2
    assert h.count(step="b") == 1
    assert h.total_count() == 3
    with h.time(step="a"):
        pass
    assert h.count(step="a") == 3


def test_unlabeled_histogram_renders_zero_buckets():
    h = Histogram("h", "help", buckets=(1, float("inf")))
    out = h.render()
    assert 'h_bucket{le="1"} 0' in out
    assert "h_count 0" in out


# --- /metrics server -------------------------------------------------------


async def _http(port: int, request: bytes) -> bytes:
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(request)
    await writer.drain()
    data = await reader.read(1 << 20)
    writer.close()
    return data


def test_metrics_server_paths_and_verbs():
    reg = _build_golden_registry()

    async def run():
        srv = MetricsServer(reg, "127.0.0.1", 0)
        await srv.start()
        try:
            ok = await _http(
                srv.port, b"GET /metrics HTTP/1.1\r\nHost: m\r\n\r\n"
            )
            nf = await _http(
                srv.port, b"GET /other HTTP/1.1\r\nHost: m\r\n\r\n"
            )
            head = await _http(
                srv.port, b"HEAD /metrics HTTP/1.1\r\nHost: m\r\n\r\n"
            )
            post = await _http(
                srv.port, b"POST /metrics HTTP/1.1\r\nHost: m\r\n\r\n"
            )
            return ok, nf, head, post
        finally:
            await srv.stop()

    ok, nf, head, post = asyncio.run(run())
    assert ok.startswith(b"HTTP/1.1 200") and b"tm_height 42" in ok
    assert nf.startswith(b"HTTP/1.1 404")
    assert b"tm_height" not in nf
    # HEAD: headers with the real content length, no body
    assert head.startswith(b"HTTP/1.1 200")
    headers, _, body = head.partition(b"\r\n\r\n")
    assert body == b""
    clen = int(
        [h for h in headers.split(b"\r\n") if h.lower().startswith(
            b"content-length")][0].split(b":")[1]
    )
    assert clen == len(reg.render().encode())
    assert post.startswith(b"HTTP/1.1 405")


def test_counter_total_across_series():
    c = Counter("c", "", labels=("klass",))
    c.inc(3, klass="a")
    c.inc(4, klass="b")
    assert c.total() == 7
    assert Counter("e", "", labels=("k",)).total() == 0


def test_histogram_series_snapshot():
    # the health monitor reads interval DELTAS of these snapshots to
    # turn a histogram into an SLO event stream
    h = Histogram("h", "", buckets=(0.1, 1.0, float("inf")),
                  labels=("step",))
    h.observe(0.05, step="a")
    h.observe(0.5, step="a")
    h.observe(2.0, step="a")
    s = h.series(step="a")
    assert s["count"] == 3
    assert s["sum"] == pytest.approx(2.55)
    assert tuple(s["buckets"]) == (0.1, 1.0, float("inf"))
    assert s["counts"] == [1, 2, 3]  # cumulative per bucket
    empty = h.series(step="missing")
    assert empty["count"] == 0 and empty["counts"] == [0, 0, 0]


# --- process-level gauges + health gauges (raw-name families) ---------------


def test_process_metrics_exposition_golden():
    """RSS / open-fd / thread gauges render under their conventional
    prometheus process_* names (NO tm_ namespace prefix — dashboards
    key on the convention), refresh at scrape time via the registry
    collector, and the event-loop-lag histogram rides the same raw
    namespace."""
    reg = Registry(namespace="tm")
    pm = ProcessMetrics(reg)
    body = reg.render()
    for family, kind in (
        ("process_resident_memory_bytes", "gauge"),
        ("process_open_fds", "gauge"),
        ("process_threads", "gauge"),
        ("tm_event_loop_lag_seconds", "histogram"),
    ):
        assert f"# TYPE {family} {kind}" in body, family
        assert f"tm_{family}" not in body  # raw: no namespace prefix
    # the collector refreshed the point-in-time reads at render
    assert pm.threads.value() >= 1
    assert pm.rss_bytes.value() > 0
    if os.path.isdir("/proc/self/fd"):
        # fd counting is /proc-backed and best-effort elsewhere
        assert pm.open_fds.value() > 0
    pm.event_loop_lag.observe(0.03)
    assert 'tm_event_loop_lag_seconds_bucket{le="0.05"} 1' in reg.render()


def test_registry_collector_errors_are_dropped():
    # /metrics must not 500 because a collector broke
    reg = Registry(namespace="tm")
    g = reg.gauge("x", "")
    g.set(1)

    def boom():
        raise RuntimeError("collector broke")

    reg.add_collector(boom)
    assert "tm_x 1" in reg.render()


def test_health_metrics_raw_names():
    reg = Registry(namespace="tm")
    hm = HealthMetrics(reg)
    hm.status.set(1, subsystem="consensus")
    hm.burn_rate.set(2.5, slo="quorum_lag")
    hm.incidents.inc(subsystem="consensus")
    body = reg.render()
    assert 'tm_health_status{subsystem="consensus"} 1' in body
    assert 'tm_slo_burn_rate{slo="quorum_lag"} 2.5' in body
    assert 'tm_health_incidents_total{subsystem="consensus"} 1' in body
    # raw names: the tm_ namespace is NOT prepended a second time
    assert "tm_tm_health_status" not in body


def test_gauge_dec_and_track_inprogress():
    from tendermint_tpu.libs.metrics import Registry

    reg = Registry("tig")
    g = reg.gauge("inflight", "work in flight", ("klass",))
    g.inc(3, klass="a")
    g.dec(klass="a")
    assert g.value(klass="a") == 2
    with g.track_inprogress(5, klass="b"):
        assert g.value(klass="b") == 5
        with g.track_inprogress(klass="b"):
            assert g.value(klass="b") == 6
    assert g.value(klass="b") == 0
    # the context restores on exceptions too (the try/finally it replaces)
    try:
        with g.track_inprogress(klass="a"):
            assert g.value(klass="a") == 3
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert g.value(klass="a") == 2


# --- README metrics-reference drift (PR 12 satellite) ----------------------


def _live_metric_families() -> set:
    """Every family a fully-assembled node exports: one fresh registry,
    every metric-set class a node (or its seams) constructs."""
    from tendermint_tpu.libs import metrics as m

    reg = Registry()
    for cls in (
        m.ConsensusMetrics,
        m.P2PMetrics,
        m.BlocksyncMetrics,
        m.StateSyncMetrics,
        m.RPCMetrics,
        m.SchedulerMetrics,
        m.RemoteSchedulerMetrics,
        m.LightServeMetrics,
        m.SequencerMetrics,
        m.HealthMetrics,
        m.ProcessMetrics,
        m.EvidenceMetrics,
    ):
        cls(reg)
    return set(re.findall(r"^# TYPE (\S+) ", reg.render(), re.M))


def test_readme_metrics_reference_matches_exposition():
    """The README "Metrics reference" section must list exactly the
    families a live node exports — the metric surface grew across PRs
    2/5/11/12 with no check that the docs track it. A new family lands
    with its doc line or fails here; a removed family takes its doc
    line with it."""
    live = _live_metric_families()
    readme = os.path.join(os.path.dirname(__file__), "..", "README.md")
    with open(readme) as f:
        text = f.read()
    m = re.search(
        r"### Metrics reference\n(.*?)\n###", text, re.S
    )
    assert m, "README.md lost its '### Metrics reference' section"
    documented = set(
        re.findall(
            r"`((?:tendermint|tm|process)_[a-z0-9_]+)`", m.group(1)
        )
    )
    assert live == documented, (
        f"README metrics reference drift: "
        f"undocumented={sorted(live - documented)} "
        f"stale={sorted(documented - live)}"
    )


def test_scheduler_ledger_metric_families_raw_names():
    """The device-cost ledger surface exports under raw tm_ names (no
    tendermint_ prefix): the capacity-dashboard contract."""
    from tendermint_tpu.libs.metrics import SchedulerMetrics

    reg = Registry()
    sm = SchedulerMetrics(reg)
    sm.device_seconds.inc(0.25, klass="consensus")
    sm.fill_ratio.set(0.5, klass="consensus")
    sm.padding_rows.inc(7)
    body = reg.render()
    assert (
        'tm_scheduler_device_seconds_total{klass="consensus"} 0.25'
        in body
    )
    assert 'tm_scheduler_fill_ratio{klass="consensus"} 0.5' in body
    assert "tm_scheduler_padding_rows_total 7" in body
    assert "tendermint_tm_scheduler" not in body
