"""Multi-device sharding correctness on the virtual 8-device CPU mesh.

Mirrors __graft_entry__.dryrun_multichip so the driver's dryrun path is
exercised in CI, not just by the driver (VERDICT round-1 item 2). The
conftest forces JAX_PLATFORMS=cpu with 8 virtual host devices.
"""

import numpy as np
import pytest

import jax


def test_cpu_mesh_has_8_devices():
    assert len(jax.devices("cpu")) >= 8


def test_dryrun_multichip_8():
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)


def test_sharded_verify_matches_host():
    """8-way batch-sharded device verify == host-serial verify."""
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from __graft_entry__ import _make_batch
    from tendermint_tpu.ops.ed25519_batch import verify_prehashed

    n = 16
    pub, rb, sb, kb, s_ok = _make_batch(n)
    # corrupt a few rows in distinct ways
    sb[3] ^= 1
    rb[7] ^= 0x80
    pub[11] ^= 2
    expected = np.ones(n, dtype=bool)
    expected[[3, 7, 11]] = False

    mesh = Mesh(np.array(jax.devices("cpu")[:8]), ("batch",))
    sh = NamedSharding(mesh, P("batch"))
    fn = jax.jit(
        verify_prehashed,
        in_shardings=(sh, sh, sh, sh, sh),
        out_shardings=NamedSharding(mesh, P()),
    )
    out = np.asarray(
        fn(*(jnp.asarray(a) for a in (pub, rb, sb, kb, s_ok)))
    )
    assert (out == expected).all()
