"""Multi-device sharding correctness on the virtual 8-device CPU mesh.

Mirrors __graft_entry__.dryrun_multichip so the driver's dryrun path is
exercised in CI, not just by the driver (VERDICT round-1 item 2). The
conftest forces JAX_PLATFORMS=cpu with 8 virtual host devices.
"""

import numpy as np
import pytest

import jax


def test_cpu_mesh_has_8_devices():
    assert len(jax.devices("cpu")) >= 8


def test_dryrun_multichip_8():
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)


def test_sharded_verify_matches_host():
    """8-way batch-sharded device verify == host-serial verify."""
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from __graft_entry__ import _make_batch
    from tendermint_tpu.ops.ed25519_batch import verify_prehashed

    n = 16
    pub, rb, sb, kb, s_ok = _make_batch(n)
    # corrupt a few rows in distinct ways
    sb[3] ^= 1
    rb[7] ^= 0x80
    pub[11] ^= 2
    expected = np.ones(n, dtype=bool)
    expected[[3, 7, 11]] = False

    mesh = Mesh(np.array(jax.devices("cpu")[:8]), ("batch",))
    sh = NamedSharding(mesh, P("batch"))
    fn = jax.jit(
        verify_prehashed,
        in_shardings=(sh, sh, sh, sh, sh),
        out_shardings=NamedSharding(mesh, P()),
    )
    out = np.asarray(
        fn(*(jnp.asarray(a) for a in (pub, rb, sb, kb, s_ok)))
    )
    assert (out == expected).all()


def _sig_items(n, corrupt=()):
    """n well-formed SigItems (distinct keys), with chosen rows corrupted."""
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.crypto.batch_verifier import SigItem

    items = []
    for i in range(n):
        sk = ed25519.PrivKey(bytes([i + 1]) * 32)
        msg = b"mesh-vote-%d" % i
        sig = sk.sign(msg)
        if i in corrupt:
            sig = sig[:50] + bytes([sig[50] ^ 1]) + sig[51:]
        items.append(SigItem(sk.public_key().data, msg, sig))
    return items


def _mesh8():
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices("cpu")[:8]), ("batch",))


def test_batch_verifier_mesh_small_tier():
    """BatchVerifier(mesh=...) correctness on the sharded small-table
    tier (VERDICT r2 weak #5: no test constructed the mesh verifier)."""
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier

    v = BatchVerifier(mesh=_mesh8(), min_device_batch=0)
    items = _sig_items(16, corrupt=(2, 9))
    out = np.asarray(v.verify(items))
    want = np.array([i not in (2, 9) for i in range(16)])
    assert (out == want).all()
    # steady state: same keys again, tables now cached
    out2 = np.asarray(v.verify(items))
    assert (out2 == want).all()


def test_batch_verifier_mesh_bigcache_tier():
    """The headline bigcache path, sharded: bigtable_min lowered so a
    16-row batch rides the doubling-free fixed-window tier on the mesh."""
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier

    v = BatchVerifier(mesh=_mesh8(), min_device_batch=0, bigtable_min=8)
    items = _sig_items(16, corrupt=(5,))
    v.warm([it.pubkey for it in items], bulk=True)
    out = np.asarray(v.verify(items))
    want = np.array([i != 5 for i in range(16)])
    assert (out == want).all()


def test_batch_verifier_mesh_cache_reset_rotation():
    """Rotation past capacity resets the cache without wrong verdicts
    (the cache-reset race: verify while another thread warms)."""
    import threading

    from tendermint_tpu.crypto.batch_verifier import BatchVerifier

    v = BatchVerifier(
        mesh=_mesh8(), min_device_batch=0, table_cache_capacity=16
    )
    gen1 = _sig_items(12)
    gen2 = [
        it for it in _sig_items(24, corrupt=(20,))
    ][12:]  # 12 fresh keys; one bad row
    assert np.asarray(v.verify(gen1)).all()

    errs = []

    def _warm():
        try:
            v.warm([it.pubkey for it in gen2])
        except Exception as e:  # pragma: no cover
            errs.append(e)

    t = threading.Thread(target=_warm)
    t.start()
    out = np.asarray(v.verify(gen2))
    t.join()
    assert not errs
    want = np.array([i != (20 - 12) for i in range(12)])
    assert (out == want).all()
    # the original set still verifies after the reset churn
    assert np.asarray(v.verify(gen1)).all()
