"""Multi-device sharding correctness on the virtual 8-device CPU mesh.

Mirrors __graft_entry__.dryrun_multichip so the driver's dryrun path is
exercised in CI, not just by the driver (VERDICT round-1 item 2). The
conftest forces JAX_PLATFORMS=cpu with 8 virtual host devices.
"""

import numpy as np
import pytest

import jax


def test_cpu_mesh_has_8_devices():
    assert len(jax.devices("cpu")) >= 8


def test_dryrun_multichip_8():
    from __graft_entry__ import dryrun_multichip

    dryrun_multichip(8)


def test_sharded_verify_matches_host():
    """8-way batch-sharded device verify == host-serial verify."""
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from __graft_entry__ import _make_batch
    from tendermint_tpu.ops.ed25519_batch import verify_prehashed

    n = 16
    pub, rb, sb, kb, s_ok = _make_batch(n)
    # corrupt a few rows in distinct ways
    sb[3] ^= 1
    rb[7] ^= 0x80
    pub[11] ^= 2
    expected = np.ones(n, dtype=bool)
    expected[[3, 7, 11]] = False

    mesh = Mesh(np.array(jax.devices("cpu")[:8]), ("batch",))
    sh = NamedSharding(mesh, P("batch"))
    fn = jax.jit(
        verify_prehashed,
        in_shardings=(sh, sh, sh, sh, sh),
        out_shardings=NamedSharding(mesh, P()),
    )
    out = np.asarray(
        fn(*(jnp.asarray(a) for a in (pub, rb, sb, kb, s_ok)))
    )
    assert (out == expected).all()


def _sig_items(n, corrupt=()):
    """n well-formed SigItems (distinct keys), with chosen rows corrupted."""
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.crypto.batch_verifier import SigItem

    items = []
    for i in range(n):
        sk = ed25519.PrivKey(bytes([i + 1]) * 32)
        msg = b"mesh-vote-%d" % i
        sig = sk.sign(msg)
        if i in corrupt:
            sig = sig[:50] + bytes([sig[50] ^ 1]) + sig[51:]
        items.append(SigItem(sk.public_key().data, msg, sig))
    return items


def _mesh8():
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices("cpu")[:8]), ("batch",))


def test_batch_verifier_mesh_small_tier():
    """BatchVerifier(mesh=...) correctness on the sharded small-table
    tier (VERDICT r2 weak #5: no test constructed the mesh verifier)."""
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier

    v = BatchVerifier(mesh=_mesh8(), min_device_batch=0)
    items = _sig_items(16, corrupt=(2, 9))
    out = np.asarray(v.verify(items))
    want = np.array([i not in (2, 9) for i in range(16)])
    assert (out == want).all()
    # steady state: same keys again, tables now cached
    out2 = np.asarray(v.verify(items))
    assert (out2 == want).all()


def test_batch_verifier_mesh_bigcache_tier():
    """The headline bigcache path, sharded: bigtable_min lowered so a
    16-row batch rides the doubling-free fixed-window tier on the mesh."""
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier

    v = BatchVerifier(mesh=_mesh8(), min_device_batch=0, bigtable_min=8)
    items = _sig_items(16, corrupt=(5,))
    v.warm([it.pubkey for it in items], bulk=True)
    out = np.asarray(v.verify(items))
    want = np.array([i != 5 for i in range(16)])
    assert (out == want).all()


def test_batch_verifier_mesh_cache_reset_rotation():
    """Rotation past capacity resets the cache without wrong verdicts
    (the cache-reset race: verify while another thread warms)."""
    import threading

    from tendermint_tpu.crypto.batch_verifier import BatchVerifier

    v = BatchVerifier(
        mesh=_mesh8(), min_device_batch=0, table_cache_capacity=16
    )
    gen1 = _sig_items(12)
    gen2 = [
        it for it in _sig_items(24, corrupt=(20,))
    ][12:]  # 12 fresh keys; one bad row
    assert np.asarray(v.verify(gen1)).all()

    errs = []

    def _warm():
        try:
            v.warm([it.pubkey for it in gen2])
        except Exception as e:  # pragma: no cover
            errs.append(e)

    t = threading.Thread(target=_warm)
    t.start()
    out = np.asarray(v.verify(gen2))
    t.join()
    assert not errs
    want = np.array([i != (20 - 12) for i in range(12)])
    assert (out == want).all()
    # the original set still verifies after the reset churn
    assert np.asarray(v.verify(gen1)).all()


def test_build_mesh_axes():
    """parallel.build_mesh: 1-axis ICI mesh, 2-axis dcn x batch mesh,
    and the error on an unsatisfiable request (VERDICT r4 missing #2)."""
    from tendermint_tpu.parallel import build_mesh

    assert build_mesh(1, 1, "cpu") is None
    m = build_mesh(8, 1, "cpu")
    assert m.axis_names == ("batch",) and m.devices.shape == (8,)
    m2 = build_mesh(4, 2, "cpu")
    assert m2.axis_names == ("dcn", "batch")
    assert m2.devices.shape == (2, 4)
    with pytest.raises(ValueError):
        build_mesh(16, 4, "cpu")
    # ici=0 -> all visible devices split across dcn rows
    m3 = build_mesh(0, 2, "cpu")
    assert m3.devices.shape == (2, 4)


def test_batch_verifier_dcn_mesh():
    """A 2-axis ("dcn", "batch") mesh shards the batch dim over every
    axis (PartitionSpec(mesh.axis_names)) and still verifies correctly."""
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier
    from tendermint_tpu.parallel import build_mesh

    v = BatchVerifier(mesh=build_mesh(4, 2, "cpu"), min_device_batch=0)
    assert v._nshards == 8
    items = _sig_items(16, corrupt=(1, 14))
    out = np.asarray(v.verify(items))
    want = np.array([i not in (1, 14) for i in range(16)])
    assert (out == want).all()


def test_node_mesh_from_config(tmp_path, monkeypatch):
    """The VERDICT r4 missing-#2 'done' criterion: a [tpu] config change
    ALONE turns on sharded verification in a running node — node assembly
    exports the axes, default_verifier() builds the mesh, the chain runs."""
    import asyncio

    from tendermint_tpu.config import Config
    from tendermint_tpu.crypto import batch_verifier as bv
    from tendermint_tpu.node import Node, init_files

    for var in (
        "TM_TPU_ICI_PARALLELISM",
        "TM_TPU_DCN_PARALLELISM",
        "TM_TPU_MESH_BACKEND",
    ):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("TM_TPU_MIN_DEVICE_BATCH", "0")
    old = bv._default
    bv._default = None
    try:
        cfg = Config.test_config()
        cfg.root_dir = str(tmp_path)
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.tpu.ici_parallelism = 8
        cfg.tpu.mesh_backend = "cpu"
        init_files(cfg)
        node = Node(cfg)

        async def run():
            await node.start()
            await node.consensus.wait_for_height(2, timeout=120)
            await node.stop()

        asyncio.run(run())
        v = bv.default_verifier()
        assert v._nshards == 8, "config did not reach the verifier mesh"
        # and the mesh verifier actually verifies (sharded end-to-end)
        out = np.asarray(v.verify(_sig_items(8, corrupt=(3,))))
        assert (out == np.array([i != 3 for i in range(8)])).all()
    finally:
        bv._default = old


def test_node_mesh_enable_from_scheduler_config(tmp_path, monkeypatch):
    """[scheduler] mesh_enable = true is the one-knob multi-chip path:
    node assembly exports ici=0 (all local devices) + mesh_min_rows,
    default_verifier() builds the mesh, the chain runs, and small
    rounds still route single-device per mesh_min_rows."""
    import asyncio

    from tendermint_tpu.config import Config
    from tendermint_tpu.crypto import batch_verifier as bv
    from tendermint_tpu.node import Node, init_files

    for var in (
        "TM_TPU_ICI_PARALLELISM",
        "TM_TPU_DCN_PARALLELISM",
        "TM_TPU_MESH_BACKEND",
        "TM_TPU_MESH_MIN_ROWS",
    ):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("TM_TPU_MIN_DEVICE_BATCH", "0")
    old = bv._default
    bv._default = None
    try:
        cfg = Config.test_config()
        cfg.root_dir = str(tmp_path)
        cfg.rpc.laddr = "tcp://127.0.0.1:0"
        cfg.p2p.laddr = "tcp://127.0.0.1:0"
        cfg.scheduler.mesh_enable = True
        cfg.scheduler.mesh_min_rows = 512
        cfg.tpu.mesh_backend = "cpu"
        init_files(cfg)
        node = Node(cfg)

        async def run():
            await node.start()
            await node.consensus.wait_for_height(2, timeout=120)
            await node.stop()

        asyncio.run(run())
        v = bv.default_verifier()
        assert v.mesh_devices == 8, "mesh_enable did not reach the verifier"
        assert v._mesh_min_rows == 512
        assert v.shards_for(16) == 1 and v.shards_for(512) == 8
        out = np.asarray(v.verify(_sig_items(8, corrupt=(3,))))
        assert (out == np.array([i != 3 for i in range(8)])).all()
    finally:
        bv._default = old


def test_g1_aggregate_sharded_matches_host():
    """BLS G1 tree aggregation under the mesh == host point sum
    (VERDICT r4 missing #4: the non-ed25519 kernels had no sharded
    execution anywhere)."""
    from tendermint_tpu.crypto import bls12_381 as h
    from tendermint_tpu.ops import bls_g1

    ks = [3, 5, 7, 11, 13, 17, 19, 23]
    pts = np.stack(
        [bls_g1.g1_from_host(h.g1_mul(h.G1_GEN, k)) for k in ks]
    )
    out = bls_g1.g1_aggregate_sharded(pts, _mesh8())
    got = h.g1_to_affine(bls_g1.g1_to_host(np.asarray(out)))
    want = h.g1_to_affine(h.g1_mul(h.G1_GEN, sum(ks)))
    assert got == want


def test_g2_aggregate_sharded_matches_host():
    """BLS G2 (pubkey-side) tree aggregation under the mesh."""
    from tendermint_tpu.crypto import bls12_381 as h
    from tendermint_tpu.ops import bls_g2

    ks = [2, 9, 31, 4, 8, 15, 16, 42]
    pts = np.stack(
        [bls_g2.g2_from_host(h.g2_mul(h.G2_GEN, k)) for k in ks]
    )
    out = bls_g2.g2_aggregate_sharded(pts, _mesh8())
    got = h.g2_to_affine(bls_g2.g2_to_host(np.asarray(out)))
    want = h.g2_to_affine(h.g2_mul(h.G2_GEN, sum(ks)))
    assert got == want


def test_secp_verify_sharded():
    """The secp256k1 joint-ladder verify kernel sharded over the mesh:
    same bitmap as the host oracle, one corrupted row rejected."""
    import hashlib

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tendermint_tpu.crypto import secp256k1 as secp
    from tendermint_tpu.crypto.secp_native import prep_digest_item
    from tendermint_tpu.ops import secp256k1_kernel as sk

    n = 8
    fe = sk.fe
    qx = np.zeros((n, fe.NLIMBS), dtype=np.int32)
    qy = np.zeros((n, fe.NLIMBS), dtype=np.int32)
    u1 = np.zeros((n, 32), dtype=np.uint8)
    u2 = np.zeros((n, 32), dtype=np.uint8)
    rb = np.zeros((n, 32), dtype=np.uint8)
    ok = np.zeros(n, dtype=bool)
    for i in range(n):
        pv = secp.PrivKey.from_secret(b"mesh-secp-%d" % i)
        msg = b"mesh-msg-%d" % i
        sig = pv.sign(msg)
        if i == 6:  # corrupt: swap in a different message's digest
            msg = b"mesh-msg-tampered"
        prep = prep_digest_item(
            pv.public_key().data, hashlib.sha256(msg).digest(), sig
        )
        assert prep is not None
        _r, pt, u1v, u2v = prep
        qx[i] = fe.from_int(pt[0])
        qy[i] = fe.from_int(pt[1])
        u1[i] = np.frombuffer(u1v.to_bytes(32, "big"), np.uint8)
        u2[i] = np.frombuffer(u2v.to_bytes(32, "big"), np.uint8)
        rb[i] = np.frombuffer(sig[:32], np.uint8)
        ok[i] = True

    mesh = _mesh8()
    sh = NamedSharding(mesh, P("batch"))
    import jax as _jax

    fn = _jax.jit(
        sk.verify_prehashed,
        in_shardings=(sh, sh, sh, sh, sh, sh),
        out_shardings=NamedSharding(mesh, P()),
    )
    args = [
        _jax.device_put(a, sh) for a in (qx, qy, u1, u2, rb, ok)
    ]
    out = np.asarray(fn(*args))
    want = np.array([i != 6 for i in range(n)])
    assert (out == want).all()
