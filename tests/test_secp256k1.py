"""secp256k1 tests: sign/verify/recover semantics matching the reference
(crypto/secp256k1/secp256k1.go, types/block_v2.go)."""

import hashlib

from tendermint_tpu.crypto import secp256k1 as s


def test_known_vector_pubkey():
    # d=1 -> G; compressed prefix depends on GY parity (even -> 0x02)
    k = s.PrivKey(1)
    pub = k.public_key()
    assert pub.data == s.compress_point((s.GX, s.GY))
    assert pub.data[0] == 0x02


def test_sign_verify_roundtrip():
    k = s.PrivKey.from_secret(b"validator-0")
    pub = k.public_key()
    msg = b"canonical vote bytes"
    sig = k.sign(msg)
    assert len(sig) == 64
    assert pub.verify(msg, sig)
    assert not pub.verify(msg + b"x", sig)
    assert not pub.verify(msg, sig[:-1] + bytes([sig[-1] ^ 1]))


def test_low_s_enforced():
    k = s.PrivKey.from_secret(b"low-s")
    msg = b"m"
    sig = k.sign(msg)
    r = int.from_bytes(sig[:32], "big")
    ss = int.from_bytes(sig[32:], "big")
    assert ss <= s.N // 2
    # high-S variant of a valid signature must be rejected (malleability)
    high = r.to_bytes(32, "big") + (s.N - ss).to_bytes(32, "big")
    assert not k.public_key().verify(msg, high)


def test_rfc6979_deterministic():
    k = s.PrivKey.from_secret(b"det")
    assert k.sign(b"abc") == k.sign(b"abc")


def test_rfc6979_known_vector():
    # RFC 6979 A.2.5 uses P-256; for secp256k1 use the widely-cross-checked
    # vector (e.g. Trezor/python-ecdsa test suite): key=1, msg="Satoshi
    # Nakamoto" -> known k and r,s.
    d = 1
    digest = hashlib.sha256(b"Satoshi Nakamoto").digest()
    sig = s.sign_digest(digest, d)
    r = int.from_bytes(sig[:32], "big")
    ss = int.from_bytes(sig[32:], "big")
    assert r == 0x934B1EA10A4B3C1757E2B0C017D0B6143CE3C9A7E6A4A49860D7A6AB210EE3D8
    assert ss == 0x2442CE9D2B916064108014783E923EC36B49743E2FFA1C4496F01A512AAFD9E5


def test_eth_recover():
    k = s.PrivKey.from_secret(b"sequencer")
    digest = hashlib.sha256(b"block hash").digest()
    sig = s.eth_sign(digest, k.secret)
    assert len(sig) == 65 and sig[64] in (0, 1)
    pt = s.decompress_point(k.public_key().data)
    addr = s.eth_address(pt)
    assert s.eth_recover_address(digest, sig) == addr
    # flipped digest recovers a different address
    bad = bytearray(digest)
    bad[0] ^= 1
    assert s.eth_recover_address(bytes(bad), sig) != addr


def test_address_format():
    k = s.PrivKey.from_secret(b"addr")
    addr = k.public_key().address()
    assert len(addr) == 20
    sha = hashlib.sha256(k.public_key().data).digest()
    assert addr == hashlib.new("ripemd160", sha).digest()


def test_decompress_rejects_bad_points():
    assert s.decompress_point(b"\x02" + b"\xff" * 32) is None  # x >= p
    assert s.decompress_point(b"\x05" + b"\x01" * 32) is None  # bad prefix
    assert s.decompress_point(b"") is None


def test_mixed_key_commit_verifies():
    """BASELINE config 4: a commit signed by ed25519 AND secp256k1
    validators verifies — the BatchVerifier partitions per key type
    (reference allows mixed key types, crypto/secp256k1/secp256k1.go:192)."""
    from tendermint_tpu.crypto import ed25519
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.priv_validator import MockPV
    from tendermint_tpu.types.validator import Validator
    from tendermint_tpu.types.validator_set import ValidatorSet

    from .helpers import CHAIN_ID, sign_commit

    pvs = [
        MockPV(ed25519.PrivKey.from_secret(b"mixed-ed-0")),
        MockPV(ed25519.PrivKey.from_secret(b"mixed-ed-1")),
        MockPV(s.PrivKey.from_secret(b"mixed-secp-0")),
        MockPV(s.PrivKey.from_secret(b"mixed-secp-1")),
    ]
    vs = ValidatorSet([Validator(pv.get_pub_key(), 10) for pv in pvs])
    by_addr = {pv.get_pub_key().address(): pv for pv in pvs}
    ordered = [by_addr[v.address] for v in vs.validators]

    bid = BlockID(hash=b"\x07" * 32)
    commit = sign_commit(vs, ordered, 5, 0, bid)
    # host-only path exercises the per-type partition (min_device_batch
    # large so ed25519 rows stay on host too — semantics identical)
    verifier = BatchVerifier(min_device_batch=1 << 30)
    vs.verify_commit(CHAIN_ID, bid, 5, commit, verifier=verifier)
    vs.verify_commit_light(CHAIN_ID, bid, 5, commit, verifier=verifier)

    # corrupt the secp256k1 validator's signature -> rejected
    for i, v in enumerate(vs.validators):
        if v.pub_key.type_name == "secp256k1":
            cs = commit.signatures[i]
            cs.signature = bytes([cs.signature[0] ^ 1]) + cs.signature[1:]
            break
    import pytest

    with pytest.raises(ValueError, match="wrong signature"):
        vs.verify_commit(CHAIN_ID, bid, 5, commit, verifier=verifier)


def test_native_batch_matches_python():
    """crypto/secp_native batched Shamir path must agree with the pure
    Python verifier on valid, corrupted, wrong-key, high-S, and malformed
    inputs (BASELINE config 4's secp rows)."""
    from tendermint_tpu.crypto import secp256k1 as s
    from tendermint_tpu.crypto import secp_native

    privs = [s.PrivKey.from_secret(b"nb%d" % i) for i in range(12)]
    msgs = [b"m-%d" % i for i in range(12)]
    sigs = [p.sign(m) for p, m in zip(privs, msgs)]
    pubs = [p.public_key().data for p in privs]

    cases = list(zip(pubs, msgs, sigs))
    # corrupted signature byte
    cases.append((pubs[0], msgs[0], sigs[0][:10] + b"\xff" + sigs[0][11:]))
    # wrong message
    cases.append((pubs[1], b"other", sigs[1]))
    # wrong key
    cases.append((pubs[2], msgs[3], sigs[3]))
    # high-S (forge malleated sig: s' = N - s)
    r_b, s_b = sigs[4][:32], sigs[4][32:]
    s_int = int.from_bytes(s_b, "big")
    cases.append(
        (pubs[4], msgs[4], r_b + (s.N - s_int).to_bytes(32, "big"))
    )
    # malformed length
    cases.append((pubs[5], msgs[5], b"\x01" * 63))

    got = secp_native.verify_msgs_batch(
        [c[0] for c in cases], [c[1] for c in cases], [c[2] for c in cases]
    )
    want = [
        s.PubKey(c[0]).verify(c[1], c[2]) if len(c[2]) == 64 else False
        for c in cases
    ]
    assert got == want
    assert got[:12] == [True] * 12
    assert got[12:] == [False] * 5


def test_mixed_key_batch_verifier_uses_native_secp():
    """BatchVerifier partitions mixed ed25519/secp256k1 rows; the secp
    rows go through the batched native call and re-interleave correctly."""
    from tendermint_tpu.crypto import ed25519, secp256k1 as s
    from tendermint_tpu.crypto.batch_verifier import BatchVerifier, SigItem

    items = []
    want = []
    for i in range(6):
        if i % 2 == 0:
            priv = s.PrivKey.from_secret(b"mix%d" % i)
            msg = b"mixed-%d" % i
            sig = priv.sign(msg)
            if i == 4:
                sig = sig[:40] + bytes([sig[40] ^ 1]) + sig[41:]
            items.append(
                SigItem(priv.public_key().data, msg, sig, "secp256k1")
            )
            want.append(i != 4)
        else:
            sk = ed25519.PrivKey(b"e" * 31 + bytes([i]))
            msg = b"edrow-%d" % i
            items.append(
                SigItem(sk.public_key().data, msg, sk.sign(msg), "ed25519")
            )
            want.append(True)
    v = BatchVerifier()
    out = list(v.verify(items))
    assert out == want
