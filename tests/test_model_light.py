"""Machine-check of spec/tla/LightClient.tla (round-4/5 follow-up to the
ConsensusSafety explorer in test_model_safety.py).

Explores the module's 4-height / 4-validator / 1-faulty instance
exhaustively: the attacker may present, at any height, a fake header
with ANY validator set / next-validators pair, signed by any subset of
FAULTY (honest validators sign only the real chain's header at their
height). StoreSound = no fake header is ever accepted.

The chain constants cover both a static validator set and a rotation
(the next-validators commitment changing between heights), since the
1/3-of-trusted check binds to ChainNextVals of the trusted header.

Self-validation: dropping any one of the three load-bearing guards —
adjacent next-validators continuity, the non-adjacent 1/3-of-trusted
threshold, or the <1/3-faulty assumption — must produce a violation.
"""

import itertools

VALIDATORS = frozenset("abcd")
FAULTY = frozenset("d")
HEIGHTS = (1, 2, 3, 4)
ROOT = 1

# two chain shapes: static set, and a rotation at height 3
CHAINS = [
    {
        "vals": {h: frozenset("abcd") for h in HEIGHTS},
        "next": {h: frozenset("abcd") for h in HEIGHTS},
    },
    {
        "vals": {1: frozenset("abcd"), 2: frozenset("abcd"),
                 3: frozenset("abce"), 4: frozenset("abce")},
        "next": {1: frozenset("abcd"), 2: frozenset("abce"),
                 3: frozenset("abce"), 4: frozenset("abce")},
    },
]
# the rotation chain introduces validator e; faulty stays {d}
UNIVERSE = frozenset("abcde")

REAL = "real"


def _subsets(s):
    s = sorted(s)
    for r in range(len(s) + 1):
        for c in itertools.combinations(s, r):
            yield frozenset(c)


def _two_thirds(signers, of):
    return 3 * len(signers & of) > 2 * len(of)


def _one_third(signers, of):
    return 3 * len(signers & of) >= len(of)


def _headers(chain, faulty):
    """All presentable headers: the real one per height + every fake
    (height, vals, next_vals) combination the attacker could craft.
    Fake vals range over subsets of the universe; signatures on a fake
    can only come from FAULTY."""
    hs = []
    for h in HEIGHTS:
        hs.append((REAL, h, None, None))
        for vals in _subsets(UNIVERSE):
            if not vals:
                continue
            # nextVals only matters for chaining once accepted; a
            # single adversarial choice (all-faulty) is attack-maximal
            hs.append(("fake", h, vals, faulty))
    return hs


def _accepts(chain, faulty, th, nh, *, continuity=True, one_third=True):
    """AdjacentOK \\/ NonAdjacentOK with maximal signer sets (the
    attacker always contributes every signature it can, honest
    validators always sign the real header — supersets only help)."""
    th_kind, th_h, th_vals, th_next = th
    nh_kind, nh_h, nh_vals, nh_next = nh
    h_next_of_th = chain["next"][th_h] if th_kind == REAL else th_next
    if nh_kind == REAL:
        vals_nh = chain["vals"][nh_h]
        signers = vals_nh | faulty  # max achievable signer set
    else:
        vals_nh = nh_vals
        signers = faulty
    if nh_h == th_h + 1:
        if continuity and vals_nh != h_next_of_th:
            return False
        return _two_thirds(signers, vals_nh)
    if nh_h > th_h + 1:
        ok = _two_thirds(signers, vals_nh)
        if one_third:
            ok = ok and _one_third(signers, h_next_of_th)
        return ok
    return False


def _explore(chain, faulty, **guards):
    """BFS over reachable stores; returns True if a fake header is ever
    accepted."""
    headers = _headers(chain, faulty)
    root = (REAL, ROOT, None, None)
    init = frozenset([root])
    seen = {init}
    stack = [init]
    while stack:
        store = stack.pop()
        for th in store:
            for nh in headers:
                if nh in store:
                    continue
                if _accepts(chain, faulty, th, nh, **guards):
                    if nh[0] != REAL:
                        return True
                    nxt = store | {nh}
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
    return False


def test_store_sound_under_fault_assumption():
    for chain in CHAINS:
        # sanity: FaultAssumption holds for these constants
        for h in HEIGHTS:
            assert 3 * len(FAULTY & chain["vals"][h]) < len(chain["vals"][h])
            assert 3 * len(FAULTY & chain["next"][h]) < len(chain["next"][h])
        assert not _explore(chain, FAULTY), (
            "light client accepted a forged header"
        )


def test_attack_without_adjacent_continuity():
    """Dropping the next-validators continuity check lets the attacker
    present an adjacent fake whose own set is all-faulty (2/3 of a set
    you chose yourself is free)."""
    assert _explore(CHAINS[0], FAULTY, continuity=False)


def test_attack_without_one_third_of_trusted():
    """Dropping the 1/3-of-trusted threshold on skipping verification
    reduces non-adjacent acceptance to 2/3 of the fake's own set —
    attacker-chosen, so forgery goes through."""
    assert _explore(CHAINS[0], FAULTY, one_third=False)


def test_attack_when_fault_assumption_broken():
    """With >= 1/3 faulty in the trusted next set, the faulty coalition
    alone satisfies the skipping threshold and forges."""
    big_faulty = frozenset("cd")  # 2 of 4 >= 1/3
    assert _explore(CHAINS[0], big_faulty)
