"""Light-client serving plane (tendermint_tpu/lightserve).

Covers the proof cache's durability-pinned admission, the ServeVerifier's
hop dedup, the new RPC proof routes + validator pagination, the
provider's retry/pagination satellites, trusted-store prune safety under
the cache interplay, and the ISSUE-8 swarm acceptance: >= 1000 simulated
light clients syncing a real 4-validator net through the plane with
cache hit-rate > 0.9, device dispatches sublinear in client count, and
the divergent-witness scenario landing LightClientAttackEvidence in the
evidence pool.
"""

import asyncio
import types

import pytest

from tendermint_tpu.libs.metrics import LightServeMetrics, Registry
from tendermint_tpu.light.client import LightClient, TrustOptions
from tendermint_tpu.light.store import LightStore
from tendermint_tpu.light.verifier import (
    ErrNewHeaderTooFarAhead,
    VerificationError,
)
from tendermint_tpu.lightserve import (
    LightBlockCache,
    LightServePlane,
    ServeVerifier,
)
from tendermint_tpu.store.kv import MemKV

from .test_light import (
    BLOCK_NS,
    CHAIN_ID as LIGHT_CHAIN_ID,
    PERIOD,
    T0,
    MockProvider,
    make_chain,
)

pytestmark = pytest.mark.lightserve


def _metrics():
    return LightServeMetrics(Registry("lightserve_test"))


# --- the proof cache -------------------------------------------------------


async def _drive_net(heights, n_vals=1):
    from tests.helpers import make_genesis, make_validators
    from tests.test_consensus import make_node, wire_net

    vs, pvs = make_validators(n_vals)
    genesis = make_genesis(vs)
    nodes = [make_node(vs, pv, genesis) for pv in pvs]
    css = [n[0] for n in nodes]
    if len(css) > 1:
        wire_net(css)
    for cs in css:
        await cs.start()
    await asyncio.gather(
        *(cs.wait_for_height(heights, timeout=120) for cs in css)
    )
    for cs in css:
        await cs.stop()
    return nodes[0]


def test_cache_assembles_once_and_pins_to_durable():
    async def run():
        _cs, _app, _l2, bs, ss = await _drive_net(4)
        cache = LightBlockCache(bs, ss, metrics=_metrics())
        tip = bs.height
        # a durable height: first get assembles, second hits
        lb = cache.get(tip - 1)
        assert lb is not None and lb.height == tip - 1
        lb.validate_basic(lb.header.chain_id)
        again = cache.get(tip - 1)
        assert again is lb  # the shared object, not a re-assembly
        assert cache.hits == 1 and cache.assembled == 1
        # the tip's canonical commit doesn't exist yet (lives in block
        # tip+1): served fresh from the seen commit, never cached
        tip_lb = cache.get(tip)
        assert tip_lb is not None and tip_lb.height == tip
        assert cache.get(tip) is not tip_lb
        assert len(cache) == 1
        # latest (height=0) resolves to the tip
        assert cache.get(0).height == tip
        # unknown heights miss cleanly
        assert cache.get(tip + 10) is None

    asyncio.run(run())


def test_cache_drops_entries_above_a_rollback():
    async def run():
        _cs, _app, _l2, bs, ss = await _drive_net(5)
        cache = LightBlockCache(bs, ss, metrics=_metrics())
        h = bs.height - 1
        assert cache.get(h) is not None
        assert len(cache) == 1
        # rewind the store below the cached entry: the durable pin must
        # refuse to serve the stale proof
        bs.prune_blocks_since(h - 1)
        assert cache.get(h) is None
        assert len(cache) == 0 or cache.get(h - 2) is not None

    asyncio.run(run())


def test_cache_rollback_purges_stale_entries_on_observation():
    """Observing the durable watermark move DOWN purges every entry
    at/above it immediately — a later recovery of the watermark can't
    resurrect a pre-rollback proof."""

    async def run():
        _cs, _app, _l2, bs, ss = await _drive_net(6)
        cache = LightBlockCache(bs, ss, metrics=_metrics())
        h = bs.height - 1
        assert cache.get(h) is not None
        assert cache.get(h - 2) is not None
        assert len(cache) == 2
        bs.prune_blocks_since(h - 1)
        # an access to an UNRELATED height observes the regression and
        # purges the now-suspect entry at h
        assert cache.get(h - 2) is not None
        assert len(cache) == 1
        assert cache.get(h) is None  # gone from cache AND store

    asyncio.run(run())


def test_cache_lru_bound():
    async def run():
        _cs, _app, _l2, bs, ss = await _drive_net(6)
        cache = LightBlockCache(bs, ss, max_entries=2, metrics=_metrics())
        for h in range(1, bs.height):
            cache.get(h)
        assert len(cache) <= 2

    asyncio.run(run())


# --- the serve verifier ----------------------------------------------------


def test_serve_verifier_dedups_identical_hops():
    chain = make_chain(40)
    sv = ServeVerifier(metrics=_metrics())
    now = T0 + 50 * BLOCK_NS

    async def run():
        # 32 concurrent identical hops -> one executed verification
        await asyncio.gather(
            *(
                sv.verify_hop(chain[0], chain[29], PERIOD, now)
                for _ in range(32)
            )
        )
        assert sv.executed == 1
        assert sv.deduped == 31
        # a later identical request inside the reuse window rides the
        # cached verdict
        await sv.verify_hop(chain[0], chain[29], PERIOD, now + BLOCK_NS)
        assert sv.executed == 1
        # outside the window it re-verifies
        await sv.verify_hop(
            chain[0], chain[29], PERIOD, now + sv.reuse_window_ns * 2
        )
        assert sv.executed == 2

    asyncio.run(run())


def test_serve_verifier_shares_failure_verdicts():
    """Verification failures — including the too-far-ahead signal that
    drives bisection — dedupe exactly like successes."""
    honest = make_chain(30)
    garbage = make_chain(30, seed=b"unrelated")
    sv = ServeVerifier(metrics=_metrics())
    now = T0 + 40 * BLOCK_NS

    async def run():
        outcomes = await asyncio.gather(
            *(
                sv.verify_hop(honest[0], garbage[29], PERIOD, now)
                for _ in range(8)
            ),
            return_exceptions=True,
        )
        assert all(
            isinstance(o, (VerificationError, ErrNewHeaderTooFarAhead))
            for o in outcomes
        )
        assert sv.executed == 1

    asyncio.run(run())


def test_skewed_client_cannot_poison_the_verdict_cache():
    """Time-dependent failures are judged per requester, never cached:
    a clock-skewed client's from-the-future rejection must not block
    honest clients from verifying the same hop (and the skew costs no
    shared verification)."""
    chain = make_chain(40)
    sv = ServeVerifier(metrics=_metrics())
    honest_now = T0 + 50 * BLOCK_NS
    # far enough behind height 30's header time (T0+30s) that the 10s
    # max-clock-drift allowance can't absorb the skew
    skewed_now = T0 + 10 * BLOCK_NS

    async def run():
        with pytest.raises(VerificationError, match="future"):
            await sv.verify_hop(chain[0], chain[29], PERIOD, skewed_now)
        assert sv.executed == 0  # rejected before the shared cache
        # honest clients verify the identical hop fine
        await sv.verify_hop(chain[0], chain[29], PERIOD, honest_now)
        assert sv.executed == 1
        # and the success verdict is NOT reusable by the skewed clock
        with pytest.raises(VerificationError, match="future"):
            await sv.verify_hop(chain[0], chain[29], PERIOD, skewed_now)
        assert sv.executed == 1

    asyncio.run(run())


def test_bogus_trusted_valset_cannot_poison_honest_key():
    """The verdict key covers every verification input: a client
    pairing the real headers with a bogus trusted validator set caches
    its failure under ITS OWN key — honest clients still verify."""
    from tendermint_tpu.light.types import LightBlock

    chain = make_chain(40)
    other = make_chain(40, seed=b"other")
    sv = ServeVerifier(metrics=_metrics())
    now = T0 + 50 * BLOCK_NS
    bogus_trusted = LightBlock(
        chain[0].header, chain[0].commit, other[0].validators
    )

    async def run():
        with pytest.raises(VerificationError):
            await sv.verify_hop(bogus_trusted, chain[29], PERIOD, now)
        # the honest hop shares nothing with the poisoned key
        await sv.verify_hop(chain[0], chain[29], PERIOD, now)
        assert sv.executed == 2 and sv.deduped == 0

    asyncio.run(run())


def test_sequential_mode_rejects_non_adjacent_blocks():
    """Sequential verification's guarantee IS adjacency: a primary
    answering interim fetches with the wrong height must fail the sync,
    not silently downgrade to 1/3-trust skipping verification."""
    chain = make_chain(10)

    class MisservingProvider(MockProvider):
        async def light_block(self, height):
            if height not in (0, 1, 10):
                height = min(height + 3, 9)  # wrong interim heights
            return await super().light_block(height)

    async def run():
        c = LightClient(
            LIGHT_CHAIN_ID,
            TrustOptions(PERIOD, 1, chain[0].header.hash()),
            MisservingProvider(chain),
            [MockProvider(chain, name="w")],
            LightStore(MemKV()),
            sequential=True,
            now_ns=lambda: T0 + 20 * BLOCK_NS,
        )
        with pytest.raises(VerificationError, match="sequential"):
            await c.verify_light_block_at_height(10)

    asyncio.run(run())


def test_server_assisted_client_swarm_dedups():
    """LightClients handed the shared ServeVerifier sync with a handful
    of executed verifications regardless of swarm size."""
    chain = make_chain(50)
    sv = ServeVerifier(metrics=_metrics())
    now = T0 + 60 * BLOCK_NS

    async def one():
        c = LightClient(
            LIGHT_CHAIN_ID,
            TrustOptions(PERIOD, 1, chain[0].header.hash()),
            MockProvider(chain),
            [MockProvider(chain, name="w")],
            LightStore(MemKV()),
            now_ns=lambda: now,
            serve_verifier=sv,
        )
        lb = await c.verify_light_block_at_height(50)
        assert lb.height == 50

    async def run():
        await asyncio.gather(*(one() for _ in range(64)))

    asyncio.run(run())
    assert sv.requests >= 64
    # static valset -> root verify + one direct skip hop per sync shape
    assert sv.executed <= 4
    assert sv.dedup_rate() > 0.9


def test_scheduler_has_lightserve_lane():
    from tendermint_tpu.parallel.scheduler import CLASS_ORDER

    assert "lightserve" in CLASS_ORDER
    # serving external clients ranks below every internal class
    assert CLASS_ORDER.index("lightserve") == len(CLASS_ORDER) - 1


# --- rpc routes ------------------------------------------------------------


def _fake_node(bs, ss, chain_id="test-chain"):
    plane = LightServePlane(bs, ss, chain_id, metrics=_metrics())
    return types.SimpleNamespace(
        block_store=bs,
        state_store=ss,
        lightserve=plane,
        config=types.SimpleNamespace(
            rpc=types.SimpleNamespace(unsafe=False)
        ),
    )


def test_rpc_proof_routes_and_pagination():
    from tendermint_tpu.rpc.core import RPCCore
    from tendermint_tpu.rpc.server import RPCError

    async def run():
        _cs, _app, _l2, bs, ss = await _drive_net(4)
        core = RPCCore(_fake_node(bs, ss))
        routes = core.routes()
        for r in ("light_block", "signed_header", "validator_set"):
            assert r in routes
        h = bs.height - 1
        res = core.light_block(height=h)
        lb = res["light_block"]
        assert lb["signed_header"]["header"]["height"] == h
        assert lb["signed_header"]["commit"]["height"] == h
        assert lb["validator_set"]["total"] == 1
        sh = core.signed_header(height=h)
        assert sh["signed_header"]["header"]["height"] == h
        vs = core.validator_set(height=h)
        assert vs["total"] == 1 and len(vs["validators"]) == 1
        # the second fetch of the same height is a cache hit
        assert core.node.lightserve.cache.hits >= 1
        # a route-less node serves no proof routes
        core2 = RPCCore(
            types.SimpleNamespace(
                lightserve=None,
                config=types.SimpleNamespace(
                    rpc=types.SimpleNamespace(unsafe=False)
                ),
            )
        )
        assert "light_block" not in core2.routes()
        # pagination contract on the legacy validators route
        with pytest.raises(RPCError):
            core.validators(height=h, page=99)

    asyncio.run(run())


def test_validators_route_paginates_large_sets():
    """>100 validators arrive across pages, never silently truncated."""
    from tests.helpers import make_validators
    from tendermint_tpu.rpc.core import RPCCore

    vs, _pvs = make_validators(130)

    class _SS:
        def load_validators(self, h):
            return vs

    node = types.SimpleNamespace(
        block_store=types.SimpleNamespace(height=5),
        state_store=_SS(),
        lightserve=None,
        config=types.SimpleNamespace(
            rpc=types.SimpleNamespace(unsafe=False)
        ),
    )
    core = RPCCore(node)
    p1 = core.validators(height=5)
    assert p1["total"] == 130 and p1["count"] == 100 and p1["page"] == 1
    p2 = core.validators(height=5, page=2)
    assert p2["count"] == 30
    addrs = {v["address"] for v in p1["validators"] + p2["validators"]}
    assert len(addrs) == 130


# --- the rpc provider satellites -------------------------------------------


class _ScriptedClient:
    """Stub RPCClient: pops scripted (method -> outcome) responses."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = []

    async def call(self, method, **params):
        self.calls.append((method, params))
        for i, (m, outcome) in enumerate(self.script):
            if m == method:
                self.script.pop(i)
                if isinstance(outcome, BaseException):
                    raise outcome
                return outcome
        raise AssertionError(f"unscripted call {method}")

    async def close(self):
        pass


def _rpc_provider(script, **kw):
    from tendermint_tpu.rpc.light_provider import RPCProvider

    sleeps = []

    async def fake_sleep(s):
        sleeps.append(s)

    p = RPCProvider("test-chain", "127.0.0.1:1", sleep=fake_sleep, **kw)
    p.client = _ScriptedClient(script)
    return p, sleeps


def _light_block_json(h=3, n_vals=4):
    """A consistent light_block RPC payload built from a signed chain."""
    from tendermint_tpu.rpc.core import RPCCore

    chain = make_chain(h, n_vals=n_vals)
    lb = chain[h - 1]
    core = RPCCore.__new__(RPCCore)  # json helpers only
    return {
        "light_block": {
            "signed_header": {
                "header": core._header_json(lb.header),
                "commit": core._commit_json(lb.commit),
            },
            "validator_set": {
                "validators": [
                    core._validator_json(v) for v in lb.validators.validators
                ],
                "total": lb.validators.size(),
            },
        }
    }, lb


def test_provider_retries_transient_failures_with_backoff():
    payload, lb = _light_block_json()

    async def run():
        p, sleeps = _rpc_provider(
            [
                ("light_block", ConnectionError("conn reset")),
                ("light_block", ConnectionError("conn reset")),
                ("light_block", payload),
            ]
        )
        got = await p.light_block(3)
        assert got is not None and got.height == 3
        assert got.header.hash() == lb.header.hash()
        got.validate_basic("light-chain")
        assert p.retries == 2
        # exponential: second sleep doubles the first
        assert len(sleeps) == 2 and sleeps[1] == 2 * sleeps[0]

    asyncio.run(run())


def test_provider_gives_up_after_bounded_retries():
    async def run():
        p, sleeps = _rpc_provider(
            [("light_block", ConnectionError("down"))] * 5,
            max_retries=3,
        )
        assert await p.light_block(3) is None
        assert len(sleeps) == 2  # 3 attempts -> 2 backoffs
        # a server dying mid-response body (IncompleteReadError is an
        # EOFError, not an OSError) also reports "no block", never
        # leaks the exception to the caller
        p2, _ = _rpc_provider(
            [
                (
                    "light_block",
                    asyncio.IncompleteReadError(b"partial", 100),
                )
            ]
            * 5,
            max_retries=3,
        )
        assert await p2.light_block(3) is None

    asyncio.run(run())


def test_provider_falls_back_and_paginates_legacy_servers():
    """-32601 latches the legacy path; >100 validators fetched across
    pages and reassembled into a set that re-hashes correctly."""
    from tendermint_tpu.rpc.client import RPCClientError
    from tendermint_tpu.rpc.core import RPCCore

    n_vals = 130
    chain = make_chain(2, n_vals=n_vals)
    lb = chain[1]
    core = RPCCore.__new__(RPCCore)
    rows = [core._validator_json(v) for v in lb.validators.validators]
    commit_payload = {
        "signed_header": {
            "header": core._header_json(lb.header),
            "commit": core._commit_json(lb.commit),
        }
    }

    async def run():
        p, _sleeps = _rpc_provider(
            [
                ("light_block", RPCClientError(-32601, "not found")),
                ("commit", commit_payload),
                (
                    "validators",
                    {"validators": rows[:100], "total": n_vals},
                ),
                (
                    "validators",
                    {"validators": rows[100:], "total": n_vals},
                ),
            ]
        )
        got = await p.light_block(2)
        assert got is not None
        assert got.validators.size() == n_vals
        got.validate_basic("light-chain")  # validators_hash matches
        assert p._has_light_block is False
        pages = [
            params for (m, params) in p.client.calls if m == "validators"
        ]
        assert [pg["page"] for pg in pages] == [1, 2]

    asyncio.run(run())


def test_provider_bounds_hostile_validator_pagination():
    """Providers are untrusted: a malicious total must cost a bounded
    number of round trips, not a billion."""
    from tendermint_tpu.rpc import light_provider as lp

    async def run():
        p, _ = _rpc_provider(
            [("validators", {"validators": [{"x": 1}], "total": 10**9})]
            * 10_000
        )
        rows = await p._fetch_validator_rows(2)
        max_pages = -(-lp._VALS_MAX // lp._VALS_PAGE)
        assert len(p.client.calls) <= max_pages
        assert len(rows) <= max_pages

    asyncio.run(run())


# --- trusted-store prune safety --------------------------------------------


def test_light_store_prune_never_evicts_latest_anchor():
    chain = make_chain(10)
    store = LightStore(MemKV())
    for lb in chain:
        store.save(lb)
    store.prune(0)  # hostile keep: the anchor must survive
    assert store.latest() is not None
    assert store.latest().height == 10
    store2 = LightStore(MemKV())
    for lb in chain:
        store2.save(lb)
    store2.prune(3)
    assert store2.heights() == [8, 9, 10]
    assert store2.latest().height == 10


def test_light_store_prune_mid_bisection_keeps_anchor():
    """A client pruned to size 1 per verified height still completes —
    the anchor the next hop verifies from is never evicted."""
    chain = make_chain(60)

    async def run():
        c = LightClient(
            LIGHT_CHAIN_ID,
            TrustOptions(PERIOD, 1, chain[0].header.hash()),
            MockProvider(chain),
            [MockProvider(chain, name="w")],
            LightStore(MemKV()),
            pruning_size=1,
            now_ns=lambda: T0 + 70 * BLOCK_NS,
        )
        lb = await c.verify_light_block_at_height(60)
        assert lb.height == 60
        assert c.store.latest().height == 60
        # resync continues from the retained anchor
        lb2 = await c.verify_light_block_at_height(60)
        assert lb2.height == 60

    asyncio.run(run())


# --- the proof routes over a live node's RPC --------------------------------


def test_light_block_route_e2e_over_rpc(tmp_path):
    """A real node serves `light_block` over the wire; RPCProvider rides
    the one-round-trip fast path and the assembled LightBlock verifies
    locally (recomputed hashes, validators_hash match)."""
    from tendermint_tpu.node.node import Node, init_files
    from tendermint_tpu.rpc.light_provider import RPCProvider

    from .test_node import make_test_config

    cfg = make_test_config(tmp_path)
    init_files(cfg)
    node = Node(cfg)

    async def run():
        await node.start()
        await node.consensus.wait_for_height(3, timeout=60)
        addr = f"127.0.0.1:{node.rpc_server.port}"
        provider = RPCProvider(node.genesis.chain_id, addr)
        lb = await provider.light_block(2)
        assert lb is not None and lb.height == 2
        lb.validate_basic(node.genesis.chain_id)
        assert provider._has_light_block is True
        # the route rode the proof cache
        assert node.lightserve.cache.assembled >= 1
        # latest (height 0) works too
        tip = await provider.light_block(0)
        assert tip is not None and tip.height >= 2
        # unknown height answers None, not an exception
        assert await provider.light_block(10_000) is None
        await provider.client.close()
        await node.stop()

    asyncio.run(run())


# --- prewarm family coverage -----------------------------------------------


def test_prewarm_family_coverage_check():
    """The manifest --verify contract covers the lightserve verify
    class: its reachable tiers must be among the built entries."""
    from tools.prewarm import FAMILY_TIERS, check_families

    covered = {
        "entries": [
            {"tier": "small", "bucket": 8},
            {"tier": "big", "bucket": 8192},
        ],
    }
    assert check_families(covered, families=["lightserve"]) == []
    uncovered = {"entries": [{"tier": "generic", "bucket": 8}]}
    problems = check_families(
        uncovered, families=sorted(FAMILY_TIERS)
    )
    assert problems and any("lightserve" in p for p in problems)
    # an operator typo must fail, not silently pass unchecked
    typo = check_families(covered, families=["lightsrv"])
    assert typo and "not a known verify class" in typo[0]


# --- the swarm acceptance (ISSUE 8) ----------------------------------------


def test_swarm_1000_clients_shared_rounds_and_attack_evidence():
    """>= 1000 simulated light clients sync a 4-validator net through
    the serving plane: cache hit-rate > 0.9, device dispatches sublinear
    in client count, divergent-witness scenario lands
    LightClientAttackEvidence in the evidence pool, forged-header
    witness removed."""
    from tools.lightserve_bench import run_swarm

    stats = run_swarm(n_clients=1000, heights=6, n_vals=4)
    assert stats["synced"] == stats["n_clients"] == 1000
    assert stats["cache"]["hit_rate"] > 0.9
    # sublinear device work: the swarm's verifications collapse to a
    # handful of executed rounds, NOT one-per-client
    assert stats["verify"]["executed"] <= 8
    assert stats["registry_delta"]["device_dispatch_count"] <= 8
    assert (
        stats["registry_delta"]["device_dispatch_count"]
        + stats["scheduler_rounds"]
        < stats["n_clients"] / 10
    )
    assert stats["verify"]["dedup_rate"] > 0.99
    sc = stats["scenarios"]
    assert sc["divergent_witness"]["attack_detected"]
    assert sc["divergent_witness"]["evidence_pool_size"] >= 1
    assert sc["forged_header"]["synced"]
    assert sc["forged_header"]["forged_witness_removed"]
