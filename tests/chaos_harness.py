"""Chaos-mesh builder shared by tests/test_chaos.py and tools/soak.py.

Builds in-proc validator nodes over real encrypted p2p (the same shape as
test_consensus_reactor.build_p2p_node) wrapped in `chaos.NodeHandle`s,
with a restart_fn that rebuilds transport/switch around the surviving
consensus state — the "restart" scenario action.
"""

from __future__ import annotations

import asyncio
import contextlib

from tendermint_tpu.chaos import NodeHandle
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.p2p.key import NodeKey
from tendermint_tpu.p2p.node_info import NodeInfo
from tendermint_tpu.p2p.switch import Switch
from tendermint_tpu.p2p.transport import MultiplexTransport, NetAddress

from tests.helpers import (
    make_genesis,
    make_validators,
    make_weighted_validators,
)
from tests.test_consensus import make_node

NETWORK = "chaos-chain"


def _wire_node(cs, nk, ping_interval: float = 10.0, vote_batch: bool = True):
    """Fresh transport + switch + consensus reactor for one node."""
    transport = None
    sw = None

    def node_info():
        return NodeInfo(
            node_id=nk.id,
            listen_addr=f"127.0.0.1:{transport.listen_port}",
            network=NETWORK,
            channels=sw.channels() if sw else b"",
        )

    transport = MultiplexTransport(nk, node_info)
    sw = Switch(transport, ping_interval=ping_interval)
    sw.add_reactor("consensus", ConsensusReactor(cs, vote_batch=vote_batch))
    return transport, sw


def zipf_powers(n: int, s: float = 1.0, base: int = 1000) -> list[int]:
    """Zipf-distributed voting powers (rank-k power ~ base/k^s, min 1):
    the committee-scale weighted-power shape where a few heavyweights
    dominate the quorum — deterministic, no RNG."""
    return [max(1, int(base / (k + 1) ** s)) for k in range(n)]


def build_chaos_handles(
    n: int = 4,
    tracer_factory=None,
    ping_interval: float = 10.0,
    powers=None,
    config=None,
    vote_batch: bool = True,
    verifier_factory=None,
    health_factory=None,
) -> list[NodeHandle]:
    """n validator NodeHandles (not yet listening/started).

    `tracer_factory(name) -> Tracer` gives each node its OWN span ring
    (cluster tracing: obs.cluster merges the per-node dumps); default
    None keeps every node on the process-wide tracer. A small
    `ping_interval` makes the peer clock-offset EWMAs converge inside a
    short run. `powers` gives per-validator voting powers (n_i holds the
    key of validator index i in the sorted set). `config` overrides the
    per-node ConsensusConfig (adaptive-pacing scenarios). `vote_batch`
    False builds legacy one-vote-per-tick reactors (the committee_scale
    bench's baseline variant). `health_factory(name, tracer) ->
    HealthMonitor` gives each node a live health plane wired to the
    consensus push seams (vote arrival lags, height commits); the
    monitor rides `cs.health`, and its incidents land in that node's
    tracer ring so `node_dump` artifacts carry verdicts.

    Setup is O(n): per-node work touches only that node's keys/stores,
    and topology cost is deferred to start_mesh's peer_degree."""
    if powers is not None:
        vs, pvs = make_weighted_validators(powers)
        n = len(powers)
    else:
        vs, pvs = make_validators(n)
    genesis = make_genesis(vs)
    handles: list[NodeHandle] = []
    for i, pv in enumerate(pvs):
        tracer = tracer_factory(f"n{i}") if tracer_factory else None
        health = (
            health_factory(f"n{i}", tracer) if health_factory else None
        )
        cs, app, l2, bs, ss = make_node(
            vs,
            pv,
            genesis,
            tracer=tracer,
            config=config,
            verifier=verifier_factory() if verifier_factory else None,
            health=health,
        )
        nk = NodeKey.generate()
        transport, sw = _wire_node(
            cs, nk, ping_interval=ping_interval, vote_batch=vote_batch
        )
        handles.append(
            NodeHandle(
                name=f"n{i}",
                cs=cs,
                node_key=nk,
                transport=transport,
                switch=sw,
                block_store=bs,
                restart_fn=_make_restart(handles),
            )
        )
    return handles


class AllTrueVerifier:
    """Signature-verification stub for committee-scale gossip-plane
    harnesses: an in-proc 100+-node net shares ONE event loop, and a
    real device verify (worse, its first-dispatch XLA compile) blocks
    every node at once — with the stub, wall time measures the gossip
    and consensus planes. Batch/scheduler plumbing is exercised
    identically; verdicts are all-accept."""

    def __init__(self):
        import threading

        self.shutdown_event = threading.Event()

    def verify(self, items):
        import numpy as np

        return np.ones(len(items), dtype=bool)

    def verify_one(self, *a):
        return True

    def warm(self, *a, **k):
        return None


@contextlib.contextmanager
def stub_default_verifier():
    """Route default_verifier() callers (block validation's
    verify_commit_light among them) through AllTrueVerifier for the
    duration — per-node injection alone misses them."""
    from tendermint_tpu.crypto import batch_verifier as bv

    saved = bv._default
    bv._default = AllTrueVerifier()
    try:
        yield
    finally:
        bv._default = saved


class ChaosVerifyService:
    """Kill-and-restart wrapper around the in-proc verify service
    (parallel/verify_service.ServiceThread) — the chaos action for the
    split-brain deployment: `kill()` tears the service down mid-flight
    (clients' pending submissions must degrade to local verify, never
    hang), `restart()` brings a fresh service up on the SAME socket
    path (clients' backoff loops re-attach transparently). Constructor
    kwargs pass through to VerifyServiceServer (inject a stub verifier
    via `scheduler=VerifyScheduler(verifier=...)` to keep chaos runs
    device-free)."""

    def __init__(self, path: str, **kw):
        self.path = path
        self._kw = kw
        self.service = None
        self.restarts = 0

    def start(self) -> None:
        from tendermint_tpu.parallel.verify_service import ServiceThread

        self.service = ServiceThread(self.path, **self._kw)
        self.service.start()

    def kill(self) -> None:
        """Tear the service down (connections die, socket unlinks)."""
        if self.service is not None:
            self.service.stop()
            self.service = None

    def restart(self) -> None:
        self.kill()
        self.start()
        self.restarts += 1

    @property
    def alive(self) -> bool:
        return self.service is not None


async def round_dissemination_ticks(
    n: int, batch: bool, chunk_max: int = 64
) -> dict:
    """Deterministic measurement of the vote plane's per-round gossip
    cost: node A holds a full n-validator prevote round, node B (real
    encrypted p2p peer) holds none — count A's vote-gossip send events
    (ticks) until B's vote set is full. The one-vote-per-tick baseline
    (batch=False) is structurally n ticks; the batched plane ships
    ceil(n / vote_batch_max) chunks. Signature verification is stubbed
    on both ends (the measurement is the gossip plane, pre-verification
    plumbing is exercised identically)."""
    import numpy as np

    from tendermint_tpu.consensus.state_machine import ConsensusConfig
    from tendermint_tpu.consensus.vote_batcher import VoteBatcher
    from tendermint_tpu.types.block_id import BlockID
    from tendermint_tpu.types.part_set import PartSetHeader
    from tendermint_tpu.types.vote import Vote, VoteType

    class _AllTrue:
        def verify(self, items):
            return np.ones(len(items), dtype=bool)

    vs, pvs = make_validators(n)
    genesis = make_genesis(vs)
    # nodes must sit still in (h1, r0) for the whole measurement
    cfg = ConsensusConfig(
        timeout_propose=600.0,
        timeout_prevote=600.0,
        timeout_precommit=600.0,
        timeout_commit=600.0,
    )
    pair = []
    for pv in pvs[:2]:
        cs, app, l2, bs, ss = make_node(vs, pv, genesis, config=cfg)
        nk = NodeKey.generate()
        transport = None
        sw = None

        def node_info(nk=nk, t=lambda: transport, s=lambda: sw):
            return NodeInfo(
                node_id=nk.id,
                listen_addr=f"127.0.0.1:{t().listen_port}",
                network=NETWORK,
                channels=s().channels() if s() else b"",
            )

        transport = MultiplexTransport(nk, node_info)
        sw = Switch(transport, ping_interval=60.0)
        reactor = ConsensusReactor(
            cs,
            vote_batcher=VoteBatcher(verifier=_AllTrue()),
            vote_batch=batch,
            vote_batch_max=chunk_max,
        )
        sw.add_reactor("consensus", reactor)
        pair.append((cs, nk, transport, sw, reactor))
    (cs_a, nk_a, t_a, sw_a, r_a), (cs_b, nk_b, t_b, sw_b, r_b) = pair
    import asyncio
    import time

    for _, _, t, sw, _ in pair:
        await t.listen()
        await sw.start()
    await sw_a.dial_peer(NetAddress(nk_b.id, "127.0.0.1", t_b.listen_port))
    for cs, *_ in pair:
        await cs.start()
    try:
        for _ in range(200):  # both sides see the peer + height 1
            if (
                sw_a.peers
                and sw_b.peers
                and cs_a.rs.height == 1
                and cs_b.rs.height == 1
            ):
                break
            await asyncio.sleep(0.02)
        bid = BlockID(b"d" * 32, PartSetHeader(1, b"d" * 32))
        target = cs_a.rs.votes.prevotes(0)
        for i, v in enumerate(vs.validators):
            target.add_vote(
                Vote(
                    type=VoteType.PREVOTE,
                    height=1,
                    round=0,
                    block_id=bid,
                    timestamp_ns=1,
                    validator_address=v.address,
                    validator_index=i,
                    signature=b"s%06d" % i + b"\x00" * 57,
                ),
                verified=True,
            )
        ticks0 = r_a.gossip_ticks
        votes0 = r_a.gossip_votes_sent
        t0 = time.perf_counter()
        full = False
        while time.perf_counter() - t0 < 60:
            pv_b = cs_b.rs.votes.prevotes(0)
            if pv_b is not None and pv_b.bit_array().num_set() >= n:
                full = True
                break
            await asyncio.sleep(0.02)
        wall = time.perf_counter() - t0
        return {
            "n": n,
            "variant": "batched" if batch else "one_vote_per_tick",
            "complete": full,
            "gossip_ticks": r_a.gossip_ticks - ticks0,
            "votes_sent": r_a.gossip_votes_sent - votes0,
            "wall_ms": round(wall * 1e3, 1),
        }
    finally:
        for cs, _, _, sw, _ in pair:
            await cs.stop()
            await sw.stop()


def ring_peer_indices(i: int, n: int, degree: int) -> list[int]:
    """Deterministic sparse topology for committee-scale meshes: node i
    DIALS its next `degree` ring successors (i+1 .. i+degree mod n), so
    every edge is dialed exactly once, total edges n*degree instead of
    the full mesh's n*(n-1)/2, and each node ends with ~2*degree
    connections. degree >= 1 keeps the ring connected; chords shrink
    the gossip diameter to ~n/(2*degree)."""
    if n <= 1:
        return []
    degree = max(1, min(degree, n - 1))
    return [(i + d) % n for d in range(1, degree + 1)]


def _make_restart(handles: list[NodeHandle]):
    async def restart(handle: NodeHandle, net) -> None:
        """Rebuild p2p around the same consensus state (restart
        semantics: same privval + stores, fresh node key) and rejoin."""
        handle.node_key = NodeKey.generate()
        handle.transport, handle.switch = _wire_node(
            handle.cs,
            handle.node_key,
            ping_interval=handle.switch.ping_interval,
        )
        net.install(handle)
        await handle.transport.listen()
        await handle.switch.start()
        handle.switch.dial_peers_async(
            [
                NetAddress(h.node_key.id, "127.0.0.1", h.transport.listen_port)
                for h in handles
                if h is not handle and h.alive
            ],
            persistent=True,
        )
        await handle.cs.start()

    return restart


async def start_mesh(
    handles: list[NodeHandle], peer_degree: int = 0
) -> None:
    """Listen, start switches, wire the topology, start consensus.
    Chaos must already be installed (ScenarioRunner/ChaosNetwork.install)
    so transports wrap their connections.

    peer_degree 0 (default) keeps the original persistent full mesh —
    O(n^2) connections, right for small nets. A positive degree wires
    the ring-with-chords topology instead (ring_peer_indices): node i
    dials only its `degree` ring successors, so a 100+-validator
    committee comes up with O(n*degree) dials and connections and votes
    relay through the batched gossip plane."""
    for h in handles:
        await h.transport.listen()
        await h.switch.start()
    n = len(handles)
    for i, h in enumerate(handles):
        if peer_degree > 0:
            targets = [handles[j] for j in ring_peer_indices(i, n, peer_degree)]
        else:
            targets = [o for o in handles if o is not h]
        h.switch.dial_peers_async(
            [
                NetAddress(o.node_key.id, "127.0.0.1", o.transport.listen_port)
                for o in targets
            ],
            persistent=True,
        )
    for h in handles:
        await h.cs.start()


async def stop_mesh(handles: list[NodeHandle]) -> None:
    for h in handles:
        if not h.alive:
            continue
        await h.cs.stop()
        await h.switch.stop()


def node_dump(handle: NodeHandle) -> dict:
    """A `dump_traces`-shaped dict for one in-proc node — the input
    obs.cluster/tools/cluster_trace.py consume. Only meaningful when the
    mesh was built with per-node tracers (tracer_factory). When the
    node's verify path owns a scheduler (or was handed a ledger), its
    device-cost summary rides along so a divergence artifact answers
    "what was the device doing" without a repro run."""
    tracer = handle.cs.tracer
    out = {
        "node_id": handle.node_key.id,
        "moniker": handle.name,
        "epoch_wall_ns": tracer.epoch_wall_ns,
        "records": [r.to_json() for r in tracer.records()],
        "peer_clock": handle.switch.peer_clock_table(),
    }
    ledger = node_ledger(handle)
    if ledger is not None:
        out["dispatch_ledger"] = ledger.summary()
    return out


def node_ledger(handle: NodeHandle):
    """The DispatchLedger behind a handle's verify path, if any: a
    scheduler-backed verifier (classed adapter or the scheduler itself)
    or an explicitly attached `cs.dispatch_ledger`."""
    cs = handle.cs
    led = getattr(cs, "dispatch_ledger", None)
    if led is not None:
        return led
    verifier = getattr(cs, "verifier", None)
    sched = getattr(verifier, "_sched", None)  # _ClassedVerifier
    if sched is None:
        sched = getattr(cs, "verify_scheduler", None)
    return getattr(sched, "ledger", None)


async def chain_hashes(handles: list[NodeHandle], height: int) -> set:
    return {
        h.block_store.load_block(height).hash()
        for h in handles
        if h.alive and h.block_store.height >= height
    }
